"""Benchmark: the BASELINE.md matrix, un-crashable, on the best available backend.

Prints ONE JSON line {"metric", "value", "unit", "vs_baseline", "backend",
"extra"} no matter what happens — and that line is the ONLY thing on stdout:
at startup fd 1 is duplicated away and replaced with stderr, so any chatty
library (the axon TPU plugin logs ANSI ERROR lines to stdout; XLA sometimes
prints multi-KB dumps) can no longer corrupt the driver's JSON parse (the
round-2 failure: `BENCH_r02.json` `parsed: null`). The same JSON — plus
per-section partials as they finish — is mirrored to `BENCH.json` so even a
driver-side timeout leaves a usable artifact.

Wedge-proofing (the round-3 failure was a wedged axon TPU tunnel silently
downgrading every flagship config to a CPU toy scale):
  * the TPU probe RETRIES across tunnel resets (several subprocess attempts
    inside a probe budget) instead of one 90 s shot;
  * measurement groups run in SEPARATE SUBPROCESSES with their own
    timeouts, checkpointing results to a file after every section — one
    hung remote compile costs its group's slice of the budget, not the
    bench (`--group <name> --out <file>` is the child entry point);
  * nothing downscales silently: when the TPU cannot be reached the CPU
    fallback records ``"downscaled": true`` plus the reason on every
    affected section and on the headline.
Sections run against a wall-clock budget (BENCH_BUDGET_S, default 540 s):
whatever doesn't fit is recorded as ``skipped_budget`` instead of risking an
rc=124 with nothing parseable. A persistent JAX compilation cache under
``.jax_cache/`` makes re-runs (including the driver's) skip the multi-minute
remote compiles — warm it by running bench.py on the TPU before round end.

Measured sections (see BASELINE.md "Metrics to measure"):
  1. stokeslet mobility-matvec throughput, f32 + f64 (pairs/s/chip), vs a
     single-core NumPy direct evaluation (the reference's oracle backend,
     `/root/reference/tests/core/kernel_test.cpp`), plus an MFU estimate and
     the Pallas-vs-XLA comparison;
  2. single-fiber implicit solve (64 nodes, free space): wall/solve + iters;
  3. trajectory frame encode at the 10k-fiber scale;
  4. the reference docs-walkthrough-scale coupled solve — 1 fiber + 1 body
     (400 nodes) + spherical periphery — f32 at 1e-8 and mixed-precision f64
     at the reference's 1e-10 tolerance, against its published footprint:
     GMRES 7 iters, 0.328 s/solve
     (`/root/reference/docs/source/getting_started.rst:96-100`);
  5. BASELINE #3/#5: ellipsoidal periphery + 1k clamped fibers, and the
     oocyte surface-of-revolution periphery + fibers — full coupled solves;
  6. BASELINE #4: the 10k-fiber (640k-node) dense Stokeslet matvec — the
     measurement that decides the FMM go/no-go (extra["fmm_go_no_go"]).

Headline: mixed-precision coupled solve at the walkthrough scale when it ran
(vs_baseline = ref_wall / our_wall, >1 means faster than the reference at a
*stricter* achieved tolerance); falls back to the f32 coupled solve, then to
kernel throughput vs the NumPy oracle.

Campaign mode (skelly-roofline): ``python bench.py --campaign`` runs every
group in one command, captures a device-trace ``profile_session`` per
headline group (ROOFLINE_PROGRAMS) and folds the per-phase roofline
verdicts (`obs roofline`) into ONE manifest,
``benchmarks/CAMPAIGN_rNN.json`` — groups run/skipped, auto-bumped archive
rounds (BENCH_ROUND_<GROUP>, appended, never overwritten), the armed
`obs perf --compare` gate verdict, full provenance, and the explicit
``downscaled`` bool every bench artifact now carries (PROVENANCE_KEYS).
``--campaign-groups a,b`` restricts to a subset (the CI smoke);
``--render-headlines [--check]`` regenerates (or freshness-checks) the
docs/performance.md headline table from the archived rounds.
`obs campaign benchmarks/CAMPAIGN_rNN.json` validates/renders a manifest.

Bench-only shortcut: shell quadrature weights are uniform (area/N on the
generated nodes) instead of the Reeger-Fornberg RBF weights, and the dense
shell operator + its inverse are assembled/inverted on-device — the host here
has one CPU core, where the production scipy path (`periphery.build_shell_operator`)
takes ~5 min at 6000 nodes. Solver structure, shapes, and flop profile are
identical to production; only quadrature accuracy (irrelevant for timing)
differs.
"""

from __future__ import annotations

import json
import os
import re
import subprocess
import sys
import time

import numpy as np

#: reference walkthrough: GMRES 7 iters, 0.328 s/solve, tol 4.6e-11
#: (docs/source/getting_started.rst:96-100; 1 fiber + body(400) + shell(6000))
REF_SOLVE_WALL_S = 0.328
REF_SOLVE_ITERS = 7

#: direct stokeslet arithmetic per source-target pair (3 sub, 5 r^2, ~4 rsqrt,
#: 2 rinv^3, 5 f.d dot, ~11 accumulate) — for the MFU estimate only
STOKESLET_FLOPS_PER_PAIR = 30

#: per-chip dense peak (flops/s) by device_kind substring, bf16 for TPUs
PEAK_FLOPS = [("v6", 918e12), ("v5p", 459e12), ("v5", 197e12), ("v4", 275e12)]

#: wall-clock budget; sections that don't fit are recorded as skipped
BUDGET_S = float(os.environ.get("BENCH_BUDGET_S", 540))
_T_START = time.monotonic()

#: real-stdout fd saved by _steal_stdout; the one JSON line goes here
_REAL_STDOUT_FD = None
#: partial/final results mirrored here after every section;
#: BENCH_JSON_PATH redirects (the campaign CI smoke must not clobber the
#: real mirror with a one-group run)
BENCH_JSON_PATH = os.environ.get(
    "BENCH_JSON_PATH",
    os.path.join(os.path.dirname(os.path.abspath(__file__)), "BENCH.json"))

#: skelly-scope artifact-format stamp on every bench artifact (BENCH.json,
#: the headline line, MULTICHIP_*.json). Deliberately a LITERAL, not an
#: import: the parent process never imports skellysim_tpu (whose package
#: __init__ imports jax — the axon plugin can wedge at init, the exact
#: failure mode this process layout defends against).
#: tests/test_obs.py pins it == skellysim_tpu.obs.tracer.TELEMETRY_VERSION.
TELEMETRY_VERSION = 1

#: span-event stream the group children append to (one tracer per child);
#: the parent clears it at startup so each bench run leaves one stream
BENCH_TRACE_PATH = os.environ.get(
    "BENCH_TRACE_PATH",
    os.path.join(os.path.dirname(os.path.abspath(__file__)),
                 ".bench_trace.jsonl"))


def _remaining() -> float:
    return BUDGET_S - (time.monotonic() - _T_START)


def _steal_stdout():
    """Redirect fd 1 to stderr (C-level, so plugin/XLA prints can't pollute
    the JSON) and keep a private dup of the real stdout for the final line."""
    global _REAL_STDOUT_FD
    if _REAL_STDOUT_FD is not None:
        return
    _REAL_STDOUT_FD = os.dup(1)
    os.dup2(2, 1)
    sys.stdout = sys.stderr


def _emit(line: dict):
    """Write the one JSON line to the real stdout + mirror to BENCH.json."""
    payload = json.dumps(line)
    try:
        with open(BENCH_JSON_PATH, "w") as fh:
            fh.write(payload + "\n")
    except Exception:
        pass
    fd = _REAL_STDOUT_FD if _REAL_STDOUT_FD is not None else 1
    os.write(fd, (payload + "\n").encode())


def _checkpoint(extra: dict):
    """Mirror partial results so a driver-side kill still leaves an artifact."""
    try:
        with open(BENCH_JSON_PATH, "w") as fh:
            fh.write(json.dumps({"metric": "bench_partial", "value": 0.0,
                                 "unit": "", "vs_baseline": 0.0,
                                 "extra": extra}) + "\n")
    except Exception:
        pass


def _short_err(e: BaseException, limit: int = 200) -> str:
    """First line of the exception repr — multi-KB XLA tracebacks embedded in
    reprs were part of what corrupted round 2's bench output."""
    first = repr(e).splitlines()[0] if repr(e) else type(e).__name__
    return first[:limit]


def _probe_backend_once(timeout_s: float):
    # enumeration alone lies: the axon tunnel has been observed answering
    # jax.default_backend() while the remote AOT compiler hangs forever
    # (r5). A backend only counts if a small compiled matmul makes it back
    # to the host.
    code = ("import jax, numpy as np, jax.numpy as jnp;"
            "b = jax.default_backend();"
            "x = jnp.ones((128, 128));"
            "np.asarray(x @ x);"
            "print('BACKEND=' + b)")
    try:
        p = subprocess.run([sys.executable, "-c", code], capture_output=True,
                           text=True, timeout=timeout_s)
        for line in (p.stdout or "").splitlines():
            if line.startswith("BACKEND="):
                return line.split("=", 1)[1].strip()
    except Exception:
        pass
    return None


def _probe_backend(probe_budget_s: float | None = None):
    """Ask subprocesses for the default backend so a wedged TPU plugin can
    never hang or crash the bench process.

    RETRIES across tunnel resets: the axon tunnel has been observed wedged
    for minutes then recovering; one 90 s shot (round 3) silently downgraded
    the whole bench to CPU. Returns (backend | None, probe_log)."""
    if probe_budget_s is None:
        probe_budget_s = min(float(os.environ.get("BENCH_PROBE_S", 180)),
                             BUDGET_S / 3.0)
    t0 = time.monotonic()
    attempts = []
    while True:
        elapsed = time.monotonic() - t0
        left = probe_budget_s - elapsed
        if left <= 5:
            break
        t_a = time.monotonic()
        backend = _probe_backend_once(timeout_s=min(75.0, left))
        attempts.append({"backend": backend,
                         "s": round(time.monotonic() - t_a, 1)})
        if backend not in (None, "cpu"):
            return backend, attempts
        # a None/cpu answer can be a transient tunnel wedge: wait and retry
        if probe_budget_s - (time.monotonic() - t0) > 30:
            time.sleep(15)
        else:
            break
    return (attempts[-1]["backend"] if attempts else None), attempts


def _numpy_pairs_per_s(n=1024, trials=3):
    """Single-core direct CPU evaluation rate (the reference oracle backend)."""
    rng = np.random.default_rng(0)
    r = rng.uniform(-1, 1, size=(n, 3))
    f = rng.standard_normal((n, 3))

    def direct(r_src, r_trg, f_src):
        d = r_trg[:, None, :] - r_src[None, :, :]
        r2 = np.sum(d * d, axis=-1)
        np.fill_diagonal(r2, np.inf)
        rinv = 1.0 / np.sqrt(r2)
        df = np.einsum("tsk,sk->ts", d, f_src)
        u = np.einsum("ts,sk->tk", rinv, f_src) + np.einsum("ts,tsk->tk", df * rinv**3, d)
        return u / (8 * np.pi)

    direct(r, r, f)  # warm caches
    t0 = time.perf_counter()
    for _ in range(trials):
        direct(r, r, f)
    dt = (time.perf_counter() - t0) / trials
    return n * n / dt


def _rate(fn, n_pairs, trials=3):
    """pairs/s of a nullary kernel call: compile+warm once, then time.

    The clock stops only after a host fetch of the last output:
    `block_until_ready` was observed returning before the work drained (both
    on the remote axon TPU tunnel and on CPU for one leaf of a larger
    program), which produced round-2-style impossible >100% MFU readings. A
    device->host copy of the result is the one barrier that cannot ack early.
    Executions on one device stream are ordered, so fetching the last trial's
    output forces all queued trials to completion.
    """
    np.asarray(fn())  # compile + warm + drain
    t0 = time.perf_counter()
    for _ in range(trials):
        out = fn()
    np.asarray(out)  # host fetch: the real completion barrier
    return n_pairs * trials / (time.perf_counter() - t0)


def _kernel_inputs(dtype, n):
    import jax.numpy as jnp

    rng = np.random.default_rng(1)
    r = jnp.asarray(rng.uniform(-5, 5, size=(n, 3)), dtype=dtype)
    f = jnp.asarray(rng.standard_normal((n, 3)), dtype=dtype)
    return r, f


def _kernel_rate(dtype, n):
    from skellysim_tpu.ops import kernels

    r, f = _kernel_inputs(dtype, n)
    return _rate(lambda: kernels.stokeslet_direct(r, r, f, 1.0), n * n)


def _solve_rate(system, state, trials=3):
    """{wall_s, iters, residual, residual_true, solves_per_s} of a jit'd
    solve, timed to a host fetch."""
    import jax

    step = jax.jit(system._solve_impl)
    float(step(state)[2].residual)  # compile + warm + drain
    t0 = time.perf_counter()
    for _ in range(trials):
        _, _, info = step(state)
    resid = float(info.residual)  # host fetch: the real completion barrier
    wall = (time.perf_counter() - t0) / trials
    return {"wall_s": round(wall, 4), "iters": int(info.iters),
            "refines": int(info.refines),
            "residual": resid, "residual_true": float(info.residual_true),
            "solves_per_s": round(1.0 / wall, 2)}


def _bench_single_fiber(dtype, tol, trials=3, mixed=False):
    """1 fiber x 64 nodes in free space, background-driven implicit solve.

    ``mixed=True`` runs the f64-state mixed-precision solver — the honest
    accuracy configuration (the pure-f32 fiber operator's ~1e7 rows amplify
    rounding, so its explicit residual plateaus near 1e-3 even when the
    implicit residual converges)."""
    import dataclasses

    import jax.numpy as jnp

    from __graft_entry__ import _make_system

    system, state = _make_system(
        n_fibers=1, n_nodes=64, dtype=jnp.float64 if mixed else dtype,
        solver_precision="mixed" if mixed else "full")
    system.params = dataclasses.replace(system.params, gmres_tol=tol)
    out = _solve_rate(system, state, trials)
    out["tol"] = tol
    return out


def _block_inv(M, max_direct: int = 12000):
    """Device Schur-complement blocked inverse — the production implementation
    lives in `skellysim_tpu.periphery.periphery.block_inv` (promoted there in
    round 5 for the `--device-operator` precompute path)."""
    from skellysim_tpu.periphery.periphery import block_inv

    return block_inv(M, max_direct)


def _device_shell_operator(nodes, normals, weights, dtype, precond_dtype=None):
    """Dense second-kind shell operator + inverse on-device — delegates to
    the production `periphery.build_shell_operator_device` (promoted there in
    round 5 as the `--device-operator` precompute path; returns device
    arrays, so no extra tunnel round trip here)."""
    from skellysim_tpu.periphery.periphery import build_shell_operator_device

    return build_shell_operator_device(nodes, normals, weights, eta=1.0,
                                       op_dtype=dtype,
                                       inv_dtype=precond_dtype or dtype)


#: per-(shell_n, radius, dtypes) cache of the walkthrough scene's dense
#: operator (device arrays). The coupled group benches several (dtype,
#: solver) combinations of the SAME geometry — reusing the assembled +
#: inverted 18000^2 operator across same-dtype scenes (f32 solve, then the
#: mxu-kernel repeat) skips repeat runs of the group's most expensive
#: setup stage. Entries for a different dtype of the same geometry are
#: EVICTED before building (not kept side by side): pinning the f64
#: operator (2.6 GB) through the f32 ladder rung would shrink HBM headroom
#: in exactly the OOM-sensitive solve the ladder exists to protect.
_WALKTHROUGH_SHELL_CACHE: dict = {}

#: walkthrough scene shell radius (the reference walkthrough's geometry)
_WALKTHROUGH_RADIUS = 6.0


def _walkthrough_shell(shell_n, radius, dtype, precond_dtype):
    import jax.numpy as jnp

    from skellysim_tpu.periphery.shapes import sphere_shape

    key = (shell_n, radius, jnp.dtype(dtype).name,
           jnp.dtype(precond_dtype).name if precond_dtype else None)
    if key not in _WALKTHROUGH_SHELL_CACHE:
        for other in [k for k in _WALKTHROUGH_SHELL_CACHE
                      if k[:2] == (shell_n, radius)]:
            del _WALKTHROUGH_SHELL_CACHE[other]
        spec = sphere_shape(shell_n, radius=radius * 1.04)
        normals = -spec.node_normals  # shell normals point inward
        weights = np.full(shell_n, 4 * np.pi * (radius * 1.04) ** 2 / shell_n)
        op, M_inv = _device_shell_operator(spec.nodes, normals, weights,
                                           dtype, precond_dtype=precond_dtype)
        _WALKTHROUGH_SHELL_CACHE[key] = (spec.nodes, normals, weights,
                                         op, M_inv)
    return _WALKTHROUGH_SHELL_CACHE[key]


def _walkthrough_state(shell_n, body_n, dtype, tol, mixed, kernel_impl="exact"):
    """Walkthrough-scale coupled scene: 1 fiber + 1 body + spherical shell."""
    import jax.numpy as jnp

    from skellysim_tpu.bodies import bodies as bd
    from skellysim_tpu.fibers import container as fc
    from skellysim_tpu.params import Params
    from skellysim_tpu.periphery import periphery as peri
    from skellysim_tpu.periphery.precompute import precompute_body
    from skellysim_tpu.system import System

    # mixed mode stores the preconditioner in f32 (preconditioner-grade;
    # TPU LU is f32-only); full-precision scenes keep the state dtype,
    # matching the pre-cache bench numerics
    pdt = jnp.float32 if mixed else None
    radius = _WALKTHROUGH_RADIUS
    nodes, normals, weights, op, M_inv = _walkthrough_shell(shell_n, radius,
                                                            dtype, pdt)
    shell = peri.make_state(nodes, normals, weights, op, M_inv,
                            dtype=dtype, precond_dtype=pdt)

    body_pre = precompute_body("sphere", body_n, radius=0.5)
    bodies = bd.make_group(
        body_pre["node_positions_ref"], body_pre["node_normals_ref"],
        body_pre["node_weights"], position=np.zeros((1, 3)),
        external_force=np.array([[0.0, 0.0, 0.5]]), radius=np.array([0.5]),
        kind="sphere", dtype=dtype)

    t = np.linspace(0, 1, 64)
    x = np.array([0.0, 3.0, 0.0])[None, :] + t[:, None] * np.array([0.0, 0.0, 1.0])
    fibers = fc.make_group(x[None], lengths=1.0, bending_rigidity=0.01,
                           radius=0.0125, dtype=dtype)

    params = Params(eta=1.0, dt_initial=0.1, t_final=1.0, gmres_tol=tol,
                    gmres_restart=60, gmres_maxiter=120,
                    solver_precision="mixed" if mixed else "full",
                    kernel_impl=kernel_impl, adaptive_timestep_flag=False)
    system = System(params, shell_shape=peri.PeripheryShape(kind="sphere",
                                                            radius=radius))
    return system, system.make_state(fibers=fibers, shell=shell, bodies=bodies)


def _bench_coupled(shell_n, body_n, dtype, tol, trials=3, mixed=False,
                   kernel_impl="exact", return_scene=False):
    """Walkthrough-scale coupled solve; ``mixed=True`` benches the
    f64-accuracy TPU path (f32 Krylov flows + LU preconditioners, f64
    iterative refinement to ``tol``) — the apples-to-apples comparison
    against the reference's 0.328 s/solve at tol 4.6e-11.
    ``return_scene`` additionally hands back (system, state) so callers
    (scripts/profile_solve.py's trace capture) can reuse the built scene
    instead of paying the dense shell inverse a second time."""
    t_setup = time.perf_counter()
    system, state = _walkthrough_state(shell_n, body_n, dtype, tol, mixed,
                                       kernel_impl)
    setup_s = time.perf_counter() - t_setup
    out = _solve_rate(system, state, trials)
    out.update({"tol": tol, "shell_n": shell_n, "body_n": body_n,
                "setup_s": round(setup_s, 2),
                "ref_wall_s": REF_SOLVE_WALL_S, "ref_iters": REF_SOLVE_ITERS,
                "vs_ref": round(REF_SOLVE_WALL_S / out["wall_s"], 2)})
    if return_scene:
        return out, system, state
    return out


def _bench_coupled_ladder(scales, body_n, dtype, tol, mixed):
    """Try the walkthrough solve at descending shell sizes; record the error
    at each failed scale instead of silently overwriting it."""
    errors = {}
    for shell_n in scales:
        if _remaining() < 60:
            errors["skipped_budget"] = f"{int(_remaining())}s left"
            break
        try:
            out = _bench_coupled(shell_n, body_n, dtype, tol, mixed=mixed)
            if errors:
                out["errors_at_larger_scales"] = errors
            return out
        except Exception as e:
            errors[str(shell_n)] = _short_err(e)
            # evict this rung's cached device operator (~4 GB at 6000):
            # keeping it pinned would shrink HBM headroom exactly while the
            # ladder retries smaller scales to recover from an OOM
            for k in [k for k in _WALKTHROUGH_SHELL_CACHE
                      if k[:2] == (shell_n, _WALKTHROUGH_RADIUS)]:
                del _WALKTHROUGH_SHELL_CACHE[k]
    return {"error": errors or "no scale attempted"}


def _clamped_fiber_field(spec, n_fibers, n_nodes, length, dtype):
    """[n_fibers, n_nodes, 3] straight fibers clamped on the shell surface,
    pointing inward — the ellipsoid/oocyte example geometry
    (`examples/ellipsoid/gen_config.py`, `examples/oocyte/gen_config.py`)."""
    import jax.numpy as jnp

    stride = max(1, len(spec.nodes) // n_fibers)
    origins = np.asarray(spec.nodes)[::stride][:n_fibers] * 0.98
    inward = -np.asarray(spec.node_normals)[::stride][:n_fibers]
    t = np.linspace(0, length, n_nodes)
    x = origins[:, None, :] + t[None, :, None] * inward[:, None, :]
    return jnp.asarray(x, dtype=dtype), origins.shape[0]


def _bench_fiber_shell(kind, n_fibers, fiber_nodes, shell_n, dtype, tol,
                       trials=2):
    """BASELINE #3/#5: many clamped fibers with motor forcing inside a
    non-spherical periphery; full coupled implicit solve."""
    import jax.numpy as jnp

    from skellysim_tpu.fibers import container as fc
    from skellysim_tpu.params import Params
    from skellysim_tpu.periphery import periphery as peri
    from skellysim_tpu.periphery import shapes
    from skellysim_tpu.system import System

    t_setup = time.perf_counter()
    if kind == "ellipsoid":
        a, b, c = 7.8, 6.0, 6.0
        spec = shapes.ellipsoid_shape(shell_n, a, b, c)
        # rough surface area (Thomsen approximation) for uniform weights
        p = 1.6075
        area = 4 * np.pi * (((a*b)**p + (a*c)**p + (b*c)**p) / 3) ** (1/p)
        shape = peri.PeripheryShape(kind="ellipsoid", abc=(a, b, c))
    elif kind == "revolution":
        env = {"n_nodes_target": shell_n, "lower_bound": -3.75,
               "upper_bound": 3.75, "T": 0.72, "p1": 0.4, "p2": 0.2,
               "length": 7.5,
               "height": "0.5 * T * ((1 + 2*x/length)**p1) "
                         "* ((1 - 2*x/length)**p2) * length"}
        spec = shapes.surface_of_revolution_shape(env)
        area = 4 * np.pi * 2.0 ** 2  # order-of-magnitude uniform weights
        shape = peri.PeripheryShape(kind="generic")
    else:
        raise ValueError(kind)

    N = len(spec.nodes)
    normals = -spec.node_normals
    weights = np.full(N, area / N)
    op, M_inv = _device_shell_operator(spec.nodes, normals, weights, dtype,
                                       precond_dtype=jnp.float32)
    shell = peri.make_state(spec.nodes, normals, weights, op, M_inv,
                            dtype=dtype, precond_dtype=jnp.float32)

    x, nf = _clamped_fiber_field(spec, n_fibers, fiber_nodes, 1.0, dtype)
    fibers = fc.make_group(x, lengths=1.0, bending_rigidity=2.5e-3,
                           radius=0.0125, force_scale=-0.05,
                           minus_clamped=True, dtype=dtype)
    # maxiter headroom: explicit-residual acceptance spends extra restart
    # cycles repairing implicit/true drift on these strongly-coupled
    # clamped-fiber configs (r3: oocyte drifted to 5.8e-8 at 49 implicit
    # iters; the repair costs ~1.3-2x the implicit count)
    params = Params(eta=1.0, dt_initial=8e-3, t_final=1.0, gmres_tol=tol,
                    gmres_restart=60, gmres_maxiter=300,
                    adaptive_timestep_flag=False)
    system = System(params, shell_shape=shape)
    state = system.make_state(fibers=fibers, shell=shell)
    setup_s = time.perf_counter() - t_setup

    out = _solve_rate(system, state, trials)
    n_nodes_total = nf * fiber_nodes + N
    # two pairwise kernel evaluations per GMRES iteration (fiber flow +
    # shell flow) over all nodes
    pairs = 2 * n_nodes_total * n_nodes_total * max(out["iters"], 1)
    out.update({"tol": tol, "kind": kind, "n_fibers": nf,
                "fiber_nodes": fiber_nodes, "shell_n": N,
                "nodes_total": n_nodes_total, "setup_s": round(setup_s, 2),
                "iters_per_s": round(out["iters"] / out["wall_s"], 2),
                "matvec_gpairs_per_s": round(pairs / out["wall_s"] / 1e9, 3)})
    return out


def _bench_640k_matvec(n_fibers, n_nodes, dtype, trials=2, ck=None):
    """BASELINE #4: dense Stokeslet mobility matvec at the 10k-fiber scale
    (640k source=target nodes) — the measurement behind the FMM go/no-go.

    ``ck(out)`` checkpoints after each sub-measurement (XLA / MXU / Pallas)
    so a remote-compile hang in a later path keeps the earlier numbers."""
    import jax
    import jax.numpy as jnp

    from skellysim_tpu.ops import kernels

    rng = np.random.default_rng(100)
    box = 20.0
    n = n_fibers * n_nodes
    origins = rng.uniform(-box / 2, box / 2, (n_fibers, 3))
    dirs = rng.normal(size=(n_fibers, 3))
    dirs /= np.linalg.norm(dirs, axis=1, keepdims=True)
    t = np.linspace(0, 1.0, n_nodes)
    r = (origins[:, None, :] + t[None, :, None] * dirs[:, None, :]).reshape(-1, 3)
    r = jnp.asarray(r, dtype=dtype)
    f = jnp.asarray(rng.standard_normal((n, 3)), dtype=dtype)

    t0 = time.perf_counter()
    rate = _rate(lambda: kernels.stokeslet_direct(r, r, f, 1.0), n * n,
                 trials=trials)
    out = {"n_nodes": n, "gpairs_per_s": round(rate / 1e9, 3)}
    if ck is not None:
        ck(out)
    try:
        # matmul-form tile: O(N^2*3) contractions on the MXU (see
        # kernels.stokeslet_block_mxu numerics caveat — valid for this
        # well-separated free-fiber cloud)
        rate_mxu = _rate(lambda: kernels.stokeslet_direct(r, r, f, 1.0,
                                                          impl="mxu"),
                         n * n, trials=trials)
        out["gpairs_per_s_mxu"] = round(rate_mxu / 1e9, 3)
        rate = max(rate, rate_mxu)
    except Exception as e:
        out["mxu_error"] = _short_err(e)
    if ck is not None:
        ck(out)
    if dtype != np.float64 and jax.default_backend() != "cpu":
        try:
            # fused VMEM Pallas tile (round 5: ~3.4x the XLA path on v5e)
            rate_p = _rate(lambda: kernels.stokeslet_direct(r, r, f, 1.0,
                                                            impl="pallas"),
                           n * n, trials=trials)
            out["gpairs_per_s_pallas"] = round(rate_p / 1e9, 3)
            rate = max(rate, rate_p)
        except Exception as e:
            out["pallas_error"] = _short_err(e)
    wall = n * n / rate
    out.update({"wall_s_per_matvec": round(wall, 3),
                "projected_v5p8_wall_s": round(wall / 8, 3),
                "total_s": round(time.perf_counter() - t0, 1)})
    # the Ewald-vs-dense comparison lives in `_bench_ewald_crossover`
    return out


def _bench_ewald_crossover(on_acc, dtype, ck=None):
    """VERDICT r3 #2: Ewald vs dense at a ladder of node counts — the
    measured crossover table replacing the round-3 projection.

    ``ck(table)`` checkpoints after every size: a remote-compile hang at one
    rung costs that rung, not the whole table (round 5: a starved child lost
    all rungs to the 640k section's budget)."""
    import jax.numpy as jnp

    from skellysim_tpu.ops import ewald as ew
    from skellysim_tpu.ops import kernels

    # the CPU ladder reaches past the measured dense/Ewald crossover
    # (~40k nodes on CPU, scripts/ewald_ladder.py) so the artifact records
    # a speedup_vs_dense > 1 row even on fallback runs
    sizes = ((1600, 10000, 40000, 160000, 640000) if on_acc
             else (1600, 6400, 16000, 40000))
    rng = np.random.default_rng(100)
    table = {}
    for n in sizes:
        if ck is not None:
            ck(table)
        if _remaining() < 75:
            table[f"n{n}"] = {"skipped_budget": int(_remaining())}
            continue
        try:
            n_fibers = -(-n // 64)  # ceil: the [:n] slice needs >= n rows
            box = 20.0 * (n / 640000.0) ** (1.0 / 3.0)  # constant density
            origins = rng.uniform(-box / 2, box / 2, (n_fibers, 3))
            dirs = rng.normal(size=(n_fibers, 3))
            dirs /= np.linalg.norm(dirs, axis=1, keepdims=True)
            t = np.linspace(0, 1.0, 64)
            r = (origins[:, None, :]
                 + t[None, :, None] * dirs[:, None, :]).reshape(-1, 3)[:n]
            r = jnp.asarray(r, dtype=dtype)
            f = jnp.asarray(rng.standard_normal((n, 3)), dtype=dtype)

            rate = _rate(lambda: kernels.stokeslet_direct(r, r, f, 1.0,
                                                          impl="mxu"),
                         n * n, trials=2)
            dense_wall = n * n / rate
            t1 = time.perf_counter()
            plan = ew.plan_ewald(np.asarray(r), eta=1.0, tol=1e-4)
            np.asarray(ew.stokeslet_ewald(plan, r, r, f))
            t_first = time.perf_counter() - t1
            t1 = time.perf_counter()
            uE = np.asarray(ew.stokeslet_ewald(plan, r, r, f))
            t_steady = time.perf_counter() - t1
            sub = np.random.default_rng(0).choice(n, size=min(n, 512),
                                                  replace=False)
            uD = np.asarray(kernels.stokeslet_direct(r, r[sub], f, 1.0))
            err = (np.linalg.norm(uE[sub] - uD)
                   / max(np.linalg.norm(uD), 1e-300))
            table[f"n{n}"] = {
                "dense_wall_s": round(dense_wall, 4),
                "ewald_wall_s": round(t_steady, 4),
                "ewald_first_call_s": round(t_first, 1),
                "speedup_vs_dense": round(dense_wall / max(t_steady, 1e-9), 2),
                "rel_err": float(err), "grid_M": plan.M,
                "near_mode": plan.near_mode, "max_occ": plan.max_occ,
                "K": plan.K}
        except Exception as e:
            table[f"n{n}"] = {"error": _short_err(e)}
    return table


# ------------------------------------------------------------- section groups

def _mark_downscaled(d: dict, reason: str):
    if isinstance(d, dict):
        d["downscaled"] = True
        d["downscale_reason"] = reason
    return d


_CPU_FALLBACK = "tpu unreachable at bench time (cpu fallback) — toy scale"


def _group_kernels(extra, ck, on_acc):
    import jax.numpy as jnp

    n32 = 65536 if on_acc else 8192
    # f64 on TPU is software-emulated (~100x slower than f32); measure at a
    # size that reliably completes
    n64 = 4096
    rate32 = None
    # numpy baseline first: pure-host, no compile risk — bank it before the
    # first remote compile can eat the child's budget (round 5: a starved
    # child timed out inside the 65536 compile with an empty checkpoint)
    try:
        extra["numpy_baseline_gpairs_per_s"] = round(
            _numpy_pairs_per_s() / 1e9, 5)
    except Exception:
        pass
    ck()
    try:
        rate32 = _kernel_rate(jnp.float32, n32)
        extra["stokeslet_f32"] = {"n": n32, "gpairs_per_s": round(rate32 / 1e9, 4)}
        if not on_acc:
            # mark like the other groups: a CPU rate at the 8x-smaller n
            # must never pass for a chip number, even if a later re-probe
            # promotes the rest of the run (the headline inherits this flag)
            _mark_downscaled(extra["stokeslet_f32"], _CPU_FALLBACK)
    except Exception as e:
        extra["stokeslet_f32"] = {"error": _short_err(e)}
    ck()
    if _remaining() > 60:
        try:
            rate64 = _kernel_rate(jnp.float64, n64)
            extra["stokeslet_f64"] = {"n": n64,
                                      "gpairs_per_s": round(rate64 / 1e9, 4)}
        except Exception as e:
            extra["stokeslet_f64"] = {"error": _short_err(e)}
        ck()

    # double-float f32 kernel: f64-class accuracy without emulated f64
    # (ops/df_kernels.py) — rate + achieved error vs the exact path
    ref_df = None
    if _remaining() > 60:
        try:
            from skellysim_tpu.ops import kernels as _k
            from skellysim_tpu.ops.df_kernels import stokeslet_direct_df

            n_df = n64 if on_acc else 1024
            r, f = _kernel_inputs(jnp.float32, n_df)
            rate_df = _rate(lambda: stokeslet_direct_df(r, r, f, 1.0),
                            n_df * n_df)
            ref_df = np.asarray(_k.stokeslet_direct(
                r.astype(jnp.float64), r.astype(jnp.float64),
                f.astype(jnp.float64), 1.0))
            got = np.asarray(stokeslet_direct_df(r, r, f, 1.0))
            extra["stokeslet_df"] = {
                "n": n_df, "gpairs_per_s": round(rate_df / 1e9, 4),
                "rel_err_vs_f64": float(np.linalg.norm(got - ref_df)
                                        / np.linalg.norm(ref_df))}
        except Exception as e:
            extra["stokeslet_df"] = {"error": _short_err(e)}
        ck()

    # fused Pallas DF tile (round 5, accelerator only): same f64-grade
    # accuracy class with the whole chain in VMEM — the rate here plus the
    # rel_err on real Mosaic is the promotion gate for refine_pair_impl
    # "auto" -> "pallas_df"
    if on_acc and _remaining() > 60:
        if ref_df is None:
            # distinguish "no reference available" (the stokeslet_df step
            # failed or was itself budget-skipped) from "never ran"
            extra["stokeslet_pallas_df"] = {
                "skipped": "no f64 reference (stokeslet_df step failed or "
                           "was skipped)"}
        else:
            try:
                from skellysim_tpu.ops.pallas_df import stokeslet_pallas_df

                rate_p = _rate(lambda: stokeslet_pallas_df(r, r, f, 1.0),
                               n_df * n_df)
                got = np.asarray(stokeslet_pallas_df(r, r, f, 1.0))
                extra["stokeslet_pallas_df"] = {
                    "n": n_df, "gpairs_per_s": round(rate_p / 1e9, 4),
                    "rel_err_vs_f64": float(np.linalg.norm(got - ref_df)
                                            / np.linalg.norm(ref_df))}
            except Exception as e:
                extra["stokeslet_pallas_df"] = {"error": _short_err(e)}
        ck()

    # Pallas fused tiles (accelerator only): report whichever path wins
    if on_acc and rate32 is not None:
        try:
            from skellysim_tpu.ops.pallas_kernels import stokeslet_pallas

            rng = np.random.default_rng(1)
            r = jnp.asarray(rng.uniform(-5, 5, (n32, 3)), dtype=jnp.float32)
            f = jnp.asarray(rng.standard_normal((n32, 3)), dtype=jnp.float32)
            prate = _rate(lambda: stokeslet_pallas(r, r, f, 1.0), n32 * n32)
            extra["stokeslet_f32_pallas"] = {"gpairs_per_s": round(prate / 1e9, 4)}
            rate32 = max(rate32, prate)
        except Exception as e:
            extra["stokeslet_f32_pallas"] = {"error": _short_err(e)}
        try:
            from skellysim_tpu.ops.pallas_kernels import stresslet_pallas

            rng = np.random.default_rng(2)
            r = jnp.asarray(rng.uniform(-5, 5, (n32, 3)), dtype=jnp.float32)
            s = jnp.asarray(rng.standard_normal((n32, 3, 3)),
                            dtype=jnp.float32)
            srate = _rate(lambda: stresslet_pallas(r, r, s, 1.0), n32 * n32)
            extra["stresslet_f32_pallas"] = {
                "gpairs_per_s": round(srate / 1e9, 4)}
        except Exception as e:
            extra["stresslet_f32_pallas"] = {"error": _short_err(e)}
        ck()

    # MFU estimate against the chip's dense peak (bf16 for TPUs)
    if rate32 is not None and extra.get("device_kind"):
        kind = str(extra["device_kind"]).lower()
        peak = next((p for sub, p in PEAK_FLOPS if sub in kind), None)
        if peak:
            extra["mfu_f32_est"] = round(
                rate32 * STOKESLET_FLOPS_PER_PAIR / peak, 4)
            extra["mfu_assumed_peak_tflops"] = peak / 1e12
    ck()


def _group_scale(extra, ck, on_acc):
    """BASELINE #4 (640k dense matvec) + the Ewald crossover ladder."""
    import jax.numpy as jnp

    def ck_section(key):
        """Store ``key``'s partial dict (downscale-marked on fallback) + ck."""
        def store(partial):
            extra[key] = dict(partial)
            if not on_acc:
                _mark_downscaled(extra[key], _CPU_FALLBACK)
            ck()
        return store

    ck_640k = ck_section("dense_matvec_10k_fibers")
    try:
        ck_640k(_bench_640k_matvec(10000 if on_acc else 100, 64, jnp.float32,
                                   ck=ck_640k))
    except Exception as e:
        extra["dense_matvec_10k_fibers"] = {"error": _short_err(e)}
    ck()

    dm = extra.get("dense_matvec_10k_fibers", {})
    if "wall_s_per_matvec" in dm:
        w8 = dm["projected_v5p8_wall_s"]
        extra["fmm_go_no_go"] = {
            "measured": f"dense {dm['n_nodes']}-node matvec "
                        f"{dm['wall_s_per_matvec']}s on one chip; /8 ring "
                        f"projection {w8}s on v5p-8",
            "verdict": ("dense viable" if w8 <= 1.0 else
                        "dense marginal — hierarchical evaluator warranted"),
            "note": "STKFMM at 640k sources on 32 CPU ranks is O(1s)/eval "
                    "(PVFMM ~1e6-1e7 pts/s/core class); >=10x needs the "
                    "projected 8-chip matvec under ~0.1s",
        }
        ck()

    ck_table = ck_section("ewald_crossover")
    try:
        ck_table(_bench_ewald_crossover(on_acc, jnp.float32, ck=ck_table))
    except Exception as e:
        extra["ewald_crossover"] = {"error": _short_err(e)}
    ck()


def _group_solves(extra, ck, on_acc):
    import jax.numpy as jnp

    dtype = jnp.float32 if on_acc else jnp.float64
    tol = 1e-8 if on_acc else 1e-10
    try:
        extra["single_fiber"] = _bench_single_fiber(dtype, tol)
    except Exception as e:
        extra["single_fiber"] = {"error": _short_err(e)}
    ck()
    try:
        # the honest accuracy configuration (f64 explicit residual <= 1e-10)
        extra["single_fiber_mixed"] = _bench_single_fiber(
            jnp.float64, 1e-10, mixed=True)
    except Exception as e:
        extra["single_fiber_mixed"] = {"error": _short_err(e)}
    ck()

    # trajectory frame encode at BASELINE scale (10k fibers x 64 nodes)
    try:
        from skellysim_tpu.fibers import container as fc
        from skellysim_tpu.io.trajectory import frame_bytes
        from skellysim_tpu.system.system import SimState

        rng = np.random.default_rng(7)
        xf = jnp.asarray(rng.standard_normal((10000, 64, 3)), dtype=jnp.float32)
        big = fc.make_group(xf, lengths=1.0, bending_rigidity=0.01,
                            radius=0.0125, dtype=jnp.float32)
        st = SimState(time=jnp.float32(0.0), dt=jnp.float32(0.1), fibers=big,
                      points=None, background=None)
        frame_bytes(st)  # warm the device->host paths
        t0 = time.perf_counter()
        buf = frame_bytes(st)
        extra["frame_encode_10k"] = {
            "encode_s": round(time.perf_counter() - t0, 3),
            "frame_mb": round(len(buf) / 1e6, 1)}
        del big, st, xf
    except Exception as e:
        extra["frame_encode_10k"] = {"error": _short_err(e)}
    ck()


def _group_coupled(extra, ck, on_acc):
    import jax.numpy as jnp

    dtype = jnp.float32 if on_acc else jnp.float64
    tol = 1e-8 if on_acc else 1e-10
    scales = [6000, 2000, 600] if on_acc else [600]
    out = _bench_coupled_ladder(scales, 400, dtype, tol, mixed=False)
    if not on_acc:
        _mark_downscaled(out, _CPU_FALLBACK)
    extra["coupled_solve"] = out
    ck()

    # MXU matmul-form kernel tiles at the scale the f32 solve survived —
    # BEFORE the mixed ladder, whose f64 shell build evicts the cached f32
    # operator this repeat reuses (the dtype-scoped cache keeps one dtype
    # per geometry to protect HBM headroom)
    cs = extra.get("coupled_solve", {})
    if "wall_s" in cs and _remaining() > 90:
        try:
            extra["coupled_solve_mxu_kernels"] = _bench_coupled(
                cs["shell_n"], 400, dtype, tol, kernel_impl="mxu")
        except Exception as e:
            extra["coupled_solve_mxu_kernels"] = {"error": _short_err(e)}
        ck()

    # mixed precision at the reference's tolerance (f64 state): the
    # apples-to-apples number against 0.328 s at 4.6e-11
    out = _bench_coupled_ladder(scales, 400, jnp.float64, 1e-10, mixed=True)
    if not on_acc:
        _mark_downscaled(out, _CPU_FALLBACK)
    extra["coupled_solve_mixed"] = out
    ck()


def _group_cells(extra, ck, on_acc):
    import jax.numpy as jnp

    dtype = jnp.float32 if on_acc else jnp.float64
    tol = 1e-8 if on_acc else 1e-10
    # BASELINE #3: ellipsoid + 1k clamped fibers
    if _remaining() > 120:
        try:
            out = _bench_fiber_shell(
                "ellipsoid", 1000 if on_acc else 16, 64,
                6000 if on_acc else 192, dtype, tol)
            if not on_acc:
                _mark_downscaled(out, _CPU_FALLBACK)
            extra["ellipsoid_1k_fibers"] = out
        except Exception as e:
            extra["ellipsoid_1k_fibers"] = {"error": _short_err(e)}
    else:
        extra["ellipsoid_1k_fibers"] = {"skipped_budget": int(_remaining())}
    ck()

    # BASELINE #5: oocyte (surface of revolution) + fibers
    if _remaining() > 120:
        try:
            out = _bench_fiber_shell(
                "revolution", 1000 if on_acc else 16, 32,
                6000 if on_acc else 200, dtype, tol)
            if not on_acc:
                _mark_downscaled(out, _CPU_FALLBACK)
            extra["oocyte_fibers"] = out
        except Exception as e:
            extra["oocyte_fibers"] = {"error": _short_err(e)}
    else:
        extra["oocyte_fibers"] = {"skipped_budget": int(_remaining())}
    ck()


def _bench_ensemble_throughput(B, n_fibers, n_nodes, dtype, rounds=6):
    """steps/s of the vmapped batched trial step at lane count B, plus the
    B=1 sequential-step baseline the speedup is measured against."""
    from __graft_entry__ import _make_system
    from skellysim_tpu.ensemble import EnsembleRunner

    system, base = _make_system(n_fibers=n_fibers, n_nodes=n_nodes,
                                dtype=dtype)
    states = [base._replace(fibers=base.fibers._replace(
        x=base.fibers.x + 0.01 * i)) for i in range(B)]
    runner = EnsembleRunner(system, batch_impl="vmap")
    # far-future t_final: every lane live for the whole measurement
    ens = runner.make_ensemble(states, [1e9] * B)

    def once():
        nonlocal ens
        ens, info = runner.step(ens)
        return info.iters

    np.asarray(once())  # compile + warm + drain
    t0 = time.perf_counter()
    for _ in range(rounds):
        out = once()
    np.asarray(out)  # host fetch: the real completion barrier
    wall = time.perf_counter() - t0
    return {"B": B, "steps_per_s": round(B * rounds / wall, 2),
            "batched_step_wall_s": round(wall / rounds, 4)}


def _group_ensemble(extra, ck, on_acc):
    """Satellite of ISSUE 2: the batching win — members/s and steps/s vs B
    at fixed small N (the regime where one member leaves the chip idle)."""
    import jax.numpy as jnp

    dtype = jnp.float32 if on_acc else jnp.float64
    n_fibers, n_nodes = (8, 32) if on_acc else (2, 16)
    b_ladder = (1, 8, 32, 128) if on_acc else (1, 4, 8)
    table = {}
    base_rate = None
    for B in b_ladder:
        if _remaining() < 60:
            table[f"B{B}"] = {"skipped_budget": int(_remaining())}
            continue
        try:
            row = _bench_ensemble_throughput(B, n_fibers, n_nodes, dtype)
            if B == 1:
                # the speedup baseline is the B=1 rung SPECIFICALLY; if it
                # errored or was budget-skipped, later rungs record rates
                # only (a surviving rung must never pose as its own baseline)
                base_rate = row["steps_per_s"]
            if base_rate is not None:
                row["speedup_vs_B1"] = round(row["steps_per_s"] / base_rate,
                                             2)
            table[f"B{B}"] = row
        except Exception as e:
            table[f"B{B}"] = {"error": _short_err(e)}
        ck()
    out = {"n_fibers": n_fibers, "n_nodes": n_nodes, "ladder": table}

    # end-to-end members/s through the continuous-batching scheduler
    # (retire + backfill included): 2B tiny members through B lanes
    if _remaining() > 60:
        try:
            import dataclasses

            from __graft_entry__ import _make_system
            from skellysim_tpu.ensemble import (EnsembleRunner,
                                                EnsembleScheduler, MemberSpec)

            B = 32 if on_acc else 4
            system, base = _make_system(n_fibers=n_fibers, n_nodes=n_nodes,
                                        dtype=dtype)
            system.params = dataclasses.replace(system.params,
                                                adaptive_timestep_flag=False)
            members = [MemberSpec(
                member_id=f"m{i}",
                state=base._replace(fibers=base.fibers._replace(
                    x=base.fibers.x + 0.01 * i)),
                t_final=8 * 1e-3) for i in range(2 * B)]
            runner = EnsembleRunner(system, batch_impl="vmap")
            # warm the compiled step on a throwaway scheduler round
            EnsembleScheduler(runner, members[:B], B, max_rounds=1).run()
            t0 = time.perf_counter()
            sched = EnsembleScheduler(runner, members, B)
            retired = sched.run()
            wall = time.perf_counter() - t0
            out["scheduler"] = {
                "B": B, "members": len(members),
                "members_retired": len(retired),
                "steps_per_member": 8, "rounds": sched.rounds,
                "members_per_s": round(len(retired) / wall, 2),
                "wall_s": round(wall, 2)}
        except Exception as e:
            out["scheduler"] = {"error": _short_err(e)}
    if not on_acc:
        _mark_downscaled(out, _CPU_FALLBACK)
    extra["ensemble"] = out
    ck()


def _scenario_scene(dtype, n_sites=4, shell_n=60):
    """(system, member-state factory) for a small confined DI scene:
    confining sphere + nucleating body + growing fibers — the oocyte-class
    shape at bench scale (docs/scenarios.md)."""
    import jax.numpy as jnp
    import numpy as np

    from skellysim_tpu.bodies import bodies as bd
    from skellysim_tpu.fibers import container as fc
    from skellysim_tpu.params import DynamicInstability, Params
    from skellysim_tpu.periphery import periphery as peri
    from skellysim_tpu.periphery.precompute import (precompute_body,
                                                    precompute_periphery)
    from skellysim_tpu.system import System

    params = Params(
        eta=1.0, dt_initial=0.02, dt_write=0.02, t_final=0.08,
        gmres_tol=1e-6 if dtype == jnp.float32 else 1e-8,
        adaptive_timestep_flag=False,
        dynamic_instability=DynamicInstability(
            n_nodes=8, v_growth=0.2, f_catastrophe=0.5,
            nucleation_rate=60.0, min_length=0.3, radius=0.0125,
            bending_rigidity=0.01))
    pdata = precompute_periphery("sphere", n_nodes=shell_n, radius=2.5,
                                 eta=1.0)
    shell = peri.make_state(pdata["nodes"], pdata["normals"],
                            pdata["quadrature_weights"],
                            pdata["stresslet_plus_complementary"],
                            pdata["M_inv"], dtype=dtype)
    shape = peri.PeripheryShape(kind="sphere", radius=2.5)
    bdata = precompute_body("sphere", 40, radius=0.4)
    rng = np.random.default_rng(5)
    sites = rng.standard_normal((n_sites, 3))
    sites = 0.4 * sites / np.linalg.norm(sites, axis=1, keepdims=True)
    bodies = bd.make_group(bdata["node_positions_ref"],
                           bdata["node_normals_ref"], bdata["node_weights"],
                           nucleation_sites_ref=sites[None], radius=0.4,
                           dtype=dtype)

    def member_state(system, i):
        x = np.tile(np.linspace(0.0, 0.8, 8)[None, :, None], (2, 1, 3))
        x += 0.6 + 0.02 * i
        fibers = fc.make_group(x, lengths=0.8 * np.sqrt(3.0),
                               bending_rigidity=0.01, radius=0.0125,
                               dtype=dtype)
        return system.make_state(fibers=fibers, bodies=bodies, shell=shell)

    return System(params, shell_shape=shape), params, member_state


def _group_scenarios(extra, ck, on_acc):
    """ISSUE 13 acceptance: members/s vs B for a DI-enabled CONFINED scene
    on the ensemble vmap path (in-trace nucleation/catastrophe +
    scheduler-driven growth reseats) — the oocyte-class workload the
    scenario subsystem unlocks. CPU-downscale-flagged like every group."""
    import time as _t

    import jax.numpy as jnp

    from skellysim_tpu.ensemble import EnsembleRunner, MemberSpec
    from skellysim_tpu.scenarios import ScenarioEnsemble
    from skellysim_tpu.utils.rng import SimRNG

    dtype = jnp.float64  # DI length/rate arithmetic is f64 on both paths
    b_ladder = (1, 8, 32) if on_acc else (1, 2, 4)
    system, params, member_state = _scenario_scene(dtype)
    steps_per_member = max(int(round(params.t_final / params.dt_initial)), 1)

    table = {}
    base_rate = None
    runner = EnsembleRunner(system, batch_impl="vmap")
    for B in b_ladder:
        if _remaining() < 60:
            table[f"B{B}"] = {"skipped_budget": int(_remaining())}
            continue
        try:
            def members(n0=0, n=2 * B):
                return [MemberSpec(
                    member_id=f"m{n0 + i}",
                    state=member_state(system, n0 + i),
                    t_final=params.t_final,
                    rng=SimRNG(23).member(n0 + i)) for i in range(n)]

            # warm the rung programs on a throwaway sweep (compile +
            # growth-reseat rungs), then measure the warm drain
            ScenarioEnsemble(system, members(1000, B), B,
                             runner=runner).run(max_rounds=80)
            t0 = _t.perf_counter()
            records = []
            se = ScenarioEnsemble(system, members(), B, runner=runner,
                                  metrics=records.append)
            finished = se.run(max_rounds=200)
            wall = _t.perf_counter() - t0
            steps = [r for r in records if r.get("event") == "step"]
            row = {"B": B, "members": 2 * B,
                   "members_retired": len(finished),
                   "members_per_s": round(len(finished) / wall, 3),
                   "steps_per_member": steps_per_member,
                   "nucleations": sum(r["nucleations"] for r in steps),
                   "catastrophes": sum(r["catastrophes"] for r in steps),
                   "growth_reseats": se.reseats,
                   "rungs": sorted(se._scheds),
                   "wall_s": round(wall, 2)}
            if B == 1:
                base_rate = row["members_per_s"]
            if base_rate:
                row["speedup_vs_B1"] = round(
                    row["members_per_s"] / base_rate, 2)
            table[f"B{B}"] = row
        except Exception as e:
            table[f"B{B}"] = {"error": _short_err(e)}
        ck()
    out = {"scene": "confined (shell 60 + body 40 + DI fibers cap 2->rungs)",
           "ladder": table}
    if not on_acc:
        _mark_downscaled(out, _CPU_FALLBACK)
    extra["scenarios"] = out
    # archived round: `obs perf --compare` diffs members_per_s across
    # rounds like the multichip/treecode ladders (skelly-flight satellite)
    _archive_round("SCENARIOS", SCENARIOS_ROUND, out, extra)
    ck()


#: current multichip measurement round; bumping this IS the re-measurement
#: protocol — the new round lands at the repo root, every round (old and
#: new) is archived under benchmarks/, stale root rounds are pruned
#: (artifact hygiene, ISSUE 8: r01..r05 no longer accumulate at the root).
#: r08 (skelly-roofline): the d4/d8 coupled ladder re-pinned at the
#: post-spectral/maskflow tree via the first `--campaign` run.
MULTICHIP_ROUND = "r08"

#: current treecode measurement round (root TREECODE_<round>.json + the
#: benchmarks/ mirror, same hygiene as the multichip ladder)
TREECODE_ROUND = "r06"

#: current measurement round per benchmarks/-only archived group
#: (<GROUP>_rNN.json naming, the `obs perf --compare` convention);
#: bumping a constant IS that group's re-measurement protocol — except
#: under `--campaign`, which auto-bumps every archived group to the next
#: free round number (BENCH_ROUND_<GROUP>, set by the parent) so a
#: campaign NEVER silently rewrites checked-in history
SCENARIOS_ROUND = "r01"
COMPILE_ROUND = "r01"
FLIGHT_ROUND = "r01"

#: where archived rounds land; BENCH_ARCHIVE_DIR redirects (the bench
#: contract test points it at a tmp dir so a budget-starved smoke run
#: never pollutes the real history the perf gate diffs)
BENCH_ARCHIVE_DIR = os.environ.get(
    "BENCH_ARCHIVE_DIR",
    os.path.join(os.path.dirname(os.path.abspath(__file__)), "benchmarks"))

#: uniform provenance stamp on every bench round artifact — pinned by
#: tests/test_bench_contract.py across ALL groups (skelly-roofline):
#: `downscaled` is an EXPLICIT bool (false on real-backend rounds, not
#: merely absent), so the perf gate's arming condition is readable off
#: any artifact without knowing which bench wrote it
PROVENANCE_KEYS = ("backend", "jax_version", "device_kind", "downscaled",
                   "telemetry_version")


def _round_id(group: str, default: str) -> str:
    """The round a group archives under: the checked-in constant for
    manual `--group` runs, the parent's auto-bumped BENCH_ROUND_<GROUP>
    under `--campaign`."""
    return os.environ.get(f"BENCH_ROUND_{group.upper()}", default)


def _next_round_id(group: str) -> str:
    """First free rNN for a group across the archive dir AND the repo
    root (the treecode history starts root-only) — campaign runs append
    rounds, never overwrite them."""
    pat = re.compile(rf"^{group.upper()}_r(\d+)\.json$")
    best = 0
    here = os.path.dirname(os.path.abspath(__file__))
    for d in (BENCH_ARCHIVE_DIR, here):
        try:
            names = os.listdir(d)
        except OSError:
            continue
        for fname in names:
            m = pat.match(fname)
            if m:
                best = max(best, int(m.group(1)))
    return f"r{best + 1:02d}"


def _stamp_provenance(payload: dict, extra: dict, generated_by: str) -> dict:
    """The ONE stamping path every bench artifact writer goes through
    (PROVENANCE_KEYS, skelly-roofline): backend/jax_version/device_kind
    from the child's `obs.tracer.provenance()` values in ``extra``, the
    downscale flag coerced to an explicit bool, the telemetry version."""
    payload["generated_by"] = generated_by
    for key in ("backend", "jax_version", "device_kind"):
        payload[key] = extra.get(key)
    payload["downscaled"] = bool(payload.get("downscaled"))
    payload["telemetry_version"] = TELEMETRY_VERSION
    return payload


def _archive_round(group: str, round_id: str, doc: dict, extra: dict):
    """Mirror one group's finished section under benchmarks/ as
    ``<GROUP>_rNN.json`` so `obs perf --compare` diffs its gated ratios
    (members_per_s / warm_speedup / steps_per_s ...) across rounds — the
    scenarios/compile/flight answer to the multichip/treecode history
    (skelly-pulse; docs/performance.md). Provenance-stamped like every
    artifact; hygiene must never cost a measurement."""
    round_id = _round_id(group, round_id)
    payload = _stamp_provenance(dict(doc), extra,
                                f"bench.py --group {group.lower()}")
    payload["round"] = round_id
    try:
        os.makedirs(BENCH_ARCHIVE_DIR, exist_ok=True)
        path = os.path.join(BENCH_ARCHIVE_DIR,
                            f"{group.upper()}_{round_id}.json")
        with open(path, "w") as fh:
            json.dump(payload, fh, indent=1)
            fh.write("\n")
    except Exception:
        pass


def _archive_root_round(group: str, doc: dict):
    """Mirror a root-artifact round (MULTICHIP/TREECODE) under the
    archive dir and prune stale root rounds so only the LATEST round
    lives at the repo root (docs/performance.md cites
    `benchmarks/<GROUP>_r*.json` for history). Redirected runs
    (BENCH_<GROUP>_PATH set — the contract smoke) archive nothing."""
    if os.environ.get(f"BENCH_{group.upper()}_PATH"):
        return
    import glob

    here = os.path.dirname(os.path.abspath(__file__))
    current = f"{group.upper()}_{doc.get('round')}.json"
    try:
        os.makedirs(BENCH_ARCHIVE_DIR, exist_ok=True)
        with open(os.path.join(BENCH_ARCHIVE_DIR, current), "w") as fh:
            json.dump(doc, fh, indent=1)
            fh.write("\n")
        for p in glob.glob(os.path.join(here,
                                        f"{group.upper()}_r*.json")):
            if os.path.basename(p) != current:
                os.remove(p)
    except Exception:
        pass  # hygiene must never cost a measurement


def _multichip_json_path(round_id: str) -> str:
    """Repo-root artifact the multichip group writes (ISSUE 3: the
    measured strong-scaling ladder). BENCH_MULTICHIP_PATH redirects it
    (the bench contract test points it at a tmp file so a budget-starved
    smoke run never clobbers the real ladder)."""
    return os.environ.get(
        "BENCH_MULTICHIP_PATH",
        os.path.join(os.path.dirname(os.path.abspath(__file__)),
                     f"MULTICHIP_{round_id}.json"))


def _treecode_json_path(round_id: str) -> str:
    """Repo-root artifact the treecode group writes (ISSUE 6: the
    measured O(N^2) -> O(N log N) crossover). BENCH_TREECODE_PATH
    redirects it, same contract as the multichip path."""
    return os.environ.get(
        "BENCH_TREECODE_PATH",
        os.path.join(os.path.dirname(os.path.abspath(__file__)),
                     f"TREECODE_{round_id}.json"))


def _bench_multichip_matvec(n_dev, r, f, mesh_cache):
    """Ring-sharded dense Stokeslet matvec wall on the first n_dev devices."""
    import jax.numpy as jnp  # noqa: F401  (keeps the import pattern uniform)

    from skellysim_tpu.ops import kernels
    from skellysim_tpu.parallel import make_mesh
    from skellysim_tpu.parallel.ring import ring_stokeslet

    n = r.shape[0]
    if n_dev == 1:
        rate = _rate(lambda: kernels.stokeslet_direct(r, r, f, 1.0), n * n,
                     trials=2)
    else:
        mesh = mesh_cache.setdefault(n_dev, make_mesh(n_dev))
        rate = _rate(lambda: ring_stokeslet(r, r, f, 1.0, mesh=mesh), n * n,
                     trials=2)
    return {"wall_s": round(n * n / rate, 4),
            "gpairs_per_s": round(rate / 1e9, 4)}


def _bench_multichip_coupled(n_dev, scene, mesh_cache):
    """Full coupled implicit step through the SPMD shard_map program
    (`parallel.spmd`) on the first n_dev devices; returns wall + residual
    (+ the solution for cross-device-count parity)."""
    from skellysim_tpu.parallel import make_mesh, shard_state

    system, state = scene()
    mesh = mesh_cache.setdefault(n_dev, make_mesh(n_dev))
    state = shard_state(state, mesh)

    def once():
        _, sol, info = system.step_spmd(state, mesh, donate=False)
        return sol, info

    sol, info = once()
    np.asarray(sol)  # compile + warm + drain
    t0 = time.perf_counter()
    for _ in range(2):
        sol, info = once()
    sol_host = np.asarray(sol)  # host fetch: the real completion barrier
    wall = (time.perf_counter() - t0) / 2
    return {"wall_s": round(wall, 4), "iters": int(info.iters),
            "residual_true": float(info.residual_true)}, sol_host


def _group_multichip(extra, ck, on_acc):
    """ISSUE 3: the measured strong-scaling ladder (1 -> 2 -> 4 -> 8
    devices) for the dense matvec AND the full coupled SPMD solve, with
    residual/solution parity against the 1-device run. Emits
    MULTICHIP_<round>.json at the repo root + benchmarks/ archive
    (downscale-flagged on the virtual
    CPU mesh like every other section)."""
    import jax
    import jax.numpy as jnp

    n_avail = len(jax.devices())
    ladder = [d for d in (1, 2, 4, 8) if d <= n_avail]
    out = {"devices_available": n_avail, "ladder": ladder}
    if not on_acc:
        _mark_downscaled(out, _CPU_FALLBACK)
    extra["multichip"] = out
    ck()

    def publish():
        # provenance (skelly-pulse): the round artifact self-describes the
        # runtime + hardware it measured (obs.tracer.provenance, stamped
        # into `extra` by _child_main)
        doc = _stamp_provenance(dict(out), extra,
                                "bench.py --group multichip")
        doc["round"] = _round_id("multichip", MULTICHIP_ROUND)
        try:
            with open(_multichip_json_path(doc["round"]), "w") as fh:
                json.dump(doc, fh, indent=1)
                fh.write("\n")
            out.pop("artifact_error", None)
            _archive_root_round("multichip", doc)
        except Exception as e:
            # never crash the measurement over an unwritable artifact path,
            # but never hide it either — the marker rides into BENCH.json
            out["artifact_error"] = _short_err(e)

    # --- matvec ladder (the 640k-node BASELINE measurement; CPU downscaled)
    n_nodes = 640000 if on_acc else 6400
    rng = np.random.default_rng(100)
    n_fibers = n_nodes // 64
    box = 20.0 * (n_nodes / 640000.0) ** (1.0 / 3.0)
    origins = rng.uniform(-box / 2, box / 2, (n_fibers, 3))
    dirs = rng.normal(size=(n_fibers, 3))
    dirs /= np.linalg.norm(dirs, axis=1, keepdims=True)
    t = np.linspace(0, 1.0, 64)
    r = jnp.asarray((origins[:, None, :]
                     + t[None, :, None] * dirs[:, None, :]).reshape(-1, 3),
                    dtype=jnp.float32)
    f = jnp.asarray(rng.standard_normal((n_nodes, 3)), dtype=jnp.float32)

    mesh_cache = {}
    mv = {"n_nodes": n_nodes}
    out["matvec"] = mv  # attached up front so skip markers survive
    for d in ladder:
        if _remaining() < 60:
            mv[f"d{d}"] = {"skipped_budget": int(_remaining())}
            ck()
            continue
        try:
            row = _bench_multichip_matvec(d, r, f, mesh_cache)
            base = mv.get("d1", {}).get("wall_s")
            if base and row["wall_s"]:
                row["speedup_vs_1dev"] = round(base / row["wall_s"], 2)
            mv[f"d{d}"] = row
        except Exception as e:
            mv[f"d{d}"] = {"error": _short_err(e)}
        ck()
        publish()

    # --- full coupled SPMD solve ladder (fibers + shell + forced body).
    # r07 (ISSUE 8): the ladder runs the communication-avoiding solver
    # (gmres_block_s=4 — 2 batched Gram psums per 4 Krylov iterations
    # instead of 12 sequential rounds) at a scene where compute/comm
    # balance is honest: the r06 CPU downscale (16x16) was so small that
    # per-round dispatch noise swamped the solve; 32 fibers x 32 nodes
    # keeps the CPU rung compile-affordable while the matvec does real work
    n_fib = 256 if on_acc else 32
    n_nod = 32

    def scene():
        import dataclasses

        from __graft_entry__ import _make_system

        system, state = _make_system(
            n_fibers=n_fib, n_nodes=n_nod, dtype=jnp.float64, coupled=True)
        system.params = dataclasses.replace(system.params, gmres_tol=1e-10,
                                            gmres_block_s=4)
        return system, state

    cp = {"n_fibers": n_fib, "n_nodes": n_nod, "shell_n": 56, "body_n": 50,
          "gmres_block_s": 4}
    out["coupled_spmd"] = cp  # attached up front so skip markers survive
    sol_1dev = None
    for d in ladder:
        if _remaining() < 75:
            cp[f"d{d}"] = {"skipped_budget": int(_remaining())}
            ck()
            continue
        try:
            row, sol = _bench_multichip_coupled(d, scene, mesh_cache)
            if d == 1:
                sol_1dev = sol
            elif sol_1dev is not None:
                row["sol_err_vs_1dev"] = float(np.abs(sol - sol_1dev).max())
            base = cp.get("d1", {}).get("wall_s")
            if base and row["wall_s"]:
                row["speedup_vs_1dev"] = round(base / row["wall_s"], 2)
            cp[f"d{d}"] = row
        except Exception as e:
            cp[f"d{d}"] = {"error": _short_err(e)}
        ck()
        publish()
    publish()  # always leave an artifact, even if every rung was skipped


def _group_collectives(extra, ck, on_acc):
    """ISSUE 8: the collective-latency budget of the coupled solve —
    the measurements behind the s-step solver and the fused rings.

    (a) psum round latency vs payload on the full mesh: per-iteration
        GMRES dots are LATENCY-bound (a [101] f32 psum moves 404 bytes;
        its wall is all launch+sync), which is why batching rounds wins;
    (b) the s-step exchange itself: s sequential masked-dot psums
        ([m+1] each) vs ONE batched [(m+1)+s, s] Gram psum — the exact
        orthogonalization traffic `solver.gmres(block_s=s)` replaces;
    (c) ring-vs-fused matvec: the ppermute source-block ring against the
        fused Pallas `make_async_remote_copy` kernel
        (`parallel.ring_fused`; TPU-only — the CPU fallback records the
        build-time mode so the artifact says WHICH path it measured).
    """
    import jax
    import jax.numpy as jnp
    from jax import lax
    from jax.sharding import PartitionSpec as P

    from skellysim_tpu.parallel import make_mesh
    from skellysim_tpu.parallel.compat import fused_ring_mode, shard_map
    from skellysim_tpu.parallel.mesh import FIBER_AXIS

    n_dev = min(8, len(jax.devices()))
    out = {"devices": n_dev}
    if not on_acc:
        _mark_downscaled(out, _CPU_FALLBACK)
    extra["collectives"] = out
    ck()
    if n_dev < 2:
        out["error"] = "needs a multi-device mesh"
        ck()
        return
    mesh = make_mesh(n_dev)
    reps = 32

    def _wall(fn, *args, trials=3):
        np.asarray(fn(*args))  # compile + warm
        t0 = time.perf_counter()
        for _ in range(trials):
            r = fn(*args)
        np.asarray(r)
        return (time.perf_counter() - t0) / trials

    # --- (a) chained psum rounds vs payload size
    rounds = {}
    out["psum_rounds"] = rounds
    for elems in (128, 2048, 32768, 262144):
        if _remaining() < 30:
            rounds[f"e{elems}"] = {"skipped_budget": int(_remaining())}
            ck()
            continue

        def local(x):
            def body(_, y):
                return lax.psum(y, FIBER_AXIS) * (1.0 / n_dev)
            return lax.fori_loop(0, reps, body, x)

        fn = jax.jit(shard_map(local, mesh=mesh, in_specs=(P(FIBER_AXIS),),
                               out_specs=P(FIBER_AXIS), check_vma=False))
        x = jnp.ones((elems,), dtype=jnp.float32)
        w = _wall(fn, x)
        rounds[f"e{elems}"] = {"us_per_round": round(w / reps * 1e6, 2),
                               "bytes": 4 * elems}
        ck()

    # --- (b) sequential dot psums vs one batched Gram psum (m=100, s=4)
    m, s, n = 100, 4, 8192
    if _remaining() > 30:
        rng = np.random.default_rng(7)
        V = jnp.asarray(rng.standard_normal((m + 1, n)), dtype=jnp.float32)
        W = jnp.asarray(rng.standard_normal((n, s)), dtype=jnp.float32)

        def seq(Vl, Wl):
            def body(_, carry):
                h = jnp.stack([lax.psum(Vl @ Wl[:, j], FIBER_AXIS)
                               for j in range(s)])   # s SEPARATE rounds
                return carry + h[0, 0] * 1e-30
            return lax.fori_loop(0, reps, body, jnp.float32(0.0))

        def batched(Vl, Wl):
            def body(_, carry):
                G = lax.psum(Vl @ Wl, FIBER_AXIS)    # ONE [m+1, s] round
                return carry + G[0, 0] * 1e-30
            return lax.fori_loop(0, reps, body, jnp.float32(0.0))

        spec = (P(None, FIBER_AXIS), P(FIBER_AXIS, None))
        w_seq = _wall(jax.jit(shard_map(seq, mesh=mesh, in_specs=spec,
                                        out_specs=P(), check_vma=False)),
                      V, W)
        w_bat = _wall(jax.jit(shard_map(batched, mesh=mesh, in_specs=spec,
                                        out_specs=P(), check_vma=False)),
                      V, W)
        out["gram_exchange"] = {
            "m": m, "s": s, "n": n,
            "sequential_us": round(w_seq / reps * 1e6, 2),
            "batched_us": round(w_bat / reps * 1e6, 2),
            "speedup": round(w_seq / w_bat, 2) if w_bat else None}
    else:
        out["gram_exchange"] = {"skipped_budget": int(_remaining())}
    ck()

    # --- (c) ring matvec: ppermute vs fused Pallas ring
    if _remaining() > 45:
        from skellysim_tpu.parallel.ring import ring_stokeslet

        n_pts = 4096 if on_acc else 1024
        rng = np.random.default_rng(11)
        r = jnp.asarray(rng.uniform(-2, 2, (n_pts, 3)), dtype=jnp.float32)
        f = jnp.asarray(rng.standard_normal((n_pts, 3)), dtype=jnp.float32)
        impl = "pallas" if on_acc else "exact"
        mode = fused_ring_mode("pallas")
        rv = {"n": n_pts, "impl": impl, "fused_ring_mode": mode}
        out["ring_matvec"] = rv
        try:
            os.environ["SKELLY_FUSED_RING"] = "0"
            jax.clear_caches()   # mode is a build-time choice, not a jit key
            w_ring = _wall(lambda: ring_stokeslet(r, r, f, 1.0, mesh=mesh,
                                                  impl=impl))
            rv["ppermute"] = {"wall_s": round(w_ring, 5),
                              "gpairs_per_s": round(
                                  n_pts * n_pts / w_ring / 1e9, 3)}
            if mode == "fused":
                os.environ.pop("SKELLY_FUSED_RING", None)
                jax.clear_caches()
                w_fused = _wall(lambda: ring_stokeslet(
                    r, r, f, 1.0, mesh=mesh, impl="pallas"))
                rv["fused"] = {"wall_s": round(w_fused, 5),
                               "gpairs_per_s": round(
                                   n_pts * n_pts / w_fused / 1e9, 3),
                               "speedup_vs_ppermute": round(
                                   w_ring / w_fused, 2) if w_fused else None}
        except Exception as e:
            rv["error"] = _short_err(e)
        finally:
            os.environ.pop("SKELLY_FUSED_RING", None)
    else:
        out["ring_matvec"] = {"skipped_budget": int(_remaining())}
    ck()


def _group_treecode(extra, ck, on_acc):
    """ISSUE 6: wall + pairs/sec for the dense Stokeslet tile vs the
    barycentric treecode (`ops.treecode`) at N in {1k, 4k, 16k, 64k}
    fiber-like source nodes in f32 at tol 1e-4 — the f32 Krylov-interior
    role the evaluator serves in the implicit solve. The tree's rate is
    EQUIVALENT dense pairs/sec (N^2 / wall), so tree_vs_direct > 1 means
    the treecode beats the O(N^2) tile outright; the smallest such N is
    the measured crossover, recorded in TREECODE_<round>.json
    (downscale-flagged on CPU like the MULTICHIP rounds)."""
    import jax.numpy as jnp

    from skellysim_tpu.ops import kernels
    from skellysim_tpu.ops import treecode as tcode

    tol = 1e-4
    out = {"tol": tol, "dtype": "float32",
           "ladder": [1024, 4096, 16384, 65536]}
    if not on_acc:
        _mark_downscaled(out, _CPU_FALLBACK)
    extra["treecode"] = out
    ck()

    def publish():
        doc = _stamp_provenance(dict(out), extra,
                                "bench.py --group treecode")
        doc["round"] = _round_id("treecode", TREECODE_ROUND)
        try:
            with open(_treecode_json_path(doc["round"]), "w") as fh:
                json.dump(doc, fh, indent=1)
                fh.write("\n")
            out.pop("artifact_error", None)
            _archive_root_round("treecode", doc)
        except Exception as e:
            # never crash the measurement over an unwritable artifact path,
            # but never hide it either — the marker rides into BENCH.json
            out["artifact_error"] = _short_err(e)

    rng = np.random.default_rng(61)
    crossover = None
    for n in out["ladder"]:
        if _remaining() < 45:
            out[f"n{n}"] = {"skipped_budget": int(_remaining())}
            ck()
            continue
        row = {}
        out[f"n{n}"] = row  # attached up front so error markers survive
        try:
            # constant-density fiber cloud (32-node fibers): the geometry
            # whose O(N^2) matvec wall this evaluator exists to break
            n_fib = max(n // 32, 1)
            box = 4.0 * (n / 1024.0) ** (1.0 / 3.0)
            origins = rng.uniform(-box / 2, box / 2, (n_fib, 3))
            dirs = rng.normal(size=(n_fib, 3))
            dirs /= np.linalg.norm(dirs, axis=1, keepdims=True)
            t = np.linspace(0.0, 1.0, 32)
            pts = (origins[:, None, :] + t[None, :, None] * dirs[:, None, :]
                   ).reshape(-1, 3)
            r = jnp.asarray(pts, dtype=jnp.float32)
            f = jnp.asarray(rng.standard_normal((n, 3)), dtype=jnp.float32)
            plan = tcode.plan_tree(pts, tol=tol)
            row["plan"] = {"depth": plan.depth, "order": plan.order,
                           "max_occ": plan.max_occ}
            rate_d = _rate(lambda: kernels.stokeslet_direct(r, r, f, 1.0),
                           n * n, trials=2)
            row["direct"] = {"gpairs_per_s": round(rate_d / 1e9, 4),
                             "wall_s": round(n * n / rate_d, 4)}
            rate_t = _rate(lambda: tcode.stokeslet_tree(plan, r, r, f, 1.0),
                           n * n, trials=2)
            row["tree"] = {"equiv_gpairs_per_s": round(rate_t / 1e9, 4),
                           "wall_s": round(n * n / rate_t, 4)}
            row["tree_vs_direct"] = round(rate_t / rate_d, 3)
            if crossover is None and rate_t > rate_d:
                crossover = n
                out["crossover_n"] = crossover
        except Exception as e:
            row["error"] = _short_err(e)
        ck()
        publish()
    out["crossover"] = (f"tree beats direct at N>={crossover}" if crossover
                        else "no crossover within the benched ladder")
    ck()
    publish()  # always leave an artifact, even if every rung was skipped


#: current spectral round (bump when re-measuring deliberately); archived
#: under benchmarks/ via `_archive_round` like the scenarios/compile rounds
SPECTRAL_ROUND = "r01"


def _group_spectral(extra, ck, on_acc):
    """ISSUE 17: wall + pairs/sec for the dense Stokeslet tile vs the
    spectral (particle-mesh) Ewald evaluator (`ops.spectral`) at N in
    {1k, 4k, 16k, 64k} constant-density triply-periodic clouds in f32 at
    tol 1e-4 — the f32 Krylov-interior role the evaluator serves in the
    implicit solve. The spectral rate is EQUIVALENT dense pairs/sec
    (N^2 / wall): since the evaluator is O(N log N), its equivalent rate
    must GROW ~linearly with N while the dense tile's stays flat —
    sub-quadratic scaling shows up as that growth, and the smallest N
    with spectral_vs_direct > 1 is the measured crossover
    (benchmarks/SPECTRAL_rNN.json; downscale-flagged on CPU like the
    treecode round). The dense tile is a FREE-SPACE sum — the comparison
    is wall-per-matvec for the solver slot, not numerical parity."""
    import jax.numpy as jnp

    from skellysim_tpu.ops import kernels
    from skellysim_tpu.ops import spectral as spec

    tol = 1e-4
    out = {"tol": tol, "dtype": "float32",
           "ladder": [1024, 4096, 16384, 65536]}
    if not on_acc:
        _mark_downscaled(out, _CPU_FALLBACK)
    extra["spectral"] = out
    ck()

    rng = np.random.default_rng(67)
    crossover = None
    for n in out["ladder"]:
        if _remaining() < 45:
            out[f"n{n}"] = {"skipped_budget": int(_remaining())}
            ck()
            continue
        row = {}
        out[f"n{n}"] = row  # attached up front so error markers survive
        try:
            # constant-density periodic cloud: the box grows as N^(1/3),
            # so the FFT grid rung ladder absorbs the scale-up while cell
            # occupancy stays flat
            box_L = 4.0 * (n / 1024.0) ** (1.0 / 3.0)
            box = (box_L, box_L, box_L)
            pts = rng.uniform(0.0, box_L, (n, 3))
            r = jnp.asarray(pts, dtype=jnp.float32)
            f = jnp.asarray(rng.standard_normal((n, 3)), dtype=jnp.float32)
            plan = spec.plan_spectral(pts, box, eta=1.0, tol=tol)
            row["plan"] = {"M3": list(plan.M3), "P": plan.P,
                           "xi": round(plan.xi, 3)}
            rate_d = _rate(lambda: kernels.stokeslet_direct(r, r, f, 1.0),
                           n * n, trials=2)
            row["direct"] = {"gpairs_per_s": round(rate_d / 1e9, 4),
                             "wall_s": round(n * n / rate_d, 4)}
            rate_s = _rate(
                lambda: spec.stokeslet_spectral(plan, r, r, f), n * n,
                trials=2)
            row["spectral"] = {"equiv_gpairs_per_s": round(rate_s / 1e9, 4),
                               "wall_s": round(n * n / rate_s, 4)}
            row["spectral_vs_direct"] = round(rate_s / rate_d, 3)
            if crossover is None and rate_s > rate_d:
                crossover = n
                out["crossover_n"] = crossover
        except Exception as e:
            row["error"] = _short_err(e)
        ck()
        _archive_round("SPECTRAL", SPECTRAL_ROUND, out, extra)
    out["crossover"] = (f"spectral beats direct at N>={crossover}"
                        if crossover
                        else "no crossover within the benched ladder")
    ck()
    # always leave an artifact, even if every rung was skipped
    _archive_round("SPECTRAL", SPECTRAL_ROUND, out, extra)


def _group_compile(extra, ck, on_acc):
    """skelly-bucket (ISSUE 12): the cold → warm → bucket-hit compile
    ladder. Three measured rungs per run entry point:

      * ``cold``  — a fresh process with an EMPTY persistent cache pays
        trace + full XLA compile for its scene's program;
      * ``warm``  — a second fresh process on the SAME cache dir pays
        trace + cache load only (the persistent-cache win every CLI now
        gets by default);
      * ``bucket_hit`` — a DIFFERENTLY-SHAPED scene landing in an
        already-compiled capacity bucket inside a running process pays
        neither: zero new `observed_jit` traces (the zero-compile pin),
        just a solve. Recorded for the single-run step and the ensemble
        batched step.
    """
    import subprocess
    import tempfile

    # per-rung bucket identities come from the measurements themselves
    # (key.describe() in each row) — the cold/warm rungs and the in-process
    # bucket-hit ladder deliberately use different fiber ladders
    out = {"scenes": ["3x16", "5x24", "2x8"]}
    extra["compile"] = out
    ck()

    # ---- cross-process cold vs warm (persistent cache) -----------------
    child_src = r"""
import json, os, sys, time
from skellysim_tpu.utils.bootstrap import (enable_compilation_cache,
                                           force_cpu_devices)
force_cpu_devices(None)
import jax
jax.config.update("jax_enable_x64", True)
enable_compilation_cache(os.environ["BENCH_COMPILE_CACHE"])
import numpy as np
from skellysim_tpu.audit import fixtures
from skellysim_tpu.system import buckets as bucket_mod
system = fixtures.make_system()
state = fixtures.free_state(system)
policy = bucket_mod.BucketPolicy(fiber_ladder=(16, 32), node_ladder=(32,))
state, key = bucket_mod.bucketize(state, policy)
t0 = time.perf_counter()
new_state, _, info = system.step(state)
float(info.residual)
print(json.dumps({"step_wall_s": round(time.perf_counter() - t0, 3),
                  "bucket": key.describe()}))
"""
    cache_dir = tempfile.mkdtemp(prefix="bench_compile_cache_")
    env = dict(os.environ, JAX_PLATFORMS="cpu",
               BENCH_COMPILE_CACHE=cache_dir)
    for rung in ("cold", "warm"):
        if _remaining() < 60:
            out[rung] = {"skipped_budget": int(_remaining())}
            ck()
            continue
        try:
            t0 = time.monotonic()
            res = subprocess.run(
                [sys.executable, "-c", child_src], env=env,
                capture_output=True, text=True,
                timeout=max(_remaining() - 10, 30))
            line = res.stdout.strip().splitlines()[-1]
            row = json.loads(line)
            row["process_wall_s"] = round(time.monotonic() - t0, 2)
            out[rung] = row
        except Exception as e:
            out[rung] = {"error": _short_err(e)}
        ck()
    if ("step_wall_s" in out.get("cold", {})
            and "step_wall_s" in out.get("warm", {})):
        out["warm_speedup"] = round(
            out["cold"]["step_wall_s"] / max(out["warm"]["step_wall_s"],
                                             1e-9), 2)

    # ---- in-process bucket hits (the zero-compile pin, measured) -------
    if _remaining() < 45:
        out["bucket_hit"] = {"skipped_budget": int(_remaining())}
        # the budget-skip path still stamps + archives: a partial round
        # carrying a gated warm_speedup must never reach the perf gate
        # un-flagged (downscaled CPU ratios are warn-only by design)
        if not on_acc:
            _mark_downscaled(out, _CPU_FALLBACK)
        _archive_round("COMPILE", COMPILE_ROUND, out, extra)
        ck()
        return
    try:
        import jax

        jax.config.update("jax_enable_x64", True)
        from skellysim_tpu.audit import fixtures
        from skellysim_tpu.system import BackgroundFlow
        from skellysim_tpu.system import buckets as bucket_mod

        policy = bucket_mod.BucketPolicy(fiber_ladder=(8, 16),
                                         node_ladder=(32,))
        system = fixtures.make_system()
        rows = []
        for n_fib, n_nodes, seed in ((3, 16, 1), (5, 24, 2), (2, 8, 3)):
            st = system.make_state(
                fibers=fixtures.make_fibers(n_fibers=n_fib, n_nodes=n_nodes,
                                            seed=seed),
                background=BackgroundFlow.make(uniform=(1.0, 0.0, 0.0)))
            st, key = bucket_mod.bucketize(st, policy)
            t0 = time.perf_counter()
            _, _, info = system.step(st)
            float(info.residual)
            rows.append({"scene": f"{n_fib}x{n_nodes}",
                         "wall_s": round(time.perf_counter() - t0, 3),
                         "traces": system._solve_jit.trace_count})
        out["bucket_hit"] = {
            "bucket": key.describe(), "steps": rows,
            # the acceptance pin, as a measured artifact: every scene after
            # the first rode the first's compiled program
            "zero_compile_hits": rows[-1]["traces"] == rows[0]["traces"]}
        hits = rows[1:]
        if hits and "step_wall_s" in out.get("cold", {}):
            # gated ratio for the perf history: a bucket hit vs the cold
            # compile — the warm-program win `obs perf --compare` tracks
            mean_hit = sum(r["wall_s"] for r in hits) / len(hits)
            out["bucket_hit"]["hit_speedup"] = round(
                out["cold"]["step_wall_s"] / max(mean_hit, 1e-9), 2)
    except Exception as e:
        out["bucket_hit"] = {"error": _short_err(e)}
    if not on_acc:
        _mark_downscaled(out, _CPU_FALLBACK)
    _archive_round("COMPILE", COMPILE_ROUND, out, extra)
    ck()


def _group_flight(extra, ck, on_acc):
    """skelly-flight (ISSUE 15): steps/s overhead of the armed physics
    flight recorder — the K=0 default program vs the K=32 armed twin
    (`Params.flight_window`, obs.flight) on the audit free-fiber fixture
    scene, measured WARM (the first step pays the compile outside the
    timed window). The acceptance bound is <=5% steps/s overhead on real
    hardware; CPU rounds are downscale-flagged like every group (toy
    walls swing +-35%, the perf gate warns instead of failing there)."""
    import time as _t

    import jax

    jax.config.update("jax_enable_x64", True)
    from skellysim_tpu.audit import fixtures

    out = {"scene": "audit free-fiber fixture (16 fibers x 16 nodes, f64)",
           "window": 32}
    if not on_acc:
        _mark_downscaled(out, _CPU_FALLBACK)
    extra["flight"] = out
    ck()

    def measure(window, steps=8):
        system = fixtures.make_system(flight_window=window)
        state = fixtures.free_state(system)
        state, _, info = system.step(state)     # compile + warm
        float(info.residual)
        t0 = _t.perf_counter()
        for _ in range(steps):
            state, _, info = system.step(state)
        float(info.residual)                    # device sync
        wall = _t.perf_counter() - t0
        return {"steps": steps, "wall_s": round(wall, 4),
                "steps_per_s": round(steps / wall, 3)}

    try:
        if _remaining() < 90:
            out["skipped_budget"] = int(_remaining())
        else:
            out["k0"] = measure(0)
            ck()
            out["k32"] = measure(32)
            r0 = out["k0"]["steps_per_s"]
            r32 = out["k32"]["steps_per_s"]
            # gated ratio (higher is better, 1.0 = free recorder): the
            # measured answer to "what does always-on flight cost"
            out["armed_vs_off"] = round(r32 / max(r0, 1e-9), 4)
            out["overhead_pct"] = round((1.0 - r32 / max(r0, 1e-9)) * 100.0,
                                        2)
    except Exception as e:
        out["error"] = _short_err(e)
    _archive_round("FLIGHT", FLIGHT_ROUND, out, extra)
    ck()


#: (name, budget weight) — children run in this order, each in its own
#: subprocess; weights split the remaining wall budget
GROUPS = [
    ("kernels", _group_kernels, 1.0),
    ("scale", _group_scale, 2.6),
    ("multichip", _group_multichip, 1.3),
    ("collectives", _group_collectives, 0.7),
    ("treecode", _group_treecode, 1.0),
    ("spectral", _group_spectral, 1.0),
    ("compile", _group_compile, 0.8),
    ("flight", _group_flight, 0.4),
    ("solves", _group_solves, 1.0),
    ("coupled", _group_coupled, 2.6),
    ("cells", _group_cells, 1.8),
    ("ensemble", _group_ensemble, 0.8),
    ("scenarios", _group_scenarios, 0.8),
]

#: campaign-profiled groups -> the program whose cost baseline the
#: roofline join apportions device time against (skelly-roofline); the
#: other groups run many unrelated modules, so a single-program join
#: would misattribute and they stay unprofiled
ROOFLINE_PROGRAMS = {
    "multichip": "step_spmd_d2",
    "treecode": "stokeslet_tree",
    "spectral": "stokeslet_spectral",
    "flight": "step_flight",
    "ensemble": "ensemble_step",
    "scenarios": "ensemble_step",
}


def _roofline_summary(profile_dir: str, group: str, extra: dict):
    """Trimmed per-phase roofline verdicts for the campaign manifest —
    the full report stays re-derivable from the profile dir via
    `obs roofline DIR`; a failed join is recorded, never fatal."""
    try:
        from skellysim_tpu.obs import roofline as rl

        doc = rl.roofline_report(profile_dir,
                                 program=ROOFLINE_PROGRAMS.get(group),
                                 device_kind=extra.get("device_kind"))
        return {
            "program": doc.get("program"),
            "device_kind": doc.get("device_kind"),
            "rated_as": doc.get("rated_as"),
            "attributed_frac": doc.get("attributed_frac"),
            "classified_frac": doc.get("classified_frac"),
            "phases": [{k: p.get(k) for k in
                        ("phase", "share", "comm_frac", "verdict",
                         "achieved_vs_peak")}
                       for p in doc.get("phases", [])[:12]],
        }
    except Exception as e:
        return {"error": _short_err(e)}


# ------------------------------------------------------------ child / parent

def _child_main(group: str, out_path: str):
    """Run one group's sections, checkpointing results to ``out_path``."""
    extra = {}

    def ck():
        try:
            tmp = out_path + ".tmp"
            with open(tmp, "w") as fh:
                json.dump(extra, fh)
            os.replace(tmp, out_path)
        except Exception:
            pass

    if os.environ.get("BENCH_FORCE_CPU") == "1":
        from skellysim_tpu.utils.bootstrap import force_cpu_devices

        # the multichip ladder and the collectives group need a virtual
        # 8-device mesh on the CPU fallback (mirroring the test strategy);
        # other groups keep the single-device platform so their numbers
        # stay comparable
        force_cpu_devices(8 if group in ("multichip", "collectives")
                          else None)
    import jax

    jax.config.update("jax_enable_x64", True)
    try:  # persistent compile cache: re-runs skip remote compiles — the
        # ONE implementation + min-compile-time threshold in
        # utils.bootstrap (shared with every CLI and the obs cost gate)
        from skellysim_tpu.utils.bootstrap import enable_compilation_cache

        enable_compilation_cache("auto")
    except Exception:
        pass
    extra["backend"] = jax.default_backend()
    # provenance stamp (skelly-pulse): jax_version/device_kind from the ONE
    # helper the telemetry header uses — bench artifacts and timelines
    # self-describe identically. Children import jax anyway; the jax-free
    # PARENT never calls this (it merges the children's values).
    from skellysim_tpu.obs.tracer import provenance

    extra.update(provenance())
    on_acc = extra["backend"] != "cpu"
    ck()

    fn = next(f for name, f, _ in GROUPS if name == group)
    prof_dir = os.environ.get("BENCH_PROFILE_DIR")

    def run():
        if not prof_dir:
            fn(extra, ck, on_acc)
            return
        # campaign mode (skelly-roofline): capture one device trace around
        # the whole group, then fold the roofline verdicts into the child
        # payload; profiling failures downgrade to an unprofiled run and
        # a recorded error — never a lost measurement
        try:
            from skellysim_tpu.obs.profile import profile_session
        except Exception as e:
            extra[f"roofline_{group}"] = {"error": _short_err(e)}
            fn(extra, ck, on_acc)
            return
        try:
            with profile_session(prof_dir):
                fn(extra, ck, on_acc)
        finally:
            extra[f"roofline_{group}"] = _roofline_summary(prof_dir, group,
                                                           extra)

    # skelly-scope: record the group through a span into the shared bench
    # trace stream (`obs summarize .bench_trace.jsonl` renders the per-group
    # wall breakdown); never let telemetry failures cost a measurement
    try:
        from skellysim_tpu.obs import tracer as obs_tracer

        tracer = obs_tracer.Tracer(BENCH_TRACE_PATH)
        scope = obs_tracer.use(tracer)
    except Exception:
        tracer, scope, obs_tracer = None, None, None
    if scope is not None:
        with scope:
            with obs_tracer.span("bench_group", group=group,
                                 backend=extra.get("backend")):
                run()
        tracer.close()
    else:
        run()
    extra["group_total_s"] = round(time.monotonic() - _T_START, 1)
    ck()


def _campaign_gate():
    """Arm `obs perf --compare` over the archive dir (subprocess — the
    parent stays jax-free) and capture rc + the machine report."""
    gate = {"rc": -1}
    cmd = [sys.executable, "-m", "skellysim_tpu.obs", "perf", "--compare",
           BENCH_ARCHIVE_DIR, "--json"]
    try:
        p = subprocess.run(cmd, capture_output=True, text=True, timeout=180)
        gate["rc"] = p.returncode
        try:
            gate["report"] = json.loads(p.stdout)
        except Exception as e:
            gate["report_error"] = _short_err(e)
    except Exception as e:
        gate["error"] = _short_err(e)
    return gate


def _parent_main(campaign: bool = False, groups_filter=None):
    extra = {}
    if groups_filter:
        known = {name for name, _, _ in GROUPS}
        unknown = [g for g in groups_filter if g not in known]
        if unknown:
            _emit({"metric": "bench_failed", "value": 0.0, "unit": "",
                   "vs_baseline": 0.0,
                   "error": "unknown campaign group(s): "
                            + ",".join(unknown),
                   "telemetry_version": TELEMETRY_VERSION})
            sys.exit(2)
        run_groups = [g for g in GROUPS if g[0] in set(groups_filter)]
    else:
        run_groups = GROUPS
    try:  # fresh span stream per bench run (children append per group)
        os.remove(BENCH_TRACE_PATH)
    except OSError:
        pass
    t_probe = time.perf_counter()
    probed, attempts = _probe_backend()
    extra["probe"] = {"backend": probed, "attempts": attempts,
                      "s": round(time.perf_counter() - t_probe, 1)}
    force_cpu = probed in (None, "cpu")
    if force_cpu:
        extra["downscaled"] = True
        extra["downscale_reason"] = _CPU_FALLBACK
    _checkpoint(extra)

    here = os.path.dirname(os.path.abspath(__file__))
    round_env, statuses, profile_root = {}, {}, None
    if campaign:
        # auto-bump every archived group to its next free round so the
        # campaign APPENDS history instead of rewriting checked-in rounds
        for g in ("multichip", "treecode", "spectral", "scenarios",
                  "compile", "flight"):
            round_env[f"BENCH_ROUND_{g.upper()}"] = _next_round_id(g)
        profile_root = os.environ.get(
            "BENCH_PROFILE_ROOT", os.path.join(here, ".bench_profile"))
        import shutil

        shutil.rmtree(profile_root, ignore_errors=True)
    backend = probed or "cpu"
    for i, (name, _, weight) in enumerate(run_groups):
        rem = _remaining()
        if rem < 50:
            extra[f"group_{name}"] = {"skipped_budget": int(rem)}
            statuses[name] = {"status": "skipped_budget", "s": 0.0}
            continue
        if force_cpu and rem > 180:
            # the tunnel is intermittent: one quick re-probe before each
            # group can promote the REST of the run back to TPU mid-bench
            # (VERDICT r4 #1) instead of finishing a whole round on the
            # CPU fallback because of a wedge at t=0
            re_backend = _probe_backend_once(timeout_s=60.0)
            if re_backend not in (None, "cpu"):
                force_cpu = False
                probed = backend = re_backend
                extra["probe_promoted"] = {"group": name,
                                           "backend": re_backend}
                if i == 0:
                    # nothing has run yet — the whole bench is TPU-clean;
                    # later promotions keep the flags because earlier
                    # groups' numbers in `extra` were measured on CPU
                    extra.pop("downscaled", None)
                    extra.pop("downscale_reason", None)
            rem = _remaining()  # a wedged re-probe burned up to 60 s
        wsum = sum(w for _, _, w in run_groups[i:])
        t_g = max(60.0, min(rem - 15.0, rem * weight / wsum))
        out_path = os.path.join(here, f".bench_{name}.json")
        try:
            os.remove(out_path)
        except OSError:
            pass
        env = dict(os.environ)
        env["BENCH_BUDGET_S"] = str(max(40.0, t_g - 15.0))
        env.update(round_env)
        if campaign and name in ROOFLINE_PROGRAMS:
            env["BENCH_PROFILE_DIR"] = os.path.join(profile_root, name)
        if force_cpu:
            env["BENCH_FORCE_CPU"] = "1"
        t0 = time.perf_counter()
        try:
            p = subprocess.run(
                [sys.executable, os.path.abspath(__file__), "--group", name,
                 "--out", out_path],
                env=env, timeout=t_g, stdout=subprocess.DEVNULL,
                stderr=subprocess.DEVNULL)
            rc = p.returncode
        except subprocess.TimeoutExpired:
            rc = "timeout"
        except Exception as e:
            rc = _short_err(e)
        info = {"rc": rc, "s": round(time.perf_counter() - t0, 1)}
        try:
            with open(out_path) as fh:
                child = json.load(fh)
            backend = child.pop("backend", backend) or backend
            extra["device_kind"] = child.pop("device_kind",
                                             extra.get("device_kind"))
            extra["jax_version"] = child.pop("jax_version",
                                             extra.get("jax_version"))
            child.pop("group_total_s", None)
            extra.update(child)
        except Exception:
            info["no_output"] = True
        if rc not in (0,):
            extra[f"group_{name}"] = info
        statuses[name] = {
            "status": ("ok" if rc == 0 else
                       "timeout" if rc == "timeout" else f"error rc={rc}"),
            "s": info["s"],
        }
        _checkpoint(extra)

    campaign_ref = None
    if campaign:
        for name, _, _ in GROUPS:
            if name not in statuses:
                statuses[name] = {"status": "skipped_budget", "s": 0.0,
                                  "filtered": True}
        rooflines = {}
        for name, _, _ in GROUPS:
            summ = extra.pop(f"roofline_{name}", None)
            if summ is not None:
                rooflines[name] = summ
        gate = _campaign_gate()
        manifest = {
            "round": _next_round_id("campaign"),
            "groups": statuses,
            "rounds": {k[len("BENCH_ROUND_"):].lower(): v
                       for k, v in round_env.items()},
            "rooflines": rooflines,
            "gate": gate,
            "downscaled": bool(force_cpu or extra.get("downscaled")),
        }
        if manifest["downscaled"]:
            manifest["downscale_reason"] = extra.get("downscale_reason",
                                                     _CPU_FALLBACK)
        # `backend` lives in a parent local (children's values are popped
        # out of their payloads), so hand the stamp a merged view
        _stamp_provenance(manifest, {**extra, "backend": backend},
                          "bench.py --campaign")
        path = os.path.join(BENCH_ARCHIVE_DIR,
                            f"CAMPAIGN_{manifest['round']}.json")
        try:
            os.makedirs(BENCH_ARCHIVE_DIR, exist_ok=True)
            with open(path, "w") as fh:
                json.dump(manifest, fh, indent=1)
                fh.write("\n")
        except Exception as e:
            extra["campaign_artifact_error"] = _short_err(e)
        campaign_ref = {"manifest": path, "round": manifest["round"],
                        "gate_rc": gate.get("rc")}
        extra["campaign"] = campaign_ref

    # --- headline ------------------------------------------------------------
    coupled = extra.get("coupled_solve", {})
    mixed = extra.get("coupled_solve_mixed", {})
    rate32 = (extra.get("stokeslet_f32") or {}).get("gpairs_per_s")
    if "wall_s" in mixed and mixed.get("shell_n") == 6000:
        # full reference tolerance (1e-10) at walkthrough scale: the honest
        # apples-to-apples headline
        line = {
            "metric": "coupled_solve_walkthrough_mixed_wall_s",
            "value": mixed["wall_s"],
            "unit": "s/solve",
            "vs_baseline": mixed["vs_ref"],
        }
    elif "wall_s" in coupled and coupled.get("shell_n") == 6000:
        line = {
            "metric": "coupled_solve_walkthrough_wall_s",
            "value": coupled["wall_s"],
            "unit": "s/solve",
            "vs_baseline": coupled["vs_ref"],
        }
    elif "wall_s" in mixed:
        line = {
            "metric": f"coupled_solve_shell{mixed.get('shell_n')}_mixed_wall_s",
            "value": mixed["wall_s"],
            "unit": "s/solve",
            "vs_baseline": mixed["vs_ref"],
        }
    elif rate32 is not None:
        baseline = extra.get("numpy_baseline_gpairs_per_s") or 0.0067
        line = {
            "metric": "stokeslet_mobility_matvec_throughput_f32",
            "value": rate32,
            "unit": "Gpairs/s/chip",
            "vs_baseline": round(rate32 / baseline, 2),
        }
    else:
        line = {"metric": "bench_failed", "value": 0.0, "unit": "",
                "vs_baseline": 0.0}
    # the headline inherits the downscale flag from the SECTION it quotes
    # (a mid-run TPU promotion must not launder a CPU-measured headline),
    # plus the run-level flag if the bench ended in CPU-fallback mode
    src = (mixed if line["metric"].endswith("mixed_wall_s")
           else coupled if "wall_s" in line["metric"]
           else extra.get("stokeslet_f32") or {})
    if force_cpu or src.get("downscaled"):
        line["downscaled"] = True
    line["total_s"] = round(time.monotonic() - _T_START, 1)
    line["backend"] = backend
    line["telemetry_version"] = TELEMETRY_VERSION
    if campaign_ref is not None:
        line["campaign"] = campaign_ref
    line["extra"] = extra
    _emit(line)


#: markers bracketing the generated headline table in docs/performance.md
HEADLINES_BEGIN = ("<!-- headlines:begin "
                   "(generated: python bench.py --render-headlines) -->")
HEADLINES_END = "<!-- headlines:end -->"


def _render_headlines(check: bool = False) -> int:
    """Regenerate the docs/performance.md headline table from the archived
    rounds (the `obs perf --json` latest view — one row per group per
    gated headline, provenance column included). ``--check`` exits 1 when
    the committed table is stale; 2 when the markers or the perf report
    are missing. Parent-side: jax-free by the same subprocess rule as the
    campaign gate."""
    here = os.path.dirname(os.path.abspath(__file__))
    doc_path = os.path.join(here, "docs", "performance.md")
    cmd = [sys.executable, "-m", "skellysim_tpu.obs", "perf", "--compare",
           BENCH_ARCHIVE_DIR, "--json"]
    try:
        p = subprocess.run(cmd, capture_output=True, text=True, timeout=180)
        report = json.loads(p.stdout)
    except Exception as e:
        sys.stderr.write("render-headlines: perf report failed: "
                         f"{_short_err(e)}\n")
        return 2
    rows = ["| group | round | headline metric | value | provenance |",
            "|---|---|---|---|---|"]
    for group in sorted(report.get("groups", {})):
        latest = (report["groups"][group] or {}).get("latest") or {}
        prov = latest.get("backend") or "?"
        if latest.get("downscaled"):
            prov += " (downscaled)"
        rnd = latest.get("round") or "?"
        heads = latest.get("headlines") or {}
        # unmeasured metrics (budget-starved rungs archive as null) are
        # omitted, not rendered as "None" — absence is visible in the JSON
        measured = {m: v for m, v in heads.items() if v is not None}
        if not measured:
            rows.append(f"| {group} | {rnd} | — | — | {prov} |")
        for metric in sorted(measured):
            v = measured[metric]
            val = f"{v:g}" if isinstance(v, (int, float)) else str(v)
            rows.append(f"| {group} | {rnd} | {metric} | {val} | {prov} |")
    block = "\n".join([HEADLINES_BEGIN, *rows, HEADLINES_END])
    try:
        with open(doc_path) as fh:
            text = fh.read()
        i = text.index(HEADLINES_BEGIN)
        j = text.index(HEADLINES_END) + len(HEADLINES_END)
    except (OSError, ValueError):
        sys.stderr.write(f"render-headlines: markers missing in {doc_path}\n")
        return 2
    updated = text[:i] + block + text[j:]
    if updated == text:
        return 0
    if check:
        sys.stderr.write("render-headlines: docs/performance.md headline "
                         "table is stale — run "
                         "`python bench.py --render-headlines`\n")
        return 1
    with open(doc_path, "w") as fh:
        fh.write(updated)
    return 0


def main():
    _parent_main()


if __name__ == "__main__":
    import argparse

    ap = argparse.ArgumentParser()
    ap.add_argument("--group", default=None)
    ap.add_argument("--out", default=None)
    ap.add_argument("--campaign", action="store_true",
                    help="run every group, profile the roofline groups, "
                         "auto-bump archive rounds, write one "
                         "CAMPAIGN_rNN.json manifest, arm the perf gate")
    ap.add_argument("--campaign-groups", default=None,
                    help="comma-separated subset of groups for --campaign "
                         "(CI smoke)")
    ap.add_argument("--render-headlines", action="store_true",
                    help="regenerate the docs/performance.md headline "
                         "table from the archived rounds")
    ap.add_argument("--check", action="store_true",
                    help="with --render-headlines: exit 1 if the table is "
                         "stale instead of rewriting it")
    args = ap.parse_args()
    if args.render_headlines:
        sys.exit(_render_headlines(check=args.check))
    _steal_stdout()
    if args.group:
        # child: no stdout contract — results go to --out
        try:
            _child_main(args.group, args.out)
        except Exception as e:
            sys.stderr.write(f"bench child {args.group} failed: "
                             f"{_short_err(e)}\n")
            sys.exit(1)
        sys.exit(0)
    try:
        groups_filter = ([s.strip() for s in args.campaign_groups.split(",")
                          if s.strip()]
                         if args.campaign_groups else None)
        _parent_main(campaign=args.campaign, groups_filter=groups_filter)
    except Exception as e:  # absolute backstop: the driver must see valid JSON
        _emit({"metric": "bench_failed", "value": 0.0, "unit": "",
               "vs_baseline": 0.0, "error": _short_err(e),
               "telemetry_version": TELEMETRY_VERSION})
        sys.exit(0)
