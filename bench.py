"""Benchmark: mobility-matvec throughput (source-target pairs/sec/chip).

Per BASELINE.md, the reference publishes no numbers, so the baseline is
self-measured: the reference's ground-truth backend is the single-threaded
direct CPU kernel (`tests/core/kernel_test.cpp` uses it as the oracle;
`performance_hydrodynamics_combined.cpp` times it). We measure the same
quantity here: pairwise Stokeslet evaluations per second, on the default
device (TPU under axon; CPU in dev runs), at the 10k-fiber scale's kernel
shape (N = 65536 sources == targets, f32), against a single-core NumPy
direct evaluation measured on this host and extrapolated per-pair.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.
"""

from __future__ import annotations

import json
import time

import numpy as np


def _numpy_pairs_per_s(n=1024, trials=3):
    """Single-core direct CPU evaluation rate (the reference oracle backend)."""
    rng = np.random.default_rng(0)
    r = rng.uniform(-1, 1, size=(n, 3))
    f = rng.standard_normal((n, 3))

    def direct(r_src, r_trg, f_src):
        d = r_trg[:, None, :] - r_src[None, :, :]
        r2 = np.sum(d * d, axis=-1)
        np.fill_diagonal(r2, np.inf)
        rinv = 1.0 / np.sqrt(r2)
        df = np.einsum("tsk,sk->ts", d, f_src)
        u = np.einsum("ts,sk->tk", rinv, f_src) + np.einsum("ts,tsk->tk", df * rinv**3, d)
        return u / (8 * np.pi)

    direct(r, r, f)  # warm caches
    t0 = time.perf_counter()
    for _ in range(trials):
        direct(r, r, f)
    dt = (time.perf_counter() - t0) / trials
    return n * n / dt


def main():
    import jax
    import jax.numpy as jnp

    from skellysim_tpu.ops import kernels

    # full 10k-fiber kernel shape on an accelerator; small smoke size on CPU
    n = 65536 if jax.default_backend() != "cpu" else 8192
    rng = np.random.default_rng(1)
    r = jnp.asarray(rng.uniform(-5, 5, size=(n, 3)), dtype=jnp.float32)
    f = jnp.asarray(rng.standard_normal((n, 3)), dtype=jnp.float32)

    u = kernels.stokeslet_direct(r, r, f, 1.0)
    u.block_until_ready()  # compile + warm
    trials = 3

    def rate(fn):
        fn().block_until_ready()  # compile + warm
        t0 = time.perf_counter()
        for _ in range(trials):
            out = fn()
        out.block_until_ready()
        return n * n * trials / (time.perf_counter() - t0)

    pairs_per_s = rate(lambda: kernels.stokeslet_direct(r, r, f, 1.0))
    backend = "xla"
    if jax.default_backend() == "tpu":
        # the fused Pallas tiles usually beat the blocked XLA kernel on-chip;
        # report whichever wins so the headline tracks the best path
        from skellysim_tpu.ops.pallas_kernels import stokeslet_pallas

        try:
            pallas_rate = rate(lambda: stokeslet_pallas(r, r, f, 1.0))
            if pallas_rate > pairs_per_s:
                pairs_per_s, backend = pallas_rate, "pallas"
        except Exception as e:
            print(f"# pallas path failed ({e}); keeping xla", flush=True)

    baseline = _numpy_pairs_per_s()
    print(json.dumps({
        "metric": f"stokeslet_mobility_matvec_throughput_n{n}_{backend}",
        "value": round(pairs_per_s / 1e9, 4),
        "unit": "Gpairs/s/chip",
        "vs_baseline": round(pairs_per_s / baseline, 2),
    }))


if __name__ == "__main__":
    main()
