"""System orchestrator: state pytree, coupled matvec, solve, adaptive time loop.

TPU-native replacement for the reference `System` namespace
(`/root/reference/src/core/system.cpp`): instead of namespace-level singletons
mutated in place, the whole simulation is one immutable `SimState` pytree and the
per-step work (`prep_state_for_solver` -> GMRES -> component steps) is a jit'd
pure function. Backup/restore for rejected adaptive steps
(`system.cpp:495-513`) is free: keep the previous pytree.

The solution vector layout matches the reference (`system.cpp:75-96`):
[fibers (4n per fiber) | shell (3 per node) | bodies (3 per node + 6 per body)].
"""

from __future__ import annotations

import json
import logging
import time as _time
from typing import NamedTuple, Optional

import jax
import jax.numpy as jnp

logger = logging.getLogger("skellysim_tpu")

from ..bodies import bodies as bd
from ..fibers import container as fc
from ..params import Params
from ..periphery import periphery as peri
from ..periphery.periphery import PeripheryShape, PeripheryState
from ..solver import gmres, gmres_ir
from .sources import BackgroundFlow, PointSources


def _cast_floats(tree, dtype):
    """Cast every floating leaf of a pytree to ``dtype`` (ints/bools pass)."""
    return jax.tree_util.tree_map(
        lambda a: a.astype(dtype)
        if jnp.issubdtype(jnp.asarray(a).dtype, jnp.floating) else a, tree)


class SimState(NamedTuple):
    """Complete simulation state (a pytree)."""

    time: jnp.ndarray
    dt: jnp.ndarray
    fibers: Optional[fc.FiberGroup]
    points: Optional[PointSources]
    background: Optional[BackgroundFlow]
    shell: Optional[PeripheryState] = None
    bodies: Optional[bd.BodyGroup] = None


class StepInfo(NamedTuple):
    converged: jnp.ndarray
    iters: jnp.ndarray
    residual: jnp.ndarray       # implicit (Givens) relative residual
    fiber_error: jnp.ndarray
    #: explicit ||b - A x|| / ||b|| from one post-solve matvec
    #: (`solver_hydro.cpp:81-92`); nan until populated by a solve
    residual_true: jnp.ndarray = jnp.nan
    #: converged by the implicit residual but the explicit one disagrees by
    #: >10x tol — Belos' loss-of-accuracy analogue (`solver_hydro.cpp:85-92`)
    loss_of_accuracy: jnp.ndarray = False


def solution_from_state(state: SimState):
    """Rebuild the flat solver solution vector from component state.

    Inverse of the post-solve advance: fibers contribute [x|y|z|tension] per
    fiber, the shell its density, bodies their stored solution — matching the
    reference's reconstruction on resume (`trajectory_reader.cpp:227-249`).
    """
    parts = []
    if state.fibers is not None:
        f = state.fibers
        parts.append(jnp.concatenate(
            [f.x[:, :, 0], f.x[:, :, 1], f.x[:, :, 2], f.tension],
            axis=1).reshape(-1))
    if state.shell is not None:
        parts.append(state.shell.density)
    if state.bodies is not None:
        parts.append(state.bodies.solution.reshape(-1))
    if not parts:
        raise ValueError("state has no implicit components")
    return jnp.concatenate(parts)


class System:
    """Holds static config; all dynamics flow through pure jit'd functions."""

    def __init__(self, params: Params, shell_shape: PeripheryShape | None = None,
                 mesh=None):
        if params.pair_evaluator not in ("direct", "ring", "ewald"):
            raise ValueError(
                f"unknown pair_evaluator {params.pair_evaluator!r}; "
                "runtime values are 'direct', 'ring', or 'ewald'")
        if params.solver_precision not in ("full", "mixed"):
            raise ValueError(
                f"unknown solver_precision {params.solver_precision!r}; "
                "use 'full' or 'mixed'")
        self.params = params
        self.shell_shape = shell_shape
        # device mesh for the ring pair evaluator (params.pair_evaluator="ring");
        # GSPMD sharding via parallel.shard_state needs no mesh here
        self.mesh = mesh
        if params.refine_pair_impl not in ("auto", "exact", "df"):
            raise ValueError(
                f"unknown refine_pair_impl {params.refine_pair_impl!r}; "
                "use 'auto', 'exact', or 'df'")
        self._solve_jit = jax.jit(self._solve_impl,
                                  static_argnames=("ewald_plan",))
        self._collision_jit = jax.jit(self._check_collision)
        self._vel_jit = jax.jit(self._velocity_at_targets_impl,
                                static_argnames=("ewald_plan",))

    @property
    def _refine_impl(self) -> str:
        """Pairwise tile for mixed-mode f64 residual/prep flows (see
        Params.refine_pair_impl). Resolved lazily from self.params — the
        codebase's pattern of replacing params post-construction
        (`system.params = dataclasses.replace(...)`) must not pin a stale
        tile."""
        impl = self.params.refine_pair_impl
        if impl == "auto":
            return "df" if jax.default_backend() != "cpu" else "exact"
        return impl

    def _ring_active(self) -> bool:
        ring = self.params.pair_evaluator == "ring"
        if ring and self.mesh is None:
            # trace-time (not per-step) diagnostic: silent degradation would
            # surprise a user expecting O(N/D) per-chip memory
            import warnings

            warnings.warn("pair_evaluator='ring' falls back to 'direct': "
                          "no mesh was configured")
            return False
        return ring

    def _ring_pad_targets(self, r_trg):
        """Pad the target rows to a mesh-size multiple (shard_map needs even
        blocks). Pad points sit at 1e6 — far from any geometry, never
        coincident with the 1e7 source pads — and their rows are sliced off."""
        T = r_trg.shape[0]
        pad = (-T) % self.mesh.size
        if pad:
            far = jnp.full((pad, 3), 1e6, dtype=r_trg.dtype)
            r_trg = jnp.concatenate([r_trg, far], axis=0)
        return r_trg, T

    def _fiber_flow(self, state: SimState, caches, r_trg, forces,
                    subtract_self: bool = True, impl: str | None = None,
                    ewald_plan=None, ewald_anchors=None):
        """Fiber-source flow through the selected pair evaluator
        (the reference's `params.pair_evaluator` seam,
        `fiber_container_base.cpp:20-33`). The ring path pads the target rows
        to a mesh multiple and rotates fiber-node source blocks around the ICI
        ring; shell/body target rows ride along in the padded target set.
        ``impl`` overrides `params.kernel_impl` (the mixed solver's f64
        residual passes "df"); the ring evaluator has no DF tile, so ring
        runs fall back to its exact (native-dtype) tile."""
        if impl is None:
            impl = self.params.kernel_impl
        if ewald_plan is not None and impl != "df":
            # the O(N log N) evaluator serves the fast tiers; "df" flows (the
            # mixed solver's f64 residual/prep) stay dense — the Ewald
            # tolerance must not cap the refined residual
            return fc.flow(state.fibers, caches, r_trg, forces,
                           self.params.eta, subtract_self=subtract_self,
                           evaluator="ewald", ewald_plan=ewald_plan,
                           ewald_anchors=ewald_anchors)
        if not self._ring_active():
            return fc.flow(state.fibers, caches, r_trg, forces, self.params.eta,
                           subtract_self=subtract_self, evaluator="direct",
                           impl=impl)
        nfn = state.fibers.n_fibers * state.fibers.n_nodes
        if nfn % self.mesh.size != 0:
            raise ValueError(
                f"pair_evaluator='ring' requires n_fibers*n_nodes ({nfn}) to be "
                f"divisible by the mesh size ({self.mesh.size}); round the "
                f"fiber batch up to a multiple of {self.mesh.size} fibers "
                "(inactive padding fibers are free)")
        r_pad, T = self._ring_pad_targets(r_trg)
        vel = fc.flow(state.fibers, caches, r_pad, forces, self.params.eta,
                      subtract_self=subtract_self, evaluator="ring",
                      mesh=self.mesh,
                      impl="exact" if impl == "df" else impl)
        return vel[:T]

    def _shell_flow(self, state: SimState, r_trg, density,
                    impl: str | None = None):
        """Shell -> target flow through the pair-evaluator seam
        (`include/kernels.hpp:78-122`: one evaluator serves all components).
        The density->f_dl math and source padding live in `peri.flow`; only
        the target padding is System's job."""
        if impl is None:
            impl = self.params.kernel_impl
        if not self._ring_active():
            return peri.flow(state.shell, r_trg, density, self.params.eta,
                             impl=impl)
        r_pad, T = self._ring_pad_targets(r_trg)
        return peri.flow(state.shell, r_pad, density, self.params.eta,
                         evaluator="ring", mesh=self.mesh,
                         impl="exact" if impl == "df" else impl)[:T]

    # ------------------------------------------------------------- state setup

    def make_state(self, fibers=None, points=None, background=None,
                   shell=None, bodies=None) -> SimState:
        if fibers is None and shell is None and bodies is None:
            raise ValueError(
                "state needs at least one implicit component (fibers, shell, or "
                "bodies) to solve; point/background sources only contribute flow")
        if shell is not None and self.shell_shape is None:
            raise ValueError(
                "a periphery state requires System(shell_shape=PeripheryShape(...)) "
                "matching the precompute geometry; use kind='generic' explicitly "
                "for a shell with no wall physics")
        if shell is not None and background is not None and background.is_active():
            # `sanity_check`, system.cpp:625-626
            raise ValueError("background sources are incompatible with peripheries")
        if fibers is not None:
            dtype = fibers.x.dtype
        elif shell is not None:
            dtype = shell.density.dtype
        elif bodies is not None:
            dtype = bodies.solution.dtype
        else:
            dtype = jnp.float64
        return SimState(
            time=jnp.asarray(0.0, dtype=dtype),
            dt=jnp.asarray(self.params.dt_initial, dtype=dtype),
            fibers=fibers, points=points, background=background,
            shell=shell, bodies=bodies)

    # ----------------------------------------------------------------- helpers

    def _node_positions(self, state: SimState, body_caches=None):
        """All hydrodynamic node positions [fibers | shell | bodies]
        (`get_node_maps`).

        Pass ``body_caches`` when available so body node targets reuse the
        exact cached lab-frame positions the kernel sources use: recomputing
        `place()` in a different precision shifts "self" pairs off exact
        coincidence (distance ~1 ulp instead of 0), un-masking the kernel
        singularity.
        """
        parts = []
        if state.fibers is not None:
            parts.append(fc.node_positions(state.fibers))
        if state.shell is not None:
            parts.append(state.shell.nodes)
        if state.bodies is not None:
            nodes = (body_caches.nodes if body_caches is not None
                     else bd.place(state.bodies)[0])
            parts.append(nodes.reshape(-1, 3))
        if not parts:
            return jnp.zeros((0, 3), dtype=jnp.float64)
        return jnp.concatenate(parts, axis=0)

    def _counts(self, state: SimState):
        nf_nodes = (state.fibers.n_fibers * state.fibers.n_nodes
                    if state.fibers is not None else 0)
        ns_nodes = state.shell.n_nodes if state.shell is not None else 0
        nb_nodes = (state.bodies.n_bodies * state.bodies.n_nodes
                    if state.bodies is not None else 0)
        return nf_nodes, ns_nodes, nb_nodes

    def _sizes(self, state: SimState):
        fib = fc.solution_size(state.fibers) if state.fibers is not None else 0
        shell = state.shell.solution_size if state.shell is not None else 0
        body = state.bodies.solution_size if state.bodies is not None else 0
        return fib, shell, body

    def _external_flows(self, state: SimState, r_trg):
        """Point-source + background contributions (`system.cpp:445-446`)."""
        v = jnp.zeros_like(r_trg)
        if state.points is not None:
            v = v + state.points.flow(r_trg, self.params.eta, state.time)
        if state.background is not None:
            v = v + state.background.flow(r_trg, self.params.eta)
        return v

    # ------------------------------------------------- fiber-periphery coupling

    def _periphery_force_fibers(self, state: SimState):
        """Steric wall force on fiber nodes [nf, n, 3] (`periphery_force`).

        Applied unconditionally during the solve, like the reference's
        `prep_state_for_solver` (`system.cpp:422`); the
        periphery_interaction_flag only gates post-processing
        (`velocity_at_targets`, `system.cpp:340-341`).
        """
        fibers = state.fibers
        fp = self.params.fiber_periphery_interaction
        if state.shell is None:
            return jnp.zeros_like(fibers.x)
        shape = self.shell_shape
        return jax.vmap(
            lambda x, mc: peri.fiber_steric_force(shape, x, fp.f_0, fp.l_0, mc)
        )(fibers.x, fibers.minus_clamped)

    def _update_plus_pinning(self, state: SimState) -> SimState:
        """Hinge plus ends near an attachment-active periphery
        (`update_boundary_conditions`, `fiber_finite_difference.cpp:74-91`)."""
        pb = self.params.periphery_binding
        fibers = state.fibers
        if state.shell is None or not pb.active or fibers is None:
            return state
        shape = self.shell_shape

        def one(x):
            tip = x[-1] / jnp.linalg.norm(x[-1])
            angle = jnp.arccos(jnp.clip(tip[2], -1.0, 1.0))
            in_window = (angle >= pb.polar_angle_start) & (angle <= pb.polar_angle_end)
            near = peri.check_collision(shape, x, pb.threshold)
            return in_window & near

        pinned = jax.vmap(one)(fibers.x)
        return state._replace(fibers=fibers._replace(plus_pinned=pinned))

    # ------------------------------------------------------------------- prep

    def _prep(self, state: SimState, ewald_plan=None,
              ewald_anchors=None):
        """All velocities/forces/RHS/BC assembly (`prep_state_for_solver`,
        `system.cpp:398-458`). Returns (state, fiber caches, body caches,
        shell RHS, body RHS)."""
        p = self.params
        state = self._update_plus_pinning(state)
        fibers = state.fibers
        caches = None
        body_caches = None
        shell_rhs = None
        body_rhs = None

        r_all = self._node_positions(state)
        nf_nodes, ns_nodes, nb_nodes = self._counts(state)
        v_all = jnp.zeros_like(r_all)

        precond_dtype = (jnp.float32 if p.solver_precision == "mixed" else None)
        # mixed mode evaluates the (f64) prep flows through the refinement
        # tile — on accelerators that is double-float f32 (~1e-14, sets the
        # RHS accuracy floor) instead of the emulated-f64 cliff
        impl_flow = (self._refine_impl
                     if p.solver_precision == "mixed"
                     and state.time.dtype == jnp.float64 else p.kernel_impl)

        if fibers is not None:
            caches = fc.update_cache(fibers, state.dt, p.eta)
            nf, n = fibers.n_fibers, fibers.n_nodes

            external = self._periphery_force_fibers(state)
            motor = jnp.where(state.time >= p.implicit_motor_activation_delay,
                              fc.generate_constant_force(fibers, caches),
                              jnp.zeros_like(fibers.x))

            v_all = v_all + self._fiber_flow(state, caches, r_all, external,
                                             impl=impl_flow,
                                             ewald_plan=ewald_plan,
                                             ewald_anchors=ewald_anchors)

        if state.bodies is not None:
            body_caches = bd.update_cache(state.bodies, p.eta,
                                          precond_dtype=precond_dtype)
            # external body forces/torques induce explicit flow everywhere
            # (`system.cpp:430-443`)
            ext_ft = bd.external_forces_torques(state.bodies, state.time)
            v_all = v_all + bd.flow(state.bodies, body_caches, r_all, None,
                                    ext_ft, p.eta, impl=impl_flow)

        v_all = v_all + self._external_flows(state, r_all)

        if state.bodies is not None:
            v_bodies = v_all[nf_nodes + ns_nodes:].reshape(
                state.bodies.n_bodies, state.bodies.n_nodes, 3)
            body_rhs = bd.update_RHS(state.bodies, v_bodies)

        if fibers is not None:
            v_fib = v_all[:nf_nodes].reshape(nf, n, 3)
            caches = fc.update_rhs_and_bc(fibers, caches, state.dt, p.eta,
                                          v_fib, motor + external, external,
                                          precond_dtype=precond_dtype)
        if state.shell is not None:
            v_shell = v_all[nf_nodes:nf_nodes + ns_nodes]
            shell_rhs = peri.update_RHS(v_shell)

        return state, caches, body_caches, shell_rhs, body_rhs

    # ------------------------------------------------------- operator closures

    def _apply_matvec(self, state: SimState, caches, body_caches, x_flat,
                      lo=None, flow_impl: str | None = None, ewald_plan=None,
                      ewald_anchors=None):
        """Coupled operator A x (`apply_matvec`, `system.cpp:269-324`).

        ``lo`` is an optional (state, caches, body_caches) triple whose float
        leaves are a lower precision (f32). When given, the O(N^2) pairwise
        flows and the well-scaled shell/body dense ops — i.e. all the flops —
        are evaluated through it, while the stiff fiber-local ops (A_bc rows
        reach ~1e7, so f32 entry rounding injects O(1) absolute noise) and the
        fiber-body link conditions stay in the ``x_flat`` dtype. This is the
        cheap operator `gmres_ir` iterates with; exactness is restored by the
        f64 refinement residuals.

        ``flow_impl`` overrides the pairwise tile for the flows (the mixed
        solver's f64 residual matvec passes the double-float tile).
        """
        p = self.params
        if flow_impl is None:
            flow_impl = p.kernel_impl
        fibers = state.fibers
        shell = state.shell
        bodies = state.bodies
        fib_size, shell_size, body_size = self._sizes(state)
        nf_nodes, ns_nodes, nb_nodes = self._counts(state)
        x_shell = x_flat[fib_size:fib_size + shell_size]

        f_state, f_caches, f_bcaches = (state, caches, body_caches) if lo is None else lo
        hi_dtype = x_flat.dtype
        # without a lo seam every cast below is a no-op (lo_dtype == x dtype);
        # deriving it from state.time would silently up-cast f32 fiberless
        # states whose time scalar defaulted to f64
        lo_dtype = hi_dtype if lo is None else lo[0].time.dtype

        r_all = self._node_positions(f_state, f_bcaches)
        v_all = jnp.zeros_like(r_all)

        x_fib = None
        if fibers is not None:
            nf, n = fibers.n_fibers, fibers.n_nodes
            x_fib = x_flat[:fib_size].reshape(nf, 4 * n)
            fw = fc.apply_fiber_force(fibers, caches, x_fib)
            v_all = v_all + self._fiber_flow(f_state, f_caches, r_all,
                                             fw.astype(lo_dtype),
                                             subtract_self=True,
                                             impl=flow_impl,
                                             ewald_plan=ewald_plan,
                                             ewald_anchors=ewald_anchors)

        if shell is not None and (fibers is not None or bodies is not None):
            # shell flow is evaluated at fiber and body nodes only; the shell
            # self-interaction lives in the dense operator (`system.cpp:301-315`)
            r_fibbody = jnp.concatenate(
                [r_all[:nf_nodes], r_all[nf_nodes + ns_nodes:]], axis=0)
            v_shell2fibbody = self._shell_flow(f_state, r_fibbody,
                                               x_shell.astype(lo_dtype),
                                               impl=flow_impl)
            v_all = v_all.at[:nf_nodes].add(v_shell2fibbody[:nf_nodes])
            v_all = v_all.at[nf_nodes + ns_nodes:].add(v_shell2fibbody[nf_nodes:])

        v_boundary = None
        x_bodies = None
        if bodies is not None:
            nb, n_b = bodies.n_bodies, bodies.n_nodes
            x_bodies = x_flat[fib_size + shell_size:].reshape(nb, 3 * n_b + 6)
            if fibers is not None:
                v_boundary, body_ft = bd.link_conditions(
                    bodies, body_caches, fibers, caches, x_fib, x_bodies)
            else:
                body_ft = jnp.zeros((nb, 6), dtype=hi_dtype)
            v_all = v_all + bd.flow(f_state.bodies, f_bcaches, r_all,
                                    x_bodies.astype(lo_dtype),
                                    body_ft.astype(lo_dtype), p.eta,
                                    impl=flow_impl)

        res = []
        if fibers is not None:
            v_fib = v_all[:nf_nodes].reshape(nf, n, 3).astype(hi_dtype)
            if v_boundary is None:
                v_boundary = jnp.zeros((nf, 7), dtype=hi_dtype)
            res.append(fc.matvec(fibers, caches, x_fib, v_fib, v_boundary).reshape(-1))
        if shell is not None:
            v_shell = v_all[nf_nodes:nf_nodes + ns_nodes]
            res.append(peri.matvec(f_state.shell, x_shell.astype(lo_dtype),
                                   v_shell).astype(hi_dtype))
        if bodies is not None:
            v_bodies = v_all[nf_nodes + ns_nodes:].reshape(nb, n_b, 3)
            res.append(bd.matvec(f_state.bodies, f_bcaches,
                                 x_bodies.astype(lo_dtype),
                                 v_bodies).astype(hi_dtype).reshape(-1))
        return jnp.concatenate(res)

    def _apply_precond(self, state: SimState, caches, body_caches, x_flat):
        """Block preconditioner P^-1 x (`apply_preconditioner`, `system.cpp:248-262`)."""
        fibers = state.fibers
        fib_size, shell_size, body_size = self._sizes(state)
        res = []
        if fibers is not None:
            nf, n = fibers.n_fibers, fibers.n_nodes
            x_fib = x_flat[:fib_size].reshape(nf, 4 * n)
            res.append(fc.apply_preconditioner(fibers, caches, x_fib).reshape(-1))
        if state.shell is not None:
            res.append(peri.apply_preconditioner(
                state.shell, x_flat[fib_size:fib_size + shell_size]))
        if state.bodies is not None:
            nb = state.bodies.n_bodies
            x_bod = x_flat[fib_size + shell_size:].reshape(nb, -1)
            res.append(bd.apply_preconditioner(
                state.bodies, body_caches, x_bod).reshape(-1))
        return jnp.concatenate(res)

    # ------------------------------------------------------------------- solve

    def _solve_impl(self, state: SimState, ewald_plan=None,
                    ewald_anchors=None):
        p = self.params
        state, caches, body_caches, shell_rhs, body_rhs = self._prep(
            state, ewald_plan=ewald_plan, ewald_anchors=ewald_anchors)

        rhs_parts = []
        if caches is not None:
            rhs_parts.append(caches.RHS.reshape(-1))
        if shell_rhs is not None:
            rhs_parts.append(shell_rhs)
        if body_rhs is not None:
            rhs_parts.append(body_rhs.reshape(-1))
        if not rhs_parts:
            raise ValueError("state has no implicit components to solve")
        rhs = jnp.concatenate(rhs_parts)

        if p.solver_precision == "mixed":
            # f64 state/assembly/refinement residuals; the Krylov loop's
            # expensive interior (kernel flows, shell/body dense ops, LU
            # preconditioners) evaluates through f32 copies via the lo seam
            # of _apply_matvec, while stiff fiber-local ops stay f64
            lo = _cast_floats((state, caches, body_caches), jnp.float32)
            # hi residual flows go through the refinement tile (df on
            # accelerators); state must be f64 for the df split to pay off
            hi_impl = (self._refine_impl
                       if state.time.dtype == jnp.float64 else p.kernel_impl)
            result = gmres_ir(
                lambda v: self._apply_matvec(state, caches, body_caches, v,
                                             flow_impl=hi_impl,
                                             ewald_plan=ewald_plan,
                                             ewald_anchors=ewald_anchors),
                lambda v: self._apply_matvec(state, caches, body_caches, v,
                                             lo=lo, ewald_plan=ewald_plan,
                                             ewald_anchors=ewald_anchors),
                rhs,
                precond_lo=lambda v: self._apply_precond(lo[0], lo[1], lo[2], v),
                tol=p.gmres_tol, inner_tol=p.inner_tol,
                restart=p.gmres_restart, maxiter=p.gmres_maxiter,
                max_refine=p.max_refine)
        else:
            result = gmres(
                lambda v: self._apply_matvec(state, caches, body_caches, v,
                                             ewald_plan=ewald_plan,
                                             ewald_anchors=ewald_anchors),
                rhs,
                precond=lambda v: self._apply_precond(state, caches, body_caches, v),
                tol=p.gmres_tol, restart=p.gmres_restart, maxiter=p.gmres_maxiter)

        fib_size, shell_size, body_size = self._sizes(state)
        new_state = state
        fiber_error = jnp.asarray(0.0, dtype=rhs.dtype)
        if state.fibers is not None:
            sol_fib = result.x[:fib_size].reshape(state.fibers.n_fibers, -1)
            new_fibers = fc.step(state.fibers, sol_fib)
            new_state = new_state._replace(fibers=new_fibers)
        if state.shell is not None:
            new_state = new_state._replace(shell=state.shell._replace(
                density=result.x[fib_size:fib_size + shell_size]))
        if state.bodies is not None:
            sol_bod = result.x[fib_size + shell_size:].reshape(
                state.bodies.n_bodies, -1)
            new_bodies = bd.step(state.bodies, sol_bod, state.dt)
            new_state = new_state._replace(bodies=new_bodies)
            if new_state.fibers is not None:
                # fibers re-pin to their (moved) nucleation sites
                # (`system.cpp:488`, `repin_to_bodies`)
                _, _, new_sites = bd.place(new_bodies)
                new_state = new_state._replace(fibers=bd.repin_to_bodies(
                    new_state.fibers, new_sites, new_bodies))
        if new_state.fibers is not None:
            fiber_error = fc.fiber_error(new_state.fibers)

        info = StepInfo(converged=result.converged, iters=result.iters,
                        residual=result.residual, fiber_error=fiber_error,
                        residual_true=result.residual_true,
                        loss_of_accuracy=(result.converged
                                          & (result.residual_true
                                             > 10.0 * p.gmres_tol)))
        return new_state, result.x, info

    # -------------------------------------------------------- velocity field

    def _velocity_at_targets_impl(self, state: SimState, solution, r_trg,
                                  ewald_plan=None, ewald_anchors=None):
        """Velocity field at arbitrary targets from a solved state
        (`velocity_at_targets`, `system.cpp:330-384`).

        Sums fiber flow (forces from the solution, plus steric wall forces when
        `periphery_interaction_flag` is set), body flow driven by fiber link
        conditions, shell flow from the solved density, and point/background
        sources; points inside a rigid body are overridden with the body's
        rigid motion v + omega x dx.
        """
        p = self.params
        fibers, shell, bodies = state.fibers, state.shell, state.bodies
        fib_size, shell_size, body_size = self._sizes(state)
        r_trg = jnp.asarray(r_trg, dtype=solution.dtype).reshape(-1, 3)
        v = jnp.zeros_like(r_trg)

        caches = (fc.update_cache(fibers, state.dt, p.eta)
                  if fibers is not None else None)
        body_caches = (bd.update_cache(bodies, p.eta)
                       if bodies is not None else None)

        x_fib = None
        if fibers is not None:
            nf, n = fibers.n_fibers, fibers.n_nodes
            x_fib = solution[:fib_size].reshape(nf, 4 * n)
            f_on_fibers = fc.apply_fiber_force(fibers, caches, x_fib)
            if p.periphery_interaction_flag and shell is not None:
                f_on_fibers = f_on_fibers + self._periphery_force_fibers(state)
            # through the pair-evaluator seam so listener-mode evaluator
            # switches genuinely change the computation (ewald engages when
            # the caller supplies a plan — velocity_at_targets does;
            # streamline integrators stay dense by design)
            v = v + self._fiber_flow(state, caches, r_trg, f_on_fibers,
                                     subtract_self=False,
                                     ewald_plan=ewald_plan,
                                     ewald_anchors=ewald_anchors)

        if bodies is not None:
            nb = bodies.n_bodies
            x_bodies = solution[fib_size + shell_size:].reshape(nb, -1)
            if fibers is not None:
                # like the reference, only the fiber link forces (not the
                # external force schedule) drive the body flow here
                _, body_ft = bd.link_conditions(
                    bodies, body_caches, fibers, caches, x_fib, x_bodies)
            else:
                body_ft = jnp.zeros((nb, 6), dtype=solution.dtype)
            v = v + bd.flow(bodies, body_caches, r_trg, x_bodies, body_ft,
                            p.eta, impl=p.kernel_impl)

        if shell is not None:
            v = v + self._shell_flow(state, r_trg,
                                     solution[fib_size:fib_size + shell_size])

        v = v + self._external_flows(state, r_trg)

        if bodies is not None:
            # rigid-motion override inside bodies (`system.cpp:364-381`);
            # spherical containment only applies to sphere-kind bodies —
            # other kinds keep the computed exterior flow until they get a
            # proper containment test
            vel6 = x_bodies[:, -6:]
            dx = r_trg[:, None, :] - bodies.position[None, :, :]
            inside = ((jnp.linalg.norm(dx, axis=-1) < bodies.radius[None, :])
                      & bodies.kind_sphere[None, :])
            u_rigid = vel6[None, :, :3] + jnp.cross(
                jnp.broadcast_to(vel6[None, :, 3:], dx.shape), dx)
            idx = jnp.argmax(inside, axis=1)
            v = jnp.where(inside.any(axis=1)[:, None],
                          u_rigid[jnp.arange(r_trg.shape[0]), idx], v)
        return v

    def velocity_at_targets(self, state: SimState, solution, r_trg):
        """Jitted velocity field evaluation at [n, 3] targets; the ewald
        evaluator (when configured) plans over nodes + targets so off-node
        probes stay inside the cell region."""
        plan, anchors = self._ewald_args(state, extra_targets=r_trg)
        return self._vel_jit(state, solution, r_trg, ewald_plan=plan,
                             ewald_anchors=anchors)

    def _check_collision(self, state: SimState):
        """Fiber/shell + body collision gate (`check_collision`, `system.cpp:576-595`)."""
        collided = jnp.asarray(False)
        if state.bodies is not None:
            collided = collided | bd.check_collision_pairwise(state.bodies, 0.0)
            if state.shell is not None and self.shell_shape.kind == "sphere":
                collided = collided | bd.check_collision_shell(
                    state.bodies, self.shell_shape.radius, 0.0)
        if state.shell is None or state.fibers is None:
            return collided
        shape = self.shell_shape

        def one(x, mc):
            # clamped fibers exclude their anchored first node
            pts = jnp.where((jnp.arange(x.shape[0]) >= jnp.where(mc, 1, 0))[:, None],
                            x, x[-1])
            return peri.check_collision(shape, pts, 0.0)

        return collided | jnp.any(
            jax.vmap(one)(state.fibers.x, state.fibers.minus_clamped))

    # -------------------------------------------------------------- public API

    def make_ewald_plan(self, state: SimState, extra_targets=None):
        """Host-side Ewald plan over every ACTIVE hydrodynamic node — the
        analogue of the reference's per-step FMM tree rebuild
        (`kernels.hpp:78-122`). Quantized planning (`ops.ewald.plan_ewald`)
        keeps the plan — and so the compiled solve — stable while the
        geometry drifts. Inactive fiber slots (dynamic-instability padding,
        which replicate slot 0's coordinates) are excluded from the bounding
        box and reserved as spread `n_fill` capacity instead — clustered
        padding would otherwise blow up the per-cell bucket size.
        ``extra_targets`` extends the box to off-node evaluation points
        (velocity fields)."""
        from ..ops.ewald import plan_ewald

        import numpy as _np

        n_fill = 0
        n_src = 0
        parts = []
        if state.fibers is not None:
            act = _np.asarray(state.fibers.active)
            x = _np.asarray(state.fibers.x)
            parts.append(x[act].reshape(-1, 3))
            n_fill = int((~act).sum()) * state.fibers.n_nodes
            n_src = parts[0].shape[0]
        if state.shell is not None:
            parts.append(_np.asarray(state.shell.nodes))
        if state.bodies is not None:
            parts.append(_np.asarray(bd.place(state.bodies)[0]).reshape(-1, 3))
        if extra_targets is not None:
            parts.append(_np.asarray(extra_targets).reshape(-1, 3))
        pts = _np.concatenate(parts, axis=0)
        return plan_ewald(pts, eta=self.params.eta,
                          tol=self.params.ewald_tol, n_fill=n_fill,
                          n_src=n_src)

    def _ewald_args(self, state: SimState, extra_targets=None):
        """(stripped static plan, traced anchors) or (None, None)."""
        if self.params.pair_evaluator != "ewald":
            return None, None
        from ..ops.ewald import plan_anchors, strip_anchors

        plan = self.make_ewald_plan(state, extra_targets=extra_targets)
        return strip_anchors(plan), plan_anchors(plan)

    def step(self, state: SimState):
        """One trial step at state.dt: solve + advance components (`step`,
        `system.cpp:482-492`). Returns (new_state, solution, info)."""
        plan, anchors = self._ewald_args(state)
        return self._solve_jit(state, ewald_plan=plan, ewald_anchors=anchors)

    def run(self, state: SimState, *, writer=None, max_steps: int | None = None,
            rng=None, metrics_path: str | None = None,
            profile_dir: str | None = None):
        """Adaptive time loop (`run`, `system.cpp:516-571`).

        Host-side control flow around the jit'd step: accept/reject on fiber
        error + collision, scale dt by beta_up/beta_down, keep the previous
        pytree as the backup for rejected steps. ``writer`` is called with
        (state, solution) after each accepted step crossing a dt_write boundary
        (plus ``rng_state=`` when ``rng`` is given). Passing a `SimRNG` enables
        dynamic instability when `params.dynamic_instability.n_nodes > 0`
        (`prep_state_for_solver`, `system.cpp:403`); like the reference, a
        rejected step does not rewind the RNG.

        Each trial step is logged (the reference's per-step spdlog lines,
        `system.cpp:474,567`); ``metrics_path`` additionally appends one JSON
        line per step {t, dt, iters, residual, fiber_error, accepted, wall_s}
        — the structured-metrics upgrade SURVEY.md §5.1 calls for.
        """
        import contextlib

        metrics_fh = open(metrics_path, "a") if metrics_path else None
        # XLA/TPU profiler capture of the whole loop (the structured upgrade
        # over the reference's omp_get_wtime logging, SURVEY.md §5.1); open
        # with TensorBoard or xprof
        prof = (jax.profiler.trace(profile_dir) if profile_dir is not None
                else contextlib.nullcontext())
        try:
            with prof:
                state = self._run_loop(state, writer=writer,
                                       max_steps=max_steps, rng=rng,
                                       metrics_fh=metrics_fh)
        finally:
            if metrics_fh is not None:
                metrics_fh.close()
        return state

    def _run_loop(self, state: SimState, *, writer, max_steps, rng, metrics_fh):
        from .dynamic_instability import apply_dynamic_instability

        p = self.params
        n_steps = 0
        while float(state.time) < p.t_final:
            if max_steps is not None and n_steps >= max_steps:
                break
            backup = state
            if rng is not None and p.dynamic_instability.n_nodes > 0:
                # a ring mesh constrains nucleation's capacity growth to
                # mesh-divisible node counts (grow_capacity invariant)
                nm = self.mesh.size if self._ring_active() else 1
                state = apply_dynamic_instability(state, p, rng,
                                                  node_multiple=nm)
            wall0 = _time.perf_counter()
            new_state, solution, info = self.step(state)
            # host fetch, not block_until_ready: blocking on one leaf was
            # observed returning before the program finished, undermeasuring
            # wall_s by >100x
            residual = float(info.residual)
            wall_s = _time.perf_counter() - wall0
            n_steps += 1
            converged = bool(info.converged)
            fiber_error = float(info.fiber_error)

            dt = float(state.dt)
            dt_new = dt
            accept = True
            if p.adaptive_timestep_flag:
                if converged and fiber_error <= p.fiber_error_tol:
                    accept = True
                    if fiber_error <= 0.9 * p.fiber_error_tol:
                        dt_new = min(p.dt_max, dt * p.beta_up)
                else:
                    dt_new = dt * p.beta_down
                    accept = False

                if converged and bool(self._collision_jit(new_state)):
                    dt_new = dt * 0.5
                    accept = False

                if dt_new < p.dt_min:
                    raise RuntimeError("Timestep smaller than dt_min")

            logger.info(
                "step t=%.6g dt=%.4g iters=%d residual=%.3e (true %.3e) "
                "fiber_error=%.3e %s (%.3fs)", float(state.time), dt,
                int(info.iters), residual,
                float(info.residual_true), fiber_error,
                "accepted" if accept else "rejected", wall_s)
            if bool(info.loss_of_accuracy):
                # `solver_hydro.cpp:85-92`: implicit convergence with a
                # drifted explicit residual means the answer is worse than
                # the solver claims
                logger.warning(
                    "GMRES loss of accuracy: implicit residual %.3e converged "
                    "but explicit ||b-Ax||/||b|| = %.3e (> 10x tol %.1e)",
                    residual, float(info.residual_true),
                    p.gmres_tol)
            if metrics_fh is not None:
                metrics_fh.write(json.dumps({
                    "t": float(state.time), "dt": dt, "iters": int(info.iters),
                    "residual": residual,
                    "residual_true": float(info.residual_true),
                    "fiber_error": fiber_error, "accepted": accept,
                    "wall_s": round(wall_s, 4)}) + "\n")
                metrics_fh.flush()

            if accept:
                t_new = float(state.time) + dt
                state = new_state._replace(
                    time=jnp.asarray(t_new, dtype=state.time.dtype),
                    dt=jnp.asarray(dt_new, dtype=state.dt.dtype))
                if writer is not None and (int(t_new / p.dt_write)
                                           > int((t_new - dt) / p.dt_write)):
                    if rng is not None:
                        writer(state, solution, rng_state=rng.dump_state())
                    else:
                        writer(state, solution)
            else:
                state = backup._replace(dt=jnp.asarray(dt_new, dtype=state.dt.dtype))
        return state
