"""System orchestrator: state pytree, coupled matvec, solve, adaptive time loop.

TPU-native replacement for the reference `System` namespace
(`/root/reference/src/core/system.cpp`): instead of namespace-level singletons
mutated in place, the whole simulation is one immutable `SimState` pytree and the
per-step work (`prep_state_for_solver` -> GMRES -> component steps) is a jit'd
pure function. Backup/restore for rejected adaptive steps
(`system.cpp:495-513`) is free: keep the previous pytree.

The solution vector layout matches the reference (`system.cpp:75-96`):
[fibers (4n per fiber) | shell (3 per node) | bodies (3 per node + 6 per body)].
"""

from __future__ import annotations

import json
import logging
import math
import time as _time
from typing import NamedTuple, Optional

import jax
import jax.numpy as jnp

logger = logging.getLogger("skellysim_tpu")

from ..bodies import bodies as bd
from ..fibers import container as fc
from ..guard import verdict as _verdict
from ..obs import tracer as obs_tracer
from ..obs.compile_log import observed_jit
from ..params import Params, REFINE_PAIR_IMPLS
from ..periphery import periphery as peri
from ..periphery.periphery import PeripheryShape, PeripheryState
from ..solver import gmres, gmres_ir
from ..solver.gmres import collective_rounds, history_rows
from .sources import BackgroundFlow, PointSources


def _cast_floats(tree, dtype):
    """Cast every floating leaf of a pytree to ``dtype`` (ints/bools pass)."""
    return jax.tree_util.tree_map(
        lambda a: a.astype(dtype)
        if jnp.issubdtype(jnp.asarray(a).dtype, jnp.floating) else a, tree)


class SimState(NamedTuple):
    """Complete simulation state (a pytree).

    ``fibers`` is a single `FiberGroup` or a TUPLE of them — one bucket per
    fiber resolution, the batched answer to the reference's mixed-resolution
    `std::list` container (`fiber_container_finite_difference.cpp:519-562`).
    Bucket order is the solution-vector order.
    """

    time: jnp.ndarray
    dt: jnp.ndarray
    fibers: Optional[fc.FiberGroup]
    points: Optional[PointSources]
    background: Optional[BackgroundFlow]
    shell: Optional[PeripheryState] = None
    bodies: Optional[bd.BodyGroup] = None
    #: skelly-flight recorder ring (`obs.flight.FlightRecorder`, a
    #: [Params.flight_window, 13] f32 ring + write counter) — per-step
    #: physics diagnostics with anomaly provenance, written in-trace by
    #: `_solve_impl`. None when `Params.flight_window == 0` (the default):
    #: an absent pytree field, so pre-flight programs are bitwise
    #: identical. Arm/strip with `System.ensure_flight`.
    flight: Optional[tuple] = None


#: tuple-of-buckets view of a fibers field (`fc.as_buckets`)
fiber_buckets = fc.as_buckets

#: tuple-of-buckets view of a bodies field (`bd.as_buckets`) — one bucket
#: per body shape/resolution, the reference's mixed `BodyContainer`
#: (`body_container.cpp:523-550`)
body_buckets = bd.as_buckets


def _rewrap_bodies(bodies, new_buckets: tuple):
    if isinstance(bodies, bd.BodyGroup):
        return new_buckets[0]
    return tuple(new_buckets)


def _rewrap_fibers(fibers, new_buckets: tuple):
    """Rebuild the fibers field in its original shape (group vs tuple)."""
    if isinstance(fibers, fc.FiberGroup):
        return new_buckets[0]
    return tuple(new_buckets)


#: run-loop metrics JSONL schema: `System.run(metrics_path=...)` appends one
#: JSON object per TRIAL step with exactly these keys (documented in
#: docs/performance.md "Run-loop metrics JSONL"; schema-pinned by
#: tests/test_cli_pipeline.py). Resumed runs are segmented by a marker line
#: {"resume": true, "t": ...} that `cli.run(resume=True)` appends first.
METRICS_FIELDS = ("step", "t", "dt", "iters", "gmres_cycles",
                  "collective_rounds", "residual", "residual_true",
                  "fiber_error", "accepted", "refines", "loss_of_accuracy",
                  "health", "guard_retries", "nucleations", "catastrophes",
                  "active_fibers", "wall_s", "wall_ms", "gmres_history",
                  "flight")


def crossed_write_boundary(t_new: float, dt: float, dt_write: float) -> bool:
    """True when the accepted step (t_new - dt, t_new] crosses a dt_write
    frame boundary.

    Float-robust: the naive ``int(t_new / dt_write) > int((t_new - dt) /
    dt_write)`` comparison skips a frame when t, accumulated by repeated
    addition, lands just BELOW a boundary (e.g. eight 0.1-steps reach
    0.7999999999999999, whose naive frame index is still 7 — the t=0.8 frame
    is silently dropped). Boundary indices here tolerate a 1e-9 relative
    shortfall, far above accumulated roundoff (~n ulps) and far below any
    physical dt. Shared by `System._run_loop` and the ensemble scheduler so
    batched and sequential runs write identical frame sets.
    """
    def idx(t: float) -> int:
        r = t / dt_write
        return math.floor(r + 1e-9 * max(abs(r), 1.0))

    return idx(t_new) > idx(t_new - dt)


class StepInfo(NamedTuple):
    converged: jnp.ndarray
    iters: jnp.ndarray
    residual: jnp.ndarray       # implicit (Givens) relative residual
    fiber_error: jnp.ndarray
    #: explicit ||b - A x|| / ||b|| from one post-solve matvec
    #: (`solver_hydro.cpp:81-92`); nan until populated by a solve
    residual_true: jnp.ndarray = jnp.nan
    #: converged by the implicit residual but the explicit one disagrees by
    #: >10x tol — Belos' loss-of-accuracy analogue (`solver_hydro.cpp:85-92`)
    loss_of_accuracy: jnp.ndarray = False
    #: mixed-mode refinement sweeps (`solver.gmres_ir`); 0 for full precision
    refines: int | jnp.ndarray = 0
    #: GMRES restart cycles (skelly-scope `gmres_cycles`)
    cycles: int | jnp.ndarray = 0
    #: per-restart convergence ring buffer ([gmres_history, 3] rows of
    #: cumulative iters / implicit / explicit; `solver.gmres` docstring) or
    #: None when Params.gmres_history == 0
    history: jnp.ndarray | None = None
    #: int32 packed health word (`guard.verdict`: nonfinite / stagnation /
    #: breakdown from the solver, dt_underflow stamped by the stepping
    #: layer) — computed device-side next to `loss_of_accuracy`, 0 = healthy
    health: int | jnp.ndarray = 0
    #: the dt this trial actually solved with — equals the input
    #: ``state.dt`` unless the guard escalation ladder (`guard.escalate`,
    #: `Params.guard_dt_halvings`) retried at a halved dt; the run
    #: loop/ensemble advance ``time`` by THIS, not the entry dt
    dt_used: float | jnp.ndarray = 0.0
    #: guard-ladder retries this trial paid (0 with the ladder off)
    guard_retries: int | jnp.ndarray = 0


def solution_from_state(state: SimState):
    """Rebuild the flat solver solution vector from component state.

    Inverse of the post-solve advance: fibers contribute [x|y|z|tension] per
    fiber, the shell its density, bodies their stored solution — matching the
    reference's reconstruction on resume (`trajectory_reader.cpp:227-249`).
    """
    parts = []
    for f in fiber_buckets(state.fibers):
        vec = jnp.concatenate(
            [f.x[:, :, 0], f.x[:, :, 1], f.x[:, :, 2], f.tension], axis=1)
        if f.rt_mats is not None:
            # masked padding rows carry placeholder coordinates, but their
            # solution entries are exact zeros (they solve the identity)
            vec = jnp.where(f.rt_mats.sol_mask[None, :], vec, 0.0)
        parts.append(vec.reshape(-1))
    if state.shell is not None:
        parts.append(state.shell.density)
    for g in bd.as_buckets(state.bodies):
        parts.append(g.solution.reshape(-1))
    if not parts:
        raise ValueError("state has no implicit components")
    return jnp.concatenate(parts)


class System:
    """Holds static config; all dynamics flow through pure jit'd functions."""

    def __init__(self, params: Params, shell_shape: PeripheryShape | None = None,
                 mesh=None):
        from ..ops.evaluator import EVALUATORS

        if params.pair_evaluator not in EVALUATORS:
            raise ValueError(
                f"unknown pair_evaluator {params.pair_evaluator!r}; "
                f"runtime values are {', '.join(map(repr, EVALUATORS))}")
        if params.solver_precision not in ("full", "mixed", "auto"):
            raise ValueError(
                f"unknown solver_precision {params.solver_precision!r}; "
                "use 'full', 'mixed', or 'auto'")
        if params.kernel_impl not in ("exact", "mxu", "df", "pallas",
                                      "pallas_df"):
            # the kernel seam's else-branch would silently run "exact" for a
            # typo'd name — reject at construction like the other knobs
            raise ValueError(
                f"unknown kernel_impl {params.kernel_impl!r}; "
                "use 'exact', 'mxu', 'df', 'pallas', or 'pallas_df'")
        if params.pair_evaluator == "spectral":
            if len(params.periodic_box) not in (2, 3) or any(
                    L <= 0 for L in params.periodic_box):
                raise ValueError(
                    "pair_evaluator='spectral' needs params.periodic_box — "
                    "(Lx, Ly, Lz) for a triply periodic box or (Lx, Ly) for "
                    f"a doubly periodic slab; got {params.periodic_box!r}. "
                    "For free space use 'ewald' or 'tree'.")
        elif params.periodic_box:
            raise ValueError(
                f"params.periodic_box is set but pair_evaluator "
                f"{params.pair_evaluator!r} sums free-space kernels and "
                "would ignore the periodic images; use "
                "pair_evaluator='spectral'")
        self.params = params
        self.shell_shape = shell_shape
        # device mesh for the ring pair evaluator (params.pair_evaluator="ring");
        # GSPMD sharding via parallel.shard_state needs no mesh here
        self.mesh = mesh
        # spectral-evaluator FFT grid ladder (`make_spectral_plan`); the
        # CLIs/listener set it from `BucketPolicy.grid_ladder` after
        # construction, () = the built-in `ops.spectral.GRID_RUNGS`
        self.grid_ladder: tuple = ()
        if params.refine_pair_impl not in REFINE_PAIR_IMPLS:
            raise ValueError(
                f"unknown refine_pair_impl {params.refine_pair_impl!r}; "
                f"use one of {REFINE_PAIR_IMPLS}")
        if params.precond not in ("gs", "jacobi"):
            raise ValueError(
                f"unknown precond {params.precond!r}; use 'gs' or 'jacobi'")
        # all entry-point jits route through `obs.compile_log.observed_jit`
        # (a `jax.jit` twin): with a tracer active (System.run(trace_path=),
        # the ensemble/bench paths) every fresh trace/compile lands in the
        # telemetry stream as a `compile` event; without one the wrapper is
        # a counter bump per call. `.trace()` passes through, so the audit
        # registry's `built_from` keeps consuming these directly.
        self._solve_jit = observed_jit(self._solve_impl, name="system.solve",
                                       static_argnames=("pair",))
        # donating twin for the run loop: the input state's buffers (the
        # dense shell operators above all) alias into the unchanged output
        # leaves instead of double-buffering per step. Only safe where a
        # rejected step never rolls back to the donated input — `_run_loop`
        # selects it exactly when the adaptive gate is off; CPU XLA has no
        # donation (it would warn per call), so there it is never selected
        # (tests pin the aliasing at lowering time instead).
        self._solve_jit_donated = observed_jit(self._solve_impl,
                                               name="system.solve_donated",
                                               static_argnames=("pair",),
                                               donate_argnums=(0,))
        #: built SPMD step programs keyed by (mesh, state structure) —
        #: see `step_spmd`
        self._spmd_steps = {}
        self._collision_jit = observed_jit(self._check_collision,
                                           name="system.collision")
        self._vel_jit = observed_jit(self._velocity_at_targets_impl,
                                     name="system.velocity_at_targets",
                                     static_argnames=("pair",))

    @property
    def _refine_impl(self) -> str:
        """Pairwise tile for mixed-mode f64 residual/prep flows (see
        Params.refine_pair_impl). Resolved lazily from self.params — the
        codebase's pattern of replacing params post-construction
        (`system.params = dataclasses.replace(...)`) must not pin a stale
        tile."""
        impl = self.params.refine_pair_impl
        if impl == "auto":
            return "df" if jax.default_backend() != "cpu" else "exact"
        return impl

    def _precision_for(self, state) -> str:
        """Resolve Params.solver_precision for one state ("full"/"mixed").

        Policy lives in `params.resolve_precision`. Host-side static
        dispatch: dtype and backend are trace-time constants, so each
        resolution compiles its own program."""
        from ..params import resolve_precision

        return resolve_precision(self.params.solver_precision,
                                 state.time.dtype == jnp.float64)

    def _ring_active(self) -> bool:
        ring = self.params.pair_evaluator == "ring"
        if ring and self.mesh is None:
            # trace-time (not per-step) diagnostic: silent degradation would
            # surprise a user expecting O(N/D) per-chip memory
            import warnings

            warnings.warn("pair_evaluator='ring' falls back to 'direct': "
                          "no mesh was configured")
            return False
        return ring

    def _ring_pad_targets(self, r_trg):
        """Pad the target rows to a mesh-size multiple (shard_map needs even
        blocks). Pad points sit at 1e6 — far from any geometry, never
        coincident with the 1e7 source pads — and their rows are sliced off."""
        T = r_trg.shape[0]
        pad = (-T) % self.mesh.size
        if pad:
            far = jnp.full((pad, 3), 1e6, dtype=r_trg.dtype)
            r_trg = jnp.concatenate([r_trg, far], axis=0)
        return r_trg, T

    def _fiber_flow(self, state: SimState, caches_list, r_trg, forces_list,
                    subtract_self: bool = True, impl: str | None = None,
                    pair=None, pair_anchors=None):
        """Fiber-source flow through the selected pair evaluator
        (the reference's `params.pair_evaluator` seam,
        `fiber_container_base.cpp:20-33`). All resolution buckets contribute
        sources to ONE evaluator pass (`fc.flow_multi`). The ring path pads
        the target rows to a mesh multiple and rotates fiber-node source
        blocks around the ICI ring; shell/body target rows ride along in the
        padded target set. ``impl`` overrides `params.kernel_impl`; the
        mixed solver's f64 residual passes "df", which the ring evaluator
        serves with its own double-float tile
        (`parallel.ring.ring_stokeslet_df`)."""
        buckets = fiber_buckets(state.fibers)
        if impl is None:
            impl = self.params.kernel_impl
        if pair is not None and pair.is_fast:
            # the O(N log N) evaluators serve whoever passes a planned
            # spec; callers whose flows must stay dense (the mixed
            # solver's f64 residual/prep — ewald_tol/tree_tol must not cap
            # the refined residual) pass pair=None, gating on the flow's
            # ROLE rather than the tile name (refine_pair_impl="auto"
            # resolves to "exact" on CPU, so an impl-name gate leaked
            # those flows here)
            return fc.flow_multi(buckets, caches_list, r_trg, forces_list,
                                 self.params.eta, subtract_self=subtract_self,
                                 pair=pair, pair_anchors=pair_anchors)
        if not self._ring_active():
            return fc.flow_multi(buckets, caches_list, r_trg, forces_list,
                                 self.params.eta, subtract_self=subtract_self,
                                 evaluator="direct", impl=impl)
        nfn = sum(g.n_fibers * g.n_nodes for g in buckets)
        if nfn % self.mesh.size != 0:
            raise ValueError(
                f"pair_evaluator='ring' requires the total fiber node count "
                f"({nfn}) to be divisible by the mesh size ({self.mesh.size}); "
                "round the fiber batch up (inactive padding fibers are free)")
        r_pad, T = self._ring_pad_targets(r_trg)
        vel = fc.flow_multi(buckets, caches_list, r_pad, forces_list,
                            self.params.eta, subtract_self=subtract_self,
                            evaluator="ring", mesh=self.mesh, impl=impl)
        return vel[:T]

    def _shell_flow(self, state: SimState, r_trg, density,
                    impl: str | None = None, pair=None, pair_anchors=None):
        """Shell -> target flow through the pair-evaluator seam
        (`include/kernels.hpp:78-122`: one evaluator serves all components).
        The density->f_dl math and source padding live in `peri.flow`; only
        the target padding is System's job. A supplied fast ``pair`` spec
        routes the double layer through the spectral-Ewald or treecode
        stresslet (the reference's `periphery.cpp:337-352` FMM path) when
        the shell is large enough to warrant it
        (`params.ewald_min_sources`); callers whose flows must stay dense
        (mixed-mode refinement/prep) pass no spec."""
        if impl is None:
            impl = self.params.kernel_impl
        if (pair is not None and pair.is_fast
                and state.shell.n_nodes >= self.params.ewald_min_sources):
            return peri.flow(state.shell, r_trg, density, self.params.eta,
                             pair=pair, pair_anchors=pair_anchors)
        if not self._ring_active():
            return peri.flow(state.shell, r_trg, density, self.params.eta,
                             impl=impl)
        r_pad, T = self._ring_pad_targets(r_trg)
        return peri.flow(state.shell, r_pad, density, self.params.eta,
                         evaluator="ring", mesh=self.mesh, impl=impl)[:T]

    def _body_pair_args(self, group, pair, pair_anchors):
        """(pair, anchors) for one body bucket's double-layer flow, or
        (None, None) when its node count is below `params.ewald_min_sources`
        (dense is strictly cheaper than an extra fast-evaluator pass
        there)."""
        if (pair is None or not pair.is_fast or group is None
                or group.n_bodies * group.n_nodes
                < self.params.ewald_min_sources):
            return None, None
        return pair, pair_anchors

    # ------------------------------------------------------------- state setup

    def make_state(self, fibers=None, points=None, background=None,
                   shell=None, bodies=None) -> SimState:
        if fibers is None and shell is None and bodies is None:
            raise ValueError(
                "state needs at least one implicit component (fibers, shell, or "
                "bodies) to solve; point/background sources only contribute flow")
        if shell is not None and self.shell_shape is None:
            raise ValueError(
                "a periphery state requires System(shell_shape=PeripheryShape(...)) "
                "matching the precompute geometry; use kind='generic' explicitly "
                "for a shell with no wall physics")
        if shell is not None and background is not None and background.is_active():
            # `sanity_check`, system.cpp:625-626
            raise ValueError("background sources are incompatible with peripheries")
        fb = fiber_buckets(fibers)
        if fibers is not None:
            dtype = fb[0].x.dtype
        elif shell is not None:
            dtype = shell.density.dtype
        elif bodies is not None:
            dtype = body_buckets(bodies)[0].solution.dtype
        else:
            dtype = jnp.float64
        from ..obs import flight as flight_mod

        return SimState(
            time=jnp.asarray(0.0, dtype=dtype),
            dt=jnp.asarray(self.params.dt_initial, dtype=dtype),
            fibers=fibers, points=points, background=background,
            shell=shell, bodies=bodies,
            # skelly-flight ring (None at flight_window=0: the pytree is
            # bit-identical to a pre-flight state)
            flight=flight_mod.new_ring(self.params.flight_window))

    def ensure_flight(self, state: SimState) -> SimState:
        """``state`` with its flight-recorder ring matching
        `Params.flight_window`: arm a fresh ring when the window is on
        and the state carries none (frame-decoded resumes, snapshots —
        the wire never carries rings), strip it when the window is off,
        re-arm on a window-size mismatch. Host-side normalization — the
        run loop, the ensemble seating paths, and `step_spmd` all call
        it, so every state entering a compiled step shares the template's
        pytree structure."""
        from ..obs import flight as flight_mod

        window = self.params.flight_window
        if window <= 0:
            return (state if state.flight is None
                    else state._replace(flight=None))
        if (state.flight is None
                or state.flight.rows.shape[-2] != window):
            return state._replace(flight=flight_mod.new_ring(window))
        return state

    # ----------------------------------------------------------------- helpers

    def _node_positions(self, state: SimState, body_caches=None):
        """All hydrodynamic node positions [fibers | shell | bodies]
        (`get_node_maps`).

        Pass ``body_caches`` when available so body node targets reuse the
        exact cached lab-frame positions the kernel sources use: recomputing
        `place()` in a different precision shifts "self" pairs off exact
        coincidence (distance ~1 ulp instead of 0), un-masking the kernel
        singularity.
        """
        parts = []
        for g in fiber_buckets(state.fibers):
            parts.append(fc.node_positions(g))
        if state.shell is not None:
            parts.append(state.shell.nodes)
        b_list = body_buckets(state.bodies)
        for i, g in enumerate(b_list):
            nodes = (body_caches[i].nodes if body_caches is not None
                     else bd.place(g)[0])
            parts.append(nodes.reshape(-1, 3))
        if not parts:
            # skelly-lint: ignore[dtype-discipline] — empty-target fallback; a solvable state always has ≥1 component (make_state enforces it), so no state dtype exists here
            return jnp.zeros((0, 3), dtype=jnp.float64)
        return jnp.concatenate(parts, axis=0)

    def _counts(self, state: SimState):
        nf_nodes = sum(g.n_fibers * g.n_nodes
                       for g in fiber_buckets(state.fibers))
        ns_nodes = state.shell.n_nodes if state.shell is not None else 0
        nb_nodes = sum(g.n_bodies * g.n_nodes
                       for g in body_buckets(state.bodies))
        return nf_nodes, ns_nodes, nb_nodes

    def _sizes(self, state: SimState):
        fib = sum(fc.solution_size(g) for g in fiber_buckets(state.fibers))
        shell = state.shell.solution_size if state.shell is not None else 0
        body = sum(g.solution_size for g in body_buckets(state.bodies))
        return fib, shell, body

    def _external_flows(self, state: SimState, r_trg):
        """Point-source + background contributions (`system.cpp:445-446`)."""
        v = jnp.zeros_like(r_trg)
        if state.points is not None:
            v = v + state.points.flow(r_trg, self.params.eta, state.time)
        if state.background is not None:
            v = v + state.background.flow(r_trg, self.params.eta)
        return v

    # ------------------------------------------------- fiber-periphery coupling

    def _periphery_force_fibers(self, state: SimState):
        """Steric wall force on fiber nodes, one [nf, n, 3] array per bucket
        (`periphery_force`).

        Applied unconditionally during the solve, like the reference's
        `prep_state_for_solver` (`system.cpp:422`); the
        periphery_interaction_flag only gates post-processing
        (`velocity_at_targets`, `system.cpp:340-341`).
        """
        buckets = fiber_buckets(state.fibers)
        fp = self.params.fiber_periphery_interaction
        if state.shell is None:
            return [jnp.zeros_like(g.x) for g in buckets]
        shape = self.shell_shape
        return [jax.vmap(
            lambda x, mc: peri.fiber_steric_force(shape, x, fp.f_0, fp.l_0, mc)
        )(g.x, g.minus_clamped) for g in buckets]

    def _update_plus_pinning(self, state: SimState) -> SimState:
        """Hinge plus ends near an attachment-active periphery
        (`update_boundary_conditions`, `fiber_finite_difference.cpp:74-91`)."""
        pb = self.params.periphery_binding
        buckets = fiber_buckets(state.fibers)
        if state.shell is None or not pb.active or not buckets:
            return state
        shape = self.shell_shape

        def make_one(g):
            rt = g.rt_mats

            def one(x):
                if rt is None:
                    tip = x[-1]
                else:
                    # the plus end is the last LIVE node; masked padding
                    # rows replicate node 0 and must not read as contact
                    tip = jnp.tensordot(rt.e_last.astype(x.dtype), x, axes=1)
                    x = jnp.where(rt.node_mask[:, None], x, tip)
                tip = tip / jnp.linalg.norm(tip)
                angle = jnp.arccos(jnp.clip(tip[2], -1.0, 1.0))
                in_window = ((angle >= pb.polar_angle_start)
                             & (angle <= pb.polar_angle_end))
                near = peri.check_collision(shape, x, pb.threshold)
                return in_window & near

            return one

        new = tuple(g._replace(plus_pinned=jax.vmap(make_one(g))(g.x))
                    for g in buckets)
        return state._replace(fibers=_rewrap_fibers(state.fibers, new))

    # ------------------------------------------------------------------- prep

    def _prep(self, state: SimState, pair=None,
              pair_anchors=None):
        """All velocities/forces/RHS/BC assembly (`prep_state_for_solver`,
        `system.cpp:398-458`). Returns (state, fiber caches, body caches,
        shell RHS, body RHS)."""
        p = self.params
        state = self._update_plus_pinning(state)
        buckets = fiber_buckets(state.fibers)
        caches = None
        body_caches = None
        shell_rhs = None
        body_rhs = None

        r_all = self._node_positions(state)
        nf_nodes, ns_nodes, nb_nodes = self._counts(state)
        v_all = jnp.zeros_like(r_all)

        precision = self._precision_for(state)
        precond_dtype = (jnp.float32 if precision == "mixed" else None)
        # mixed mode evaluates the (f64) prep flows through the refinement
        # tile — on accelerators that is double-float f32 (~1e-14, sets the
        # RHS accuracy floor) instead of the emulated-f64 cliff; those flows
        # also stay DENSE (plan withheld below) so ewald_tol cannot cap the
        # RHS accuracy
        refine_prep = (precision == "mixed"
                       and state.time.dtype == jnp.float64)
        impl_flow = self._refine_impl if refine_prep else p.kernel_impl
        prep_pair = None if refine_prep else pair
        prep_anchors = None if refine_prep else pair_anchors

        if buckets:
            caches = [fc.update_cache(g, state.dt, p.eta) for g in buckets]

            external = self._periphery_force_fibers(state)
            motor = [jnp.where(state.time >= p.implicit_motor_activation_delay,
                               fc.generate_constant_force(g, c),
                               jnp.zeros_like(g.x))
                     for g, c in zip(buckets, caches)]

            v_all = v_all + self._fiber_flow(state, caches, r_all, external,
                                             impl=impl_flow,
                                             pair=prep_pair,
                                             pair_anchors=prep_anchors)

        b_list = body_buckets(state.bodies)
        if b_list:
            body_caches = [bd.update_cache(g, p.eta,
                                           precond_dtype=precond_dtype)
                           for g in b_list]
            # external body forces/torques induce explicit flow everywhere
            # (`system.cpp:430-443`)
            for g, bc in zip(b_list, body_caches):
                ext_ft = bd.external_forces_torques(g, state.time)
                v_all = v_all + bd.flow(g, bc, r_all, None, ext_ft, p.eta,
                                        impl=impl_flow)

        v_all = v_all + self._external_flows(state, r_all)

        if b_list:
            body_rhs = []
            off = nf_nodes + ns_nodes
            for g in b_list:
                nbn = g.n_bodies * g.n_nodes
                v_bodies = v_all[off:off + nbn].reshape(
                    g.n_bodies, g.n_nodes, 3)
                body_rhs.append(bd.update_RHS(g, v_bodies))
                off += nbn

        if buckets:
            off = 0
            new_caches = []
            for g, c, mo, ex in zip(buckets, caches, motor, external):
                nfn = g.n_fibers * g.n_nodes
                v_fib = v_all[off:off + nfn].reshape(g.n_fibers, g.n_nodes, 3)
                new_caches.append(fc.update_rhs_and_bc(
                    g, c, state.dt, p.eta, v_fib, mo + ex, ex,
                    precond_dtype=precond_dtype))
                off += nfn
            caches = new_caches
        if state.shell is not None:
            v_shell = v_all[nf_nodes:nf_nodes + ns_nodes]
            shell_rhs = peri.update_RHS(v_shell,
                                        node_mask=state.shell.node_mask)

        return state, caches, body_caches, shell_rhs, body_rhs

    # ------------------------------------------------------- operator closures

    def _apply_matvec(self, state: SimState, caches, body_caches, x_flat,
                      lo=None, flow_impl: str | None = None, pair=None,
                      pair_anchors=None):
        """Coupled operator A x (`apply_matvec`, `system.cpp:269-324`).

        ``lo`` is an optional (state, caches, body_caches) triple whose float
        leaves are a lower precision (f32). When given, the O(N^2) pairwise
        flows and the well-scaled shell/body dense ops — i.e. all the flops —
        are evaluated through it, while the stiff fiber-local ops (A_bc rows
        reach ~1e7, so f32 entry rounding injects O(1) absolute noise) and the
        fiber-body link conditions stay in the ``x_flat`` dtype. This is the
        cheap operator `gmres_ir` iterates with; exactness is restored by the
        f64 refinement residuals.

        ``flow_impl`` overrides the pairwise tile for the flows (the mixed
        solver's f64 residual matvec passes the double-float tile).
        """
        p = self.params
        if flow_impl is None:
            flow_impl = p.kernel_impl
        buckets = fiber_buckets(state.fibers)
        shell = state.shell
        bodies = state.bodies
        fib_size, shell_size, body_size = self._sizes(state)
        nf_nodes, ns_nodes, nb_nodes = self._counts(state)
        x_shell = x_flat[fib_size:fib_size + shell_size]

        f_state, f_caches, f_bcaches = (state, caches, body_caches) if lo is None else lo
        hi_dtype = x_flat.dtype
        # without a lo seam every cast below is a no-op (lo_dtype == x dtype);
        # deriving it from state.time would silently up-cast f32 fiberless
        # states whose time scalar defaulted to f64
        lo_dtype = hi_dtype if lo is None else lo[0].time.dtype

        r_all = self._node_positions(f_state, f_bcaches)
        v_all = jnp.zeros_like(r_all)

        x_fibs = []
        if buckets:
            off = 0
            for g in buckets:
                size = fc.solution_size(g)
                x_fibs.append(x_flat[off:off + size].reshape(g.n_fibers,
                                                             4 * g.n_nodes))
                off += size
            fws = [fc.apply_fiber_force(g, c, xf)
                   for g, c, xf in zip(buckets, caches, x_fibs)]
            v_all = v_all + self._fiber_flow(f_state, f_caches, r_all,
                                             [fw.astype(lo_dtype) for fw in fws],
                                             subtract_self=True,
                                             impl=flow_impl,
                                             pair=pair,
                                             pair_anchors=pair_anchors)

        if shell is not None and (buckets or bodies is not None):
            # shell flow is evaluated at fiber and body nodes only; the shell
            # self-interaction lives in the dense operator (`system.cpp:301-315`)
            r_fibbody = jnp.concatenate(
                [r_all[:nf_nodes], r_all[nf_nodes + ns_nodes:]], axis=0)
            v_shell2fibbody = self._shell_flow(f_state, r_fibbody,
                                               x_shell.astype(lo_dtype),
                                               impl=flow_impl,
                                               pair=pair,
                                               pair_anchors=pair_anchors)
            v_all = v_all.at[:nf_nodes].add(v_shell2fibbody[:nf_nodes])
            v_all = v_all.at[nf_nodes + ns_nodes:].add(v_shell2fibbody[nf_nodes:])

        v_boundaries = None
        x_bods = []
        b_list = body_buckets(bodies)
        f_b_list = body_buckets(f_state.bodies)
        if b_list:
            nbt = bd.n_total(b_list)
            off_b = fib_size + shell_size
            for g in b_list:
                size = g.solution_size
                x_bods.append(x_flat[off_b:off_b + size].reshape(
                    g.n_bodies, 3 * g.n_nodes + 6))
                off_b += size
            body_fts = [jnp.zeros((g.n_bodies, 6), dtype=hi_dtype)
                        for g in b_list]
            if buckets:
                # link conditions per (fiber bucket x body bucket): each
                # fiber's GLOBAL binding_body id remaps to a bucket-local
                # slot (-1 elsewhere), so a fiber contributes to exactly one
                # body bucket and v_boundary sums correctly
                v_boundaries = [jnp.zeros((g.n_fibers, 7), dtype=hi_dtype)
                                for g in buckets]
                for j, (gb, bc, xb) in enumerate(
                        zip(b_list, body_caches, x_bods)):
                    for i, (gf, c, xf) in enumerate(
                            zip(buckets, caches, x_fibs)):
                        gf_loc = bd.local_binding(gf, gb, nbt)
                        vb, ft = bd.link_conditions(gb, bc, gf_loc, c,
                                                    xf, xb)
                        v_boundaries[i] = v_boundaries[i] + vb
                        body_fts[j] = body_fts[j] + ft
            for gb, f_gb, f_bc, xb, ft in zip(b_list, f_b_list,
                                              f_bcaches or [None] * len(b_list),
                                              x_bods, body_fts):
                b_plan, b_anchors = self._body_pair_args(gb, pair,
                                                          pair_anchors)
                v_all = v_all + bd.flow(f_gb, f_bc, r_all,
                                        xb.astype(lo_dtype),
                                        ft.astype(lo_dtype), p.eta,
                                        impl=flow_impl, pair=b_plan,
                                        pair_anchors=b_anchors)

        res = []
        off = 0
        for i, (g, c, xf) in enumerate(zip(buckets, caches or [], x_fibs)):
            nfn = g.n_fibers * g.n_nodes
            v_fib = v_all[off:off + nfn].reshape(g.n_fibers, g.n_nodes,
                                                 3).astype(hi_dtype)
            vb = (v_boundaries[i] if v_boundaries is not None
                  else jnp.zeros((g.n_fibers, 7), dtype=hi_dtype))
            res.append(fc.matvec(g, c, xf, v_fib, vb).reshape(-1))
            off += nfn
        if shell is not None:
            v_shell = v_all[nf_nodes:nf_nodes + ns_nodes]
            res.append(peri.matvec(f_state.shell, x_shell.astype(lo_dtype),
                                   v_shell).astype(hi_dtype))
        off = nf_nodes + ns_nodes
        for g, f_gb, f_bc, xb in zip(b_list, f_b_list,
                                     f_bcaches or [None] * len(b_list),
                                     x_bods):
            nbn = g.n_bodies * g.n_nodes
            v_bodies = v_all[off:off + nbn].reshape(g.n_bodies, g.n_nodes, 3)
            res.append(bd.matvec(f_gb, f_bc, xb.astype(lo_dtype),
                                 v_bodies).astype(hi_dtype).reshape(-1))
            off += nbn
        return jnp.concatenate(res)

    def _apply_precond(self, state: SimState, caches, body_caches, x_flat,
                       pair=None, pair_anchors=None):
        """Block preconditioner P^-1 x.

        `precond="jacobi"` is the reference's independent block solves
        (`apply_preconditioner`, `system.cpp:248-262`). `precond="gs"` (the
        default) upgrades to a block Gauss-Seidel sweep, shell block first:
        the shell solve's double-layer flow is evaluated at the fiber/body
        nodes and subtracted from their right-hand sides before the
        fiber/body block solves — the triangular part of the fiber<->shell
        coupling that dominates clamped-fiber configs. One extra
        shell->fiber/body kernel evaluation per application (through the
        same `_shell_flow` evaluator seam as the matvec, so ring/Ewald
        paths serve it too).

        The whole application is scoped ``precond`` for device-time
        attribution (obs/profile.py) — nested under whatever solver phase
        invoked it (``gmres/arnoldi/precond`` in the Krylov loop)."""
        with jax.named_scope("precond"):
            return self._apply_precond_impl(state, caches, body_caches,
                                            x_flat, pair=pair,
                                            pair_anchors=pair_anchors)

    def _apply_precond_impl(self, state: SimState, caches, body_caches,
                            x_flat, pair=None, pair_anchors=None):
        buckets = fiber_buckets(state.fibers)
        fib_size, shell_size, body_size = self._sizes(state)
        nf_nodes, ns_nodes, nb_nodes = self._counts(state)
        b_list = body_buckets(state.bodies)

        y_shell = None
        if state.shell is not None:
            y_shell = peri.apply_preconditioner(
                state.shell, x_flat[fib_size:fib_size + shell_size])

        # shell-first coupling correction at fiber + body nodes
        v_corr = None
        if (self.params.precond == "gs" and y_shell is not None
                and nf_nodes + nb_nodes > 0):
            r_all = self._node_positions(state, body_caches)
            r_fibbody = jnp.concatenate(
                [r_all[:nf_nodes], r_all[nf_nodes + ns_nodes:]], axis=0)
            # the flow runs entirely in the shell's own float dtype (the
            # actual operand dtype — NOT state.time, which can be f64 on
            # f32 states, see the lo_dtype note in _apply_matvec): in
            # mixed mode `state` is the f32 lo copy, and a mixed
            # f64-density/f32-state eval would change dtypes mid-ring-carry;
            # a preconditioner only approximates, so f32 flow is plenty
            v_corr = self._shell_flow(state, r_fibbody,
                                      y_shell.astype(state.shell.nodes.dtype),
                                      pair=pair,
                                      pair_anchors=pair_anchors
                                      ).astype(x_flat.dtype)

        res = []
        off = 0
        off_v = 0
        for g, c in zip(buckets, caches or []):
            size = fc.solution_size(g)
            x_fib = x_flat[off:off + size].reshape(g.n_fibers, 4 * g.n_nodes)
            if v_corr is not None:
                nfn = g.n_fibers * g.n_nodes
                v_fib = v_corr[off_v:off_v + nfn].reshape(
                    g.n_fibers, g.n_nodes, 3)
                # fiber rows of A at (0, y_shell, 0): pure coupling term
                x_fib = x_fib - fc.matvec(
                    g, c, jnp.zeros_like(x_fib), v_fib,
                    jnp.zeros((g.n_fibers, 7), dtype=x_flat.dtype))
                off_v += nfn
            res.append(fc.apply_preconditioner(g, c, x_fib).reshape(-1))
            off += size
        if y_shell is not None:
            res.append(y_shell)
        off_b = fib_size + shell_size
        for j, g in enumerate(b_list):
            size = g.solution_size
            x_bod = x_flat[off_b:off_b + size].reshape(g.n_bodies, -1)
            if v_corr is not None:
                nbn = g.n_bodies * g.n_nodes
                v_bod = v_corr[off_v:off_v + nbn].reshape(
                    g.n_bodies, g.n_nodes, 3)
                # body rows of A at (0, y_shell, 0) = [v_nodes, 0]
                x_bod = x_bod - bd.matvec(
                    g, body_caches[j], jnp.zeros_like(x_bod), v_bod)
                off_v += nbn
            res.append(bd.apply_preconditioner(
                g, body_caches[j], x_bod).reshape(-1))
            off_b += size
        return jnp.concatenate(res)

    # ------------------------------------------------------------------- solve

    def _solve_impl(self, state: SimState, pair=None,
                    pair_anchors=None):
        """One trial solve, with the guard escalation ladder around it when
        any `Params.guard_*` stage is enabled (docs/robustness.md). The
        ladder lives HERE — below every jit/vmap entry point — so
        sequential `System.run`, the vmapped ensemble, and the donating
        run-loop twin all share one implementation."""
        out = self._solve_once(state, pair=pair, pair_anchors=pair_anchors)
        p = self.params
        if (p.guard_dt_halvings or p.guard_block_fallback
                or p.guard_f64_fallback):
            from ..guard.escalate import escalate

            out = escalate(self, state, out, pair=pair,
                           pair_anchors=pair_anchors)
        if p.flight_window > 0:
            # skelly-flight: ONE diagnostics row per trial (recording the
            # attempt that actually advanced — below the escalation
            # ladder's retries, like the health word). Pure masked jnp
            # reductions + one `.at[].set`: no host sync, vmaps per
            # ensemble member (obs.flight, docs/observability.md).
            from ..obs import flight as flight_mod

            new_state, x, info = out
            if new_state.flight is None:
                raise ValueError(
                    "Params.flight_window > 0 but the state carries no "
                    "recorder ring; arm it with System.ensure_flight "
                    "(make_state-built states arm automatically)")
            new_state = new_state._replace(flight=flight_mod.record_step(
                state, new_state, x,
                residual_true=info.residual_true, health=info.health,
                dt_used=info.dt_used, shell_shape=self.shell_shape))
            out = (new_state, x, info)
        return out

    def _solve_once(self, state: SimState, pair=None, pair_anchors=None,
                    block_s: int | None = None, force_full: bool = False):
        """The bare prep/GMRES/advance pipeline. ``block_s``/``force_full``
        are trace-time overrides for the guard ladder's fallback stages
        (`guard.escalate`): re-solve with the sequential Arnoldi cycle /
        the full-precision f64 operator instead of the configured ones."""
        p = self.params
        bs = p.gmres_block_s if block_s is None else block_s
        # skelly-pulse phase scopes (obs/profile.py PHASE_SCOPES): pure HLO
        # metadata — op counts, dtypes, collectives, retraces all unchanged,
        # so every audit contract and cost baseline stays byte-identical
        with jax.named_scope("prep"):
            state, caches, body_caches, shell_rhs, body_rhs = self._prep(
                state, pair=pair, pair_anchors=pair_anchors)

            rhs_parts = []
            for c in (caches or []):
                rhs_parts.append(c.RHS.reshape(-1))
            if shell_rhs is not None:
                rhs_parts.append(shell_rhs)
            for br in (body_rhs or []):
                rhs_parts.append(br.reshape(-1))
            if not rhs_parts:
                raise ValueError("state has no implicit components to solve")
            rhs = jnp.concatenate(rhs_parts)

        precision = "full" if force_full else self._precision_for(state)
        if precision == "mixed":
            # f64 state/assembly/refinement residuals; the Krylov loop's
            # expensive interior (kernel flows, shell/body dense ops, LU
            # preconditioners) evaluates through f32 copies via the lo seam
            # of _apply_matvec, while stiff fiber-local ops stay f64
            lo = _cast_floats((state, caches, body_caches), jnp.float32)
            # hi residual flows go through the refinement tile (df on
            # accelerators); state must be f64 for the df split to pay off
            hi_impl = (self._refine_impl
                       if state.time.dtype == jnp.float64 else p.kernel_impl)
            with jax.named_scope("gmres"):
                result = gmres_ir(
                    # hi residual matvec: dense (no ewald plan) regardless
                    # of the refinement tile — ewald_tol must not cap
                    # residual_true
                    lambda v: self._apply_matvec(state, caches, body_caches,
                                                 v, flow_impl=hi_impl),
                    lambda v: self._apply_matvec(state, caches, body_caches,
                                                 v, lo=lo, pair=pair,
                                                 pair_anchors=pair_anchors),
                    rhs,
                    precond_lo=lambda v: self._apply_precond(
                        lo[0], lo[1], lo[2], v, pair=pair,
                        pair_anchors=pair_anchors),
                    tol=p.gmres_tol, inner_tol=p.inner_tol,
                    restart=p.gmres_restart, maxiter=p.gmres_maxiter,
                    max_refine=p.max_refine, history=p.gmres_history,
                    block_s=bs)
        else:
            with jax.named_scope("gmres"):
                result = gmres(
                    lambda v: self._apply_matvec(state, caches, body_caches,
                                                 v, pair=pair,
                                                 pair_anchors=pair_anchors),
                    rhs,
                    precond=lambda v: self._apply_precond(
                        state, caches, body_caches, v, pair=pair,
                        pair_anchors=pair_anchors),
                    tol=p.gmres_tol, restart=p.gmres_restart,
                    maxiter=p.gmres_maxiter, history=p.gmres_history,
                    block_s=bs)

        with jax.named_scope("advance"):
            fib_size, shell_size, body_size = self._sizes(state)
            new_state = state
            fiber_error = jnp.asarray(0.0, dtype=rhs.dtype)
            buckets = fiber_buckets(state.fibers)
            if buckets:
                off = 0
                stepped = []
                for g in buckets:
                    size = fc.solution_size(g)
                    sol_fib = result.x[off:off + size].reshape(g.n_fibers,
                                                               -1)
                    stepped.append(fc.step(g, sol_fib))
                    off += size
                new_state = new_state._replace(
                    fibers=_rewrap_fibers(state.fibers, stepped))
            if state.shell is not None:
                new_state = new_state._replace(shell=state.shell._replace(
                    density=result.x[fib_size:fib_size + shell_size]))
            b_list = body_buckets(state.bodies)
            if b_list:
                off_b = fib_size + shell_size
                new_b = []
                for g in b_list:
                    size = g.solution_size
                    sol_bod = result.x[off_b:off_b + size].reshape(
                        g.n_bodies, -1)
                    new_b.append(bd.step(g, sol_bod, state.dt))
                    off_b += size
                new_state = new_state._replace(
                    bodies=_rewrap_bodies(state.bodies, new_b))
                if buckets:
                    # fibers re-pin to their (moved) nucleation sites
                    # (`system.cpp:488`, `repin_to_bodies`); applied per
                    # body bucket with global->local binding remaps — a
                    # fiber is bound to at most one bucket, so the moves
                    # compose
                    nbt = bd.n_total(new_b)
                    repinned = list(fiber_buckets(new_state.fibers))
                    for gb in new_b:
                        _, _, new_sites = bd.place(gb)
                        repinned = [
                            g._replace(x=bd.repin_to_bodies(
                                bd.local_binding(g, gb, nbt), new_sites,
                                gb).x)
                            for g in repinned]
                    new_state = new_state._replace(
                        fibers=_rewrap_fibers(new_state.fibers, repinned))
            if buckets:
                fiber_error = jnp.max(jnp.stack(
                    [fc.fiber_error(g)
                     for g in fiber_buckets(new_state.fibers)]))

        # the packed health word (guard.verdict): the solver's own bits,
        # plus a nonfinite check on the post-advance fiber error — a
        # poisoned state (injected NaN, overflow blow-up) shows up here
        # even when the solver's residual arithmetic short-circuited
        health = (jnp.asarray(result.health, dtype=jnp.int32)
                  | _verdict.nonfinite_word(fiber_error))
        info = StepInfo(converged=result.converged, iters=result.iters,
                        residual=result.residual, fiber_error=fiber_error,
                        residual_true=result.residual_true,
                        loss_of_accuracy=(result.converged
                                          & (result.residual_true
                                             > 10.0 * p.gmres_tol)),
                        refines=result.refines, cycles=result.cycles,
                        history=result.history, health=health,
                        dt_used=state.dt, guard_retries=jnp.int32(0))
        return new_state, result.x, info

    # -------------------------------------------------------- velocity field

    def _velocity_at_targets_impl(self, state: SimState, solution, r_trg,
                                  pair=None, pair_anchors=None):
        """Velocity field at arbitrary targets from a solved state
        (`velocity_at_targets`, `system.cpp:330-384`).

        Sums fiber flow (forces from the solution, plus steric wall forces when
        `periphery_interaction_flag` is set), body flow driven by fiber link
        conditions, shell flow from the solved density, and point/background
        sources; points inside a rigid body are overridden with the body's
        rigid motion v + omega x dx.
        """
        p = self.params
        buckets = fiber_buckets(state.fibers)
        shell, bodies = state.shell, state.bodies
        fib_size, shell_size, body_size = self._sizes(state)
        r_trg = jnp.asarray(r_trg, dtype=solution.dtype).reshape(-1, 3)
        v = jnp.zeros_like(r_trg)

        caches = [fc.update_cache(g, state.dt, p.eta) for g in buckets]
        b_list = body_buckets(bodies)
        body_caches = [bd.update_cache(g, p.eta) for g in b_list]

        x_fibs = []
        if buckets:
            off = 0
            for g in buckets:
                size = fc.solution_size(g)
                x_fibs.append(solution[off:off + size].reshape(g.n_fibers,
                                                               4 * g.n_nodes))
                off += size
            f_on_fibers = [fc.apply_fiber_force(g, c, xf)
                           for g, c, xf in zip(buckets, caches, x_fibs)]
            if p.periphery_interaction_flag and shell is not None:
                steric = self._periphery_force_fibers(state)
                f_on_fibers = [f + s for f, s in zip(f_on_fibers, steric)]
            # through the pair-evaluator seam so listener-mode evaluator
            # switches genuinely change the computation: ewald engages when
            # the caller supplies a plan — velocity_at_targets plans over
            # nodes + probes, and the listener's streamline integrators pass
            # per-request extended-box plans (`listener.process_request`)
            v = v + self._fiber_flow(state, caches, r_trg, f_on_fibers,
                                     subtract_self=False,
                                     pair=pair,
                                     pair_anchors=pair_anchors)

        x_bods = []
        if b_list:
            nbt = bd.n_total(b_list)
            off_b = fib_size + shell_size
            for g in b_list:
                size = g.solution_size
                x_bods.append(solution[off_b:off_b + size].reshape(
                    g.n_bodies, -1))
                off_b += size
            # like the reference, only the fiber link forces (not the
            # external force schedule) drive the body flow here
            for gb, bc, xb in zip(b_list, body_caches, x_bods):
                body_ft = jnp.zeros((gb.n_bodies, 6), dtype=solution.dtype)
                for g, c, xf in zip(buckets, caches, x_fibs):
                    _, ft = bd.link_conditions(
                        gb, bc, bd.local_binding(g, gb, nbt), c, xf, xb)
                    body_ft = body_ft + ft
                b_plan, b_anchors = self._body_pair_args(gb, pair,
                                                          pair_anchors)
                v = v + bd.flow(gb, bc, r_trg, xb, body_ft, p.eta,
                                impl=p.kernel_impl, pair=b_plan,
                                pair_anchors=b_anchors)

        if shell is not None:
            v = v + self._shell_flow(state, r_trg,
                                     solution[fib_size:fib_size + shell_size],
                                     pair=pair,
                                     pair_anchors=pair_anchors)

        v = v + self._external_flows(state, r_trg)

        if b_list:
            # rigid-motion override inside bodies (`system.cpp:364-381`):
            # spheres by radius, ellipsoids by the body-frame ellipsoid
            # equation (`system.cpp:371-380` handles both kinds). The
            # per-body columns concatenate across buckets.
            from ..utils import quaternion as quat

            vel6 = jnp.concatenate([xb[:, -6:] for xb in x_bods], axis=0)
            position = jnp.concatenate([g.position for g in b_list], axis=0)
            radius = jnp.concatenate([g.radius for g in b_list], axis=0)
            kind_sphere = jnp.concatenate([g.kind_sphere for g in b_list])
            orientation = jnp.concatenate([g.orientation for g in b_list],
                                          axis=0)
            semiaxes = jnp.concatenate([g.semiaxes for g in b_list], axis=0)

            dx = r_trg[:, None, :] - position[None, :, :]
            in_sphere = ((jnp.linalg.norm(dx, axis=-1) < radius[None, :])
                         & kind_sphere[None, :])
            rot = quat.rotation_matrix(orientation)          # [nb, 3, 3]
            dx_body = jnp.einsum("bji,tbj->tbi", rot, dx)    # R^T dx
            has_ax = jnp.all(semiaxes > 0.0, axis=-1)        # [nb]
            ax_safe = jnp.where(semiaxes > 0.0, semiaxes, 1.0)
            in_ellipsoid = (jnp.sum((dx_body / ax_safe[None]) ** 2, axis=-1)
                            < 1.0) & has_ax[None, :] & ~kind_sphere[None, :]
            inside = in_sphere | in_ellipsoid
            u_rigid = vel6[None, :, :3] + jnp.cross(
                jnp.broadcast_to(vel6[None, :, 3:], dx.shape), dx)
            idx = jnp.argmax(inside, axis=1)
            v = jnp.where(inside.any(axis=1)[:, None],
                          u_rigid[jnp.arange(r_trg.shape[0],
                                             dtype=jnp.int32), idx], v)
        return v

    def velocity_at_targets(self, state: SimState, solution, r_trg):
        """Jitted velocity field evaluation at [n, 3] targets; a configured
        fast evaluator (ewald/tree) plans over nodes + targets so off-node
        probes stay inside the cell/box region."""
        pair, anchors = self._pair_args(state, extra_targets=r_trg)
        return self._vel_jit(state, solution, r_trg, pair=pair,
                             pair_anchors=anchors)

    def _check_collision(self, state: SimState):
        """Fiber/shell + body collision gate (`check_collision`, `system.cpp:576-595`).

        Scoped ``collision`` for device-time attribution
        (obs/profile.py PHASE_SCOPES — metadata only)."""
        with jax.named_scope("collision"):
            return self._check_collision_impl(state)

    def _check_collision_impl(self, state: SimState):
        collided = jnp.asarray(False)
        if state.bodies is not None:
            collided = collided | bd.check_collision_pairwise_multi(
                state.bodies, 0.0)
            if state.shell is not None and self.shell_shape.kind == "sphere":
                collided = collided | bd.check_collision_shell_multi(
                    state.bodies, self.shell_shape.radius, 0.0)
        buckets = fiber_buckets(state.fibers)
        if state.shell is None or not buckets:
            return collided
        shape = self.shell_shape

        def make_one(g):
            rt = g.rt_mats

            def one(x, mc):
                # excluded rows (a clamped fiber's anchored first node, and
                # any masked padding rows, which replicate node 0 and would
                # inherit its wall contact) are replaced by the last LIVE
                # node — interior by construction
                safe = x[-1] if rt is None else jnp.tensordot(
                    rt.e_last.astype(x.dtype), x, axes=1)
                keep = (jnp.arange(x.shape[0], dtype=jnp.int32)
                        >= jnp.where(mc, 1, 0))
                if rt is not None:
                    keep = keep & rt.node_mask
                pts = jnp.where(keep[:, None], x, safe)
                return peri.check_collision(shape, pts, 0.0)

            return one

        for g in buckets:
            collided = collided | jnp.any(
                jax.vmap(make_one(g))(g.x, g.minus_clamped))
        return collided

    # -------------------------------------------------------------- public API

    def _plan_points(self, state: SimState, extra_targets=None):
        """(points, n_fill, n_src) over every ACTIVE hydrodynamic node —
        the shared host-side input of both fast-summation planners.
        Inactive fiber slots (dynamic-instability padding, which replicate
        slot 0's coordinates) are excluded from the bounding box and
        reserved as spread `n_fill` capacity instead — clustered padding
        would otherwise blow up the per-cell/leaf bucket size.
        ``extra_targets`` extends the box to off-node evaluation points
        (velocity fields)."""
        import numpy as _np

        n_fill = 0
        n_src = 0
        parts = []
        for g in fiber_buckets(state.fibers):
            # per-NODE activity: inactive fiber slots and masked padding
            # node rows (skelly-bucket) are both reserved as spread fill
            # capacity — their placeholder coordinates replicate live nodes
            # and would otherwise overflow a cell/leaf bucket
            act = (_np.asarray(g.active)[:, None]
                   & fc.node_mask_np(g)[None, :])
            x = _np.asarray(g.x)
            parts.append(x[act])
            n_fill += int((~act).sum())
            n_src += parts[-1].shape[0]
        if state.shell is not None:
            nodes = _np.asarray(state.shell.nodes)
            if state.shell.node_mask is not None:
                # padded quadrature rows replicate node 0; plan over the
                # live rows (bucketize refuses padded shells under the fast
                # evaluators, so this is belt-and-braces for plain plans)
                nodes = nodes[_np.asarray(state.shell.node_mask)]
            parts.append(nodes)
        for g in body_buckets(state.bodies):
            parts.append(_np.asarray(bd.place(g)[0]).reshape(-1, 3))
        if extra_targets is not None:
            parts.append(_np.asarray(extra_targets).reshape(-1, 3))
        return _np.concatenate(parts, axis=0), n_fill, n_src

    def make_ewald_plan(self, state: SimState, extra_targets=None):
        """Host-side Ewald plan over the `_plan_points` cloud — the
        analogue of the reference's per-step FMM tree rebuild
        (`kernels.hpp:78-122`). Quantized planning (`ops.ewald.plan_ewald`)
        keeps the plan — and so the compiled solve — stable while the
        geometry drifts."""
        from ..ops.ewald import plan_ewald

        pts, n_fill, n_src = self._plan_points(state, extra_targets)
        return plan_ewald(pts, eta=self.params.eta,
                          tol=self.params.ewald_tol, n_fill=n_fill,
                          n_src=n_src)

    def make_tree_plan(self, state: SimState, extra_targets=None):
        """Host-side treecode plan over the `_plan_points` cloud
        (`ops.treecode.plan_tree`) — same quantized-planning discipline as
        `make_ewald_plan`, choosing octree depth/order from the active node
        count and `params.tree_tol`."""
        from ..ops.treecode import plan_tree

        pts, n_fill, _ = self._plan_points(state, extra_targets)
        return plan_tree(pts, tol=self.params.tree_tol, n_fill=n_fill)

    def make_spectral_plan(self, state: SimState, extra_targets=None):
        """Host-side spectral Ewald plan over the `_plan_points` cloud
        (`ops.spectral.plan_spectral`) for `params.periodic_box` — the
        periodic analogue of `make_ewald_plan`. Grid dims snap onto the
        `grid_ladder` rungs (skelly-bucket's `[runtime] grid_ladder`, or
        the built-in 2^a 3^b ladder), so the plan — the jit key — is
        stable under drift: in a triply-periodic box it depends only on
        the box, tolerances, and occupancy rungs; in a slab only the
        ladder-quantized z extent can move it."""
        from ..ops.spectral import plan_spectral

        pts, n_fill, _ = self._plan_points(state, extra_targets)
        return plan_spectral(pts, self.params.periodic_box,
                             eta=self.params.eta,
                             tol=self.params.spectral_tol, n_fill=n_fill,
                             grid_ladder=self.grid_ladder)

    def _pair_args(self, state: SimState, extra_targets=None):
        """(`PairEvaluator` spec, traced anchors) for the configured fast
        evaluator, or (None, None) for the dense/ring paths. The ONE place
        evaluator selection + plan construction happens per solve — the
        spec then rides every flow call site unchanged (satellite of the
        treecode PR: adding a fourth evaluator must not grow every
        signature again)."""
        ev = self.params.pair_evaluator
        if ev not in ("ewald", "tree", "spectral"):
            return None, None
        from ..ops.evaluator import make_pair

        maker = {"ewald": self.make_ewald_plan, "tree": self.make_tree_plan,
                 "spectral": self.make_spectral_plan}[ev]
        plan = maker(state, extra_targets=extra_targets)
        return make_pair(ev, self.params.kernel_impl, plan)

    def step(self, state: SimState):
        """One trial step at state.dt: solve + advance components (`step`,
        `system.cpp:482-492`). Returns (new_state, solution, info)."""
        state = self.ensure_flight(state)
        pair, anchors = self._pair_args(state)
        return self._solve_jit(state, pair=pair, pair_anchors=anchors)

    def _step_donating(self, state: SimState):
        """`step` through the donating jit — the caller's ``state`` buffers
        are CONSUMED on backends with donation support (see __init__)."""
        state = self.ensure_flight(state)
        pair, anchors = self._pair_args(state)
        return self._solve_jit_donated(state, pair=pair,
                                       pair_anchors=anchors)

    def step_spmd(self, state: SimState, mesh, *,
                  allow_replicated_shell: bool = False,
                  flat_solution: bool = True, donate: str | bool = "auto"):
        """One explicitly-sharded implicit step on ``mesh`` — the whole
        prep/GMRES/advance pipeline as ONE `shard_map` program with manual
        collectives (`parallel.spmd`: psum'd dot products, ring ppermutes
        for the pairwise flows, one density all-gather per shell operator
        application) instead of GSPMD-chosen ones. The built program is
        cached per (mesh, state structure); returns (new_state, solution,
        info) with ``new_state`` still sharded.

        ``donate="auto"`` donates ``state``'s buffers on accelerator
        backends — do not reuse the argument afterwards there.

        ``pair_evaluator="tree"`` composes with this path: the Krylov
        matvec's fiber flows route through the treecode on every shard
        (`fibers.container.flow_multi_local`'s tree branch), re-planned
        host-side per call like `step`. Requires every fiber slot active —
        the SPMD layout has no global inactive-slot spread
        (`fc._spread_inactive` needs the full concatenated active mask),
        so states with inactive padding fall back to the ring flows."""
        import numpy as np

        from ..parallel.spmd import build_spmd_step

        # guard_* inertness on this path is diagnosed by build_spmd_step
        # itself (once per BUILD, not per step_spmd call): the mesh program
        # threads the health WORD but not the escalation ladder — see the
        # analyzer-backed follow-up note there and in docs/robustness.md
        state = self.ensure_flight(state)
        buckets = fiber_buckets(state.fibers)
        pair = anchors = None
        if self.params.pair_evaluator == "tree" and all(
                bool(np.all(np.asarray(g.active))) for g in buckets):
            pair, anchors = self._pair_args(state)
        key = (mesh, allow_replicated_shell, flat_solution, donate,
               jax.tree_util.tree_structure(state), state.time.dtype,
               tuple(g.n_fibers for g in buckets),
               state.shell.n_nodes if state.shell is not None else 0,
               pair)
        fn = self._spmd_steps.get(key)
        if fn is None:
            from ..obs.compile_log import jit_wrapper

            fn = build_spmd_step(
                self, mesh, state,
                allow_replicated_shell=allow_replicated_shell,
                flat_solution=flat_solution, donate=donate, pair=pair,
                jit_wrapper=jit_wrapper(f"step_spmd_d{mesh.size}"))
            self._spmd_steps[key] = fn
        return fn(state, anchors) if pair is not None else fn(state)

    def trial_step(self, state: SimState, pair=None, pair_anchors=None):
        """The pure, un-jitted trial step: (new_state, solution, info) with a
        per-member `StepInfo`. This is the batch-steppable seam the ensemble
        subsystem (`skellysim_tpu.ensemble`) maps over a stacked member axis
        — `jax.vmap(system.trial_step)` batches the whole prep/GMRES/advance
        pipeline, because GMRES already keeps its control flow in `lax`
        primitives (solver/gmres.py "batching" note). Host-REBUILT plans
        (ewald/tree) cannot live inside a closed batched trace, so the
        ensemble runner rejects those evaluators up front; the spectral
        plan is bucket-quantized data that never rebuilds under drift, so
        the runner builds the ``pair`` spec once and threads it (with its
        traced ``pair_anchors``) through every batched call."""
        return self._solve_impl(state, pair=pair, pair_anchors=pair_anchors)

    def collision(self, state: SimState):
        """Pure collision gate (traced bool) — the adaptive loop's reject
        trigger, exposed un-jitted so the ensemble runner can evaluate it
        inside the batched step."""
        return self._check_collision(state)

    def run(self, state: SimState, *, writer=None, max_steps: int | None = None,
            rng=None, metrics_path: str | None = None,
            profile_dir: str | None = None, trace_path: str | None = None):
        """Adaptive time loop (`run`, `system.cpp:516-571`).

        Host-side control flow around the jit'd step: accept/reject on fiber
        error + collision, scale dt by beta_up/beta_down, keep the previous
        pytree as the backup for rejected steps. ``writer`` is called with
        (state, solution) after each accepted step crossing a dt_write boundary
        (plus ``rng_state=`` when ``rng`` is given). Passing a `SimRNG` enables
        dynamic instability when `params.dynamic_instability.n_nodes > 0`
        (`prep_state_for_solver`, `system.cpp:403`); like the reference, a
        rejected step does not rewind the RNG.

        Each trial step is logged (the reference's per-step spdlog lines,
        `system.cpp:474,567`); ``metrics_path`` additionally appends one JSON
        line per step (key set == `METRICS_FIELDS`) — the structured-metrics
        upgrade SURVEY.md §5.1 calls for. ``trace_path`` opens a skelly-scope
        telemetry stream for the loop (span events per trial step, compile
        events from every jit entry point — docs/observability.md; render
        with `python -m skellysim_tpu.obs summarize`). An externally
        installed tracer (`obs.tracer.use`) is honored when ``trace_path``
        is None, so callers can aggregate several runs into one stream.
        """
        import contextlib

        metrics_fh = open(metrics_path, "a") if metrics_path else None
        # XLA/TPU profiler capture of the whole loop (the structured upgrade
        # over the reference's omp_get_wtime logging, SURVEY.md §5.1); open
        # with TensorBoard/xprof, `obs profile DIR`, or `obs timeline`.
        # obs.profile.profile_session keeps the Python tracer OFF so the
        # device op events survive the trace buffer (span telemetry covers
        # the host side)
        if profile_dir is not None:
            from ..obs.profile import profile_session

            prof = profile_session(profile_dir)
        else:
            prof = contextlib.nullcontext()
        tracer = obs_tracer.Tracer(trace_path) if trace_path else None
        scope = (obs_tracer.use(tracer) if tracer is not None
                 else contextlib.nullcontext())
        try:
            with scope:
                with prof:
                    with obs_tracer.span("run", t_final=self.params.t_final):
                        state = self._run_loop(state, writer=writer,
                                               max_steps=max_steps, rng=rng,
                                               metrics_fh=metrics_fh)
                if profile_dir is not None:
                    # fold the dump into the SAME telemetry stream: one
                    # `device_phase` event per attributed phase, so `obs
                    # summarize` prints device time next to the host spans
                    # and the profile dir stops being write-only dead
                    # weight (docs/observability.md)
                    from ..obs.profile import emit_device_phases

                    emit_device_phases(profile_dir, tracer)
        finally:
            if tracer is not None:
                tracer.close()
            if metrics_fh is not None:
                metrics_fh.close()
        return state

    def _run_loop(self, state: SimState, *, writer, max_steps, rng, metrics_fh):
        from .dynamic_instability import (_count_active as _di_count_active,
                                          apply_dynamic_instability)

        from ..obs import flight as flight_mod

        p = self.params
        state = self.ensure_flight(state)
        n_steps = 0
        # with the adaptive gate off no step is ever rejected, so the
        # pre-step pytree is never rolled back to — donate it through the
        # jit (the ~GB-class caches/operators alias in place instead of
        # double-buffering per step). Adaptive runs keep the non-donating
        # jit: `backup` must stay alive for rejects. CPU XLA has no
        # donation support, so skip there (jit warns on every call).
        donate_ok = (not p.adaptive_timestep_flag
                     and jax.default_backend() != "cpu")
        step_fn = self._step_donating if donate_ok else self.step
        while float(state.time) < p.t_final:
            if max_steps is not None and n_steps >= max_steps:
                break
            backup = state
            di_stats = None
            if rng is not None and p.dynamic_instability.n_nodes > 0:
                # a ring mesh constrains nucleation's capacity growth to
                # mesh-divisible node counts (grow_capacity invariant)
                nm = self.mesh.size if self._ring_active() else 1
                di_stats = {}
                with obs_tracer.span("dynamic_instability"):
                    state = apply_dynamic_instability(state, p, rng,
                                                      node_multiple=nm,
                                                      stats=di_stats)
            # snapshot the time scalars BEFORE the step: with donation on,
            # the step consumes the input state's buffers
            t_cur = float(state.time)
            dt = float(state.dt)
            with obs_tracer.span("step", step=n_steps) as sp:
                wall0 = _time.perf_counter()
                new_state, solution, info = step_fn(state)
                # host fetch, not block_until_ready: blocking on one leaf
                # was observed returning before the program finished,
                # undermeasuring wall_s by >100x — the fetch doubles as the
                # span's device-work sync
                residual = float(info.residual)
                wall_s = _time.perf_counter() - wall0
                sp.note(iters=int(info.iters), residual=residual)
            n_steps += 1
            converged = bool(info.converged)
            fiber_error = float(info.fiber_error)
            health = int(info.health)
            # skelly-flight: the trial's decoded diagnostics row (one small
            # device fetch), consumed by the metrics JSONL, the telemetry
            # stream (timeline counter tracks), and fault provenance below
            flight_row = None
            if new_state.flight is not None and (
                    metrics_fh is not None or health
                    or obs_tracer.active() is not None):
                flight_row = flight_mod.last_row(new_state.flight.rows,
                                                 new_state.flight.count)
                if flight_row is not None:
                    obs_tracer.emit("flight", step=n_steps - 1,
                                    **flight_row)
            # the guard ladder may have retried this trial at a halved dt
            # (Params.guard_dt_halvings): the dt that actually advanced the
            # state is info.dt_used — identical to `dt` when the ladder is
            # off or never fired, so the pre-guard arithmetic is unchanged
            dt = float(info.dt_used)

            dt_new = dt
            accept = True
            if p.adaptive_timestep_flag:
                if converged and fiber_error <= p.fiber_error_tol:
                    accept = True
                    if fiber_error <= 0.9 * p.fiber_error_tol:
                        dt_new = min(p.dt_max, dt * p.beta_up)
                else:
                    dt_new = dt * p.beta_down
                    accept = False

                if converged and bool(self._collision_jit(new_state)):
                    dt_new = dt * 0.5
                    accept = False

                if dt_new < p.dt_min:
                    raise RuntimeError("Timestep smaller than dt_min")

            logger.info(
                "step t=%.6g dt=%.4g iters=%d residual=%.3e (true %.3e) "
                "fiber_error=%.3e %s (%.3fs)", t_cur, dt,
                int(info.iters), residual,
                float(info.residual_true), fiber_error,
                "accepted" if accept else "rejected", wall_s)
            if not converged and accept:
                # without adaptive timestepping a non-converged solve is
                # still accepted (the reference's loop likewise only rejects
                # under the adaptive gate) — but never silently: the
                # round-5 x64 CLI bug surfaced as exactly this, a 1e-10
                # request quietly flooring at f32 noise
                logger.warning(
                    "GMRES did not converge: residual %.3e (true %.3e) vs "
                    "tol %.1e; step accepted (adaptive timestep off)",
                    residual, float(info.residual_true), p.gmres_tol)
            if bool(info.loss_of_accuracy):
                # `solver_hydro.cpp:85-92`: implicit convergence with a
                # drifted explicit residual means the answer is worse than
                # the solver claims
                logger.warning(
                    "GMRES loss of accuracy: implicit residual %.3e converged "
                    "but explicit ||b-Ax||/||b|| = %.3e (> 10x tol %.1e)",
                    residual, float(info.residual_true),
                    p.gmres_tol)
            if health:
                # the device-side verdict, surfaced host-side exactly once
                # per trial: a structured `fault` telemetry event (the obs
                # summarize fault table) plus the log line the reference
                # would have aborted with
                verdict_s = _verdict.describe(health)
                # flight provenance rides the fault event when the recorder
                # localized the offender (obs.flight — "who and where"
                # next to guard's "something died")
                prov = (flight_row or {}).get("provenance") or {}
                prov_fields = ({"prov_field": prov.get("field"),
                                "prov_fiber": prov.get("fiber"),
                                "prov_node": prov.get("node")}
                               if prov else {})
                obs_tracer.emit("fault", kind="solver_health",
                                verdict=verdict_s, health=health,
                                t=t_cur, dt=dt,
                                retries=int(info.guard_retries),
                                **prov_fields)
                logger.warning(
                    "solver health verdict at t=%.6g: %s (health=%#x, "
                    "guard retries=%d)", t_cur, verdict_s, health,
                    int(info.guard_retries))
            if metrics_fh is not None:
                # key set == METRICS_FIELDS (schema-pinned; docs/performance.md)
                metrics_fh.write(json.dumps({
                    "step": n_steps - 1,
                    "t": t_cur, "dt": dt, "iters": int(info.iters),
                    "gmres_cycles": int(info.cycles),
                    # dot-product psum rounds this solve paid through the
                    # rdot seam (the s-step lever; `gmres.collective_rounds`
                    # — restart= floors boundaries at ceil(iters/restart)
                    # so mixed-precision inner restarts still register)
                    "collective_rounds": collective_rounds(
                        info.iters, info.cycles, p.gmres_block_s,
                        restart=p.gmres_restart),
                    "residual": residual,
                    "residual_true": float(info.residual_true),
                    "fiber_error": fiber_error, "accepted": accept,
                    "refines": int(info.refines),
                    "loss_of_accuracy": bool(info.loss_of_accuracy),
                    "health": health,
                    "guard_retries": int(info.guard_retries),
                    # dynamic-instability trajectory (docs/scenarios.md):
                    # events applied this trial (a rejected trial discards
                    # its DI update, so it reports 0/0, matching the
                    # ensemble records) and the live count that persists
                    "nucleations": (di_stats["nucleations"]
                                    if accept and di_stats else 0),
                    "catastrophes": (di_stats["catastrophes"]
                                     if accept and di_stats else 0),
                    "active_fibers": (_di_count_active(
                        (new_state if accept else backup).fibers)
                        if di_stats is not None else 0),
                    "wall_s": round(wall_s, 4),
                    "wall_ms": round(wall_s * 1e3, 3),
                    "gmres_history": history_rows(info.history,
                                                  info.cycles),
                    # the flight recorder's decoded row for THIS trial
                    # (None at flight_window=0; docs/observability.md)
                    "flight": flight_row}) + "\n")
                metrics_fh.flush()

            if accept:
                t_new = t_cur + dt
                state = new_state._replace(
                    time=jnp.asarray(t_new, dtype=state.time.dtype),
                    dt=jnp.asarray(dt_new, dtype=state.dt.dtype))
                if writer is not None and crossed_write_boundary(
                        t_new, dt, p.dt_write):
                    with obs_tracer.span("write_frame", t=t_new):
                        if rng is not None:
                            writer(state, solution,
                                   rng_state=rng.dump_state())
                        else:
                            writer(state, solution)
            else:
                # a rejected trial rolls back the physics but KEEPS the
                # flight ring: the recorder's whole point is the trajectory
                # into trouble, and the rejected attempt's row is evidence
                state = backup._replace(
                    dt=jnp.asarray(dt_new, dtype=state.dt.dtype),
                    flight=new_state.flight)
        return state


# ---------------------------------------------------------------- skelly-audit

def auditable_programs():
    """This layer's entries in the audit matrix (docs/audit.md): the
    single-chip implicit step (plain + donating twin — the donation check
    pins what `tests/test_spmd.py` used to regex out of the HLO) and the
    mixed-precision step whose deliberate f32->f64 refinement merges the
    dtype-flow contract pins."""
    from ..audit import fixtures
    from ..audit.registry import AuditProgram, built_from

    def build(donated=False, **overrides):
        def _build():
            system = fixtures.make_system(**overrides)
            state = fixtures.free_state(system)
            fn = (system._solve_jit_donated if donated
                  else system._solve_jit)
            return built_from(fn, state, pair=None, pair_anchors=None)
        return _build

    def retrace_probe(**overrides):
        def _probe():
            from ..testing import trace_counting_jit

            system = fixtures.make_system(**overrides)
            step = trace_counting_jit(system._solve_impl,
                                      static_argnames=("pair",))
            new_state, _, _ = step(fixtures.free_state(system))
            step(new_state)  # same structure, new values: must not retrace
            return step.trace_count
        return _probe

    return [
        AuditProgram(
            name="step_single", layer="system",
            summary="single-chip implicit step (free fibers, f64, "
                    "non-donating jit)",
            build=build(), retrace_probe=retrace_probe()),
        AuditProgram(
            name="step_single_donated", layer="system",
            summary="single-chip implicit step through the donating jit "
                    "(run-loop twin; must alias its inputs)",
            build=build(donated=True)),
        AuditProgram(
            name="step_mixed", layer="system",
            summary="mixed-precision step (f32 Krylov + f64 df refinement)",
            build=build(solver_precision="mixed", refine_pair_impl="df")),
        AuditProgram(
            # skelly-flight: the ARMED (K=32) twin of the step is its own
            # contracted program, so the recorder's overhead (op counts,
            # bytes, retraces — and that it stays collective- and
            # callback-free) is contract-pinned, not folklore; the K=0
            # default program stays byte-identical to pre-flight and rides
            # the step_single contract unchanged
            name="step_flight", layer="system",
            summary="single-chip implicit step with the K=32 flight "
                    "recorder armed (skelly-flight diagnostics ring)",
            build=build(flight_window=32),
            retrace_probe=retrace_probe(flight_window=32)),
    ]
