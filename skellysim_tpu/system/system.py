"""System orchestrator: state pytree, coupled matvec, solve, adaptive time loop.

TPU-native replacement for the reference `System` namespace
(`/root/reference/src/core/system.cpp`): instead of namespace-level singletons
mutated in place, the whole simulation is one immutable `SimState` pytree and the
per-step work (`prep_state_for_solver` -> GMRES -> component steps) is a jit'd
pure function. Backup/restore for rejected adaptive steps
(`system.cpp:495-513`) is free: keep the previous pytree.

The solution vector layout matches the reference (`system.cpp:75-96`):
[fibers (4n per fiber) | shell (3 per node) | bodies (3 per node + 6 per body)].
Periphery and bodies plug into `_apply_matvec`/`_apply_precond`/`_prep` in the
same seams as `system.cpp:269-324`.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, NamedTuple, Optional

import jax
import jax.numpy as jnp
import numpy as np

from ..fibers import container as fc
from ..params import Params
from ..solver import gmres
from .sources import BackgroundFlow, PointSources


class SimState(NamedTuple):
    """Complete simulation state (a pytree)."""

    time: jnp.ndarray
    dt: jnp.ndarray
    fibers: Optional[fc.FiberGroup]
    points: Optional[PointSources]
    background: Optional[BackgroundFlow]
    shell: Any = None    # periphery.PeripheryState once present
    bodies: Any = None   # bodies.BodyState once present


class StepInfo(NamedTuple):
    converged: jnp.ndarray
    iters: jnp.ndarray
    residual: jnp.ndarray
    fiber_error: jnp.ndarray


class System:
    """Holds static config; all dynamics flow through pure jit'd functions."""

    def __init__(self, params: Params):
        self.params = params
        self._solve_jit = jax.jit(self._solve_impl)
        self._fiber_error_jit = jax.jit(self._fiber_error)

    # ------------------------------------------------------------- state setup

    def make_state(self, fibers=None, points=None, background=None,
                   shell=None, bodies=None) -> SimState:
        dtype = fibers.x.dtype if fibers is not None else jnp.float64
        return SimState(
            time=jnp.asarray(0.0, dtype=dtype),
            dt=jnp.asarray(self.params.dt_initial, dtype=dtype),
            fibers=fibers, points=points, background=background,
            shell=shell, bodies=bodies)

    # ----------------------------------------------------------------- helpers

    def _fiber_node_positions(self, state: SimState):
        if state.fibers is None:
            return jnp.zeros((0, 3), dtype=jnp.float64)
        return fc.node_positions(state.fibers)

    def _external_flows(self, state: SimState, r_trg):
        """Point-source + background contributions (`system.cpp:445-446`)."""
        v = jnp.zeros_like(r_trg)
        if state.points is not None:
            v = v + state.points.flow(r_trg, self.params.eta, state.time)
        if state.background is not None:
            v = v + state.background.flow(r_trg, self.params.eta)
        return v

    # ------------------------------------------------------------------- prep

    def _prep(self, state: SimState):
        """All velocities/forces/RHS/BC assembly (`prep_state_for_solver`,
        `system.cpp:398-458`). Returns per-component caches."""
        p = self.params
        fibers = state.fibers
        caches = None
        if fibers is not None:
            caches = fc.update_cache(fibers, state.dt, p.eta)

            r_all = self._fiber_node_positions(state)

            nf, n = fibers.n_fibers, fibers.n_nodes
            zero_f = jnp.zeros((nf, n, 3), dtype=fibers.x.dtype)

            # motor force activates after the configured delay (`system.cpp:417-419`)
            motor = jnp.where(state.time >= p.implicit_motor_activation_delay,
                              fc.generate_constant_force(fibers, caches), zero_f)
            external = zero_f  # fiber-periphery steric force once shell exists

            v_all = fc.flow(fibers, caches, r_all, external, p.eta)
            v_all = v_all + self._external_flows(state, r_all)
            v_fib = v_all.reshape(nf, n, 3)

            caches = fc.update_rhs_and_bc(fibers, caches, state.dt, p.eta,
                                          v_fib, motor + external, external)
        return caches

    # ------------------------------------------------------- operator closures

    def _apply_matvec(self, state: SimState, caches, x_flat):
        """Coupled operator A x (`apply_matvec`, `system.cpp:269-324`)."""
        p = self.params
        fibers = state.fibers
        nf, n = fibers.n_fibers, fibers.n_nodes
        x_fib = x_flat[:nf * 4 * n].reshape(nf, 4 * n)

        r_all = self._fiber_node_positions(state)
        fw = fc.apply_fiber_force(fibers, caches, x_fib)
        v_all = fc.flow(fibers, caches, r_all, fw, p.eta, subtract_self=True)
        v_fib = v_all[:nf * n].reshape(nf, n, 3)

        v_boundary = jnp.zeros((nf, 7), dtype=x_flat.dtype)  # body links later
        res_fib = fc.matvec(fibers, caches, x_fib, v_fib, v_boundary)
        return res_fib.reshape(-1)

    def _apply_precond(self, state: SimState, caches, x_flat):
        """Block preconditioner P^-1 x (`apply_preconditioner`, `system.cpp:248-262`)."""
        fibers = state.fibers
        nf, n = fibers.n_fibers, fibers.n_nodes
        x_fib = x_flat[:nf * 4 * n].reshape(nf, 4 * n)
        y = fc.apply_preconditioner(fibers, caches, x_fib)
        return y.reshape(-1)

    # ------------------------------------------------------------------- solve

    def _solve_impl(self, state: SimState):
        p = self.params
        caches = self._prep(state)
        rhs = caches.RHS.reshape(-1)
        result = gmres(
            lambda v: self._apply_matvec(state, caches, v), rhs,
            precond=lambda v: self._apply_precond(state, caches, v),
            tol=p.gmres_tol, restart=p.gmres_restart, maxiter=p.gmres_maxiter)

        fibers = state.fibers
        nf, n = fibers.n_fibers, fibers.n_nodes
        sol_fib = result.x[:nf * 4 * n].reshape(nf, 4 * n)
        new_fibers = fc.step(fibers, sol_fib)
        new_state = state._replace(fibers=new_fibers)
        info = StepInfo(converged=result.converged, iters=result.iters,
                        residual=result.residual,
                        fiber_error=fc.fiber_error(new_fibers))
        return new_state, result.x, info

    def _fiber_error(self, state: SimState):
        return fc.fiber_error(state.fibers)

    # -------------------------------------------------------------- public API

    def step(self, state: SimState):
        """One trial step at state.dt: solve + advance components (`step`,
        `system.cpp:482-492`). Returns (new_state, solution, info)."""
        return self._solve_jit(state)

    def run(self, state: SimState, *, writer=None, max_steps: int | None = None):
        """Adaptive time loop (`run`, `system.cpp:516-571`).

        Host-side control flow around the jit'd step: accept/reject on fiber
        error, scale dt by beta_up/beta_down, keep the previous pytree as the
        backup for rejected steps. ``writer`` is called with (state, solution)
        after each accepted step that crosses a dt_write boundary.
        """
        p = self.params
        n_steps = 0
        while float(state.time) < p.t_final:
            if max_steps is not None and n_steps >= max_steps:
                break
            backup = state
            new_state, solution, info = self.step(state)
            n_steps += 1
            converged = bool(info.converged)
            fiber_error = float(info.fiber_error)

            dt = float(state.dt)
            dt_new = dt
            accept = True
            if p.adaptive_timestep_flag:
                if converged and fiber_error <= p.fiber_error_tol:
                    accept = True
                    if fiber_error <= 0.9 * p.fiber_error_tol:
                        dt_new = min(p.dt_max, dt * p.beta_up)
                else:
                    dt_new = dt * p.beta_down
                    accept = False

                # collision gate (`system.cpp:542-546`) once shell/bodies exist

                if dt_new < p.dt_min:
                    raise RuntimeError("Timestep smaller than dt_min")

            if accept:
                t_new = float(state.time) + dt
                state = new_state._replace(
                    time=jnp.asarray(t_new, dtype=state.time.dtype),
                    dt=jnp.asarray(dt_new, dtype=state.dt.dtype))
                if writer is not None and (int(t_new / p.dt_write)
                                           > int((t_new - dt) / p.dt_write)):
                    writer(state, solution)
            else:
                state = backup._replace(dt=jnp.asarray(dt_new, dtype=state.dt.dtype))
        return state
