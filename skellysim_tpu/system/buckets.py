"""skelly-bucket: capacity-bucket shape polymorphism — one policy, one door.

ROADMAP item 4: XLA compilation (75 s cold / 35 s warm on the obs cost CLI)
is the largest per-scenario cost left in the system, and every new
`(n_fibers, nodes_per_fiber, shell_n)` combination used to pay it afresh in
every entry point. This module owns the ONE policy that quantizes scene
shapes onto a small set of padded capacity buckets, generalizing the
ensemble's masked-lane trick to all three shape axes:

* **fiber count** — geometric ladder; scenes pad with inert replicated
  slots (`fibers.container.grow_capacity`, the mechanism dynamic
  instability and the ring-divisibility pad already trusted);
* **nodes per fiber** — ladder over `matrices.VALID_NODE_COUNTS`; scenes
  below a rung pad with masked node rows whose differentiation matrices
  ride the state as DATA (`container.grow_node_capacity` /
  `matrices.FibMatsRT`), so different live resolutions share one program;
* **shell quadrature** — ladder over shell sizes; scenes pad with masked
  quadrature rows whose operators grow block-diagonally with the identity
  (`periphery.grow_capacity`).

`bucketize(state, policy)` is the single entry point every front door
calls — the run CLI, the listener, ensemble sweep admission, and
skelly-serve's capacity buckets — replacing the three ad-hoc padding call
sites (builder mesh pad, serve lane pad, dynamic-instability growth pad)
that used to be free to drift. The resulting `BucketKey` IS the compiled
program's identity: two scenes with equal keys are served by one warm
program with zero `observed_jit` compile events on the second
(docs/performance.md "Warm programs and capacity buckets").

Defaults are conservative: the node and shell ladders are identity/off, so
an unconfigured run produces byte-identical programs to the pre-bucket
tree (audit contracts and cost baselines unchanged). Opt into coarser
ladders via the `[runtime]` config table (`config.schema.RuntimeConfig`).
"""

from __future__ import annotations

import dataclasses
from typing import NamedTuple, Optional

from ..fibers import container as fc
from ..fibers.matrices import VALID_NODE_COUNTS

#: the geometric fiber-capacity ladder (x2 from 2; extended by doubling
#: past the last rung, so no scene is ever unplaceable) — the opt-in rungs
#: behind `[runtime] bucket_ladder = "geometric"`, skelly-serve's derived
#: buckets, and dynamic instability's capacity growth. The POLICY DEFAULT
#: is the identity (no fiber padding): unconfigured runs keep byte-exact
#: pre-bucket shapes, and warm-program sharing is an explicit opt-in.
GEOMETRIC_FIBER_LADDER = (2, 4, 8, 16, 32, 64, 128, 256, 512,
                          1024, 2048, 4096, 8192, 16384)


class BucketKey(NamedTuple):
    """The compiled-program identity a bucketized state maps to.

    ``fibers`` holds one ``(fiber_capacity, node_capacity)`` pair per
    resolution group in bucket order; ``shell`` is the padded shell
    quadrature size (None: no shell or shell unpadded); ``rt_nodes``
    records whether the bucket's groups carry runtime node mats
    (`matrices.FibMatsRT`) — part of the pytree STRUCTURE, so a state
    can only share the bucket's program if it matches. Hashable — serve
    uses it as the admission-bucket id, tests as the program-cache key.
    """

    fibers: tuple = ()
    shell: Optional[int] = None
    rt_nodes: bool = False

    def describe(self) -> str:
        fib = " + ".join(f"{cap}x{nn}" for cap, nn in self.fibers) or "none"
        return (f"fibers[{fib}]"
                + (" rt" if self.rt_nodes else "")
                + (f" shell[{self.shell}]" if self.shell is not None else ""))


def _rung(ladder, n: int) -> int:
    """Smallest ladder rung >= n; doubles past the last rung."""
    for r in ladder:
        if r >= n:
            return r
    r = ladder[-1] if ladder else 1
    while r < n:
        r *= 2
    return r


@dataclasses.dataclass(frozen=True)
class BucketPolicy:
    """The three capacity ladders (each ascending). Identity defaults: an
    empty ``fiber_ladder`` means no fiber padding (capacity == scene
    count), the `VALID_NODE_COUNTS` ``node_ladder`` means no node padding
    (every config resolution is already a rung), an empty ``shell_ladder``
    disables shell padding — so the default policy's `bucketize` is the
    identity and unconfigured programs stay byte-identical to the
    pre-bucket tree. Coarsen via the `[runtime]` config table
    (`from_runtime`); ``node_ladder`` rungs must come from
    `VALID_NODE_COUNTS`."""

    fiber_ladder: tuple = ()
    node_ladder: tuple = VALID_NODE_COUNTS
    shell_ladder: tuple = ()
    #: spectral-evaluator FFT grid-dimension ladder; () = the built-in
    #: 2^a 3^b rungs (`ops.spectral.GRID_RUNGS`). Unlike the capacity
    #: ladders this quantizes PLAN data (grid dims), not state shapes —
    #: `System.make_spectral_plan` threads it into `plan_spectral`.
    grid_ladder: tuple = ()

    def __post_init__(self):
        for name in ("fiber_ladder", "node_ladder", "shell_ladder",
                     "grid_ladder"):
            lad = tuple(int(v) for v in getattr(self, name))
            if list(lad) != sorted(set(lad)) or any(v < 1 for v in lad):
                raise ValueError(
                    f"{name} must be strictly ascending positive ints, "
                    f"got {lad}")
            object.__setattr__(self, name, lad)
        bad = set(self.node_ladder) - set(VALID_NODE_COUNTS)
        if bad:
            raise ValueError(
                f"node_ladder rungs {sorted(bad)} are not valid fiber "
                f"resolutions {VALID_NODE_COUNTS}")
        if not self.node_ladder:
            raise ValueError("node_ladder must not be empty")

    @classmethod
    def from_runtime(cls, runtime) -> "BucketPolicy":
        """Policy from a `config.schema.RuntimeConfig` (or None → defaults).
        ``bucket_ladder = [-1]`` (the TOML spelling of "geometric") selects
        `GEOMETRIC_FIBER_LADDER`; empty lists keep the identity defaults."""
        if runtime is None:
            return cls()
        fib = tuple(runtime.bucket_ladder)
        if fib == (-1,):
            fib = GEOMETRIC_FIBER_LADDER
        return cls(
            fiber_ladder=fib,
            node_ladder=tuple(runtime.node_ladder) or VALID_NODE_COUNTS,
            shell_ladder=tuple(runtime.shell_ladder),
            grid_ladder=tuple(getattr(runtime, "grid_ladder", ())))

    # ------------------------------------------------------------- rungs

    def fiber_capacity(self, n: int) -> int:
        if not self.fiber_ladder:
            return max(n, 1)
        return _rung(self.fiber_ladder, max(n, 1))

    def node_capacity(self, n: int) -> int:
        cap = _rung(self.node_ladder, n)
        if cap not in VALID_NODE_COUNTS:
            raise ValueError(
                f"no node_ladder rung holds {n} nodes (ladder "
                f"{self.node_ladder}, valid resolutions {VALID_NODE_COUNTS})")
        return cap

    def shell_capacity(self, n: int) -> Optional[int]:
        if not self.shell_ladder:
            return None
        return _rung(self.shell_ladder, n)

    @property
    def node_polymorphism(self) -> bool:
        """True when the node ladder is coarser than the identity — groups
        then carry runtime mats even at exact fit, so every scene in a rung
        shares the bucket's pytree structure."""
        return self.node_ladder != VALID_NODE_COUNTS


#: the module-default policy (the ladders every entry point uses unless a
#: config overrides them)
DEFAULT_POLICY = BucketPolicy()


def state_key(state) -> BucketKey:
    """The BucketKey describing a state's CURRENT (possibly padded) shapes."""
    buckets = fc.as_buckets(state.fibers)
    fibs = tuple((g.n_fibers, g.n_nodes) for g in buckets)
    shell = (state.shell.n_nodes
             if state.shell is not None and state.shell.node_mask is not None
             else None)
    return BucketKey(fibers=fibs, shell=shell,
                     rt_nodes=any(g.rt_mats is not None for g in buckets))


def bucketize(state, policy: BucketPolicy = None, *, node_multiple: int = 1,
              fiber_capacity: int | None = None,
              pair_evaluator: str = "direct"):
    """Pad ``state`` onto its policy bucket → ``(padded_state, BucketKey)``.

    The one shape-quantization door: fiber slots round up to the fiber
    ladder (and to a ``node_multiple``-divisible node count — the ring
    evaluator's divisibility invariant, re-homed from the builder), node
    rows to the node ladder (runtime-mats masked padding), the shell to the
    shell ladder. ``fiber_capacity`` overrides the fiber rung for
    single-group states (skelly-serve's explicit bucket sizes). A state
    already on its bucket passes through unchanged — bucketize is
    idempotent, and with the default policy it is the identity.
    """
    policy = policy or DEFAULT_POLICY
    buckets = list(fc.as_buckets(state.fibers))
    if fiber_capacity is not None and len(buckets) > 1:
        raise ValueError(
            "explicit fiber_capacity applies to single-resolution states; "
            "mixed-resolution scenes take their per-group ladder rungs")
    new_groups = []
    for g in buckets:
        n_cap = policy.node_capacity(fc.live_node_count(g))
        if n_cap != g.n_nodes or (policy.node_polymorphism
                                  and g.rt_mats is None):
            g = fc.grow_node_capacity(g, n_cap)
        cap = (fiber_capacity if fiber_capacity is not None
               else policy.fiber_capacity(g.n_fibers))
        if cap < g.n_fibers:
            raise ValueError(
                f"bucket fiber capacity {cap} below the scene's "
                f"{g.n_fibers} slots")
        g = fc.grow_capacity(g, cap, node_multiple=node_multiple)
        new_groups.append(g)
    if new_groups:
        state = state._replace(
            fibers=(new_groups[0] if isinstance(state.fibers, fc.FiberGroup)
                    else tuple(new_groups)))

    if state.shell is not None:
        cap = policy.shell_capacity(
            int(state.shell.node_mask.sum()) if state.shell.node_mask
            is not None else state.shell.n_nodes)
        if cap is not None:
            if pair_evaluator in ("ewald", "tree", "spectral"):
                live = (int(state.shell.node_mask.sum())
                        if state.shell.node_mask is not None
                        else state.shell.n_nodes)
                raise ValueError(
                    "shell_ladder padding is incompatible with the fast "
                    "summation evaluators ('ewald'/'tree'/'spectral'; this "
                    f"config selects {pair_evaluator!r} and the shell would "
                    f"pad {live} -> {cap} quadrature rows): padded rows "
                    "replicate node 0 and would overflow the planner's "
                    "static cell/leaf/occupancy buckets (see periphery."
                    "grow_capacity); use 'direct' or 'ring', or drop "
                    "[runtime] shell_ladder")
            from ..periphery import periphery as peri

            if cap != state.shell.n_nodes or state.shell.node_mask is None:
                state = state._replace(
                    shell=peri.grow_capacity(state.shell, cap))
    return state, state_key(state)


def bucketize_to(state, key: BucketKey, *, node_multiple: int = 1):
    """Pad ``state`` onto an EXPLICIT bucket key (serve admission into an
    already-compiled bucket whose rungs may exceed the scene's natural
    ones). Raises when the scene cannot fit the key — group-structure
    mismatch, capacity overflow, or incompatible live resolutions."""
    buckets = list(fc.as_buckets(state.fibers))
    if len(buckets) != len(key.fibers):
        raise ValueError(
            f"scene has {len(buckets)} fiber resolution group(s) but the "
            f"bucket holds {len(key.fibers)} ({key.describe()})")
    new_groups = []
    for g, (cap, n_cap) in zip(buckets, key.fibers):
        nl = fc.live_node_count(g)
        if nl > n_cap:
            raise ValueError(
                f"scene fibers have {nl} nodes but the bucket's node "
                f"capacity is {n_cap} ({key.describe()})")
        if g.n_fibers > cap:
            raise ValueError(
                f"scene needs {g.n_fibers} fiber slots but the bucket "
                f"holds {cap} ({key.describe()})")
        if key.rt_nodes:
            g = fc.grow_node_capacity(g, n_cap)
        elif nl != n_cap or g.rt_mats is not None:
            # a non-rt bucket's program reads static per-resolution mats:
            # only exact-resolution scenes share its pytree structure
            raise ValueError(
                f"scene fibers at {nl} live nodes cannot ride the static-"
                f"resolution bucket {key.describe()}; configure a "
                "[runtime] node_ladder for node polymorphism")
        g = fc.grow_capacity(g, cap, node_multiple=node_multiple)
        new_groups.append(g)
    if new_groups:
        state = state._replace(
            fibers=(new_groups[0] if isinstance(state.fibers, fc.FiberGroup)
                    else tuple(new_groups)))
    if key.shell is not None:
        from ..periphery import periphery as peri

        if state.shell is None:
            raise ValueError(
                f"bucket {key.describe()} expects a shell; scene has none")
        live = (int(state.shell.node_mask.sum())
                if state.shell.node_mask is not None
                else state.shell.n_nodes)
        if live > key.shell:
            raise ValueError(
                f"scene shell has {live} quadrature rows but the bucket's "
                f"capacity is {key.shell} ({key.describe()})")
        state = state._replace(shell=peri.grow_capacity(state.shell,
                                                        key.shell))
    return state


def admits(key: BucketKey, state) -> bool:
    """True when ``bucketize_to(state, key)`` would succeed (cheap
    shape-only check — serve's bucket selection predicate)."""
    buckets = list(fc.as_buckets(state.fibers))
    if len(buckets) != len(key.fibers):
        return False
    for g, (cap, n_cap) in zip(buckets, key.fibers):
        nl = fc.live_node_count(g)
        if g.n_fibers > cap or nl > n_cap:
            return False
        if not key.rt_nodes and (nl != n_cap or g.rt_mats is not None):
            return False
    if key.shell is not None:
        if state.shell is None:
            return False
        live = (int(state.shell.node_mask.sum())
                if state.shell.node_mask is not None
                else state.shell.n_nodes)
        if live > key.shell:
            return False
    return True


def pad_for_mesh(fibers, mesh_size: int):
    """Round each fiber group up to a mesh-divisible node count with inert
    padding slots — the ring evaluator's divisibility invariant, re-homed
    from `builder.build_simulation`'s ad-hoc pad onto the bucket module so
    the growers can never drift (`System._fiber_flow` dies mid-flight on a
    violation)."""
    if fibers is None or mesh_size <= 1:
        return fibers
    if isinstance(fibers, fc.FiberGroup):
        return fc.grow_capacity(fibers, fibers.n_fibers,
                                node_multiple=mesh_size)
    return tuple(fc.grow_capacity(g, g.n_fibers, node_multiple=mesh_size)
                 for g in fibers)


def next_fiber_capacity(n_needed: int, policy: BucketPolicy = None) -> int:
    """Dynamic instability's geometric growth target, on the SAME rungs as
    serve admission (`GEOMETRIC_FIBER_LADDER`) — nucleation re-lands on a
    bucket rung instead of drifting to ad-hoc ceil(1.5x) capacities (the
    third re-homed padding call site). A policy with an explicit fiber
    ladder overrides the rungs."""
    if policy is not None and policy.fiber_ladder:
        return policy.fiber_capacity(n_needed)
    return _rung(GEOMETRIC_FIBER_LADDER, max(n_needed, 1))
