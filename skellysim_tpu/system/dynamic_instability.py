"""Dynamic instability: stochastic fiber catastrophe + nucleation.

Host-side re-bucketing between jit'd solve steps, the TPU-native counterpart of
`System::dynamic_instability` (`/root/reference/src/core/dynamic_instability.cpp`):

- each active fiber draws a catastrophe with P = 1 - exp(-dt * f_cat)
  (`dynamic_instability.cpp:83-84`), with growth/catastrophe rates rescaled for
  plus-pinned fibers (`:76-79`); survivors grow by dt * v_growth (`:89-91`)
- nucleation-site occupancy is a flat bitmap over all body sites (`:63,87`)
- the number of new fibers is Poisson(dt * rate * n_inactive_old) capped by the
  free-site count (`:115-116`), each placed on a uniformly drawn free site
  (`:118-126`), pointing radially out of its body (`:178-186`)

Where the reference mutates a `std::list` and load-balances new fibers across
MPI ranks (`:150-156`), we flip an `active` mask over a fixed-capacity fiber
batch: catastrophes deactivate slots (no recompilation), nucleations fill
inactive slots, and capacity grows geometrically so XLA only re-traces
O(log n) times. There is no rank placement — the batch axis is mesh-sharded.
"""

from __future__ import annotations

import numpy as np
import jax.numpy as jnp

from ..fibers import container as fc
from ..utils.rng import SimRNG


#: shared with the builder's ring-evaluator padding; see
#: `container.grow_capacity`
_grow_capacity = fc.grow_capacity


def apply_dynamic_instability(state, params, rng: SimRNG, capacity_factor=1.5,
                              node_multiple: int = 1):
    """One nucleation/catastrophe update. Returns a new SimState.

    Runs on host between solves (like the reference, which calls it at the top
    of `prep_state_for_solver`, `system.cpp:403`).
    """
    di = params.dynamic_instability
    if di.n_nodes == 0:
        return state
    fibers = state.fibers
    bodies = state.bodies
    dt = float(state.dt)

    if fibers is not None and fibers.n_nodes != di.n_nodes:
        raise NotImplementedError(
            "dynamic_instability.n_nodes must match the fiber group resolution "
            f"({di.n_nodes} != {fibers.n_nodes}); mixed-resolution buckets are "
            "not implemented")

    # ---------------------------------------------- catastrophe + growth
    if fibers is not None and fibers.n_fibers > 0:
        nf = fibers.n_fibers
        active = np.asarray(fibers.active).copy()
        plus_pinned = np.asarray(fibers.plus_pinned)
        v_growth = np.where(plus_pinned, di.v_growth * di.v_grow_collision_scale,
                            di.v_growth)
        f_cat = np.where(plus_pinned,
                         di.f_catastrophe * di.f_catastrophe_collision_scale,
                         di.f_catastrophe)
        attached = active & (np.asarray(fibers.binding_body) >= 0)
        n_active_old = int(attached.sum())

        u = rng.distributed.uniform(size=nf)
        die = active & (u > np.exp(-dt * f_cat))
        survive = active & ~die

        length = np.asarray(fibers.length)
        length_prev = np.where(survive, length, np.asarray(fibers.length_prev))
        length = np.where(survive, length + dt * v_growth, length)
        fibers = fibers._replace(
            active=survive,
            length=length, length_prev=length_prev,
            v_growth=np.where(survive, v_growth, 0.0),
            binding_body=np.where(survive, np.asarray(fibers.binding_body), -1),
        )
    else:
        n_active_old = 0

    # ---------------------------------------------------------- nucleation
    if bodies is None or bodies.nucleation_sites_ref.shape[1] == 0:
        return state._replace(fibers=_as_device(fibers, state))
    nb, ns = bodies.n_bodies, bodies.nucleation_sites_ref.shape[1]
    n_sites = nb * ns

    occupied = np.zeros(n_sites, dtype=bool)
    if fibers is not None and fibers.n_fibers > 0:
        bb = np.asarray(fibers.binding_body)
        bs = np.asarray(fibers.binding_site)
        bound = np.asarray(fibers.active) & (bb >= 0)
        occupied[bb[bound] * ns + bs[bound]] = True

    free_sites = np.flatnonzero(~occupied)
    n_inactive_old = n_sites - n_active_old
    n_nucleate = min(
        rng.distributed.poisson_int(dt * di.nucleation_rate * n_inactive_old),
        free_sites.size)

    # sequential uniform draws without replacement (`dynamic_instability.cpp:118-126`)
    chosen = []
    pool = list(free_sites)
    for _ in range(n_nucleate):
        j = rng.distributed.uniform_int(0, len(pool))
        chosen.append(pool.pop(j))
    if not chosen:
        return state._replace(fibers=_as_device(fibers, state))

    from ..bodies import bodies as bd

    _, _, sites_lab = bd.place(bodies)
    sites_lab = np.asarray(sites_lab)          # [nb, ns, 3]
    body_pos = np.asarray(bodies.position)     # [nb, 3]

    new_x, new_body, new_site = [], [], []
    s = np.linspace(0.0, di.min_length, di.n_nodes)
    for flat in chosen:
        i_body, i_site = divmod(int(flat), ns)
        origin = sites_lab[i_body, i_site]
        u_dir = origin - body_pos[i_body]
        u_dir = u_dir / np.linalg.norm(u_dir)
        new_x.append(origin[None, :] + s[:, None] * u_dir[None, :])
        new_body.append(i_body)
        new_site.append(i_site)

    if fibers is None or fibers.n_fibers == 0:
        dtype = state.time.dtype
        fibers = fc.make_group(
            np.stack(new_x), lengths=di.min_length,
            bending_rigidity=di.bending_rigidity, radius=di.radius,
            minus_clamped=True, binding_body=np.array(new_body),
            binding_site=np.array(new_site), dtype=dtype)
        fibers = fc.grow_capacity(fibers, fibers.n_fibers, node_multiple)
        return state._replace(fibers=fibers)

    # fill inactive slots; grow capacity geometrically when out of room
    active = np.asarray(fibers.active)
    slots = np.flatnonzero(~active)
    if slots.size < len(chosen):
        need = int(active.sum()) + len(chosen)
        new_cap = max(int(np.ceil(fibers.n_fibers * capacity_factor)), need)
        # node_multiple keeps the ring evaluator's mesh-divisibility invariant
        fibers = _grow_capacity(fibers, new_cap, node_multiple)
        active = np.asarray(fibers.active)
        slots = np.flatnonzero(~active)
    slots = slots[:len(chosen)]

    from ..fibers import fd_fiber

    arr = {name: np.asarray(leaf).copy()
           for name, leaf in zip(fibers._fields, fibers)
           if np.asarray(leaf).ndim >= 1
           and np.asarray(leaf).shape[0] == fibers.n_fibers}
    handled = {"x", "tension", "length", "length_prev", "bending_rigidity",
               "radius", "penalty", "beta_tstep", "v_growth", "force_scale",
               "minus_clamped", "plus_pinned", "binding_body", "binding_site",
               "active"}
    if set(arr) - handled:
        raise RuntimeError(
            f"nucleation slot-fill does not reset fiber fields {set(arr) - handled}; "
            "recycled slots would inherit dead fibers' values")
    for k, slot in enumerate(slots):
        arr["x"][slot] = new_x[k]
        arr["tension"][slot] = 0.0
        arr["length"][slot] = di.min_length
        arr["length_prev"][slot] = di.min_length
        arr["bending_rigidity"][slot] = di.bending_rigidity
        arr["radius"][slot] = di.radius
        arr["penalty"][slot] = fd_fiber.DEFAULT_PENALTY
        arr["beta_tstep"][slot] = fd_fiber.DEFAULT_BETA_TSTEP
        arr["v_growth"][slot] = 0.0
        arr["force_scale"][slot] = 0.0
        arr["minus_clamped"][slot] = True
        arr["plus_pinned"][slot] = False
        arr["binding_body"][slot] = new_body[k]
        arr["binding_site"][slot] = new_site[k]
        arr["active"][slot] = True
    fibers = fibers._replace(**arr)
    return state._replace(fibers=_as_device(fibers, state))


def _as_device(fibers, state):
    """Re-materialize numpy-edited leaves as device arrays of the state dtype."""
    if fibers is None:
        return None
    dtype = state.time.dtype

    def conv(name, leaf):
        leaf = np.asarray(leaf)
        if leaf.dtype.kind == "f":
            return jnp.asarray(leaf, dtype=dtype)
        return jnp.asarray(leaf)

    return type(fibers)(*[conv(n, l) for n, l in zip(fibers._fields, fibers)])
