"""Dynamic instability: stochastic fiber catastrophe + nucleation.

Host-side re-bucketing between jit'd solve steps, the TPU-native counterpart of
`System::dynamic_instability` (`/root/reference/src/core/dynamic_instability.cpp`):

- each active fiber draws a catastrophe with P = 1 - exp(-dt * f_cat)
  (`dynamic_instability.cpp:83-84`), with growth/catastrophe rates rescaled for
  plus-pinned fibers (`:76-79`); survivors grow by dt * v_growth (`:89-91`)
- nucleation-site occupancy is a flat bitmap over all body sites (`:63,87`)
- the number of new fibers is Poisson(dt * rate * n_inactive_old) capped by the
  free-site count (`:115-116`), each placed on a uniformly drawn free site
  (`:118-126`), pointing radially out of its body (`:178-186`)

Where the reference mutates a `std::list` and load-balances new fibers across
MPI ranks (`:150-156`), we flip an `active` mask over a fixed-capacity fiber
batch: catastrophes deactivate slots (no recompilation), nucleations fill
inactive slots, and capacity grows geometrically so XLA only re-traces
O(log n) times. There is no rank placement — the batch axis is mesh-sharded.
"""

from __future__ import annotations

import numpy as np
import jax.numpy as jnp

from ..fibers import container as fc
from ..utils.rng import SimRNG
from . import di_rates


#: shared with the builder's ring-evaluator padding; see
#: `container.grow_capacity`
_grow_capacity = fc.grow_capacity


def _bucket_bindings(groups):
    """(occupied {(gid, site)}, n_bound) over the given fiber groups —
    fibers bind by GLOBAL body id, so occupancy must aggregate every
    bucket (the reference's one flat bitmap, `dynamic_instability.cpp:63`)."""
    occupied = set()
    n_bound = 0
    for g in groups:
        if g.n_fibers == 0:
            continue
        bb = np.asarray(g.binding_body)
        bs = np.asarray(g.binding_site)
        bound = np.asarray(g.active) & (bb >= 0)
        occupied |= set(zip(bb[bound].tolist(), bs[bound].tolist()))
        n_bound += int(bound.sum())
    return occupied, n_bound


def apply_dynamic_instability(state, params, rng: SimRNG, capacity_factor=1.5,
                              node_multiple: int = 1, stats: dict | None = None,
                              _extra_occupied=None,
                              _extra_bound: int = 0, _rank_floor: int = -1):
    """One nucleation/catastrophe update. Returns a new SimState.

    Runs on host between solves (like the reference, which calls it at the top
    of `prep_state_for_solver`, `system.cpp:403`). With multiple resolution
    buckets, nucleation/catastrophe act on the bucket whose resolution
    matches `dynamic_instability.n_nodes` (the reference nucleates at one
    resolution too, `dynamic_instability.cpp:128-139`); other buckets pass
    through untouched but their site occupancy, bound-fiber count, and
    config ranks still feed the global bookkeeping (the reference's flat
    site bitmap spans all fibers).

    The rate math is the shared `system.di_rates` module — ONE definition
    with the device-side engine (`scenarios.di_device`), so the host oracle
    and the in-trace ensemble update cannot drift. ``stats`` (optional
    dict) is filled with this update's ``catastrophes`` / ``nucleations``
    counts — the run-loop metrics fields (the loop counts the surviving
    ``active_fibers`` off the final state itself).
    """
    di = params.dynamic_instability
    if stats is not None:
        stats.setdefault("catastrophes", 0)
        stats.setdefault("nucleations", 0)
    if di.n_nodes == 0:
        return state
    if (state.fibers is not None
            and not isinstance(state.fibers, fc.FiberGroup)):
        buckets = list(fc.as_buckets(state.fibers))
        idx = next((i for i, g in enumerate(buckets)
                    if fc.live_node_count(g) == di.n_nodes), None)
        if idx is None:
            raise NotImplementedError(
                f"dynamic_instability.n_nodes={di.n_nodes} matches no fiber "
                f"bucket (resolutions: {[g.n_nodes for g in buckets]}); add "
                "an (empty-capacity) bucket at that resolution")
        others = [g for i, g in enumerate(buckets) if i != idx]
        occ, n_bound = _bucket_bindings(others)
        rank_floor = max(
            (int(np.asarray(g.config_rank).max(initial=-1))
             for g in others if g.config_rank is not None), default=-1)
        sub = apply_dynamic_instability(
            state._replace(fibers=buckets[idx]), params, rng,
            capacity_factor, node_multiple, stats=stats,
            _extra_occupied=occ,
            _extra_bound=n_bound, _rank_floor=rank_floor)
        buckets[idx] = sub.fibers
        return state._replace(fibers=tuple(buckets))
    fibers = state.fibers
    bodies = state.bodies
    dt = float(state.dt)

    if fibers is not None and fc.live_node_count(fibers) != di.n_nodes:
        # LIVE resolution, not node capacity: a node-padded bucket
        # (skelly-bucket) nucleates at its live resolution
        raise NotImplementedError(
            "dynamic_instability.n_nodes must match the fiber group's live "
            f"resolution ({di.n_nodes} != {fc.live_node_count(fibers)}); "
            "use a tuple of buckets for mixed resolutions")

    # ---------------------------------------------- catastrophe + growth
    if fibers is not None and fibers.n_fibers > 0:
        nf = fibers.n_fibers
        active = np.asarray(fibers.active).copy()
        plus_pinned = np.asarray(fibers.plus_pinned)
        v_growth, f_cat = di_rates.effective_rates(di, plus_pinned, np)
        attached = active & (np.asarray(fibers.binding_body) >= 0)
        n_active_old = int(attached.sum())

        u = rng.distributed.uniform(size=nf)
        die = di_rates.catastrophe_mask(active, u, dt, f_cat, np)
        survive = active & ~die
        if stats is not None:
            stats["catastrophes"] += int(die.sum())

        length = np.asarray(fibers.length)
        length_prev = np.where(survive, length, np.asarray(fibers.length_prev))
        length = di_rates.grown_length(length, survive, dt, v_growth, np)
        fibers = fibers._replace(
            active=survive,
            length=length, length_prev=length_prev,
            v_growth=np.where(survive, v_growth, 0.0),
            binding_body=np.where(survive, np.asarray(fibers.binding_body), -1),
        )
    else:
        n_active_old = 0

    # ---------------------------------------------------------- nucleation
    site_tab = host_site_table(bodies)
    if not site_tab:
        return state._replace(fibers=_as_device(fibers, state))
    n_sites = len(site_tab)

    occupied = set(_extra_occupied or ())
    if fibers is not None and fibers.n_fibers > 0:
        bb = np.asarray(fibers.binding_body)
        bs = np.asarray(fibers.binding_site)
        bound = np.asarray(fibers.active) & (bb >= 0)
        occupied |= set(zip(bb[bound].tolist(), bs[bound].tolist()))

    free_sites = [k for k, (gid, s_i, _, _) in enumerate(site_tab)
                  if (gid, s_i) not in occupied]
    n_inactive_old = n_sites - n_active_old - _extra_bound
    n_nucleate = int(di_rates.nucleation_count(
        rng.distributed.poisson_int(
            di_rates.nucleation_mean(dt, di.nucleation_rate, n_inactive_old)),
        len(free_sites)))

    # sequential uniform draws without replacement (`dynamic_instability.cpp:118-126`)
    chosen = []
    pool = list(free_sites)
    for _ in range(n_nucleate):
        j = rng.distributed.uniform_int(0, len(pool))
        chosen.append(pool.pop(j))
    if not chosen:
        return state._replace(fibers=_as_device(fibers, state))

    new_x, new_body, new_site = [], [], []
    for flat in chosen:
        gid, i_site, origin, com = site_tab[int(flat)]
        new_x.append(di_rates.nucleated_nodes(origin, com, di.min_length,
                                              di.n_nodes, np))
        new_body.append(gid)
        new_site.append(i_site)
    if stats is not None:
        stats["nucleations"] += len(chosen)

    if fibers is None or fibers.n_fibers == 0:
        from . import buckets as _buckets

        dtype = state.time.dtype
        fibers = fc.make_group(
            np.stack(new_x), lengths=di.min_length,
            bending_rigidity=di.bending_rigidity, radius=di.radius,
            minus_clamped=True, binding_body=np.array(new_body),
            binding_site=np.array(new_site),
            config_rank=_rank_floor + 1 + np.arange(len(new_x)),
            dtype=dtype)
        # from-scratch groups land on the SAME geometric rungs as overflow
        # growth and bucket admission (`buckets.next_fiber_capacity`): a
        # `[runtime]`-laddered resume re-bucketizes live fibers onto their
        # rung, and only a rung-aligned capacity keeps the continued
        # trajectory bitwise (padding changes reduction shapes)
        fibers = fc.grow_capacity(
            fibers, _buckets.next_fiber_capacity(fibers.n_fibers),
            node_multiple)
        return state._replace(fibers=fibers)

    # fill inactive slots; grow capacity geometrically when out of room —
    # onto the SAME rungs skelly-bucket admission uses (buckets.
    # next_fiber_capacity), so a nucleation burst re-lands on a bucket
    # capacity another warm program may already serve instead of drifting
    # to an ad-hoc ceil(capacity_factor x) count
    active = np.asarray(fibers.active)
    slots = np.flatnonzero(~active)
    if slots.size < len(chosen):
        from . import buckets as _buckets

        need = int(active.sum()) + len(chosen)
        new_cap = _buckets.next_fiber_capacity(need)
        # node_multiple keeps the ring evaluator's mesh-divisibility invariant
        fibers = _grow_capacity(fibers, new_cap, node_multiple)
        active = np.asarray(fibers.active)
        slots = np.flatnonzero(~active)
    slots = slots[:len(chosen)]

    from ..fibers import fd_fiber

    arr = {name: np.asarray(leaf).copy()
           for name, leaf in zip(fibers._fields, fibers)
           if name != "rt_mats" and leaf is not None
           and np.asarray(leaf).ndim >= 1
           and np.asarray(leaf).shape[0] == fibers.n_fibers}
    handled = {"x", "tension", "length", "length_prev", "bending_rigidity",
               "radius", "penalty", "beta_tstep", "v_growth", "force_scale",
               "minus_clamped", "plus_pinned", "binding_body", "binding_site",
               "active", "config_rank"}
    if set(arr) - handled:
        raise RuntimeError(
            f"nucleation slot-fill does not reset fiber fields {set(arr) - handled}; "
            "recycled slots would inherit dead fibers' values")
    # fresh config ranks: nucleated fibers append after every existing fiber
    # in the trajectory's config order — across ALL buckets (_rank_floor
    # carries the other buckets' max; a collision would scramble the wire
    # order)
    next_rank = max(int(arr["config_rank"].max(initial=-1)), _rank_floor) + 1
    # node-capacity-padded groups (skelly-bucket): the nucleated geometry
    # fills the LIVE prefix; masked padding rows replicate its first node,
    # the same placeholder discipline as grow_node_capacity
    n_cap = fibers.n_nodes
    if n_cap > di.n_nodes:
        new_x = [np.concatenate(
            [xr, np.repeat(xr[:1], n_cap - di.n_nodes, axis=0)], axis=0)
            for xr in new_x]
    for k, slot in enumerate(slots):
        arr["config_rank"][slot] = next_rank + k
        arr["x"][slot] = new_x[k]
        arr["tension"][slot] = 0.0
        arr["length"][slot] = di.min_length
        arr["length_prev"][slot] = di.min_length
        arr["bending_rigidity"][slot] = di.bending_rigidity
        arr["radius"][slot] = di.radius
        arr["penalty"][slot] = fd_fiber.DEFAULT_PENALTY
        arr["beta_tstep"][slot] = fd_fiber.DEFAULT_BETA_TSTEP
        arr["v_growth"][slot] = 0.0
        arr["force_scale"][slot] = 0.0
        arr["minus_clamped"][slot] = True
        arr["plus_pinned"][slot] = False
        arr["binding_body"][slot] = new_body[k]
        arr["binding_site"][slot] = new_site[k]
        arr["active"][slot] = True
    fibers = fibers._replace(**arr)
    return state._replace(fibers=_as_device(fibers, state))


def host_site_table(bodies) -> list:
    """Flat ``[(global_id, site_index, origin, com)]`` nucleation-site table
    across every body bucket, host-side — the reference's flat bitmap over
    all sites (`dynamic_instability.cpp:63,87`): bucket-concatenated,
    body-major, site-minor; fibers bind by GLOBAL body id
    (`BodyGroup.config_rank`). ONE definition shared by this host update
    and the scenario front-end (`scenarios.sweep`); the traced twin
    (`scenarios.di_device.site_table`) must keep exactly this order or
    injected-draw site-selection parity between the paths breaks."""
    from ..bodies import bodies as bd

    tab = []
    for g in bd.as_buckets(bodies):
        ns_b = g.nucleation_sites_ref.shape[1]
        if ns_b == 0:
            continue
        _, _, sites_lab = bd.place(g)
        sites_lab = np.asarray(sites_lab)       # [nb, ns_b, 3]
        pos = np.asarray(g.position)
        ranks = (np.asarray(g.config_rank) if g.config_rank is not None
                 else np.arange(g.n_bodies))
        for lb in range(g.n_bodies):
            for s_i in range(ns_b):
                tab.append((int(ranks[lb]), s_i, sites_lab[lb, s_i], pos[lb]))
    return tab


def _count_active(fibers) -> int:
    """Host-side live fiber count over every bucket (the `active_fibers`
    metrics field; cheap — one bool mask fetch per bucket)."""
    return sum(int(np.asarray(g.active).sum()) for g in fc.as_buckets(fibers))


def _as_device(fibers, state):
    """Re-materialize numpy-edited leaves as device arrays of the state dtype."""
    if fibers is None:
        return None
    dtype = state.time.dtype

    def conv(name, leaf):
        if name == "rt_mats" or leaf is None:
            return leaf  # group-level runtime mats / absent optional fields
        leaf = np.asarray(leaf)
        if leaf.dtype.kind == "f":
            return jnp.asarray(leaf, dtype=dtype)
        return jnp.asarray(leaf)

    return type(fibers)(*[conv(n, l) for n, l in zip(fibers._fields, fibers)])
