"""Point force/torque sources and background flow.

Mirrors `PointSourceContainer` (`/root/reference/src/core/point_source.cpp:16-53`)
and `BackgroundSource` (`src/core/background_source.cpp:15-23`) as stateless
batched pytrees.
"""

from __future__ import annotations

from typing import NamedTuple

import jax.numpy as jnp

from ..ops import kernels


class PointSources(NamedTuple):
    """Batched point sources; time_to_live == 0 means always alive."""

    position: jnp.ndarray      # [np, 3]
    force: jnp.ndarray         # [np, 3]
    torque: jnp.ndarray        # [np, 3]
    time_to_live: jnp.ndarray  # [np]

    @staticmethod
    def make(position, force=None, torque=None, time_to_live=0.0, dtype=jnp.float64):
        position = jnp.asarray(position, dtype=dtype).reshape(-1, 3)
        n = position.shape[0]
        z = jnp.zeros((n, 3), dtype=dtype)
        return PointSources(
            position=position,
            force=z if force is None else jnp.asarray(force, dtype=dtype).reshape(-1, 3),
            torque=z if torque is None else jnp.asarray(torque, dtype=dtype).reshape(-1, 3),
            time_to_live=jnp.broadcast_to(jnp.asarray(time_to_live, dtype=dtype), (n,)),
        )

    def flow(self, r_trg, eta, time):
        """Oseen + rotlet flow at targets; expired sources are masked to zero."""
        alive = (self.time_to_live == 0.0) | (time < self.time_to_live)
        f = jnp.where(alive[:, None], self.force, 0.0)
        t = jnp.where(alive[:, None], self.torque, 0.0)
        u = kernels.oseen_contract(self.position, r_trg, f, eta)
        u = u + kernels.rotlet(self.position, r_trg, t, eta)
        return u


class BackgroundFlow(NamedTuple):
    """v_j = uniform_j + r[components_j] * scale_j (`background_source.cpp:15-23`)."""

    uniform: jnp.ndarray     # [3]
    components: jnp.ndarray  # [3] int
    scale: jnp.ndarray       # [3]

    @staticmethod
    def make(uniform=(0.0, 0.0, 0.0), components=(0, 1, 2), scale=(0.0, 0.0, 0.0),
             dtype=jnp.float64):
        return BackgroundFlow(
            uniform=jnp.asarray(uniform, dtype=dtype),
            components=jnp.asarray(components, dtype=jnp.int32),
            scale=jnp.asarray(scale, dtype=dtype),
        )

    def flow(self, r_trg, eta):
        return self.uniform[None, :] + r_trg[:, self.components] * self.scale[None, :]

    def is_active(self):
        return bool(jnp.any(self.uniform != 0.0) | jnp.any(self.scale != 0.0))
