from .sources import PointSources, BackgroundFlow  # noqa: F401
from .system import SimState, System  # noqa: F401
from .dynamic_instability import apply_dynamic_instability  # noqa: F401
from .buckets import (BucketKey, BucketPolicy, bucketize,  # noqa: F401
                      bucketize_to)
