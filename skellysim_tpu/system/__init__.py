from .sources import PointSources, BackgroundFlow  # noqa: F401
from .system import SimState, System  # noqa: F401
