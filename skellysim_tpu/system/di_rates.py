"""Dynamic-instability rate math — ONE definition for host and device paths.

The catastrophe/growth/nucleation arithmetic of the reference
(`dynamic_instability.cpp:76-91,115-116`) is consumed by TWO
implementations that must never drift:

* the host path (`system.dynamic_instability.apply_dynamic_instability`),
  which re-buckets fibers between jit'd steps with numpy + `SimRNG` — the
  oracle for parity tests and the `--resume` wire format;
* the device path (`scenarios.di_device.di_update`), which runs the same
  update as pure masked jnp ops INSIDE the batched ensemble trace.

Every helper takes the array namespace ``xp`` (numpy for the host path,
jax.numpy inside a trace) so the formulas are written exactly once. All
arithmetic is element-wise in the caller's dtype — at float64 the two
namespaces agree bitwise on everything except transcendentals (``exp``
differs between libm and XLA by <= 1 ulp), which is why the ensemble
parity pins run at the vmap-plan tolerance, not bitwise
(docs/scenarios.md).
"""

from __future__ import annotations

import numpy as np


def effective_rates(di, plus_pinned, xp=np):
    """(v_growth, f_catastrophe) per fiber with the plus-pinned rescaling
    (`dynamic_instability.cpp:76-79`): a fiber whose plus end is pinned to
    the periphery grows slower and dies faster by the collision scales."""
    v_growth = xp.where(plus_pinned,
                        di.v_growth * di.v_grow_collision_scale,
                        di.v_growth)
    f_cat = xp.where(plus_pinned,
                     di.f_catastrophe * di.f_catastrophe_collision_scale,
                     di.f_catastrophe)
    return v_growth, f_cat


def catastrophe_mask(active, u, dt, f_cat, xp=np):
    """Fibers dying this step: P(die) = 1 - exp(-dt * f_cat) per active
    fiber against one uniform draw (`dynamic_instability.cpp:83-84`).
    ``u`` in [0, 1): a fiber dies when its draw exceeds the survival
    probability, so ``u = 0`` never kills and ``u -> 1`` always does —
    the injection convention the parity tests rely on."""
    return active & (u > xp.exp(-dt * f_cat))


def grown_length(length, survive, dt, v_growth, xp=np):
    """Survivor target lengths: L + dt * v_growth; dead fibers keep their
    final length (`dynamic_instability.cpp:89-91`)."""
    return xp.where(survive, length + dt * v_growth, length)


def nucleation_mean(dt, rate, n_inactive):
    """Poisson mean for this step's nucleation count: dt * rate * (number
    of sites not bound at step entry) (`dynamic_instability.cpp:115`)."""
    return dt * rate * n_inactive


def nucleation_count(n_raw, n_free, xp=np):
    """Poisson draw capped by the free-site count
    (`dynamic_instability.cpp:116`)."""
    return xp.minimum(n_raw, n_free)


def nucleated_nodes(origin, com, min_length, n_nodes, xp=np):
    """[n_nodes, 3] node positions of one nucleated fiber: minus end on its
    site, pointing radially out of the body COM, length ``min_length``
    (`dynamic_instability.cpp:118-126,178-186`). ``origin``/``com`` may
    carry leading batch axes; nodes fill a new second-to-last axis."""
    u_dir = origin - com
    u_dir = u_dir / xp.sqrt((u_dir * u_dir).sum(axis=-1, keepdims=True))
    s = xp.linspace(0.0, min_length, n_nodes)
    shape = origin.shape[:-1] + (n_nodes, 3)
    return (origin[..., None, :]
            + s.reshape((1,) * (len(shape) - 2) + (n_nodes, 1))
            * u_dir[..., None, :]).reshape(shape)
