"""Serve driver: `python -m skellysim_tpu.serve --config-file=...`.

Boots the long-lived multi-tenant simulation service (docs/serving.md): the
config file's fibers/params define the warm compiled program tenants admit
against, its `[serve]` table (host/port/buckets/lanes/queue) sizes the
service. `--port 0` binds an ephemeral port; pair it with `--port-file` so
spawners (CI, `serve.client.SpawnedServer`) can find it.
"""

from __future__ import annotations

import argparse
import os
import sys


def main(argv=None) -> None:
    ap = argparse.ArgumentParser(
        prog="skellysim-tpu-serve",
        description="persistent multi-tenant simulation service with "
                    "warm-program admission control (docs/serving.md)")
    ap.add_argument("--config-file", default="skelly_config.toml",
                    help="server run config; its [serve] table sizes the "
                         "service, its fibers/params define the compiled-"
                         "program contract tenants admit against")
    ap.add_argument("--host", default=None,
                    help="override [serve] host")
    ap.add_argument("--port", type=int, default=None,
                    help="override [serve] port (0 = ephemeral)")
    ap.add_argument("--port-file", default=None,
                    help="publish the bound port to this file once listening")
    ap.add_argument("--max-lanes", type=int, default=None,
                    help="override [serve] max_lanes (tenant slots/bucket)")
    ap.add_argument("--trace-file", default=None,
                    help="skelly-scope telemetry JSONL (lane/compile/span "
                         "events; `python -m skellysim_tpu.obs summarize`)")
    ap.add_argument("--jax-cache", default=None, metavar="DIR",
                    help="persistent XLA compilation cache directory shared "
                         "across runs/CLIs (default-on: [runtime] jax_cache, "
                         "else the package .jax_cache) — cold server starts "
                         "reuse prior compiles")
    ap.add_argument("--no-jax-cache", action="store_true",
                    help="disable the persistent compilation cache")
    ap.add_argument("--no-warmup", action="store_true",
                    help="skip the startup bucket-program compile (programs "
                         "then compile on first admission)")
    ap.add_argument("--log-level",
                    default=os.environ.get("SKELLYSIM_LOG", "INFO"))
    args = ap.parse_args(argv)

    import logging

    logging.basicConfig(level=args.log_level.upper(),
                        format="[%(asctime)s] [%(levelname)s] %(message)s",
                        stream=sys.stderr)

    # x64 for the same reason as the run/ensemble CLIs: without it the
    # builder's "f64" states silently canonicalize to f32 and tight
    # tolerances floor at f32 noise while steps are still accepted
    import jax

    jax.config.update("jax_enable_x64", True)

    from ..cli import resolve_cache_dir
    from ..utils.bootstrap import enable_compilation_cache

    enable_compilation_cache(resolve_cache_dir(
        args.config_file, flag=args.jax_cache, off=args.no_jax_cache))

    from ..config import schema
    from .server import SimulationServer

    serve_cfg = schema.load_serve_config(args.config_file)
    if args.host is not None:
        serve_cfg.host = args.host
    if args.port is not None:
        serve_cfg.port = args.port
    if args.max_lanes is not None:
        serve_cfg.max_lanes = args.max_lanes

    server = SimulationServer(args.config_file, serve_cfg=serve_cfg,
                              trace_path=args.trace_file,
                              warmup=not args.no_warmup)
    server.serve_forever(port_file=args.port_file)
    print("serve: shutdown complete "
          f"({server.metrics.stats()['retired']} tenant(s) retired)")
