"""Crash-safe write-ahead tenant journal for the serve event loop.

`kill -9` of a serve process must not lose its tenants (docs/robustness.md):
the server appends one journal entry — tenant identity + a trajectory-v1
state snapshot (the SAME bytes `snapshot` requests and `--resume` consume)
— at every point tenant state becomes durable-worthy:

* ``admit``    — BEFORE the tenant is seated (write-ahead: if the server
  dies mid-admission the journal already knows the tenant);
* ``checkpoint`` — every ``[serve] journal_every`` batched rounds, one
  entry per seated tenant (the replay bound: a restart loses at most
  that many rounds);
* ``retire``   — terminal transition (finished / evicted / cancelled /
  dt_underflow / failed), final snapshot + the health verdict.

On restart, `SimulationServer` replays the journal (`replay`), re-admits
every tenant whose LAST entry is live (queued/running) from its latest
snapshot, and restores terminal tenants' records so clients can still
fetch their final snapshot/status.

Wire format: the length-prefixed msgpack framing of `serve.protocol`
(HEADER + msgpack map), one frame per entry, appended + flushed per write
— after ``kill -9`` the OS page cache still holds every flushed entry, so
only a torn FINAL frame is possible and `replay` simply stops there
(`protocol.read_frame` returns None on a mid-frame EOF). jax-free.
"""

from __future__ import annotations

import os
from typing import Optional

from . import protocol

#: journal statuses considered live (re-admitted on recovery)
LIVE_STATES = ("queued", "running")


class TenantJournal:
    """Append-only journal at ``path`` (created, with parent dirs, on
    first use). ``truncate=True`` starts a fresh file — the
    compaction-on-recovery path (`SimulationServer` rewrites the replayed
    latest-entry-per-tenant set into a sibling file and atomically
    `os.replace`s it over the old journal, so a crash mid-compaction
    still finds a complete journal at ``path``).

    Growth bound: within one server lifetime the journal grows by one
    snapshot per seated tenant every `journal_every` rounds plus
    admit/retire entries — compaction happens at RESTART, not in-flight
    (an in-run compactor would have to quiesce appends; restart-time
    compaction keeps the event loop free). Size a long-lived server's
    journal disk for (live tenants) x (snapshot size) x (rounds /
    journal_every) between restarts, and pair terminal-record growth with
    `[serve] record_ttl_s` (docs/robustness.md)."""

    def __init__(self, path: str, *, truncate: bool = False):
        self.path = path
        parent = os.path.dirname(os.path.abspath(path))
        os.makedirs(parent, exist_ok=True)
        self._fh = open(path, "wb" if truncate else "ab")
        self._seq = 0

    def record(self, kind: str, tenant_id: str, *, bucket: int,
               t_final: float, status: str, frame: Optional[bytes] = None,
               health: int = 0, t: float = 0.0,
               flight: Optional[dict] = None):
        """Append one entry. ``frame`` is one trajectory-v1 snapshot (None
        only for terminal entries whose final frame is already journaled);
        ``flight`` is the skelly-flight blast-radius payload of a failed
        tenant (`obs.flight.failure_payload`) — journaled so a restarted
        server still answers the fault's provenance on `status`."""
        entry = {
            "kind": kind, "tenant": tenant_id, "bucket": int(bucket),
            "t_final": float(t_final), "status": status, "t": float(t),
            "health": int(health), "seq": self._seq,
        }
        if frame is not None:
            entry["frame"] = bytes(frame)
        if flight is not None:
            entry["flight"] = flight
        self._seq += 1
        buf = protocol.pack_message(entry)
        self._fh.write(protocol.HEADER.pack(len(buf)) + buf)
        # flush to the OS: SIGKILL cannot lose page-cache data, so this is
        # the whole durability story short of power loss (fsync would
        # serialize the event loop on disk latency for no kill -9 benefit)
        self._fh.flush()

    def close(self):
        if self._fh is not None:
            self._fh.close()
            self._fh = None

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()


def replay(path: str) -> dict:
    """Journal file -> {tenant_id: last entry dict}, latest-wins.

    Entries keep the most recent ``frame`` seen for the tenant even when
    the last entry carries none (a terminal entry without a final frame
    falls back to the last checkpoint). Tolerates a torn final frame
    (crash mid-append) and a missing file (fresh journal) — both simply
    end the replay."""
    tenants: dict = {}
    if not os.path.exists(path):
        return tenants
    with open(path, "rb") as fh:
        while True:
            try:
                buf = protocol.read_frame(fh)
            except ValueError:
                break  # corrupt header: everything before it is intact
            if not buf:
                break
            try:
                entry = protocol.unpack_message(buf)
            except Exception:
                break  # torn msgpack tail
            if not isinstance(entry, dict) or "tenant" not in entry:
                continue
            tid = entry["tenant"]
            prev = tenants.get(tid)
            if prev is not None and "frame" not in entry:
                prev_frame = prev.get("frame")
                if prev_frame is not None:
                    entry = dict(entry, frame=prev_frame)
            tenants[tid] = entry
    return tenants
