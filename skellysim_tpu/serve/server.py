"""The serve event loop: warm compiled lanes, tenants joining between rounds.

`SimulationServer` composes three existing subsystems into one long-lived
process (ROADMAP item 3):

* the ensemble continuous-batching scheduler steps B lanes as ONE compiled
  program and swaps members in/out without retracing
  (`ensemble.scheduler.EnsembleScheduler.admit/poll/evict`);
* the trajectory frame machinery encodes per-tenant frames + snapshots
  byte-compatible with every existing reader (`io.trajectory`);
* skelly-scope telemetry carries the SLO stream (`serve.metrics` folds the
  same events `/stats` reports from).

One thread, no locks: the socket loop services whatever client requests are
pending (admission, streaming, snapshots, eviction), then runs ONE batched
round over every bucket with live lanes, then returns to the sockets —
requests land exactly at round boundaries, which is also the only place the
scheduler allows lane churn. Latency per request is therefore bounded by
one batched step, and the solves never leave the device between rounds.

Capacity buckets: each configured capacity is one `EnsembleScheduler` whose
template pads the base config's fiber batch to that capacity. `warmup()`
compiles every bucket's program ONCE at startup (an idle-lane batched step
— all lanes masked inert); from then on every admission is pure leaf
substitution into a warm program, and any further compile event is a
warm-path retrace counted by `metrics.compiles_after_warm` (the acceptance
gate pins it at zero).
"""

from __future__ import annotations

import logging
import os
from typing import Optional

from ..obs import tracer as obs_tracer
from . import protocol, tenants as tenants_mod
from .metrics import ServeMetrics, StatsTracer

logger = logging.getLogger("skellysim_tpu")


class Bucket:
    """One capacity bucket: a padded template + its compiled lanes."""

    def __init__(self, capacity: int, template, scheduler):
        self.capacity = capacity
        self.template = template
        self.scheduler = scheduler
        self.warmed = False


class SimulationServer:
    """The serve core, socket-free: `handle_request` + `tick`.

    Tests drive these directly; `serve_forever` wraps them in the TCP event
    loop. ``config`` is the server's run-config TOML path (or a parsed
    `schema.Config` plus an explicit ``serve_cfg``): its fibers/params
    define the compiled-program contract every tenant must match, its
    `[serve]` table sizes the service.
    """

    def __init__(self, config, *, serve_cfg=None, trace_path: str = None,
                 config_dir: str = ".", warmup: bool = True):
        from ..builder import build_simulation
        from ..config import schema
        from ..ensemble.runner import EnsembleRunner
        from ..ensemble.scheduler import EnsembleScheduler

        if isinstance(config, (str, os.PathLike)):
            if serve_cfg is None:
                serve_cfg = schema.load_serve_config(str(config))
            config_dir = os.path.dirname(os.path.abspath(config)) or "."
            config = schema.load_config(str(config))
        elif serve_cfg is None:
            serve_cfg = schema.ServeConfig()
        self.base_config = config
        self.serve_cfg = serve_cfg
        self.metrics = ServeMetrics()
        self.tracer = StatsTracer(self.metrics, trace_path)
        self.registry = tenants_mod.TenantRegistry()
        self._shutdown = False
        self.address = None

        system, base_state, _ = build_simulation(config,
                                                 config_dir=config_dir)
        if base_state.fibers is None:
            raise ValueError("serve needs a base config with fibers: they "
                             "define the compiled-program contract tenants "
                             "admit against")
        self.system = system
        base_n = self._fiber_count(base_state)
        caps = sorted(set(serve_cfg.bucket_capacities)) or [base_n]
        if caps[0] < base_n:
            raise ValueError(
                f"[serve] bucket_capacities {caps} below the base config's "
                f"fiber count {base_n}; buckets PAD the base scene, so every "
                "capacity must be >= it")
        self.buckets: list[Bucket] = []
        for cap in caps:
            template = tenants_mod.pad_state_to_capacity(base_state, cap)
            runner = EnsembleRunner(system, batch_impl=serve_cfg.batch_impl)
            sched = EnsembleScheduler(
                runner, [], serve_cfg.max_lanes, template=template,
                writer=self._on_frame, metrics=self._on_sched_event,
                on_retire=self._on_retire, on_dt_underflow="retire")
            self.buckets.append(Bucket(cap, template, sched))
        if warmup:
            self.warmup()

    @staticmethod
    def _fiber_count(state) -> int:
        from ..fibers import container as fc

        return sum(g.n_fibers for g in fc.as_buckets(state.fibers))

    # ----------------------------------------------------------- warm path

    def warmup(self):
        """Compile every bucket's batched program on its idle template lanes
        (all masked inert — one cheap round each), then arm the
        zero-compiles-after-warmup gate."""
        with obs_tracer.use(self.tracer):
            for b in self.buckets:
                if not b.warmed:
                    # pure call, result discarded: compiles (and emits the
                    # compile event) without advancing the idle lanes
                    b.scheduler.step_fn(b.scheduler.ens)
                    b.warmed = True
            self.metrics.mark_warm()
        logger.info("serve: %d bucket program(s) warm (capacities %s)",
                    len(self.buckets), [b.capacity for b in self.buckets])

    def tick(self) -> bool:
        """One batched round over every bucket with live lanes; True when
        any stepping happened (the socket loop's idle signal)."""
        did = False
        with obs_tracer.use(self.tracer):
            for b in self.buckets:
                if b.scheduler.live:
                    b.scheduler.poll()
                    did = True
        self._expire_records()
        return did

    def _expire_records(self):
        """Bounded tenant-record retention (`[serve] record_ttl_s`):
        terminal records expire `ttl` after retirement — a long-lived
        server under sustained traffic must not grow its registry (and the
        final-frame snapshots it holds) without bound. Runs on every tick
        AND every request, so idle servers expire too."""
        import time

        dead = self.registry.expire(self.serve_cfg.record_ttl_s,
                                    time.monotonic())
        for tid in dead:
            logger.info("serve: tenant record %s expired (record_ttl_s=%g)",
                        tid, self.serve_cfg.record_ttl_s)

    def any_live(self) -> bool:
        return any(b.scheduler.live for b in self.buckets)

    # ------------------------------------------------- scheduler callbacks

    def _tenant(self, member_id: str):
        return self.registry.get(member_id)

    def _on_frame(self, member_id: str, state, *, rng_state=None):
        t = self._tenant(member_id)
        if t is not None:
            t.frames.append(tenants_mod.state_snapshot(state,
                                                       rng_state=rng_state))
            t.frames_total += 1

    def _on_retire(self, member_id: str, state, reason: str):
        import time

        t = self._tenant(member_id)
        if t is not None:
            t.final_frame = tenants_mod.state_snapshot(
                state, rng_state=t.rng_state)
            t.t = float(state.time)
            t.status = reason if reason in tenants_mod.TENANT_STATES \
                else "finished"
            t.retired_at = time.monotonic()   # [serve] record_ttl_s clock

    def _on_sched_event(self, rec: dict):
        t = self._tenant(rec.get("member", ""))
        if t is None:
            return
        ev = rec.get("event")
        if ev == "start":
            t.status = "running"
        elif ev == "step":
            t.steps = int(rec["step"]) + 1
            t.t = float(rec["t"])

    # ------------------------------------------------------------ requests

    def handle_request(self, req, conn=None) -> dict:
        """One request dict -> one response dict (never raises: admission
        rejections and malformed requests answer structured errors — one
        bad client must not kill the service)."""
        err = protocol.validate_request(req)
        if err:
            return protocol.error(err)
        self._expire_records()
        handler = getattr(self, f"_req_{req['type']}")
        try:
            with obs_tracer.use(self.tracer):
                return handler(req, conn)
        except Exception as e:  # defense for the event loop
            logger.exception("serve: %s request failed", req.get("type"))
            return protocol.error(f"{type(e).__name__}: {e}")

    def _req_submit(self, req, conn) -> dict:
        from ..builder import build_simulation
        from ..utils.rng import SimRNG

        if all(b.scheduler.live >= b.scheduler.batch
               and len(b.scheduler.queue) >= self.serve_cfg.queue_depth
               for b in self.buckets):
            # shed BEFORE the host-side scene build: a saturated server must
            # not pay build_simulation per rejected retry (overload is
            # exactly when the event loop can least afford it)
            self.metrics.note_rejected()
            return protocol.error(
                "admission queue full on every bucket — retry later",
                retry=True)
        try:
            cfg = tenants_mod.parse_tenant_config(req["config"])
        except ValueError as e:
            self.metrics.note_rejected()
            return protocol.error(str(e))
        err = tenants_mod.check_params_contract(cfg.params,
                                                self.base_config.params)
        if err:
            self.metrics.note_rejected()
            return protocol.error(err)
        _, state, rng = build_simulation(cfg)

        # capacity-bucket selection: smallest bucket the padded scene fits
        n = self._fiber_count(state)
        bucket = next((b for b in self.buckets if b.capacity >= n), None)
        if bucket is not None:
            state = tenants_mod.pad_state_to_capacity(state, bucket.capacity)
            if req.get("resume_frame") is not None:
                # rebuild from the snapshot frame over the fresh state, then
                # re-pad (frames carry ACTIVE fibers only); the frame's
                # serialized RNG streams resume too, like cli's --resume
                state, rng_state = tenants_mod.state_from_snapshot(
                    bytes(req["resume_frame"]), state)
                if rng_state:
                    rng = SimRNG.from_state(rng_state)
                state = tenants_mod.pad_state_to_capacity(state,
                                                         bucket.capacity)
            mismatch = tenants_mod.bucket_mismatch(bucket.template, state)
        else:
            mismatch = (f"scene needs {n} fiber slots but the largest "
                        f"bucket holds {self.buckets[-1].capacity}")
        if bucket is None or mismatch:
            self.metrics.note_rejected()
            return protocol.error(
                "no capacity bucket matches this scene: " + mismatch
                + f" (bucket capacities: {[b.capacity for b in self.buckets]})")

        sched = bucket.scheduler
        if (sched.live >= sched.batch
                and len(sched.queue) >= self.serve_cfg.queue_depth):
            self.metrics.note_rejected()
            return protocol.error(
                f"admission queue full ({len(sched.queue)} waiting, "
                f"{sched.batch} lanes busy) — retry later", retry=True)

        tid = req.get("tenant") or self.registry.new_id()
        if self.registry.get(tid) is not None:
            self.metrics.note_rejected()
            return protocol.error(f"tenant id {tid!r} already exists")
        # explicit None check: a client-requested t_final of 0.0 means "admit
        # and stop immediately", not "use the config's"
        t_final = float(cfg.params.t_final if req.get("t_final") is None
                        else req["t_final"])
        tenant = tenants_mod.Tenant(
            tenant_id=tid, bucket=bucket.capacity, t_final=t_final,
            conn=conn, t=float(state.time),
            rng_state=rng.dump_state() if rng is not None else None)
        self.registry.add(tenant)
        if req.get("resume_frame") is None:
            # the initial-config frame, like a fresh CLI run (resumed
            # tenants skip it, like `--resume` appends)
            self._on_frame(tid, state, rng_state=tenant.rng_state)

        from ..ensemble.scheduler import MemberSpec

        lane = sched.admit(MemberSpec(member_id=tid, state=state,
                                      t_final=t_final, rng=rng))
        logger.info("serve: tenant %s -> bucket %d %s", tid, bucket.capacity,
                    f"lane {lane}" if lane is not None else "queued")
        return protocol.ok(tenant=tid, bucket=bucket.capacity,
                           status=tenant.status, lane=lane,
                           queued=lane is None)

    def _find(self, req):
        t = self.registry.get(req["tenant"])
        if t is None:
            return None, protocol.error(f"unknown tenant {req['tenant']!r}")
        return t, None

    def _bucket_of(self, tenant) -> Bucket:
        return next(b for b in self.buckets if b.capacity == tenant.bucket)

    def _req_status(self, req, conn) -> dict:
        t, err = self._find(req)
        if err:
            return err
        sched = self._bucket_of(t).scheduler
        return protocol.ok(
            tenant=t.tenant_id, status=t.status, t=t.t, t_final=t.t_final,
            steps=t.steps, lane=sched.lane_of(t.tenant_id),
            bucket=t.bucket, frames_total=t.frames_total,
            frames_pending=len(t.frames))

    def _req_stream(self, req, conn) -> dict:
        t, err = self._find(req)
        if err:
            return err
        limit = req.get("max_frames")
        # None = drain everything; an explicit 0 drains NOTHING (a client
        # probing eof/pending must not lose frames to a falsy check)
        limit = len(t.frames) if limit is None else int(limit)
        frames = [t.frames.popleft() for _ in range(min(limit, len(t.frames)))]
        t.frames_streamed += len(frames)
        self.metrics.note_frames_streamed(t.tenant_id, len(frames))
        eof = (t.status not in ("queued", "running")) and not t.frames
        return protocol.ok(tenant=t.tenant_id, frames=frames, eof=eof,
                           pending=len(t.frames))

    def _req_snapshot(self, req, conn) -> dict:
        t, err = self._find(req)
        if err:
            return err
        sched = self._bucket_of(t).scheduler
        lane = sched.lane_of(t.tenant_id)
        t_now = t.t
        if lane is not None:
            from ..ensemble.runner import lane_state

            state = lane_state(sched.ens.states, lane)
            frame = tenants_mod.state_snapshot(state, rng_state=t.rng_state)
            t_now = float(state.time)
        elif t.final_frame is not None:
            frame = t.final_frame
        else:
            # queued: its initial frame is the snapshot
            for spec in sched.queue:
                if spec.member_id == t.tenant_id:
                    frame = tenants_mod.state_snapshot(
                        spec.state, rng_state=t.rng_state)
                    break
            else:
                return protocol.error(
                    f"tenant {t.tenant_id!r} has no snapshot yet")
        return protocol.ok(tenant=t.tenant_id, frame=frame, t=t_now,
                           status=t.status)

    def _req_cancel(self, req, conn) -> dict:
        t, err = self._find(req)
        if err:
            return err
        self._release(t, reason="cancelled")
        return protocol.ok(tenant=t.tenant_id, status=t.status)

    def _release(self, tenant, reason: str):
        """Free whatever the tenant holds (lane or queue slot); terminal
        states pass through untouched."""
        sched = self._bucket_of(tenant).scheduler
        lane = sched.lane_of(tenant.tenant_id)
        if lane is not None:
            sched.evict(lane, reason=reason)  # _on_retire stamps the status
        else:
            spec = sched.unqueue(tenant.tenant_id)
            if spec is not None:
                import time

                # a queued member's spec state IS its resume point — keep it
                # as the snapshot (resumed submits buffer no initial frame,
                # so dropping the spec here would lose the tenant entirely)
                tenant.final_frame = tenants_mod.state_snapshot(
                    spec.state, rng_state=tenant.rng_state)
                tenant.t = float(spec.state.time)
                tenant.status = reason
                tenant.retired_at = time.monotonic()

    def evict_conn(self, conn):
        """Graceful eviction on client disconnect: every tenant the
        connection owns frees its lane/queue slot, keeping its final
        snapshot for a later resume."""
        with obs_tracer.use(self.tracer):
            for t in self.registry.of_conn(conn):
                if t.status in ("queued", "running"):
                    logger.info("serve: evicting tenant %s (disconnect)",
                                t.tenant_id)
                    self._release(t, reason="evicted")

    def _req_stats(self, req, conn) -> dict:
        stats = self.metrics.stats()
        stats.update(
            tenants=len(self.registry),
            buckets=[{"capacity": b.capacity, "lanes": b.scheduler.batch,
                      "live": b.scheduler.live,
                      "queued": len(b.scheduler.queue),
                      "warmed": b.warmed} for b in self.buckets])
        return protocol.ok(stats=stats)

    def _req_shutdown(self, req, conn) -> dict:
        self._shutdown = True
        return protocol.ok(shutdown=True)

    # ---------------------------------------------------------- socket loop

    def serve_forever(self, *, port_file: Optional[str] = None,
                      idle_wait_s: float = 0.05):
        """The TCP event loop (single thread): accept/read/answer pending
        client traffic, then run one batched round, repeat. Returns after a
        ``shutdown`` request."""
        import selectors
        import socket

        lsock = socket.create_server(
            (self.serve_cfg.host, self.serve_cfg.port))
        self.address = lsock.getsockname()
        if port_file:
            # atomic publish: spawners poll for this file to learn the port
            tmp = port_file + ".tmp"
            with open(tmp, "w") as fh:
                fh.write(f"{self.address[1]}\n")
            os.replace(tmp, port_file)
        logger.info("serve: listening on %s:%d", *self.address[:2])
        lsock.setblocking(False)
        sel = selectors.DefaultSelector()
        sel.register(lsock, selectors.EVENT_READ)
        decoders: dict = {}
        try:
            while not self._shutdown:
                # step-bound request latency: zero timeout while simulations
                # are live (service sockets between rounds), short block when
                # fully idle
                for key, _ in sel.select(0.0 if self.any_live()
                                         else idle_wait_s):
                    if key.fileobj is lsock:
                        conn, addr = lsock.accept()
                        # bounded sends: a client that stops reading its
                        # responses (full TCP window) must not freeze the
                        # single-threaded loop — the timeout surfaces as
                        # OSError and drops only that connection
                        conn.settimeout(self.serve_cfg.send_timeout_s)
                        sel.register(conn, selectors.EVENT_READ)
                        decoders[conn] = protocol.FrameDecoder()
                        logger.info("serve: client %s connected", addr)
                    else:
                        self._service_conn(key.fileobj, decoders, sel)
                    if self._shutdown:
                        break
                if not self._shutdown:
                    self.tick()
        finally:
            for conn in list(decoders):
                self._drop_conn(conn, decoders, sel)
            sel.unregister(lsock)
            lsock.close()
            sel.close()
            self.tracer.close()

    def _drop_conn(self, conn, decoders, sel):
        self.evict_conn(conn)
        decoders.pop(conn, None)
        try:
            sel.unregister(conn)
        except KeyError:
            pass
        conn.close()

    def _service_conn(self, conn, decoders, sel):
        try:
            data = conn.recv(1 << 16)
        except (ConnectionError, OSError):
            data = b""
        if not data:
            self._drop_conn(conn, decoders, sel)
            return
        try:
            payloads = decoders[conn].feed(data)
        except ValueError:
            self._drop_conn(conn, decoders, sel)
            return
        for payload in payloads:
            if not payload:
                # in-band goodbye (the listener protocol's terminate frame)
                self._drop_conn(conn, decoders, sel)
                return
            try:
                req = protocol.unpack_message(payload)
            except Exception:
                resp = protocol.error("undecodable msgpack request")
            else:
                resp = self.handle_request(req, conn=conn)
            buf = protocol.pack_message(resp)
            try:
                conn.sendall(protocol.HEADER.pack(len(buf)) + buf)
            except (ConnectionError, OSError):
                self._drop_conn(conn, decoders, sel)
                return
