"""The serve event loop: warm compiled lanes, tenants joining between rounds.

`SimulationServer` composes three existing subsystems into one long-lived
process (ROADMAP item 3):

* the ensemble continuous-batching scheduler steps B lanes as ONE compiled
  program and swaps members in/out without retracing
  (`ensemble.scheduler.EnsembleScheduler.admit/poll/evict`);
* the trajectory frame machinery encodes per-tenant frames + snapshots
  byte-compatible with every existing reader (`io.trajectory`);
* skelly-scope telemetry carries the SLO stream (`serve.metrics` folds the
  same events `/stats` reports from).

One thread, no locks: the socket loop services whatever client requests are
pending (admission, streaming, snapshots, eviction), then runs ONE batched
round over every bucket with live lanes, then returns to the sockets —
requests land exactly at round boundaries, which is also the only place the
scheduler allows lane churn. Latency per request is therefore bounded by
one batched step, and the solves never leave the device between rounds.

Capacity buckets: each configured capacity is one `EnsembleScheduler` whose
template pads the base config's fiber batch to that capacity. `warmup()`
compiles every bucket's program ONCE at startup (an idle-lane batched step
— all lanes masked inert); from then on every admission is pure leaf
substitution into a warm program, and any further compile event is a
warm-path retrace counted by `metrics.compiles_after_warm` (the acceptance
gate pins it at zero).
"""

from __future__ import annotations

import logging
import os
from typing import Optional

from ..obs import tracer as obs_tracer
from . import protocol, tenants as tenants_mod
from .metrics import ServeMetrics, StatsTracer

logger = logging.getLogger("skellysim_tpu")


class Bucket:
    """One capacity bucket: a padded template + its compiled lanes.

    ``key`` (`system.buckets.BucketKey`) is the compiled program's shape
    identity — per-group (fiber capacity, node capacity) pairs; admission
    tests scenes against it with `buckets.admits`. ``capacity`` remains
    the total fiber-slot count (the wire's integer bucket id)."""

    def __init__(self, capacity: int, template, scheduler, key=None):
        self.capacity = capacity
        self.template = template
        self.scheduler = scheduler
        self.key = key
        self.warmed = False


class SimulationServer:
    """The serve core, socket-free: `handle_request` + `tick`.

    Tests drive these directly; `serve_forever` wraps them in the TCP event
    loop. ``config`` is the server's run-config TOML path (or a parsed
    `schema.Config` plus an explicit ``serve_cfg``): its fibers/params
    define the compiled-program contract every tenant must match, its
    `[serve]` table sizes the service.
    """

    def __init__(self, config, *, serve_cfg=None, trace_path: str = None,
                 config_dir: str = ".", warmup: bool = True):
        from ..builder import build_simulation
        from ..config import schema
        from ..ensemble.runner import EnsembleRunner
        from ..ensemble.scheduler import EnsembleScheduler

        runtime_cfg = None
        if isinstance(config, (str, os.PathLike)):
            if serve_cfg is None:
                serve_cfg = schema.load_serve_config(str(config))
            runtime_cfg = schema.load_runtime_config(str(config))
            config_dir = os.path.dirname(os.path.abspath(config)) or "."
            config = schema.load_config(str(config))
        elif serve_cfg is None:
            serve_cfg = schema.ServeConfig()
        self.base_config = config
        self.serve_cfg = serve_cfg
        self.metrics = ServeMetrics()
        self.tracer = StatsTracer(self.metrics, trace_path)
        self.registry = tenants_mod.TenantRegistry()
        self._shutdown = False
        self.address = None
        #: write-ahead tenant journal ([serve] journal_path;
        #: serve.journal) — None when journaling is off
        self.journal = None
        self._rounds_since_checkpoint = 0

        from ..fibers import container as fc
        from ..system import buckets as bucket_mod

        system, base_state, _ = build_simulation(
            config, config_dir=config_dir, synthesize_body_precompute=True)
        self.di_enabled = system.params.dynamic_instability.n_nodes > 0
        if self.di_enabled:
            # dynamic-instability serving (docs/scenarios.md): the base
            # scene pre-allocates its fiber capacity rung (fiber-less DI
            # bases get the inert placeholder group), so bucket templates
            # carry the capacity the in-trace DI update flips masks over
            from ..scenarios import ensure_di_capacity

            base_state = ensure_di_capacity(base_state, system.params)
        if base_state.fibers is None:
            raise ValueError("serve needs a base config with fibers: they "
                             "define the compiled-program contract tenants "
                             "admit against")
        self.system = system
        # skelly-bucket: admission buckets derive from the ONE shape policy
        # ([runtime] ladders of the server's config); [serve]
        # bucket_capacities remains the manual single-resolution override
        self.policy = bucket_mod.BucketPolicy.from_runtime(runtime_cfg)
        # spectral grid rungs are plan data, not state shapes — they ride
        # the System (cli.py does the same for single runs)
        system.grid_ladder = self.policy.grid_ladder
        base_n = self._fiber_count(base_state)
        single = isinstance(base_state.fibers, fc.FiberGroup)
        caps = sorted(set(serve_cfg.bucket_capacities))
        if caps and not single:
            raise ValueError(
                "[serve] bucket_capacities applies to single-resolution "
                "base configs; a mixed-resolution base derives its one "
                "bucket from the [runtime] ladders")
        if caps and caps[0] < base_n:
            raise ValueError(
                f"[serve] bucket_capacities {caps} below the base config's "
                f"fiber count {base_n}; buckets PAD the base scene, so every "
                "capacity must be >= it")
        if not caps:
            if single:
                if (serve_cfg.bucket_count > 1
                        and not self.policy.fiber_ladder):
                    # identity policy: "the next rung" would be n+1, n+2...
                    # — one warmup compile per single extra fiber slot, the
                    # exact waste this subsystem exists to avoid
                    raise ValueError(
                        "[serve] bucket_count > 1 needs a fiber ladder to "
                        "take rungs from; set [runtime] bucket_ladder "
                        "(e.g. [-1] for the geometric ladder) or list "
                        "[serve] bucket_capacities explicitly")
                # bucket_count policy-ladder rungs, starting at the base
                # scene's own rung
                caps = [self.policy.fiber_capacity(base_n)]
                for _ in range(serve_cfg.bucket_count - 1):
                    caps.append(self.policy.fiber_capacity(caps[-1] + 1))
            else:
                caps = [None]   # one bucket at the tuple base's policy key
        self.buckets: list[Bucket] = []
        for cap in caps:
            template, key = bucket_mod.bucketize(
                base_state, self.policy, fiber_capacity=cap,
                pair_evaluator=system.params.pair_evaluator)
            runner = EnsembleRunner(system, batch_impl=serve_cfg.batch_impl)
            sched = EnsembleScheduler(
                runner, [], serve_cfg.max_lanes, template=template,
                writer=self._on_frame, metrics=self._on_sched_event,
                on_retire=self._on_retire, on_dt_underflow="retire",
                on_failure="retire", on_growth="retire")
            self.buckets.append(Bucket(
                sum(c for c, _ in key.fibers), template, sched, key=key))
        self.buckets.sort(key=lambda b: b.capacity)
        if warmup:
            self.warmup()
        if serve_cfg.journal_path:
            from .journal import TenantJournal

            # recover BEFORE opening for write: replay wants the file as
            # the dead server left it
            recovered = self._recover_from_journal(serve_cfg.journal_path)
            if len(self.registry):
                # COMPACT on recovery: rewrite latest-entry-per-tenant
                # (live tenants at their recovery snapshots, terminal ones
                # with their final frames) into a sibling file, then
                # atomically replace the old journal — unbounded append
                # growth resets at every restart, and a crash mid-compact
                # still finds the complete old journal in place. The open
                # fh keeps writing the replaced inode, which IS the file
                # now at journal_path.
                tmp = serve_cfg.journal_path + ".compact"
                self.journal = TenantJournal(tmp, truncate=True)
                live_frames = {t.tenant_id: f for t, f in recovered}
                for t in list(self.registry.values()):
                    if t.tenant_id in live_frames:
                        self._journal_record("checkpoint", t,
                                             frame=live_frames[t.tenant_id])
                    else:
                        self._journal_record("retire", t,
                                             frame=t.final_frame)
                os.replace(tmp, serve_cfg.journal_path)
                self.journal.path = serve_cfg.journal_path
            else:
                self.journal = TenantJournal(serve_cfg.journal_path)

    @staticmethod
    def _fiber_count(state) -> int:
        from ..fibers import container as fc

        return sum(g.n_fibers for g in fc.as_buckets(state.fibers))

    # ----------------------------------------------------------- warm path

    def warmup(self):
        """Compile every bucket's batched program on its idle template lanes
        (all masked inert — one cheap round each), then arm the
        zero-compiles-after-warmup gate."""
        with obs_tracer.use(self.tracer):
            for b in self.buckets:
                if not b.warmed:
                    # pure call, result discarded: compiles (and emits the
                    # compile event) without advancing the idle lanes
                    b.scheduler.step_fn(b.scheduler.ens)
                    b.warmed = True
            self.metrics.mark_warm()
        logger.info("serve: %d bucket program(s) warm (capacities %s)",
                    len(self.buckets), [b.capacity for b in self.buckets])

    def tick(self) -> bool:
        """One batched round over every bucket with live lanes; True when
        any stepping happened (the socket loop's idle signal)."""
        did = False
        with obs_tracer.use(self.tracer):
            for b in self.buckets:
                if b.scheduler.live:
                    b.scheduler.poll()
                    did = True
        if did and self.journal is not None:
            # journal checkpoint cadence: every journal_every rounds, one
            # snapshot per seated tenant — the bound on post-crash replay
            self._rounds_since_checkpoint += 1
            if self._rounds_since_checkpoint >= self.serve_cfg.journal_every:
                self._rounds_since_checkpoint = 0
                self._checkpoint_live()
        self._expire_records()
        return did

    def _expire_records(self):
        """Bounded tenant-record retention (`[serve] record_ttl_s`):
        terminal records expire `ttl` after retirement — a long-lived
        server under sustained traffic must not grow its registry (and the
        final-frame snapshots it holds) without bound. Runs on every tick
        AND every request, so idle servers expire too."""
        import time

        dead = self.registry.expire(self.serve_cfg.record_ttl_s,
                                    time.monotonic())
        for tid in dead:
            logger.info("serve: tenant record %s expired (record_ttl_s=%g)",
                        tid, self.serve_cfg.record_ttl_s)

    def any_live(self) -> bool:
        return any(b.scheduler.live for b in self.buckets)

    # --------------------------------------------- write-ahead journal

    def _journal_record(self, kind: str, tenant, *, frame=None):
        if self.journal is None:
            return
        self.journal.record(kind, tenant.tenant_id, bucket=tenant.bucket,
                            t_final=tenant.t_final, status=tenant.status,
                            frame=frame, health=tenant.health, t=tenant.t,
                            flight=tenant.flight)

    def _checkpoint_live(self):
        """One journal snapshot per seated tenant (queued tenants' admit
        snapshots are already current — they have not stepped)."""
        from ..ensemble.runner import lane_state

        for b in self.buckets:
            sched = b.scheduler
            for lane, ln in enumerate(sched.lanes):
                if ln is None:
                    continue
                t = self._tenant(ln.spec.member_id)
                if t is None:
                    continue
                state = lane_state(sched.ens.states, lane)
                frame = tenants_mod.state_snapshot(state,
                                                   rng_state=t.rng_state)
                self._journal_record("checkpoint", t, frame=frame)

    def _recover_from_journal(self, path: str) -> list:
        """Replay ``path`` and rebuild the tenant registry: live tenants
        re-admit from their latest snapshot (<= journal_every rounds of
        replay), terminal ones restore their record + final frame so
        clients can still fetch status/snapshot. Returns [(tenant,
        frame_bytes)] for the re-admitted set."""
        import time

        from ..ensemble.scheduler import MemberSpec
        from ..utils.rng import SimRNG
        from . import journal as journal_mod

        entries = journal_mod.replay(path)
        if not entries:
            return []
        recovered = []
        with obs_tracer.use(self.tracer):
            for tid, entry in entries.items():
                status = entry.get("status", "finished")
                frame = entry.get("frame")
                bucket = next((b for b in self.buckets
                               if b.capacity == entry.get("bucket")), None)
                tenant = tenants_mod.Tenant(
                    tenant_id=tid, bucket=int(entry.get("bucket", 0)),
                    t_final=float(entry.get("t_final", 0.0)),
                    t=float(entry.get("t", 0.0)),
                    health=int(entry.get("health", 0)),
                    # a failed tenant's blast radius survives the restart
                    # (journaled at retirement — `status` keeps answering
                    # the provenance after recovery)
                    flight=entry.get("flight"))
                live = (status in journal_mod.LIVE_STATES and frame
                        and bucket is not None)
                if live:
                    # one bad entry must not make the server UNBOOTABLE on
                    # its own journal (the exact outcome the WAL exists to
                    # prevent): a snapshot that no longer decodes against
                    # this server's template (scene config changed at the
                    # same capacity, bitrot) degrades to the terminal
                    # restore below, like the bucket-mismatch case
                    try:
                        state, rng_state = tenants_mod.state_from_snapshot(
                            bytes(frame), bucket.template)
                        state = tenants_mod.pad_state_to_capacity(
                            state, bucket.key)
                        mismatch = tenants_mod.bucket_mismatch(
                            bucket.template, state)
                        if mismatch:
                            raise ValueError(mismatch)
                        if self.di_enabled and not rng_state:
                            raise ValueError(
                                "DI tenant snapshot lacks rng_state")
                    except Exception as e:
                        logger.warning(
                            "serve: journal tenant %s snapshot does not "
                            "re-admit (%s) — restored as evicted", tid, e)
                        live = False
                if live:
                    tenant.rng_state = rng_state
                    tenant.t = float(state.time)
                    self.registry.add(tenant)
                    bucket.scheduler.admit(MemberSpec(
                        member_id=tid, state=state, t_final=tenant.t_final,
                        rng=(SimRNG.from_state(rng_state)
                             if rng_state else None)))
                    recovered.append((
                        tenant,
                        tenants_mod.state_snapshot(state,
                                                   rng_state=rng_state)))
                    logger.info("serve: tenant %s re-admitted from journal "
                                "(t=%.6g)", tid, tenant.t)
                else:
                    if status in journal_mod.LIVE_STATES:
                        # a live-status record we CANNOT re-admit (bucket
                        # capacities changed across the restart, the entry
                        # never carried a snapshot, or the snapshot failed
                        # to decode above): restoring it as "running"
                        # would leave a zombie no scheduler drives —
                        # clients polling wait()/status would hang on it
                        # forever. Terminal-evict instead; the last
                        # snapshot (if any) stays fetchable.
                        logger.warning(
                            "serve: journal tenant %s (bucket %s) not "
                            "re-admitted on buckets %s — restored as "
                            "evicted", tid, entry.get("bucket"),
                            [b.capacity for b in self.buckets])
                        tenant.status = "evicted"
                    else:
                        tenant.status = (status if status
                                         in tenants_mod.TENANT_STATES
                                         else "finished")
                    tenant.final_frame = bytes(frame) if frame else None
                    tenant.retired_at = time.monotonic()
                    self.registry.add(tenant)
            self.tracer.emit("journal", action="recover",
                             tenants=len(entries), live=len(recovered))
        logger.info("serve: journal recovery: %d record(s), %d live "
                    "tenant(s) re-admitted", len(entries), len(recovered))
        return recovered

    # ------------------------------------------------- scheduler callbacks

    def _tenant(self, member_id: str):
        return self.registry.get(member_id)

    def _on_frame(self, member_id: str, state, *, rng_state=None):
        t = self._tenant(member_id)
        if t is not None:
            t.frames.append(tenants_mod.state_snapshot(state,
                                                       rng_state=rng_state))
            t.frames_total += 1
            if rng_state is not None:
                # DI tenants advance their stream in-trace; keep the record
                # current so checkpoints/snapshots resume the exact counters
                t.rng_state = rng_state

    def _on_retire(self, member_id: str, state, reason: str, **extra):
        import time

        t = self._tenant(member_id)
        if extra.get("rng_state") is not None and t is not None:
            t.rng_state = extra["rng_state"]
        if reason == "growth":
            # not a terminal retirement: the tenant's nucleation outgrew
            # its capacity bucket — reseat onto the next bucket rung
            # (docs/scenarios.md "Growth reseats")
            self._grow_tenant(member_id, state, extra)
            return
        if t is not None:
            t.final_frame = tenants_mod.state_snapshot(
                state, rng_state=t.rng_state)
            t.t = float(state.time)
            t.status = reason if reason in tenants_mod.TENANT_STATES \
                else "finished"
            t.health |= int(extra.get("health", 0))
            if extra.get("flight") is not None:
                # skelly-flight blast radius (failed/dt_underflow retires):
                # the ring tail + provenance, surfaced via `status`
                t.flight = extra["flight"]
            t.retired_at = time.monotonic()   # [serve] record_ttl_s clock
            # terminal journal entry: the final snapshot + verdict, so a
            # restarted server still answers status/snapshot for this
            # tenant (and knows NOT to re-admit it)
            self._journal_record("retire", t, frame=t.final_frame)

    def _grow_tenant(self, member_id: str, state, extra: dict):
        """Reseat a DI tenant whose nucleation outgrew its bucket onto the
        next capacity bucket; with no larger bucket the tenant terminates
        as ``evicted`` (its current snapshot stays fetchable — resubmit it
        to a server with bigger buckets)."""
        import time

        from ..ensemble.scheduler import MemberSpec
        from ..system import buckets as bucket_mod
        from ..utils.rng import SimRNG

        t = self._tenant(member_id)
        if t is None:
            return
        nxt = next((b for b in self.buckets
                    if b.capacity > t.bucket
                    and bucket_mod.admits(b.key, state)), None)
        if nxt is None:
            self.tracer.emit("fault", kind="growth_overflow",
                             member=member_id, bucket=t.bucket)
            logger.warning(
                "serve: tenant %s outgrew the largest bucket (%d slots) — "
                "evicting with its current snapshot", member_id, t.bucket)
            t.status = "evicted"
            t.t = float(state.time)
            t.final_frame = tenants_mod.state_snapshot(
                state, rng_state=t.rng_state)
            t.retired_at = time.monotonic()
            self._journal_record("retire", t, frame=t.final_frame)
            return
        grown = bucket_mod.bucketize_to(state, nxt.key)
        old = t.bucket
        t.bucket = nxt.capacity
        rng = (SimRNG.from_state(t.rng_state) if t.rng_state else None)
        nxt.scheduler.admit(MemberSpec(member_id=member_id, state=grown,
                                       t_final=t.t_final, rng=rng))
        self._journal_record(
            "checkpoint", t,
            frame=tenants_mod.state_snapshot(grown, rng_state=t.rng_state))
        logger.info("serve: tenant %s reseated bucket %d -> %d",
                    member_id, old, nxt.capacity)

    def _on_sched_event(self, rec: dict):
        t = self._tenant(rec.get("member", ""))
        if t is None:
            return
        ev = rec.get("event")
        if ev == "start":
            t.status = "running"
        elif ev == "step":
            t.steps = int(rec["step"]) + 1
            t.t = float(rec["t"])
            # the per-step solver verdicts — previously these died in the
            # metrics JSONL; now they accumulate on the tenant record and
            # surface through `status`/`stats` (docs/robustness.md)
            t.health |= int(rec.get("health", 0))
            if rec.get("loss_of_accuracy"):
                t.loss_of_accuracy_steps += 1
                self.metrics.note_loss_of_accuracy()

    # ------------------------------------------------------------ requests

    def handle_request(self, req, conn=None) -> dict:
        """One request dict -> one response dict (never raises: admission
        rejections and malformed requests answer structured errors — one
        bad client must not kill the service)."""
        err = protocol.validate_request(req)
        if err:
            return protocol.error(err)
        self._expire_records()
        handler = getattr(self, f"_req_{req['type']}")
        try:
            with obs_tracer.use(self.tracer):
                return handler(req, conn)
        except Exception as e:  # defense for the event loop
            logger.exception("serve: %s request failed", req.get("type"))
            return protocol.error(f"{type(e).__name__}: {e}")

    def _req_submit(self, req, conn) -> dict:
        from ..builder import build_simulation
        from ..utils.rng import SimRNG

        if all(b.scheduler.live >= b.scheduler.batch
               and len(b.scheduler.queue) >= self.serve_cfg.queue_depth
               for b in self.buckets):
            # shed BEFORE the host-side scene build: a saturated server must
            # not pay build_simulation per rejected retry (overload is
            # exactly when the event loop can least afford it)
            self.metrics.note_rejected()
            return protocol.error(
                "admission queue full on every bucket — retry later",
                retry=True)
        try:
            cfg = tenants_mod.parse_tenant_config(req["config"],
                                                  di_enabled=self.di_enabled)
        except ValueError as e:
            self.metrics.note_rejected()
            return protocol.error(str(e))
        err = tenants_mod.check_params_contract(cfg.params,
                                                self.base_config.params)
        if err:
            self.metrics.note_rejected()
            return protocol.error(err)
        _, state, rng = build_simulation(cfg,
                                         synthesize_body_precompute=True)
        if self.di_enabled:
            # fiber-less DI scenes get the inert placeholder group (capacity
            # 1 here — bucketize_to below pads to the admitted bucket's)
            from ..scenarios import ensure_di_capacity

            try:
                state = ensure_di_capacity(state, self.system.params,
                                           capacity=1)
            except ValueError as e:
                self.metrics.note_rejected()
                return protocol.error(str(e))

        # capacity-bucket selection: smallest bucket whose key admits the
        # scene (per-group fiber AND node capacities — `buckets.admits`)
        from ..system import buckets as bucket_mod

        nearest = self.buckets[-1]
        bucket = next((b for b in self.buckets
                       if bucket_mod.admits(b.key, state)), None)
        if bucket is not None:
            try:
                state = bucket_mod.bucketize_to(state, bucket.key)
                if req.get("resume_frame") is not None:
                    # rebuild from the snapshot frame over the fresh state,
                    # then re-pad (frames carry ACTIVE fibers and LIVE node
                    # rows only); the frame's serialized RNG streams resume
                    # too, like cli's --resume
                    state, rng_state = tenants_mod.state_from_snapshot(
                        bytes(req["resume_frame"]), state)
                    if rng_state:
                        rng = SimRNG.from_state(rng_state)
                    state = bucket_mod.bucketize_to(state, bucket.key)
            except ValueError as e:
                bucket, mismatch = None, str(e)
            else:
                mismatch = tenants_mod.bucket_mismatch(
                    bucket.template, state,
                    nearest=nearest.key.describe())
        else:
            mismatch = (f"scene shape {bucket_mod.state_key(state).describe()}"
                        f" fits no bucket")
        if bucket is None or mismatch:
            self.metrics.note_rejected()
            # structured rejection: the nearest admissible bucket rides the
            # error payload so clients can resize/re-target instead of
            # parsing a raw leaf-shape string (docs/serving.md)
            return protocol.error(
                "no capacity bucket matches this scene: " + mismatch
                + f" (bucket capacities: {[b.capacity for b in self.buckets]})",
                nearest_bucket={
                    "capacity": nearest.capacity,
                    "bucket": nearest.key.describe(),
                    "fibers": [list(p) for p in nearest.key.fibers]})

        sched = bucket.scheduler
        if (sched.live >= sched.batch
                and len(sched.queue) >= self.serve_cfg.queue_depth):
            self.metrics.note_rejected()
            return protocol.error(
                f"admission queue full ({len(sched.queue)} waiting, "
                f"{sched.batch} lanes busy) — retry later", retry=True)

        tid = req.get("tenant") or self.registry.new_id()
        if self.registry.get(tid) is not None:
            self.metrics.note_rejected()
            return protocol.error(f"tenant id {tid!r} already exists")
        # explicit None check: a client-requested t_final of 0.0 means "admit
        # and stop immediately", not "use the config's"
        t_final = float(cfg.params.t_final if req.get("t_final") is None
                        else req["t_final"])
        tenant = tenants_mod.Tenant(
            tenant_id=tid, bucket=bucket.capacity, t_final=t_final,
            conn=conn, t=float(state.time),
            rng_state=rng.dump_state() if rng is not None else None)
        self.registry.add(tenant)
        # WRITE-AHEAD: journal the admission (with the admitted state as
        # the first snapshot) BEFORE seating — a crash from here on must
        # re-admit this tenant on restart
        self._journal_record(
            "admit", tenant,
            frame=tenants_mod.state_snapshot(state,
                                             rng_state=tenant.rng_state))
        if req.get("resume_frame") is None:
            # the initial-config frame, like a fresh CLI run (resumed
            # tenants skip it, like `--resume` appends)
            self._on_frame(tid, state, rng_state=tenant.rng_state)

        from ..ensemble.scheduler import MemberSpec

        lane = sched.admit(MemberSpec(member_id=tid, state=state,
                                      t_final=t_final, rng=rng))
        logger.info("serve: tenant %s -> bucket %d %s", tid, bucket.capacity,
                    f"lane {lane}" if lane is not None else "queued")
        return protocol.ok(tenant=tid, bucket=bucket.capacity,
                           status=tenant.status, lane=lane,
                           queued=lane is None)

    def _find(self, req):
        t = self.registry.get(req["tenant"])
        if t is None:
            return None, protocol.error(f"unknown tenant {req['tenant']!r}")
        return t, None

    def _bucket_of(self, tenant) -> Optional[Bucket]:
        """None for a journal-recovered tenant whose bucket no longer
        exists on this server (restored terminal — it holds no lane)."""
        return next((b for b in self.buckets
                     if b.capacity == tenant.bucket), None)

    def _req_status(self, req, conn) -> dict:
        from ..guard import verdict as _verdict

        t, err = self._find(req)
        if err:
            return err
        bucket = self._bucket_of(t)
        return protocol.ok(
            tenant=t.tenant_id, status=t.status, t=t.t, t_final=t.t_final,
            steps=t.steps,
            lane=(bucket.scheduler.lane_of(t.tenant_id)
                  if bucket is not None else None),
            bucket=t.bucket, frames_total=t.frames_total,
            frames_pending=len(t.frames),
            # solver-health surfacing (docs/robustness.md): the packed
            # word + decoded bit names, plus the two flags that used to
            # die in the metrics JSONL
            health=t.health, verdict=_verdict.decode(t.health),
            loss_of_accuracy_steps=t.loss_of_accuracy_steps,
            dt_underflow=(t.status == "dt_underflow"
                          or bool(t.health & _verdict.DT_UNDERFLOW)),
            # skelly-flight: the last-window diagnostics tail + anomaly
            # provenance for failed tenants (None while healthy or with
            # the recorder off — docs/observability.md "Flight recorder")
            flight=t.flight)

    def _req_stream(self, req, conn) -> dict:
        t, err = self._find(req)
        if err:
            return err
        limit = req.get("max_frames")
        # None = drain everything; an explicit 0 drains NOTHING (a client
        # probing eof/pending must not lose frames to a falsy check)
        limit = len(t.frames) if limit is None else int(limit)
        # the drain rides ONE `stream_frames` span: frames-streamed
        # accounting AND the frame-stream latency histogram both fold from
        # it in ServeMetrics.observe (no second bookkeeping path), and a
        # service --trace-file shows per-tenant streaming under summarize
        with obs_tracer.span("stream_frames", tenant=t.tenant_id) as sp:
            frames = [t.frames.popleft()
                      for _ in range(min(limit, len(t.frames)))]
            t.frames_streamed += len(frames)
            sp.note(frames=len(frames))
        eof = (t.status not in ("queued", "running")) and not t.frames
        return protocol.ok(tenant=t.tenant_id, frames=frames, eof=eof,
                           pending=len(t.frames))

    def _req_snapshot(self, req, conn) -> dict:
        t, err = self._find(req)
        if err:
            return err
        bucket = self._bucket_of(t)
        # a recovered tenant whose bucket is gone holds no lane/queue slot;
        # its final_frame (if journaled) is still served below
        sched = bucket.scheduler if bucket is not None else None
        lane = sched.lane_of(t.tenant_id) if sched is not None else None
        t_now = t.t
        if lane is not None:
            from ..ensemble.runner import lane_state

            state = lane_state(sched.ens.states, lane)
            frame = tenants_mod.state_snapshot(state, rng_state=t.rng_state)
            t_now = float(state.time)
        elif t.final_frame is not None:
            frame = t.final_frame
        else:
            # queued: its initial frame is the snapshot
            for spec in (sched.queue if sched is not None else ()):
                if spec.member_id == t.tenant_id:
                    frame = tenants_mod.state_snapshot(
                        spec.state, rng_state=t.rng_state)
                    break
            else:
                return protocol.error(
                    f"tenant {t.tenant_id!r} has no snapshot yet")
        return protocol.ok(tenant=t.tenant_id, frame=frame, t=t_now,
                           status=t.status)

    def _req_cancel(self, req, conn) -> dict:
        t, err = self._find(req)
        if err:
            return err
        self._release(t, reason="cancelled")
        return protocol.ok(tenant=t.tenant_id, status=t.status)

    def _release(self, tenant, reason: str):
        """Free whatever the tenant holds (lane or queue slot); terminal
        states pass through untouched (incl. recovered tenants whose
        bucket no longer exists — they hold nothing to free)."""
        bucket = self._bucket_of(tenant)
        if bucket is None:
            return
        sched = bucket.scheduler
        lane = sched.lane_of(tenant.tenant_id)
        if lane is not None:
            sched.evict(lane, reason=reason)  # _on_retire stamps the status
        else:
            spec = sched.unqueue(tenant.tenant_id)
            if spec is not None:
                import time

                # a queued member's spec state IS its resume point — keep it
                # as the snapshot (resumed submits buffer no initial frame,
                # so dropping the spec here would lose the tenant entirely)
                tenant.final_frame = tenants_mod.state_snapshot(
                    spec.state, rng_state=tenant.rng_state)
                tenant.t = float(spec.state.time)
                tenant.status = reason
                tenant.retired_at = time.monotonic()
                self._journal_record("retire", tenant,
                                     frame=tenant.final_frame)

    def evict_conn(self, conn):
        """Graceful eviction on client disconnect: every tenant the
        connection owns frees its lane/queue slot, keeping its final
        snapshot for a later resume."""
        with obs_tracer.use(self.tracer):
            for t in self.registry.of_conn(conn):
                if t.status in ("queued", "running"):
                    logger.info("serve: evicting tenant %s (disconnect)",
                                t.tenant_id)
                    self._release(t, reason="evicted")

    def _req_stats(self, req, conn) -> dict:
        stats = self.metrics.stats()
        stats.update(
            tenants=len(self.registry),
            journal=bool(self.journal is not None),
            buckets=[{"capacity": b.capacity, "lanes": b.scheduler.batch,
                      "live": b.scheduler.live,
                      "queued": len(b.scheduler.queue),
                      "warmed": b.warmed} for b in self.buckets])
        return protocol.ok(stats=stats)

    def _req_chaos(self, req, conn) -> dict:
        """Fault injection (guard.chaos) — config-gated: a production
        server rejects these outright."""
        if not self.serve_cfg.chaos_enabled:
            return protocol.error(
                "chaos requests are disabled ([serve] chaos_enabled)")
        action = req.get("action")
        if action == "nan_lane":
            from ..guard import chaos as chaos_mod

            if "tenant" not in req:
                return protocol.error("chaos action 'nan_lane' needs a "
                                      "tenant field")
            t, err = self._find(req)
            if err:
                return err
            bucket = self._bucket_of(t)
            if bucket is None:
                return protocol.error(
                    f"tenant {t.tenant_id!r} holds no lane on this server")
            try:
                lane = chaos_mod.nan_lane_of(bucket.scheduler, t.tenant_id)
            except ValueError as e:
                return protocol.error(str(e))
            self.tracer.emit("fault", kind="chaos_nan", tenant=t.tenant_id,
                             lane=lane)
            logger.warning("serve: CHAOS nan injected into tenant %s "
                           "(lane %d)", t.tenant_id, lane)
            return protocol.ok(tenant=t.tenant_id, lane=lane,
                               action=action)
        return protocol.error(f"unknown chaos action {action!r}; "
                              "valid actions: nan_lane")

    def _req_shutdown(self, req, conn) -> dict:
        self._shutdown = True
        return protocol.ok(shutdown=True)

    # ---------------------------------------------------------- socket loop

    def serve_forever(self, *, port_file: Optional[str] = None,
                      idle_wait_s: float = 0.05):
        """The TCP event loop (single thread): accept/read/answer pending
        client traffic, then run one batched round, repeat. Returns after a
        ``shutdown`` request."""
        import selectors
        import socket

        lsock = socket.create_server(
            (self.serve_cfg.host, self.serve_cfg.port))
        self.address = lsock.getsockname()
        if port_file:
            # atomic publish: spawners poll for this file to learn the port
            tmp = port_file + ".tmp"
            with open(tmp, "w") as fh:
                fh.write(f"{self.address[1]}\n")
            os.replace(tmp, port_file)
        logger.info("serve: listening on %s:%d", *self.address[:2])
        lsock.setblocking(False)
        sel = selectors.DefaultSelector()
        sel.register(lsock, selectors.EVENT_READ)
        decoders: dict = {}
        try:
            while not self._shutdown:
                # step-bound request latency: zero timeout while simulations
                # are live (service sockets between rounds), short block when
                # fully idle
                for key, _ in sel.select(0.0 if self.any_live()
                                         else idle_wait_s):
                    if key.fileobj is lsock:
                        conn, addr = lsock.accept()
                        # bounded sends: a client that stops reading its
                        # responses (full TCP window) must not freeze the
                        # single-threaded loop — the timeout surfaces as
                        # OSError and drops only that connection
                        conn.settimeout(self.serve_cfg.send_timeout_s)
                        sel.register(conn, selectors.EVENT_READ)
                        decoders[conn] = protocol.FrameDecoder(
                            max_frame_bytes=self.serve_cfg.max_frame_bytes)
                        logger.info("serve: client %s connected", addr)
                    else:
                        self._service_conn(key.fileobj, decoders, sel)
                    if self._shutdown:
                        break
                if not self._shutdown:
                    self.tick()
        finally:
            for conn in list(decoders):
                self._drop_conn(conn, decoders, sel)
            sel.unregister(lsock)
            lsock.close()
            sel.close()
            if self.journal is not None:
                self.journal.close()
            self.tracer.close()

    def _drop_conn(self, conn, decoders, sel):
        self.evict_conn(conn)
        decoders.pop(conn, None)
        try:
            sel.unregister(conn)
        except KeyError:
            pass
        conn.close()

    def _service_conn(self, conn, decoders, sel):
        try:
            data = conn.recv(1 << 16)
        except (ConnectionError, OSError):
            data = b""
        if not data:
            self._drop_conn(conn, decoders, sel)
            return
        payloads = decoders[conn].feed(data)
        for payload in payloads:
            if isinstance(payload, protocol.OversizedFrame):
                # a hostile/corrupt header must cost a structured error,
                # not the connection (docs/robustness.md): the decoder
                # skips the declared bytes and resynchronizes
                self.tracer.emit("fault", kind="frame_oversized",
                                 size=payload.size,
                                 limit=self.serve_cfg.max_frame_bytes)
                logger.warning("serve: oversized frame header (%d bytes > "
                               "max_frame_bytes %d) — answered error, "
                               "connection kept", payload.size,
                               self.serve_cfg.max_frame_bytes)
                resp = protocol.error(
                    f"frame of {payload.size} bytes exceeds this server's "
                    f"max_frame_bytes ({self.serve_cfg.max_frame_bytes})",
                    oversized=True)
            elif not payload:
                # in-band goodbye (the listener protocol's terminate frame)
                self._drop_conn(conn, decoders, sel)
                return
            else:
                try:
                    req = protocol.unpack_message(payload)
                except Exception:
                    # garbled but well-framed bytes: structured error, the
                    # connection survives (round-trip pinned in
                    # tests/test_serve.py)
                    self.tracer.emit("fault", kind="frame_undecodable",
                                     size=len(payload))
                    resp = protocol.error("undecodable msgpack request")
                else:
                    resp = self.handle_request(req, conn=conn)
            buf = protocol.pack_message(resp)
            try:
                conn.sendall(protocol.HEADER.pack(len(buf)) + buf)
            except (ConnectionError, OSError):
                self._drop_conn(conn, decoders, sel)
                return
