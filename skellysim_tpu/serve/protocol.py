"""Wire protocol: length-prefixed msgpack framing + the serve request schema.

One source of truth for the framing both servers speak: a message is a
little-endian u64 byte count followed by that many bytes of msgpack — the
reference listener protocol (`/root/reference/src/core/listener.cpp:86-136`),
unchanged. `listener.py` (the reference's blocking post-processing server)
and the skelly-serve simulation service both read/write through the helpers
here, so a framing fix lands in every surface at once; `io.listener_client`
shares them from the client side.

Frame semantics (the reference's, kept):

* a ZERO-LENGTH frame is in-band control — "terminate" from a client,
  "invalid request" from a server;
* EOF mid-frame means the peer went away (`read_frame` returns None — never
  an exception, disconnects are an expected event for a server).

On top of the framing, this module defines the serve request/response
schema (`REQUEST_FIELDS`): every request is a msgpack map with a ``type``
key; every response is a map with an ``ok`` bool (error text under
``error`` when False). Arrays cross the wire in the reference's
``__eigen__`` encoding (`io.eigen`), trajectory frames as raw
trajectory-v1 msgpack bytes — a streamed frame is byte-identical to the
same frame in a `.out` file, so every existing reader works on it.

Import discipline: jax-free (msgpack + numpy only) — clients must be able
to import this without paying JAX backend init.
"""

from __future__ import annotations

import struct
from typing import Optional

import msgpack
import numpy as np

from ..io import eigen

#: little-endian u64 frame header (`listener.cpp:92`)
HEADER = struct.Struct("<Q")

#: sanity bound on one frame (a corrupt/hostile header must not make a
#: server try to buffer exabytes); generous vs real payloads — a 10k-fiber
#: 32-node f64 frame is ~8 MB
MAX_FRAME_BYTES = 1 << 31


def _ndencode(obj):
    if isinstance(obj, np.ndarray):
        return eigen.pack_matrix(obj)
    return obj


def pack_message(obj) -> bytes:
    """Message dict -> msgpack bytes (ndarrays via the ``__eigen__`` wire
    encoding, like every trajectory payload)."""
    return msgpack.packb(obj, default=_ndencode)


def unpack_message(buf: bytes) -> dict:
    """msgpack bytes -> message dict with ``__eigen__``/``__quat__`` wire
    payloads decoded back to arrays."""
    return eigen.decode_tree(msgpack.unpackb(buf, raw=False))


# ------------------------------------------------------------ stream framing

def write_frame(stream, payload: bytes) -> None:
    """One framed message (header + payload) to a file-like stream."""
    if len(payload) > MAX_FRAME_BYTES:
        raise ValueError(f"frame of {len(payload)} bytes exceeds "
                         f"MAX_FRAME_BYTES ({MAX_FRAME_BYTES})")
    stream.write(HEADER.pack(len(payload)))
    if payload:
        stream.write(payload)
    stream.flush()


def write_empty(stream) -> None:
    """The in-band zero-length frame (terminate / invalid-request)."""
    write_frame(stream, b"")


def read_frame(stream) -> Optional[bytes]:
    """One framed payload from a file-like stream.

    Returns the payload bytes (``b""`` for the in-band zero-length frame) or
    None when the peer closed the stream at a frame boundary or mid-frame —
    a disconnect is an expected event, not an exception."""
    hdr = stream.read(HEADER.size)
    if hdr is None or len(hdr) < HEADER.size:
        return None
    (size,) = HEADER.unpack(hdr)
    if size == 0:
        return b""
    if size > MAX_FRAME_BYTES:
        raise ValueError(f"incoming frame header claims {size} bytes "
                         f"(> MAX_FRAME_BYTES {MAX_FRAME_BYTES})")
    chunks = []
    remaining = size
    while remaining:
        chunk = stream.read(remaining)
        if not chunk:
            return None
        chunks.append(chunk)
        remaining -= len(chunk)
    return b"".join(chunks)


def write_message(stream, obj) -> None:
    write_frame(stream, pack_message(obj))


def read_message(stream) -> Optional[dict]:
    """One message from a stream; None on disconnect, ``{}``-falsy empty
    dict NEVER happens (a zero-length frame decodes to None too — callers
    that must distinguish control frames use `read_frame` directly, like
    `listener.serve`)."""
    buf = read_frame(stream)
    if not buf:
        return None
    return unpack_message(buf)


class OversizedFrame:
    """Sentinel yielded by `FrameDecoder.feed` for a frame whose header
    exceeds the decoder's byte bound: the server answers a structured
    error and the CONNECTION SURVIVES (the decoder consumes the frame's
    declared bytes as they arrive, then resynchronizes on the next
    header) — before skelly-guard, any oversized header dropped the
    client outright."""

    __slots__ = ("size",)

    def __init__(self, size: int):
        self.size = size

    def __repr__(self):
        return f"OversizedFrame(size={self.size})"


class FrameDecoder:
    """Incremental framing for non-blocking sockets.

    ``feed(data)`` buffers arbitrary byte chunks and returns every COMPLETE
    frame payload they finish (zero-length control frames come back as
    ``b""``); partial frames stay buffered until the next feed. The serve
    event loop reads whatever a socket has ready and feeds it here — the
    blocking read loop of `read_frame`, inverted.

    A header claiming more than ``max_frame_bytes`` yields one
    `OversizedFrame` sentinel IMMEDIATELY (so the server can answer a
    structured error before the body even arrives) and puts the decoder
    into skip mode: the declared bytes are discarded as they stream in,
    after which framing resynchronizes. Note a GARBAGE byte stream whose
    fake header claims an astronomical size therefore parks the
    connection in skip mode — framing cannot resync inside arbitrary
    garbage — but the server stays up and the client gets the error
    reply, which is the robustness contract (docs/robustness.md).
    """

    def __init__(self, max_frame_bytes: int = MAX_FRAME_BYTES):
        self._buf = bytearray()
        self.max_frame_bytes = int(max_frame_bytes)
        #: bytes of the current oversized frame still to discard
        self._skip = 0
        #: oversized frames seen (telemetry/debugging)
        self.oversized = 0

    def feed(self, data: bytes) -> list:
        self._buf.extend(data)
        frames: list = []
        while True:
            if self._skip:
                take = min(self._skip, len(self._buf))
                del self._buf[:take]
                self._skip -= take
                if self._skip:
                    return frames
            if len(self._buf) < HEADER.size:
                return frames
            (size,) = HEADER.unpack(self._buf[:HEADER.size])
            if size > self.max_frame_bytes:
                del self._buf[:HEADER.size]
                self._skip = size
                self.oversized += 1
                frames.append(OversizedFrame(size))
                continue
            if len(self._buf) < HEADER.size + size:
                return frames
            frames.append(bytes(self._buf[HEADER.size:HEADER.size + size]))
            del self._buf[:HEADER.size + size]


# ----------------------------------------------------------- request schema

#: request type -> (required fields, optional fields). The serve server
#: rejects anything else up front — a typo'd request must answer with a
#: structured error, not a stack trace mid-event-loop.
REQUEST_FIELDS = {
    # enter the admission queue: a full run-config TOML as text (the same
    # contract `skelly_config.toml` satisfies), optionally resuming from a
    # previously snapshotted trajectory frame
    "submit": (("config",), ("tenant", "t_final", "resume_frame")),
    # tenant lifecycle + progress counters
    "status": (("tenant",), ()),
    # drain the tenant's pending trajectory frames (raw trajectory-v1 bytes)
    "stream": (("tenant",), ("max_frames",)),
    # the tenant's CURRENT state as one trajectory frame (the exact resume
    # point — newer than its last dt_write frame)
    "snapshot": (("tenant",), ()),
    # free the tenant's lane (running) or queue slot (queued) now
    "cancel": (("tenant",), ()),
    # server-wide SLO counters (serve.metrics)
    "stats": ((), ()),
    # fault injection (guard.chaos; REFUSED unless the server config sets
    # [serve] chaos_enabled — a production server must not expose it).
    # action: "nan_lane" poisons the tenant's lane state between rounds
    "chaos": (("action",), ("tenant",)),
    # stop the event loop after answering
    "shutdown": ((), ()),
}

#: tenant lifecycle states (`serve.tenants`); ``failed`` = quarantined on
#: a terminal solver health verdict (the `status` response carries the
#: decoded verdict — docs/robustness.md)
TENANT_STATES = ("queued", "running", "finished", "evicted", "cancelled",
                 "dt_underflow", "failed")


def make_request(rtype: str, **fields) -> dict:
    """Validated request dict (the client-side constructor)."""
    req = {"type": rtype, **fields}
    err = validate_request(req)
    if err:
        raise ValueError(err)
    return req


def validate_request(req) -> Optional[str]:
    """None when ``req`` is a well-formed request, else the error text the
    server answers with."""
    if not isinstance(req, dict):
        return f"request must be a msgpack map, got {type(req).__name__}"
    rtype = req.get("type")
    if rtype not in REQUEST_FIELDS:
        return (f"unknown request type {rtype!r}; valid types: "
                + ", ".join(sorted(REQUEST_FIELDS)))
    required, optional = REQUEST_FIELDS[rtype]
    missing = [f for f in required if f not in req]
    if missing:
        return f"request {rtype!r} missing required field(s): {missing}"
    unknown = sorted(set(req) - {"type"} - set(required) - set(optional))
    if unknown:
        return f"request {rtype!r} has unknown field(s): {unknown}"
    return None


def ok(**fields) -> dict:
    return {"ok": True, **fields}


def error(message: str, **fields) -> dict:
    return {"ok": False, "error": message, **fields}


# -------------------------------------------------- prometheus exposition

#: scalar /stats counters exported to scrapers: stats key ->
#: (metric name, TYPE, HELP)
PROMETHEUS_COUNTERS = [
    ("admitted", "skellysim_serve_admitted_total", "counter",
     "lane seats granted (admit + backfill)"),
    ("rejected", "skellysim_serve_rejected_total", "counter",
     "admission rejections"),
    ("retired", "skellysim_serve_retired_total", "counter",
     "lanes freed"),
    ("rounds", "skellysim_serve_rounds_total", "counter",
     "batched ensemble rounds"),
    ("steps", "skellysim_serve_member_steps_total", "counter",
     "member trial steps (live lanes x rounds)"),
    ("compiles", "skellysim_serve_compiles_total", "counter",
     "program compiles"),
    ("compiles_after_warm", "skellysim_serve_compiles_after_warm_total",
     "counter", "warm-path retraces (SLO violation when > 0)"),
    ("frames_streamed_total", "skellysim_serve_frames_streamed_total",
     "counter", "trajectory frames streamed to clients"),
    ("loss_of_accuracy_steps", "skellysim_serve_loss_of_accuracy_total",
     "counter", "steps flagged loss_of_accuracy"),
    ("growth_reseats", "skellysim_serve_growth_reseats_total", "counter",
     "DI capacity-growth reseats"),
    ("tenants", "skellysim_serve_tenants", "gauge",
     "tenant records currently held"),
    ("mean_occupancy", "skellysim_serve_mean_occupancy", "gauge",
     "mean live/lanes per round"),
]

#: /stats histogram key -> prometheus metric name (obs.hist wire dicts)
PROMETHEUS_HISTOGRAMS = {
    "admission_wait_s": "skellysim_serve_admission_wait_seconds",
    "round_wall_s": "skellysim_serve_round_wall_seconds",
    "frame_stream_s": "skellysim_serve_frame_stream_seconds",
}


def render_prometheus(stats: dict) -> str:
    """A `/stats` response body -> Prometheus text exposition (the
    ``GET /metrics``-style page; `ServeClient.stats_prometheus` and
    ``python -m skellysim_tpu.serve.client stats --prometheus`` render it
    for scrapers — docs/serving.md "SLO histograms")."""
    from ..obs.hist import render_prometheus_histogram

    out = []
    for key, name, mtype, help_text in PROMETHEUS_COUNTERS:
        if key not in stats:
            continue
        out.append(f"# HELP {name} {help_text}")
        out.append(f"# TYPE {name} {mtype}")
        out.append(f"{name} {float(stats[key]):.6g}")
    for reason, count in sorted((stats.get("retire_reasons") or {}).items()):
        out.append('skellysim_serve_retired_by_reason_total'
                   f'{{reason="{reason}"}} {int(count)}')
    for kind, count in sorted((stats.get("faults") or {}).items()):
        out.append(f'skellysim_serve_faults_total{{kind="{kind}"}} '
                   f'{int(count)}')
    hists = stats.get("histograms") or {}
    for key, name in PROMETHEUS_HISTOGRAMS.items():
        if key in hists:
            out.extend(render_prometheus_histogram(
                name, hists[key],
                help_text=f"{key} distribution (log buckets)"))
    return "\n".join(out) + "\n"
