"""Client for the skelly-serve simulation service.

`ServeClient` speaks the serve request schema over one TCP connection
(framing from `serve.protocol` — the same length-prefixed msgpack the
listener client uses over pipes). `SpawnedServer` launches a server
subprocess for scripts/CI: it waits for the `--port-file` publish, hands
out connected clients, and guarantees teardown.

jax-free on purpose: a client drives a remote simulation service without
paying JAX backend init (the same discipline as `bench.py`'s parent
process).
"""

from __future__ import annotations

import os
import socket
import subprocess
import sys
import time
from typing import Optional

from . import protocol


class ServeClient:
    """One connection to a running serve server (request/response)."""

    def __init__(self, host: str = "127.0.0.1", port: int = 0,
                 timeout: Optional[float] = 60.0):
        self._sock = socket.create_connection((host, port), timeout=timeout)
        self._decoder = protocol.FrameDecoder()

    # ------------------------------------------------------------ transport

    def request(self, req: dict) -> dict:
        """Send one request; block for its response dict."""
        err = protocol.validate_request(req)
        if err:
            raise ValueError(err)
        buf = protocol.pack_message(req)
        self._sock.sendall(protocol.HEADER.pack(len(buf)) + buf)
        while True:
            data = self._sock.recv(1 << 16)
            if not data:
                raise ConnectionError("serve server closed the connection")
            frames = self._decoder.feed(data)
            if frames:
                if isinstance(frames[0], protocol.OversizedFrame):
                    raise ConnectionError(
                        f"server answered an oversized frame "
                        f"({frames[0].size} bytes)")
                return protocol.unpack_message(frames[0])

    def _checked(self, req: dict) -> dict:
        resp = self.request(req)
        if not resp.get("ok"):
            raise RuntimeError(f"serve {req['type']} failed: "
                               f"{resp.get('error', '?')}")
        return resp

    # ------------------------------------------------------------- requests

    def submit(self, config_toml: str, *, tenant: Optional[str] = None,
               t_final: Optional[float] = None,
               resume_frame: Optional[bytes] = None) -> dict:
        """Admit a simulation; returns the submit response ({tenant, bucket,
        lane/queued, ...}). ``config_toml`` is full run-config TOML text;
        ``resume_frame`` resumes from a previously fetched snapshot."""
        fields = {}
        if tenant is not None:
            fields["tenant"] = tenant
        if t_final is not None:
            fields["t_final"] = float(t_final)
        if resume_frame is not None:
            fields["resume_frame"] = resume_frame
        return self._checked(protocol.make_request(
            "submit", config=config_toml, **fields))

    def status(self, tenant: str) -> dict:
        return self._checked(protocol.make_request("status", tenant=tenant))

    def stream(self, tenant: str, max_frames: Optional[int] = None) -> dict:
        """Drain pending trajectory frames; response ``frames`` is a list of
        raw trajectory-v1 frame bytes, ``eof`` True once the tenant is done
        and drained."""
        fields = {"max_frames": max_frames} if max_frames is not None else {}
        return self._checked(protocol.make_request(
            "stream", tenant=tenant, **fields))

    def snapshot(self, tenant: str) -> bytes:
        """The tenant's CURRENT state as one trajectory frame (the exact
        resume point)."""
        return bytes(self._checked(protocol.make_request(
            "snapshot", tenant=tenant))["frame"])

    def cancel(self, tenant: str) -> dict:
        return self._checked(protocol.make_request("cancel", tenant=tenant))

    def stats(self) -> dict:
        return self._checked(protocol.make_request("stats"))["stats"]

    def stats_prometheus(self) -> str:
        """`/stats` rendered as a Prometheus text exposition page
        (`protocol.render_prometheus`) — counters, fault/retire labels,
        and the SLO latency histograms with cumulative ``le`` buckets.
        Pair with the node-exporter textfile collector or any sidecar
        scraper (docs/serving.md "SLO histograms")."""
        return protocol.render_prometheus(self.stats())

    def chaos(self, action: str, tenant: Optional[str] = None) -> dict:
        """Fault injection (`guard.chaos`) — the server refuses unless its
        config sets ``[serve] chaos_enabled``."""
        fields = {"tenant": tenant} if tenant is not None else {}
        return self._checked(protocol.make_request(
            "chaos", action=action, **fields))

    def shutdown(self) -> dict:
        return self._checked(protocol.make_request("shutdown"))

    def wait(self, tenant: str, timeout: float = 300.0,
             interval: float = 0.05) -> dict:
        """Poll ``status`` until the tenant leaves queued/running."""
        t0 = time.monotonic()
        while True:
            st = self.status(tenant)
            if st["status"] not in ("queued", "running"):
                return st
            if time.monotonic() - t0 > timeout:
                raise TimeoutError(
                    f"tenant {tenant} still {st['status']} after {timeout}s")
            time.sleep(interval)

    # ------------------------------------------------------------ lifecycle

    def close(self):
        if self._sock is not None:
            try:
                # the in-band goodbye: the server evicts our tenants
                self._sock.sendall(protocol.HEADER.pack(0))
            except OSError:
                pass
            self._sock.close()
            self._sock = None

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()


class SpawnedServer:
    """`python -m skellysim_tpu.serve` as a managed subprocess.

    Publishes its ephemeral port through ``--port-file``; `client()` hands
    out connected `ServeClient`s. The context exit terminates the server
    (after a best-effort ``shutdown`` request).
    """

    def __init__(self, config_file: str, *, args: Optional[list] = None,
                 startup_timeout: float = 240.0, env: Optional[dict] = None):
        self.port_file = config_file + ".serve_port"
        if os.path.exists(self.port_file):
            os.unlink(self.port_file)
        cmd = [sys.executable, "-m", "skellysim_tpu.serve",
               f"--config-file={config_file}", "--port", "0",
               f"--port-file={self.port_file}"] + list(args or [])
        self._proc = subprocess.Popen(cmd, env=env)
        self.port = self._wait_port(startup_timeout)

    def _wait_port(self, timeout: float) -> int:
        t0 = time.monotonic()
        while time.monotonic() - t0 < timeout:
            if self._proc.poll() is not None:
                raise RuntimeError(
                    f"serve server exited rc={self._proc.returncode} "
                    "before publishing its port")
            if os.path.exists(self.port_file):
                text = open(self.port_file).read().strip()
                if text:
                    return int(text)
            time.sleep(0.1)
        self._proc.terminate()
        raise TimeoutError(f"serve server did not publish a port within "
                           f"{timeout}s (warmup compile too slow?)")

    def client(self, **kw) -> ServeClient:
        return ServeClient(port=self.port, **kw)

    def kill(self) -> None:
        """SIGKILL the server — the crash-recovery injector (guard.chaos):
        no shutdown request, no graceful teardown, exactly what the
        write-ahead journal must survive. Pair with a fresh
        `SpawnedServer` on the same config/journal to test recovery."""
        import signal

        if self._proc.poll() is None:
            self._proc.send_signal(signal.SIGKILL)
            self._proc.wait()
        if os.path.exists(self.port_file):
            os.unlink(self.port_file)

    def stop(self, timeout: float = 30.0) -> int:
        if self._proc.poll() is None:
            try:
                with self.client(timeout=timeout) as c:
                    c.shutdown()
            except Exception:
                self._proc.terminate()
            try:
                self._proc.wait(timeout=timeout)
            except subprocess.TimeoutExpired:
                self._proc.kill()
                self._proc.wait()
        if os.path.exists(self.port_file):
            os.unlink(self.port_file)
        return self._proc.returncode

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.stop()


def main(argv=None) -> int:
    """Scraper-facing CLI: ``python -m skellysim_tpu.serve.client stats
    [--prometheus]`` prints a running server's `/stats` as JSON or as the
    Prometheus text page. jax-free (this module's import discipline), so
    a metrics sidecar costs no backend init."""
    import argparse
    import json

    ap = argparse.ArgumentParser(
        prog="python -m skellysim_tpu.serve.client",
        description="skelly-serve client utility (docs/serving.md)")
    ap.add_argument("command", choices=("stats",),
                    help="request to perform")
    ap.add_argument("--host", default="127.0.0.1")
    ap.add_argument("--port", type=int, required=True)
    ap.add_argument("--prometheus", action="store_true",
                    help="render stats as Prometheus text exposition "
                         "(GET /metrics-style) instead of JSON")
    args = ap.parse_args(argv)
    with ServeClient(host=args.host, port=args.port) as client:
        if args.prometheus:
            print(client.stats_prometheus(), end="")
        else:
            print(json.dumps(client.stats(), indent=1, default=str))
    return 0


if __name__ == "__main__":
    sys.exit(main())
