"""Per-tenant lifecycle: admission, capacity buckets, snapshot/resume, eviction.

A tenant is one independent client simulation multiplexed onto an ensemble
lane (tenant = lane). This module owns everything about a tenant EXCEPT the
stepping itself (which stays in `ensemble.scheduler`):

* **Admission** — a submitted config becomes a `SimState` only if it can
  ride an ALREADY-COMPILED program: its runtime `Params` must equal the
  server's up to the per-member knobs (seed, t_final — the same
  one-compiled-program contract the ensemble sweep CLI enforces), and its
  padded state shapes must match a capacity bucket's template exactly.
  Scenes smaller than the bucket capacity are padded with inert masked
  fibers (`fibers.container.grow_capacity` — the ensemble masked-lane trick
  applied to admission), so many different scenes hit one warm program.
* **Snapshot/resume** — a tenant's state round-trips through ONE
  trajectory-v1 frame (`io.trajectory.frame_bytes` / `frame_to_state`),
  byte-compatible with the `--resume` machinery: a snapshot streamed to a
  client can be appended to a `.out` file, fed back in a later ``submit``,
  or inspected by every existing reader.
* **Eviction** — a tenant whose client disconnects is retired gracefully:
  its lane frees for the queue, its final state is kept as the snapshot a
  reconnecting client resumes from.
"""

from __future__ import annotations

import dataclasses
from collections import deque
from typing import Optional

from ..config import schema
from ..config.toml_io import loads as toml_loads
from . import protocol

#: tenant lifecycle states (mirrored in `protocol.TENANT_STATES`):
#: queued -> running -> finished | evicted | cancelled | dt_underflow
TENANT_STATES = protocol.TENANT_STATES


@dataclasses.dataclass
class Tenant:
    """One client simulation's service-side record."""

    tenant_id: str
    bucket: int                       # capacity (padded fiber count)
    t_final: float
    status: str = "queued"
    #: owning connection key (server-side); None for in-process tenants.
    #: Disconnect of this connection evicts the tenant.
    conn: Optional[object] = None
    t: float = 0.0
    steps: int = 0
    #: pending trajectory-v1 frame bytes, drained by ``stream`` requests
    frames: deque = dataclasses.field(default_factory=deque)
    frames_total: int = 0
    frames_streamed: int = 0
    #: final-state snapshot (one frame), captured at retire/evict
    final_frame: Optional[bytes] = None
    #: serialized RNG streams (SimRNG.dump_state) stamped into every frame/
    #: snapshot, so serve trajectories resume with RNG continuity like a
    #: CLI-written one (free-space tenants never advance the streams, so
    #: the admission-time dump stays current)
    rng_state: Optional[object] = None
    #: monotonic timestamp of entry into a terminal state (finished /
    #: evicted / cancelled / dt_underflow / failed) — the `[serve]
    #: record_ttl_s` retention clock; None while queued/running (never
    #: expires)
    retired_at: Optional[float] = None
    #: accumulated packed health word (`guard.verdict` bit layout), ORed
    #: over every step record + the terminal verdict; surfaced (with its
    #: decoded bit names) in `status` responses — docs/robustness.md
    health: int = 0
    #: steps whose solve converged implicitly but drifted explicitly
    #: (Belos' loss-of-accuracy analogue) — previously died in the
    #: metrics JSONL, now surfaced in `status`/`stats`
    loss_of_accuracy_steps: int = 0
    #: skelly-flight blast radius, captured at a failed/underflowed
    #: retirement: ``{"tail": [decoded diagnostic rows...], "provenance":
    #: {field, fiber, node} | None}`` (`obs.flight.failure_payload`) —
    #: the trajectory into the fault + the first nonfinite's coordinates,
    #: surfaced on ``status`` responses; None while healthy or with the
    #: recorder off (Params.flight_window == 0)
    flight: Optional[dict] = None

    def snapshot_pending(self) -> int:
        return len(self.frames)


class TenantRegistry:
    """Id -> Tenant map with server-assigned ids and per-connection index."""

    def __init__(self):
        self._tenants: dict[str, Tenant] = {}
        self._next = 0

    def new_id(self) -> str:
        # skip ids a client already claimed explicitly — the server must
        # never invent a collision and reject its own assignment
        while True:
            tid = f"t{self._next:04d}"
            self._next += 1
            if tid not in self._tenants:
                return tid

    def add(self, tenant: Tenant):
        if tenant.tenant_id in self._tenants:
            raise ValueError(f"tenant id {tenant.tenant_id!r} already exists")
        self._tenants[tenant.tenant_id] = tenant

    def get(self, tenant_id: str) -> Optional[Tenant]:
        return self._tenants.get(tenant_id)

    def of_conn(self, conn) -> list[Tenant]:
        """Tenants owned by one connection (the disconnect-eviction set)."""
        return [t for t in self._tenants.values() if t.conn is conn]

    def expire(self, ttl_s: float, now: float) -> list[str]:
        """Drop terminal records older than ``ttl_s`` (the `[serve]
        record_ttl_s` retention bound); returns the expired ids. ``ttl_s
        <= 0`` disables expiry; live (queued/running) tenants never
        expire — only `Tenant.retired_at` starts the clock."""
        if ttl_s <= 0:
            return []
        dead = [tid for tid, t in self._tenants.items()
                if t.retired_at is not None and now - t.retired_at >= ttl_s]
        for tid in dead:
            del self._tenants[tid]
        return dead

    def __len__(self):
        return len(self._tenants)

    def values(self):
        return self._tenants.values()


# ------------------------------------------------------------- admission

#: the one-compiled-program contract (shared with the ensemble sweep CLI —
#: ONE definition in `config.schema`)
normalized_params = schema.normalized_member_params


def parse_tenant_config(config_text: str, di_enabled: bool = False):
    """Submitted TOML text -> validated `schema.Config`.

    Serve tenants are free-space scenes (fibers + background + point
    sources) plus — on a dynamic-instability server — ANALYTIC bodies:
    a periphery needs a server-side precompute npz a wire submission
    cannot carry, but a spherical/ellipsoidal MTOC's quadrature is a
    deterministic function of (shape, n_nodes, radius) the server
    rebuilds itself (`builder.build_bodies(synthesize_precompute=True)`),
    so DI tenants can bring their nucleation bodies over the wire
    (nucleation sites must be embedded in the TOML — site generation is
    random and belongs client-side). Everything else is rejected up front
    with a message instead of failing deep in the builder."""
    try:
        data = toml_loads(config_text)
    except Exception as e:
        raise ValueError(f"config TOML parse error: {e}") from None
    cfg = schema.config_from_data(data)
    if getattr(cfg, "periphery", None) is not None:
        raise ValueError(
            "serve tenants cannot use a periphery: its precompute npz lives "
            "server-side; run periphery scenes through the batch CLIs")
    if cfg.bodies and not di_enabled:
        raise ValueError(
            "serve tenants cannot use bodies on a server without dynamic "
            "instability: run body scenes through the batch CLIs (a "
            "[dynamic_instability] server admits analytic nucleation "
            "bodies — docs/scenarios.md)")
    for j, b in enumerate(cfg.bodies):
        if b.shape not in ("sphere", "ellipsoid"):
            raise ValueError(
                f"bodies[{j}]: serve tenants can only bring analytic "
                f"(sphere/ellipsoid) bodies, not {b.shape!r} — other "
                "surfaces need a server-side precompute npz")
        if b.n_nucleation_sites > 0 and not b.nucleation_sites:
            raise ValueError(
                f"bodies[{j}]: embed generated nucleation_sites in the "
                "config (site generation is random and must happen "
                "client-side so the submitted scene is deterministic)")
    if not cfg.fibers and not (di_enabled and cfg.bodies):
        raise ValueError("tenant config has no fibers")
    problems = cfg.validate()
    if problems:
        raise ValueError("invalid tenant config:\n  " + "\n  ".join(problems))
    return cfg


def check_params_contract(tenant_params: schema.Params,
                          server_params: schema.Params) -> Optional[str]:
    """None when the tenant can share the server's compiled program, else
    the rejection text naming every differing param."""
    tn, sn = normalized_params(tenant_params), normalized_params(server_params)
    if tn == sn:
        return None
    diffs = [f.name for f in dataclasses.fields(schema.Params)
             if getattr(tn, f.name) != getattr(sn, f.name)]
    return ("tenant params differ from the server's compiled program in "
            f"{diffs}; only params.seed/params.t_final may vary per tenant "
            "(one-compiled-program contract)")


def pad_state_to_capacity(state, capacity):
    """State padded onto its admission bucket (inert masked padding).

    ``capacity`` is either a `system.buckets.BucketKey` — the policy path,
    covering mixed-resolution tuple containers and masked node/shell axes
    via `buckets.bucketize_to` — or a plain int fiber capacity (the legacy
    single-group spelling, kept for journal/readers that stored ints)."""
    from ..fibers import container as fc
    from ..system import buckets as bucket_mod

    if isinstance(capacity, bucket_mod.BucketKey):
        return bucket_mod.bucketize_to(state, capacity)
    if state.fibers is None or not isinstance(state.fibers, fc.FiberGroup):
        return state
    if state.fibers.n_fibers >= capacity:
        return state
    return state._replace(fibers=fc.grow_capacity(state.fibers, capacity))


def bucket_mismatch(template_state, state,
                    nearest: Optional[str] = None) -> Optional[str]:
    """None when ``state``'s leaves match the bucket template's static
    shapes/dtypes (admissible), else the mismatch text. Wraps the ensemble
    runner's member check — the SAME predicate that guards `set_lane`, so
    admission can never admit a state the scheduler would later reject.
    ``nearest`` (a bucket description from `BucketKey.describe`) is
    appended so the raw leaf-shape text comes with an actionable next
    step."""
    import jax

    from ..ensemble.runner import _check_member

    try:
        _check_member(0, jax.tree_util.tree_leaves(template_state), state)
    except ValueError as e:
        msg = str(e)
        if nearest:
            msg += f"; nearest admissible bucket: {nearest}"
        return msg
    return None


def state_snapshot(state, rng_state=None) -> bytes:
    """One trajectory-v1 frame of ``state`` — the tenant snapshot format."""
    from ..io.trajectory import frame_bytes

    return frame_bytes(state, rng_state=rng_state)


def state_from_snapshot(frame_buf: bytes, template_state):
    """Snapshot frame bytes -> (SimState, rng_state) over a bucket template
    (the resume half; the wire twin of `io.trajectory.resume_state`, which
    also hands back the frame's serialized RNG streams)."""
    from ..io.trajectory import frame_to_state

    frame = protocol.unpack_message(frame_buf)
    if not isinstance(frame, dict) or "time" not in frame:
        raise ValueError("resume_frame is not a trajectory-v1 frame")
    return frame_to_state(frame, template_state), frame.get("rng_state")
