"""SLO counters for the serve event loop, derived from skelly-scope events.

The server does not keep a second bookkeeping path: every number `/stats`
reports is folded from the SAME telemetry events the tracer already emits
(`obs.tracer` schema, docs/observability.md) — ``lane`` events carry
admissions/backfills/retirements and the `queue_wait_s` admission latency,
``span`` events named ``ensemble_step`` carry per-round lane occupancy and
wall time, ``span`` events named ``stream_frames`` carry per-drain frame
counts and stream latency, ``compile`` events mark program (re)compiles.
`StatsTracer` tees the stream: each event updates the in-memory
`ServeMetrics` accumulator AND flows on to the ordinary tracer sink (JSONL
file or in-memory list), so a `--trace-file` from a service run renders
under ``obs summarize`` exactly like an ensemble sweep's.

SLO distributions (skelly-pulse, docs/serving.md "SLO histograms"): the
three latency streams — admission wait, per-round batched-step wall,
frame-stream drain — fold into fixed-bucket log-scale `obs.hist.
LogHistogram`s, so `/stats` answers p50/p95/p99 under sustained traffic
with BOUNDED memory (the pre-pulse ``queue_waits`` list grew per
admission, forever), and ``render_prometheus`` in `serve.protocol` turns
the same buckets into a scrape-able ``GET /metrics``-style text page.

The one serving-specific counter the event stream cannot carry is
``compiles_after_warm``: the server calls `mark_warm()` once every
constructed bucket has completed its first batched round — from then on ANY
compile event is a warm-path retrace, the defect class `test_retrace.py`
pins at trace time and this counter exposes at serve time (the acceptance
gate: zero after warmup).
"""

from __future__ import annotations

from typing import Optional

from ..obs import tracer as obs_tracer
from ..obs.hist import LogHistogram

#: the /stats SLO histogram inventory: name -> (lo, hi) seconds. One
#: place, so the stats payload, the prometheus rendering, and the tests
#: agree on the set (docs/serving.md).
SLO_HISTOGRAMS = {
    "admission_wait_s": (1e-4, 1e3),
    "round_wall_s": (1e-4, 1e3),
    "frame_stream_s": (1e-6, 1e2),
}


class ServeMetrics:
    """Accumulator of serving SLO counters (see `stats`)."""

    def __init__(self):
        self.admitted = 0          # lane seats (admit + backfill actions)
        self.retired = 0           # lanes freed, by reason
        self.retire_reasons: dict[str, int] = {}
        self.rejected = 0          # admission rejections (server increments)
        self.rounds = 0            # batched ensemble_step rounds
        self.round_wall_s = 0.0
        self.occupancy_sum = 0.0   # sum of live/lanes per round
        self.steps = 0             # member trial steps (live lanes x rounds)
        self.compiles = 0
        self.compiles_after_warm = 0
        self.warm = False
        self.frames_streamed: dict[str, int] = {}
        #: fault events by kind (`ev == "fault"`: lane_failed /
        #: dt_underflow / chaos_nan / frame_oversized / ... —
        #: docs/robustness.md)
        self.faults: dict[str, int] = {}
        #: skelly-flight fault localization: offender FIELD of each fault
        #: event carrying anomaly provenance (``prov_field`` — fiber_x /
        #: shell_density / ..., `obs.flight.PROV_FIELDS`), so /stats
        #: answers "what keeps blowing up" across tenants
        self.fault_fields: dict[str, int] = {}
        #: steps flagged loss_of_accuracy across every tenant (server
        #: increments via `note_loss_of_accuracy`)
        self.loss_of_accuracy_steps = 0
        #: DI capacity-growth reseats (lane ``growth`` events)
        self.growth_reseats = 0
        #: SLO latency distributions (skelly-pulse): fixed log buckets,
        #: bounded memory under unbounded traffic
        self.hists = {name: LogHistogram(lo, hi)
                      for name, (lo, hi) in SLO_HISTOGRAMS.items()}

    # ------------------------------------------------------------ ingest

    def observe(self, ev: str, fields: dict):
        """Fold one telemetry event (called by `StatsTracer.emit`)."""
        if ev == "lane":
            action = fields.get("action")
            if action in ("admit", "backfill"):
                self.admitted += 1
                if "queue_wait_s" in fields:
                    self.hists["admission_wait_s"].observe(
                        float(fields["queue_wait_s"]))
            elif action == "retire":
                self.retired += 1
                reason = fields.get("reason", "finished")
                self.retire_reasons[reason] = (
                    self.retire_reasons.get(reason, 0) + 1)
            elif action == "growth":
                # a DI tenant's nucleation outgrew its capacity bucket and
                # the lane is being reseated onto a larger one
                # (docs/scenarios.md "Growth reseats")
                self.growth_reseats += 1
        elif ev == "span" and fields.get("name") == "ensemble_step":
            self.rounds += 1
            dur = float(fields.get("dur_s", 0.0))
            self.round_wall_s += dur
            self.hists["round_wall_s"].observe(dur)
            live = fields.get("live")
            lanes = fields.get("lanes")
            if live is not None and lanes:
                self.occupancy_sum += float(live) / float(lanes)
                self.steps += int(live)
        elif ev == "span" and fields.get("name") == "stream_frames":
            # the `stream` request's drain span: frame accounting AND the
            # frame-stream latency distribution from ONE event
            n = int(fields.get("frames", 0))
            tenant = fields.get("tenant")
            if n and tenant:
                self.frames_streamed[tenant] = (
                    self.frames_streamed.get(tenant, 0) + n)
            if n:
                self.hists["frame_stream_s"].observe(
                    float(fields.get("dur_s", 0.0)))
        elif ev == "compile":
            self.compiles += 1
            if self.warm:
                self.compiles_after_warm += 1
        elif ev == "fault":
            kind = fields.get("kind", "?")
            self.faults[kind] = self.faults.get(kind, 0) + 1
            if fields.get("prov_field"):
                f = str(fields["prov_field"])
                self.fault_fields[f] = self.fault_fields.get(f, 0) + 1

    def mark_warm(self):
        """Every bucket has compiled + completed a round: from here on a
        compile event means a warm-path retrace (SLO violation)."""
        self.warm = True

    def note_rejected(self):
        self.rejected += 1

    def note_loss_of_accuracy(self):
        self.loss_of_accuracy_steps += 1

    # ------------------------------------------------------------ report

    def stats(self) -> dict:
        """The `/stats` response body (also the shape tests pin).

        The three SLO latency keys each carry
        ``{n, mean, max, p50, p95, p99}`` (`LogHistogram.summary`);
        ``histograms`` carries the full cumulative buckets for scrapers
        (`serve.protocol.render_prometheus`)."""
        return {
            "admitted": self.admitted,
            "rejected": self.rejected,
            "retired": self.retired,
            "retire_reasons": dict(self.retire_reasons),
            "rounds": self.rounds,
            "steps": self.steps,
            "steps_per_s": (self.steps / self.round_wall_s
                            if self.round_wall_s > 0 else 0.0),
            "round_wall_s": round(self.round_wall_s, 6),
            "mean_occupancy": (self.occupancy_sum / self.rounds
                               if self.rounds else 0.0),
            "admission_wait_s": self.hists["admission_wait_s"].summary(),
            "round_wall_s_hist": self.hists["round_wall_s"].summary(),
            "frame_stream_s": self.hists["frame_stream_s"].summary(),
            "histograms": {name: h.to_wire()
                           for name, h in self.hists.items()},
            "compiles": self.compiles,
            "compiles_after_warm": self.compiles_after_warm,
            "warm": self.warm,
            "faults": dict(self.faults),
            "fault_fields": dict(self.fault_fields),
            "loss_of_accuracy_steps": self.loss_of_accuracy_steps,
            "growth_reseats": self.growth_reseats,
            "frames_streamed": dict(self.frames_streamed),
            "frames_streamed_total": sum(self.frames_streamed.values()),
        }


class StatsTracer(obs_tracer.Tracer):
    """A `Tracer` that tees every event into a `ServeMetrics` accumulator.

    ``path=None`` keeps the ordinary in-memory event list (tests assert on
    it); a path appends telemetry JSONL exactly like any other tracer.
    """

    def __init__(self, metrics: ServeMetrics, path: Optional[str] = None):
        # set before super().__init__: the base constructor emits the
        # telemetry header through our emit()
        self.metrics = metrics
        super().__init__(path)

    def emit(self, ev: str, **fields):
        self.metrics.observe(ev, fields)
        super().emit(ev, **fields)
