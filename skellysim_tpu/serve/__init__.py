"""skelly-serve: a persistent multi-tenant simulation service.

The reference's interaction story is one process serving one client over a
blocking request loop (`listener.py`); this subsystem composes the pieces
that already exist — the ensemble continuous-batching scheduler
(`ensemble.scheduler`), the trajectory snapshot/resume machinery
(`io.trajectory.resume_state`), and the skelly-scope telemetry stream
(`obs.tracer`) — into a long-lived server that keeps compiled ensemble
programs warm and multiplexes many independent client simulations onto
ensemble lanes (tenant = lane). The "millions of users" leg of the ROADMAP
north star, and the forcing function for shape-bucketed warm programs.

Layers (see docs/serving.md):

* `protocol` — length-prefixed msgpack framing (one source of truth, shared
               with `listener.py`) + the serve request/response schema
               (submit/status/stream/snapshot/cancel/stats/shutdown);
* `tenants`  — per-tenant lifecycle: admission queue with a capacity-bucket
               check (a tenant only admits into a lane whose padded shapes
               match an already-compiled program), snapshot/resume, graceful
               eviction on client disconnect;
* `server`   — the event loop: service client requests between batched
               rounds of the ensemble scheduler (admit/step/retire with
               tenants joining and leaving, never retracing);
* `metrics`  — SLO counters derived from obs events (admission latency,
               lane occupancy, steps/s + frames per tenant, compile events
               after warmup, faults by kind), exported as telemetry JSONL
               + `/stats`;
* `journal`  — the crash-safe write-ahead tenant journal (skelly-guard,
               docs/robustness.md): append-only snapshots on admit /
               every-K-rounds / retire, replayed on restart so `kill -9`
               loses no tenant;
* `client`   — `ServeClient` / `SpawnedServer` for driving a server;
* `cli`      — `python -m skellysim_tpu.serve`.

Import discipline: this package root and `protocol` stay jax-free so
clients (and `listener.py`) can import them without paying backend init;
`server` pulls in the jax-heavy ensemble stack lazily.
"""

from . import protocol  # noqa: F401  (jax-free)


def __getattr__(name):
    # lazy jax-heavy surfaces: `serve.SimulationServer` etc. resolve on
    # first touch without making `import skellysim_tpu.serve` heavy
    if name in ("SimulationServer", "Bucket"):
        from . import server

        return getattr(server, name)
    if name in ("Tenant", "TenantRegistry"):
        from . import tenants

        return getattr(tenants, name)
    if name in ("ServeMetrics", "StatsTracer"):
        from . import metrics

        return getattr(metrics, name)
    if name in ("ServeClient", "SpawnedServer"):
        from . import client

        return getattr(client, name)
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")
