"""Listener mode: post-processing server over stdin/stdout (placeholder).

Counterpart of `listener::run` (`/root/reference/src/core/listener.cpp:86-136`).
Implemented with streamlines/velocity-field support in a follow-up; the CLI
flag is wired already.
"""

from __future__ import annotations


def serve(config_file: str) -> None:
    raise NotImplementedError(
        "listener mode lands with the post-processing subsystem")
