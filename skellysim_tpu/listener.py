"""Listener mode: post-processing server over stdin/stdout.

Counterpart of `listener::run` (`/root/reference/src/core/listener.cpp:86-136`):
length-prefixed (little-endian u64) msgpack requests
{frame_no, evaluator, streamlines, vortexlines, velocity_field} arrive on
stdin; the requested trajectory frame is loaded, streamlines / vortex lines /
velocity fields are computed from it, and a msgpack response
{time, i_frame, n_frames, streamlines, vortexlines, velocity_field} is written
to stdout. A zero-length message terminates the server.

The `evaluator` field selected CPU/GPU/FMM backends in the reference
(`listener.cpp:117`, `System::set_evaluator`, `system.cpp:389-393`); it maps
onto our pair-evaluator seam (case-insensitive): "FMM" -> "ewald" (the
spectral-Ewald fast evaluator filling the reference's FMM slot; "tree", the
hierarchical answer to the same slot, is reachable by its native name),
"CPU"/"GPU" -> "direct" (dense XLA kernels — the device is whatever backend
JAX runs on); our native names ("direct"/"ring"/"ewald"/"tree") are also
accepted.
Scope: the switch covers `velocity_field` requests AND streamline /
vortex-line integration, matching the reference's whole-request evaluator
switch (`listener.cpp:117` + `system.cpp:389-393`): each request plans over
the frame's nodes, the line seeds, and an EXTENDED box (the node/seed
bounding box grown by half a diameter per side), so integrator points can
roam well beyond the seeds before leaving the planned cell/FFT region.
Trajectories that escape even the extended box read wrapped far-field
values — the same box-bound behavior as the reference's FMM evaluator,
whose octree must also contain every evaluation point. An invalid frame_no
answers with a zero-length response like the reference
(`listener.cpp:111-116`).
"""

from __future__ import annotations

import dataclasses
import sys
import weakref

import numpy as np

from .builder import build_simulation
from .ops.evaluator import EVALUATOR_ALIASES
from .io import eigen
from .io.trajectory import TrajectoryReader, frame_to_state
from .postprocess import streamlines as compute_streamlines
from .postprocess import vortex_lines as compute_vortex_lines
from .serve import protocol
from .system.system import solution_from_state

_LINE_DEFAULTS = dict(dt_init=0.1, t_final=1.0, abs_err=1e-10, rel_err=1e-6,
                      back_integrate=True)

#: reference evaluator names (`listener.cpp:117`) -> runtime pair evaluators;
#: the one alias table shared with the TOML mapping in `config.schema`
#: (lookup is case-insensitive at both sites)
EVALUATOR_MAP = EVALUATOR_ALIASES


def switch_evaluator(system, evaluator: str | None):
    """Rebuild the System for a requested evaluator (`System::set_evaluator`,
    `system.cpp:389-393`). Returns (system, switched); an absent name keeps
    the current evaluator, an unrecognized one raises (the same
    reject-config-typos policy as the TOML schema path — silently keeping
    the old evaluator would misattribute every subsequent result).
    Switching to "ring" creates a mesh over the local devices when the
    System has none — without one the ring path would silently fall back to
    direct, making the switch a cache-discarding no-op."""
    if not evaluator:
        return system, False
    ev = EVALUATOR_MAP.get(evaluator.lower())
    if ev is None:
        raise ValueError(
            f"unknown evaluator {evaluator!r} in listener request; valid "
            "names: " + ", ".join(sorted(EVALUATOR_MAP)))
    if ev == system.params.pair_evaluator:
        return system, False
    from .system import System

    mesh = system.mesh
    if ev == "ring" and mesh is None:
        from .parallel import make_mesh

        mesh = make_mesh()
    # NOTE a periodic config (params.periodic_box set) only serves
    # "spectral" and vice versa — System.__init__ raises on a mismatched
    # switch and the serve loop rejects that one request, like any other
    # invalid evaluator name
    new = System(dataclasses.replace(system.params, pair_evaluator=ev),
                 shell_shape=system.shell_shape, mesh=mesh)
    new.grid_ladder = system.grid_ladder
    return new, True


def _line_kwargs(req: dict) -> dict:
    kw = dict(_LINE_DEFAULTS)
    for k in kw:
        if req and k in req:
            kw[k] = req[k]
    return kw


def _seeds(req: dict) -> np.ndarray:
    x0 = req.get("x0") if req else None
    if x0 is None:
        return np.zeros((0, 3))
    return np.atleast_2d(np.asarray(x0, dtype=np.float64))


def _pack_lines(lines: list) -> list:
    return [{"x": eigen.pack_matrix(ln["x"]), "val": eigen.pack_matrix(ln["val"]),
             "time": eigen.pack_matrix(ln["time"])} for ln in lines]


def _extended_corners(state, system, seeds: np.ndarray) -> np.ndarray:
    """Corner points of the node/seed bounding box grown by half a diameter
    per side — extra plan targets that give line integrators room to roam
    inside the Ewald cell/FFT region."""
    pts = [np.asarray(system._node_positions(state))]
    if seeds.size:
        pts.append(seeds)
    pts = np.vstack(pts)
    lo, hi = pts.min(axis=0), pts.max(axis=0)
    margin = 0.5 * max(float(np.linalg.norm(hi - lo)), 1.0)
    lo, hi = lo - margin, hi + margin
    return np.array([[x, y, z] for x in (lo[0], hi[0])
                     for y in (lo[1], hi[1]) for z in (lo[2], hi[2])])


#: per-System cache of (plan -> stable velocity-field fn): the fn's identity
#: keys the streamline integrator's jit cache, so repeated requests with the
#: same (quantized) plan reuse the compiled program
_VEL_FNS: "weakref.WeakKeyDictionary" = weakref.WeakKeyDictionary()


def _vel_fn_for(system, pair):
    per = _VEL_FNS.setdefault(system, {})
    fn = per.get(pair)
    if fn is None:
        if pair is None:
            def fn(pts, state, solution, _sys=system):
                return _sys._velocity_at_targets_impl(state, solution, pts)
        else:
            def fn(pts, state, solution, anchors, _sys=system, _pair=pair):
                return _sys._velocity_at_targets_impl(
                    state, solution, pts, pair=_pair,
                    pair_anchors=anchors)
        per[pair] = fn
    return fn


def process_request(system, template_state, reader: TrajectoryReader,
                    cmd: dict, vel_fn=None, policy=None) -> dict | None:
    """One request → response dict, or None for an invalid frame.

    ``vel_fn(pts, state, solution)`` must be a *stable* function across
    requests (created once per server); per-frame state/solution flow through
    `field_args` so the compiled streamline integrator is reused instead of
    retraced on every request. ``policy`` (a `system.buckets.BucketPolicy`)
    re-lands each decoded frame on the server's capacity bucket, so frames
    whose live fiber count drifted (dynamic instability) still hit the warm
    compiled field programs.
    """
    frame_no = int(cmd.get("frame_no", 0))
    if frame_no < 0 or frame_no >= len(reader):
        return None
    frame = reader.load_frame(frame_no)
    state = frame_to_state(frame, template_state)
    if policy is not None:
        from .system import buckets as bucket_mod

        state, _ = bucket_mod.bucketize(
            state, policy, pair_evaluator=system.params.pair_evaluator)
    solution = solution_from_state(state)

    sl_req = cmd.get("streamlines") or {}
    vl_req = cmd.get("vortexlines") or {}
    vf_req = cmd.get("velocity_field") or {}

    seeds_sl = _seeds(sl_req)
    seeds_vl = _seeds(vl_req)
    if (system.params.pair_evaluator in ("ewald", "tree", "spectral")
            and (seeds_sl.size or seeds_vl.size)):
        # per-request extended-box plan: line integration goes through the
        # fast evaluator too, like the reference's whole-request switch
        # (`listener.cpp:117`); the quantized plan keys a reused jit program
        corners = _extended_corners(state, system,
                                    np.vstack([seeds_sl, seeds_vl]))
        pair, anchors = system._pair_args(state, extra_targets=corners)
        vel_fn = _vel_fn_for(system, pair)
        field_args = (state, solution, anchors)
    else:
        if vel_fn is None:
            vel_fn = _vel_fn_for(system, None)
        field_args = (state, solution)

    sl = compute_streamlines(vel_fn, seeds_sl, **_line_kwargs(sl_req),
                             field_args=field_args)
    vl = compute_vortex_lines(vel_fn, seeds_vl, **_line_kwargs(vl_req),
                              field_args=field_args)

    vf_x = vf_req.get("x")
    if vf_x is not None and np.asarray(vf_x).size:
        vf = np.asarray(system.velocity_at_targets(state, solution,
                                                   np.atleast_2d(vf_x)))
    else:
        vf = np.zeros((0, 3))

    return {
        "time": frame["time"],
        "i_frame": frame_no,
        "n_frames": len(reader),
        "streamlines": _pack_lines(sl),
        "vortexlines": _pack_lines(vl),
        "velocity_field": eigen.pack_matrix(vf),
    }


def serve(config_file: str = "skelly_config.toml",
          trajectory_file: str | None = None,
          stdin=None, stdout=None) -> None:
    import os

    stdin = stdin if stdin is not None else sys.stdin.buffer
    stdout = stdout if stdout is not None else sys.stdout.buffer
    traj = trajectory_file or os.path.join(
        os.path.dirname(os.path.abspath(config_file)) or ".", "skelly_sim.out")

    system, template_state, _ = build_simulation(config_file)
    # skelly-bucket: the listener quantizes its template (and every decoded
    # frame, see process_request) onto the config's capacity bucket before
    # the first compile — post-processing over a long trajectory then runs
    # one warm field program per evaluator
    from .config.schema import load_runtime_config
    from .system import buckets as bucket_mod

    policy = bucket_mod.BucketPolicy.from_runtime(
        load_runtime_config(config_file))
    system.grid_ladder = policy.grid_ladder
    template_state, _ = bucket_mod.bucketize(
        template_state, policy, pair_evaluator=system.params.pair_evaluator)
    reader = TrajectoryReader(traj)
    print(f"Entering listener mode ({len(reader)} frames)", file=sys.stderr)

    # framing from serve.protocol — ONE source of truth for the
    # length-prefixed msgpack wire format both servers speak
    while True:
        payload = protocol.read_frame(stdin)
        if payload is None:
            return
        if payload == b"":
            print("Terminate message received. Exiting listener mode",
                  file=sys.stderr)
            return
        cmd = protocol.unpack_message(payload)

        try:
            system, switched = switch_evaluator(system, cmd.get("evaluator"))
        except ValueError as e:
            # reject the request (zero-length answer, like an invalid frame)
            # but keep serving — one typo'd client must not kill the server
            print(f"listener: {e}", file=sys.stderr)
            protocol.write_empty(stdout)
            continue
        # velocity-field fns are cached per (system, plan) in _vel_fn_for,
        # so an evaluator switch naturally rebinds while repeated frames on
        # one evaluator reuse the compiled integrator
        response = process_request(system, template_state, reader, cmd,
                                   policy=policy)
        if response is None:
            protocol.write_empty(stdout)
            continue
        protocol.write_message(stdout, response)
