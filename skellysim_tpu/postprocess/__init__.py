"""Post-processing: velocity fields, streamlines, vortex lines.

Counterpart of the reference's listener-mode analysis stack
(`/root/reference/src/core/streamline.cpp`, `listener.cpp`), redesigned for
TPU: all line seeds integrate simultaneously as one batched adaptive RK
program instead of one odeint call per line.
"""

from .streamline import streamlines, vortex_lines, make_vorticity_fn

__all__ = ["streamlines", "vortex_lines", "make_vorticity_fn"]
