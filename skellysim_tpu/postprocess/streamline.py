"""Batched adaptive streamline / vortex-line integration.

TPU-native replacement for the reference's per-line Boost.odeint RK Cash-Karp
5(4) loops (`/root/reference/src/core/streamline.cpp:67-165`): all K seed
points advance together under one `lax.while_loop`, so every integrator stage
is a single batched velocity-field evaluation (one kernel launch over K
targets) instead of K sequential 1-point evaluations. Per-line adaptive step
control, early termination (t_final reached, buffer full, or singularity
bailout at ||v|| > 1e3, `streamline.cpp:51-53`) is carried as a done-mask.

Error control mirrors Boost's `controlled_runge_kutta` +
`default_error_checker` (a_x = a_dxdt = 1): per-component tolerance
abs_err + rel_err*(|x| + dt*|dxdt|), max-norm acceptance at 1, step shrink
0.9*err^(-1/3) floored at 0.2, growth 0.9*err^(-1/5) capped at 5 when
err < 0.5.
"""

from __future__ import annotations

from functools import lru_cache, partial
from typing import Callable, NamedTuple

import jax
import jax.numpy as jnp
import numpy as np

# Cash-Karp 5(4) tableau
_A = (
    (1 / 5,),
    (3 / 40, 9 / 40),
    (3 / 10, -9 / 10, 6 / 5),
    (-11 / 54, 5 / 2, -70 / 27, 35 / 27),
    (1631 / 55296, 175 / 512, 575 / 13824, 44275 / 110592, 253 / 4096),
)
_B5 = (37 / 378, 0.0, 250 / 621, 125 / 594, 0.0, 512 / 1771)
_B4 = (2825 / 27648, 0.0, 18575 / 48384, 13525 / 55296, 277 / 14336, 1 / 4)

_SINGULAR_SPEED = 1e3  # `streamline.cpp:51`


class _LineBatch(NamedTuple):
    """Raw padded integration output for K lines."""

    x: jnp.ndarray      # [K, S, 3]
    time: jnp.ndarray   # [K, S]
    count: jnp.ndarray  # [K] valid samples per line


@partial(jax.jit, static_argnums=(0, 1), static_argnames=("max_steps",))
def _integrate_batch(field_fn: Callable, speed_fn: Callable | None, x0, dt_init,
                     t_final, abs_err, rel_err, sign, max_steps: int,
                     field_args=()):
    """Integrate dx/ds = sign*field(x, *field_args) for s in [0, t_final], all
    lines at once.

    ``field_args`` are traced operands threaded to ``field_fn``/``speed_fn``:
    callers with per-frame data (the listener) pass it here so the compiled
    executable is reused across frames instead of retracing per closure.
    ``speed_fn`` (the singularity bailout field) may be None when it equals
    ``field_fn``; the k1 stage evaluation is reused then.

    Recorded times are sign*s, matching the reference's backward integration
    from 0 to -t_final (`streamline.cpp:84`).
    """
    K = x0.shape[0]
    S = max_steps
    dtype = x0.dtype
    ks = jnp.arange(K, dtype=jnp.int32)

    buf_x = jnp.zeros((K, S, 3), dtype=dtype).at[:, 0].set(x0)
    buf_t = jnp.zeros((K, S), dtype=dtype)
    count = jnp.ones((K,), dtype=jnp.int32)
    t = jnp.zeros((K,), dtype=dtype)
    dt = jnp.full((K,), dt_init, dtype=dtype)
    done = jnp.zeros((K,), dtype=bool) | (t_final <= 0.0)

    def cond(carry):
        x, t, dt, bufs, counts, done, it = carry
        return (~done).any() & (it < 8 * S)

    def body(carry):
        x, t, dt, (buf_x, buf_t), count, done, it = carry

        dt_use = jnp.minimum(dt, t_final - t)

        def f(xx):
            return sign * field_fn(xx, *field_args)

        k1 = f(x)

        # singularity bailout: the previous point was recorded; if the field
        # speed there explodes, the line ends (observer-throw semantics,
        # `streamline.cpp:51-53`). For streamlines the speed field IS the
        # integrated field, so |k1| is reused (sign does not change the norm).
        if speed_fn is None:
            speed = jnp.linalg.norm(k1, axis=-1)
        else:
            speed = jnp.linalg.norm(speed_fn(x, *field_args), axis=-1)
        done = done | (speed > _SINGULAR_SPEED)
        k2 = f(x + dt_use[:, None] * (_A[0][0] * k1))
        k3 = f(x + dt_use[:, None] * (_A[1][0] * k1 + _A[1][1] * k2))
        k4 = f(x + dt_use[:, None] * (_A[2][0] * k1 + _A[2][1] * k2
                                      + _A[2][2] * k3))
        k5 = f(x + dt_use[:, None] * (_A[3][0] * k1 + _A[3][1] * k2
                                      + _A[3][2] * k3 + _A[3][3] * k4))
        k6 = f(x + dt_use[:, None] * (_A[4][0] * k1 + _A[4][1] * k2
                                      + _A[4][2] * k3 + _A[4][3] * k4
                                      + _A[4][4] * k5))
        stages = (k1, k2, k3, k4, k5, k6)
        dx5 = sum(b * k for b, k in zip(_B5, stages))
        dx4 = sum(b * k for b, k in zip(_B4, stages))
        x5 = x + dt_use[:, None] * dx5

        tol = abs_err + rel_err * (jnp.abs(x) + dt_use[:, None] * jnp.abs(k1))
        err = jnp.max(dt_use[:, None] * jnp.abs(dx5 - dx4) / tol, axis=-1)
        err = jnp.maximum(err, 1e-30)

        accept = (err <= 1.0) & ~done
        new_x = jnp.where(accept[:, None], x5, x)
        new_t = jnp.where(accept, t + dt_use, t)

        write = accept & (count < S)
        idx = jnp.clip(count, 0, S - 1)
        buf_x = buf_x.at[ks, idx].set(
            jnp.where(write[:, None], new_x, buf_x[ks, idx]))
        buf_t = buf_t.at[ks, idx].set(
            jnp.where(write, sign * new_t, buf_t[ks, idx]))
        count = count + write.astype(jnp.int32)

        fac_dec = jnp.maximum(0.9 * err ** (-1 / 3), 0.2)
        fac_inc = jnp.minimum(0.9 * err ** (-1 / 5), 5.0)
        dt = jnp.where(err > 1.0, dt_use * fac_dec,
                       jnp.where(err < 0.5, dt_use * fac_inc, dt_use))

        eps_t = jnp.asarray(1e-14, dtype) * jnp.maximum(1.0, jnp.abs(t_final))
        done = done | (new_t >= t_final - eps_t) | (count >= S)
        return new_x, new_t, dt, (buf_x, buf_t), count, done, it + 1

    carry = (x0, t, dt, (buf_x, buf_t), count, done, jnp.asarray(0, jnp.int32))
    _, _, _, (buf_x, buf_t), count, _, _ = jax.lax.while_loop(cond, body, carry)
    return _LineBatch(x=buf_x, time=buf_t, count=count)


@lru_cache(maxsize=64)
def make_vorticity_fn(vel_fn: Callable, eps: float | None = None) -> Callable:
    """Curl of the velocity field via 6-point central differences
    (`get_vorticity_at_point`, `streamline.cpp:16-35`). Batched: one velocity
    evaluation over 6K points per call. Extra args pass through to vel_fn.

    Cached on (vel_fn, eps) so repeated `vortex_lines` calls hand the jit
    layer a stable function identity (no retrace per call)."""

    def vort(x, *args):
        x = jnp.atleast_2d(x)
        e = eps if eps is not None else (1e-7 if x.dtype == jnp.float64 else 1e-3)
        K = x.shape[0]
        offs = jnp.array([[1, 0, 0], [-1, 0, 0], [0, 1, 0],
                          [0, -1, 0], [0, 0, 1], [0, 0, -1]], dtype=x.dtype) * e
        pts = (x[:, None, :] + offs[None, :, :]).reshape(-1, 3)
        v = vel_fn(pts, *args).reshape(K, 6, 3)
        return (0.5 / e) * jnp.stack([
            (v[:, 2, 2] - v[:, 3, 2]) - (v[:, 4, 1] - v[:, 5, 1]),
            (v[:, 4, 0] - v[:, 5, 0]) - (v[:, 0, 2] - v[:, 1, 2]),
            (v[:, 0, 1] - v[:, 1, 1]) - (v[:, 2, 0] - v[:, 3, 0]),
        ], axis=-1)

    return vort


def _assemble(field_fn, speed_fn, x0, dt_init, t_final, abs_err, rel_err,
              back_integrate, max_steps, val_fn, field_args=()):
    """Run forward (+ optional backward) passes and join per line on host."""
    x0 = jnp.atleast_2d(jnp.asarray(x0))
    if x0.size == 0:
        return []

    def run(sign):
        batch = _integrate_batch(field_fn, speed_fn, x0, dt_init, t_final,
                                 abs_err, rel_err, sign, max_steps=max_steps,
                                 field_args=field_args)
        # evaluate val only over the recorded extent, not the padded buffer
        # (short lines would otherwise pay max_steps/n_samples x the kernel
        # cost); bucket to a multiple of 64 so val_fn sees a bounded set of
        # shapes instead of recompiling per distinct line length
        used = min(-(-max(int(batch.count.max()), 1) // 64) * 64, max_steps)
        x_used = batch.x[:, :used]
        val = val_fn(x_used.reshape(-1, 3), *field_args).reshape(x_used.shape)
        return (np.asarray(x_used), np.asarray(batch.time[:, :used]),
                np.asarray(val), np.asarray(batch.count))

    parts = [run(1.0)]
    if back_integrate:
        parts.insert(0, run(-1.0))

    lines = []
    for i in range(x0.shape[0]):
        if back_integrate:
            (bx, bt, bv, bc), (fx, ft, fv, fc) = parts
            nb, nf = int(bc[i]), int(fc[i])
            # reversed backward leg minus its seed + full forward leg
            # (`join_back_and_forward`, `streamline.cpp:56-65`)
            x = np.concatenate([bx[i, :nb][::-1][:-1], fx[i, :nf]])
            tm = np.concatenate([bt[i, :nb][::-1][:-1], ft[i, :nf]])
            val = np.concatenate([bv[i, :nb][::-1][:-1], fv[i, :nf]])
        else:
            fx, ft, fv, fc = parts[0]
            nf = int(fc[i])
            x, tm, val = fx[i, :nf], ft[i, :nf], fv[i, :nf]
        lines.append({"x": x, "val": val, "time": tm})
    return lines


def streamlines(vel_fn: Callable, x0, *, dt_init: float = 0.1,
                t_final: float = 1.0, abs_err: float = 1e-10,
                rel_err: float = 1e-6, back_integrate: bool = True,
                max_steps: int = 512, field_args=()):
    """Trace velocity-field streamlines from [K, 3] seeds.

    ``vel_fn(pts, *field_args)`` is the velocity field; keep ``vel_fn`` a
    stable function and route per-frame data through ``field_args`` to reuse
    the compiled integrator. Returns a list of dicts
    {x: [n,3], val: [n,3], time: [n]} per line, matching the reference
    `StreamLine` wire fields (`streamline.hpp:29`).
    """
    return _assemble(vel_fn, None, x0, dt_init, t_final, abs_err, rel_err,
                     back_integrate, max_steps, val_fn=vel_fn,
                     field_args=field_args)


def vortex_lines(vel_fn: Callable, x0, *, dt_init: float = 0.1,
                 t_final: float = 1.0, abs_err: float = 1e-10,
                 rel_err: float = 1e-6, back_integrate: bool = True,
                 max_steps: int = 512, eps: float | None = None,
                 field_args=()):
    """Trace vorticity field lines; val holds the vorticity along each line
    (`VortexLine::compute`, `streamline.cpp:115-165`). The singularity bailout
    tests the *velocity* like the reference's shared observer."""
    vort_fn = make_vorticity_fn(vel_fn, eps)
    return _assemble(vort_fn, vel_fn, x0, dt_init, t_final, abs_err, rel_err,
                     back_integrate, max_steps, val_fn=vort_fn,
                     field_args=field_args)
