from .mesh import make_mesh, shard_state  # noqa: F401
