from .mesh import FIBER_AXIS, make_mesh, shard_state  # noqa: F401
from .ring import (ring_oseen_contract, ring_stokeslet,  # noqa: F401
                   ring_stresslet)
