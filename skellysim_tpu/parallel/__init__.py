from .compat import use_mesh  # noqa: F401
from .mesh import (FIBER_AXIS, MEMBER_AXIS, make_mesh,  # noqa: F401
                   make_member_mesh, shard_ensemble, shard_state)
from .multihost import initialize as initialize_multihost  # noqa: F401
from .multihost import process_info  # noqa: F401
from .ring import (ring_oseen_contract, ring_stokeslet,  # noqa: F401
                   ring_stresslet)
from .spmd import (SpmdSolution, build_spmd_step,  # noqa: F401
                   spmd_shell_mode, spmd_step)
