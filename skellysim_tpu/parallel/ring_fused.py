"""Fused Pallas ring collectives: the source-block ring as ONE kernel.

The `lax.ppermute` ring (`parallel.ring._ring_accumulate`) expresses the
overlap intent — permute the next blocks, compute on the current ones — but
leaves the scheduling to XLA, and on the measured ladder
(MULTICHIP_r06/r07) the per-hop collective launch latency dominates the
coupled solve at exactly the sizes the SPMD step runs at. This module fuses
the WHOLE ring into one Pallas kernel per shard (SNIPPETS.md [1]-[3], the
jax distributed-pallas ring pattern): the neighbor transfer is a
`pltpu.make_async_remote_copy` RDMA started BEFORE the resident block's
pair-kernel arithmetic, so the ICI hop hides under VPU compute instead of
serializing with it, and the n_dev-1 hops cost zero collective launches
beyond the single kernel.

Scope (build-time checked, `fused_ring_fits`):

* f32 `impl="pallas"` tiles only — the kernel's pair math IS the Pallas
  tile math (`ops.pallas_kernels.stokeslet_tile_sums` /
  `stresslet_tile_sums`, one shared definition), so a user probing the
  exact/mxu tiles keeps the `ppermute` ring and its tile semantics;
* whole-shard blocks resident in VMEM (`audit.dmaflow.VMEM_PAIR_BUDGET`,
  the shared build/verify-time accounting): this is a
  LATENCY optimization for the solve-scale regime where the ladder loses
  to one device — bandwidth-bound blocks too big for VMEM fall back to the
  `ppermute` ring at build time, which already streams fine at scale;
* a compiled TPU backend. CPU CI always falls back (selection lives in
  `parallel.compat.fused_ring_mode`, so the call site in `parallel.ring`
  is ONE line shared by both paths); ``SKELLY_FUSED_RING=interpret`` opts
  the Pallas interpreter in where its remote-DMA emulation supports it.

Ring safety: ``n_dev`` comm slots, each written and read EXACTLY ONCE per
kernel instance — step ``s`` starts the RDMA of slot ``s`` into the right
neighbor's slot ``s+1``, computes on slot ``s`` while the transfer is in
flight, then waits its send+receive. No slot reuse means no mid-step
synchronization at all; the recv semaphore per slot is the only intra-step
ordering. Across kernel INSTANCES (the same call site re-executed inside
the solver loop, or back-to-back stokeslet/stresslet rings) the kernel
brackets itself with an ENTRY and an EXIT neighbor barrier: with both in
place a device needs 2 barrier credits per phase and its neighbors can
have produced at most 5 of the 6 credits required to reach instance k+1's
sends while a neighbor is still reading instance k — the counting makes
phase skew >= 2 impossible even though barrier credits are anonymous
(a single entry barrier alone would NOT be safe: a fast neighbor's next-
instance signal could stand in for a slow neighbor's missing one, and the
RDMA would overwrite comm slots still being read). This argument is no
longer only prose: the ``dma`` audit check (`audit.dmaflow`) re-derives it
from the traced kernel every CI run — per-slot read/write ordering against
the recv semaphores, credit balance, and an explicit-state search over the
barrier protocol that both proves the ENTRY+EXIT pairing bounds phase skew
to 1 and *derives* the entry-only counterexample as a reachable overwrite.
The slot buffers cost ``n_dev * (3 + payload_rows) * ns`` floats of VMEM,
bounded by `fused_ring_fits` alongside the pair tile.

The accumulation order around the ring is the SAME as the ppermute ring's
(my block first, then left neighbor's, ...), so the two paths agree to the
Pallas tile's usual f32 tolerance, shard by shard.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from ..ops.pallas_kernels import (_PAD_SENTINEL, _out_struct, _pad_to,
                                  stokeslet_tile_sums, stresslet_tile_sums)

#: payload rows in the rotating comm block (3 coord rows + payload rows)
_PAYLOAD_ROWS = {"stokeslet": 3, "stresslet": 9}

#: pallas_call collective_id for the ring's barrier semaphore (one ring
#: kernel family; concurrent distinct collectives would need distinct ids)
_COLLECTIVE_ID = 7


def fused_ring_fits(kind: str, n_trg: int, n_src: int,
                    n_dev: int = 1) -> bool:
    """True when the whole-block fused kernel serves this shape: known
    kernel family, padded pair tile inside the VMEM budget, and the
    n_dev-slot comm buffer inside its own. The budget accounting itself
    lives in `audit.dmaflow.fused_ring_within_budget` — ONE closed-form
    consulted both here (build-time eligibility) and by the ``dma`` audit
    check (verify-time gate on the traced kernel), so the two cannot
    drift. `audit.dmaflow` is import-light (no jax)."""
    from ..audit.dmaflow import fused_ring_within_budget

    if kind not in _PAYLOAD_ROWS:
        return False
    nt = -(-n_trg // 8) * 8
    ns = -(-n_src // 128) * 128
    return fused_ring_within_budget(_PAYLOAD_ROWS[kind], n_dev, nt, ns)


def _ring_kernel(kind: str, axis_name: str, n_dev: int):
    """Kernel body: resident targets x rotating [rows, ns] comm blocks."""
    tile_sums = (stokeslet_tile_sums if kind == "stokeslet"
                 else stresslet_tile_sums)

    def kernel(trg_ref, blk_ref, out_ref, comm, send_sem, recv_sem):
        my_id = lax.axis_index(axis_name)
        right = lax.rem(my_id + 1, n_dev)
        left = lax.rem(my_id + n_dev - 1, n_dev)

        comm[0] = blk_ref[:]
        out_ref[:] = jnp.zeros_like(out_ref)

        def neighbor_barrier():
            barrier_sem = pltpu.get_barrier_semaphore()
            for nb in (left, right):
                pltpu.semaphore_signal(
                    barrier_sem, inc=1, device_id=nb,
                    device_id_type=pltpu.DeviceIdType.LOGICAL)
            pltpu.semaphore_wait(barrier_sem, 2)

        # ENTRY barrier: no RDMA before both neighbors entered THIS
        # instance (paired with the exit barrier below, the credit count
        # bounds cross-instance skew to < 2 phases — module docstring)
        neighbor_barrier()

        for step in range(n_dev):      # static unroll: n_dev is mesh size
            rdma = None
            if step < n_dev - 1:
                # slot step -> right neighbor's slot step+1: every slot is
                # written once and read once, so steps need no slot-reuse
                # synchronization beyond the per-slot recv semaphore
                rdma = pltpu.make_async_remote_copy(
                    src_ref=comm.at[step], dst_ref=comm.at[step + 1],
                    send_sem=send_sem.at[step], recv_sem=recv_sem.at[step + 1],
                    device_id=right,
                    device_id_type=pltpu.DeviceIdType.LOGICAL)
                rdma.start()           # transfer in flight DURING compute
            blk = comm[step]
            ux, uy, uz = tile_sums(trg_ref[:], blk[:3], blk[3:])
            out_ref[0, :] += ux
            out_ref[1, :] += uy
            out_ref[2, :] += uz
            if step < n_dev - 1:
                rdma.wait()

        # EXIT barrier: we are done READING every comm slot; the paired
        # entry wait of the next instance cannot be satisfied while either
        # neighbor still sits before this point
        neighbor_barrier()

    return kernel


@partial(jax.jit, static_argnames=("kind", "axis_name", "n_dev", "interpret"))
def fused_ring_block_sum(kind: str, r_trg, src, payload, *, axis_name: str,
                         n_dev: int, interpret: bool = False):
    """UNSCALED ring-accumulated pair sum for one shard (call INSIDE the
    `shard_map` over ``axis_name``): [nt, 3] resident targets, [ns, 3]
    resident sources, payload [ns, 3] forces ("stokeslet") or [ns, 3, 3]
    stresslets. Drop-in for `parallel.ring._ring_accumulate`'s result (the
    caller applies the 1/(8 pi eta) scale), transfer overlapped with
    compute via one fused Pallas kernel.
    """
    prows = _PAYLOAD_ROWS[kind]
    n_trg, n_src = r_trg.shape[0], src.shape[0]
    dtype = r_trg.dtype

    nt = -(-n_trg // 8) * 8
    ns = -(-n_src // 128) * 128
    trg_T = _pad_to(r_trg.T, nt, axis=1)
    src_T = _pad_to(src.T, ns, axis=1, value=_PAD_SENTINEL)
    pay_T = _pad_to(payload.reshape(n_src, prows).T, ns, axis=1)
    blk = jnp.concatenate([src_T, pay_T], axis=0)  # [3 + prows, ns]

    # no grid: operands stage whole-block into VMEM (the budget check in
    # `fused_ring_fits` is what makes that legal), comm slots in VMEM so
    # the RDMA lands directly where the next step computes
    compiler_params = pltpu.TPUCompilerParams(collective_id=_COLLECTIVE_ID)
    u_T = pl.pallas_call(
        _ring_kernel(kind, axis_name, n_dev),
        out_shape=_out_struct((3, nt), dtype, trg_T, blk),
        scratch_shapes=(
            pltpu.VMEM((n_dev, 3 + prows, ns), dtype),
            pltpu.SemaphoreType.DMA((n_dev,)),
            pltpu.SemaphoreType.DMA((n_dev,)),
        ),
        compiler_params=compiler_params,
        interpret=interpret,
    )(trg_T, blk)
    return u_T.T[:n_trg]


def auditable_kernels():
    """The fused rings' entries for the ``dma`` audit check: both kernel
    families traced through `shard_map` on an 8-device ring at a shape
    `fused_ring_fits` accepts (the scene parameters ride along so the
    verifier can cross-check that build-time gate against the traced
    comm-buffer accounting). Defining this seam is also what licenses this
    module's DMA/semaphore callsites for the ``raw-dma`` lint rule."""
    from jax.sharding import PartitionSpec as P

    from ..audit.dmaflow import pallas_calls
    from ..audit.registry import AuditKernel, BuiltKernel
    from .compat import shard_map
    from .mesh import FIBER_AXIS, make_mesh

    n_dev, n_trg, n_src = 8, 8, 128

    def build(kind):
        def _build():
            payload_shape = ((n_src * n_dev, 3) if kind == "stokeslet"
                             else (n_src * n_dev, 3, 3))
            mesh = make_mesh(n_dev)
            fn = shard_map(
                lambda r, s, w: fused_ring_block_sum(
                    kind, r, s, w, axis_name=FIBER_AXIS, n_dev=n_dev),
                mesh=mesh,
                in_specs=(P(FIBER_AXIS), P(FIBER_AXIS), P(FIBER_AXIS)),
                out_specs=P(FIBER_AXIS))
            closed = jax.make_jaxpr(fn)(
                jnp.zeros((n_trg * n_dev, 3), jnp.float32),
                jnp.zeros((n_src * n_dev, 3), jnp.float32),
                jnp.zeros(payload_shape, jnp.float32))
            (kernel_jaxpr, grid_mapping), = pallas_calls(closed.jaxpr)
            return BuiltKernel(kernel_jaxpr=kernel_jaxpr,
                               grid_mapping=grid_mapping, n_dev=n_dev,
                               scene={"kind": kind, "n_trg": n_trg,
                                      "n_src": n_src})
        return _build

    return [
        AuditKernel(name=f"ring_{kind}_fused", layer="parallel",
                    summary=(f"fused {kind} ring: RDMA ring collective "
                             f"on an {n_dev}-device mesh"),
                    build=build(kind))
        for kind in ("stokeslet", "stresslet")
    ]
