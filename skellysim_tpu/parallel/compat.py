"""Version compatibility seam for the sharding API surface.

The repo targets the modern spellings (``jax.shard_map`` with ``check_vma``,
``jax.set_mesh``), but the pinned container jax (0.4.x) only ships
``jax.experimental.shard_map.shard_map`` with ``check_rep`` and has no
``jax.set_mesh`` at all — which left every mesh test red at seed. All
sharded code routes through this module so the call sites stay written
against the modern API and the fallback logic lives in exactly one place.
"""

from __future__ import annotations

import contextlib
import logging
import os

import jax

logger = logging.getLogger("skellysim_tpu")


def shard_map(f, *, mesh, in_specs, out_specs, check_vma: bool = True):
    """`jax.shard_map` where available, else the 0.4.x experimental API.

    ``check_vma`` maps onto the old ``check_rep``; the fallback always
    disables it because 0.4.x's replication checker has no rule for
    ``while``/``scan`` bodies (every solver loop here is a `lax.while_loop`)
    — the modern checker, where present, stays on as requested.
    """
    if hasattr(jax, "shard_map"):
        return jax.shard_map(f, mesh=mesh, in_specs=in_specs,
                             out_specs=out_specs, check_vma=check_vma)
    from jax.experimental.shard_map import shard_map as _shard_map

    return _shard_map(f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
                      check_rep=False)


def fused_ring_mode(impl: str = "pallas") -> str:
    """Build-time transfer-mode selection for the source-block rings:
    ``"fused"`` (one Pallas `make_async_remote_copy` kernel per ring,
    `parallel.ring_fused`), ``"fused-interpret"`` (the same kernel on the
    Pallas interpreter — CPU debugging, opt-in only), or ``"ppermute"``
    (the `lax.ppermute` loop). ONE call site in `parallel.ring` serves CPU
    CI and TPU runs; this function is where the fallback logic lives, next
    to the other version/backend seams.

    The fused kernel engages only for ``impl="pallas"`` (its pair math IS
    the Pallas tile math — exact/mxu probes must keep their tile
    semantics), on a compiled TPU backend whose pallas build ships the
    remote-DMA API. ``SKELLY_FUSED_RING=0`` forces the ppermute ring
    (escape hatch); ``SKELLY_FUSED_RING=interpret`` opts the interpreter
    in off-TPU (where its remote-DMA emulation supports it).

    Every ENVIRONMENTAL fallback from a pallas request — the build lacks
    pallas, ships no `make_async_remote_copy`, or the backend is not a
    compiled TPU — is a clean degrade, never a crash, and is logged as a
    structured ``fault`` telemetry event (kind ``fused_ring_fallback``
    with the reason) so a production run that silently lost its fused
    rings shows up in `obs summarize`'s fault table (docs/robustness.md).
    Explicit opt-outs (env override, non-pallas tile) are intentional and
    emit nothing.
    """
    override = os.environ.get("SKELLY_FUSED_RING", "").strip().lower()
    if override in ("0", "off", "ppermute"):
        return "ppermute"
    if impl != "pallas":
        return "ppermute"
    try:
        from jax.experimental.pallas import tpu as pltpu
    except Exception:  # pallas not shipped on this build
        return _fused_fallback("pallas-unavailable", leg="missing-api")
    if not hasattr(pltpu, "make_async_remote_copy"):
        return _fused_fallback("no-remote-dma", leg="missing-api")
    if override == "interpret":
        return "fused-interpret"
    if jax.default_backend() != "tpu":
        return _fused_fallback(f"backend-{jax.default_backend()}",
                               leg="platform")
    return "fused"


def _fused_fallback(reason: str, *, leg: str) -> str:
    """Log + emit the structured fault for an environmental fused-ring
    fallback; always returns "ppermute".

    ``leg`` names WHICH eligibility leg failed — ``missing-api`` (the jax
    build lacks pallas or remote DMA), ``platform`` (not a compiled TPU
    backend), or ``budget`` (`ring_fused.fused_ring_fits` rejected the
    shape; emitted from the `parallel.ring` call site via
    `fused_ring_budget_fallback`) — so `obs summarize`'s fault table
    distinguishes "too big for VMEM" from "not a TPU".
    """
    from ..obs import tracer as obs_tracer

    logger.warning("fused ring unavailable (%s): falling back to the "
                   "lax.ppermute ring", reason)
    obs_tracer.emit("fault", kind="fused_ring_fallback", reason=reason,
                    leg=leg)
    return "ppermute"


def fused_ring_budget_fallback(kind: str, n_trg: int, n_src: int,
                               n_dev: int) -> None:
    """Emit the budget-leg fallback fault from the ring call site: the
    backend could run the fused kernel, but the shape failed the VMEM
    eligibility check — without this event that fallback was silent, and
    the fault table could not tell it apart from an environmental one."""
    _fused_fallback(
        f"vmem-budget-{kind}-{n_trg}x{n_src}x{n_dev}", leg="budget")


def use_mesh(mesh):
    """Context manager activating ``mesh`` for sharding resolution.

    ``jax.set_mesh`` on modern jax; on 0.4.x the `Mesh` object itself is the
    (legacy) context manager. A None mesh is a no-op context either way.
    """
    if mesh is None:
        return contextlib.nullcontext()
    if hasattr(jax, "set_mesh"):
        return jax.set_mesh(mesh)
    return mesh
