"""Device mesh + sharding helpers.

TPU-native replacement for the reference's MPI domain decomposition
(SURVEY.md §2.3): the fiber batch axis is sharded over a 1-D mesh (the analogue
of the round-robin fiber distribution, `fiber_container_finite_difference.cpp:98-121`);
small replicated state (bodies, time, dt) stays replicated (the analogue of the
reference's rank-0 body ownership + Bcast). XLA GSPMD inserts the all-gathers /
psums that the reference issued explicitly through MPI.
"""

from __future__ import annotations

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

FIBER_AXIS = "fib"

#: ensemble member (batch) axis — batch parallelism is the OUTER axis: B
#: independent small-N simulations per device beat sharding any one of them
MEMBER_AXIS = "member"


def make_mesh(n_devices: int | None = None) -> Mesh:
    devs = jax.devices()
    if n_devices is not None:
        devs = devs[:n_devices]
    return Mesh(np.array(devs), (FIBER_AXIS,))


def make_member_mesh(n_devices: int | None = None) -> Mesh:
    """1-D mesh over the ensemble member axis (`shard_ensemble`)."""
    devs = jax.devices()
    if n_devices is not None:
        devs = devs[:n_devices]
    return Mesh(np.array(devs), (MEMBER_AXIS,))


def shard_ensemble(ens, mesh: Mesh):
    """Shard an `ensemble.EnsembleState`'s member axis across the mesh.

    Every ensemble leaf carries a leading [B] member axis (per-member
    time/dt/t_final included), so placement is uniform: axis 0 splits over
    ``MEMBER_AXIS``, trailing axes stay unsharded. The data-parallel outer
    axis of the ISSUE's serving analogy — each device owns B/D whole
    members, and the vmapped batch step needs no cross-device collectives
    at all (GSPMD sees fully independent rows). Requires the vmap execution
    plan: "unroll" inlines lanes as separate subgraphs, which do not split
    over devices. B must divide the mesh size evenly (pjit rejects uneven
    shardings, and an uneven remainder would silently replicate).
    """
    B = ens.t_final.shape[0]
    if B % mesh.size != 0:
        raise ValueError(
            f"ensemble batch B={B} is not divisible by the mesh size "
            f"({mesh.size}); pick B as a multiple of the device count (idle "
            "padding lanes are cheap — the scheduler masks them)")
    member_sharding = NamedSharding(mesh, P(MEMBER_AXIS))
    return jax.tree_util.tree_map(
        lambda leaf: jax.device_put(jax.numpy.asarray(leaf), member_sharding),
        ens)


def shard_state(state, mesh: Mesh, *, allow_replicated_shell: bool = False):
    """Place a SimState on the mesh.

    - fiber-batch leaves: sharded along the fiber axis;
    - shell dense operators (stresslet_plus_complementary, M_inv): row-sharded
      — the analogue of the reference's Scatterv'd shell rows
      (`periphery.cpp:408-442`), whose matvec becomes all-gather(density) +
      local row-block GEMV (`periphery.cpp:21-47`), inserted by GSPMD;
    - everything else (small body state, scalars, shell vectors): replicated.

    pjit rejects uneven shardings, so the shell rows can only distribute when
    the mesh size divides 3*n_nodes. Anything else raises: silently
    replicating an O(n_nodes^2) matrix per device turns the expected O(N/D)
    footprint into D copies of the full operator, an OOM a user would only
    find with a profiler. Pass ``allow_replicated_shell=True`` to opt in for
    small shells.
    """
    fib_sharding = NamedSharding(mesh, P(FIBER_AXIS))
    row_sharding = NamedSharding(mesh, P(FIBER_AXIS, None))
    rep_sharding = NamedSharding(mesh, P())

    from ..fibers.container import as_buckets

    nfs = {g.n_fibers for g in as_buckets(state.fibers) if g.n_fibers > 0}

    def place(leaf):
        leaf = jax.numpy.asarray(leaf)
        if (leaf.ndim >= 1 and leaf.shape[0] in nfs
                and leaf.shape[0] % mesh.size == 0):
            return jax.device_put(leaf, fib_sharding)
        return jax.device_put(leaf, rep_sharding)

    # place the O(n^2) shell operators straight to their final sharding (never
    # replicate them first — peak per-device memory would be the full matrix)
    shell = state.shell
    state = jax.tree_util.tree_map(place, state._replace(shell=None))
    if shell is not None:
        rows = shell.M_inv.shape[0]
        if rows % mesh.size == 0:
            big = row_sharding
        elif allow_replicated_shell:
            big = rep_sharding
        else:
            raise ValueError(
                f"shell operator rows (3*n_nodes = {rows}) are not divisible "
                f"by the mesh size ({mesh.size}), so the O(n_nodes^2) dense "
                "operators cannot be row-sharded and would be fully replicated "
                "on every device. Pick a shell n_nodes that is a multiple of "
                f"{mesh.size}, or pass allow_replicated_shell=True to accept "
                "the per-device memory cost.")
        rest = jax.tree_util.tree_map(
            place, shell._replace(stresslet_plus_complementary=None,
                                  M_inv=None))
        shell = rest._replace(
            stresslet_plus_complementary=jax.device_put(
                shell.stresslet_plus_complementary, big),
            M_inv=jax.device_put(shell.M_inv, big))
    return state._replace(shell=shell)
