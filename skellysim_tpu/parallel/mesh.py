"""Device mesh + sharding helpers.

TPU-native replacement for the reference's MPI domain decomposition
(SURVEY.md §2.3): the fiber batch axis is sharded over a 1-D mesh (the analogue
of the round-robin fiber distribution, `fiber_container_finite_difference.cpp:98-121`);
small replicated state (bodies, time, dt) stays replicated (the analogue of the
reference's rank-0 body ownership + Bcast). XLA GSPMD inserts the all-gathers /
psums that the reference issued explicitly through MPI.
"""

from __future__ import annotations

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

FIBER_AXIS = "fib"


def make_mesh(n_devices: int | None = None) -> Mesh:
    devs = jax.devices()
    if n_devices is not None:
        devs = devs[:n_devices]
    return Mesh(np.array(devs), (FIBER_AXIS,))


def shard_state(state, mesh: Mesh):
    """Place a SimState on the mesh.

    - fiber-batch leaves: sharded along the fiber axis;
    - shell dense operators (stresslet_plus_complementary, M_inv): row-sharded
      — the analogue of the reference's Scatterv'd shell rows
      (`periphery.cpp:408-442`), whose matvec becomes all-gather(density) +
      local row-block GEMV (`periphery.cpp:21-47`), inserted by GSPMD;
    - everything else (small body state, scalars, shell vectors): replicated.
    """
    fib_sharding = NamedSharding(mesh, P(FIBER_AXIS))
    row_sharding = NamedSharding(mesh, P(FIBER_AXIS, None))
    rep_sharding = NamedSharding(mesh, P())

    nf = state.fibers.n_fibers if state.fibers is not None else 0

    def place(leaf):
        leaf = jax.numpy.asarray(leaf)
        if leaf.ndim >= 1 and nf > 0 and leaf.shape[0] == nf and nf % mesh.size == 0:
            return jax.device_put(leaf, fib_sharding)
        return jax.device_put(leaf, rep_sharding)

    # place the O(n^2) shell operators straight to their final sharding (never
    # replicate them first — peak per-device memory would be the full matrix);
    # pjit rejects uneven shardings, so rows distribute only when the mesh
    # size divides 3*n_nodes (pick shell n_nodes accordingly)
    shell = state.shell
    state = jax.tree_util.tree_map(place, state._replace(shell=None))
    if shell is not None:
        big = (row_sharding if shell.M_inv.shape[0] % mesh.size == 0
               else rep_sharding)
        rest = jax.tree_util.tree_map(
            place, shell._replace(stresslet_plus_complementary=None,
                                  M_inv=None))
        shell = rest._replace(
            stresslet_plus_complementary=jax.device_put(
                shell.stresslet_plus_complementary, big),
            M_inv=jax.device_put(shell.M_inv, big))
    return state._replace(shell=shell)
