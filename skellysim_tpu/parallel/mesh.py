"""Device mesh + sharding helpers.

TPU-native replacement for the reference's MPI domain decomposition
(SURVEY.md §2.3): the fiber batch axis is sharded over a 1-D mesh (the analogue
of the round-robin fiber distribution, `fiber_container_finite_difference.cpp:98-121`);
small replicated state (bodies, time, dt) stays replicated (the analogue of the
reference's rank-0 body ownership + Bcast). XLA GSPMD inserts the all-gathers /
psums that the reference issued explicitly through MPI.
"""

from __future__ import annotations

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

FIBER_AXIS = "fib"


def make_mesh(n_devices: int | None = None) -> Mesh:
    devs = jax.devices()
    if n_devices is not None:
        devs = devs[:n_devices]
    return Mesh(np.array(devs), (FIBER_AXIS,))


def shard_state(state, mesh: Mesh):
    """Place a SimState: fiber-batch leaves sharded over the mesh, rest replicated."""
    fib_sharding = NamedSharding(mesh, P(FIBER_AXIS))
    rep_sharding = NamedSharding(mesh, P())

    nf = state.fibers.n_fibers if state.fibers is not None else 0

    def place(leaf):
        leaf = jax.numpy.asarray(leaf)
        if leaf.ndim >= 1 and nf > 0 and leaf.shape[0] == nf and nf % mesh.size == 0:
            return jax.device_put(leaf, fib_sharding)
        return jax.device_put(leaf, rep_sharding)

    return jax.tree_util.tree_map(place, state)
