"""Device mesh + sharding helpers.

TPU-native replacement for the reference's MPI domain decomposition
(SURVEY.md §2.3): the fiber batch axis is sharded over a 1-D mesh (the analogue
of the round-robin fiber distribution, `fiber_container_finite_difference.cpp:98-121`);
small replicated state (bodies, time, dt) stays replicated (the analogue of the
reference's rank-0 body ownership + Bcast). XLA GSPMD inserts the all-gathers /
psums that the reference issued explicitly through MPI.
"""

from __future__ import annotations

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

FIBER_AXIS = "fib"

#: ensemble member (batch) axis — batch parallelism is the OUTER axis: B
#: independent small-N simulations per device beat sharding any one of them
MEMBER_AXIS = "member"


def make_mesh(n_devices: int | None = None) -> Mesh:
    devs = jax.devices()
    if n_devices is not None:
        devs = devs[:n_devices]
    return Mesh(np.array(devs), (FIBER_AXIS,))


def make_member_mesh(n_devices: int | None = None) -> Mesh:
    """1-D mesh over the ensemble member axis (`shard_ensemble`)."""
    devs = jax.devices()
    if n_devices is not None:
        devs = devs[:n_devices]
    return Mesh(np.array(devs), (MEMBER_AXIS,))


def make_2d_mesh(n_member: int, n_fiber: int) -> Mesh:
    """(member, fiber) 2-D sub-mesh — ROADMAP item 1's shape: the ensemble
    member axis outermost, each member's fibers sharded over its own
    ``n_fiber``-device group. Collectives over ``FIBER_AXIS`` then stay
    inside a member's group; ``MEMBER_AXIS`` collectives cross groups."""
    need = n_member * n_fiber
    devs = jax.devices()
    if len(devs) < need:
        raise ValueError(
            f"2-D mesh {n_member}x{n_fiber} needs {need} devices, "
            f"have {len(devs)}")
    return Mesh(np.array(devs[:need]).reshape(n_member, n_fiber),
                (MEMBER_AXIS, FIBER_AXIS))


def shard_ensemble(ens, mesh: Mesh):
    """Shard an `ensemble.EnsembleState`'s member axis across the mesh.

    Every ensemble leaf carries a leading [B] member axis (per-member
    time/dt/t_final included), so placement is uniform: axis 0 splits over
    ``MEMBER_AXIS``, trailing axes stay unsharded. The data-parallel outer
    axis of the ISSUE's serving analogy — each device owns B/D whole
    members, and the vmapped batch step needs no cross-device collectives
    at all (GSPMD sees fully independent rows). Requires the vmap execution
    plan: "unroll" inlines lanes as separate subgraphs, which do not split
    over devices. B must divide the mesh size evenly (pjit rejects uneven
    shardings, and an uneven remainder would silently replicate).
    """
    B = ens.t_final.shape[0]
    if B % mesh.size != 0:
        raise ValueError(
            f"ensemble batch B={B} is not divisible by the mesh size "
            f"({mesh.size}); pick B as a multiple of the device count (idle "
            "padding lanes are cheap — the scheduler masks them)")
    member_sharding = NamedSharding(mesh, P(MEMBER_AXIS))
    return jax.tree_util.tree_map(
        lambda leaf: jax.device_put(jax.numpy.asarray(leaf), member_sharding),
        ens)


#: shell placement schema, by PeripheryState FIELD NAME: the two O(n_nodes^2)
#: dense operators row-shard (the analogue of the reference's Scatterv'd shell
#: rows, `periphery.cpp:408-442`, whose matvec becomes all-gather(density) +
#: local row-block GEMV, `periphery.cpp:21-47`); every other shell leaf
#: (nodes/normals/weights/density — all O(n_nodes)) replicates.
SHELL_ROW_SHARDED_FIELDS = ("stresslet_plus_complementary", "M_inv")


def shard_state(state, mesh: Mesh, *, allow_replicated_shell: bool = False):
    """Place a SimState on the mesh, schema-driven off the field names.

    - ``fibers``: every leaf of a bucket is [n_fibers]-leading by
      construction (`fibers.container.FiberGroup`), so the whole bucket
      shards along the fiber axis when the mesh divides its fiber count
      (and replicates as a unit otherwise);
    - ``shell``: per-field spec table (`SHELL_ROW_SHARDED_FIELDS`) — the
      dense operators row-shard, the O(n_nodes) vectors replicate;
    - everything else (time/dt scalars, bodies, point/background sources):
      replicated, the analogue of the reference's rank-0 body ownership.

    Placement used to shape-sniff leaves (leading dim == some bucket's
    n_fibers), which mis-sharded any replicated leaf whose length collided
    with a fiber count — e.g. a [3*n_nodes] shell density when n_fibers ==
    3*n_nodes (regression-pinned in tests/test_shell_sharding.py). Field
    names, not shapes, now decide.

    pjit rejects uneven shardings, so the shell rows can only distribute when
    the mesh size divides 3*n_nodes. Anything else raises: silently
    replicating an O(n_nodes^2) matrix per device turns the expected O(N/D)
    footprint into D copies of the full operator, an OOM a user would only
    find with a profiler. Pass ``allow_replicated_shell=True`` to opt in for
    small shells.
    """
    fib_sharding = NamedSharding(mesh, P(FIBER_AXIS))
    row_sharding = NamedSharding(mesh, P(FIBER_AXIS, None))
    rep_sharding = NamedSharding(mesh, P())

    from ..fibers.container import FiberGroup, as_buckets

    def rep(leaf):
        return jax.device_put(jax.numpy.asarray(leaf), rep_sharding)

    def place_bucket(group):
        if group.n_fibers > 0 and group.n_fibers % mesh.size == 0:
            return jax.tree_util.tree_map(
                lambda leaf: jax.device_put(jax.numpy.asarray(leaf),
                                            fib_sharding), group)
        return jax.tree_util.tree_map(rep, group)

    fibers = state.fibers
    if fibers is not None:
        placed = tuple(place_bucket(g) for g in as_buckets(fibers))
        fibers = placed[0] if isinstance(fibers, FiberGroup) else placed

    shell = state.shell
    if shell is not None:
        rows = shell.M_inv.shape[0]
        if rows % mesh.size == 0:
            big = row_sharding
        elif allow_replicated_shell:
            big = rep_sharding
        else:
            raise ValueError(
                f"shell operator rows (3*n_nodes = {rows}) are not divisible "
                f"by the mesh size ({mesh.size}), so the O(n_nodes^2) dense "
                "operators cannot be row-sharded and would be fully replicated "
                "on every device. Pick a shell n_nodes that is a multiple of "
                f"{mesh.size}, or pass allow_replicated_shell=True to accept "
                "the per-device memory cost.")
        # place the O(n^2) operators straight to their final sharding (never
        # replicate them first — peak per-device memory would be the full
        # matrix)
        shell = type(shell)(*[
            leaf if leaf is None else
            jax.device_put(jax.numpy.asarray(leaf),
                           big if name in SHELL_ROW_SHARDED_FIELDS else
                           rep_sharding)
            for name, leaf in zip(shell._fields, shell)])

    rest = jax.tree_util.tree_map(
        rep, state._replace(fibers=None, shell=None))
    return rest._replace(fibers=fibers, shell=shell)
