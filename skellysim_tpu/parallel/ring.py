"""Ring-pass pairwise Stokes kernels over a device mesh.

Multi-chip evaluation of the all-to-all N-body sums (the framework's hottest
op, SURVEY.md §2.3/§5.7): instead of all-gathering every source onto every
chip (the GSPMD default for the dense kernels, and the analogue of the
reference FMM's cross-rank coupling, `/root/reference/include/kernels.hpp:78-122`),
each chip keeps its target block resident and the source blocks rotate
neighbor-to-neighbor around the ICI ring with `lax.ppermute` — structurally
identical to ring attention's KV-block rotation, applied to Stokes kernels.
Peak per-chip memory is O(N/D) instead of O(N), and every hop is a
nearest-neighbor ICI transfer that overlaps with the local block computation.
On TPU backends the whole ring can build as ONE fused Pallas
`make_async_remote_copy` kernel instead of D-1 ppermute launches
(`parallel.ring_fused`; selection at build time via
`compat.fused_ring_mode`, shared call site `_ring_or_fused`).

All functions take sources/targets/densities sharded along their leading axis
over ``mesh`` (pad to a multiple of the mesh size) and return targets with the
same sharding. The per-block math is shared with `ops.kernels`
(stokeslet_block / stresslet_block / oseen_block), so the self-term masking
and regularization semantics are identical by construction; coincident-point
masking works across blocks because coincidence is a property of the
coordinates, not the block layout.
"""

from __future__ import annotations

import math
from functools import partial

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

from ..ops.kernels import (DEFAULT_EPS, DEFAULT_REG, oseen_block,
                           pallas_impl_for, stokeslet_block,
                           stokeslet_block_mxu, stresslet_block,
                           stresslet_block_mxu)
from .compat import fused_ring_mode, shard_map
from .mesh import FIBER_AXIS


def _ring_or_fused(kind, impl: str, block_fn, axis_name: str, n_dev: int,
                   r_trg, *rotating, unroll: bool = False):
    """THE ring call site: fused Pallas ring kernel where the build-time
    seam (`compat.fused_ring_mode`) selects it, else the `lax.ppermute`
    accumulation — CPU CI and TPU runs share this one dispatch.

    ``kind`` names the fused kernel family ("stokeslet"/"stresslet"; None
    for tiles the fused path does not serve, e.g. the Oseen contraction
    and the DF accuracy tier). Selection is per-build: the fused kernel
    additionally requires whole-shard blocks inside its VMEM budget
    (`ring_fused.fused_ring_fits`) and a multi-device ring.
    """
    mode = fused_ring_mode(impl) if kind is not None else "ppermute"
    if mode != "ppermute" and n_dev > 1:
        from . import ring_fused
        from .compat import fused_ring_budget_fallback

        if ring_fused.fused_ring_fits(kind, r_trg.shape[0],
                                      rotating[0].shape[0], n_dev):
            return ring_fused.fused_ring_block_sum(
                kind, r_trg, *rotating, axis_name=axis_name, n_dev=n_dev,
                interpret=(mode == "fused-interpret"))
        # eligible backend, ineligible shape: the budget leg (trace-time
        # event — shapes are static, so this fires once per build)
        fused_ring_budget_fallback(kind, r_trg.shape[0],
                                   rotating[0].shape[0], n_dev)
    return _ring_accumulate(lambda *r: block_fn(r_trg, *r), axis_name,
                            n_dev, jnp.zeros_like(r_trg), *rotating,
                            unroll=unroll)


def _ring_accumulate(block_fn, axis_name: str, n_dev: int, u0, *rotating,
                     unroll: bool = False):
    """Accumulate ``block_fn(*rotating)`` over all ring positions.

    Each iteration launches the permute of the *next* blocks before computing
    on the current ones — the two are data-independent, so the ICI hop
    overlaps with the local block computation. The final position is consumed
    outside the loop: n_dev-1 hops total, no wasted trailing transfer.
    ``unroll`` replaces the fori_loop with a Python loop (same graph,
    statically unrolled) — required for tiles whose lowering cannot nest in a
    loop body (interpret-mode pallas_call trips a lowering-cache KeyError).
    """
    if n_dev == 1:
        return u0 + block_fn(*rotating)
    perm = [(j, (j + 1) % n_dev) for j in range(n_dev)]

    def step(i, carry):
        u, rot = carry
        # "ring-step" device-time scope (obs/profile.py): one hop's
        # ppermute + resident-block pair math — metadata only, the
        # collective inventory contracts are unchanged
        with jax.named_scope("ring-step"):
            nxt = jax.tree_util.tree_map(
                lambda a: lax.ppermute(a, axis_name, perm), rot)
            u = u + block_fn(*rot)
        return u, nxt

    carry = (u0, tuple(rotating))
    if unroll:
        for i in range(n_dev - 1):
            carry = step(i, carry)
        u, rot = carry
    else:
        u, rot = lax.fori_loop(0, n_dev - 1, step, carry)
    with jax.named_scope("ring-step"):
        return u + block_fn(*rot)


def _pallas_interpret(impl: str) -> bool:
    """True when the pallas tile will run in interpret mode (CPU test
    meshes). Interpret mode needs two workarounds in `_ring_eval` — static
    unrolling (interpret pallas_call in a fori_loop body trips a
    lowering-cache KeyError) and check_vma=False (its grid emulation's
    dynamic_slice mixes varying/non-varying operands) — that the compiled
    Mosaic path must NOT pay: unrolling a v5p-256 ring would duplicate 255
    kernel launches, and vma checking should stay on where it works."""
    return impl == "pallas" and jax.default_backend() == "cpu"


def _ring_block(impl: str, exact_block, mxu_block, pallas_block_name=None):
    """Tile dispatch for the ring evaluator. Names the ring does NOT serve
    ("df" has its own ring entry points) raise instead of silently running
    the exact tile — a user probing a specific tile on a mesh must not get
    exact-tile results misattributed to it."""
    if impl == "exact":
        return exact_block
    if impl == "mxu":
        return mxu_block
    if impl == "pallas" and pallas_block_name is not None:
        # the fused VMEM tile composes with shard_map: each chip runs the
        # Mosaic kernel on its resident target shard x the rotating source
        # shard. Import lazily so exact/mxu ring users never pay the
        # jax.experimental.pallas import.
        from ..ops import pallas_kernels

        return partial(getattr(pallas_kernels, pallas_block_name),
                       interpret=jax.default_backend() == "cpu")
    raise ValueError(
        f"ring evaluator has no {impl!r} tile; use 'exact', 'mxu', or "
        "'pallas' (double-float rides ring_stokeslet_df / ring_stresslet_df)")


def _ring_eval(block_fn, mesh: Mesh, axis_name: str, specs, scale, *operands,
               unroll: bool = False, kind: str | None = None,
               impl: str = "exact"):
    """shard_map a ring accumulation: operands[0] = targets (stay resident),
    operands[1:] rotate. ``kind``/``impl`` feed the fused-ring dispatch
    (`_ring_or_fused`)."""
    n_dev = mesh.shape[axis_name]

    def local(trg_l, *rot_l):
        u = _ring_or_fused(kind, impl, block_fn, axis_name, n_dev, trg_l,
                           *rot_l, unroll=unroll)
        return u * scale

    # check_vma off on the interpret-mode pallas path only (see
    # _pallas_interpret): its grid emulation's dynamic_slice mixes
    # varying/non-varying operands, which the vma checker rejects — the jax
    # error message itself prescribes check_vma=False as the workaround
    return shard_map(local, mesh=mesh, in_specs=specs,
                     out_specs=P(axis_name),
                     check_vma=not unroll)(*operands)


#: per-kernel block table for `ring_flow_local`: (exact block, MXU block,
#: pallas block name, XLA DF block name, pallas DF block name)
_LOCAL_FLOW_BLOCKS = {
    "stokeslet": (stokeslet_block, stokeslet_block_mxu,
                  "stokeslet_pallas_block", "_stokeslet_block_df",
                  "stokeslet_pallas_df_block"),
    "stresslet": (stresslet_block, stresslet_block_mxu,
                  "stresslet_pallas_block", "_stresslet_block_df",
                  "stresslet_pallas_df_block"),
}


def ring_flow_local(kind: str, impl: str, r_trg, src, payload, eta, *,
                    axis_name: str, n_dev: int, ring: bool = True):
    """Pairwise flow for callers ALREADY INSIDE a `shard_map` over
    ``axis_name`` (the SPMD full step, `parallel.spmd`) — the ONE place the
    local-ring evaluation contract lives for every tile family, so the DF
    seam (f64 accumulate, weak-typing-safe eta scale, cast back to the
    target dtype) cannot drift between the fiber and shell callers.

    ``kind`` picks the kernel ("stokeslet" payload [n, 3] forces,
    "stresslet" payload [n, 3, 3]); ``impl`` any of the tile names
    (exact/mxu/pallas/df/pallas_df — pallas falls back per
    `ops.kernels.pallas_impl_for`, interpret-mode unrolling per
    `_pallas_interpret`). ``ring=True`` accumulates over the rotating
    source blocks (targets stay resident — every shard's targets see all
    sources after n_dev-1 `ppermute` hops); ``ring=False`` evaluates ONE
    local source-block partial for the caller to `psum` — the evaluation
    strategy for REPLICATED target rows, whose values must come out
    bitwise identical on every shard (a ring would add the same terms in a
    different order per shard).
    """
    exact_block, mxu_block, pallas_name, df_name, pallas_df_name = \
        _LOCAL_FLOW_BLOCKS[kind]
    if impl in ("df", "pallas_df"):
        from ..ops import df_kernels

        if not jax.config.jax_enable_x64:
            raise RuntimeError("DF ring tiles need jax_enable_x64 for "
                               "their float64 accumulator")
        block, interp = _df_ring_block(impl, getattr(df_kernels, df_name),
                                       pallas_df_name)
        th, tl = df_kernels._df_split(r_trg)
        sh, sl = df_kernels._df_split(src)
        ph, pl = df_kernels._df_split(payload)
        # eta enters as an f64 scalar: a weak-typed eta would demote the
        # f64 DF accumulator
        scale = jnp.asarray(1.0 / (8.0 * math.pi), dtype=jnp.float64) \
            / jnp.asarray(eta, dtype=jnp.float64)
        if ring:
            # accumulator derived via zeros_like so it carries the
            # mesh-varying axis (see `_ring_df`)
            u0 = jnp.zeros_like(th, dtype=jnp.float64)
            u = _ring_accumulate(
                lambda sh_r, sl_r, ph_r, pl_r: block(
                    (th, tl), (sh_r, sl_r), (ph_r, pl_r)),
                axis_name, n_dev, u0, sh, sl, ph, pl, unroll=interp)
        else:
            u = block((th, tl), (sh, sl), (ph, pl))
        # seam contract: DF accumulates f64, callers get the target dtype
        return (u * scale).astype(r_trg.dtype)

    impl = pallas_impl_for(impl, r_trg, src, payload)
    block = _ring_block(impl, exact_block, mxu_block, pallas_name)
    scale = 1.0 / (8.0 * math.pi * eta)
    if ring:
        u = _ring_or_fused(kind, impl, block, axis_name, n_dev, r_trg,
                           src, payload, unroll=_pallas_interpret(impl))
    else:
        u = block(r_trg, src, payload)
    return u * scale


@partial(jax.jit, static_argnames=("mesh", "axis_name", "impl"))
def ring_stokeslet(r_src, r_trg, f_src, eta, *, mesh: Mesh,
                   axis_name: str = FIBER_AXIS, impl: str = "exact"):
    """Ring-parallel singular Stokeslet sum (`ops.kernels.stokeslet_direct`).

    Leading axes of ``r_src``/``f_src``/``r_trg`` must be divisible by the
    mesh size. ``impl="mxu"`` uses the matmul-form tile; each rotating
    source shard recenters on its own first point inside the tile
    (`stokeslet_block_mxu`), so the f32 cancellation bound scales with the
    shard's spatial extent.
    """
    spec = P(axis_name)
    impl = pallas_impl_for(impl, r_trg, r_src, f_src)
    block = _ring_block(impl, stokeslet_block, stokeslet_block_mxu,
                        "stokeslet_pallas_block")
    return _ring_eval(block, mesh, axis_name, (spec, spec, spec),
                      1.0 / (8.0 * math.pi * eta), r_trg, r_src, f_src,
                      unroll=_pallas_interpret(impl), kind="stokeslet",
                      impl=impl)


@partial(jax.jit, static_argnames=("mesh", "axis_name", "impl"))
def ring_stresslet(r_dl, r_trg, f_dl, eta, *, mesh: Mesh,
                   axis_name: str = FIBER_AXIS, impl: str = "exact"):
    """Ring-parallel stresslet (double-layer) sum
    (`ops.kernels.stresslet_direct`); ``f_dl`` is [n_src, 3, 3]."""
    spec = P(axis_name)
    impl = pallas_impl_for(impl, r_trg, r_dl, f_dl)
    block = _ring_block(impl, stresslet_block, stresslet_block_mxu,
                        "stresslet_pallas_block")
    return _ring_eval(block, mesh, axis_name,
                      (spec, spec, P(axis_name, None, None)),
                      1.0 / (8.0 * math.pi * eta), r_trg, r_dl, f_dl,
                      unroll=_pallas_interpret(impl), kind="stresslet",
                      impl=impl)


def _df_ring_block(impl: str, xla_block, pallas_block_name: str):
    """DF tile dispatch: "df" = the XLA blocks, "pallas_df" = the fused
    Pallas DF tiles (`ops.pallas_df`), interpret-mode on CPU like the exact
    pallas ring path. Returns (block_fn, interpret)."""
    if impl == "df":
        return xla_block, False
    if impl == "pallas_df":
        from ..ops import pallas_df

        interpret = jax.default_backend() == "cpu"
        return partial(getattr(pallas_df, pallas_block_name),
                       interpret=interpret), interpret
    raise ValueError(f"DF ring tiles serve 'df' or 'pallas_df', got {impl!r}")


def _ring_df(block_fn, mesh: Mesh, axis_name: str, r_src, r_trg, payload, eta,
             unroll: bool = False):
    """Shared driver for the double-float ring tiles.

    The (hi, lo) f32 split happens OUTSIDE the shard_map so the word pairs
    rotate the ring together; each chip accumulates its resident target
    block in f64 (one exact hi+lo conversion per partial sum, never per
    pair). This is the refinement tile the mixed-precision solver needs on
    a mesh — without it ring+mixed fell back to emulated f64 (~100x f32 on
    TPU; round-3 verdict weak #6). ``unroll`` is the interpret-mode pallas
    workaround (see `_pallas_interpret`)."""
    import jax.numpy as _jnp

    from ..ops.df_kernels import _df_split

    if not jax.config.jax_enable_x64:
        raise RuntimeError(
            "DF ring tiles need jax_enable_x64 for their float64 accumulator")
    n_dev = mesh.shape[axis_name]
    spec = P(axis_name)
    th, tl = _df_split(r_trg)
    sh, sl = _df_split(r_src)
    ph, pl = _df_split(payload)

    def local(th_l, tl_l, sh_l, sl_l, ph_l, pl_l):
        # derive the accumulator from the sharded operand so it carries the
        # mesh-varying axis (a fresh jnp.zeros is unvarying and shard_map's
        # scan rejects the carry mismatch)
        u0 = jnp.zeros_like(th_l, dtype=jnp.float64)  # skelly-lint: ignore[dtype-discipline] — DF ring tile: the f64 accumulator IS the contract (callers get float64 targets; `flow_multi` casts back at the seam)
        u = _ring_accumulate(
            lambda sh_r, sl_r, ph_r, pl_r: block_fn(
                (th_l, tl_l), (sh_r, sl_r), (ph_r, pl_r)),
            axis_name, n_dev, u0, sh_l, sl_l, ph_l, pl_l, unroll=unroll)
        return u / (8.0 * math.pi) / _jnp.asarray(eta, dtype=jnp.float64)  # skelly-lint: ignore[dtype-discipline] — eta scales the f64 DF accumulator; a weak-typed eta would demote it

    return shard_map(local, mesh=mesh, in_specs=(spec,) * 6,
                     out_specs=spec,
                     check_vma=not unroll)(th, tl, sh, sl, ph, pl)


@partial(jax.jit, static_argnames=("mesh", "axis_name", "impl"))
def ring_stokeslet_df(r_src, r_trg, f_src, eta, *, mesh: Mesh,
                      axis_name: str = FIBER_AXIS, impl: str = "df"):
    """Ring-parallel double-float Stokeslet (`ops.df_kernels`): ~1e-14-class
    pair accuracy from f32 VPU ops, sharded like `ring_stokeslet`. Returns
    float64 targets. ``impl="pallas_df"`` runs the fused Pallas DF tile on
    each chip (`ops.pallas_df.stokeslet_pallas_df_block`)."""
    from ..ops.df_kernels import _stokeslet_block_df

    block, interp = _df_ring_block(impl, _stokeslet_block_df,
                                   "stokeslet_pallas_df_block")
    return _ring_df(block, mesh, axis_name, r_src, r_trg, f_src, eta,
                    unroll=interp)


@partial(jax.jit, static_argnames=("mesh", "axis_name", "impl"))
def ring_stresslet_df(r_dl, r_trg, f_dl, eta, *, mesh: Mesh,
                      axis_name: str = FIBER_AXIS, impl: str = "df"):
    """Ring-parallel double-float stresslet; ``f_dl`` is [n_src, 3, 3]."""
    from ..ops.df_kernels import _stresslet_block_df

    block, interp = _df_ring_block(impl, _stresslet_block_df,
                                   "stresslet_pallas_df_block")
    return _ring_df(block, mesh, axis_name, r_dl, r_trg, f_dl, eta,
                    unroll=interp)


@partial(jax.jit, static_argnames=("mesh", "axis_name"))
def ring_oseen_contract(r_src, r_trg, density, eta, reg=DEFAULT_REG,
                        epsilon_distance=DEFAULT_EPS, *, mesh: Mesh,
                        axis_name: str = FIBER_AXIS):
    """Ring-parallel regularized Oseen contraction
    (`ops.kernels.oseen_contract`)."""
    spec = P(axis_name)
    return _ring_eval(
        lambda trg, src, rho: oseen_block(trg, src, rho, eta, reg,
                                          epsilon_distance),
        mesh, axis_name, (spec, spec, spec), 1.0, r_trg, r_src, density)
