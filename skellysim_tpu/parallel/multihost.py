"""Multi-host (DCN) bring-up for multi-slice / multi-process runs.

The reference scales across nodes with `mpirun` + MPI collectives
(`/root/reference/src/skelly_sim.cpp:14`, SURVEY.md §5.8); the TPU-native
equivalent is JAX's distributed runtime: every host runs the same program,
`jax.distributed.initialize` wires the processes together, and the same
GSPMD-sharded jit programs used single-host then span all hosts — XLA routes
collectives over ICI within a slice and DCN across slices. No simulation code
changes: `make_mesh()` over `jax.devices()` simply sees every chip.

Typical launch (one process per host, same command everywhere):

    SKELLY_COORDINATOR=host0:1234 SKELLY_NUM_PROCS=4 SKELLY_PROC_ID=$RANK \
        python -m skellysim_tpu --config-file=skelly_config.toml

On Cloud TPU / GKE, `jax.distributed.initialize()` auto-discovers all of
this from the environment and the arguments may be omitted entirely.
"""

from __future__ import annotations

import os

import jax


def initialize(coordinator_address: str | None = None,
               num_processes: int | None = None,
               process_id: int | None = None) -> bool:
    """Join the multi-host runtime; no-op for single-process runs.

    Arguments default from SKELLY_COORDINATOR / SKELLY_NUM_PROCS /
    SKELLY_PROC_ID, falling back to JAX's own autodetection (TPU pods
    populate it from the metadata server). Returns True when a distributed
    runtime was started. The analogue of the reference's MPI_Init_thread —
    but resumable state stays rank-count-independent here (our RNG streams
    are not per-rank, unlike `trajectory_reader.cpp:204-219`).
    """
    coordinator_address = coordinator_address or os.environ.get(
        "SKELLY_COORDINATOR")
    if num_processes is None and "SKELLY_NUM_PROCS" in os.environ:
        num_processes = int(os.environ["SKELLY_NUM_PROCS"])
    if process_id is None and "SKELLY_PROC_ID" in os.environ:
        process_id = int(os.environ["SKELLY_PROC_ID"])

    if num_processes in (None, 1) and coordinator_address is None:
        return False
    jax.distributed.initialize(coordinator_address=coordinator_address,
                               num_processes=num_processes,
                               process_id=process_id)
    return True


def process_info() -> dict:
    """{process_index, process_count, local/global device counts} — the
    analogue of the reference's rank/size echo (`system.cpp:30-45`)."""
    return {
        "process_index": jax.process_index(),
        "process_count": jax.process_count(),
        "local_device_count": jax.local_device_count(),
        "global_device_count": jax.device_count(),
    }
