"""Explicitly-sharded end-to-end implicit timestep: one `shard_map` program.

The GSPMD path (`shard_state` + jit) leaves the collectives of the coupled
solve to the compiler; this module is the reference's actual distributed
design (SURVEY §2, §5.8: Scatterv'd shell rows, per-rank fiber blocks,
all-reduced dot products) written out as ONE `shard_map` program over the
fiber axis that runs the entire implicit step — prep, GMRES, preconditioner
applications, mixed-precision refinement sweeps, and the state advance —
without leaving the mesh program.

Decomposition (everything per shard, mesh size D):

* fiber buckets shard along the batch axis (nf/D whole fibers per shard):
  caches, batched LU factors, and their solves never leave the owning shard
  — the preconditioner-locality analogue of the reference's round-robin
  fiber distribution;
* the shell row-shards node-aligned (N/D nodes per shard): the dense
  operators [3N/D, 3N], the density rows, and the RHS rows live distributed;
  applying the dense operator / its inverse is all-gather(density) + local
  row-block GEMV — exactly the reference's `periphery.cpp:21-47` matvec;
* bodies and scalars replicate (the reference's rank-0 body ownership).

Collectives are explicit and bounded (docs/parallel.md documents the full
inventory; tests/test_spmd.py pins it against the lowered HLO):

* `psum` for the GMRES reductions (injected into `solver.gmres` through
  its ``rdot`` seam — with ``Params.gmres_block_s > 1`` the s-step cycle
  batches them into two [(m+1)+s, s] Gram rounds per s iterations instead
  of 3 per iteration; docs/parallel.md) and for the partial sums onto
  REPLICATED rows (body-node velocities, link forces/torques, bundled
  into ONE tuple-psum per matvec);
* `ppermute` ring rotation of fiber/shell source blocks for all pairwise
  flows at shard-resident targets (`fibers.container.flow_multi_local`,
  `periphery.flow_local`) — including the double-float refinement tiles, so
  mixed-precision sweeps stay inside the mesh program;
* one density-sized (3N) `all_gather` per shell operator/preconditioner
  application — the Scatterv analogue, never an operand of fiber-cache size.

Replicated values are kept BITWISE identical across shards by the
replication discipline (docs/parallel.md "Replication discipline"):
replicated-inputs-only computation or psum-of-partials, never a ring
accumulation. This is no longer a prose convention — the `replication`
audit check (`audit.repflow`) statically verifies it on every registered
step_spmd program, with the replicated-output surface pinned in
`audit/contracts/step_spmd_d*.toml`.

The spectral-Ewald evaluator is not served here (its plan is built
host-side per step and is a different scaling regime); `pair_evaluator`
is ignored — the SPMD program always rings over its mesh.
"""

from __future__ import annotations

from typing import Callable, NamedTuple

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

from ..bodies import bodies as bd
from ..fibers import container as fc
from ..periphery import periphery as peri
from ..solver import gmres, gmres_ir
from ..system.system import (SimState, StepInfo, _cast_floats, _rewrap_bodies,
                             _rewrap_fibers, body_buckets, fiber_buckets)
from .compat import shard_map
from .mesh import FIBER_AXIS


class SpmdSolution(NamedTuple):
    """Structured (still-sharded) solution: per-bucket fiber blocks [nf, 4n],
    the shell density [3N], the body solution — what
    ``build_spmd_step(flat_solution=False)`` returns instead of gathering
    the flat reference-layout vector."""

    fibers: tuple
    shell: jnp.ndarray | None
    bodies: jnp.ndarray | None


def spmd_shell_mode(state: SimState, mesh: Mesh, *,
                    allow_replicated_shell: bool = False) -> str:
    """Validate a state for the SPMD step; returns the shell placement mode
    ("sharded" | "replicated" | "none").

    Stricter than `shard_state`: the shell must split NODE-aligned
    (n_nodes % D == 0, not just 3*n_nodes % D == 0) so a node's three
    density components never straddle shards, and every fiber bucket must
    divide the mesh (`fibers.container.grow_capacity` pads a batch up).
    """
    buckets = fiber_buckets(state.fibers)
    if not buckets:
        raise ValueError(
            "the SPMD step shards the fiber batch axis; a fiberless state "
            "has nothing to distribute (use the plain solve)")
    for g in buckets:
        if g.n_fibers % mesh.size != 0:
            raise ValueError(
                f"fiber bucket of {g.n_fibers} fibers does not divide the "
                f"mesh size ({mesh.size}); round the batch up with "
                "fibers.container.grow_capacity (inactive padding fibers "
                "are free)")
    if state.shell is None:
        return "none"
    if state.shell.n_nodes % mesh.size == 0:
        return "sharded"
    if allow_replicated_shell:
        return "replicated"
    raise ValueError(
        f"shell n_nodes ({state.shell.n_nodes}) is not divisible by the "
        f"mesh size ({mesh.size}), so the shell rows cannot be sharded "
        "node-aligned and the O(n_nodes^2) dense operators would replicate "
        "on every device. Pick a node count that is a multiple of "
        f"{mesh.size}, or pass allow_replicated_shell=True to accept the "
        "per-device memory cost.")


def _state_specs(state: SimState, shell_mode: str) -> SimState:
    """PartitionSpec pytree for a SimState under the SPMD decomposition."""
    def rep(sub):
        return (None if sub is None
                else jax.tree_util.tree_map(lambda _: P(), sub))

    buckets = fiber_buckets(state.fibers)
    placed = tuple(jax.tree_util.tree_map(lambda _: P(FIBER_AXIS), g)
                   for g in buckets)
    fib_spec = (placed[0] if isinstance(state.fibers, fc.FiberGroup)
                else placed)
    shell_spec = None
    if state.shell is not None:
        if shell_mode == "sharded":
            # every shell leaf is leading-axis sharded: nodes/normals [N, 3],
            # weights [N], density [3N], and the dense operators' ROWS;
            # absent optional fields (node_mask) are empty subtrees
            shell_spec = type(state.shell)(
                *[None if leaf is None else P(FIBER_AXIS)
                  for leaf in state.shell])
        else:
            shell_spec = rep(state.shell)
    return SimState(time=P(), dt=P(), fibers=fib_spec,
                    points=rep(state.points), background=rep(state.background),
                    shell=shell_spec, bodies=rep(state.bodies),
                    # the flight-recorder ring replicates: every shard
                    # writes the bitwise-identical row (psum'd/pmax'd
                    # reductions — obs.flight; repflow-verified)
                    flight=rep(state.flight))


def _make_rdot(axis: str, nonrep_end: int) -> Callable:
    """Reduction over the SPMD vector layout [sharded rows | replicated rows]:
    `psum` the sharded partial, add the replicated tail exactly once (it is
    bitwise identical on every shard, so no collective is needed for it)."""
    def rdot(A, w):
        # "psum-dots" device-time scope (obs/profile.py): THE solver
        # collective the s-step ladder exists to batch — metadata only
        with jax.named_scope("psum-dots"):
            part = lax.psum(A[..., :nonrep_end] @ w[:nonrep_end], axis)
            return part + A[..., nonrep_end:] @ w[nonrep_end:]
    return rdot


def build_spmd_step(system, mesh: Mesh, state: SimState, *,
                    allow_replicated_shell: bool = False,
                    flat_solution: bool = True, donate: str | bool = "auto",
                    pair=None, jit_wrapper=None):
    """Build the jitted explicitly-sharded full step for states shaped like
    ``state``. Returns ``step(state) -> (new_state, solution, info)`` with
    ``new_state`` still sharded on ``mesh``.

    ``pair`` (an anchor-stripped `ops.evaluator.PairEvaluator` carrying a
    `TreePlan`) routes the Krylov-interior fiber Stokeslet flows through
    the treecode instead of the ring (`fibers.container.flow_multi_local`'s
    tree branch: one tiled source all-gather + per-shard tree evaluation at
    resident targets). The built ``step`` then takes the plan's traced
    anchors as a second argument — `System.step_spmd` supplies both. The
    f64 refinement-residual matvec and prep flows keep the same role gating
    as the single-chip solve (dense — tree_tol must not cap the refined
    residual in mixed mode); the Gauss-Seidel shell correction stays on the
    ring path (the shell double layer is not the O(N^2) wall this evaluator
    exists to break).

    ``flat_solution=True`` assembles the reference-layout flat solution
    vector outside the mesh program (one explicit gather — skip it at scale
    with ``False``, which returns an `SpmdSolution` of sharded parts).
    ``donate="auto"`` donates the input state's buffers into the step on
    accelerator backends (XLA aliases the pass-through leaves — the dense
    shell operators above all — instead of double-buffering them); rejected
    adaptive steps must not reuse a donated input, so callers that roll
    back pass ``donate=False``.

    ``jit_wrapper`` replaces the final `jax.jit` (same kwargs) — the
    audit layer's retrace-probe seam (`testing.trace_counting_jit`).
    """
    p = system.params
    if (p.guard_dt_halvings or p.guard_block_fallback
            or p.guard_f64_fallback):
        # once per BUILD (System.step_spmd caches the program): the mesh
        # program threads the HEALTH WORD but not the escalation ladder —
        # silent inertness would surprise a user who armed guard_*
        # expecting device-side retries. The replication analyzer
        # (audit.repflow) proves the guard-armed build AND the ladder's
        # retry pattern replication-safe (tests/test_guard.py), so what
        # remains for in-mesh escalation is wiring and compile cost, not a
        # correctness unknown — docs/robustness.md "In-mesh escalation".
        import warnings

        warnings.warn("Params.guard_* escalation is not applied on the "
                      "step_spmd path: the mesh program reports health "
                      "verdicts but does not retry; escalation runs on "
                      "the single-chip and ensemble paths only")
    axis = FIBER_AXIS
    n_dev = mesh.size
    shell_mode = spmd_shell_mode(
        state, mesh, allow_replicated_shell=allow_replicated_shell)
    sharded_shell = shell_mode == "sharded"
    has_shell = shell_mode != "none"
    has_bodies = state.bodies is not None

    precision = system._precision_for(state)
    is_f64 = state.time.dtype == jnp.float64
    # mixed f64: prep flows AND the refinement-residual matvec both run
    # through the refinement tile (System._prep / _solve_impl semantics)
    refine = precision == "mixed" and is_f64
    prep_impl = hi_impl = (system._refine_impl if refine else p.kernel_impl)
    precond_dtype = jnp.float32 if precision == "mixed" else None
    has_pair = pair is not None and getattr(pair, "is_fast", False)
    if has_pair and pair.evaluator != "tree":
        # flow_multi_local's fast branch serves ONLY the tree: an ewald
        # spec would pass validation, thread a dead anchors operand, and
        # silently run the O(N^2/D) ring flows the caller thinks it
        # replaced (the FFT-grid evaluator has no per-shard decomposition
        # here — docs/parallel.md)
        raise ValueError(
            f"build_spmd_step(pair=...) composes only the 'tree' "
            f"evaluator with the SPMD step, got {pair.evaluator!r}; "
            "pass pair=None for the ring flows")
    if has_pair:
        # the SPMD layout has no global inactive-slot spread (flow_multi's
        # _spread_inactive needs the full concatenated active mask, which
        # no single shard holds): padding nodes replicating slot 0 would
        # pile into one leaf and overflow the plan's static bucket
        # capacity, silently evicting real sources (_bucket's rank clamp).
        # System.step_spmd falls back to the ring flows for such states;
        # direct callers of this seam get a build-time error, not wrong
        # physics.
        import numpy as np
        if not all(bool(np.all(np.asarray(g.active)))
                   for g in fiber_buckets(state.fibers)):
            raise ValueError(
                "build_spmd_step(pair=...) requires every fiber slot "
                "active: the SPMD layout cannot spread inactive padding "
                "nodes, which would overflow the fast plan's static leaf "
                "buckets; pass pair=None (ring flows) for states with "
                "inactive capacity")
    # mixed-mode prep flows stay dense through the refinement tile — the
    # same role gating as System._prep (tree_tol must not cap RHS accuracy)
    prep_pair = None if (refine or not has_pair) else pair

    def node_targets(st, body_caches):
        """(r_loc, r_rep, nf_nodes_local): shard-resident target rows
        (this shard's fiber nodes [+ shell row block]) and replicated
        target rows ([replicated shell nodes +] body nodes)."""
        parts_loc = [fc.node_positions(g) for g in fiber_buckets(st.fibers)]
        nf_l = sum(g.n_fibers * g.n_nodes for g in fiber_buckets(st.fibers))
        if sharded_shell:
            parts_loc.append(st.shell.nodes)
        parts_rep = []
        if shell_mode == "replicated":
            parts_rep.append(st.shell.nodes)
        b_list = body_buckets(st.bodies)
        for i, g in enumerate(b_list):
            nodes = (body_caches[i].nodes if body_caches is not None
                     else bd.place(g)[0])
            parts_rep.append(nodes.reshape(-1, 3))
        r_loc = jnp.concatenate(parts_loc, axis=0)
        r_rep = jnp.concatenate(parts_rep, axis=0) if parts_rep else None
        return r_loc, r_rep, nf_l

    def rep_splits(st):
        """(shell rows, body rows) node counts inside the r_rep block."""
        ns_rep = st.shell.n_nodes if shell_mode == "replicated" else 0
        nb = sum(g.n_bodies * g.n_nodes for g in body_buckets(st.bodies))
        return ns_rep, nb

    # ----------------------------------------------------------------- prep

    def prep(st, anchors=None):
        """Port of `System._prep` to the SPMD layout: all per-fiber work
        (caches, BC/RHS assembly, LU factorization) on the owning shard;
        explicit flows ring at resident rows, psum onto replicated rows."""
        st = system._update_plus_pinning(st)
        buckets = fiber_buckets(st.fibers)
        b_list = body_buckets(st.bodies)
        caches = None
        body_caches = None
        shell_rhs = None
        body_rhs = None

        if b_list:
            body_caches = [bd.update_cache(g, p.eta,
                                           precond_dtype=precond_dtype)
                           for g in b_list]
        r_loc, r_rep, nf_l = node_targets(st, body_caches)
        v_loc = jnp.zeros_like(r_loc)
        v_rep_dense = jnp.zeros_like(r_rep) if r_rep is not None else None
        v_rep_part = None

        caches = [fc.update_cache(g, st.dt, p.eta) for g in buckets]
        external = system._periphery_force_fibers(st)
        motor = [jnp.where(st.time >= p.implicit_motor_activation_delay,
                           fc.generate_constant_force(g, c),
                           jnp.zeros_like(g.x))
                 for g, c in zip(buckets, caches)]
        fl, fp = fc.flow_multi_local(buckets, caches, external, r_loc, r_rep,
                                     p.eta, axis_name=axis, n_dev=n_dev,
                                     subtract_self=True, impl=prep_impl,
                                     pair=prep_pair, pair_anchors=anchors)
        v_loc = v_loc + fl
        v_rep_part = fp

        if b_list:
            for g, bc in zip(b_list, body_caches):
                ext_ft = bd.external_forces_torques(g, st.time)
                v_loc = v_loc + bd.flow(g, bc, r_loc, None, ext_ft, p.eta,
                                        impl=prep_impl)
                v_rep_dense = v_rep_dense + bd.flow(g, bc, r_rep, None,
                                                    ext_ft, p.eta,
                                                    impl=prep_impl)

        v_loc = v_loc + system._external_flows(st, r_loc)
        if r_rep is not None:
            v_rep_dense = v_rep_dense + system._external_flows(st, r_rep)
            v_rep = v_rep_dense
            if v_rep_part is not None:
                v_rep = v_rep + lax.psum(v_rep_part, axis)
        else:
            v_rep = None

        ns_rep, _ = rep_splits(st)
        if b_list:
            body_rhs = []
            off = ns_rep
            for g in b_list:
                nbn = g.n_bodies * g.n_nodes
                v_bodies = v_rep[off:off + nbn].reshape(
                    g.n_bodies, g.n_nodes, 3)
                body_rhs.append(bd.update_RHS(g, v_bodies))
                off += nbn

        off = 0
        new_caches = []
        for g, c, mo, ex in zip(buckets, caches, motor, external):
            nfn = g.n_fibers * g.n_nodes
            v_fib = v_loc[off:off + nfn].reshape(g.n_fibers, g.n_nodes, 3)
            new_caches.append(fc.update_rhs_and_bc(
                g, c, st.dt, p.eta, v_fib, mo + ex, ex,
                precond_dtype=precond_dtype))
            off += nfn
        caches = new_caches

        if has_shell:
            if sharded_shell:
                v_shell = v_loc[nf_l:]
            else:
                v_shell = v_rep[:ns_rep]
            shell_rhs = peri.update_RHS(v_shell)

        return st, caches, body_caches, shell_rhs, body_rhs

    # --------------------------------------------------------- the operator

    def make_matvec(st, caches, body_caches, lo=None, flow_impl=None,
                    pair_spec=None, pair_anchors=None):
        """Port of `System._apply_matvec` to the SPMD layout (same lo-seam
        semantics: all flows/dense ops through the f32 copies, stiff
        fiber-local rows in the solve dtype). ``pair_spec`` routes the
        fiber Stokeslet flow through `flow_multi_local`'s tree branch."""
        impl = p.kernel_impl if flow_impl is None else flow_impl
        buckets = fiber_buckets(st.fibers)
        b_list = body_buckets(st.bodies)
        fib_size, shell_size, _ = system._sizes(st)
        f_state, f_caches, f_bcaches = ((st, caches, body_caches)
                                        if lo is None else lo)
        f_buckets = fiber_buckets(f_state.fibers)
        f_b_list = body_buckets(f_state.bodies)

        def matvec(x):
            hi = x.dtype
            lo_dtype = hi if lo is None else f_state.time.dtype
            r_loc, r_rep, nf_l = node_targets(f_state, f_bcaches)
            ns_rep, _ = rep_splits(f_state)
            v_loc = jnp.zeros_like(r_loc)
            # replicated-row velocities split by evaluation strategy:
            # per-shard PARTIALS that one psum will sum, vs dense values
            # every shard computes identically from replicated inputs
            v_rep_part = (jnp.zeros_like(r_rep) if r_rep is not None
                          else None)
            v_rep_dense = (jnp.zeros_like(r_rep) if r_rep is not None
                           else None)

            x_fibs = []
            off = 0
            for g in buckets:
                size = fc.solution_size(g)
                x_fibs.append(x[off:off + size].reshape(g.n_fibers,
                                                        4 * g.n_nodes))
                off += size
            fws = [fc.apply_fiber_force(g, c, xf)
                   for g, c, xf in zip(buckets, caches, x_fibs)]
            fl, fp = fc.flow_multi_local(
                f_buckets, f_caches, [fw.astype(lo_dtype) for fw in fws],
                r_loc, r_rep, p.eta, axis_name=axis, n_dev=n_dev,
                subtract_self=True, impl=impl, pair=pair_spec,
                pair_anchors=pair_anchors)
            v_loc = v_loc + fl
            if fp is not None:
                v_rep_part = v_rep_part + fp

            x_shell = x[fib_size:fib_size + shell_size]
            if has_shell and (buckets or b_list):
                # shell flow at fiber and body rows only; the shell
                # self-interaction lives in the dense operator
                rho = x_shell.astype(lo_dtype)
                if sharded_shell:
                    sl, sp = peri.flow_local(
                        f_state.shell, r_loc[:nf_l], r_rep, rho, p.eta,
                        axis_name=axis, n_dev=n_dev, impl=impl)
                    v_loc = v_loc.at[:nf_l].add(sl)
                    if sp is not None:
                        v_rep_part = v_rep_part + sp
                else:
                    # replicated shell: dense double layer from the full
                    # node set, deterministic on every shard — added OUTSIDE
                    # the psum of partials
                    r_fb = (jnp.concatenate([r_loc[:nf_l], r_rep[ns_rep:]],
                                            axis=0)
                            if r_rep is not None and r_rep.shape[0] > ns_rep
                            else r_loc[:nf_l])
                    vfb = peri.flow(f_state.shell, r_fb, rho, p.eta,
                                    impl=impl)
                    v_loc = v_loc.at[:nf_l].add(vfb[:nf_l])
                    if vfb.shape[0] > nf_l:
                        v_rep_dense = v_rep_dense.at[ns_rep:].add(
                            vfb[nf_l:])

            # body link conditions: per-shard fiber partials -> one psum
            x_bods = []
            v_boundaries = None
            body_fts = None
            if b_list:
                nbt = bd.n_total(b_list)
                off_b = fib_size + shell_size
                for g in b_list:
                    size = g.solution_size
                    x_bods.append(x[off_b:off_b + size].reshape(
                        g.n_bodies, 3 * g.n_nodes + 6))
                    off_b += size
                body_fts = [jnp.zeros((g.n_bodies, 6), dtype=hi)
                            for g in b_list]
                if buckets:
                    v_boundaries = [jnp.zeros((g.n_fibers, 7), dtype=hi)
                                    for g in buckets]
                    for j, (gb, bc, xb) in enumerate(
                            zip(b_list, body_caches, x_bods)):
                        for i, (gf, c, xf) in enumerate(
                                zip(buckets, caches, x_fibs)):
                            gf_loc = bd.local_binding(gf, gb, nbt)
                            vb, ft = bd.link_conditions(gb, bc, gf_loc, c,
                                                        xf, xb)
                            v_boundaries[i] = v_boundaries[i] + vb
                            body_fts[j] = body_fts[j] + ft

            # ONE psum per matvec: replicated-row partial velocities + the
            # link forces/torques together (bodies imply r_rep is present)
            v_rep = None
            if body_fts is not None:
                v_rep_part, body_fts = lax.psum((v_rep_part, body_fts), axis)
            elif r_rep is not None:
                v_rep_part = lax.psum(v_rep_part, axis)
            if r_rep is not None:
                v_rep = v_rep_part + v_rep_dense

            if b_list:
                r_all = (jnp.concatenate([r_loc, r_rep], axis=0)
                         if r_rep is not None else r_loc)
                for gb, f_gb, f_bc, xb, ft in zip(
                        b_list, f_b_list,
                        f_bcaches or [None] * len(b_list), x_bods, body_fts):
                    vflow = bd.flow(f_gb, f_bc, r_all,
                                    xb.astype(lo_dtype),
                                    ft.astype(lo_dtype), p.eta, impl=impl)
                    v_loc = v_loc + vflow[:r_loc.shape[0]]
                    v_rep = v_rep + vflow[r_loc.shape[0]:]

            res = []
            off = 0
            for i, (g, c, xf) in enumerate(zip(buckets, caches, x_fibs)):
                nfn = g.n_fibers * g.n_nodes
                v_fib = v_loc[off:off + nfn].reshape(
                    g.n_fibers, g.n_nodes, 3).astype(hi)
                vb = (v_boundaries[i] if v_boundaries is not None
                      else jnp.zeros((g.n_fibers, 7), dtype=hi))
                res.append(fc.matvec(g, c, xf, v_fib, vb).reshape(-1))
                off += nfn
            if has_shell:
                if sharded_shell:
                    v_shell = v_loc[nf_l:]
                    with jax.named_scope("allgather-density"):
                        x_full = lax.all_gather(x_shell, axis, tiled=True)
                    res.append(peri.matvec(f_state.shell,
                                           x_full.astype(lo_dtype),
                                           v_shell).astype(hi))
                else:
                    v_shell = v_rep[:ns_rep]
                    res.append(peri.matvec(f_state.shell,
                                           x_shell.astype(lo_dtype),
                                           v_shell).astype(hi))
            off = ns_rep
            for g, f_gb, f_bc, xb in zip(b_list, f_b_list,
                                         f_bcaches or [None] * len(b_list),
                                         x_bods):
                nbn = g.n_bodies * g.n_nodes
                v_bodies = v_rep[off:off + nbn].reshape(
                    g.n_bodies, g.n_nodes, 3)
                res.append(bd.matvec(f_gb, f_bc, xb.astype(lo_dtype),
                                     v_bodies).astype(hi).reshape(-1))
                off += nbn
            return jnp.concatenate(res)

        return matvec

    # ----------------------------------------------------- the preconditioner

    def make_precond(st, caches, body_caches):
        """Port of `System._apply_precond`: per-fiber LU solves on the
        owning shard; shell solve = all-gather(density) + local M_inv row
        block; the shell-first Gauss-Seidel correction rings the local
        shell blocks at fiber rows and psums the body-row partial."""
        buckets = fiber_buckets(st.fibers)
        b_list = body_buckets(st.bodies)
        fib_size, shell_size, _ = system._sizes(st)
        nf_l = sum(g.n_fibers * g.n_nodes for g in buckets)

        def precond(x):
            # scoped like System._apply_precond: device time lands under
            # gmres/arnoldi/precond in the obs profile table
            with jax.named_scope("precond"):
                return precond_impl(x)

        def precond_impl(x):
            y_shell = None
            if has_shell:
                x_shell = x[fib_size:fib_size + shell_size]
                if sharded_shell:
                    with jax.named_scope("allgather-density"):
                        x_full = lax.all_gather(x_shell, axis, tiled=True)
                    shell = st.shell
                    y_shell = (shell.M_inv
                               @ x_full.astype(shell.M_inv.dtype)
                               ).astype(x.dtype)
                else:
                    y_shell = peri.apply_preconditioner(st.shell, x_shell)

            v_corr_loc = None
            v_corr_rep = None
            if p.precond == "gs" and y_shell is not None:
                r_loc, r_rep, _ = node_targets(st, body_caches)
                rho = y_shell.astype(st.shell.nodes.dtype)
                ns_rep, nb_nodes = rep_splits(st)
                r_body = (r_rep[ns_rep:] if (r_rep is not None and nb_nodes)
                          else None)
                if sharded_shell:
                    vl, vp = peri.flow_local(st.shell, r_loc[:nf_l], r_body,
                                             rho, p.eta, axis_name=axis,
                                             n_dev=n_dev, impl=p.kernel_impl)
                    v_corr_loc = vl.astype(x.dtype)
                    if vp is not None:
                        v_corr_rep = lax.psum(vp, axis).astype(x.dtype)
                else:
                    r_fb = (jnp.concatenate([r_loc[:nf_l], r_body], axis=0)
                            if r_body is not None else r_loc[:nf_l])
                    v = peri.flow(st.shell, r_fb, rho, p.eta,
                                  impl=p.kernel_impl).astype(x.dtype)
                    v_corr_loc = v[:nf_l]
                    if r_body is not None:
                        v_corr_rep = v[nf_l:]

            res = []
            off = 0
            off_v = 0
            for g, c in zip(buckets, caches):
                size = fc.solution_size(g)
                x_fib = x[off:off + size].reshape(g.n_fibers, 4 * g.n_nodes)
                if v_corr_loc is not None:
                    nfn = g.n_fibers * g.n_nodes
                    v_fib = v_corr_loc[off_v:off_v + nfn].reshape(
                        g.n_fibers, g.n_nodes, 3)
                    # fiber rows of A at (0, y_shell, 0): pure coupling term
                    x_fib = x_fib - fc.matvec(
                        g, c, jnp.zeros_like(x_fib), v_fib,
                        jnp.zeros((g.n_fibers, 7), dtype=x.dtype))
                    off_v += nfn
                res.append(fc.apply_preconditioner(g, c, x_fib).reshape(-1))
                off += size
            if y_shell is not None:
                res.append(y_shell)
            off_b = fib_size + shell_size
            off_v = 0
            for j, g in enumerate(b_list):
                size = g.solution_size
                x_bod = x[off_b:off_b + size].reshape(g.n_bodies, -1)
                if v_corr_rep is not None:
                    nbn = g.n_bodies * g.n_nodes
                    v_bod = v_corr_rep[off_v:off_v + nbn].reshape(
                        g.n_bodies, g.n_nodes, 3)
                    # body rows of A at (0, y_shell, 0) = [v_nodes, 0]
                    x_bod = x_bod - bd.matvec(
                        g, body_caches[j], jnp.zeros_like(x_bod), v_bod)
                    off_v += nbn
                res.append(bd.apply_preconditioner(
                    g, body_caches[j], x_bod).reshape(-1))
                off_b += size
            return jnp.concatenate(res)

        return precond

    # ------------------------------------------------------------ local step

    def local_step(st, anchors=None):
        # skelly-pulse phase scopes (obs/profile.py): metadata-only — the
        # audited mesh programs (collective inventory, replication
        # analysis, cost baselines) are byte-identical
        with jax.named_scope("prep"):
            st, caches, body_caches, shell_rhs, body_rhs = prep(st, anchors)
            buckets = fiber_buckets(st.fibers)
            b_list = body_buckets(st.bodies)
            fib_size, shell_size, _ = system._sizes(st)

            rhs_parts = [c.RHS.reshape(-1) for c in caches]
            if shell_rhs is not None:
                rhs_parts.append(shell_rhs)
            for br in (body_rhs or []):
                rhs_parts.append(br.reshape(-1))
            rhs = jnp.concatenate(rhs_parts)

        nonrep_end = fib_size + (shell_size if sharded_shell else 0)
        rdot = _make_rdot(axis, nonrep_end)

        krylov_pair = pair if has_pair else None
        if precision == "mixed":
            lo = _cast_floats((st, caches, body_caches), jnp.float32)
            with jax.named_scope("gmres"):
                result = gmres_ir(
                    # hi residual matvec: dense regardless of the spec —
                    # the fast evaluator's tol must not cap residual_true
                    make_matvec(st, caches, body_caches,
                                flow_impl=hi_impl),
                    make_matvec(st, caches, body_caches, lo=lo,
                                pair_spec=krylov_pair,
                                pair_anchors=anchors),
                    rhs,
                    precond_lo=make_precond(lo[0], lo[1], lo[2]),
                    tol=p.gmres_tol, inner_tol=p.inner_tol,
                    restart=p.gmres_restart, maxiter=p.gmres_maxiter,
                    max_refine=p.max_refine, rdot=rdot,
                    block_s=p.gmres_block_s)
        else:
            with jax.named_scope("gmres"):
                result = gmres(
                    make_matvec(st, caches, body_caches,
                                pair_spec=krylov_pair,
                                pair_anchors=anchors), rhs,
                    precond=make_precond(st, caches, body_caches),
                    tol=p.gmres_tol, restart=p.gmres_restart,
                    maxiter=p.gmres_maxiter, rdot=rdot,
                    block_s=p.gmres_block_s)

        # ------------------------------------------------ advance components
        with jax.named_scope("advance"):
            new_state = st
            off = 0
            stepped = []
            sol_fibs = []
            for g in buckets:
                size = fc.solution_size(g)
                sol_fib = result.x[off:off + size].reshape(g.n_fibers, -1)
                sol_fibs.append(sol_fib)
                stepped.append(fc.step(g, sol_fib))
                off += size
            new_state = new_state._replace(
                fibers=_rewrap_fibers(st.fibers, stepped))
            sol_shell = None
            if has_shell:
                sol_shell = result.x[fib_size:fib_size + shell_size]
                new_state = new_state._replace(shell=st.shell._replace(
                    density=sol_shell))
            sol_body = None
            if b_list:
                off_b = fib_size + shell_size
                sol_body = result.x[off_b:]
                new_b = []
                for g in b_list:
                    size = g.solution_size
                    sol_bod = result.x[off_b:off_b + size].reshape(
                        g.n_bodies, -1)
                    new_b.append(bd.step(g, sol_bod, st.dt))
                    off_b += size
                new_state = new_state._replace(
                    bodies=_rewrap_bodies(st.bodies, new_b))
                # fibers re-pin to their (moved) nucleation sites —
                # per-shard local fibers against the replicated moved bodies
                nbt = bd.n_total(new_b)
                repinned = list(fiber_buckets(new_state.fibers))
                for gb in new_b:
                    _, _, new_sites = bd.place(gb)
                    repinned = [
                        g._replace(x=bd.repin_to_bodies(
                            bd.local_binding(g, gb, nbt), new_sites, gb).x)
                        for g in repinned]
                new_state = new_state._replace(
                    fibers=_rewrap_fibers(new_state.fibers, repinned))
            err_local = jnp.max(jnp.stack(
                [fc.fiber_error(g) for g in fiber_buckets(new_state.fibers)]))
            fiber_error = lax.pmax(err_local, axis)

        # the guard health word rides the mesh program too: the solver's
        # bits are replicated (psum'd reductions), the fiber-error check is
        # on the pmax'd global error — every shard computes the identical
        # word, keeping replicated outputs bitwise in lockstep
        from ..guard.verdict import nonfinite_word

        health = (jnp.asarray(result.health, dtype=jnp.int32)
                  | nonfinite_word(fiber_error))
        if st.flight is not None:
            # skelly-flight on the mesh program: the SAME diagnostics row,
            # with every reduction an explicit collective (pmax/pmin via
            # record_step's axis_name spelling, the solution norm through
            # the replication-restoring rdot seam) so all shards write the
            # bitwise-identical replicated ring — `audit.repflow` analyzes
            # the armed build clean (tests/test_flight.py)
            from ..obs import flight as flight_mod

            new_state = new_state._replace(flight=flight_mod.record_step(
                st, new_state, result.x,
                residual_true=result.residual_true, health=health,
                dt_used=st.dt, shell_shape=system.shell_shape,
                solution_norm=jnp.sqrt(rdot(result.x, result.x)),
                axis_name=axis, axis_size=n_dev,
                sol_scan_rows=nonrep_end, shell_sharded=sharded_shell))
        info = StepInfo(
            converged=result.converged, iters=result.iters,
            residual=result.residual, fiber_error=fiber_error,
            residual_true=result.residual_true,
            loss_of_accuracy=(result.converged
                              & (result.residual_true > 10.0 * p.gmres_tol)),
            refines=jnp.asarray(result.refines, dtype=jnp.int32),
            # skelly-scope gmres_cycles ride along; the convergence ring
            # buffer stays None in the mesh program (a replicated [N,3]
            # carry per shard buys nothing over the single-chip history)
            cycles=jnp.asarray(result.cycles, dtype=jnp.int32),
            health=health, dt_used=st.dt, guard_retries=jnp.int32(0))
        return new_state, (tuple(sol_fibs), sol_shell, sol_body), info

    # -------------------------------------------------------------- assembly

    state_specs = _state_specs(state, shell_mode)
    sol_specs = (
        tuple(P(FIBER_AXIS) for _ in fiber_buckets(state.fibers)),
        (P(FIBER_AXIS) if sharded_shell else P()) if has_shell else None,
        P() if has_bodies else None,
    )
    info_specs = jax.tree_util.tree_map(
        lambda _: P(), StepInfo(converged=0, iters=0, residual=0.0,
                                fiber_error=0.0, residual_true=0.0,
                                loss_of_accuracy=False, refines=0,
                                cycles=0, history=None, health=0,
                                dt_used=0.0, guard_retries=0))
    # check_vma off: the 0.4.x replication checker has no while-loop rule
    # (every solver loop is lax.while_loop), and replicated-output
    # correctness is guaranteed by construction here (psum-or-replicated
    # inputs only — see the module docstring) and pinned by the parity tests
    if has_pair:
        # the plan's traced anchors enter as one replicated operand so a
        # quantized anchor hop under drift reuses the compiled program
        sharded = shard_map(local_step, mesh=mesh,
                            in_specs=(state_specs, P()),
                            out_specs=(state_specs, sol_specs, info_specs),
                            check_vma=False)
    else:
        sharded = shard_map(lambda st: local_step(st), mesh=mesh,
                            in_specs=(state_specs,),
                            out_specs=(state_specs, sol_specs, info_specs),
                            check_vma=False)

    def step(st, pair_anchors=None):
        if has_pair:
            new_state, (sol_fibs, sol_shell, sol_body), info = sharded(
                st, pair_anchors)
        else:
            new_state, (sol_fibs, sol_shell, sol_body), info = sharded(st)
        if flat_solution:
            with jax.named_scope("advance"):
                parts = [s.reshape(-1) for s in sol_fibs]
                if sol_shell is not None:
                    parts.append(sol_shell)
                if sol_body is not None:
                    parts.append(sol_body)
                solution = jnp.concatenate(parts)
        else:
            solution = SpmdSolution(fibers=tuple(sol_fibs), shell=sol_shell,
                                    bodies=sol_body)
        return new_state, solution, info

    if donate == "auto":
        # CPU XLA has no buffer donation — jit would warn on every call
        donate = jax.default_backend() != "cpu"
    wrap = jax.jit if jit_wrapper is None else jit_wrapper
    return wrap(step, donate_argnums=(0,) if donate else ())


def spmd_step(system, state: SimState, mesh: Mesh, *,
              allow_replicated_shell: bool = False,
              flat_solution: bool = True):
    """One explicitly-sharded implicit step (build + run, uncached).

    `System.step_spmd` caches the built program per (mesh, state structure)
    — prefer it for anything iterative.
    """
    fn = build_spmd_step(system, mesh, state,
                         allow_replicated_shell=allow_replicated_shell,
                         flat_solution=flat_solution, donate=False)
    return fn(state)


# ---------------------------------------------------------------- skelly-audit

def auditable_programs():
    """The SPMD scaling ladder's audit entries: the coupled explicitly-
    sharded step lowered on 2/4/8-device CPU meshes. The contracts pin the
    collective inventory of docs/parallel.md's table per mesh size —
    including the bound that no all-gather ever exceeds the shell density
    (the GSPMD silent-replication failure mode). The d2 program also runs
    the retrace probe (d4/d8 would re-pay the same compile for no new
    signal)."""
    from ..audit import fixtures
    from ..audit.registry import AuditProgram, built_from
    from . import shard_state
    from .mesh import make_mesh

    def build(n_dev):
        def _build():
            mesh = make_mesh(n_dev)
            # gmres_block_s=4: the audited ladder configuration IS the
            # communication-avoiding solver (ISSUE 8) — the contracts pin
            # the BATCHED Gram rounds (2 all-reduces per 4 Krylov
            # iterations in the solver loop body, vs the sequential
            # cycle's 3 per iteration), so a regression back to
            # per-iteration psums fails the collective inventory
            system = fixtures.make_system(shell=True, gmres_block_s=4)
            state = shard_state(fixtures.coupled_state(system), mesh)
            fn = build_spmd_step(system, mesh, state, flat_solution=False,
                                 donate=True)
            return built_from(fn, state)
        return _build

    def retrace_probe():
        from ..testing import trace_counting_jit

        mesh = make_mesh(2)
        system = fixtures.make_system(gmres_block_s=4)
        state = shard_state(fixtures.free_state(system), mesh)
        fn = build_spmd_step(system, mesh, state, donate=False,
                             jit_wrapper=trace_counting_jit)
        new_state, _, _ = fn(state)
        fn(new_state)  # same structure, new values: must not retrace
        return fn.trace_count

    progs = []
    for n_dev in (2, 4, 8):
        progs.append(AuditProgram(
            name=f"step_spmd_d{n_dev}", layer="parallel",
            summary=f"explicitly-sharded coupled step on the {n_dev}-device "
                    "mesh (row-sharded shell, donated state)",
            build=build(n_dev),
            retrace_probe=retrace_probe if n_dev == 2 else None))
    return progs
