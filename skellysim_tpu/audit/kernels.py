"""Aggregate the auditable-kernel matrix from every Pallas-owning module.

The kernel-level twin of `audit.programs`: modules that author Pallas
kernels (`parallel.ring_fused`, `ops.pallas_kernels`) expose
``auditable_kernels()`` returning `registry.AuditKernel`s, and the ``dma``
check (`audit.dmaflow`) verifies each one. Defining ``auditable_kernels``
is also the lint boundary: the ``raw-dma`` skelly-lint rule flags DMA /
semaphore primitives in any module without it.
"""

from __future__ import annotations


def all_kernels():
    """Every registered `AuditKernel`, ops before parallel. Lazy module
    imports, same rationale as `programs.all_programs`."""
    from ..ops.pallas_kernels import auditable_kernels as ops_kernels
    from ..parallel.ring_fused import auditable_kernels as ring_kernels

    kerns = []
    for layer in (ops_kernels, ring_kernels):
        kerns.extend(layer())
    names = [k.name for k in kerns]
    dupes = {n for n in names if names.count(n) > 1}
    if dupes:
        raise ValueError(f"duplicate auditable kernel name(s): "
                         f"{', '.join(sorted(dupes))}")
    return kerns


def get_kernel(name: str):
    for k in all_kernels():
        if k.name == name:
            return k
    raise KeyError(
        f"no auditable kernel named {name!r} "
        f"(registered: {', '.join(k.name for k in all_kernels())})")
