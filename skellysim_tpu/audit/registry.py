"""Auditable-program registration seam.

Each layer that owns a jit entry point (`system.system`, `parallel.spmd`,
`ensemble.runner`, `solver.gmres`) exposes a small ``auditable_programs()``
returning `AuditProgram`s; `audit.programs.all_programs` aggregates them.
The layer declares *what* to lower (it knows its own entry points and their
fixtures); the audit engine owns *how* the lowered artifacts are checked.

Keeping this module import-light matters: layer modules import it lazily
inside their ``auditable_programs()`` so the audit package never becomes an
import-time dependency of the simulation stack.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Callable


@dataclass
class BuiltProgram:
    """The lowering artifacts every check consumes.

    ``closed_jaxpr`` is the traced `jax.core.ClosedJaxpr` (dtype-flow and
    host-sync walk its equations recursively); ``lowered_text`` is the
    StableHLO module text (collective inventory and donation markers — the
    program XLA actually receives, including the shard_map lowering the
    jaxpr only names symbolically). ``lowered`` is the live `jax.stages
    .Lowered` handle the text came from — skelly-scope's cost gate
    (`obs.cost`) compiles it for XLA's cost/memory analyses; audit checks
    never touch it (tests construct BuiltProgram without one).
    """

    closed_jaxpr: object
    lowered_text: str
    lowered: object = None


@dataclass
class AuditProgram:
    """One registered entry point.

    ``build()`` assembles the fixture scene, traces, and lowers — called
    lazily so ``--list-programs`` and single-program runs never pay for the
    rest of the matrix. ``retrace_probe()``, when provided, runs the entry
    point twice with same-structure/different-value arguments through
    `testing.trace_counting_jit` and returns the trace count (the
    ``retrace-budget`` check compares it against the contract).
    """

    name: str
    layer: str                      # system | parallel | ensemble | solver
    summary: str
    build: Callable[[], BuiltProgram]
    retrace_probe: Callable[[], int] | None = None


@dataclass
class BuiltKernel:
    """The traced artifacts the ``dma`` check (`audit.dmaflow`) consumes.

    ``kernel_jaxpr`` is the Pallas kernel-body jaxpr (ref semantics:
    get/swap/dma_start/semaphore primitives); ``grid_mapping`` the
    `pallas_call` GridMapping (block shapes, input/output/scratch
    partition); ``n_dev`` the ring size the kernel was traced for;
    ``scene`` the builder's shape parameters (``kind``/``n_trg``/
    ``n_src`` for ring kernels, {} for gridded) so the verifier can
    cross-check the build-time eligibility gate against the traced
    artifact.
    """

    kernel_jaxpr: object
    grid_mapping: object
    n_dev: int
    scene: dict


@dataclass
class AuditKernel:
    """One registered Pallas kernel (the ``auditable_kernels()`` seam —
    same shape as `AuditProgram`, but ``build()`` returns the kernel-level
    artifact the DMA verifier walks rather than a whole-program lowering).
    Modules defining ``auditable_kernels`` are the lint boundary for the
    ``raw-dma`` rule: DMA/semaphore primitives are legal only inside them.
    """

    name: str
    layer: str
    summary: str
    build: Callable[[], BuiltKernel]


def built_from(jitted, *args, **kwargs) -> BuiltProgram:
    """Trace + lower a `jax.jit`-wrapped callable once, capturing every
    artifact from the same trace (no double tracing/lowering)."""
    traced = jitted.trace(*args, **kwargs)
    lowered = traced.lower()
    return BuiltProgram(closed_jaxpr=traced.jaxpr,
                        lowered_text=lowered.as_text(),
                        lowered=lowered)
