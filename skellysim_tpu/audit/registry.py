"""Auditable-program registration seam.

Each layer that owns a jit entry point (`system.system`, `parallel.spmd`,
`ensemble.runner`, `solver.gmres`) exposes a small ``auditable_programs()``
returning `AuditProgram`s; `audit.programs.all_programs` aggregates them.
The layer declares *what* to lower (it knows its own entry points and their
fixtures); the audit engine owns *how* the lowered artifacts are checked.

Keeping this module import-light matters: layer modules import it lazily
inside their ``auditable_programs()`` so the audit package never becomes an
import-time dependency of the simulation stack.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Callable


@dataclass
class BuiltProgram:
    """The lowering artifacts every check consumes.

    ``closed_jaxpr`` is the traced `jax.core.ClosedJaxpr` (dtype-flow and
    host-sync walk its equations recursively); ``lowered_text`` is the
    StableHLO module text (collective inventory and donation markers — the
    program XLA actually receives, including the shard_map lowering the
    jaxpr only names symbolically). ``lowered`` is the live `jax.stages
    .Lowered` handle the text came from — skelly-scope's cost gate
    (`obs.cost`) compiles it for XLA's cost/memory analyses; audit checks
    never touch it (tests construct BuiltProgram without one).

    ``in_paths``/``out_paths`` name the flat jaxpr inputs/outputs with
    their pytree paths (``"0.fibers.active"`` = first positional arg,
    attr ``fibers``, attr ``active``), in invar/outvar order — the
    vocabulary the ``mask`` contracts declare capacity masks and output
    pad-class pins in. None when a test builds the artifact by hand (the
    mask check then falls back to flat indices).
    """

    closed_jaxpr: object
    lowered_text: str
    lowered: object = None
    in_paths: tuple | None = None
    out_paths: tuple | None = None


@dataclass
class AuditProgram:
    """One registered entry point.

    ``build()`` assembles the fixture scene, traces, and lowers — called
    lazily so ``--list-programs`` and single-program runs never pay for the
    rest of the matrix. ``retrace_probe()``, when provided, runs the entry
    point twice with same-structure/different-value arguments through
    `testing.trace_counting_jit` and returns the trace count (the
    ``retrace-budget`` check compares it against the contract).
    """

    name: str
    layer: str                      # system | parallel | ensemble | solver
    summary: str
    build: Callable[[], BuiltProgram]
    retrace_probe: Callable[[], int] | None = None


@dataclass
class BuiltKernel:
    """The traced artifacts the ``dma`` check (`audit.dmaflow`) consumes.

    ``kernel_jaxpr`` is the Pallas kernel-body jaxpr (ref semantics:
    get/swap/dma_start/semaphore primitives); ``grid_mapping`` the
    `pallas_call` GridMapping (block shapes, input/output/scratch
    partition); ``n_dev`` the ring size the kernel was traced for;
    ``scene`` the builder's shape parameters (``kind``/``n_trg``/
    ``n_src`` for ring kernels, {} for gridded) so the verifier can
    cross-check the build-time eligibility gate against the traced
    artifact.
    """

    kernel_jaxpr: object
    grid_mapping: object
    n_dev: int
    scene: dict


@dataclass
class AuditKernel:
    """One registered Pallas kernel (the ``auditable_kernels()`` seam —
    same shape as `AuditProgram`, but ``build()`` returns the kernel-level
    artifact the DMA verifier walks rather than a whole-program lowering).
    Modules defining ``auditable_kernels`` are the lint boundary for the
    ``raw-dma`` rule: DMA/semaphore primitives are legal only inside them.
    """

    name: str
    layer: str
    summary: str
    build: Callable[[], BuiltKernel]


def _keystr(path) -> str:
    """One flat pytree path as a dotted name: SequenceKey indices and
    GetAttr/Dict keys joined with '.' (``0.fibers.active``)."""
    parts = []
    for k in path:
        if hasattr(k, "idx"):
            parts.append(str(k.idx))
        elif hasattr(k, "name"):
            parts.append(str(k.name))
        elif hasattr(k, "key"):
            parts.append(str(k.key))
        else:  # pragma: no cover - future key kinds
            parts.append(str(k))
    return ".".join(parts) or "result"   # a whole-output leaf has no keys


def _flat_paths(info_tree, strip_leading=False):
    """Dotted path per flat leaf of a Traced.args_info/out_info pytree.
    ``strip_leading`` drops the (args, kwargs) wrapper index args_info
    nests under, so declared paths read ``0.fibers.active`` rather than
    ``0.0.fibers.active``."""
    from jax import tree_util as jtu

    leaves, _ = jtu.tree_flatten_with_path(info_tree)
    out = []
    for path, _ in leaves:
        if strip_leading:
            path = path[1:]
        out.append(_keystr(path))
    return tuple(out)


def built_from(jitted, *args, **kwargs) -> BuiltProgram:
    """Trace + lower a `jax.jit`-wrapped callable once, capturing every
    artifact from the same trace (no double tracing/lowering)."""
    traced = jitted.trace(*args, **kwargs)
    lowered = traced.lower()
    in_paths = out_paths = None
    try:
        in_paths = _flat_paths(traced.args_info, strip_leading=True)
        out_paths = _flat_paths(traced.out_info)
        if len(in_paths) != len(traced.jaxpr.jaxpr.invars):
            in_paths = None        # static/donated args shift the mapping
    except Exception:  # pragma: no cover - older tracing APIs
        in_paths = out_paths = None
    return BuiltProgram(closed_jaxpr=traced.jaxpr,
                        lowered_text=lowered.as_text(),
                        lowered=lowered,
                        in_paths=in_paths,
                        out_paths=out_paths)
