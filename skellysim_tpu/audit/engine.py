"""Contract loading, suppression discipline, and the per-program driver.

A contract (`audit/contracts/<program>.toml`) is the machine-readable twin
of docs/parallel.md's collective table: it pins what the lowered program is
allowed to look like. Deviations are findings; deliberate deviations are
suppressed *in the contract file* with a mandatory reason::

    [[suppress]]
    check = "dtype-flow"
    match = "float32->float64"
    reason = "refinement merges the f64 correction back into the f32 basis"

mirroring skelly-lint's pragma discipline: a suppression that matches no
finding is itself a finding, so every entry stays load-bearing.
"""

from __future__ import annotations

import os
from dataclasses import dataclass

from ..config import toml_io

CONTRACT_DIR = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                            "contracts")

#: contract sections the engine understands; anything else is drift (a
#: typo'd section would otherwise silently stop gating)
_KNOWN_SECTIONS = ("program", "collectives", "dtype", "host_sync",
                   "donation", "retrace", "fft", "replication", "dma",
                   "mask", "suppress")


@dataclass(frozen=True)
class Finding:
    program: str
    check: str
    message: str

    def render(self) -> str:
        return f"{self.program}: {self.check}: {self.message}"


def contract_path(name: str) -> str:
    return os.path.join(CONTRACT_DIR, f"{name}.toml")


def load_contract(name: str):
    """(contract dict, [Finding]) — findings for missing/invalid files."""
    path = contract_path(name)
    if not os.path.exists(path):
        return None, [Finding(name, "contract", (
            f"no contract file at audit/contracts/{name}.toml — every "
            "registered program must pin its lowered shape (run "
            f"`python -m skellysim_tpu.audit --dump-contract {name}` for "
            "the observed inventory)"))]
    data = toml_io.load(path)
    out = []
    for key in data:
        if key not in _KNOWN_SECTIONS:
            out.append(Finding(name, "contract", (
                f"unknown contract section [{key}] (known: "
                f"{', '.join(_KNOWN_SECTIONS)}) — a typo here would "
                "silently stop gating")))
    declared = data.get("program", {}).get("name")
    if declared is not None and declared != name:
        out.append(Finding(name, "contract", (
            f"contract file {name}.toml declares program.name="
            f"{declared!r} — copy-paste drift")))
    for i, sup in enumerate(data.get("suppress", [])):
        if not sup.get("check") or not sup.get("match"):
            # an EMPTY match would substring-match every finding of the
            # check — a blanket suppression must not be expressible
            out.append(Finding(name, "contract", (
                f"suppress entry #{i + 1} needs both `check` and a "
                "non-empty `match`")))
        if not sup.get("reason"):
            out.append(Finding(name, "contract", (
                f"suppress entry #{i + 1} is missing its reason: every "
                "suppression must say why")))
    return data, out


def apply_suppressions(name, contract, findings, active_checks=None):
    """Filter ``findings`` through the contract's ``[[suppress]]`` entries;
    unused entries become findings (the lint-pragma rule, contract-side).

    ``active_checks`` limits the unused-suppression enforcement: a
    ``--check``-filtered run must not flag entries for checks it skipped
    (same rule as the lint engine's filtered-pragma behavior).
    """
    entries = [dict(e, used=False) for e in (contract or {}).get(
        "suppress", [])]
    kept = []
    for f in findings:
        hit = False
        for e in entries:
            if (e.get("check") == f.check and e.get("reason")
                    and e.get("match") and e["match"] in f.message):
                e["used"] = True
                hit = True
        if not hit:
            kept.append(f)
    for e in entries:
        if (not e["used"] and e.get("reason") and e.get("check")
                and (active_checks is None or e["check"] in active_checks)):
            kept.append(Finding(name, "contract", (
                f"unused suppression (check={e['check']!r}, "
                f"match={e.get('match')!r}): it matches no finding — "
                "remove it or it hides the next real one")))
    return kept


def run_program_audit(prog, contract=None, checks=None):
    """Audit one registered `AuditProgram`; returns unsuppressed findings.

    ``contract=None`` loads the program's file from `CONTRACT_DIR` (tests
    pass a dict directly to exercise drift/suppression paths without
    touching the tree's contracts).
    """
    from .checks import CHECKS

    if contract is None:
        contract, findings = load_contract(prog.name)
        if contract is None:
            return findings
    else:
        findings = []
    # kernel-only checks (dma) belong to `run_kernel_audit`'s matrix
    program_checks = tuple(c for c in CHECKS if c.over_programs)
    active_ids = (None if checks is None
                  else {c.id for c in program_checks if c.id in set(checks)})
    try:
        built = prog.build()
    except Exception as e:  # a program that no longer lowers IS the finding
        findings.append(Finding(prog.name, "build", (
            f"entry point failed to trace/lower: {type(e).__name__}: {e}")))
        return apply_suppressions(prog.name, contract, findings, active_ids)
    active = program_checks if checks is None else tuple(
        c for c in program_checks if c.id in set(checks))
    for check in active:
        probe = prog.retrace_probe if check.wants_probe else None
        findings.extend(check.run(prog.name, built, contract, probe))
    return apply_suppressions(prog.name, contract, findings, active_ids)


def run_kernel_audit(kern, contract=None, checks=None):
    """Audit one registered `AuditKernel` (the Pallas-kernel twin of
    `run_program_audit`): only the kernel-scoped checks (today: ``dma``)
    apply; contract loading, suppression discipline, and build-failure
    handling are identical."""
    from .checks import CHECKS

    if contract is None:
        contract, findings = load_contract(kern.name)
        if contract is None:
            return findings
    else:
        findings = []
    active = tuple(c for c in CHECKS if c.over_kernels
                   and (checks is None or c.id in set(checks)))
    active_ids = {c.id for c in active}
    try:
        built = kern.build()
    except Exception as e:  # a kernel that no longer traces IS the finding
        findings.append(Finding(kern.name, "build", (
            f"kernel failed to trace: {type(e).__name__}: {e}")))
        return apply_suppressions(kern.name, contract, findings, active_ids)
    for check in active:
        findings.extend(check.run(kern.name, built, contract, None))
    return apply_suppressions(kern.name, contract, findings, active_ids)


def dump_kernel_contract(kern) -> str:
    """The observed ``[dma]`` inventory of one registered kernel in
    contract TOML (round-trips through `config.toml_io`)."""
    from . import dmaflow

    report = dmaflow.analyze(kern.build())
    data = {"program": {"name": kern.name}, "dma": dict(report.observed),
            "mask": {"axes": []}}
    return toml_io.dumps(data)


def _mask_section(name, built):
    """The observed `[mask]` dict for ``--dump-contract``: axes come from
    the EXISTING contract (the declaration is a human decision, not an
    observation), the per-output pad classes from the analyzer."""
    from .checks import mask_axes_from_contract, mask_summary

    existing = {}
    path = contract_path(name)
    if os.path.exists(path):
        existing = toml_io.load(path).get("mask", {})
    axes, _ = mask_axes_from_contract(existing, name)
    _, observed = mask_summary(built, axes)
    if existing.get("axes"):
        observed["axes"] = existing["axes"]
    return observed


def dump_contract(prog) -> str:
    """The observed inventory of ``prog`` in contract TOML — the starting
    point for writing (or deliberately updating) its contract file."""
    from .checks import (callback_inventory, collective_inventory, dtype_flow,
                         fft_inventory, replication_summary)

    built = prog.build()
    sites = collective_inventory(built.lowered_text)
    by_op = {}
    for s in sites:
        spec = by_op.setdefault(s.op, {"count": 0, "max_elems": 0,
                                       "max_bytes": 0})
        spec["count"] += 1
        spec["max_elems"] = max(spec["max_elems"], s.max_elems)
        spec["max_bytes"] = max(spec["max_bytes"], s.max_bytes)
    promotions, weak = dtype_flow(built.closed_jaxpr)
    callbacks = callback_inventory(built.closed_jaxpr)
    from .checks import DONATION_MARKERS

    data = {"program": {"name": prog.name}}
    if by_op:
        data["collectives"] = {op: spec for op, spec in sorted(by_op.items())}
    if promotions:
        data["dtype"] = {"promotions": dict(sorted(promotions.items()))}
    if callbacks:
        data["host_sync"] = {"allowed_callbacks": sorted(callbacks)}
    data["donation"] = {"donated": any(m in built.lowered_text
                                       for m in DONATION_MARKERS)}
    if prog.retrace_probe is not None:
        data["retrace"] = {"max_traces": 1}
    ffts = fft_inventory(built.closed_jaxpr)
    if ffts:
        data["fft"] = {"count": sum(ffts.values())}
    _, replication = replication_summary(built.closed_jaxpr)
    if replication is not None:
        data["replication"] = replication
    data["mask"] = _mask_section(prog.name, built)
    text = toml_io.dumps(data)
    if weak:
        text += ("\n# NOTE: weak-typed promotions observed (always findings;"
                 " fix or suppress):\n")
        for edge, n in sorted(weak.items()):
            text += f"#   {edge} x{n}\n"
    return text
