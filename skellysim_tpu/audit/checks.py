"""The audit checks: extractors over lowered artifacts + contract comparison.

Each check is a pure function ``(program_name, built, contract, probe) ->
[Finding]`` over the artifacts in `registry.BuiltProgram`. Extraction is
deliberately split from comparison so ``--dump-contract`` can print the
observed inventory in contract syntax (the sanctioned way to update a
contract after a deliberate program change).
"""

from __future__ import annotations

import re
from dataclasses import dataclass

from .engine import Finding

#: StableHLO collective ops audited (sans the `stablehlo.` prefix). Anything
#: matching here that the contract does not name is an uncontracted
#: collective — the GSPMD silent-resharding failure mode this layer exists
#: to rule out.
COLLECTIVE_OPS = ("all_reduce", "all_gather", "collective_permute",
                  "all_to_all", "reduce_scatter", "collective_broadcast")

_OP_RE = re.compile(
    r'"?stablehlo\.(%s)"?\(' % "|".join(COLLECTIVE_OPS))
#: the op's function-type signature: `... : (operand types) -> results`,
#: preceded by the attr-dict close (`}> : (...) ->`, inline ops) or the
#: region close (`}) : (...) ->`, all_reduce/reduce_scatter) or a bare
#: operand-list close (`) : (`). It is the first `: (` after the op head —
#: attr dicts and region bodies only contain value-typed colons
#: (`0 : i64`, `: tensor<f64>`), never `: (`.
_SIG_RE = re.compile(r"[)>]\s*:\s*\(([^)]*)\)\s*->\s*([^\n]*)")
_TENSOR_RE = re.compile(r"tensor<([0-9x]*)x?(f|bf|i|ui|c)([0-9]+)>")


def _tensor_elems_bytes(type_list: str):
    """[(elems, bytes)] for every tensor type in a signature fragment."""
    out = []
    for dims, kind, bits in _TENSOR_RE.findall(type_list):
        elems = 1
        for d in dims.split("x"):
            if d:
                elems *= int(d)
        width = int(bits) * (2 if kind == "c" else 1)
        out.append((elems, max(1, width // 8) * elems))
    return out


@dataclass(frozen=True)
class CollectiveSite:
    op: str
    max_elems: int    # largest tensor (operand or result) at the site
    max_bytes: int


def collective_inventory(lowered_text: str):
    """Every collective site in the StableHLO text, in program order."""
    sites = []
    for m in _OP_RE.finditer(lowered_text):
        window = lowered_text[m.start():m.start() + 6000]
        sig = _SIG_RE.search(window)
        tensors = _tensor_elems_bytes(
            f"{sig.group(1)} {sig.group(2)}") if sig else []
        sites.append(CollectiveSite(
            op=m.group(1),
            max_elems=max((e for e, _ in tensors), default=0),
            max_bytes=max((b for _, b in tensors), default=0)))
    return sites


def _subjaxprs(params):
    for v in params.values():
        for item in (v if isinstance(v, (list, tuple)) else [v]):
            if hasattr(item, "jaxpr") and hasattr(item.jaxpr, "eqns"):
                yield item.jaxpr           # ClosedJaxpr
            elif hasattr(item, "eqns"):
                yield item                 # raw Jaxpr


def walk_eqns(jaxpr):
    """Every equation in ``jaxpr`` and its sub-jaxprs (while/cond/scan/
    shard_map/... bodies), statically — one visit per program-text site."""
    for eqn in jaxpr.eqns:
        yield eqn
        for sub in _subjaxprs(eqn.params):
            yield from walk_eqns(sub)


_FLOAT_WIDTH = {"bfloat16": 16, "float16": 16, "float32": 32, "float64": 64}


def dtype_flow(closed_jaxpr):
    """(promotions, weak_promotions): ``promotions`` maps
    "float32->float64"-style edges to their static site count;
    ``weak_promotions`` counts converts whose *weak-typed float* operand
    widens — the Python-literal promotion family the AST cannot see."""
    promotions = {}
    weak = {}
    for eqn in walk_eqns(closed_jaxpr.jaxpr):
        if eqn.primitive.name != "convert_element_type":
            continue
        src = eqn.invars[0].aval
        dst = eqn.outvars[0].aval
        sw = _FLOAT_WIDTH.get(str(src.dtype))
        dw = _FLOAT_WIDTH.get(str(dst.dtype))
        if sw is None or dw is None or dw <= sw:
            continue
        edge = f"{src.dtype}->{dst.dtype}"
        if getattr(src, "weak_type", False):
            weak[edge] = weak.get(edge, 0) + 1
        else:
            promotions[edge] = promotions.get(edge, 0) + 1
    return promotions, weak


def callback_inventory(closed_jaxpr):
    """Host-callback primitive -> static site count (each site is a
    device->host round-trip per execution of its enclosing region)."""
    out = {}
    for eqn in walk_eqns(closed_jaxpr.jaxpr):
        name = eqn.primitive.name
        if "callback" in name or name in ("infeed", "outfeed"):
            out[name] = out.get(name, 0) + 1
    return out


def fft_inventory(closed_jaxpr):
    """fft kind (FFT/IFFT/RFFT/IRFFT) -> static site count. One spectral
    apply is one forward + one inverse transform per kernel; extra sites
    mean an accidental per-component or per-axis re-transform — an
    O(N log N) constant-factor regression invisible to correctness tests."""
    out = {}
    for eqn in walk_eqns(closed_jaxpr.jaxpr):
        if eqn.primitive.name != "fft":
            continue
        kind = str(eqn.params.get("fft_type", "fft")).rsplit(".", 1)[-1]
        out[kind] = out.get(kind, 0) + 1
    return out


DONATION_MARKERS = ("jax.buffer_donor", "tf.aliasing_output")


_PAD_CLASSES = ("pad-exact-zero", "pad-passthrough", "live-only")


def mask_axes_from_contract(spec, name):
    """([MaskAxis], [Finding]) from a contract's `[mask]` section: each
    `[[mask.axes]]` entry needs a `name` and a `mask` input path;
    `scope`/`dim`/`inputs` refine which input leaves it guards."""
    from . import maskflow

    axes, out = [], []
    seen = set()
    for i, e in enumerate(spec.get("axes", [])):
        ax_name, mask = e.get("name"), e.get("mask")
        if not ax_name or not mask:
            out.append(Finding(name, "mask", (
                f"[[mask.axes]] entry #{i + 1} needs both `name` and "
                "`mask` (the boolean live-mask input path)")))
            continue
        if ax_name in seen:
            out.append(Finding(name, "mask", (
                f"duplicate mask axis name {ax_name!r} — each capacity "
                "axis declares exactly once")))
            continue
        seen.add(ax_name)
        inputs = tuple(sorted((e.get("inputs") or {}).items()))
        axes.append(maskflow.MaskAxis(
            name=ax_name, mask=mask, scope=e.get("scope"),
            dim=int(e.get("dim", 0)), inputs=inputs))
    return axes, out


def mask_summary(built, axes):
    """(report, observed) — the maskflow analysis plus the contract-shaped
    `[mask]` dict ``--dump-contract`` emits (outputs table only when
    capacity axes are declared: with none, every output is trivially
    live-only and pins would be noise)."""
    from . import maskflow

    kernel_jaxpr = getattr(built, "kernel_jaxpr", None)
    if kernel_jaxpr is not None:
        report = maskflow.analyze(kernel_jaxpr, axes=())
        return report, {"axes": []}
    report = maskflow.analyze(built.closed_jaxpr, axes,
                              built.in_paths, built.out_paths)
    observed = {"axes": []}
    if axes:
        observed["outputs"] = dict(report.observed)
    return report, observed


def check_mask(name, built, contract, probe):
    """skelly-maskflow (`audit.maskflow`, docs/audit.md "Masking
    discipline"): taint/non-interference analysis proving padded lanes,
    nodes, and leaves cannot contaminate live physics. Runs over BOTH
    matrices: programs declare their capacity masks (pytree input paths)
    in `[[mask.axes]]` and pin every output's pad class in
    `[mask.outputs]`; Pallas kernels (no pytree inputs) get the
    declaration-free detectors only (`0 * inf` multiplicative masking)."""
    out = []
    cid = "mask"
    spec = contract.get("mask")
    if spec is None:
        out.append(Finding(name, cid, (
            "no [mask] section — declare the program's padded-capacity "
            "axes (`axes = []` when nothing is padded) so the masking "
            "discipline is pinned, not assumed (run --dump-contract "
            "for the observed surface)")))
        return out
    is_kernel = getattr(built, "kernel_jaxpr", None) is not None
    axes, ax_findings = mask_axes_from_contract(spec, name)
    out.extend(ax_findings)
    if is_kernel and (axes or spec.get("outputs")):
        out.append(Finding(name, cid, (
            "kernel contracts cannot declare mask axes or output pins — "
            "Pallas kernel refs have no pytree paths; only the "
            "declaration-free detectors apply")))
        axes = []
    report, _ = mask_summary(built, axes)
    for f in report.findings:
        out.append(Finding(name, cid, f.message))
    if is_kernel:
        return out
    pins = dict(spec.get("outputs", {}))
    if not axes:
        if pins:
            out.append(Finding(name, cid, (
                "stale [mask.outputs] table: no capacity axes are "
                "declared, so every output is trivially live-only — "
                "drop the pins or declare the axes")))
        return out
    observed = report.observed
    for path in observed:
        pin = pins.pop(path, None)
        if pin is None:
            out.append(Finding(name, cid, (
                f"output '{path}' has no [mask.outputs] pin — every "
                f"output of a padded program must pin its pad class "
                f"(observed: {observed[path]})")))
        elif pin not in _PAD_CLASSES:
            out.append(Finding(name, cid, (
                f"output '{path}' pins unknown pad class {pin!r} "
                f"(known: {', '.join(_PAD_CLASSES)})")))
        elif pin != observed[path]:
            out.append(Finding(name, cid, (
                f"output '{path}' pad class drifted: contract pins "
                f"{pin!r}, the analyzer proves {observed[path]!r} — "
                "an output moved across the padded/live boundary; "
                "re-derive the pin deliberately")))
    for path, pin in sorted(pins.items()):
        out.append(Finding(name, cid, (
            f"stale pin: [mask.outputs] pins '{path}' = {pin!r} but the "
            "traced program has no such output path")))
    return out


def replication_summary(closed_jaxpr):
    """(report, observed) — the repflow analysis plus its contract-shaped
    summary dict (what ``--dump-contract`` emits as ``[replication]``)."""
    from . import repflow

    report = repflow.analyze(closed_jaxpr)
    observed = None
    if report.regions:
        observed = {
            "mesh_axes": report.mesh_axes,
            "replicated_outputs": sum(r.replicated_outputs
                                      for r in report.regions),
            "varying_outputs": sum(r.varying_outputs for r in report.regions),
        }
    return report, observed


# ------------------------------------------------------------------ checks

def check_collective_contract(name, built, contract, probe):
    out = []
    cid = "collective-contract"
    want = dict(contract.get("collectives", {}))
    sites = collective_inventory(built.lowered_text)
    by_op = {}
    for s in sites:
        by_op.setdefault(s.op, []).append(s)
    for op, op_sites in sorted(by_op.items()):
        spec = want.pop(op, None)
        if spec is None:
            out.append(Finding(name, cid, (
                f"uncontracted collective: {len(op_sites)} "
                f"stablehlo.{op} site(s) in the lowered program but the "
                f"contract has no [collectives.{op}] entry")))
            continue
        count = spec.get("count")
        if count is None:
            # a bound-only entry would rot silently once the op vanishes
            # (no count gate, no stale gate) — the count pin is mandatory
            out.append(Finding(name, cid, (
                f"[collectives.{op}] has no `count` pin — every "
                "contracted collective must pin its static count")))
        elif count != len(op_sites):
            out.append(Finding(name, cid, (
                f"{op} count drifted: contract pins {count}, lowered "
                f"program has {len(op_sites)}")))
        max_elems = spec.get("max_elems")
        max_bytes = spec.get("max_bytes")
        for s in op_sites:
            if max_elems is not None and s.max_elems > max_elems:
                out.append(Finding(name, cid, (
                    f"{op} carries {s.max_elems} elements, over the "
                    f"contract bound of {max_elems} — an unexpected "
                    "operand is crossing the mesh")))
            if max_bytes is not None and s.max_bytes > max_bytes:
                out.append(Finding(name, cid, (
                    f"{op} moves {s.max_bytes} bytes, over the contract "
                    f"bound of {max_bytes}")))
    for op, spec in sorted(want.items()):
        if spec.get("count", 1) != 0:
            out.append(Finding(name, cid, (
                f"stale contract: [collectives.{op}] pins count="
                f"{spec.get('count')} but the lowered program has none")))
    return out


def check_dtype_flow(name, built, contract, probe):
    out = []
    cid = "dtype-flow"
    spec = contract.get("dtype", {})
    allowed = dict(spec.get("promotions", {}))
    promotions, weak = dtype_flow(built.closed_jaxpr)
    for edge, n in sorted(promotions.items()):
        pinned = allowed.pop(edge, None)
        if pinned is None:
            out.append(Finding(name, cid, (
                f"{n} {edge} promotion site(s): a narrow float widens on "
                "the traced path with no [dtype.promotions] entry — the "
                "46b498b leak family, now visible at the jaxpr level")))
        elif pinned != n:
            out.append(Finding(name, cid, (
                f"{edge} promotion count drifted: contract pins {pinned}, "
                f"jaxpr has {n}")))
    for edge, pinned in sorted(allowed.items()):
        out.append(Finding(name, cid, (
            f"stale contract: [dtype.promotions] pins {edge} = {pinned} "
            "but the jaxpr has no such edge")))
    for edge, n in sorted(weak.items()):
        out.append(Finding(name, cid, (
            f"{n} weak-typed {edge} promotion site(s): a Python float "
            "literal is widening traced data (pin the literal's dtype at "
            "the site)")))
    return out


def check_host_sync(name, built, contract, probe):
    out = []
    cid = "host-sync"
    allowed = set(contract.get("host_sync", {}).get("allowed_callbacks", []))
    found = callback_inventory(built.closed_jaxpr)
    for prim, n in sorted(found.items()):
        if prim in allowed:
            allowed.discard(prim)
        else:
            out.append(Finding(name, cid, (
                f"{n} {prim} site(s) inside the jitted program: each is a "
                "host round-trip per execution (and a tracer sync point); "
                "hoist it out of the step or allow it in the contract "
                "with a reason")))
    for prim in sorted(allowed):
        out.append(Finding(name, cid, (
            f"stale contract: host_sync allows {prim!r} but the program "
            "has no such callback")))
    return out


def check_donation(name, built, contract, probe):
    spec = contract.get("donation")
    if spec is None:
        return []
    cid = "donation"
    marked = any(m in built.lowered_text for m in DONATION_MARKERS)
    if spec.get("donated") and not marked:
        return [Finding(name, cid, (
            "contract says the input buffers are donated but the lowered "
            "program carries no aliasing marker "
            f"({' / '.join(DONATION_MARKERS)}) — every step double-buffers "
            "the pass-through leaves"))]
    if not spec.get("donated") and marked:
        return [Finding(name, cid, (
            "contract says NO donation (rollback safety) but the lowered "
            "program aliases its inputs — a rejected step would roll back "
            "into consumed buffers"))]
    return []


def check_retrace_budget(name, built, contract, probe):
    spec = contract.get("retrace")
    if spec is None:
        return []
    cid = "retrace-budget"
    if probe is None:
        return [Finding(name, cid, (
            "contract has a [retrace] budget but the program registers no "
            "retrace probe — drop the section or register one"))]
    budget = spec.get("max_traces", 1)
    traces = probe()
    if traces > budget:
        return [Finding(name, cid, (
            f"entry point traced {traces}x across same-structure calls "
            f"(budget {budget}): some argument's static signature varies "
            "call-to-call, paying full XLA compilation on the hot path"))]
    return []


def check_fft_inventory(name, built, contract, probe):
    out = []
    cid = "fft-inventory"
    observed = fft_inventory(built.closed_jaxpr)
    total = sum(observed.values())
    breakdown = ", ".join(f"{k} x{n}" for k, n in sorted(observed.items()))
    spec = contract.get("fft")
    if spec is None:
        if total:
            out.append(Finding(name, cid, (
                f"{total} fft primitive site(s) ({breakdown}) with no "
                "[fft] section — transforms are the spectral evaluator's "
                "cost center; pin their static count")))
        return out
    pinned = spec.get("count")
    if pinned is None:
        out.append(Finding(name, cid, (
            "[fft] has no `count` pin — a contracted fft inventory must "
            "pin its static site count")))
    elif pinned != total:
        detail = breakdown if total else "none"
        out.append(Finding(name, cid, (
            f"fft count drifted: contract pins {pinned}, the jaxpr has "
            f"{total} ({detail}) — a per-component or per-axis "
            "re-transform crept in (or the contract is stale); re-derive "
            "it deliberately")))
    return out


def check_replication(name, built, contract, probe):
    """Replication-flow analysis (`audit.repflow`, docs/parallel.md):
    statically prove the program's `shard_map` regions cannot deadlock —
    no varying `while_loop`/`cond` predicates, no collectives under
    divergence, every replicated-declared output provably replicated, no
    ppermute-fed accumulation escaping to a replicated consumer — and pin
    the replicated-output surface against ``[replication]``."""
    out = []
    cid = "replication"
    report, observed = replication_summary(built.closed_jaxpr)
    for f in report.findings:
        out.append(Finding(name, cid, f.message))
    spec = contract.get("replication")
    if observed is None:
        if spec is not None:
            out.append(Finding(name, cid, (
                "stale contract: a [replication] section is pinned but the "
                "lowered program has no shard_map region")))
        return out
    if spec is None:
        out.append(Finding(name, cid, (
            f"sharded program with no [replication] section: "
            f"{len(report.regions)} shard_map region(s) over mesh axes "
            f"{observed['mesh_axes']} — pin mesh_axes / replicated_outputs "
            "/ varying_outputs (run --dump-contract for the observed "
            "surface)")))
        return out
    pinned_axes = list(spec.get("mesh_axes", []))
    if pinned_axes != observed["mesh_axes"]:
        out.append(Finding(name, cid, (
            f"mesh axes drifted: contract pins {pinned_axes}, the program "
            f"shards over {observed['mesh_axes']}")))
    for key, what in (("replicated_outputs", "replicated"),
                      ("varying_outputs", "varying (sharded)")):
        pinned = spec.get(key)
        if pinned is None:
            out.append(Finding(name, cid, (
                f"[replication] has no `{key}` pin — the {what} output "
                "surface must pin its static count")))
        elif pinned != observed[key]:
            out.append(Finding(name, cid, (
                f"{key} drifted: contract pins {pinned}, the analyzed "
                f"program has {observed[key]} — an output moved across the "
                "replicated/sharded boundary; re-derive the contract "
                "deliberately")))
    return out


def check_dma(name, built, contract, probe):
    """skelly-fence (`audit.dmaflow`): DMA happens-before, semaphore
    balance, barrier-protocol model check, and VMEM accounting over one
    registered Pallas kernel. Unlike the six program checks this one
    consumes a `registry.BuiltKernel` (the engine routes it over
    `kernels.all_kernels`, not the program matrix); the ``[dma]`` contract
    section pins the analyzer's full observed inventory key by key."""
    from . import dmaflow

    cid = "dma"
    report = dmaflow.analyze(built)
    out = [Finding(name, cid, f.message) for f in report.findings]
    spec = (contract or {}).get("dma")
    if spec is None:
        out.append(Finding(name, cid, (
            "[dma] contract section missing — pin the kernel's slot "
            "counts, semaphore inventory, and footprint (run "
            f"`--dump-contract {name}` for the observed values)")))
        return out
    observed = report.observed
    for key in sorted(set(spec) | set(observed)):
        if key not in observed:
            out.append(Finding(name, cid, (
                f"stale pin `{key}`: the analyzer no longer reports it — "
                "remove it or it documents an inventory that is not being "
                "checked")))
        elif key not in spec:
            out.append(Finding(name, cid, (
                f"[dma] has no `{key}` pin — the analyzer reports "
                f"{observed[key]!r}; every inventory key must be pinned")))
        elif spec[key] != observed[key]:
            out.append(Finding(name, cid, (
                f"{key} drifted: contract pins {spec[key]!r}, the traced "
                f"kernel shows {observed[key]!r} — re-derive the contract "
                "deliberately")))
    return out


@dataclass(frozen=True)
class Check:
    id: str
    summary: str
    run: object  # callable(name, built, contract, probe) -> [Finding]
    #: needs the (possibly expensive) retrace probe instead of artifacts
    wants_probe: bool = False
    #: runs over the Pallas kernel registry (`kernels.all_kernels`) —
    #: ``built`` is a `registry.BuiltKernel` there
    over_kernels: bool = False
    #: runs over the program matrix (`programs.all_programs`); a check
    #: may cover both matrices (mask) or exactly one (dma: kernels only)
    over_programs: bool = True


CHECKS = (
    Check("collective-contract",
          "StableHLO collective inventory (kind/count/operand size) must "
          "match the per-program contract exactly",
          check_collective_contract),
    Check("dtype-flow",
          "convert_element_type promotion edges and weak-typed float "
          "widenings in the closed jaxpr vs the contract",
          check_dtype_flow),
    Check("host-sync",
          "pure_callback/io_callback/debug_callback (and in/outfeed) "
          "primitives inside the jitted program",
          check_host_sync),
    Check("donation",
          "input->output buffer aliasing markers at lowering time match "
          "the contract's donated flag",
          check_donation),
    Check("retrace-budget",
          "trace_counting_jit compile count across same-structure calls "
          "stays within the contract budget",
          check_retrace_budget, wants_probe=True),
    Check("fft-inventory",
          "fft primitive sites in the closed jaxpr vs the contract's "
          "[fft] count pin (the spectral evaluator's transform budget)",
          check_fft_inventory),
    Check("replication",
          "replication-flow analysis over shard_map regions: no varying "
          "while/cond predicates (the manual-SPMD deadlock), no collectives "
          "under divergence, replicated outputs provably replicated",
          check_replication),
    Check("dma",
          "skelly-fence static DMA verifier over the Pallas kernel "
          "registry: read-before-arrival, overwrite-in-flight (barrier "
          "protocol model-checked), semaphore credit balance, VMEM "
          "footprint vs the shared budget",
          check_dma, over_kernels=True, over_programs=False),
    Check("mask",
          "skelly-maskflow taint analysis over programs AND kernels: "
          "padded capacity slots provably cannot contaminate live "
          "physics (pad-escape, 0*inf multiplicative masking, unmasked "
          "reductions, unsentineled argreduces; per-output pad-class "
          "pins)",
          check_mask, over_kernels=True),
)
