"""Mask-flow analysis: statically prove padded capacity cannot leak.

Every scaling lever in this tree rides the same trick: fix the shapes, pad
the scene, mask the garbage — capacity buckets (`system.buckets`), DI
nucleation as in-trace mask flips (`scenarios.di_device`), quarantined
ensemble lanes (`ensemble.runner`), the treecode's power-of-two leaf
buckets, the spectral evaluator's rung ladders. The soundness of those
~176 mask sites used to rest on bitwise runtime tests and comment prose.
This module is the machine check (docs/audit.md "Masking discipline"): a
taint / non-interference abstract interpreter over closed jaxprs. The
contract declares which boolean inputs are capacity masks (pytree paths
like ``0.fibers.active``) and which input leaves they guard; the analyzer
tracks, per value and per (mask axis, array dimension), what the padded
slots hold, and reports four finding kinds:

* ``pad-escape`` — padded-slot garbage arithmetically mixed into live
  entries reaches a program output: the contamination itself.
* ``nan-unsafe-neutralization`` — multiplicative masking (``x * mask``)
  of a possibly-nonfinite float: ``0 * inf = NaN``, so the "masked"
  value poisons everything downstream. Flagged unless the operand is
  proven finite; ``jnp.where(mask, x, 0)`` is exact for every x.
* ``unmasked-reduction`` — a sum/max/min/prod (or dot_general
  contraction, prefix scan, sort) over a padded dimension whose padded
  slots still hold garbage, or hold values that are not the reduction's
  neutral element (zeros are neutral for sum, NOT for max/min/prod).
* ``unsentineled-argreduce`` — argmax/argmin over a padded dimension
  without the matching ∓inf sentinel (``where(mask, x, -inf)`` for
  argmax): provenance ids — the flight recorder's anomaly attribution —
  could name a padded lane.

The lattice
-----------

Per value, per declared mask axis ``A`` and array dimension ``d``, the
padded slots are in one of five classes::

    DIRTY   input-pad garbage (grow_capacity replicates stale rows)
    ZERO    exactly zero       (neutral for sum; safe to contract away)
    SNEG    exactly -inf       (the argmax sentinel)
    SPOS    exactly +inf       (the argmin sentinel)
    CLEAN   live-derived values (no region recorded): no garbage, but
            nothing provable about the padded slots either

``jnp.where(mask, x, fill)`` with a declared mask is the class-setting
discipline: padded slots take the fill branch, so a literal ``0`` fill
proves ZERO, ``-inf`` proves SNEG, and any clean fill proves CLEAN.
DIRTY regions contaminate on mixing (reductions, contractions, prefix
scans, sorts over the padded dim); ZERO regions are transparent to
additive mixing only. Contamination is tracked per value (``escaped``)
and sticks — once garbage reaches live entries no later select can
un-mix it.

Each program output is classified (the contract's ``[mask.outputs]``
pins)::

    pad-passthrough   padded slots still carry DIRTY/sentinel data
    pad-exact-zero    padded slots provably zero (bitwise; the
                      skelly-bucket "masked rows solve to exact zeros"
                      claim, checked instead of trusted)
    live-only         no padded structure survives to this output

Soundness is directional, like repflow: "analyzes clean" is a proof
modulo the modeled primitive set (unknown primitives degrade DIRTY
regions to escaped, never to clean), while a finding on a deliberate
site is suppressed in the contract with a reason. Two documented
precision choices: program *inputs* are assumed finite (live physics
data; runtime nonfiniteness is the flight recorder's job), and a select
under an arbitrary comparison guard launders nonfiniteness (the
``where(r > 0, 1/r, 0)`` self-interaction guard is treated as guarding —
the nan-unsafe finding targets UNguarded multiplicative masking).

Import-light by design (no jax import), reusing repflow's recursion
machinery: while/scan fixed points, pjit/cond/custom_* recursion, and
the integer constant folder for index provenance.
"""

from __future__ import annotations

import math
import os
from dataclasses import dataclass

from .repflow import _fold, _is_literal, _shape, _sub_jaxpr

#: finding kinds (messages lead with the kind so suppressions can match)
PAD_ESCAPE = "pad-escape"
NAN_UNSAFE = "nan-unsafe-neutralization"
UNMASKED_REDUCTION = "unmasked-reduction"
UNSENTINELED_ARGREDUCE = "unsentineled-argreduce"

#: output pad classes (the `[mask.outputs]` contract vocabulary)
PAD_PASSTHROUGH = "pad-passthrough"
PAD_EXACT_ZERO = "pad-exact-zero"
LIVE_ONLY = "live-only"

#: region classes, worst first
DIRTY = "dirty"
SNEG = "sneg"
SPOS = "spos"
ZERO = "zero"
_RANK = {DIRTY: 3, SNEG: 2, SPOS: 2, ZERO: 1}

_DEBUG = os.environ.get("SKELLY_MASKFLOW_DEBUG", "") not in ("", "0")


# --------------------------------------------------------------- the lattice

@dataclass(frozen=True)
class MState:
    """Abstract state of one value (see module docstring).

    ``regions``: frozenset of ``(axis, dim, cls)`` — what the padded
    slots of mask axis ``axis`` hold along array dimension ``dim``.
    ``escaped``: mask axes whose pad garbage has mixed into LIVE entries
    of this value (sticky). ``mask``: ``(axis, dims, live_polarity)``
    when the value IS a declared capacity mask (or its negation).
    ``boolish``: value is boolean / a 0-1 cast of one (the multiplicative
    -masking detector's trigger). ``nonfinite``: may hold inf/NaN even
    with finite program inputs. ``const``: known uniform scalar value.
    """

    regions: frozenset = frozenset()
    escaped: frozenset = frozenset()
    mask: tuple | None = None
    boolish: bool = False
    nonfinite: bool = False
    const: float | None = None

    def cls(self, axis, dim):
        for a, d, c in self.regions:
            if a == axis and d == dim:
                return c
        return None

    def region_dims(self):
        return {(a, d) for a, d, _ in self.regions}

    def __repr__(self):  # compact for debug logs
        bits = []
        if self.regions:
            bits.append("regions=" + ",".join(
                f"{a}@{d}:{c}" for a, d, c in sorted(self.regions)))
        if self.escaped:
            bits.append(f"escaped={sorted(self.escaped)}")
        if self.mask:
            bits.append(f"mask={self.mask}")
        if self.boolish:
            bits.append("boolish")
        if self.nonfinite:
            bits.append("nonfinite")
        if self.const is not None:
            bits.append(f"const={self.const}")
        return "M(" + " ".join(bits) + ")" if bits else "M(clean)"


CLEAN_STATE = MState()


def _worst(*classes):
    """Worst region class among ``classes`` (None = clean loses to all);
    mismatched sentinels are garbage to each other (-inf vs +inf)."""
    present = [c for c in classes if c is not None]
    if not present:
        return None
    if len(set(present)) > 1 and {SNEG, SPOS} <= set(present):
        return DIRTY
    return max(present, key=lambda c: _RANK[c])


def join(a: MState, b: MState) -> MState:
    """Control-flow join (cond branches, loop fixed points): pad classes
    must agree to survive — a slot that is zero on one path and clean on
    the other is provably neither."""
    if a == b:
        return a
    regions = set()
    for axis, dim in a.region_dims() | b.region_dims():
        ca, cb = a.cls(axis, dim), b.cls(axis, dim)
        if ca == cb:
            c = ca
        elif DIRTY in (ca, cb):
            c = DIRTY          # maybe-garbage joins to garbage
        else:
            c = None           # differing exact claims join to unprovable
        if c is not None:
            regions.add((axis, dim, c))
    return MState(
        regions=frozenset(regions),
        escaped=a.escaped | b.escaped,
        mask=a.mask if a.mask == b.mask else None,
        boolish=a.boolish and b.boolish,
        nonfinite=a.nonfinite or b.nonfinite,
        const=a.const if a.const == b.const else None)


def join_all(states):
    out = CLEAN_STATE
    for s in states:
        out = join(out, s)
    return out


def _escape(states, extra=frozenset()):
    """Conservative fallback: any DIRTY/sentinel region whose alignment
    an unmodeled primitive would lose is escalated to escaped (never
    silently laundered to clean)."""
    esc = set(extra)
    for s in states:
        esc |= s.escaped
        for a, _, c in s.regions:
            if c != ZERO:
                esc.add(a)
    return MState(escaped=frozenset(esc),
                  nonfinite=any(s.nonfinite for s in states))


# ------------------------------------------------------------------ findings

@dataclass(frozen=True)
class MaskFinding:
    kind: str
    message: str


@dataclass
class MaskReport:
    findings: list      # [MaskFinding], program order, deduped
    classes: list       # [(output path, pad class)], flat-output order

    @property
    def observed(self):
        return {path: cls for path, cls in self.classes}


@dataclass(frozen=True)
class MaskAxis:
    """One declared capacity axis (a `[[mask.axes]]` contract entry).

    ``mask`` is the flat-input path of the boolean live mask (True =
    live). ``scope``+``dim``: every input leaf under the ``scope`` path
    prefix whose shape at dims ``dim..dim+mask_ndim-1`` matches the
    mask's shape is padded there. ``inputs`` maps explicit paths to
    their pad dim for leaves outside the scope.
    """

    name: str
    mask: str
    scope: str | None = None
    dim: int = 0
    inputs: tuple = ()          # ((path, dim), ...)


# ------------------------------------------------------------------ helpers

_ZERO_PRESERVING = frozenset((
    "add", "sub", "mul", "neg", "abs", "max", "min", "square", "sqrt",
    "sign", "floor", "ceil", "round", "real", "imag", "copy",
    "stop_gradient", "convert_element_type", "reduce_precision",
    "device_put", "transpose"))

#: ops that can mint inf/NaN from finite operands (the nan-unsafe set;
#: exp-family overflow-to-inf is deliberately below the abstraction)
_NONFINITE_SOURCES = frozenset((
    "div", "rsqrt", "log", "log1p", "pow", "tan", "atanh", "acosh",
    "digamma", "lgamma", "rem", "erf_inv"))

_CMP = frozenset(("eq", "ne", "lt", "le", "gt", "ge", "is_finite"))

_ELEMENTWISE = frozenset("""
add sub mul div rem max min pow integer_pow exp exp2 log log1p expm1 sqrt
rsqrt cbrt sign neg abs floor ceil round is_finite eq ne lt le gt ge and or
xor not convert_element_type stop_gradient copy real imag conj erf erfc
erf_inv tanh sin cos tan asin acos atan atan2 sinh cosh asinh acosh atanh
logistic clamp nextafter square reduce_precision shift_left
shift_right_logical shift_right_arithmetic population_count clz device_put
copy_p logistic digamma lgamma
""".split())

_PASSTHROUGH = frozenset((
    "convert_element_type", "copy", "stop_gradient", "reduce_precision",
    "device_put", "copy_p", "real"))


def _is_float(atom) -> bool:
    dt = str(getattr(atom.aval, "dtype", ""))
    return dt.startswith("float") or dt.startswith("bfloat") or (
        dt.startswith("complex"))


def _is_bool(atom) -> bool:
    return str(getattr(atom.aval, "dtype", "")) == "bool"


def _scalar_const(val):
    """(const, nonfinite, boolish) of a literal / uniform ndarray."""
    try:
        import numpy as np

        arr = np.asarray(val)
        if arr.dtype == bool:
            if arr.size == 1:
                return float(bool(arr.reshape(-1)[0])), False, True
            return None, False, True
        if arr.size == 1 and arr.dtype.kind in "iuf":
            v = float(arr.reshape(-1)[0])
            return v, not math.isfinite(v), False
    except Exception:
        pass
    return None, False, False


def _dim_map_reshape(in_shape, out_shape, dim):
    """Output dim(s) carrying input dim ``dim`` across a row-major reshape:
    an int, a tuple of consecutive dims (``dim`` was SPLIT, e.g. the
    ``[N, 3] -> [blocks, block, 3]`` chunking before a scan — pad slots
    then scatter over every split dim), or None when alignment is lost.

    Exact for squeeze/unsqueeze of size-1 dims, for splits of ``dim``, and
    for the row-major flatten family (``[nf, n, ...] -> [nf*n, ...]``)
    when ``dim`` is the MAJOR merged dim — the pad structure stays a
    contiguous block per padded slot, so region/mask alignment survives
    (node_active_flat's ``repeat`` + flatten discipline)."""
    in_real = [(i, d) for i, d in enumerate(in_shape) if d != 1]
    out_real = [(i, d) for i, d in enumerate(out_shape) if d != 1]
    if [d for _, d in in_real] == [d for _, d in out_real]:
        if in_shape[dim] == 1:
            return None
        pos = [i for i, _ in in_real].index(dim)
        return out_real[pos][0]
    if 0 in in_shape or 0 in out_shape:
        return None
    ii = oi = 0
    while ii < len(in_shape) and oi < len(out_shape):
        # grow an m:n group [ii, ij) <-> [oi, oj) of equal extent
        ip, op, ij, oj = in_shape[ii], out_shape[oi], ii + 1, oi + 1
        while ip != op:
            if ip < op:
                if ij >= len(in_shape):
                    return None
                ip *= in_shape[ij]
                ij += 1
            else:
                if oj >= len(out_shape):
                    return None
                op *= out_shape[oj]
                oj += 1
        if ii <= dim < ij:
            if ij - ii == 1 and oj - oi == 1:
                return oi
            if ij - ii == 1:
                # pure split: a pad slot lands at mixed coordinates, the
                # claim spreads over EVERY split dim (realign may narrow)
                return tuple(range(oi, oj))
            if dim != ii:
                return None     # minor merged dim: alignment lost
            # dim is the group's MAJOR in dim: each pad slot is one
            # contiguous block of prod(in minors) elements, which covers
            # whole out-major slots iff it is a multiple of the out minor
            # extent ([nf, 3n] -> [nf*n, 3]: blocks of 3n = n rows of 3)
            in_minor = math.prod(in_shape[ii + 1:ij])
            out_minor = math.prod(out_shape[oi + 1:oj])
            return oi if in_minor % out_minor == 0 else None
        ii, oi = ij, oj
    return None


def _src(eqn):
    """Best-effort user ``file:line`` for an equation, '' when unknown —
    findings without a source frame are still findings, just harder to
    triage."""
    if eqn is None:
        return ""
    try:
        from jax._src import source_info_util

        frame = source_info_util.user_frame(eqn.source_info)
        if frame is None:
            return ""
        return f"{os.path.basename(frame.file_name)}:{frame.start_line}"
    except Exception:  # pragma: no cover - jax-internal API drift
        return ""


# --------------------------------------------------------------- interpreter

class _Analyzer:
    def __init__(self):
        self._findings = {}          # message -> MaskFinding (ordered dedupe)
        self._cache = {}             # (id(jaxpr), states) -> out states

    def _finding(self, kind, message, eqn=None):
        src = _src(eqn)
        msg = f"{kind}: {message}" + (f" [{src}]" if src else "")
        if msg not in self._findings:
            self._findings[msg] = MaskFinding(kind, msg)

    # -- reads -------------------------------------------------------------
    @staticmethod
    def _read(env, atom):
        if _is_literal(atom):
            const, nonfin, boolish = _scalar_const(atom.val)
            return MState(const=const, nonfinite=nonfin, boolish=boolish)
        return env.get(atom, CLEAN_STATE)

    @staticmethod
    def _read_val(vals, atom):
        if _is_literal(atom):
            try:
                import numpy as np

                arr = np.asarray(atom.val)
                if arr.ndim == 0 and arr.dtype.kind in "iub":
                    return int(arr)
            except Exception:
                return None
            return None
        return vals.get(atom)

    # -- drivers -----------------------------------------------------------
    def run_jaxpr(self, jaxpr, in_states, path, record, consts=None):
        if not record:
            key = (id(jaxpr), tuple(in_states))
            hit = self._cache.get(key)
            if hit is not None:
                return hit
        env = {}
        vals = {}
        for i, v in enumerate(tuple(getattr(jaxpr, "constvars", ()))):
            st = CLEAN_STATE
            if consts is not None and i < len(consts):
                const, nonfin, boolish = _scalar_const(consts[i])
                st = MState(const=const, nonfinite=nonfin, boolish=boolish)
            env[v] = st
        for v, s in zip(jaxpr.invars, in_states):
            env[v] = s
        for eqn in jaxpr.eqns:
            ins = [self._read(env, a) for a in eqn.invars]
            in_vals = [self._read_val(vals, a) for a in eqn.invars]
            outs = self._eqn(eqn, ins, in_vals, path, record)
            for var, s in zip(eqn.outvars, outs):
                env[var] = self._dtype_clamp(var, s)
            for var, v in zip(eqn.outvars, _fold(eqn, in_vals)):
                if v is not None:
                    vals[var] = v
        res = [self._read(env, a) for a in jaxpr.outvars]
        if not record:
            self._cache[key] = res
        return res

    def run_closed(self, closed, in_states, path, record):
        return self.run_jaxpr(_sub_jaxpr(closed), in_states, path, record,
                              consts=getattr(closed, "consts", None))

    @staticmethod
    def _dtype_clamp(var, s):
        """Non-float outputs cannot hold inf/NaN; bool outputs are
        boolish by construction."""
        dt = str(getattr(var.aval, "dtype", ""))
        if dt == "bool" and not s.boolish:
            s = MState(s.regions, s.escaped, s.mask, True, False, s.const)
        elif s.nonfinite and not (dt.startswith("float")
                                  or dt.startswith("bfloat")
                                  or dt.startswith("complex")):
            s = MState(s.regions, s.escaped, s.mask, s.boolish, False,
                       s.const)
        return s

    # -- equation dispatch -------------------------------------------------
    def _eqn(self, eqn, ins, in_vals, path, record):
        name = eqn.primitive.name
        n_out = len(eqn.outvars)

        if name == "select_n":
            return [self._select(eqn, ins, path, record)]
        if name == "while":
            return self._while(eqn, ins, path, record)
        if name == "cond":
            return self._cond(eqn, ins, path, record)
        if name == "scan":
            return self._scan(eqn, ins, path, record)
        if name == "pjit":
            label = eqn.params.get("name", "")
            return self.run_closed(eqn.params["jaxpr"], ins,
                                   f"{path}/jit:{label}", record)
        if name == "shard_map":
            return self.run_jaxpr(_sub_jaxpr(eqn.params["jaxpr"]), ins,
                                  f"{path}/shard_map", record)

        if name == "optimization_barrier":
            return list(ins)       # multi-value identity
        if name in _ELEMENTWISE:
            return [self._elementwise(name, eqn, ins, path, record)] * n_out
        h = _SHAPED.get(name)
        if h is not None:
            out = h(self, eqn, ins, in_vals, path, record)
            return out if isinstance(out, list) else [out] * n_out

        # generic call-like primitive: one sub-jaxpr whose invars match
        for key in ("call_jaxpr", "jaxpr", "fun_jaxpr"):
            obj = eqn.params.get(key)
            sub = _sub_jaxpr(obj) if obj is not None else None
            if sub is not None and len(sub.invars) == len(ins):
                return self.run_jaxpr(sub, ins, f"{path}/{name}", record)

        if _DEBUG and any(
                c != ZERO for s in ins for _, _, c in s.regions):
            print(f"maskflow: escalate via unmodeled `{name}` at {path}")
        return [_escape(ins)] * n_out

    # -- elementwise -------------------------------------------------------
    def _elementwise(self, name, eqn, ins, path, record):
        escaped = frozenset().union(*[s.escaped for s in ins]) if ins \
            else frozenset()
        nonfinite = any(s.nonfinite for s in ins)
        if name in _NONFINITE_SOURCES and _is_float(eqn.outvars[0]):
            if not (name == "div" and ins[1].const not in (None, 0.0)):
                nonfinite = True
        boolish = False
        mask = None
        const = None

        if name in _CMP:
            boolish, nonfinite = True, False
        elif name in ("and", "or", "xor", "not"):
            boolish = all(s.boolish for s in ins)
            if name == "not" and ins[0].mask is not None:
                a, dims, pol = ins[0].mask
                mask = (a, dims, not pol)
            elif name == "and":
                # False-at-pads survives an AND with anything
                for s in ins:
                    if s.mask is not None and s.mask[2]:
                        mask = s.mask
            elif name == "or":
                for s in ins:
                    if s.mask is not None and not s.mask[2]:
                        mask = s.mask
        elif name in _PASSTHROUGH and len(ins) == 1:
            s = ins[0]
            return MState(s.regions, s.escaped, s.mask, s.boolish,
                          s.nonfinite and _is_float(eqn.outvars[0]),
                          s.const)

        if name == "mul" and len(ins) == 2 and (
                ins[0].boolish != ins[1].boolish):
            m_side = ins[0] if ins[0].boolish else ins[1]
            if _is_float(eqn.outvars[0]):
                other = ins[1] if m_side is ins[0] else ins[0]
                if other.nonfinite and record:
                    self._finding(NAN_UNSAFE, (
                        f"multiplicative masking at {path or '<top>'}: a "
                        "0/1 mask multiplies a possibly-nonfinite float "
                        "(0 * inf = NaN poisons the masked slot) — use "
                        "jnp.where(mask, x, 0.0), which is exact for "
                        "every x"), eqn)
                if (m_side.mask is not None and m_side.mask[2]
                        and not other.nonfinite):
                    a, dims, _ = m_side.mask
                    regions = {(a, d, ZERO) for d in dims}
                    regions |= {(ax, d, c) for ax, d, c in other.regions
                                if (ax, d) not in {(a, d) for d in dims}}
                    return MState(frozenset(regions), escaped, None, False,
                                  False, None)

        # per-(axis, dim) class combination
        regions = set()
        all_dims = frozenset().union(*[s.region_dims() for s in ins])
        for axis, dim in all_dims:
            classes = [s.cls(axis, dim) for s in ins]
            if name == "and" and any(c == ZERO for c in classes):
                # False/0 pads absorb anything bitwise — garbage included
                # (`active & (binding_body >= 0)` stays False at pads)
                c = ZERO
            elif any(c == DIRTY for c in classes):
                c = DIRTY
            elif any(c in (SNEG, SPOS) for c in classes):
                # sentinel arithmetic is nonfinite garbage outside its
                # one sanctioned consumer (argmax/argmin)
                c = DIRTY if len(ins) > 1 else _worst(*classes)
            elif name == "mul" and any(
                    c == ZERO and not any(
                        s.nonfinite for s in ins) for c in classes):
                c = ZERO
            elif all(c == ZERO for c in classes) and (
                    name in _ZERO_PRESERVING):
                c = ZERO
            elif (len(ins) == 1 and classes[0] == ZERO
                    and name in _ZERO_PRESERVING):
                c = ZERO
            else:
                c = None
            if c is not None:
                regions.add((axis, dim, c))

        if all(s.const is not None for s in ins) and len(ins) <= 2:
            try:
                if name == "add":
                    const = ins[0].const + ins[1].const
                elif name == "sub":
                    const = ins[0].const - ins[1].const
                elif name == "mul":
                    const = ins[0].const * ins[1].const
                elif name == "neg":
                    const = -ins[0].const
            except (OverflowError, IndexError):
                const = None
        return MState(frozenset(regions), escaped, mask, boolish,
                      nonfinite, const)

    # -- select ------------------------------------------------------------
    def _select(self, eqn, ins, path, record):
        pred, cases = ins[0], ins[1:]
        escaped = pred.escaped
        if pred.mask is not None and len(cases) == 2:
            axis, dims, pol = pred.mask
            pad_branch = cases[0] if pol else cases[1]
            live_branch = cases[1] if pol else cases[0]
            regions = set()
            mask_dims = set(dims)
            for d in dims:
                if pad_branch.const == 0.0:
                    regions.add((axis, d, ZERO))
                elif pad_branch.const == float("-inf"):
                    regions.add((axis, d, SNEG))
                elif pad_branch.const == float("inf"):
                    regions.add((axis, d, SPOS))
                else:
                    c = pad_branch.cls(axis, d)
                    if c is not None:
                        regions.add((axis, d, c))
            for s in cases:
                for ax, d, c in s.regions:
                    if ax == axis and d in mask_dims:
                        continue          # overridden by the mask select
                    cj = _worst(*[x.cls(ax, d) for x in cases])
                    if cj is not None:
                        regions.add((ax, d, cj))
            out_boolish = all(s.boolish for s in cases)
            return MState(frozenset(regions),
                          escaped | live_branch.escaped,
                          live_branch.mask if out_boolish else None,
                          out_boolish, live_branch.nonfinite, None)
        # arbitrary-guard select: branches join; a DIRTY pred region means
        # the pads choose by garbage; nonfinite is laundered (the
        # where(r > 0, 1/r, 0) guard pattern — see module docstring)
        regions = set()
        all_dims = frozenset().union(*[s.region_dims() for s in ins])
        for axis, dim in all_dims:
            classes = [s.cls(axis, dim) for s in cases]
            if pred.cls(axis, dim) == DIRTY:
                c = DIRTY
            elif any(c == DIRTY for c in classes):
                c = DIRTY
            elif all(c == classes[0] for c in classes):
                c = classes[0]
            else:
                c = _worst(*classes) if all(
                    c is not None for c in classes) else None
            if c is not None:
                regions.add((axis, dim, c))
        return MState(
            frozenset(regions),
            escaped | frozenset().union(*[s.escaped for s in cases]),
            None, all(s.boolish for s in cases),
            all(s.nonfinite for s in cases),
            cases[0].const if all(
                s.const == cases[0].const for s in cases) else None)

    # -- structured control flow ------------------------------------------
    def _while(self, eqn, ins, path, record):
        p = eqn.params
        cn, bn = p["cond_nconsts"], p["body_nconsts"]
        bconsts = ins[cn:cn + bn]
        carry = list(ins[cn + bn:])
        for _ in range(64):            # lattice height bounds this far lower
            outs = self.run_closed(p["body_jaxpr"], bconsts + carry, path,
                                   False)
            new = [join(c, o) for c, o in zip(carry, outs)]
            if new == carry:
                break
            carry = new
        if record:
            self.run_closed(p["cond_jaxpr"], ins[:cn] + carry,
                            f"{path}/while.cond", True)
            self.run_closed(p["body_jaxpr"], bconsts + carry,
                            f"{path}/while.body", True)
        return carry

    def _cond(self, eqn, ins, path, record):
        pred, ops = ins[0], ins[1:]
        outs = None
        for i, b in enumerate(eqn.params["branches"]):
            b_outs = self.run_closed(b, ops, f"{path}/cond.br{i}", record)
            outs = (b_outs if outs is None
                    else [join(a, c) for a, c in zip(outs, b_outs)])
        if pred.escaped or any(c == DIRTY for _, _, c in pred.regions):
            extra = pred.escaped | frozenset(
                a for a, _, c in pred.regions if c == DIRTY)
            outs = [MState(o.regions, o.escaped | extra, o.mask, o.boolish,
                           o.nonfinite, o.const) for o in outs]
        return outs

    def _scan(self, eqn, ins, path, record):
        p = eqn.params
        nc, ncar = p["num_consts"], p["num_carry"]
        consts, carry = ins[:nc], list(ins[nc:nc + ncar])
        xs = [_shift_regions(s, -1) for s in ins[nc + ncar:]]
        for _ in range(64):
            outs = self.run_closed(p["jaxpr"], consts + carry + xs, path,
                                   False)
            new = [join(c, o) for c, o in zip(carry, outs[:ncar])]
            if new == carry:
                break
            carry = new
        outs = self.run_closed(p["jaxpr"], consts + carry + xs,
                               f"{path}/scan", record)
        ys = [_shift_regions(s, +1) for s in outs[ncar:]]
        return carry + ys


def _shift_regions(s, delta):
    """Scan unstacks xs along dim 0 (regions shift down) and restacks ys
    (regions shift up); a region ON the scanned dim itself degrades —
    the scan mixes its slices into the carry."""
    if not s.regions and s.mask is None:
        return s
    regions = set()
    dropped = set()
    for a, d, c in s.regions:
        nd = d + delta
        if nd < 0:
            if c != ZERO:
                dropped.add(a)
            continue
        regions.add((a, nd, c))
    # a claim lost on the scanned dim only escapes when NO sibling claim
    # for the axis survives: after a chunk-split ([N] -> [nb, block]) the
    # within-chunk region still covers every padded slot of the axis, so
    # the per-iteration slice stays attributable
    escaped = set(s.escaped) | {
        a for a in dropped if not any(ra == a for ra, _, _ in regions)}
    mask = s.mask
    if mask is not None:
        a, dims, pol = mask
        nd = tuple(d + delta for d in dims)
        mask = (a, nd, pol) if all(d >= 0 for d in nd) else None
    return MState(frozenset(regions), frozenset(escaped), mask, s.boolish,
                  s.nonfinite, s.const)


# ----------------------------------------------------- shape-aware transfers

def _remap(s, dim_map, escaped_extra=frozenset(), realign=None):
    """Rebuild a state's regions/mask through a dim mapping (None = dim
    dropped: DIRTY/sentinel escalates to escaped, ZERO is laundered; a
    tuple = the dim was split). On a split the claim lands on every split
    dim — a reduction over ANY of them mixes pad slots into live rows —
    unless ``realign(axis, dims)`` names the one dim that re-aligns with
    the axis's mask (the ``[nf*n, 3] -> [nf, n, 3]`` unflatten)."""
    regions = set()
    escaped = set(s.escaped) | set(escaped_extra)
    for a, d, c in s.regions:
        nd = dim_map(d)
        if nd is None:
            if c != ZERO:
                escaped.add(a)
        elif isinstance(nd, tuple):
            one = realign(a, nd) if realign is not None else None
            if one is not None:
                regions.add((a, one, c))
            else:
                regions.update((a, x, c) for x in nd)
        else:
            regions.add((a, nd, c))
    mask = s.mask
    if mask is not None:
        a, dims, pol = mask
        flat = []
        for d in dims:
            nd = dim_map(d)
            if nd is None:
                mask = None
                break
            if isinstance(nd, tuple):
                one = realign(a, nd) if realign is not None else None
                flat.extend((one,) if one is not None else nd)
            else:
                flat.append(nd)
        else:
            mask = (a, tuple(flat), pol)
    return MState(frozenset(regions), frozenset(escaped), mask, s.boolish,
                  s.nonfinite, s.const)


def _t_broadcast_in_dim(an, eqn, ins, vals, path, record):
    s = ins[0]
    bdims = eqn.params["broadcast_dimensions"]
    in_shape = _shape(eqn.invars[0])
    out_shape = eqn.params["shape"]

    def dim_map(d):
        nd = bdims[d]
        return nd if in_shape[d] == out_shape[nd] else None

    return _remap(s, dim_map)


def _t_reshape(an, eqn, ins, vals, path, record):
    s = ins[0]
    in_shape = _shape(eqn.invars[0])
    out_shape = tuple(eqn.params.get("new_sizes", _shape(eqn.outvars[0])))
    sizes = getattr(an, "axis_sizes", {})

    def realign(a, nd):
        # the major split dim re-acquires the mask's own indexing when its
        # size IS the mask length ([nf*n, 3] -> [nf, n, 3]): pad slots are
        # whole major blocks, the minor dims carry no pad structure
        m = sizes.get(a)
        return nd[0] if m is not None and out_shape[nd[0]] == m else None

    return _remap(s, lambda d: _dim_map_reshape(in_shape, out_shape, d),
                  realign=realign)


def _t_squeeze(an, eqn, ins, vals, path, record):
    dims = sorted(eqn.params["dimensions"])

    def dim_map(d):
        if d in dims:
            return None
        return d - sum(1 for x in dims if x < d)

    return _remap(ins[0], dim_map)


def _t_expand_dims(an, eqn, ins, vals, path, record):
    dims = sorted(eqn.params["dimensions"])

    def dim_map(d):
        nd = d
        for x in dims:
            if x <= nd:
                nd += 1
        return nd

    return _remap(ins[0], dim_map)


def _t_transpose(an, eqn, ins, vals, path, record):
    perm = tuple(eqn.params["permutation"])
    return _remap(ins[0], lambda d: perm.index(d))


def _t_slice_like(an, eqn, ins, vals, path, record):
    # a window keeps its dims; surviving pad slots keep their class
    # (DIRTY stays sound, surviving ZERO slots are still zero), and a
    # sliced mask still carries False exactly at its surviving pads
    s = ins[0]
    escaped = frozenset().union(*[x.escaped for x in ins])
    return MState(s.regions, escaped, s.mask, s.boolish, s.nonfinite,
                  s.const)


def _t_dynamic_update_slice(an, eqn, ins, vals, path, record):
    op, upd = ins[0], ins[1]
    escaped = frozenset().union(*[x.escaped for x in ins])
    regions = set()
    for axis, dim in op.region_dims() | upd.region_dims():
        ca, cb = op.cls(axis, dim), upd.cls(axis, dim)
        if DIRTY in (ca, cb):
            c = DIRTY
        elif ca == cb and ca is not None:
            c = ca
        else:
            c = None
        if c is not None:
            regions.add((axis, dim, c))
    return MState(frozenset(regions), escaped, None, False,
                  op.nonfinite or upd.nonfinite, None)


def _t_concatenate(an, eqn, ins, vals, path, record):
    escaped = frozenset().union(*[s.escaped for s in ins])
    regions = set()
    for axis, dim in frozenset().union(*[s.region_dims() for s in ins]):
        classes = [s.cls(axis, dim) for s in ins]
        if any(c == DIRTY for c in classes):
            c = DIRTY
        elif any(c in (SNEG, SPOS) for c in classes):
            c = _worst(*classes)
        elif all(c == ZERO for c in classes):
            c = ZERO
        else:
            c = None
        if c is not None:
            regions.add((axis, dim, c))
    return MState(frozenset(regions), escaped, None,
                  all(s.boolish for s in ins),
                  any(s.nonfinite for s in ins), None)


def _t_pad(an, eqn, ins, vals, path, record):
    s, fill = ins[0], ins[1]
    regions = set()
    for a, d, c in s.regions:
        if c == ZERO and fill.const not in (0.0, None):
            continue       # nonzero fill interleaves with the zero slots
        regions.add((a, d, c))
    return MState(frozenset(regions), s.escaped | fill.escaped, None,
                  s.boolish and fill.boolish,
                  s.nonfinite or fill.nonfinite, None)


_REDUCE_NEUTRAL = {
    "reduce_sum": (ZERO,),
    "reduce_or": (ZERO,),
    "reduce_max": (ZERO, SNEG),   # bool masks reduce via max on some paths
    "reduce_min": (SPOS,),
    "reduce_prod": (),
    "reduce_and": (),
    "reduce_xor": (),
}


def _t_reduce(an, eqn, ins, vals, path, record):
    name = eqn.primitive.name
    s = ins[0]
    axes = tuple(eqn.params.get("axes", ()))
    neutral = _REDUCE_NEUTRAL.get(name, ())
    if name == "reduce_max":
        # zero is neutral for max only over booleans (False pads)
        neutral = (ZERO, SNEG) if _is_bool(eqn.invars[0]) else (SNEG,)
    escaped = set(s.escaped)
    for a, d, c in s.regions:
        if d in axes and c not in neutral:
            if record:
                what = ("input-pad garbage" if c == DIRTY else
                        f"pad slots holding "
                        f"{'zeros' if c == ZERO else 'a ∓inf sentinel'}")
                an._finding(UNMASKED_REDUCTION, (
                    f"{name} at {path or '<top>'} reduces over padded dim "
                    f"{d} of mask axis '{a}' with {what}, which is not the "
                    "reduction's neutral element — mask to the neutral "
                    "value (jnp.where) before reducing, or the result "
                    "mixes padded slots into live physics"), eqn)
            escaped.add(a)

    def dim_map(d):
        if d in axes:
            return None
        return d - sum(1 for x in axes if x < d)

    kept = {(a, dim_map(d), c) for a, d, c in s.regions
            if d not in axes and dim_map(d) is not None}
    return MState(frozenset(kept), frozenset(escaped), None, False,
                  s.nonfinite, None)


def _t_argreduce(an, eqn, ins, vals, path, record):
    name = eqn.primitive.name
    s = ins[0]
    axes = tuple(eqn.params.get("axes", ()))
    # False IS the -inf of booleans: argmax over `flags & mask` cannot
    # name a padded slot, no explicit sentinel needed
    want = (SNEG, ZERO) if name == "argmax" and s.boolish else \
        (SNEG,) if name == "argmax" else (SPOS,)
    escaped = set(s.escaped)
    for a, d, c in s.regions:
        if d in axes and c not in want:
            if record:
                sentinel = "-inf" if name == "argmax" else "+inf"
                an._finding(UNSENTINELED_ARGREDUCE, (
                    f"{name} at {path or '<top>'} scans padded dim {d} of "
                    f"mask axis '{a}' without the {sentinel} sentinel "
                    f"(pad slots hold "
                    f"{'garbage' if c == DIRTY else 'zeros' if c == ZERO else 'the WRONG-SIGN sentinel'}): "
                    "the winning index can name a padded slot — "
                    f"jnp.where(mask, x, {sentinel}) first, so live "
                    "entries always win"), eqn)
            escaped.add(a)

    def dim_map(d):
        if d in axes:
            return None
        return d - sum(1 for x in axes if x < d)

    kept = {(a, dim_map(d), c) for a, d, c in s.regions
            if d not in axes and dim_map(d) is not None}
    return MState(frozenset(kept), frozenset(escaped), None, False, False,
                  None)


def _t_cumulative(an, eqn, ins, vals, path, record):
    name = eqn.primitive.name
    s = ins[0]
    axis = eqn.params.get("axis")
    escaped = set(s.escaped)
    regions = set()
    for a, d, c in s.regions:
        if d != axis:
            regions.add((a, d, c))
            continue
        if c == ZERO and name == "cumsum":
            continue       # zeros are transparent to a running sum
        if record:
            an._finding(UNMASKED_REDUCTION, (
                f"{name} at {path or '<top>'} prefix-scans padded dim {d} "
                f"of mask axis '{a}' whose pad slots are not the scan's "
                "neutral element: every position after a padded slot "
                "absorbs it"), eqn)
        escaped.add(a)
    return MState(frozenset(regions), frozenset(escaped), None, False,
                  s.nonfinite, None)


def _t_sort(an, eqn, ins, vals, path, record):
    dim = eqn.params.get("dimension", len(_shape(eqn.invars[0])) - 1)
    out = []
    escaped = set(frozenset().union(*[s.escaped for s in ins]))
    for s in ins:
        for a, d, c in s.regions:
            if d == dim and c != ZERO:
                if record:
                    an._finding(UNMASKED_REDUCTION, (
                        f"sort at {path or '<top>'} orders padded dim {d} "
                        f"of mask axis '{a}' with non-zero pad slots: "
                        "padded entries interleave with live ones"), eqn)
                escaped.add(a)
    for s in ins:
        regions = {(a, d, c) for a, d, c in s.regions if d != dim}
        out.append(MState(frozenset(regions), frozenset(escaped), None,
                          s.boolish, s.nonfinite, None))
    return out


def _t_dot_general(an, eqn, ins, vals, path, record):
    lhs, rhs = ins[0], ins[1]
    (lc, rc), (lb, rb) = eqn.params["dimension_numbers"]
    lhs_shape, rhs_shape = _shape(eqn.invars[0]), _shape(eqn.invars[1])
    escaped = set(lhs.escaped | rhs.escaped)
    axes_here = {a for a, _, _ in lhs.regions | rhs.regions}
    for axis in axes_here:
        for dl, dr in zip(lc, rc):
            cl, cr = lhs.cls(axis, dl), rhs.cls(axis, dr)
            if cl is None and cr is None:
                continue
            if cl == ZERO and cr == ZERO:
                continue   # 0 * 0 pads contribute exact zeros
            if ZERO in (cl, cr):
                other_side = lhs if cr == ZERO else rhs
                if other_side.nonfinite:
                    if record:
                        an._finding(NAN_UNSAFE, (
                            f"dot_general at {path or '<top>'} contracts "
                            f"padded dim of mask axis '{axis}' against a "
                            "zero-padded partner whose other side may be "
                            "nonfinite: 0 * inf = NaN re-poisons the "
                            "contraction"), eqn)
                    escaped.add(axis)
                continue   # zero pads contribute exact zeros
            # both sides carry live-or-dirty pad slots on the contraction
            if DIRTY in (cl, cr) or SNEG in (cl, cr) or SPOS in (cl, cr):
                if record:
                    an._finding(UNMASKED_REDUCTION, (
                        f"dot_general at {path or '<top>'} contracts over "
                        f"padded dim of mask axis '{axis}' with "
                        "non-zeroed pad slots on the "
                        f"{'lhs' if cl else 'rhs'}: padded garbage enters "
                        "every live row of the product — zero the padded "
                        "slots (jnp.where) on one side first"), eqn)
                escaped.add(axis)
    # out dims: batch..., lhs free..., rhs free...
    lhs_free = [d for d in range(len(lhs_shape))
                if d not in lc and d not in lb]
    rhs_free = [d for d in range(len(rhs_shape))
                if d not in rc and d not in rb]
    regions = set()
    for a, d, c in lhs.regions:
        if d in lb:
            cb = _worst(c, rhs.cls(a, rb[lb.index(d)]))
            regions.add((a, lb.index(d), cb))
        elif d in lhs_free:
            regions.add((a, len(lb) + lhs_free.index(d), c))
    for a, d, c in rhs.regions:
        if d in rb:
            if not any(x == a and dd == rb.index(d)
                       for x, dd, _ in regions):
                regions.add((a, rb.index(d), _worst(c, lhs.cls(
                    a, lb[rb.index(d)]))))
        elif d in rhs_free:
            regions.add((a, len(lb) + len(lhs_free) + rhs_free.index(d), c))
    return MState(frozenset(regions), frozenset(escaped), None, False,
                  lhs.nonfinite or rhs.nonfinite, None)


def _t_gather(an, eqn, ins, vals, path, record):
    op, idx = ins[0], ins[1]
    dn = eqn.params["dimension_numbers"]
    sizes = eqn.params["slice_sizes"]
    op_shape = _shape(eqn.invars[0])
    idx_shape = _shape(eqn.invars[1])
    out_rank = len(_shape(eqn.outvars[0]))
    collapsed = tuple(dn.collapsed_slice_dims)
    offset_dims = tuple(dn.offset_dims)
    ob = tuple(getattr(dn, "operand_batching_dims", ()) or ())
    ib = tuple(getattr(dn, "start_indices_batching_dims", ()) or ())
    # vmapped gather: operand batch dim i pairs with indices batch dim
    # ib[i]; output non-offset dims correspond, in order, to the indices'
    # non-index-vector dims (jax keeps the index vector trailing)
    batch_out = [d for d in range(out_rank) if d not in offset_dims]
    idx_dims = list(range(max(len(idx_shape) - 1, 0)))
    bmap = {}
    for obd, ibd in zip(ob, ib):
        if ibd in idx_dims and idx_dims.index(ibd) < len(batch_out):
            bmap[obd] = batch_out[idx_dims.index(ibd)]
    kept = [d for d in range(len(op_shape))
            if d not in collapsed and d not in ob]
    escaped = set(op.escaped | idx.escaped)
    regions = set()
    for a, d, c in op.regions:
        if d in bmap:
            # batch slices map 1:1 — the claim rides to the output batch dim
            regions.add((a, bmap[d], c))
        elif (d not in collapsed and d not in ob
                and sizes[d] == op_shape[d]
                and kept.index(d) < len(offset_dims)):
            regions.add((a, offset_dims[kept.index(d)], c))
        elif c != ZERO:
            # gathered window may or may not include pad slots: garbage
            # at unknown positions is an escape, zeros launder silently
            escaped.add(a)
    return MState(frozenset(regions), frozenset(escaped), None,
                  op.boolish, op.nonfinite, None)


def _t_scatter(an, eqn, ins, vals, path, record):
    op, idx, upd = ins[0], ins[1], ins[2]
    escaped = set(op.escaped | idx.escaped | upd.escaped)
    dn = eqn.params["dimension_numbers"]
    op_shape, upd_shape = _shape(eqn.invars[0]), _shape(eqn.invars[2])
    skipped = set(dn.inserted_window_dims) | set(
        getattr(dn, "operand_batching_dims", ()) or ())
    owindow = [d for d in range(len(op_shape)) if d not in skipped]
    # update window dim -> operand dim, when the window spans the FULL
    # operand dim: positions along it are then known 1:1 (the vmapped
    # `res.at[j].add(col)` case — updates [nf], operand [nf, m]) and the
    # update's claim lands on the operand dim instead of escaping
    full = {}
    for uw, od in zip(sorted(dn.update_window_dims), owindow):
        if uw < len(upd_shape) and od < len(op_shape) \
                and upd_shape[uw] == op_shape[od]:
            full[uw] = od
    simple = eqn.primitive.name in ("scatter", "scatter-add", "scatter_add")
    regions = {(a, d, c) for a, d, c in op.regions if c == DIRTY}
    for a, du, cu in upd.regions:
        od = full.get(du)
        if od is None:
            if cu != ZERO:
                escaped.add(a)     # garbage lands at unknown positions
            continue
        co = op.cls(a, od)
        if cu == DIRTY or co == DIRTY or {co, cu} == {SNEG, SPOS}:
            regions.add((a, od, DIRTY))
        elif cu == ZERO:
            if co == ZERO:
                regions.add((a, od, ZERO))
            # else: zero update into live-derived slots — claim drops
        elif simple:
            # replace/add of a sentinel: -inf + finite = -inf, claim holds
            regions.add((a, od, cu))
        else:
            regions.add((a, od, DIRTY))
    return MState(frozenset(regions), frozenset(escaped), None, False,
                  op.nonfinite or upd.nonfinite, None)


def _t_iota(an, eqn, ins, vals, path, record):
    return CLEAN_STATE


def _t_rev(an, eqn, ins, vals, path, record):
    # reversal permutes within each dim: pad positions move, classes hold
    s = ins[0]
    return MState(s.regions, s.escaped, s.mask, s.boolish, s.nonfinite,
                  s.const)


def _t_batched_solve(an, eqn, ins, vals, path, record):
    """lu / triangular_solve / cholesky / custom_linear_solve family:
    batch dims stay independent (pad batch entries are garbage-in
    garbage-out, live entries never read them), but nothing about the
    padded VALUES survives — a DIRTY batch slot stays DIRTY, everything
    else degrades to clean (a zero RHS only solves to zero when the
    operator is provably nonsingular, which this abstraction cannot
    see)."""
    mats = [s for s in ins]
    escaped = frozenset().union(*[s.escaped for s in ins])
    ndim = len(_shape(eqn.outvars[0]))
    solve_dims = {ndim - 1, ndim - 2}
    regions = set()
    for s in mats:
        for a, d, c in s.regions:
            if d in solve_dims:
                if c not in (ZERO,):
                    escaped = escaped | {a}
            elif c == DIRTY:
                regions.add((a, d, DIRTY))
    return MState(frozenset(regions), escaped, None, False, True, None)


_SHAPED = {
    "broadcast_in_dim": _t_broadcast_in_dim,
    "reshape": _t_reshape,
    "squeeze": _t_squeeze,
    "expand_dims": _t_expand_dims,
    "transpose": _t_transpose,
    "slice": _t_slice_like,
    "dynamic_slice": _t_slice_like,
    "dynamic_update_slice": _t_dynamic_update_slice,
    "concatenate": _t_concatenate,
    "pad": _t_pad,
    "reduce_sum": _t_reduce,
    "reduce_max": _t_reduce,
    "reduce_min": _t_reduce,
    "reduce_prod": _t_reduce,
    "reduce_and": _t_reduce,
    "reduce_or": _t_reduce,
    "reduce_xor": _t_reduce,
    "argmax": _t_argreduce,
    "argmin": _t_argreduce,
    "cumsum": _t_cumulative,
    "cumprod": _t_cumulative,
    "cummax": _t_cumulative,
    "cummin": _t_cumulative,
    "cumlogsumexp": _t_cumulative,
    "sort": _t_sort,
    "dot_general": _t_dot_general,
    "gather": _t_gather,
    "scatter": _t_scatter,
    "scatter-add": _t_scatter,
    "scatter_add": _t_scatter,
    "scatter-mul": _t_scatter,
    "scatter-min": _t_scatter,
    "scatter-max": _t_scatter,
    "rev": _t_rev,
    "iota": _t_iota,
    "lu": _t_batched_solve,
    "triangular_solve": _t_batched_solve,
    "cholesky": _t_batched_solve,
    "custom_linear_solve": _t_batched_solve,
    "lu_solve": _t_batched_solve,
}


# ----------------------------------------------------------------- entry API

def _seed_inputs(jaxpr, axes, in_paths):
    """[MState] per flat invar from the declared mask axes, plus any
    configuration findings (a declaration that names no input is itself
    drift)."""
    findings = []
    n = len(jaxpr.invars)
    paths = list(in_paths) if in_paths is not None else [str(i)
                                                         for i in range(n)]
    if len(paths) != n:
        findings.append(MaskFinding("mask-config", (
            "mask-config: input path table does not match the traced "
            f"program ({len(paths)} paths, {n} jaxpr inputs) — re-lower "
            "the program")))
        paths = [str(i) for i in range(n)]
    by_path = {p: i for i, p in enumerate(paths)}
    states = [CLEAN_STATE] * n
    axis_sizes = {}
    for ax in axes:
        mi = by_path.get(ax.mask)
        if mi is None:
            findings.append(MaskFinding("mask-config", (
                f"mask-config: axis '{ax.name}' declares mask input "
                f"'{ax.mask}' but the traced program has no such input "
                "path (check --dump-contract for the real paths)")))
            continue
        mvar = jaxpr.invars[mi]
        if not _is_bool(mvar):
            findings.append(MaskFinding("mask-config", (
                f"mask-config: axis '{ax.name}' mask input '{ax.mask}' "
                f"has dtype {getattr(mvar.aval, 'dtype', '?')} — a "
                "capacity mask must be boolean (True = live)")))
        mshape = _shape(mvar)
        k = len(mshape)
        if mshape:
            axis_sizes[ax.name] = mshape[0]
        states[mi] = MState(
            regions=frozenset((ax.name, d, ZERO) for d in range(k)),
            mask=(ax.name, tuple(range(k)), True), boolish=True)
        guarded = dict(ax.inputs)
        if ax.scope is not None:
            prefix = ax.scope + "."
            for p, i in by_path.items():
                if (p.startswith(prefix) or p == ax.scope) and p != ax.mask:
                    guarded.setdefault(p, ax.dim)
        matched = 0
        for p, dim in sorted(guarded.items()):
            i = by_path.get(p)
            if i is None:
                findings.append(MaskFinding("mask-config", (
                    f"mask-config: axis '{ax.name}' guards input '{p}' "
                    "but the traced program has no such input path")))
                continue
            shape = _shape(jaxpr.invars[i])
            if tuple(shape[dim:dim + k]) != tuple(mshape):
                if p in dict(ax.inputs):
                    findings.append(MaskFinding("mask-config", (
                        f"mask-config: axis '{ax.name}' guards input "
                        f"'{p}' at dim {dim}, but its shape {shape} does "
                        f"not carry the mask's shape {mshape} there")))
                continue       # scope prefix matches non-padded leaves too
            matched += 1
            prev = states[i]
            states[i] = MState(
                regions=prev.regions | frozenset(
                    (ax.name, dim + j, DIRTY) for j in range(k)),
                escaped=prev.escaped, mask=prev.mask, boolish=prev.boolish,
                nonfinite=prev.nonfinite, const=prev.const)
        if not matched and (ax.scope is not None or ax.inputs):
            findings.append(MaskFinding("mask-config", (
                f"mask-config: axis '{ax.name}' guards no input leaf "
                "(scope/inputs matched nothing with the mask's shape) — "
                "the declaration is dead")))
    return states, findings, axis_sizes


def classify(state: MState) -> str:
    """The output pad class of one flat output value."""
    if any(c in (DIRTY, SNEG, SPOS) for _, _, c in state.regions):
        return PAD_PASSTHROUGH
    if state.regions or state.mask is not None:
        return PAD_EXACT_ZERO
    return LIVE_ONLY


def analyze(closed_jaxpr, axes=(), in_paths=None, out_paths=None
            ) -> MaskReport:
    """Run the mask-flow analysis over one traced program.

    ``axes`` is the contract's `[[mask.axes]]` declaration (possibly
    empty: the program then has no padded capacity inputs, and only the
    declaration-free detectors — multiplicative masking of nonfinite
    values — can fire). ``in_paths``/``out_paths`` are the flat pytree
    path names from `registry.BuiltProgram` (positional fallback when
    absent, e.g. for Pallas kernel jaxprs).
    """
    a = _Analyzer()
    jaxpr = _sub_jaxpr(closed_jaxpr)
    in_states, findings, a.axis_sizes = _seed_inputs(jaxpr, axes, in_paths)
    for f in findings:
        a._findings[f.message] = f
    outs = a.run_jaxpr(jaxpr, in_states, "", True,
                       consts=getattr(closed_jaxpr, "consts", None))
    n = len(outs)
    paths = list(out_paths) if out_paths is not None and \
        len(out_paths) == n else [str(i) for i in range(n)]
    classes = []
    for p, s in zip(paths, outs):
        if s.escaped:
            a._finding(PAD_ESCAPE, (
                f"output '{p}' carries live entries contaminated by "
                f"padded slots of mask axis(es) "
                f"{sorted(s.escaped)} — garbage crossed into live "
                "physics with no interposed select-on-mask"))
        classes.append((p, classify(s)))
    return MaskReport(findings=list(a._findings.values()), classes=classes)
