"""skelly-audit CLI: `python -m skellysim_tpu.audit [--program NAME]`.

Exit status mirrors skelly-lint so CI gates on it directly: 0 when every
audited program is contract-clean, 1 when any unsuppressed finding remains,
2 on usage errors.

The auditor needs the same backend environment as the test suite — an
8-device virtual CPU platform (the SPMD programs lower on 2/4/8 sub-meshes)
with x64 enabled (the contracts pin f64 inventories) — and sets it up
itself before any jax-importing module loads.
"""

from __future__ import annotations

import argparse
import sys


def _bootstrap_backend():
    from ..utils.bootstrap import force_cpu_devices

    force_cpu_devices(8)
    import jax

    jax.config.update("jax_enable_x64", True)


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m skellysim_tpu.audit",
        description="Trace-time program auditor: lowered-jaxpr/StableHLO "
                    "contracts for collectives, dtype flow, host syncs, "
                    "donation, retrace budgets, and Pallas DMA safety "
                    "(see docs/audit.md).")
    parser.add_argument("--program", action="append", default=None,
                        metavar="NAME",
                        help="audit only this program (repeatable)")
    parser.add_argument("--check", action="append", default=None,
                        metavar="CHECK-ID",
                        help="run only this check (repeatable)")
    parser.add_argument("--list-checks", action="store_true",
                        help="print every check id with its summary and exit")
    parser.add_argument("--list-programs", action="store_true",
                        help="print every registered program and exit")
    parser.add_argument("--dump-contract", metavar="NAME",
                        help="print NAME's observed inventory as contract "
                             "TOML (the starting point for a deliberate "
                             "contract update) and exit")
    args = parser.parse_args(argv)

    from .checks import CHECKS

    if args.list_checks:
        width = max(len(c.id) for c in CHECKS)
        for c in CHECKS:
            print(f"{c.id:<{width}}  {c.summary}")
        return 0
    if args.check:
        known = {c.id for c in CHECKS}
        unknown = [c for c in args.check if c not in known]
        if unknown:
            print(f"skelly-audit: unknown check id(s): "
                  f"{', '.join(unknown)} (try --list-checks)",
                  file=sys.stderr)
            return 2

    _bootstrap_backend()
    from .engine import run_kernel_audit, run_program_audit
    from .kernels import all_kernels
    from .programs import all_programs

    progs = all_programs()
    kerns = all_kernels()
    if args.list_programs:
        width = max(len(p.name) for p in progs + kerns)
        for p in progs:
            print(f"{p.name:<{width}}  [{p.layer}] {p.summary}")
        for k in kerns:
            print(f"{k.name:<{width}}  [{k.layer}/kernel] {k.summary}")
        return 0

    if args.dump_contract:
        from .engine import dump_contract, dump_kernel_contract

        prog = next((p for p in progs if p.name == args.dump_contract),
                    None)
        if prog is not None:
            print(dump_contract(prog), end="")
            return 0
        kern = next((k for k in kerns if k.name == args.dump_contract),
                    None)
        if kern is not None:
            print(dump_kernel_contract(kern), end="")
            return 0
        print(f"skelly-audit: unknown program {args.dump_contract!r} "
              f"(try --list-programs)", file=sys.stderr)
        return 2

    if args.program:
        known = {p.name for p in progs} | {k.name for k in kerns}
        unknown = [n for n in args.program if n not in known]
        if unknown:
            print(f"skelly-audit: unknown program(s): "
                  f"{', '.join(unknown)} (try --list-programs)",
                  file=sys.stderr)
            return 2
        progs = [p for p in progs if p.name in set(args.program)]
        kerns = [k for k in kerns if k.name in set(args.program)]

    # --check filters route each matrix to the checks that cover it: a
    # `--check dma` run never pays a program lowering, and `--check mask`
    # covers BOTH matrices (programs and Pallas kernels)
    if args.check is not None:
        selected = set(args.check)
        if not selected & {c.id for c in CHECKS if c.over_programs}:
            progs = []
        if not selected & {c.id for c in CHECKS if c.over_kernels}:
            kerns = []

    findings = []
    for prog in progs:
        findings.extend(run_program_audit(prog, checks=args.check))
    for kern in kerns:
        findings.extend(run_kernel_audit(kern, checks=args.check))
    for f in findings:
        print(f.render())
    audited = len(progs) + len(kerns)
    if findings:
        print(f"skelly-audit: {len(findings)} finding(s) across "
              f"{audited} program(s). Fix the program, or record the "
              "deliberate change in its audit/contracts/<name>.toml "
              "(docs/audit.md).", file=sys.stderr)
        return 1
    print(f"skelly-audit: {audited} program(s) contract-clean.")
    return 0


if __name__ == "__main__":
    sys.exit(main())
