"""Aggregate the auditable-program matrix from every layer's seam."""

from __future__ import annotations


def all_programs():
    """Every registered `AuditProgram`, in layer order (system → solver →
    ops → parallel → ensemble). Import is lazy per layer: registration must
    not force the whole simulation stack (or a jax backend) into memory
    before the CLI decides what to build."""
    # import the module path directly: package __init__s re-export same-named
    # FUNCTIONS (`solver.gmres`), which would shadow `from ..solver import
    # gmres`-style module lookups
    from ..ensemble.runner import auditable_programs as ensemble_programs
    from ..ops.spectral import auditable_programs as spectral_programs
    from ..ops.treecode import auditable_programs as ops_programs
    from ..parallel.spmd import auditable_programs as parallel_programs
    from ..scenarios.di_device import auditable_programs as scenario_programs
    from ..solver.gmres import auditable_programs as solver_programs
    from ..system.system import auditable_programs as system_programs

    progs = []
    for layer in (system_programs, solver_programs, ops_programs,
                  spectral_programs, parallel_programs, ensemble_programs,
                  scenario_programs):
        progs.extend(layer())
    names = [p.name for p in progs]
    dupes = {n for n in names if names.count(n) > 1}
    if dupes:
        raise ValueError(f"duplicate auditable program name(s): "
                         f"{', '.join(sorted(dupes))}")
    return progs


def get_program(name: str):
    for p in all_programs():
        if p.name == name:
            return p
    raise KeyError(
        f"no auditable program named {name!r} "
        f"(registered: {', '.join(p.name for p in all_programs())})")
