"""skelly-audit: trace-time program auditor over lowered jaxprs/StableHLO.

`skellysim_tpu.lint` polices the Python *source*; this package audits what
the source actually *lowers to*. Every registered entry point (the
single-chip implicit step, the explicitly-sharded `step_spmd` on 2/4/8
device meshes, the vmapped ensemble step, the bare GMRES kernel) is traced
and lowered, and the resulting program is checked against a per-program
contract file (`audit/contracts/<name>.toml`):

* ``collective-contract`` — the StableHLO collective inventory (op kind,
  static count, operand/result element count and bytes) must match the
  contract exactly; any collective the contract does not name is a finding.
  This is the engine behind docs/parallel.md's collective table and the
  GSPMD guardrail (no all-gather bigger than the shell density).
* ``dtype-flow`` — `convert_element_type` promotion edges in the closed
  jaxpr (narrow float -> wider float, and weak-typed float promotions) that
  the AST linter cannot prove; the mixed-precision program pins its
  deliberate refinement merges, everything else pins zero.
* ``host-sync`` — `pure_callback` / `io_callback` / `debug_callback`
  primitives reachable from the jitted program (each one is a device->host
  round-trip per execution).
* ``donation`` — input->output buffer aliasing markers present (or absent)
  at lowering time, per contract.
* ``retrace-budget`` — `testing.trace_counting_jit` pins the compile count
  across same-structure calls of the entry point.
* ``replication`` — skelly-rep, the replication-flow analyzer
  (`audit.repflow`): abstract interpretation over each `shard_map` region
  statically proves the manual-SPMD programs cannot deadlock (no varying
  `while`/`cond` predicates, no collectives under divergence, replicated
  outputs provably replicated, no ppermute-order accumulation escaping to
  a replicated consumer) and pins the replicated-output surface.

CLI: ``python -m skellysim_tpu.audit [--list-checks] [--list-programs]
[--program NAME] [--dump-contract NAME]`` — exit 0 only when every program
is contract-clean (gated in ci/run_ci.sh after the lint tier). Deliberate
deviations are suppressed in the contract file with a mandatory reason
(``[[suppress]]``); unused suppressions are findings, mirroring
skelly-lint's pragma discipline. docs/audit.md has the full write-up.
"""

from .engine import Finding, load_contract, run_program_audit  # noqa: F401
from .registry import AuditProgram, BuiltProgram, built_from  # noqa: F401
