"""Replication-flow analysis: statically prove SPMD programs cannot deadlock.

The scariest invariant in the tree used to be prose: replicated values
inside a `shard_map` program must stay BITWISE identical across shards,
because ulp-level divergence in a replicated scalar desynchronizes the
solver's `lax.while_loop` convergence decisions — shards disagree on trip
counts, their collective schedules diverge, and the mesh hangs with no
error (the manual-SPMD analogue of a data race; cf. barrier-divergence
verification in GPUVerify-style tools and the reference's Belos/Tpetra
collective-consistency assumptions, SURVEY §2/§5.8). This module is the
machine check (docs/parallel.md "Replication discipline"): an abstract
interpreter over closed jaxprs that infers, for every intermediate value,
a replication state, and reports four finding kinds:

* ``divergent-control`` — a `while_loop` predicate (or a `cond`/`switch`
  predicate selecting between collective-bearing branches) that varies
  over a mesh axis: the deadlock itself.
* ``collective-under-divergence`` — a collective primitive reachable only
  under a varying predicate: shards run mismatched collective schedules.
* ``unreduced-replicated-output`` — a varying value flowing into a
  `shard_map` output position whose out_spec declares it replicated: the
  psum-of-partials discipline, checked instead of trusted.
* ``ring-order-accumulation`` — a `ppermute`-fed accumulation reaching a
  replicated output with no interposed psum: each shard added the same
  terms in a different ring order, so the "replicated" value differs at
  the ulp level (the documented anti-pattern, verbatim).

The lattice
-----------

A value's state is one of:

* ``replicated`` — bitwise identical on every shard (``Rep(axes=∅)``);
* ``varying over S`` — may differ across the mesh axes in ``S``, with a
  ``ring`` taint bit recording ppermute-fed provenance;
* ``mixed along axis a at boundary b`` — rows ``[0:b)`` of dimension
  ``a`` vary (head), rows ``[b:)`` are replicated (tail). This third
  element is what makes the real programs provable: the SPMD solution
  layout is ``[sharded fiber/shell rows | replicated body rows]``
  (`parallel.spmd._make_rdot`), and every Krylov vector, basis matrix,
  and residual carries that structure. Without it, ``rdot``'s replicated
  tail product would analyze as varying and every solver loop would
  false-positive as divergent.

Transfer rules: elementwise ops region-join; static slices split a mixed
value exactly at its boundary (this is how ``rdot`` analyzes as
replication-restoring: head → psum → replicated, tail → replicated ·
replicated); `psum`/`pmax`/`pmin`/`all_gather` remove the reduced axes
(and clear the ring taint — a cross-shard reduction is deterministic and
identical everywhere); `ppermute` makes its output varying AND
ring-tainted; `while`/`scan` run to a fixed point over their carries;
`pjit`/`cond`/`custom_*` recurse into their sub-jaxprs; anything unknown
degrades conservatively (never toward "replicated").

Soundness note: the analysis is conservative for the finding kinds above
— an unknown primitive joins its inputs and degrades mixed structure, so
"analyzes replicated" is a proof modulo the modeled primitive set, while
"analyzes varying" can be a false positive to refactor around (or, for a
deliberate site, suppress in the program's contract with a reason).

Import-light by design (no jax import): the interpreter walks jaxpr
objects duck-typed, so `--list-checks` and unit tests stay cheap.
"""

from __future__ import annotations

import os
from dataclasses import dataclass
from functools import reduce

#: finding kinds (the `replication` check's vocabulary; messages lead with
#: the kind so contract suppressions can match on it)
DIVERGENT_CONTROL = "divergent-control"
COLLECTIVE_UNDER_DIVERGENCE = "collective-under-divergence"
UNREDUCED_REPLICATED_OUTPUT = "unreduced-replicated-output"
RING_ORDER_ACCUMULATION = "ring-order-accumulation"

#: primitives that COMMUNICATE across a mesh axis (reachable-under-a-
#: varying-predicate = mismatched schedules across shards)
COMM_PRIMS = frozenset((
    "psum", "pmax", "pmin", "ppermute", "pshuffle", "all_gather",
    "all_gather_invariant", "all_to_all", "psum_scatter", "reduce_scatter",
    "pgather", "pbroadcast"))

#: communicating primitives whose OUTPUT is identical on every shard of the
#: reduced axis (replication-restoring: they also clear the ring taint)
_RESTORING = frozenset(("psum", "pmax", "pmin", "all_gather",
                        "all_gather_invariant", "pbroadcast"))

_DEBUG = os.environ.get("SKELLY_REPFLOW_DEBUG", "") not in ("", "0")


# --------------------------------------------------------------- the lattice

@dataclass(frozen=True)
class Rep:
    """Replication state of one value (see module docstring).

    Uniform: ``axis is None`` — varying over ``axes`` everywhere (empty =
    replicated). Mixed: rows ``[0:boundary)`` of dimension ``axis`` carry
    ``axes``/``ring``; the tail ``[boundary:)`` is replicated.
    """

    axes: frozenset
    ring: bool = False
    axis: int | None = None
    boundary: int | None = None

    @property
    def is_mixed(self) -> bool:
        return self.axis is not None

    def __repr__(self):  # compact for debug logs
        if self.is_mixed:
            return (f"mixed(ax{self.axis}<{self.boundary}:"
                    f"{set(self.axes) or '{}'}{'+ring' if self.ring else ''})")
        if not self.axes:
            return "replicated"
        return f"varying({set(self.axes)}{'+ring' if self.ring else ''})"


REPLICATED = Rep(frozenset())


def varying(axes, ring=False) -> Rep:
    axes = frozenset(axes)
    if not axes and not ring:
        return REPLICATED
    return Rep(axes, ring)


def mixed(axis, boundary, axes, ring=False, size=None) -> Rep:
    """Normalized mixed state: an empty head (or a head with nothing
    varying) collapses to replicated; a head covering the whole extent
    collapses to uniform varying."""
    axes = frozenset(axes)
    if (not axes and not ring) or boundary <= 0:
        return REPLICATED
    if size is not None and boundary >= size:
        return Rep(axes, ring)
    return Rep(axes, ring, axis, boundary)


def degrade(s: Rep) -> Rep:
    """Forget mixed structure (the tail is replicated, so the uniform
    over-approximation is just the head's state)."""
    if s.is_mixed:
        return varying(s.axes, s.ring)
    return s


def join(a: Rep, b: Rep) -> Rep:
    if a == b:
        return a
    if not a.is_mixed and not b.is_mixed:
        return varying(a.axes | b.axes, a.ring or b.ring)
    if a.is_mixed and b.is_mixed:
        if (a.axis, a.boundary) == (b.axis, b.boundary):
            return Rep(a.axes | b.axes, a.ring or b.ring, a.axis, a.boundary)
        da, db = degrade(a), degrade(b)
        return varying(da.axes | db.axes, da.ring or db.ring)
    m, u = (a, b) if a.is_mixed else (b, a)
    if not u.axes and not u.ring:   # replicated adds nothing anywhere
        return m
    dm = degrade(m)
    return varying(dm.axes | u.axes, dm.ring or u.ring)


def region_join(states) -> Rep:
    return reduce(join, states, REPLICATED)


def _degraded_union(states) -> Rep:
    return degrade(region_join([degrade(s) for s in states]))


# ------------------------------------------------------------------ findings

@dataclass(frozen=True)
class RepFinding:
    kind: str
    message: str


@dataclass(frozen=True)
class ShardRegion:
    """Summary of one analyzed `shard_map` region (the contract surface)."""

    path: str
    axes: tuple
    replicated_outputs: int   # out positions DECLARED replicated
    varying_outputs: int      # out positions declared varying (sharded)


@dataclass
class RepReport:
    findings: list            # [RepFinding], program order, deduped
    regions: list             # [ShardRegion]

    @property
    def mesh_axes(self):
        return sorted({a for r in self.regions for a in r.axes})


# ----------------------------------------------------------------- utilities

def _axis_set(v) -> frozenset:
    if v is None:
        return frozenset()
    if isinstance(v, (tuple, list, set, frozenset)):
        return frozenset(str(x) for x in v)
    return frozenset([str(v)])


def _eqn_axes(params) -> frozenset:
    return _axis_set(params.get("axes", params.get("axis_name")))


def _shape(atom):
    return tuple(getattr(atom.aval, "shape", ()))


def _is_literal(atom) -> bool:
    return type(atom).__name__ == "Literal"


def _sub_jaxpr(obj):
    """The raw Jaxpr inside a params value (ClosedJaxpr or Jaxpr)."""
    inner = getattr(obj, "jaxpr", None)
    if inner is not None and hasattr(inner, "eqns"):
        return inner
    if hasattr(obj, "eqns"):
        return obj
    return None


def _names_axes(names) -> frozenset:
    """Axis names mentioned in one shard_map in_names/out_names dict."""
    return frozenset(str(a) for dims in names.values() for a in dims)


def _int_value(x):
    """``x`` as an int or tuple-of-ints when it is a small static integer
    array/scalar, else None. Feeds the gather/dynamic_slice refinement:
    jnp lowers some static slices as `gather` with a CONSTANT index array
    (`broadcast_in_dim 0` → `gather slice_sizes=(8,)`), and without the
    index value the layout boundary would degrade conservatively."""
    try:
        import numpy as np
    except Exception:  # pragma: no cover - numpy is always present
        return None
    arr = np.asarray(x)
    if not np.issubdtype(arr.dtype, np.integer) or arr.size > 256:
        return None
    if arr.ndim == 0:
        return int(arr)
    return tuple(int(v) for v in arr.reshape(-1))


def _fold(eqn, in_vals):
    """Tiny integer constant propagation (index provenance only)."""
    name = eqn.primitive.name
    p = eqn.params
    if name == "broadcast_in_dim":
        v = in_vals[0]
        if isinstance(v, int):
            import math

            n = math.prod(p["shape"])
            if n <= 256:
                return (tuple([v] * n) if p["shape"] else v,)
        return (None,)
    if name == "iota" and len(p.get("shape", ())) == 1:
        n = p["shape"][0]
        if n <= 256:
            return (tuple(range(n)),)
        return (None,)
    if name in ("convert_element_type", "copy", "stop_gradient", "squeeze",
                "reshape"):
        return (in_vals[0],)
    if name == "concatenate":
        if all(v is not None for v in in_vals):
            out = []
            for v in in_vals:
                out.extend(v if isinstance(v, tuple) else (v,))
            return (tuple(out),)
        return (None,)
    if name in ("add", "sub", "mul") and all(
            isinstance(v, int) for v in in_vals):
        a, b = in_vals
        return ({"add": a + b, "sub": a - b, "mul": a * b}[name],)
    return (None,) * len(eqn.outvars)


def _contains_comm(jaxpr, cache) -> bool:
    """Any communicating primitive anywhere under ``jaxpr``. ``cache`` is
    per-analysis (an id()-keyed module global would go stale across
    analyses once earlier jaxprs are garbage-collected)."""
    hit = cache.get(id(jaxpr))
    if hit is not None:
        return hit
    from .checks import walk_eqns

    found = any(e.primitive.name in COMM_PRIMS for e in walk_eqns(jaxpr))
    cache[id(jaxpr)] = found
    return found


# --------------------------------------------------------------- interpreter

class _Analyzer:
    def __init__(self):
        self._findings = {}          # message -> RepFinding (ordered dedupe)
        self.regions = []
        self._cache = {}             # (id(jaxpr), states, guard) -> outs
        self._comm_cache = {}        # id(jaxpr) -> contains-collective

    # -- bookkeeping -------------------------------------------------------
    def _finding(self, kind, message):
        msg = f"{kind}: {message}"
        if msg not in self._findings:
            self._findings[msg] = RepFinding(kind, msg)

    @staticmethod
    def _read(env, atom):
        if _is_literal(atom):
            return REPLICATED
        if 0 in _shape(atom):
            # zero-element values carry no data, so they are EXACTLY
            # replicated — e.g. rdot's empty replicated-tail slice on a
            # state with no replicated rows contracts to deterministic
            # zeros, not a varying value
            return REPLICATED
        return env.get(atom, REPLICATED)

    @staticmethod
    def _read_val(vals, atom):
        if _is_literal(atom):
            return _int_value(atom.val)
        return vals.get(atom)

    # -- drivers -----------------------------------------------------------
    def run_jaxpr(self, jaxpr, in_states, path, guard, record, consts=None):
        if not record:
            key = (id(jaxpr), tuple(in_states), guard)
            hit = self._cache.get(key)
            if hit is not None:
                return hit
        env = {}
        vals = {}
        constvars = tuple(getattr(jaxpr, "constvars", ()))
        for i, v in enumerate(constvars):
            env[v] = REPLICATED
            if consts is not None and i < len(consts):
                cv = _int_value(consts[i])
                if cv is not None:
                    vals[v] = cv
        for v, s in zip(jaxpr.invars, in_states):
            env[v] = s
        for eqn in jaxpr.eqns:
            ins = [self._read(env, a) for a in eqn.invars]
            in_vals = [self._read_val(vals, a) for a in eqn.invars]
            outs = self._eqn(eqn, ins, in_vals, path, guard, record)
            for var, s in zip(eqn.outvars, outs):
                env[var] = s
            for var, v in zip(eqn.outvars, _fold(eqn, in_vals)):
                if v is not None:
                    vals[var] = v
        res = [self._read(env, a) for a in jaxpr.outvars]
        if not record:
            self._cache[key] = res
        return res

    def run_closed(self, closed, in_states, path, guard, record):
        return self.run_jaxpr(_sub_jaxpr(closed), in_states, path, guard,
                              record, consts=getattr(closed, "consts", None))

    # -- equation dispatch -------------------------------------------------
    def _eqn(self, eqn, ins, in_vals, path, guard, record):
        name = eqn.primitive.name
        n_out = len(eqn.outvars)

        if name == "axis_index":
            # shard-identity itself: varying over its axis by definition.
            # NOT in COMM_PRIMS — it reads a register, it does not
            # communicate, so it is legal under a varying predicate
            return [varying(_eqn_axes(eqn.params))]
        if name in COMM_PRIMS:
            if record and guard:
                kind, where = guard[-1]
                self._finding(COLLECTIVE_UNDER_DIVERGENCE, (
                    f"{name} at {path} executes under a VARYING {kind} "
                    f"predicate ({where}): shards take different trip/branch "
                    "counts, so their collective schedules mismatch and the "
                    "mesh deadlocks"))
            return self._collective(name, eqn, ins)

        if name == "shard_map":
            return self._shard_map(eqn, ins, path, guard, record)
        if name == "while":
            return self._while(eqn, ins, path, guard, record)
        if name == "cond":
            return self._cond(eqn, ins, path, guard, record)
        if name == "scan":
            return self._scan(eqn, ins, path, guard, record)
        if name == "pjit":
            sub = eqn.params.get("jaxpr")
            label = eqn.params.get("name", "")
            return self.run_closed(sub, ins, f"{path}/jit:{label}", guard,
                                   record)

        if name in _ELEMENTWISE:
            return [region_join(ins)] * n_out
        h = _SHAPED.get(name)
        if h is not None:
            return [h(eqn, ins, in_vals)] * n_out

        # generic call-like primitive: one sub-jaxpr whose invars match
        for key in ("call_jaxpr", "jaxpr", "fun_jaxpr"):
            obj = eqn.params.get(key)
            sub = _sub_jaxpr(obj) if obj is not None else None
            if sub is not None and len(sub.invars) == len(ins):
                return self.run_jaxpr(sub, ins, f"{path}/{name}", guard,
                                      record)

        if _DEBUG and any(s.is_mixed for s in ins):
            print(f"repflow: degrade via unmodeled `{name}` at {path}")
        return [_degraded_union(ins)] * n_out

    # -- collectives -------------------------------------------------------
    def _collective(self, name, eqn, ins):
        axes = _eqn_axes(eqn.params)
        # a grouped reduction (axis_index_groups) only equalizes WITHIN each
        # group — the result still differs across groups of the axis, so it
        # must not count as replication-restoring
        grouped = eqn.params.get("axis_index_groups") is not None
        out = []
        for s in ins:
            d = degrade(s)
            if name in _RESTORING and not grouped:
                left = d.axes - axes
                out.append(varying(left, d.ring if left else False))
            elif name in ("ppermute", "pshuffle"):
                out.append(varying(d.axes | axes, ring=True))
            elif name in ("psum_scatter", "reduce_scatter"):
                # reduced deterministically, but each shard keeps a
                # DIFFERENT chunk: varying, ring cleared
                out.append(varying(d.axes | axes))
            else:                      # all_to_all / pgather / unknown comm
                out.append(varying(d.axes | axes, d.ring))
        return out or [varying(axes)]

    # -- shard_map ---------------------------------------------------------
    def _shard_map(self, eqn, ins, path, guard, record):
        params = eqn.params
        mesh = params.get("mesh")
        axis_names = tuple(str(a) for a in getattr(mesh, "axis_names", ()))
        in_names = params.get("in_names", ())
        out_names = params.get("out_names", ())
        inner_in = [varying(_names_axes(n)) for n in in_names]
        spath = f"{path}/shard_map"
        outs = self.run_jaxpr(_sub_jaxpr(params["jaxpr"]), inner_in, spath,
                              guard, record)
        n_rep = n_var = 0
        for i, (names, s) in enumerate(zip(out_names, outs)):
            declared = _names_axes(names)
            if declared:
                n_var += 1
            else:
                n_rep += 1
            d = degrade(s)
            undeclared = d.axes - declared
            if undeclared and record:
                spec = ("replicated" if not declared
                        else f"varying only over {sorted(declared)}")
                if d.ring:
                    self._finding(RING_ORDER_ACCUMULATION, (
                        f"output #{i} of {spath} is declared {spec} but "
                        "receives a ppermute-fed accumulation with no "
                        "interposed psum: each shard sums the same terms in "
                        "a different ring order, so the value diverges at "
                        "the ulp level across shards — psum per-shard "
                        "partials onto replicated rows instead"))
                else:
                    self._finding(UNREDUCED_REPLICATED_OUTPUT, (
                        f"output #{i} of {spath} is declared {spec} but "
                        f"analyzes varying over {sorted(undeclared)} — a "
                        "shard-dependent value is about to be treated as "
                        "replicated; reduce it (psum/pmax) before the "
                        "shard_map boundary"))
        if record:
            self.regions.append(ShardRegion(
                path=spath, axes=axis_names, replicated_outputs=n_rep,
                varying_outputs=n_var))
        # outside the mesh the results are global arrays again
        return [REPLICATED] * len(eqn.outvars)

    # -- structured control flow ------------------------------------------
    def _while(self, eqn, ins, path, guard, record):
        p = eqn.params
        cn, bn = p["cond_nconsts"], p["body_nconsts"]
        cconsts, bconsts = ins[:cn], ins[cn:cn + bn]
        carry = list(ins[cn + bn:])
        for _ in range(64):            # lattice height bounds this far lower
            outs = self.run_closed(p["body_jaxpr"], bconsts + carry, path,
                                   guard, False)
            new = [join(c, o) for c, o in zip(carry, outs)]
            if new == carry:
                break
            carry = new
        pred = self.run_closed(p["cond_jaxpr"], cconsts + carry, path,
                               guard, False)[0]
        pd = degrade(pred)
        inner_guard = guard
        if pd.axes:
            inner_guard = guard + (("while_loop", f"{path}/while"),)
            if record:
                via = (" (through a ppermute ring chain)" if pd.ring else "")
                self._finding(DIVERGENT_CONTROL, (
                    f"while_loop predicate at {path}/while varies over mesh "
                    f"axis(es) {sorted(pd.axes)}{via}: shards disagree on "
                    "trip counts — the manual-SPMD deadlock (psum/pmax the "
                    "quantity the predicate reads)"))
        if record:
            self.run_closed(p["cond_jaxpr"], cconsts + carry,
                            f"{path}/while.cond", inner_guard, True)
            self.run_closed(p["body_jaxpr"], bconsts + carry,
                            f"{path}/while.body", inner_guard, True)
        return carry

    def _cond(self, eqn, ins, path, guard, record):
        branches = eqn.params["branches"]
        pred, ops = ins[0], ins[1:]
        pd = degrade(pred)
        comm = any(_contains_comm(_sub_jaxpr(b), self._comm_cache)
                   for b in branches)
        inner_guard = guard
        if pd.axes:
            inner_guard = guard + (("cond", f"{path}/cond"),)
            if comm and record:
                self._finding(DIVERGENT_CONTROL, (
                    f"cond/switch predicate at {path}/cond varies over mesh "
                    f"axis(es) {sorted(pd.axes)} and selects between "
                    "collective-bearing branches: shards take different "
                    "branches and their collective schedules diverge"))
        outs = None
        for i, b in enumerate(branches):
            b_outs = self.run_closed(b, ops, f"{path}/cond.br{i}",
                                     inner_guard, record)
            outs = (b_outs if outs is None
                    else [join(a, c) for a, c in zip(outs, b_outs)])
        if pd.axes or pd.ring:
            # outputs data-depend on a varying predicate
            outs = [join(o, varying(pd.axes, pd.ring)) for o in outs]
        return outs

    def _scan(self, eqn, ins, path, guard, record):
        p = eqn.params
        nc, ncar = p["num_consts"], p["num_carry"]
        consts, carry = ins[:nc], list(ins[nc:nc + ncar])
        xs = [self._scan_unstack(s) for s in ins[nc + ncar:]]
        for _ in range(64):
            outs = self.run_closed(p["jaxpr"], consts + carry + xs, path,
                                   guard, False)
            new = [join(c, o) for c, o in zip(carry, outs[:ncar])]
            if new == carry:
                break
            carry = new
        outs = self.run_closed(p["jaxpr"], consts + carry + xs,
                               f"{path}/scan", guard, record)
        ys = [self._scan_stack(s) for s in outs[ncar:]]
        return carry + ys

    @staticmethod
    def _scan_unstack(s):
        if not s.is_mixed:
            return s
        if s.axis == 0:
            return degrade(s)
        return Rep(s.axes, s.ring, s.axis - 1, s.boundary)

    @staticmethod
    def _scan_stack(s):
        if not s.is_mixed:
            return s
        return Rep(s.axes, s.ring, s.axis + 1, s.boundary)


# ----------------------------------------------------- shape-aware transfers

def _t_broadcast_in_dim(eqn, ins, vals):
    s = ins[0]
    if not s.is_mixed:
        return s
    bdims = eqn.params["broadcast_dimensions"]
    in_shape = _shape(eqn.invars[0])
    out_shape = eqn.params["shape"]
    new_axis = bdims[s.axis]
    if in_shape[s.axis] == out_shape[new_axis]:
        return Rep(s.axes, s.ring, new_axis, s.boundary)
    return degrade(s)   # the layout dim itself is being broadcast from 1


def _t_reshape(eqn, ins, vals):
    """Squeeze/unsqueeze of size-1 dims preserves the layout axis; real
    splits/merges degrade."""
    s = ins[0]
    if not s.is_mixed:
        return s
    in_shape = _shape(eqn.invars[0])
    out_shape = tuple(eqn.params.get("new_sizes",
                                     _shape(eqn.outvars[0])))
    in_real = [(i, d) for i, d in enumerate(in_shape) if d != 1]
    out_real = [(i, d) for i, d in enumerate(out_shape) if d != 1]
    if [d for _, d in in_real] != [d for _, d in out_real]:
        return degrade(s)
    if in_shape[s.axis] == 1:
        return degrade(s)   # a size-1 layout axis carries no real structure
    pos = [i for i, _ in in_real].index(s.axis)
    return Rep(s.axes, s.ring, out_real[pos][0], s.boundary)


def _t_squeeze(eqn, ins, vals):
    s = ins[0]
    if not s.is_mixed:
        return s
    dims = sorted(eqn.params["dimensions"])
    if s.axis in dims:
        return degrade(s)
    shift = sum(1 for d in dims if d < s.axis)
    return Rep(s.axes, s.ring, s.axis - shift, s.boundary)


def _t_transpose(eqn, ins, vals):
    s = ins[0]
    if not s.is_mixed:
        return s
    perm = tuple(eqn.params["permutation"])
    return Rep(s.axes, s.ring, perm.index(s.axis), s.boundary)


def _t_slice(eqn, ins, vals):
    s = ins[0]
    if not s.is_mixed:
        return s
    p = eqn.params
    start = p["start_indices"][s.axis]
    limit = p["limit_indices"][s.axis]
    strides = p.get("strides")
    stride = 1 if strides is None else strides[s.axis]
    if limit <= s.boundary:
        return varying(s.axes, s.ring)           # pure head
    if start >= s.boundary:
        return REPLICATED                        # pure tail
    if stride != 1:
        return degrade(s)
    return mixed(s.axis, s.boundary - start, s.axes, s.ring,
                 size=limit - start)


def _slice_window(s, start, size):
    """Uniform head/tail state of a contiguous window [start, start+size)
    along a mixed value's layout axis, or the narrowed mixed state."""
    if start + size <= s.boundary:
        return varying(s.axes, s.ring)           # pure head
    if start >= s.boundary:
        return REPLICATED                        # pure tail
    return mixed(s.axis, s.boundary - start, s.axes, s.ring, size=size)


def _t_dynamic_slice(eqn, ins, vals):
    n_idx = len(eqn.invars) - 1
    s, idx = ins[0], ins[1:1 + n_idx]
    idx_state = _degraded_union(idx) if idx else REPLICATED
    if not s.is_mixed:
        return join(degrade(s), idx_state)
    if idx_state.axes or idx_state.ring:
        return join(degrade(s), idx_state)       # shard-dependent offsets
    sizes = eqn.params["slice_sizes"]
    in_shape = _shape(eqn.invars[0])
    if sizes[s.axis] == in_shape[s.axis]:
        return s                                 # full extent on layout axis
    start = vals[1 + s.axis]
    if isinstance(start, int):                   # statically known offset
        start = max(0, min(start, in_shape[s.axis] - sizes[s.axis]))
        return _slice_window(s, start, sizes[s.axis])
    return degrade(s)


def _t_dynamic_update_slice(eqn, ins, vals):
    op, upd = ins[0], ins[1]
    idx_state = _degraded_union(ins[2:]) if len(ins) > 2 else REPLICATED
    if idx_state.axes or idx_state.ring:
        return join(join(degrade(op), degrade(upd)), idx_state)
    layout = op if op.is_mixed else (upd if upd.is_mixed else None)
    if layout is None:
        return join(degrade(op), degrade(upd))
    a = layout.axis
    op_shape = _shape(eqn.invars[0])
    upd_shape = _shape(eqn.invars[1])
    # preserve only when the update covers the FULL layout-axis extent (so
    # the head/tail split lines up) and both sides agree on the structure
    if (len(upd_shape) == len(op_shape)
            and upd_shape[a] == op_shape[a]
            and (not op.is_mixed or not upd.is_mixed
                 or (op.axis, op.boundary) == (upd.axis, upd.boundary))):
        target = op if op.is_mixed else Rep(upd.axes, upd.ring, a,
                                            upd.boundary)
        other = upd if op.is_mixed else op
        return join(target, other)
    return join(degrade(op), degrade(upd))


def _t_concatenate(eqn, ins, vals):
    dim = eqn.params["dimension"]
    shapes = [_shape(v) for v in eqn.invars]
    mixed_axes = {s.axis for s in ins if s.is_mixed}
    if mixed_axes and mixed_axes != {dim}:
        # concat along a NON-layout axis: rows keep their head/tail split
        a = next(iter(mixed_axes))
        if len(mixed_axes) == 1 and all(
                (not s.is_mixed) or s.axis == a for s in ins):
            bounds = {s.boundary for s in ins if s.is_mixed}
            if len(bounds) == 1 and all(
                    s.is_mixed or not (s.axes or s.ring) for s in ins):
                b = bounds.pop()
                head = varying(
                    frozenset().union(*[s.axes for s in ins]),
                    any(s.ring for s in ins))
                return mixed(a, b, head.axes, head.ring)
        return _degraded_union(ins)
    # concat ALONG the (potential) layout axis: build regions in order
    regions = []                   # [(size, uniform_state)]
    for s, shp in zip(ins, shapes):
        size = shp[dim]
        if s.is_mixed and s.axis == dim:
            regions.append((s.boundary, varying(s.axes, s.ring)))
            regions.append((size - s.boundary, REPLICATED))
        else:
            regions.append((size, degrade(s)))
    # collapse to the varying-head / replicated-tail pattern if possible
    boundary = 0
    head = REPLICATED
    seen_tail = False
    for size, st in regions:
        if size == 0:
            continue
        if st.axes or st.ring:
            if seen_tail:
                return _degraded_union(ins)   # interleaved: no clean split
            head = join(head, st)
            boundary += size
        else:
            seen_tail = True
    total = sum(size for size, _ in regions)
    return mixed(dim, boundary, head.axes, head.ring, size=total)


def _t_reduce(eqn, ins, vals):
    s = ins[0]
    axes = eqn.params.get("axes", ())
    if not s.is_mixed:
        return _degraded_union(ins)
    if s.axis in axes:
        return degrade(s)          # head and tail mix in the reduction
    shift = sum(1 for d in axes if d < s.axis)
    return Rep(s.axes, s.ring, s.axis - shift, s.boundary)


def _t_cumulative(eqn, ins, vals):
    s = ins[0]
    if s.is_mixed and eqn.params.get("axis") == s.axis:
        return degrade(s)          # prefix ops leak head into tail
    return region_join(ins)


def _t_dot_general(eqn, ins, vals):
    lhs, rhs = ins[0], ins[1]
    (lc, rc), (lb, rb) = eqn.params["dimension_numbers"]
    lhs_shape, rhs_shape = _shape(eqn.invars[0]), _shape(eqn.invars[1])
    if not lhs.is_mixed and not rhs.is_mixed:
        return _degraded_union(ins)
    if lhs.is_mixed and rhs.is_mixed:
        # both mixed is provable in ONE shape: the two layout axes are the
        # SAME batch axis (kernel einsums batch over the padded target rows
        # on both operands: `einsum("ts,tsk->tk", ...)`) — head rows combine
        # heads, tail rows combine replicated tails
        if (lhs.axis in lb and rhs.axis in rb
                and lb.index(lhs.axis) == rb.index(rhs.axis)
                and lhs.boundary == rhs.boundary):
            return Rep(lhs.axes | rhs.axes, lhs.ring or rhs.ring,
                       lb.index(lhs.axis), lhs.boundary)
        return _degraded_union(ins)
    m, other = (lhs, rhs) if lhs.is_mixed else (rhs, lhs)
    is_lhs = lhs.is_mixed
    contract = lc if is_lhs else rc
    batch = lb if is_lhs else rb
    if m.axis in contract:
        return _degraded_union(ins)        # head+tail mix in the contraction
    if other.axes or other.ring:
        return _degraded_union(ins)        # varying partner taints the tail
    # output dims: batch..., lhs free..., rhs free...
    if m.axis in batch:
        out_axis = batch.index(m.axis)
    else:
        lhs_free = [d for d in range(len(lhs_shape))
                    if d not in lc and d not in lb]
        rhs_free = [d for d in range(len(rhs_shape))
                    if d not in rc and d not in rb]
        if is_lhs:
            out_axis = len(lb) + lhs_free.index(m.axis)
        else:
            out_axis = len(lb) + len(lhs_free) + rhs_free.index(m.axis)
    return Rep(m.axes, m.ring, out_axis, m.boundary)


def _t_gather(eqn, ins, vals):
    op, idx = ins[0], ins[1]
    if not op.is_mixed:
        return join(degrade(op), degrade(idx))
    if idx.axes or idx.ring:
        return join(degrade(op), degrade(idx))
    dn = eqn.params["dimension_numbers"]
    sizes = eqn.params["slice_sizes"]
    op_shape = _shape(eqn.invars[0])
    a = op.axis
    collapsed = tuple(dn.collapsed_slice_dims)
    start_map = tuple(dn.start_index_map)
    full = sizes[a] == op_shape[a]
    start_a = 0 if (full or a not in start_map) else None
    if not full and a in start_map:
        # jnp lowers some STATIC slices as gather with a constant index
        # array; a single known index vector recovers the window exactly
        iv = vals[1]
        idx_shape = _shape(eqn.invars[1])
        n_idx = len(idx_shape) and idx_shape[-1] or 1
        if (isinstance(iv, tuple) and len(iv) == n_idx
                and n_idx == len(start_map)):
            start_a = max(0, min(iv[start_map.index(a)],
                                 op_shape[a] - sizes[a]))
    if start_a is None:
        return degrade(op)
    window = (_slice_window(op, start_a, sizes[a]) if not full else op)
    if not window.is_mixed:
        return window
    if a in collapsed:                 # a mixed window cannot collapse away
        return degrade(op)
    kept = [d for d in range(len(op_shape)) if d not in collapsed]
    out_axis = tuple(dn.offset_dims)[kept.index(a)]
    return Rep(window.axes, window.ring, out_axis, window.boundary)


def _t_scatter(eqn, ins, vals):
    op, idx, upd = ins[0], ins[1], ins[2]
    if idx.axes or idx.ring:
        return _degraded_union(ins)
    layout = op if op.is_mixed else (upd if upd.is_mixed else None)
    if layout is None:
        return join(degrade(op), degrade(upd))
    dn = eqn.params["dimension_numbers"]
    op_shape = _shape(eqn.invars[0])
    upd_shape = _shape(eqn.invars[2])
    inserted = tuple(dn.inserted_window_dims)
    scatter_dims = tuple(dn.scatter_dims_to_operand_dims)
    batching = tuple(getattr(dn, "operand_batching_dims", ()))
    if op.is_mixed:
        a = op.axis
        if a in inserted or a in scatter_dims or a in batching:
            return _degraded_union(ins)
        window_ops = [d for d in range(len(op_shape))
                      if d not in inserted and d not in batching]
        upd_axis = tuple(dn.update_window_dims)[window_ops.index(a)]
        if upd_shape[upd_axis] != op_shape[a]:
            return _degraded_union(ins)    # partial window on the layout axis
        if upd.is_mixed and (upd.axis, upd.boundary) != (upd_axis,
                                                         op.boundary):
            return _degraded_union(ins)
        other = upd if not upd.is_mixed else Rep(upd.axes, upd.ring, a,
                                                 upd.boundary)
        return join(op, other)
    # operand uniform (e.g. zeros), update mixed: map the update's layout
    # axis back to the operand axis it writes
    u_axis = upd.axis
    window_upd = tuple(dn.update_window_dims)
    if u_axis not in window_upd:
        return _degraded_union(ins)
    window_ops = [d for d in range(len(op_shape))
                  if d not in inserted and d not in batching]
    a = window_ops[window_upd.index(u_axis)]
    if upd_shape[u_axis] != op_shape[a]:
        return _degraded_union(ins)
    return join(Rep(upd.axes, upd.ring, a, upd.boundary), op)


def _t_triangular_solve(eqn, ins, vals):
    a, b = ins[0], ins[1]
    if not b.is_mixed or a.axes or a.ring or a.is_mixed:
        return _degraded_union(ins)
    ndim = len(_shape(eqn.invars[1]))
    contracted = ndim - 2 if eqn.params.get("left_side") else ndim - 1
    if b.axis == contracted:
        return _degraded_union(ins)
    return b


def _t_pad(eqn, ins, vals):
    s = ins[0]
    if not s.is_mixed:
        return _degraded_union(ins)
    lo, hi, interior = eqn.params["padding_config"][s.axis]
    # trailing padding with a replicated value lands AFTER the replicated
    # tail (kernel tile rounding pads targets this way): structure survives;
    # leading/interior padding would interleave with the head — degrade
    if lo == 0 and interior == 0 and not (ins[1].axes or ins[1].ring):
        return s
    return _degraded_union(ins)


def _t_rev(eqn, ins, vals):
    s = ins[0]
    if s.is_mixed and s.axis in eqn.params["dimensions"]:
        return degrade(s)
    return region_join(ins)


def _t_iota(eqn, ins, vals):
    return REPLICATED


_ELEMENTWISE = frozenset("""
add sub mul div rem max min pow integer_pow exp exp2 log log1p expm1 sqrt
rsqrt cbrt sign neg abs floor ceil round is_finite eq ne lt le gt ge and or
xor not select_n convert_element_type stop_gradient copy real imag conj erf
erfc erf_inv tanh sin cos tan asin acos atan atan2 sinh cosh asinh acosh
atanh logistic clamp nextafter square reduce_precision shift_left
shift_right_logical shift_right_arithmetic population_count clz device_put
select_and_scatter_add
""".split())

_SHAPED = {
    "broadcast_in_dim": _t_broadcast_in_dim,
    "reshape": _t_reshape,
    "squeeze": _t_squeeze,
    "expand_dims": lambda e, i, v: (degrade(i[0]) if i[0].is_mixed
                                    else region_join(i)),
    "transpose": _t_transpose,
    "slice": _t_slice,
    "dynamic_slice": _t_dynamic_slice,
    "dynamic_update_slice": _t_dynamic_update_slice,
    "concatenate": _t_concatenate,
    "reduce_sum": _t_reduce,
    "reduce_max": _t_reduce,
    "reduce_min": _t_reduce,
    "reduce_prod": _t_reduce,
    "reduce_and": _t_reduce,
    "reduce_or": _t_reduce,
    "argmax": _t_reduce,
    "argmin": _t_reduce,
    "cumsum": _t_cumulative,
    "cumprod": _t_cumulative,
    "cummax": _t_cumulative,
    "cummin": _t_cumulative,
    "cumlogsumexp": _t_cumulative,
    "dot_general": _t_dot_general,
    "gather": _t_gather,
    "scatter": _t_scatter,
    "scatter-add": _t_scatter,
    "scatter_add": _t_scatter,
    "scatter-mul": _t_scatter,
    "scatter-min": _t_scatter,
    "scatter-max": _t_scatter,
    "triangular_solve": _t_triangular_solve,
    "pad": _t_pad,
    "rev": _t_rev,
    "iota": _t_iota,
}


# ----------------------------------------------------------------- entry API

def analyze(closed_jaxpr) -> RepReport:
    """Run the replication-flow analysis over one traced program.

    ``closed_jaxpr`` is the `registry.BuiltProgram.closed_jaxpr` of a
    registered entry point (or any `jax.make_jaxpr`-style closed jaxpr).
    Outside any `shard_map` there are no mesh axes, so single-device
    programs report no regions and no findings by construction.
    """
    a = _Analyzer()
    jaxpr = _sub_jaxpr(closed_jaxpr)
    a.run_jaxpr(jaxpr, [REPLICATED] * len(jaxpr.invars), "", (), True)
    return RepReport(findings=list(a._findings.values()), regions=a.regions)
