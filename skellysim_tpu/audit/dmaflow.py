"""skelly-fence: static DMA-race / semaphore-protocol / VMEM-budget verifier.

The fused ring kernels (`parallel.ring_fused`) have never executed in CI —
CPU runs always fall back to the `lax.ppermute` ring, so their entire
safety argument (write-once comm slots, per-slot recv semaphores, paired
ENTRY/EXIT neighbor barriers) lived in comments. This module is the
repflow move applied to that gap: an abstract interpreter over the Pallas
kernel jaxpr that checks the argument instead of trusting it. Four
properties, each a finding kind:

* ``read-before-arrival`` — every load from a comm slot that receives a
  remote DMA must be program-ordered after a wait on that slot's recv
  semaphore. The kernel is SPMD-symmetric, so each *outgoing*
  ``dma_start`` (src slot a -> right neighbor's slot b, recv sem rb)
  mirrors an *incoming* write to MY slot b signalling MY rb; the analyzer
  builds that mirror and demands the wait.
* ``overwrite-in-flight`` — no slot is retargeted while its previous
  generation is still being read. Intra-instance this is program-order
  bookkeeping (a write to a slot with an un-waited outbound or inbound
  DMA). Cross-instance it is the barrier question: the analyzer extracts
  the kernel's barrier protocol (anonymous-credit signals/waits plus the
  first-send / last-read program points), and model-checks the ring by
  explicit-state search over every interleaving. A reachable state where
  a device starts its instance-(k+1) RDMA while its victim neighbor has
  not finished reading instance k IS the race, reported with the derived
  interleaving — this is how the module docstring's "a single entry
  barrier alone would NOT be safe" counterexample is *derived*, credit by
  anonymous credit, rather than asserted.
* ``semaphore-imbalance`` — per-instance credit balance on every
  semaphore slot. DMA sems: each start produces one send credit (locally)
  and one recv credit (on the mirrored receiver); each must be consumed by
  exactly one ``dma_wait``. Barrier sems: by symmetry a device receives
  one credit per signal op it executes, so total signalled inc must equal
  total waited value. Any residue is a hardware deadlock or a stale
  credit poisoning the next collective on the same ``collective_id``.
* ``vmem-budget`` — closed-form worst-case VMEM accounting in
  (n_dev, payload_rows, ns, nt) for the fused rings and (tile_t, tile_s)
  for the gridded kernels, gated against the budgets below. The budget
  constants here are the ONLY definition: `parallel.ring_fused
  .fused_ring_fits` (the build-time eligibility check behind
  `compat.fused_ring_mode`'s selection) delegates to
  `fused_ring_within_budget`, so the verifier and the builder cannot
  drift apart.

Like `audit.repflow`, this module is import-light (no jax): it walks
whatever jaxpr-shaped objects the registration seam
(`auditable_kernels()` in `parallel.ring_fused` / `ops.pallas_kernels`,
aggregated by `audit.kernels.all_kernels`) hands it, and decodes the
Pallas mosaic primitives (``dma_start``/``dma_wait``/``semaphore_signal``/
``semaphore_wait``/``get_barrier_semaphore``/``get``/``swap``) purely
through their params trees. Driven by the ``dma`` audit check
(`python -m skellysim_tpu.audit --check dma`, docs/audit.md).

Bounded-model scope: the barrier search runs on a ring of
``min(n_dev, _MODEL_RING)`` devices over ``_MODEL_INSTANCES`` back-to-back
kernel instances, all devices starting aligned. Four devices is the
smallest ring where anonymous-credit aliasing can manifest (the hazard
needs the victim, the racer, and a >=2-device fast chain on the racer's
far side for credits to arrive around the ring — on a 3-ring the victim
itself gates the chain), and skew growth, when a protocol fails to bound
it, compounds every instance, so it surfaces within the window. The
search also reports the maximum reachable neighbor phase skew, which the
contract pins.
"""

from __future__ import annotations

from collections import deque
from dataclasses import dataclass

# ------------------------------------------------------------------ budgets

#: cap on nt_padded * ns_padded for a whole-block pair tile resident in
#: VMEM: the pair intermediates are a handful of [nt, ns] f32 arrays, so
#: this bounds them at a few MB (the gridded tile sweep topped out at
#: 512x2048-class tiles; bigger compiles fail on VMEM).
VMEM_PAIR_BUDGET = 512 * 2048

#: cap on the n_dev-slot ring comm buffer (floats): 4 MB of f32 leaves the
#: pair tile its VMEM headroom on a v5-lite-class core.
VMEM_COMM_BUDGET = 1 << 20


def fused_ring_footprint(payload_rows: int, n_dev: int, nt: int,
                         ns: int) -> dict:
    """Closed-form worst-case VMEM terms (floats) of the fused ring kernel
    for padded shapes: the [nt, ns] pair-tile intermediates and the
    ``n_dev`` rotating comm slots of ``3 + payload_rows`` rows."""
    return {
        "pair_elems": nt * ns,
        "comm_floats": n_dev * (3 + payload_rows) * ns,
    }


def fused_ring_within_budget(payload_rows: int, n_dev: int, nt: int,
                             ns: int) -> bool:
    """THE fused-ring VMEM gate: consumed by `parallel.ring_fused
    .fused_ring_fits` at build time and by the ``dma`` audit check at
    verify time, from this one definition."""
    fp = fused_ring_footprint(payload_rows, n_dev, nt, ns)
    return (fp["pair_elems"] <= VMEM_PAIR_BUDGET
            and fp["comm_floats"] <= VMEM_COMM_BUDGET)


def gridded_footprint(tile_t: int, tile_s: int) -> dict:
    """VMEM terms of one gridded interaction tile (floats): the
    [tile_t, tile_s] pair intermediates dominate the block operands."""
    return {"pair_elems": tile_t * tile_s}


def gridded_within_budget(tile_t: int, tile_s: int) -> bool:
    return gridded_footprint(tile_t, tile_s)["pair_elems"] \
        <= VMEM_PAIR_BUDGET


# ------------------------------------------------- jaxpr walking / decoding

KIND_READ = "read-before-arrival"
KIND_OVERWRITE = "overwrite-in-flight"
KIND_BALANCE = "semaphore-imbalance"
KIND_VMEM = "vmem-budget"
KIND_STRUCT = "structure"


@dataclass(frozen=True)
class DmaFinding:
    kind: str
    message: str


@dataclass
class DmaReport:
    """``findings`` carry kind-prefixed messages (contract suppressions
    match on the kind); ``observed`` is the contract-shaped inventory the
    ``dma`` check compares and ``--dump-contract`` emits."""

    findings: list
    observed: dict


def _sub_jaxprs(params):
    for v in params.values():
        for item in (v if isinstance(v, (list, tuple)) else [v]):
            if hasattr(item, "jaxpr") and hasattr(item.jaxpr, "eqns"):
                yield item.jaxpr
            elif hasattr(item, "eqns"):
                yield item


def pallas_calls(jaxpr):
    """Every ``pallas_call`` equation under ``jaxpr`` (recursively), as
    (kernel_jaxpr, grid_mapping) pairs in program order."""
    out = []
    for eqn in jaxpr.eqns:
        if eqn.primitive.name == "pallas_call":
            out.append((eqn.params["jaxpr"], eqn.params["grid_mapping"]))
        for sub in _sub_jaxprs(eqn.params):
            out.extend(pallas_calls(sub))
    return out


def _as_int(x):
    """Static integer value of an index leaf: plain int (embedded in the
    NDIndexer treedef), jax Literal, or 0-d numpy scalar; None when the
    index is a traced Var (dynamic)."""
    if isinstance(x, bool):
        return None
    if isinstance(x, int):
        return x
    val = getattr(x, "val", None)    # jax Literal
    if val is not None:
        try:
            return int(val)
        except (TypeError, ValueError):
            return None
    return None


def _leading_slot(transforms):
    """The static leading slot index of a ref access: the first
    NDIndexer's first index when it is a static integer; None for a
    whole-ref / full-slice / dynamic access (conservatively: all slots)."""
    for t in transforms or ():
        indices = getattr(t, "indices", None)
        if indices is None:
            continue
        if not indices:
            return None
        first = indices[0]
        if hasattr(first, "start") and hasattr(first, "size"):
            return None              # a Slice: whole-range access
        return _as_int(first)
    return None


# decoded straight-line events (pos = program-order index)

@dataclass(frozen=True)
class _Read:
    pos: int
    ref: object
    slot: object          # int | None (whole/dynamic)


@dataclass(frozen=True)
class _Write:
    pos: int
    ref: object
    slot: object


@dataclass(frozen=True)
class _Start:
    pos: int
    src: object
    src_slot: object
    dst: object
    dst_slot: object
    send_sem: object
    send_slot: object
    recv_sem: object
    recv_slot: object
    offset: object        # ring offset of device_id, None = local copy


@dataclass(frozen=True)
class _DmaWait:
    pos: int
    sem: object
    slot: object


@dataclass(frozen=True)
class _Sig:
    pos: int
    sem: object
    inc: object
    offset: object        # neighbor ring offset, None = local signal


@dataclass(frozen=True)
class _SemWait:
    pos: int
    sem: object
    value: object


def _device_offset(var, defs, n_dev):
    """Ring offset (mod n_dev, folded into (-n_dev/2, n_dev/2]) of a
    device-id computed as arithmetic on ``axis_index``; None when the
    expression is not a recognizable my_id+const pattern."""
    def walk(v, depth=0):
        if depth > 16:
            return None
        lit = _as_int(v)
        if lit is not None:
            return lit               # constant term (no axis_index)
        eqn = defs.get(id(v))
        if eqn is None:
            return None
        name = eqn.primitive.name
        if name == "axis_index":
            return 0
        if name in ("convert_element_type", "squeeze", "broadcast_in_dim"):
            return walk(eqn.invars[0], depth + 1)
        if name in ("add", "sub"):
            a = walk(eqn.invars[0], depth + 1)
            b = walk(eqn.invars[1], depth + 1)
            if a is None or b is None:
                return None
            return a + b if name == "add" else a - b
        if name == "rem":
            a = walk(eqn.invars[0], depth + 1)
            m = _as_int(eqn.invars[1])
            if a is None or m is None or m == 0:
                return None
            return a % m
        return None
    off = walk(var)
    if off is None:
        return None
    off %= n_dev
    return off if off <= n_dev // 2 else off - n_dev


def _extract(kernel_jaxpr, n_dev):
    """Decode the kernel body into straight-line events.

    Returns (events, barrier_refs, control_flow_dma): Pallas mosaic
    primitives nested under sub-jaxprs (``pl.when`` / scan bodies) cannot
    be ordered against the straight line, so any DMA/semaphore op found
    there sets ``control_flow_dma`` (a structure finding) instead of
    silently mis-modelling it.
    """
    defs = {}

    def index_defs(jaxpr):
        for eqn in jaxpr.eqns:
            for ov in eqn.outvars:
                defs[id(ov)] = eqn
            for sub in _sub_jaxprs(eqn.params):
                index_defs(sub)

    index_defs(kernel_jaxpr)

    _DMA_PRIMS = ("dma_start", "dma_wait", "semaphore_signal",
                  "semaphore_wait", "get_barrier_semaphore")
    control_flow_dma = []

    def nested_dma(jaxpr):
        for eqn in jaxpr.eqns:
            if eqn.primitive.name in _DMA_PRIMS:
                control_flow_dma.append(eqn.primitive.name)
            for sub in _sub_jaxprs(eqn.params):
                nested_dma(sub)

    events = []
    barrier_refs = set()
    pos = 0
    for eqn in kernel_jaxpr.eqns:
        name = eqn.primitive.name
        for sub in _sub_jaxprs(eqn.params):
            nested_dma(sub)
        if name == "get":
            transforms = eqn.params["tree"].unflatten(list(eqn.invars[1:]))
            events.append(_Read(pos, eqn.invars[0],
                                _leading_slot(transforms)))
        elif name == "swap":
            transforms = eqn.params["tree"].unflatten(list(eqn.invars[2:]))
            events.append(_Write(pos, eqn.invars[0],
                                 _leading_slot(transforms)))
        elif name == "dma_start":
            (src, src_tr, dst, dst_tr, dst_sem, _dst_sem_tr2, src_sem,
             _src_sem_tr2, dev) = eqn.params["tree"].unflatten(
                 list(eqn.invars))
            events.append(_Start(
                pos, src, _leading_slot(src_tr), dst, _leading_slot(dst_tr),
                send_sem=src_sem, send_slot=_leading_slot(_src_sem_tr2),
                recv_sem=dst_sem, recv_slot=_leading_slot(_dst_sem_tr2),
                offset=(None if dev is None
                        else _device_offset(dev, defs, n_dev))))
        elif name == "dma_wait":
            # dma_wait waits the sem in its tree's dst_sem position (the
            # descriptor's wait_send binds with src/dst swapped, so the
            # send-completion wait lands here too)
            (_s, _st, _d, _dt, sem, sem_tr, _ss, _sst, _dev) = \
                eqn.params["tree"].unflatten(list(eqn.invars))
            events.append(_DmaWait(pos, sem, _leading_slot(sem_tr)))
        elif name == "semaphore_signal":
            sem, _tr, inc, dev, _core = eqn.params["args_tree"].unflatten(
                list(eqn.invars))
            events.append(_Sig(
                pos, sem, _as_int(inc),
                offset=(None if dev is None
                        else _device_offset(dev, defs, n_dev))))
        elif name == "semaphore_wait":
            sem, _tr, value = eqn.params["args_tree"].unflatten(
                list(eqn.invars))
            events.append(_SemWait(pos, sem, _as_int(value)))
        elif name == "get_barrier_semaphore":
            barrier_refs.add(id(eqn.outvars[0]))
        pos += 1
    return events, barrier_refs, control_flow_dma


# -------------------------------------------- anonymous-credit ring model

#: ring size of the bounded model (see module docstring: 4 is the smallest
#: ring where a fast far-side chain can launder anonymous credits past a
#: lagging victim) and the instance-unroll window.
_MODEL_RING = 4
_MODEL_INSTANCES = 4
_MODEL_STATE_CAP = 400_000

#: protocol-signature -> result memo: both ring kernel families reduce to
#: the same abstract protocol, so the search runs once per audit.
_model_memo = {}


def _check_ring_protocol(tokens, n, send_offset):
    """Explicit-state search over every interleaving of ``n`` symmetric
    devices each executing ``tokens`` for `_MODEL_INSTANCES` instances.

    ``tokens``: per-instance tuple of ('sigs', ((offset, inc), ...)) |
    ('wait', value) | ('send',) | ('read',). Signals are non-blocking, so
    adjacent runs arrive pre-merged (delivering more credits at once only
    enlarges the adversary's options — sound for hazard reachability).
    Credits are derived state: device d's balance is what its neighbors'
    program counters have signalled toward it minus what its own waits
    consumed, which keeps the searched state to the PC vector alone.

    Returns (hazard, max_skew, deadlock, truncated): ``hazard`` is the
    derived interleaving (a list of "d<k>:<token>@inst<j>" steps) reaching
    a state where some device executes its instance-j send while the
    victim neighbor has not finished its instance-(j-1) reads; ``max_skew``
    the maximum reachable adjacent instance skew; ``deadlock`` a reachable
    all-blocked state short of completion.
    """
    key = (tokens, n, send_offset, _MODEL_INSTANCES)
    if key in _model_memo:
        return _model_memo[key]
    T = len(tokens)
    total = T * _MODEL_INSTANCES
    read_idx = next((i for i, t in enumerate(tokens) if t[0] == "read"),
                    None)
    # per-PC cumulative credit tables: consumed by own waits, produced
    # toward each relative offset by own signal runs
    offsets = sorted({off for t in tokens if t[0] == "sigs"
                      for off, _ in t[1]})
    cum_wait = [0] * (total + 1)
    cum_sig = {off: [0] * (total + 1) for off in offsets}
    for p in range(total):
        tok = tokens[p % T]
        cum_wait[p + 1] = cum_wait[p] + (tok[1] if tok[0] == "wait" else 0)
        for off in offsets:
            cum_sig[off][p + 1] = cum_sig[off][p] + (
                sum(inc for o, inc in tok[1] if o == off)
                if tok[0] == "sigs" else 0)

    def credits(state, d):
        got = 0
        for off in offsets:
            got += cum_sig[off][state[(d - off) % n]]
        return got - cum_wait[state[d]]

    start = (0,) * n
    seen = {start}
    parent = {start: None}
    queue = deque([start])
    max_skew = 0
    hazard = None
    deadlock = None
    truncated = False
    while queue:
        state = queue.popleft()
        moved = False
        for d in range(n):
            pc = state[d]
            if pc >= total:
                continue
            tok = tokens[pc % T]
            if tok[0] == "wait" and credits(state, d) < tok[1]:
                continue
            inst = pc // T
            if tok[0] == "send" and inst >= 1 and read_idx is not None:
                victim = (d + send_offset) % n
                need = (inst - 1) * T + read_idx + 1
                if state[victim] < need:
                    steps = []
                    s = state
                    while parent[s] is not None:
                        s, (dd, ppc) = parent[s]
                        steps.append(f"d{dd}:{tokens[ppc % T][0]}"
                                     f"@inst{ppc // T}")
                    steps.reverse()
                    steps.append(f"d{d}:send@inst{inst} while d{victim} "
                                 f"has not finished inst{inst - 1} reads")
                    hazard = steps
                    queue.clear()
                    break
            moved = True
            nxt = state[:d] + (pc + 1,) + state[d + 1:]
            if nxt not in seen:
                seen.add(nxt)
                parent[nxt] = (state, (d, pc))
                queue.append(nxt)
                for a in range(n):
                    b = (a + 1) % n
                    skew = abs(min(nxt[a], total - 1) // T
                               - min(nxt[b], total - 1) // T)
                    if skew > max_skew:
                        max_skew = skew
        if hazard is not None:
            break
        if not moved and any(p < total for p in state):
            deadlock = state
        if len(seen) > _MODEL_STATE_CAP:
            truncated = True
            break
    result = (hazard, max_skew, deadlock, truncated)
    _model_memo[key] = result
    return result


def _abstract_protocol(events, barrier_sems, incoming):
    """Collapse the event stream to the barrier-model alphabet: signal
    runs and waits on the barrier-class semaphores, the first remote send,
    and the last read of a remotely-written slot."""
    remote_starts = [e for e in events
                     if isinstance(e, _Start) and e.offset is not None]
    reads = [e for e in events if isinstance(e, _Read)
             and id(e.ref) in {id(r) for (r, _s) in incoming}]
    if not remote_starts or not reads:
        return None, None
    send_pos = min(e.pos for e in remote_starts)
    read_pos = max(e.pos for e in reads)
    send_offset = remote_starts[0].offset
    raw = []
    for e in events:
        if isinstance(e, _Sig) and id(e.sem) in barrier_sems \
                and e.offset is not None:
            raw.append((e.pos, ("sig", (e.offset, e.inc or 0))))
        elif isinstance(e, _SemWait) and id(e.sem) in barrier_sems:
            raw.append((e.pos, ("wait", e.value or 0)))
    raw.append((send_pos, ("send",)))
    raw.append((read_pos, ("read",)))
    raw.sort(key=lambda t: t[0])
    tokens = []
    for _pos, tok in raw:
        if tok[0] == "sig":
            if tokens and tokens[-1][0] == "sigs":
                tokens[-1] = ("sigs", tokens[-1][1] + (tok[1],))
            else:
                tokens.append(("sigs", (tok[1],)))
        else:
            tokens.append(tok)
    return tuple(tokens), send_offset


# ----------------------------------------------------------------- analyze

def _aval_str(var):
    return str(getattr(var, "aval", ""))


def _ref_name(var, names):
    return names.get(id(var), "ref")


def analyze(built) -> DmaReport:
    """Verify one registered kernel artifact (`audit.registry.BuiltKernel`:
    ``kernel_jaxpr``, ``grid_mapping``, ``n_dev``, ``scene``)."""
    findings = []
    kj = built.kernel_jaxpr
    gm = built.grid_mapping
    n_dev = built.n_dev

    events, barrier_sems, cf_dma = _extract(kj, n_dev)
    if cf_dma:
        findings.append(DmaFinding(KIND_STRUCT, (
            f"{KIND_STRUCT}: {len(cf_dma)} DMA/semaphore op(s) "
            f"({', '.join(sorted(set(cf_dma)))}) under dynamic control "
            "flow — the straight-line happens-before model cannot order "
            "them; hoist them to the kernel's top level")))

    # name the kernel invars for messages: inputs / outputs / scratch
    names = {}
    invars = list(kj.invars)
    n_in = gm.num_inputs
    n_out = gm.num_outputs
    for i, v in enumerate(invars):
        if i < n_in:
            names[id(v)] = f"in{i}"
        elif i < n_in + n_out:
            names[id(v)] = f"out{i - n_in}"
        else:
            names[id(v)] = f"scratch{i - n_in - n_out}"

    starts = [e for e in events if isinstance(e, _Start)]
    for e in starts:
        if e.offset is None and _aval_str(e.src).find("semaphore") < 0 \
                and e.src is not e.dst:
            continue                  # plain local async copy: no mirror
    unresolved = [e for e in starts if e.offset is None
                  and any("dma_sem" in _aval_str(s)
                          for s in (e.send_sem, e.recv_sem))
                  and e.send_sem is not None and e.recv_sem is not None
                  and e.src is e.dst]
    # remote starts whose neighbor offset the walker could not fold
    for e in starts:
        if e.offset is None and e.send_sem is not None \
                and e.recv_sem is not None and e.src is e.dst:
            findings.append(DmaFinding(KIND_STRUCT, (
                f"{KIND_STRUCT}: dma_start at eqn {e.pos} has a device_id "
                "the analyzer cannot fold to an axis_index offset — the "
                "SPMD mirror (and every ordering proof built on it) is "
                "unavailable")))
    del unresolved

    remote_starts = [e for e in starts if e.offset is not None]

    # SPMD mirror: my incoming writes = my outgoing starts, slot for slot
    incoming = {}                     # (ref-id) -> {slot: start}
    for e in remote_starts:
        incoming.setdefault(id(e.dst), {})
        if e.dst_slot in incoming[id(e.dst)]:
            findings.append(DmaFinding(KIND_OVERWRITE, (
                f"{KIND_OVERWRITE}: comm slot "
                f"{_ref_name(e.dst, names)}[{e.dst_slot}] is the target of "
                "two remote DMA starts in one instance — anonymous "
                "arrivals to one slot cannot be ordered")))
        incoming[id(e.dst)][e.dst_slot] = e
    incoming_pairs = [(e.dst, s) for e in remote_starts
                      for s in [e.dst_slot]]

    # (1) read-before-arrival
    wait_positions = {}               # (sem-id, slot) -> [pos]
    for e in events:
        if isinstance(e, _DmaWait):
            wait_positions.setdefault((id(e.sem), e.slot), []).append(e.pos)
    for e in events:
        if not isinstance(e, _Read) or id(e.ref) not in incoming:
            continue
        slots = ([e.slot] if e.slot is not None
                 else sorted(incoming[id(e.ref)], key=str))
        for slot in slots:
            start = incoming[id(e.ref)].get(slot)
            if start is None:
                continue
            waits = wait_positions.get((id(start.recv_sem),
                                        start.recv_slot), [])
            if not any(w < e.pos for w in waits):
                findings.append(DmaFinding(KIND_READ, (
                    f"{KIND_READ}: load of comm slot "
                    f"{_ref_name(e.ref, names)}[{slot}] at eqn {e.pos} has "
                    "no preceding wait on its recv semaphore "
                    f"{_ref_name(start.recv_sem, names)}"
                    f"[{start.recv_slot}] — the remote write may still be "
                    "in flight when the load issues")))

    # (2a) overwrite-in-flight, intra-instance program order
    for st in starts:
        send_waits = wait_positions.get((id(st.send_sem), st.send_slot),
                                        []) if st.send_sem is not None \
            else []
        for e in events:
            if not isinstance(e, _Write) or id(e.ref) != id(st.src):
                continue
            if e.pos <= st.pos:
                continue
            if e.slot is not None and st.src_slot is not None \
                    and e.slot != st.src_slot:
                continue
            if not any(st.pos < w < e.pos for w in send_waits):
                findings.append(DmaFinding(KIND_OVERWRITE, (
                    f"{KIND_OVERWRITE}: write to "
                    f"{_ref_name(e.ref, names)}[{e.slot}] at eqn {e.pos} "
                    f"overwrites the source of the DMA started at eqn "
                    f"{st.pos} with no intervening send-semaphore wait")))
    for e in events:
        if not isinstance(e, _Write) or id(e.ref) not in incoming:
            continue
        slots = ([e.slot] if e.slot is not None
                 else sorted(incoming[id(e.ref)], key=str))
        for slot in slots:
            start = incoming[id(e.ref)].get(slot)
            if start is None:
                continue
            waits = wait_positions.get((id(start.recv_sem),
                                        start.recv_slot), [])
            if not any(w < e.pos for w in waits):
                findings.append(DmaFinding(KIND_OVERWRITE, (
                    f"{KIND_OVERWRITE}: local write to remotely-targeted "
                    f"slot {_ref_name(e.ref, names)}[{slot}] at eqn "
                    f"{e.pos} is unordered against the incoming DMA "
                    "(no preceding recv-semaphore wait)")))

    # (2b) cross-instance: the anonymous-credit barrier model
    skew_bound = None
    if remote_starts:
        tokens, send_offset = _abstract_protocol(events, barrier_sems,
                                                 incoming_pairs)
        if tokens is None:
            pass                      # sends with no reads: nothing at risk
        elif not any(t[0] == "wait" for t in tokens):
            findings.append(DmaFinding(KIND_OVERWRITE, (
                f"{KIND_OVERWRITE}: remote DMA with no barrier protocol "
                "at all — back-to-back kernel instances overwrite comm "
                "slots that neighbors may still be reading")))
        elif send_offset is None:
            findings.append(DmaFinding(KIND_STRUCT, (
                f"{KIND_STRUCT}: remote send target is not a foldable "
                "axis_index offset; cross-instance ordering unverifiable")))
        else:
            n_model = max(3, min(n_dev, _MODEL_RING))
            hazard, max_skew, deadlock, truncated = _check_ring_protocol(
                tokens, n_model, send_offset)
            if truncated:
                findings.append(DmaFinding(KIND_OVERWRITE, (
                    f"{KIND_OVERWRITE}: barrier model exceeded "
                    f"{_MODEL_STATE_CAP} states without a proof — treat "
                    "as unverified")))
            elif hazard is not None:
                tail = " -> ".join(hazard[-8:])
                findings.append(DmaFinding(KIND_OVERWRITE, (
                    f"{KIND_OVERWRITE}: barrier credits do not order "
                    "instance k+1 sends after instance k reads — derived "
                    f"interleaving on a {n_model}-ring "
                    f"({len(hazard)} steps): ... {tail}")))
            else:
                skew_bound = max_skew
                if deadlock is not None:
                    findings.append(DmaFinding(KIND_BALANCE, (
                        f"{KIND_BALANCE}: barrier protocol can wedge — "
                        f"reachable all-blocked state {deadlock} on a "
                        f"{n_model}-ring")))

    # (3) semaphore balance
    produced = {}
    for e in remote_starts:
        if e.send_sem is not None:
            produced[(id(e.send_sem), e.send_slot)] = produced.get(
                (id(e.send_sem), e.send_slot), 0) + 1
        produced[(id(e.recv_sem), e.recv_slot)] = produced.get(
            (id(e.recv_sem), e.recv_slot), 0) + 1
    consumed = {k: len(v) for k, v in wait_positions.items()}
    for key in sorted(set(produced) | set(consumed), key=str):
        p = produced.get(key, 0)
        c = consumed.get(key, 0)
        if p != c:
            sem_id, slot = key
            name = next((names[i] for i in names if i == sem_id), "sem")
            findings.append(DmaFinding(KIND_BALANCE, (
                f"{KIND_BALANCE}: DMA semaphore {name}[{slot}] earns {p} "
                f"credit(s) per instance but is waited {c} time(s) — "
                + ("the unconsumed credit poisons the next instance"
                   if p > c else "the extra wait deadlocks the kernel"))))
    bar_sig = sum((e.inc or 0) for e in events if isinstance(e, _Sig)
                  and id(e.sem) in barrier_sems and e.offset is not None)
    bar_wait = sum((e.value or 0) for e in events
                   if isinstance(e, _SemWait) and id(e.sem) in barrier_sems)
    if bar_sig != bar_wait:
        findings.append(DmaFinding(KIND_BALANCE, (
            f"{KIND_BALANCE}: barrier semaphore credits are unbalanced — "
            f"each instance signals {bar_sig} credit(s) ringwide but "
            f"waits for {bar_wait}"
            + (" (stale credits accumulate across instances and alias "
               "into later collectives on the same collective_id)"
               if bar_sig > bar_wait else " (hardware deadlock)"))))
    local_sig = [e for e in events if isinstance(e, _Sig)
                 and id(e.sem) not in barrier_sems]
    for e in local_sig:
        if not any("sem" in _aval_str(e.sem) for _ in (0,)):
            continue
        findings.append(DmaFinding(KIND_BALANCE, (
            f"{KIND_BALANCE}: semaphore_signal at eqn {e.pos} targets a "
            "non-barrier semaphore the DMA engine also signals — mixed "
            "producers make the credit ledger unverifiable")))

    # (4) VMEM accounting
    observed = {}
    scratch = invars[n_in + n_out:]
    comm_refs = [v for v in scratch if "dma_sem" not in _aval_str(v)
                 and "barrier" not in _aval_str(v)
                 and "sem" not in _aval_str(v)]
    dma_sem_slots = 0
    for v in scratch:
        if "dma_sem" in _aval_str(v):
            shape = getattr(getattr(v.aval, "inner_aval", v.aval),
                            "shape", ())
            n = 1
            for d in shape:
                n *= d
            dma_sem_slots += n
    if remote_starts:
        comm = comm_refs[0] if comm_refs else None
        if comm is None:
            findings.append(DmaFinding(KIND_STRUCT, (
                f"{KIND_STRUCT}: ring kernel has remote DMA but no VMEM "
                "comm scratch the analyzer can account")))
            return DmaReport(findings, observed)
        cshape = getattr(getattr(comm.aval, "inner_aval", comm.aval),
                         "shape", ())
        slots, rows, ns = (cshape + (0, 0, 0))[:3]
        out_bm = gm.block_mappings[n_in]
        nt = out_bm.block_shape[-1]
        payload_rows = rows - 3
        fp = fused_ring_footprint(payload_rows, n_dev, nt, ns)
        if slots != n_dev:
            findings.append(DmaFinding(KIND_STRUCT, (
                f"{KIND_STRUCT}: comm buffer has {slots} slot(s) for an "
                f"{n_dev}-device ring — the write-once slot discipline "
                "needs one slot per device")))
        if not fused_ring_within_budget(payload_rows, n_dev, nt, ns):
            findings.append(DmaFinding(KIND_VMEM, (
                f"{KIND_VMEM}: fused ring footprint over budget — "
                f"pair {fp['pair_elems']} elems "
                f"(budget {VMEM_PAIR_BUDGET}), comm {fp['comm_floats']} "
                f"floats (budget {VMEM_COMM_BUDGET}) for n_dev={n_dev}, "
                f"payload_rows={payload_rows}, nt={nt}, ns={ns}")))
        observed.update({
            "kernel": "fused-ring", "n_dev": n_dev, "comm_slots": slots,
            "remote_writes": len(remote_starts),
            "dma_sem_slots": dma_sem_slots,
            "barrier_signals": bar_sig, "barrier_waits": bar_wait,
            "pair_elems": fp["pair_elems"],
            "comm_floats": fp["comm_floats"],
        })
        if skew_bound is not None:
            observed["phase_skew_bound"] = skew_bound
    else:
        tile_t = gm.block_mappings[n_in].block_shape[-1]
        tile_s = max((bm.block_shape[-1]
                      for bm in gm.block_mappings[:n_in]), default=0)
        fp = gridded_footprint(tile_t, tile_s)
        if not gridded_within_budget(tile_t, tile_s):
            findings.append(DmaFinding(KIND_VMEM, (
                f"{KIND_VMEM}: gridded tile footprint over budget — "
                f"pair {fp['pair_elems']} elems (budget "
                f"{VMEM_PAIR_BUDGET}) for tile_t={tile_t}, "
                f"tile_s={tile_s}")))
        observed.update({
            "kernel": "gridded", "n_dev": n_dev, "comm_slots": 0,
            "remote_writes": 0, "dma_sem_slots": dma_sem_slots,
            "barrier_signals": bar_sig, "barrier_waits": bar_wait,
            "pair_elems": fp["pair_elems"],
        })
    observed["pair_budget"] = VMEM_PAIR_BUDGET
    if remote_starts:
        observed["comm_budget"] = VMEM_COMM_BUDGET

    # formula-vs-builder pin: the registered scene must agree with the
    # build-time eligibility check (one formula, consulted twice)
    scene = getattr(built, "scene", None) or {}
    if scene.get("kind") is not None and remote_starts:
        from ..parallel import ring_fused

        fits = ring_fused.fused_ring_fits(
            scene["kind"], scene["n_trg"], scene["n_src"], n_dev)
        verdict = fused_ring_within_budget(
            rows - 3, n_dev, nt, ns)
        if fits != verdict:
            findings.append(DmaFinding(KIND_VMEM, (
                f"{KIND_VMEM}: build-time fused_ring_fits says "
                f"{fits} but the traced-artifact accounting says "
                f"{verdict} — the eligibility check and the verifier "
                "have drifted apart")))
    # dedupe (whole-ref events can repeat a message per slot)
    seen = set()
    uniq = []
    for f in findings:
        if f.message not in seen:
            seen.add(f.message)
            uniq.append(f)
    return DmaReport(uniq, observed)
