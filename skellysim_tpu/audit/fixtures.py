"""Deterministic fixture scenes for the auditable-program matrix.

Small enough to trace in seconds, structurally complete enough that the
lowered programs exercise every contract surface: the free-fiber scene
drives the fiber-only paths (and the retrace probes, which must *run* the
program twice), the coupled scene (56-node shell, node-aligned on the 2/4/8
meshes, plus one forced body) drives the row-sharded shell operators whose
collectives the SPMD contracts pin. Mirrors `tests/test_spmd.py`'s scene so
the audit contracts and the sharded-parity tests describe the same program.
"""

from __future__ import annotations

import numpy as np

#: baseline parameter set shared by every audited entry point (adaptive gate
#: off: the audited program is the pure trial step, like the SPMD tests)
BASE_PARAMS = dict(eta=1.0, dt_initial=1e-3, t_final=1e-2, gmres_tol=1e-10,
                   adaptive_timestep_flag=False)

#: shell node count for the coupled scene — divides 2/4/8 node-aligned
SHELL_NODES = 56
BODY_NODES = 50


def make_fibers(n_fibers=16, n_nodes=16, seed=5, box=4.0, dtype=None):
    import jax.numpy as jnp

    from ..fibers import container as fc

    rng = np.random.default_rng(seed)
    t = np.linspace(0, 1, n_nodes)
    origins = rng.uniform(-box, box, size=(n_fibers, 3))
    dirs = rng.normal(size=(n_fibers, 3))
    dirs /= np.linalg.norm(dirs, axis=1, keepdims=True)
    x = origins[:, None, :] + t[None, :, None] * dirs[:, None, :]
    return fc.make_group(x, lengths=1.0, bending_rigidity=0.01,
                         radius=0.0125, dtype=dtype or jnp.float64)


def make_system(shell: bool = False, **param_overrides):
    """A `System` (optionally with the spherical periphery) on the audit's
    baseline parameters."""
    from ..params import Params
    from ..periphery.periphery import PeripheryShape
    from ..system import System

    shape = PeripheryShape(kind="sphere", radius=6.0) if shell else None
    return System(Params(**dict(BASE_PARAMS, **param_overrides)),
                  shell_shape=shape)


def free_state(system, seed=5):
    """16 free fibers in a uniform background flow (divides the 2/4/8
    meshes; same scene as tests/test_spmd.py's free variant)."""
    import jax.numpy as jnp

    from ..system import BackgroundFlow

    return system.make_state(
        fibers=make_fibers(seed=seed),
        background=BackgroundFlow.make(uniform=(1.0, 0.0, 0.0),
                                       dtype=jnp.float64))


def coupled_state(system, seed=7):
    """16 fibers + the 56-node shell + one externally forced body."""
    import jax.numpy as jnp

    from ..testing import make_coupled_parts

    shell, _, bodies = make_coupled_parts(SHELL_NODES, BODY_NODES,
                                          jnp.float64)
    return system.make_state(fibers=make_fibers(seed=seed, box=2.0),
                             shell=shell, bodies=bodies)
