"""Exit-code-gated DI-ensemble smoke for CI (docs/scenarios.md).

    python -m skellysim_tpu.scenarios.smoke

Boots a SMALL confined dynamic-instability sweep — confining periphery +
nucleating body + growing fibers, B=2 members on the ensemble vmap path —
deliberately undersized (2 fiber slots) so nucleation outgrows the first
capacity rung, and gates the skelly-scenario acceptance surface:

* both members finish their horizon with >= 1 nucleation applied;
* >= 1 growth reseat happened (lane froze, member re-admitted at the next
  geometric rung);
* ZERO warm-path compiles: every `observed_jit` compile event belongs to
  a rung's FIRST round — after a reseat warms its rung, within-bucket
  nucleation/catastrophe never retrace (compile events == rung count).

Exits 0 on success, 1 with a message on any violation (ci/run_ci.sh gates
on the exit code).
"""

from __future__ import annotations

import sys


def main() -> int:
    import os

    # pin CPU BEFORE anything initializes a backend (jax.devices() here
    # would initialize the default platform and make the pin a no-op);
    # an explicit JAX_PLATFORMS (e.g. tpu) is respected
    if not os.environ.get("JAX_PLATFORMS"):
        from ..utils.bootstrap import force_cpu_devices

        force_cpu_devices(1)
    import jax

    jax.config.update("jax_enable_x64", True)
    import numpy as np
    import jax.numpy as jnp

    from ..bodies import bodies as bd
    from ..obs import tracer as obs_tracer
    from ..params import DynamicInstability, Params
    from ..periphery import periphery as peri
    from ..periphery.precompute import precompute_body, precompute_periphery
    from ..scenarios import ScenarioEnsemble
    from ..ensemble.scheduler import MemberSpec
    from ..fibers import container as fc
    from ..system import System
    from ..utils.rng import SimRNG

    params = Params(
        eta=1.0, dt_initial=0.02, dt_write=0.02, t_final=0.08,
        gmres_tol=1e-8, adaptive_timestep_flag=False,
        dynamic_instability=DynamicInstability(
            n_nodes=8, v_growth=0.2, f_catastrophe=0.1,
            nucleation_rate=100.0, min_length=0.3, radius=0.0125,
            bending_rigidity=0.01))

    # confining sphere (60-node quadrature) + nucleating body with 2 sites
    pdata = precompute_periphery("sphere", n_nodes=60, radius=2.5, eta=1.0)
    shell = peri.make_state(pdata["nodes"], pdata["normals"],
                            pdata["quadrature_weights"],
                            pdata["stresslet_plus_complementary"],
                            pdata["M_inv"], dtype=jnp.float64)
    shape = peri.PeripheryShape(kind="sphere", radius=2.5)
    bdata = precompute_body("sphere", 40, radius=0.4)
    rng = np.random.default_rng(3)
    sites = rng.standard_normal((2, 3))
    sites = 0.4 * sites / np.linalg.norm(sites, axis=1, keepdims=True)
    bodies = bd.make_group(bdata["node_positions_ref"],
                           bdata["node_normals_ref"], bdata["node_weights"],
                           nucleation_sites_ref=sites[None], radius=0.4)
    system = System(params, shell_shape=shape)

    members = []
    for i in range(2):
        x = np.tile(np.linspace(0.0, 0.8, 8)[None, :, None], (2, 1, 3))
        x += 0.6 + 0.1 * i
        fibers = fc.make_group(x, lengths=0.8 * np.sqrt(3.0),
                               bending_rigidity=0.01, radius=0.0125)
        # 2 slots, both live: the first nucleation forces a growth reseat
        state = system.make_state(fibers=fibers, bodies=bodies, shell=shell)
        members.append(MemberSpec(member_id=f"m{i}", state=state,
                                  t_final=params.t_final,
                                  rng=SimRNG(17).member(i)))

    tracer = obs_tracer.Tracer(None)
    records: list = []
    with obs_tracer.use(tracer):
        se = ScenarioEnsemble(system, members, batch=2,
                              metrics=records.append)
        finished = se.run(max_rounds=60)

    steps = [r for r in records if r.get("event") == "step"]
    nucleations = sum(r["nucleations"] for r in steps)
    compiles = [e for e in tracer.events if e.get("ev") == "compile"
                and e.get("name") == "ensemble_step"]
    rungs = sorted(se._scheds)

    problems = []
    if sorted(finished) != ["m0", "m1"]:
        problems.append(f"members did not finish: {finished}")
    if nucleations < 1:
        problems.append("no nucleation was applied")
    if se.reseats < 1:
        problems.append("no growth reseat happened (capacity never filled)")
    if len(compiles) != len(rungs):
        problems.append(
            f"{len(compiles)} compile events for {len(rungs)} capacity "
            f"rungs {rungs} — a warm rung retraced (the zero-compiles-"
            "after-reseat gate)")
    if problems:
        for p in problems:
            print(f"scenario smoke FAILED: {p}", file=sys.stderr)
        return 1
    print(f"scenario smoke ok: 2 confined DI members finished, "
          f"{nucleations} nucleation(s), {se.reseats} growth reseat(s) "
          f"across rungs {rungs}, {len(compiles)} compiles "
          f"(one per rung, zero warm-path)")
    return 0


if __name__ == "__main__":
    sys.exit(main())
