"""Scenario front-end: DI ensembles across a ladder of capacity rungs.

The device DI engine (`scenarios.di_device`) runs nucleation/catastrophe
as mask flips inside ONE compiled batched step — but a fixed-capacity
trace cannot grow. This module owns the host half of that contract: every
member runs at a geometric capacity rung (`system.buckets.
next_fiber_capacity`, the same rungs skelly-serve admission uses), one
`EnsembleScheduler` per rung shares ONE `EnsembleRunner` (so a rung's
program is one `observed_jit` trace, warm via the persistent compile
cache), and when a member's bucket fills (``EnsembleStepInfo.
needs_growth``) the scheduler hands it back and `ScenarioEnsemble`
reseats it onto the next rung: `fibers.container.grow_capacity` host-side
(mask flips in-trace, geometric re-bucketing outside — O(log n) traces
total over a sweep's whole life).

The member's frozen round re-runs at the new rung with its RNG counter
untouched, so a reseat costs one batched round plus (at most once per
rung, ever) one trace.
"""

from __future__ import annotations

import logging
from typing import Callable, Optional

import numpy as np
import jax.numpy as jnp

from ..ensemble.runner import EnsembleRunner
from ..ensemble.scheduler import EnsembleScheduler, MemberSpec
from ..fibers import container as fc
from ..obs import tracer as obs_tracer
from ..system import buckets as bucket_mod, di_rates, dynamic_instability
from .di_device import check_di_state

logger = logging.getLogger("skellysim_tpu")


def ensure_di_capacity(state, params, capacity: Optional[int] = None,
                       node_multiple: int = 1,
                       policy: Optional[bucket_mod.BucketPolicy] = None):
    """State padded onto a DI-runnable capacity rung.

    Dynamic instability under the batched paths needs a single
    fixed-capacity `FiberGroup` whose live resolution matches
    ``dynamic_instability.n_nodes``. Fiber-less scenes (nucleation from
    scratch — the host loop creates the group lazily on first nucleation)
    get an all-inactive placeholder group seeded from the first body
    nucleation site's geometry, so the batch has valid (finite-cache)
    coordinates before anything nucleates. ``capacity`` overrides the
    rung; the default is the smallest geometric rung holding the scene.
    """
    di = params.dynamic_instability
    if di.n_nodes == 0:
        return state
    fibers = state.fibers
    if fibers is not None and not isinstance(fibers, fc.FiberGroup):
        raise ValueError(
            "device dynamic instability supports a single fiber resolution "
            "bucket; mixed-resolution tuples run the host loop")
    if fibers is None:
        tab = _host_sites(state.bodies)
        if tab is None:
            raise ValueError(
                "cannot pre-allocate DI capacity: the scene has no fibers "
                "and no body nucleation sites to seed a placeholder from")
        origin, com = tab[0]
        x = di_rates.nucleated_nodes(origin, com, di.min_length,
                                     di.n_nodes, np)
        dtype = state.time.dtype
        group = fc.make_group(x[None], lengths=di.min_length,
                              bending_rigidity=di.bending_rigidity,
                              radius=di.radius, minus_clamped=True,
                              dtype=dtype)
        # the placeholder slot is INERT capacity, not a fiber: inactive and
        # unbound, it weighs zero in every flow and solves the identity
        group = group._replace(
            active=jnp.zeros(1, dtype=jnp.bool_),
            config_rank=jnp.full((1,), -1, dtype=jnp.int32))
        fibers = group
    cap = (capacity if capacity is not None
           else bucket_mod.next_fiber_capacity(fibers.n_fibers, policy))
    state = state._replace(
        fibers=fc.grow_capacity(fibers, cap, node_multiple=node_multiple))
    check_di_state(state, params)
    return state


def _host_sites(bodies):
    """[(origin, com)] nucleation sites host-side (the ONE flat table order
    of `dynamic_instability.host_site_table`), or None when no body carries
    sites."""
    tab = dynamic_instability.host_site_table(bodies)
    return [(origin, com) for _, _, origin, com in tab] or None


class ScenarioEnsemble:
    """Drain DI members through per-rung schedulers sharing one runner.

    The composition layer ROADMAP item 5 asks for: `members` (MemberSpec
    iterable — each member MUST carry a per-member `SimRNG`) are padded
    onto their geometric capacity rung and drained through one
    `EnsembleScheduler` per rung, all rungs sharing one `EnsembleRunner`
    (one `observed_jit` program; a rung's first member pays its one
    trace, every later member and every reseat into it is warm).

    Growth reseats are transparent: a member whose bucket fills freezes,
    retires with reason ``"growth"``, is re-padded onto the next rung and
    re-admitted under the same id with its synced RNG — its trajectory
    stream continues seamlessly (``writer`` sees one monotone frame
    sequence). ``on_retire`` fires for terminal retirements only.
    """

    def __init__(self, system, members, batch: int, *,
                 batch_impl: str = "vmap",
                 policy: Optional[bucket_mod.BucketPolicy] = None,
                 writer: Optional[Callable] = None,
                 metrics: Optional[Callable] = None,
                 step_fn: Optional[Callable] = None,
                 write_initial_frames: bool = False,
                 on_dt_underflow: str = "retire",
                 on_failure: str = "retire",
                 on_retire: Optional[Callable] = None,
                 node_multiple: int = 1,
                 runner: Optional[EnsembleRunner] = None):
        if not system.params.dynamic_instability.n_nodes:
            raise ValueError(
                "ScenarioEnsemble drives dynamic-instability sweeps; for "
                "deterministic members use ensemble.EnsembleScheduler")
        self.system = system
        self.runner = runner or EnsembleRunner(system, batch_impl=batch_impl)
        self.batch = batch
        self.policy = policy
        self.node_multiple = node_multiple
        self.writer = writer
        self.metrics = metrics
        self.step_fn = step_fn
        self.write_initial_frames = write_initial_frames
        self.on_dt_underflow = on_dt_underflow
        self.on_failure = on_failure
        self.user_on_retire = on_retire
        self._scheds: dict[int, EnsembleScheduler] = {}
        self._specs: dict[str, MemberSpec] = {}
        self.finished: list[str] = []
        self.reseats = 0
        self.rounds = 0
        for spec in members:
            self.admit(spec)

    # ------------------------------------------------------------ admission

    def _sched_for(self, capacity: int, template) -> EnsembleScheduler:
        sched = self._scheds.get(capacity)
        if sched is None:
            sched = EnsembleScheduler(
                self.runner, [], self.batch, template=template,
                writer=self.writer, metrics=self.metrics,
                step_fn=self.step_fn,
                write_initial_frames=False,
                on_dt_underflow=self.on_dt_underflow,
                on_failure=self.on_failure,
                on_growth="retire", on_retire=self._on_retire)
            self._scheds[capacity] = sched
            logger.info("scenario: capacity rung %d opened (%d rung(s))",
                        capacity, len(self._scheds))
        return sched

    def admit(self, spec: MemberSpec):
        """Pad ``spec`` onto its capacity rung and seat/queue it."""
        if spec.rng is None:
            raise ValueError(
                f"member {spec.member_id}: scenario members need a "
                "per-member SimRNG (SimRNG(seed).member(i))")
        state = ensure_di_capacity(spec.state, self.system.params,
                                   node_multiple=self.node_multiple,
                                   policy=self.policy)
        cap = state.fibers.n_fibers
        spec = MemberSpec(member_id=spec.member_id, state=state,
                          t_final=spec.t_final, rng=spec.rng,
                          enqueued_at=spec.enqueued_at)
        self._specs[spec.member_id] = spec
        if self.write_initial_frames and self.writer is not None:
            self.writer(spec.member_id, state,
                        rng_state=spec.rng.dump_state())
        return self._sched_for(cap, state).admit(spec)

    # --------------------------------------------------------------- drain

    def _on_retire(self, member_id: str, state, reason: str,
                   rng_state=None, **extra):
        spec = self._specs.get(member_id)
        if reason == "growth":
            # reseat onto the next geometric rung: the member's CURRENT
            # state (frozen un-advanced) grows masked inert slots and
            # re-admits under the same id — the scheduler already synced
            # its SimRNG counter, so the re-run draws the same step the
            # frozen round would have
            old_cap = extra.get("capacity", state.fibers.n_fibers)
            new_cap = bucket_mod.next_fiber_capacity(old_cap + 1, self.policy)
            grown = state._replace(fibers=fc.grow_capacity(
                state.fibers, new_cap, node_multiple=self.node_multiple))
            self.reseats += 1
            obs_tracer.emit("lane", action="growth_reseat",
                            member=member_id, capacity=new_cap)
            logger.info("scenario: member %s reseated %d -> %d fiber slots",
                        member_id, old_cap, new_cap)
            self._sched_for(new_cap, grown).admit(MemberSpec(
                member_id=member_id, state=grown,
                t_final=spec.t_final, rng=spec.rng))
            return
        if reason == "finished":
            self.finished.append(member_id)
        if self.user_on_retire is not None:
            self.user_on_retire(member_id, state, reason,
                                rng_state=rng_state, **extra)

    def poll(self) -> bool:
        """One batched round over every rung with live lanes; True when any
        rung stepped."""
        stepped = False
        for cap in sorted(self._scheds):
            sched = self._scheds[cap]
            if sched.live:
                sched.poll()
                stepped = True
        if stepped:
            self.rounds += 1
        return stepped

    def run(self, max_rounds: Optional[int] = None) -> list:
        """Drain every rung (growth reseats included) to completion;
        returns finished member ids in retirement order."""
        while self.poll():
            if max_rounds is not None and self.rounds >= max_rounds:
                break
        return self.finished
