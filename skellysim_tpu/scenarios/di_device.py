"""Device-side dynamic instability: nucleation/catastrophe as in-trace mask
flips over a fixed-capacity fiber batch.

The host path (`system.dynamic_instability.apply_dynamic_instability`)
re-buckets fibers with numpy between jit'd solves — which is exactly why
`ensemble.runner` used to reject dynamic instability: a host round-trip
cannot live inside one closed batched trace. This module is the same
update as pure masked jnp ops, so it vmaps over the ensemble's member axis
(the JAX Fast Stokesian Dynamics shape, PAPERS.md arXiv 2503.07847:
stochastic per-step dynamics kept inside one jit'd program):

* **catastrophe** — P(die) = 1 - exp(-dt * f_cat) per active fiber (with
  the plus-pinned rate rescaling), one uniform draw per capacity slot;
  dying fibers flip ``active`` off and free their binding site — no shape
  changes, no recompilation;
* **growth** — survivors grow by dt * v_growth (`system.di_rates` is the
  ONE rate-math definition shared with the host oracle);
* **nucleation** — Poisson(dt * rate * n_inactive) capped by the free-site
  count; chosen sites fill inactive capacity slots via a static-shape
  masked prefix-sum over the slot bitmap + an argsort over the site
  bitmap (uniform selection without replacement: free sites ranked by an
  independent uniform priority). New fibers point radially out of their
  body, minus-clamped at ``min_length`` — all field writes are
  ``jnp.where`` selects at fixed shapes (docs/audit.md "Masking
  discipline"; this module is the registered `di_device` audit program,
  so the `mask` check proves the flip engine's non-interference — the
  one program whose *inputs* carry real stale-garbage padding).

**RNG discipline**: all draws come from the member's `SimRNG.member(i)`
``distributed`` stream, threaded through the trace as DATA — a ``[3]``
int32 carry ``(seed, stream_id, counter)`` riding `EnsembleState.di_rng`.
Each step folds ``counter + j`` (j = 0..2) into the stream's base key
exactly like `utils.rng.Stream` does host-side, then the runner advances
the counter by `DRAWS_PER_STEP`; the carry round-trips through
`SimRNG` dump/restore, so serve snapshots and ``--resume`` keep RNG
continuity. (The host loop's draw COUNT per step is data-dependent, so
host and device streams are not draw-for-draw aligned; cross-path parity
tests inject deterministic draws instead — docs/scenarios.md.)

**Capacity overflow**: when a nucleation burst wants more slots than the
batch holds, the whole update aborts for that member (its lane freezes
un-advanced, its counter does NOT advance) and ``DIInfo.needs_growth``
flags it. The scheduler reseats the lane onto the next
`system.buckets.next_fiber_capacity` rung host-side (`scenarios.sweep`) —
mask flips in-trace, geometric re-bucketing outside, O(log n) traces
total, warm via the persistent compile cache.
"""

from __future__ import annotations

from typing import NamedTuple, Optional

import jax
import jax.numpy as jnp

from ..bodies import bodies as bd
from ..fibers import container as fc, fd_fiber
from ..system import di_rates

#: keys consumed per step (u_cat / poisson / u_site) — the runner advances
#: the member's stream counter by this after every applied update
DRAWS_PER_STEP = 3


class DIDraws(NamedTuple):
    """One step's stochastic inputs (the injection seam for parity tests).

    ``u_cat`` [capacity] uniforms in [0, 1) (catastrophe; 0 = never dies),
    ``n_raw`` scalar int32 (the un-capped Poisson nucleation count),
    ``u_site`` [n_sites] uniform priorities (site selection: the n lowest
    free-site priorities nucleate, in ascending order).
    """

    u_cat: jnp.ndarray
    n_raw: jnp.ndarray
    u_site: jnp.ndarray


class DIInfo(NamedTuple):
    """Per-member outcome of one device DI update (scalars inside vmap)."""

    nucleations: jnp.ndarray     # int32 slots filled (0 on abort)
    catastrophes: jnp.ndarray    # int32 fibers deactivated (0 on abort)
    active_fibers: jnp.ndarray   # int32 live count AFTER the update
    #: the nucleation burst outgrew the capacity bucket: the update was
    #: aborted (state and RNG counter untouched) — reseat the member onto
    #: the next capacity rung and re-run
    needs_growth: jnp.ndarray


class SiteTable(NamedTuple):
    """Flat lab-frame nucleation-site table over every body bucket — the
    traced twin of the host path's ``site_tab`` (same body-major,
    site-minor flat order, so injected-draw selection parity holds)."""

    sites: jnp.ndarray    # [S, 3] lab-frame site positions
    coms: jnp.ndarray     # [S, 3] owning body centers
    gids: jnp.ndarray     # [S] int32 global body ids (config_rank)
    sids: jnp.ndarray     # [S] int32 per-body site indices


def site_table(bodies) -> Optional[SiteTable]:
    """Traced site table, or None when no body carries nucleation sites
    (site COUNT is static — body positions/orientations are traced)."""
    sites, coms, gids, sids = [], [], [], []
    for g in bd.as_buckets(bodies):
        ns = g.nucleation_sites_ref.shape[1]
        if ns == 0:
            continue
        _, _, s_lab = bd.place(g)                     # [nb, ns, 3]
        sites.append(s_lab.reshape(-1, 3))
        coms.append(jnp.repeat(g.position, ns, axis=0))
        ranks = (g.config_rank if g.config_rank is not None
                 else jnp.arange(g.n_bodies, dtype=jnp.int32))
        gids.append(jnp.repeat(ranks, ns))
        sids.append(jnp.tile(jnp.arange(ns, dtype=jnp.int32), g.n_bodies))
    if not sites:
        return None
    return SiteTable(jnp.concatenate(sites), jnp.concatenate(coms),
                     jnp.concatenate(gids), jnp.concatenate(sids))


def _stream_key(di_rng, offset: int):
    """The `utils.rng.Stream` key chain, in-trace: fold stream id then
    (counter + offset) into the seeded base key."""
    base = jax.random.fold_in(jax.random.PRNGKey(di_rng[0]), di_rng[1])
    return jax.random.fold_in(base, di_rng[2] + offset)


def sample_draws(di_rng, lam, capacity: int, n_sites: int,
                 dtype=jnp.float64) -> DIDraws:
    """Natural draws for one step from the member's stream carry (three
    keys: counter+0 / +1 / +2). ``lam`` is traced — the Poisson mean
    depends on the live occupancy."""
    u_cat = jax.random.uniform(_stream_key(di_rng, 0), (capacity,),
                               dtype=dtype)
    n_raw = jax.random.poisson(_stream_key(di_rng, 1),
                               jnp.maximum(lam, 0.0)).astype(jnp.int32)
    u_site = jax.random.uniform(_stream_key(di_rng, 2), (max(n_sites, 1),),
                                dtype=dtype)
    return DIDraws(u_cat=u_cat, n_raw=n_raw, u_site=u_site[:n_sites])


#: per-fiber fields the slot-fill writes — the device twin of the host
#: path's ``handled`` set; a new FiberGroup field with a leading fiber
#: axis must be added HERE too or nucleation would recycle dead values
_HANDLED = {"x", "tension", "length", "length_prev", "bending_rigidity",
            "radius", "penalty", "beta_tstep", "v_growth", "force_scale",
            "minus_clamped", "plus_pinned", "binding_body", "binding_site",
            "active", "config_rank"}


def check_di_state(state, params) -> None:
    """Static (trace-time) validation that ``state`` can run the device DI
    update; raises with an actionable message otherwise. Shared by the
    ensemble runner's admission and the scenario front-end."""
    di = params.dynamic_instability
    fibers = state.fibers
    if fibers is None or not isinstance(fibers, fc.FiberGroup):
        raise ValueError(
            "device dynamic instability needs a single fixed-capacity "
            "FiberGroup (mixed-resolution tuples and fiber-less states "
            "run the host loop; pre-allocate capacity with "
            "scenarios.ensure_di_capacity)")
    if fc.live_node_count(fibers) != di.n_nodes:
        raise ValueError(
            "dynamic_instability.n_nodes must match the fiber group's live "
            f"resolution ({di.n_nodes} != {fc.live_node_count(fibers)})")
    per_fiber = {name for name, leaf in zip(fibers._fields, fibers)
                 if name != "rt_mats" and leaf is not None
                 and getattr(leaf, "ndim", 0) >= 1
                 and leaf.shape[0] == fibers.n_fibers}
    if per_fiber - _HANDLED:
        raise RuntimeError(
            f"device nucleation slot-fill does not reset fiber fields "
            f"{sorted(per_fiber - _HANDLED)}; recycled slots would inherit "
            "dead fibers' values (update di_device._HANDLED and the host "
            "path's handled set together)")


def di_update(state, params, di_rng, *, sample_fn=None):
    """One in-trace nucleation/catastrophe update -> (new_state, DIInfo).

    Pure at fixed shapes: vmaps over a stacked member axis (``di_rng``
    becomes [B, 3]) and inlines per-lane under the unroll plan. The
    arithmetic runs in float64 and casts back at the state boundary,
    mirroring the host path's numpy-f64 discipline, so f32 states see the
    same update the host loop would apply. On ``needs_growth`` every
    output equals its input (the member's round never happened).

    Scoped ``dynamic-instability`` for device-time attribution
    (obs/profile.py PHASE_SCOPES — metadata only, the ensemble_step
    contract is unchanged).
    """
    with jax.named_scope("dynamic-instability"):
        return _di_update_impl(state, params, di_rng, sample_fn=sample_fn)


def _di_update_impl(state, params, di_rng, *, sample_fn=None):
    di = params.dynamic_instability
    fibers = state.fibers
    # no validation HERE: this body runs at trace time, where the host-side
    # checks (live_node_count pulls the node mask) would sync or abort —
    # every admission seam (`check_di_state` via runner.make_ensemble,
    # `ensure_di_capacity`, serve admission) validates concrete states
    dtype = fibers.x.dtype
    cap = fibers.n_fibers
    n_live = di.n_nodes
    dt64 = state.dt.astype(jnp.float64)

    active = fibers.active
    v_growth, f_cat = di_rates.effective_rates(di, fibers.plus_pinned, jnp)
    attached = active & (fibers.binding_body >= 0)
    n_active_old = jnp.sum(attached).astype(jnp.int32)

    tab = site_table(state.bodies)
    n_sites = tab.sites.shape[0] if tab is not None else 0
    lam = di_rates.nucleation_mean(
        dt64, di.nucleation_rate,
        jnp.maximum(n_sites - n_active_old, 0).astype(jnp.float64))
    draws = (sample_fn or sample_draws)(di_rng, lam, cap, n_sites,
                                        jnp.float64)

    # ---------------------------------------------- catastrophe + growth
    die = di_rates.catastrophe_mask(active, draws.u_cat, dt64, f_cat, jnp)
    survive = active & ~die
    length64 = fibers.length.astype(jnp.float64)
    length_prev64 = jnp.where(survive, length64,
                              fibers.length_prev.astype(jnp.float64))
    length64 = di_rates.grown_length(length64, survive, dt64, v_growth, jnp)
    v_growth64 = jnp.where(survive, v_growth, 0.0)
    binding_body = jnp.where(survive, fibers.binding_body,
                             jnp.int32(-1))

    if tab is None:
        # catastrophe-only scene (no nucleation sites): never overflows
        out = fibers._replace(
            active=survive, length=length64.astype(dtype),
            length_prev=length_prev64.astype(dtype),
            v_growth=v_growth64.astype(dtype), binding_body=binding_body)
        info = DIInfo(
            nucleations=jnp.int32(0),
            catastrophes=jnp.sum(die).astype(jnp.int32),
            active_fibers=jnp.sum(survive).astype(jnp.int32),
            needs_growth=jnp.asarray(False))
        return state._replace(fibers=out), info

    # ---------------------------------------------------------- nucleation
    # occupancy bitmap over the flat site table (the reference's one flat
    # bitmap, `dynamic_instability.cpp:63,87`), from the POST-catastrophe
    # bindings — a dying fiber frees its site this very step
    bound = survive & (binding_body >= 0)
    occ = jnp.any(bound[None, :]
                  & (binding_body[None, :] == tab.gids[:, None])
                  & (fibers.binding_site[None, :] == tab.sids[:, None]),
                  axis=1)                                        # [S]
    n_free = jnp.sum(~occ).astype(jnp.int32)
    n_want = di_rates.nucleation_count(draws.n_raw, n_free, jnp)
    free_slots = jnp.sum(~survive).astype(jnp.int32)
    needs_growth = n_want > free_slots
    n_fill = jnp.minimum(n_want, free_slots)

    # uniform selection without replacement at static shape: free sites
    # ranked by their priority draw (occupied sites sort to the back),
    # the first n_fill of the order nucleate
    prio = jnp.where(occ, jnp.inf, draws.u_site)
    order = jnp.argsort(prio).astype(jnp.int32)                  # [S]

    # k-th chosen site fills the k-th inactive capacity slot (the host
    # path's flatnonzero(~active)[:n] in masked prefix-sum form)
    slot_rank = (jnp.cumsum(~survive) - 1).astype(jnp.int32)     # [cap]
    fill = (~survive) & (slot_rank < n_fill)
    site_of = order[jnp.clip(slot_rank, 0, n_sites - 1)]         # [cap]
    origin = tab.sites[site_of].astype(jnp.float64)              # [cap, 3]
    com = tab.coms[site_of].astype(jnp.float64)
    nodes = di_rates.nucleated_nodes(origin, com, di.min_length, n_live,
                                     jnp)                        # [cap, nl, 3]
    pad = fibers.n_nodes - n_live
    if pad:
        # node-capacity-padded groups (skelly-bucket): live prefix gets the
        # geometry, masked pad rows replicate node 0 — the grow_node_capacity
        # placeholder discipline
        nodes = jnp.concatenate(
            [nodes, jnp.repeat(nodes[:, :1], pad, axis=1)], axis=1)

    next_rank = jnp.max(fibers.config_rank) + 1

    def sel(mask, new, old):
        m = mask.reshape(mask.shape + (1,) * (jnp.ndim(old) - 1))
        return jnp.where(m, new, old)

    upd = fibers._replace(
        x=sel(fill, nodes.astype(dtype), fibers.x),
        tension=sel(fill, jnp.zeros((), dtype), fibers.tension),
        length=sel(fill, jnp.asarray(di.min_length, dtype),
                   length64.astype(dtype)),
        length_prev=sel(fill, jnp.asarray(di.min_length, dtype),
                        length_prev64.astype(dtype)),
        bending_rigidity=sel(fill, jnp.asarray(di.bending_rigidity, dtype),
                             fibers.bending_rigidity),
        radius=sel(fill, jnp.asarray(di.radius, dtype), fibers.radius),
        penalty=sel(fill, jnp.asarray(fd_fiber.DEFAULT_PENALTY, dtype),
                    fibers.penalty),
        beta_tstep=sel(fill, jnp.asarray(fd_fiber.DEFAULT_BETA_TSTEP, dtype),
                       fibers.beta_tstep),
        v_growth=sel(fill, jnp.zeros((), dtype), v_growth64.astype(dtype)),
        force_scale=sel(fill, jnp.zeros((), dtype), fibers.force_scale),
        minus_clamped=jnp.where(fill, True, fibers.minus_clamped),
        plus_pinned=jnp.where(fill, False, fibers.plus_pinned),
        binding_body=jnp.where(fill, tab.gids[site_of], binding_body),
        binding_site=jnp.where(fill, tab.sids[site_of], fibers.binding_site),
        active=survive | fill,
        config_rank=jnp.where(fill, next_rank + slot_rank,
                              fibers.config_rank),
    )

    # abort wholesale on overflow: the lane freezes, the scheduler reseats
    # it onto the next capacity rung and this round re-runs there (inside
    # vmap needs_growth is a scalar, so plain where broadcasts every
    # changed leaf; untouched leaves — rt_mats — stay shared)
    out = fibers._replace(**{
        name: jnp.where(needs_growth, getattr(fibers, name),
                        getattr(upd, name))
        for name in _HANDLED})
    info = DIInfo(
        nucleations=jnp.where(needs_growth, 0, n_fill).astype(jnp.int32),
        catastrophes=jnp.where(needs_growth, 0,
                               jnp.sum(die)).astype(jnp.int32),
        active_fibers=jnp.where(needs_growth, jnp.sum(active),
                                jnp.sum(survive | fill)).astype(jnp.int32),
        needs_growth=needs_growth)
    return state._replace(fibers=out), info


def auditable_programs():
    """The scenarios layer's audit entry: one device DI update over a
    fixture with REAL capacity padding (8 slots, 3 live fibers, one bound
    to a nucleation site). The only registered program whose `[mask]`
    contract declares a capacity axis: its pins prove the update never
    reads a dead slot into live physics — dead slots are pad-passthrough
    (stale until nucleation overwrites them), never summed or argsorted
    without a sentinel."""
    import numpy as np

    from ..audit.registry import AuditProgram, built_from

    def _fixture():
        import jax.numpy as jnp

        from ..params import DynamicInstability, Params
        from ..periphery.precompute import precompute_body
        from ..system import System

        params = Params(
            eta=1.0, dt_initial=0.02, dt_write=0.02, t_final=0.08,
            gmres_tol=1e-10, adaptive_timestep_flag=False,
            dynamic_instability=DynamicInstability(
                n_nodes=8, v_growth=0.2, f_catastrophe=0.5,
                nucleation_rate=60.0, min_length=0.4, radius=0.0125,
                bending_rigidity=0.01))
        pre = precompute_body("sphere", 40, radius=0.5)
        rng = np.random.default_rng(11)
        sites = rng.standard_normal((6, 3))
        sites = 0.5 * sites / np.linalg.norm(sites, axis=1, keepdims=True)
        bodies = bd.make_group(pre["node_positions_ref"],
                               pre["node_normals_ref"], pre["node_weights"],
                               nucleation_sites_ref=sites[None], radius=0.5)
        x = np.tile(np.linspace(0.0, 1.0, 8)[None, :, None], (3, 1, 3))
        x += (1.5 + np.arange(3))[:, None, None]
        g = fc.make_group(x, lengths=1.0, bending_rigidity=0.01,
                          radius=0.0125)
        g = fc.grow_capacity(g, 8)
        bb = np.asarray(g.binding_body).copy()
        bs = np.asarray(g.binding_site).copy()
        bb[0], bs[0] = 0, 0          # occupied site: exercises the bitmap
        g = g._replace(binding_body=bb, binding_site=bs)
        state = System(params).make_state(fibers=g, bodies=bodies)
        return state, params, jnp.asarray([0, 3, 0], jnp.int32)

    def build():
        import jax

        state, params, rng = _fixture()
        step = jax.jit(lambda s, r: di_update(s, params, r))
        return built_from(step, state, rng)

    return [AuditProgram(
        name="di_device", layer="scenarios",
        summary="device DI update (nucleation/catastrophe mask flips over "
                "an 8-slot capacity batch, 3 live fibers)",
        build=build)]
