"""skelly-scenario: device-side dynamic instability + the scenario sweep
front-end (docs/scenarios.md).

Two halves:

* `di_device` — the stochastic nucleation/catastrophe update of
  `system.dynamic_instability` re-expressed as pure masked jnp ops over a
  fixed-capacity fiber batch, so it runs INSIDE the batched ensemble trace
  (ROADMAP item 5's ensemble leg, unlocked by skelly-bucket's capacity
  rungs);
* `sweep` — the front-end that composes it with the ensemble scheduler:
  one shared compiled step across a geometric ladder of capacity rungs,
  growth reseats between rungs when a member's bucket fills.
"""

from .di_device import DIDraws, DIInfo, di_update, sample_draws  # noqa: F401
from .sweep import ScenarioEnsemble, ensure_di_capacity  # noqa: F401
