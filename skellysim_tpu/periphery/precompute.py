"""Offline precompute: periphery/body quadrature + dense shell operator inverse.

Mirror of the reference's `skelly_precompute` pipeline
(`/root/reference/src/skelly_sim/precompute.py:37-245`): build surface nodes
(shape gallery), triangulate (convex hull), compute Reeger-Fornberg quadrature
weights, assemble the dense second-kind shell operator and invert it. Results
are plain dicts of NumPy arrays, storable as npz (same keys as the reference so
trajectories/precompute files interoperate).
"""

from __future__ import annotations

import numpy as np
from scipy.spatial import ConvexHull

from .periphery import build_shell_operator, build_shell_operator_device
from .quadrature import surface_quadrature_weights
from .shapes import ShapeSpec, ellipsoid_shape, sphere_shape, surface_of_revolution_shape

#: node radius inflation relative to the attachment radius (`precompute.py:34`)
PERIPHERY_NODE_SCALE_FACTOR = 1.04
#: body quadrature radius shrinkage (`precompute.py:27-29`)
BODY_QUADRATURE_RADIUS_OFFSET_LOW = 0.1
BODY_QUADRATURE_RADIUS_OFFSET_HIGH = 0.2
BODY_QUADRATURE_RADIUS_THRESHOLD = 2.0


def _shape_for_periphery(shape: str, n_nodes: int, **kw) -> ShapeSpec:
    s = PERIPHERY_NODE_SCALE_FACTOR
    if shape == "sphere":
        return sphere_shape(n_nodes, radius=kw["radius"] * s)
    if shape == "ellipsoid":
        return ellipsoid_shape(n_nodes, a=kw["a"] * s, b=kw["b"] * s, c=kw["c"] * s)
    if shape == "surface_of_revolution":
        return surface_of_revolution_shape(kw["envelope"], scale_factor=s)
    raise ValueError(f"unknown periphery shape: {shape}")


def precompute_periphery(shape: str, n_nodes: int = 0, eta: float = 1.0,
                         operator_backend: str = "host", **kw) -> dict:
    """Full periphery precompute. Returns dict with the reference npz keys:
    nodes, normals (inward), quadrature_weights, stresslet_plus_complementary,
    M_inv (+ envelope fit state for surfaces of revolution).

    ``operator_backend="device"`` assembles the dense operator and computes
    the inverse on the accelerator (`periphery.build_shell_operator_device`):
    the reference's host-LAPACK inverse (`precompute.py:133`) is the O(N^3)
    pole of the whole precompute (~5 min at 6000 nodes on one core; seconds
    on a TPU chip). The device inverse is float32 (preconditioner-grade —
    TPU LuDecomposition is f32-only); the operator stays float64. Quadrature
    (hull + RBF weights) remains on host either way.
    """
    if operator_backend not in ("host", "device"):
        # validate before the hull + RBF quadrature (minutes at 6k nodes)
        raise ValueError(
            f"unknown operator_backend {operator_backend!r} "
            "(expected 'host' or 'device')")
    import jax

    if not jax.config.jax_enable_x64:
        # BOTH backends assemble through the JAX kernels; without x64 the
        # stored float64 operator silently degrades to f32-grade values
        # (~2.7e-8 relative, found by round-5 verify). Check here — before
        # the expensive quadrature — so direct library callers fail fast
        # instead of only the CLI (which enables x64 itself).
        raise RuntimeError(
            "precompute_periphery needs jax_enable_x64 (the dense operator "
            "assembles through JAX kernels; without x64 it silently "
            "degrades to float32 accuracy). Enable it or use the "
            "`python -m skellysim_tpu.precompute` CLI, which does.")
    spec = _shape_for_periphery(shape, n_nodes, **kw)
    nodes = spec.nodes
    normals = -spec.node_normals  # periphery normals point inward (`precompute.py:82`)

    tris = ConvexHull(nodes).simplices
    weights = surface_quadrature_weights(nodes, tris, spec.gradh)

    if operator_backend == "device":
        operator, M_inv = build_shell_operator_device(nodes, normals, weights,
                                                      eta=eta)
        operator, M_inv = np.asarray(operator), np.asarray(M_inv)
    else:
        operator, M_inv = build_shell_operator(nodes, normals, weights, eta=eta)

    out = {
        "nodes": nodes,
        "normals": normals,
        "quadrature_weights": weights,
        "stresslet_plus_complementary": operator,
        "M_inv": M_inv,
    }
    if spec.envelope is not None:
        out.update(spec.envelope.get_state())
    return out


def precompute_body(shape: str, n_nodes: int, radius: float = 0.0,
                    a: float = 0.0, b: float = 0.0, c: float = 0.0) -> dict:
    """Body surface precompute: reference-frame nodes/normals + quadrature weights.

    Spheres shrink the quadrature-node radius below the hydrodynamic radius
    (`precompute.py:153-160`).
    """
    if shape == "sphere":
        r = radius - (BODY_QUADRATURE_RADIUS_OFFSET_LOW
                      if radius < BODY_QUADRATURE_RADIUS_THRESHOLD
                      else BODY_QUADRATURE_RADIUS_OFFSET_HIGH)
        spec = sphere_shape(n_nodes, radius=r)
    elif shape == "ellipsoid":
        spec = ellipsoid_shape(n_nodes, a=a, b=b, c=c)
    else:
        raise ValueError(f"unknown body shape: {shape}")

    tris = ConvexHull(spec.nodes).simplices
    weights = surface_quadrature_weights(spec.nodes, tris, spec.gradh)
    return {
        "node_positions_ref": spec.nodes,
        "node_normals_ref": spec.node_normals,
        "node_weights": weights,
    }
