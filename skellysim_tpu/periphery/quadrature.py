"""High-order quadrature weights on smooth closed triangulated surfaces.

Implements the method of J. A. Reeger, B. Fornberg, and M. L. Watts,
"Numerical quadrature over smooth, closed surfaces" (Proc. R. Soc. A 472, 2016)
— the same algorithm behind the reference's precompute quadrature
(`/root/reference/src/skelly_sim/Smooth_Closed_Surface_Quadrature_RBF.py`), but
re-implemented from the published method with the per-triangle work batched
into stacked linear solves instead of a Python loop per triangle.

Algorithm sketch (per triangle of the convex-hull triangulation):
 1. Build a projection point O from the triangle's three edge planes (each edge
   paired with the average normal of its two adjacent triangles); projecting
   nearby surface nodes onto the triangle's plane from O tiles the surface
   exactly (adjacent triangles share their edge planes).
 2. Map the k nearest surface nodes into 2-D plane coordinates.
 3. Integrate the polyharmonic RBF phi(r) = r^7 centered at each projected
   node exactly over the triangle (right-triangle decomposition), and all
   monomials x^a y^b of total degree <= m exactly (divergence-theorem polygon
   moments).
 4. Solve the RBF+poly saddle system for plane quadrature weights.
 5. Scale each weight by the surface/plane area-element distortion computed
   from the exact surface normal (gradh) and accumulate onto the nodes.
"""

from __future__ import annotations

import numpy as np
from scipy.spatial import cKDTree

POLY_ORDER = 7          # m in the paper
N_NEIGHBORS = 80        # k in the paper; >= (m+1)(m+2)/2 = 36
_CHUNK = 512            # triangles per batched solve


def _triangle_normals(nodes, tris):
    v1 = nodes[tris[:, 1]] - nodes[tris[:, 0]]
    v2 = nodes[tris[:, 2]] - nodes[tris[:, 0]]
    n = np.cross(v1, v2)
    return n / np.linalg.norm(n, axis=1, keepdims=True)


def _edge_normals(nodes, tris, tri_normals):
    """For each triangle's three edges, the sign-aligned average of the normals
    of the two triangles sharing that edge. Returns [T, 3, 3] (edge order:
    (v0,v1), (v0,v2), (v1,v2) of the index-sorted triangle)."""
    T = len(tris)
    edges = np.concatenate([tris[:, [0, 1]], tris[:, [0, 2]], tris[:, [1, 2]]])
    owner = np.concatenate([np.arange(T)] * 3)
    # canonical edge key
    key = edges[:, 0].astype(np.int64) * len(nodes) + edges[:, 1]
    order = np.argsort(key, kind="stable")
    e_sorted, o_sorted = key[order], owner[order]
    assert np.all(e_sorted[0::2] == e_sorted[1::2]), "non-manifold triangulation"
    n_a = tri_normals[o_sorted[0::2]]
    n_b = tri_normals[o_sorted[1::2]]
    sign = np.sign(np.sum(n_a * n_b, axis=1, keepdims=True))
    avg = n_a + sign * n_b
    avg /= np.linalg.norm(avg, axis=1, keepdims=True)
    # scatter the average back to both owners
    edge_normal = np.empty((3 * T, 3))
    edge_normal[order[0::2]] = avg
    edge_normal[order[1::2]] = avg
    return edge_normal.reshape(3, T, 3).transpose(1, 0, 2)


def _projection_points(nodes, tris, edge_normals):
    """Intersection of the three edge planes: the point O from which the
    projection onto the triangle plane tiles the surface."""
    A = nodes[tris[:, 0]]
    B = nodes[tris[:, 1]]
    C = nodes[tris[:, 2]]
    nAB, nAC, nBC = edge_normals[:, 0], edge_normals[:, 1], edge_normals[:, 2]
    # plane through edge e with in-plane direction e and normal direction n_e:
    # its normal is n_e x e
    pAB = np.cross(nAB, B - A)
    pAC = np.cross(nAC, C - A)
    pBC = np.cross(nBC, C - B)
    v = np.cross(pAB, pAC)  # direction through A common to both planes
    denom = np.sum(pBC * v, axis=1)
    t = np.sum(pBC * (B - A), axis=1) / denom
    return A + t[:, None] * v


def _monomial_exponents(m):
    return np.array([(a - b, b) for a in range(m + 1) for b in range(a + 1)])


def _polygon_monomial_integrals(verts, m):
    """Exact integrals of x^a y^b (a+b <= m) over batched triangles.

    ``verts`` is [T, 3, 2]. Uses the divergence theorem:
    integral x^a y^b dA = 1/(a+1) * contour integral x^(a+1) y^b dy,
    with each (linearly parameterized) side integrated by Gauss-Legendre of
    sufficient order (exact for the polynomial integrand).
    """
    exps = _monomial_exponents(m)
    q, wq = np.polynomial.legendre.leggauss(m + 2)  # exact to degree 2m+3
    q = 0.5 * (q + 1.0)
    wq = 0.5 * wq

    T = verts.shape[0]
    out = np.zeros((T, len(exps)))
    for side in range(3):
        p0 = verts[:, side]
        p1 = verts[:, (side + 1) % 3]
        dx = p1 - p0
        # points along the side: [T, q, 2]
        pts = p0[:, None, :] + q[None, :, None] * dx[:, None, :]
        dy = dx[:, 1]
        for i, (a, b) in enumerate(exps):
            integrand = pts[:, :, 0] ** (a + 1) * pts[:, :, 1] ** b
            out[:, i] += (integrand @ wq) * dy / (a + 1)
    return out, exps


def _rbf_triangle_integrals(centers, verts):
    """Exact integral of phi(r) = r^7 centered at each point over each triangle.

    ``centers`` [T, k, 2], ``verts`` [T, 3, 2]; right-triangle decomposition:
    for each side, drop the orthogonal foot from the center, producing two
    signed right triangles with legs alpha (height) and beta (along the side);
    integral of r^7 over such a right triangle has the closed form
    alpha*(beta*sqrt(a^2+b^2)*(279a^6+326a^4b^2+200a^2b^4+48b^6)
           + 105 a^8 asinh(b/a)) / 3456.
    """
    Tn, k, _ = centers.shape
    out = np.zeros((Tn, k))
    sABC = np.sign(
        (verts[:, 0, 1] - verts[:, 1, 1]) * (verts[:, 2, 0] - verts[:, 0, 0])
        + (verts[:, 1, 0] - verts[:, 0, 0]) * (verts[:, 2, 1] - verts[:, 0, 1]))

    def right_tri(alpha, beta):
        with np.errstate(divide="ignore", invalid="ignore"):
            val = alpha * (beta * np.sqrt(alpha**2 + beta**2)
                           * (279 * alpha**6 + 326 * alpha**4 * beta**2
                              + 200 * alpha**2 * beta**4 + 48 * beta**6)
                           + 105 * alpha**8 * np.arcsinh(beta / np.where(alpha > 0, alpha, 1.0))
                           ) / 3456.0
        return np.where((alpha > 1e-30) & (beta > 1e-30), val, 0.0)

    for side in range(3):
        a_v = verts[:, side]                   # [T, 2]
        b_v = verts[:, (side + 1) % 3]
        d = b_v - a_v
        L2 = np.sum(d * d, axis=1)
        t = (np.einsum("tkj,tj->tk", centers - a_v[:, None, :], d)) / L2[:, None]
        foot = a_v[:, None, :] + t[..., None] * d[:, None, :]   # [T, k, 2]
        alpha = np.linalg.norm(centers - foot, axis=2)          # height
        beta1 = np.linalg.norm(foot - a_v[:, None, :], axis=2)
        beta2 = np.linalg.norm(foot - b_v[:, None, :], axis=2)

        # orientation signs of the two right triangles (O, foot, vertex)
        ca = a_v[:, None, :] - centers
        cf = foot - centers
        cb = b_v[:, None, :] - centers
        cross1 = ca[..., 0] * cf[..., 1] - ca[..., 1] * cf[..., 0]
        cross2 = cf[..., 0] * cb[..., 1] - cf[..., 1] * cb[..., 0]
        s1 = sABC[:, None] * np.sign(cross1)
        s2 = sABC[:, None] * np.sign(cross2)

        out += s1 * right_tri(alpha, beta1) + s2 * right_tri(alpha, beta2)
    return out


def surface_quadrature_weights(nodes, triangles, gradh=None):
    """Quadrature weights for surface integrals over the closed surface.

    ``nodes`` [N, 3] on the surface; ``triangles`` [T, 3] triangulation (e.g.
    scipy ConvexHull simplices); ``gradh`` callable giving the (unnormalized)
    exact surface normal at given points. Returns weights [N].
    """
    nodes = np.asarray(nodes, dtype=np.float64)
    tris = np.sort(np.asarray(triangles), axis=1)
    N = len(nodes)
    k = min(N_NEIGHBORS, N)
    n_poly = (POLY_ORDER + 1) * (POLY_ORDER + 2) // 2
    assert k >= n_poly, "need more nodes than polynomial terms"

    tri_n = _triangle_normals(nodes, tris)
    edge_n = _edge_normals(nodes, tris, tri_n)
    proj_pt = _projection_points(nodes, tris, edge_n)
    mids = nodes[tris].mean(axis=1)
    tree = cKDTree(nodes)
    _, nni = tree.query(mids, k=k)

    if gradh is not None:
        ns_all = np.asarray(gradh(nodes), dtype=np.float64)
        ns_all /= np.linalg.norm(ns_all, axis=1, keepdims=True)
    else:
        raise NotImplementedError("approximate-normal branch not implemented; "
                                  "all framework shapes supply gradh")

    weights = np.zeros(N)
    T = len(tris)
    for lo in range(0, T, _CHUNK):
        hi = min(lo + _CHUNK, T)
        sl = slice(lo, hi)
        tn = tri_n[sl]                       # [t, 3]
        O = proj_pt[sl]                      # [t, 3]
        idx = nni[sl]                        # [t, k]
        pts = nodes[idx]                     # [t, k, 3]
        tv = nodes[tris[sl]]                 # [t, 3, 3]

        # project nodes onto the triangle plane along rays from O
        anchor = tv[:, 0]                    # a point on the plane
        denom = np.einsum("tj,tkj->tk", tn, pts - O[:, None, :])
        lam = np.einsum("tj,tkj->tk", tn, anchor[:, None, :] - pts) / denom
        proj = pts + lam[..., None] * (pts - O[:, None, :])   # [t, k, 3]

        # orthonormal in-plane basis
        ref = np.where(np.abs(tn[:, [0]]) < 0.9,
                       np.broadcast_to([1.0, 0.0, 0.0], tn.shape),
                       np.broadcast_to([0.0, 1.0, 0.0], tn.shape))
        e1 = np.cross(tn, ref)
        e1 /= np.linalg.norm(e1, axis=1, keepdims=True)
        e2 = np.cross(tn, e1)

        # 2-D coordinates relative to the triangle midpoint (conditioning)
        mid = mids[sl]
        uv = np.stack([np.einsum("tkj,tj->tk", proj - mid[:, None, :], e1),
                       np.einsum("tkj,tj->tk", proj - mid[:, None, :], e2)], axis=-1)
        tuv = np.stack([np.einsum("tkj,tj->tk", tv - mid[:, None, :], e1),
                        np.einsum("tkj,tj->tk", tv - mid[:, None, :], e2)], axis=-1)

        I_rbf = _rbf_triangle_integrals(uv, tuv)          # [t, k]
        I_poly, exps = _polygon_monomial_integrals(tuv, POLY_ORDER)
        # orient polygon moments positively (unsigned area), matching the
        # sABC-corrected RBF integrals
        area2 = (tuv[:, 1, 0] - tuv[:, 0, 0]) * (tuv[:, 2, 1] - tuv[:, 0, 1]) \
            - (tuv[:, 2, 0] - tuv[:, 0, 0]) * (tuv[:, 1, 1] - tuv[:, 0, 1])
        I_poly *= np.sign(area2)[:, None]

        # saddle system [phi P; P^T 0]
        d2 = np.sum((uv[:, :, None, :] - uv[:, None, :, :]) ** 2, axis=-1)
        Phi = d2 ** 3.5                       # r^7
        P = np.stack([uv[..., 0] ** a * uv[..., 1] ** b for a, b in exps], axis=-1)
        nbig = k + n_poly
        Amat = np.zeros((hi - lo, nbig, nbig))
        Amat[:, :k, :k] = Phi
        Amat[:, :k, k:] = P
        Amat[:, k:, :k] = np.transpose(P, (0, 2, 1))
        rhs = np.concatenate([I_rbf, I_poly], axis=1)
        w = np.linalg.solve(Amat, rhs[..., None])[:, :k, 0]

        # area-element distortion: plane -> surface
        V = pts - O[:, None, :]
        rho = np.linalg.norm(V, axis=2)
        Vhat = V / rho[..., None]
        Rdist = np.linalg.norm(proj - O[:, None, :], axis=2)
        nS = ns_all[idx]
        cos_plane = np.einsum("tj,tkj->tk", tn, Vhat)
        cos_surf = np.einsum("tkj,tkj->tk", nS, Vhat)
        distort = np.abs(cos_plane / cos_surf * (rho / Rdist) ** 2)

        np.add.at(weights, idx.ravel(), (w * distort).ravel())

    return weights
