"""Surface node/normal generation: sphere, ellipsoid, surface of revolution.

Mirror of the reference `ShapeGallery` (`/root/reference/src/skelly_sim/shape_gallery.py:59-214`):
spherical-Fibonacci node placement on spheres/ellipsoids, arclength-equispaced
rings for surfaces of revolution, with the implicit level function h and its
gradient for exact normals (consumed by the quadrature and collision checks).

The surface-of-revolution envelope takes the user's height expression (a string
over ``x`` with numpy available as ``np``, matching the reference's TOML
contract) and fits a Chebyshev proxy for fast evaluation/differentiation,
replacing the reference's `function_generator` dependency.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Callable, Optional

import numpy as np


@dataclass
class ShapeSpec:
    nodes: np.ndarray          # [N, 3]
    node_normals: np.ndarray   # [N, 3] outward unit normals
    h: Callable                # level function, h(points [N,3]) -> [N]
    gradh: Callable            # gradient, gradh(points) -> [N, 3]
    envelope: Optional["Envelope"] = None


def _fibonacci_sphere(n_nodes: int) -> np.ndarray:
    """Spherical-Fibonacci unit-sphere points (`shape_gallery.py:69-84`)."""
    phi = (1 + np.sqrt(5)) / 2
    N = n_nodes // 2
    i = np.arange(-N, N)
    lat = np.arcsin(2.0 * i / (2 * N + 1))
    lon = (i % phi) * 2 * np.pi / phi
    lon = np.where(lon < -np.pi, 2 * np.pi + lon, lon)
    lon = np.where(lon > np.pi, lon - 2 * np.pi, lon)
    return np.stack([np.cos(lon) * np.cos(lat),
                     np.sin(lon) * np.cos(lat),
                     np.sin(lat)], axis=1)


def sphere_shape(n_nodes: int, radius: float) -> ShapeSpec:
    nodes = radius * _fibonacci_sphere(n_nodes)

    def h(p):
        return np.sum(p * p, axis=1) - radius * radius

    def gradh(p):
        return 2.0 * p

    normals = gradh(nodes)
    normals /= np.linalg.norm(normals, axis=1, keepdims=True)
    return ShapeSpec(nodes=nodes, node_normals=normals, h=h, gradh=gradh)


def ellipsoid_shape(n_nodes: int, a: float, b: float, c: float) -> ShapeSpec:
    abc = np.array([a, b, c])
    nodes = _fibonacci_sphere(n_nodes) * abc[None, :]

    def h(p):
        return np.sum((p / abc) ** 2, axis=1) - 1.0

    def gradh(p):
        return 2.0 * p / abc**2

    normals = gradh(nodes)
    normals /= np.linalg.norm(normals, axis=1, keepdims=True)
    return ShapeSpec(nodes=nodes, node_normals=normals, h=h, gradh=gradh)


class Envelope:
    """Height function r(x) of a surface of revolution about the x axis.

    Accepts the reference's config contract (`shape_gallery.py:6-42`):
    ``height`` is a python expression in ``x``, with ``lower_bound``,
    ``upper_bound`` and any extra constants available as names in the
    expression. Internally fits a high-degree Chebyshev approximation for
    differentiation and fast evaluation.
    """

    def __init__(self, config: dict):
        self.config = dict(config)
        self.lower_bound = float(config["lower_bound"])
        self.upper_bound = float(config["upper_bound"])
        env = {k: v for k, v in config.items() if isinstance(v, (int, float))}
        env["np"] = np
        self.raw_height = eval("lambda x: " + config["height"], env)  # noqa: S307

        # fit slightly inside the bounds to dodge end-point singularities
        # (the reference's FunctionGenerator fit retries with shrunken bounds)
        delta = 1e-10 * (self.upper_bound - self.lower_bound)
        lo, hi = self.lower_bound + delta, self.upper_bound - delta
        x = 0.5 * (lo + hi) + 0.5 * (hi - lo) * np.cos(np.pi * np.arange(2000) / 1999)
        self._cheb = np.polynomial.Chebyshev.fit(x, self.raw_height(x), deg=200,
                                                 domain=[lo, hi])
        self._dcheb = self._cheb.deriv()

    def __call__(self, x):
        return self._cheb(np.clip(x, self.lower_bound, self.upper_bound))

    def differentiate(self, x):
        return self._dcheb(np.clip(x, self.lower_bound, self.upper_bound))

    def get_state(self) -> dict:
        """Serializable fit state (coefficient vector + bounds) for npz files."""
        return {
            "env_coef": self._cheb.coef,
            "env_domain": np.array(self._cheb.domain),
            "env_bounds": np.array([self.lower_bound, self.upper_bound]),
        }


def surface_of_revolution_shape(envelope_config: dict, scale_factor: float = 1.0) -> ShapeSpec:
    """Arclength-equispaced rings around the x axis (`shape_gallery.py:151-214`)."""
    env = Envelope(envelope_config)
    target_nodes = int(envelope_config["n_nodes_target"])
    n_x = int(round(np.sqrt(target_nodes)))

    # equi-arclength sampling of the generating curve
    x_fine = np.linspace(env.lower_bound, env.upper_bound, 1_000_000)
    r_fine = env.raw_height(x_fine)
    seg = np.sqrt(np.diff(x_fine) ** 2 + np.diff(r_fine) ** 2)
    s = np.concatenate([[0.0], np.cumsum(seg)])
    t = np.linspace(0, s[-1], n_x)
    xn = np.interp(t, s, x_fine)
    rn = env.raw_height(xn)

    ds = np.mean(np.sqrt(np.diff(xn) ** 2 + np.diff(rn) ** 2))
    nodes = []
    for xi, ri in zip(xn, rn):
        n_rad = int(round(2 * np.pi * ri / ds))
        if n_rad <= 1:
            nodes.append([xi, 0.0, 0.0])
            continue
        theta = 2 * np.pi * np.arange(n_rad) / n_rad
        for th in theta:
            nodes.append([xi, ri * np.cos(th), ri * np.sin(th)])
    nodes = np.asarray(nodes) * scale_factor

    def h(p):
        return env.raw_height(p[:, 0]) ** 2 - p[:, 1] ** 2 - p[:, 2] ** 2

    def gradh(p):
        out = np.zeros_like(p)
        x, y, z = p[:, 0], p[:, 1], p[:, 2]
        hv = env(x)
        dh = env.differentiate(x)
        out[:, 0] = -hv * dh
        out[:, 1] = y
        out[:, 2] = z
        nrm = np.linalg.norm(out, axis=1, keepdims=True)
        out /= np.where(nrm > 0, nrm, 1.0)
        # end caps point along the axis
        out[x <= env.lower_bound] = [-1.0, 0.0, 0.0]
        out[x >= env.upper_bound] = [1.0, 0.0, 0.0]
        return out

    normals = gradh(nodes)
    return ShapeSpec(nodes=nodes, node_normals=normals, h=h, gradh=gradh, envelope=env)
