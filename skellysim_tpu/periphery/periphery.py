"""Confining periphery (cell cortex) as a second-kind boundary integral.

TPU-native replacement for `Periphery` (`/root/reference/src/core/periphery.cpp`,
`include/periphery.hpp`): the dense precomputed operator and its inverse live as
device arrays; matvec/preconditioner are single dense matmuls (MXU-native)
instead of MPI row-scatter + Allgatherv + local GEMV. Row-sharding over a mesh
replaces the reference's `MPI_Scatterv` distribution.

Operator assembly (matching `src/skelly_sim/precompute.py:104-140`):
  M = stresslet_times_normal(nodes, normals; eta=1)
      - blockdiag([ex_i | ey_i | ez_i] / w_i)          (singularity subtraction)
      - diag(1/w_i per component)                       (second-kind identity)
      + n n^T                                           (null-space completion)
  M_inv = inverse(M)   (the preconditioner; exact inverse of the self-operator)

Shape-specific collision / fiber steric forces mirror
`SphericalPeriphery`/`EllipsoidalPeriphery`/`GenericPeriphery`
(`src/core/periphery.cpp:94-335`).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import NamedTuple, Optional

import numpy as np

import jax.numpy as jnp

from ..ops import kernels


class PeripheryState(NamedTuple):
    """Device-resident shell state (a pytree)."""

    nodes: jnp.ndarray        # [N, 3]
    normals: jnp.ndarray      # [N, 3] (inward, as stored by precompute)
    weights: jnp.ndarray      # [N]
    M_inv: jnp.ndarray        # [3N, 3N] preconditioner
    stresslet_plus_complementary: jnp.ndarray  # [3N, 3N] operator
    density: jnp.ndarray      # [3N] current solution slice
    #: [N] bool quadrature-row mask, or None (all rows live — the default).
    #: Padded rows (skelly-bucket's shell axis, `grow_capacity`) carry zero
    #: normals/weights and solve the identity: scenes with different shell
    #: quadrature sizes share one compiled program at a capacity rung.
    node_mask: jnp.ndarray = None

    @property
    def n_nodes(self) -> int:
        return self.nodes.shape[0]

    @property
    def solution_size(self) -> int:
        return 3 * self.n_nodes


@dataclass(frozen=True)
class PeripheryShape:
    """Static collision geometry. kind: 'sphere' | 'ellipsoid' | 'generic'."""

    kind: str = "generic"
    radius: float = 0.0
    abc: tuple = (0.0, 0.0, 0.0)


def build_shell_operator(nodes, normals, weights, eta: float = 1.0):
    """Dense second-kind operator + inverse (host-side, float64).

    Faithful to `precompute.py:113-140`; uses the tested JAX kernels for the
    stresslet blocks and NumPy/LAPACK for the O(N^3) inversion.
    """
    nodes = np.asarray(nodes, dtype=np.float64)
    normals = np.asarray(normals, dtype=np.float64)
    weights = np.asarray(weights, dtype=np.float64)
    N = len(nodes)

    # row-blocked 2-D assembly: the dense 4-D builder materializes a
    # [N, 3, N, 3] device array whose trailing dim of 3 XLA tile-pads to 128
    # (55 GB at N = 6000 — an OOM on any real accelerator backend)
    M = np.array(kernels.stresslet_times_normal_blocked(nodes, normals, eta))

    # singularity subtraction vectors e_k integrated with quadrature weights
    def sing_vec(k):
        e = np.zeros((N, 3))
        e[:, k] = weights
        return np.asarray(
            kernels.stresslet_times_normal_times_density(nodes, normals, e, eta))

    ex, ey, ez = sing_vec(0), sing_vec(1), sing_vec(2)
    for i in range(N):
        M[3 * i:3 * i + 3, 3 * i + 0] -= ex[i] / weights[i]
        M[3 * i:3 * i + 3, 3 * i + 1] -= ey[i] / weights[i]
        M[3 * i:3 * i + 3, 3 * i + 2] -= ez[i] / weights[i]

    M -= np.diag(np.repeat(1.0 / weights, 3))
    M += np.outer(normals.reshape(-1), normals.reshape(-1))

    import scipy.linalg as scla

    M_inv = scla.inv(M)
    return M, M_inv


def block_inv(M, max_direct: int = 12000):
    """Dense inverse via recursive 2x2 Schur-complement blocking (on device).

    TPU LuDecomposition keeps an [n, 128] panel in scoped VMEM; at n = 18000
    (a 6000-node shell) that panel is 17.7 MB against a 16 MB limit and the
    compile fails. Halving until blocks fit turns the inverse into two
    smaller LUs plus MXU matmuls. Accuracy is preconditioner-grade, which is
    all its callers need: M_inv only ever feeds `apply_preconditioner`; the
    solve's convergence tolerance is enforced by GMRES against the
    *operator*, not the inverse.
    """
    n = M.shape[0]
    if n <= max_direct:
        return jnp.linalg.inv(M)
    h = n // 2
    A, B = M[:h, :h], M[:h, h:]
    C, D = M[h:, :h], M[h:, h:]
    Ai = block_inv(A, max_direct)
    AiB = Ai @ B
    Si = block_inv(D - C @ AiB, max_direct)
    CAi = C @ Ai
    top = jnp.concatenate([Ai + AiB @ (Si @ CAi), -AiB @ Si], axis=1)
    bot = jnp.concatenate([-Si @ CAi, Si], axis=1)
    return jnp.concatenate([top, bot], axis=0)


def build_shell_operator_device(nodes, normals, weights, eta: float = 1.0, *,
                                op_dtype=jnp.float64,
                                inv_dtype=jnp.float32):
    """Dense second-kind operator + inverse, assembled and inverted on device.

    Same math as `build_shell_operator` (the host/scipy path, mirroring the
    reference's `precompute.py:113-140`), with the O(N^2) assembly row-blocked
    on the accelerator and the O(N^3) inverse done by `block_inv` instead of
    host LAPACK — at 6000 nodes the scipy inverse is ~5 minutes on one host
    core vs seconds on a TPU chip. ``op_dtype`` should stay float64 (the
    operator's accuracy caps the mixed solver's achievable residual);
    ``inv_dtype`` defaults to float32 because the inverse is only ever a
    preconditioner AND TPU LuDecomposition is f32-only. Returns DEVICE
    arrays (callers that persist to npz convert; callers that keep solving —
    bench's scene builder — skip a pointless device->host->device round trip
    through the TPU tunnel).
    """
    import jax

    if jnp.dtype(op_dtype) == jnp.float64 and not jax.config.jax_enable_x64:
        # without x64 the float64 request silently canonicalizes to f32 and
        # the stored operator caps the mixed solver's achievable residual
        raise RuntimeError(
            "build_shell_operator_device(op_dtype=float64) needs "
            "jax_enable_x64 (the operator's accuracy bounds the solve)")
    N = len(nodes)
    nodes_d = jnp.asarray(nodes, dtype=op_dtype)
    normals_d = jnp.asarray(normals, dtype=op_dtype)
    w_d = jnp.asarray(weights, dtype=op_dtype)

    M = kernels.stresslet_times_normal_blocked(nodes_d, normals_d, eta)

    def sv(k):
        e = jnp.zeros((N, 3), dtype=op_dtype).at[:, k].set(w_d)
        return kernels.stresslet_times_normal_times_density(
            nodes_d, normals_d, e, eta)

    M = kernels.subtract_singularity_columns(M, (sv(0), sv(1), sv(2)), w_d)
    d = jnp.arange(3 * N, dtype=jnp.int32)
    M = M.at[d, d].add(-jnp.repeat(1.0 / w_d, 3))
    M = M + jnp.outer(normals_d.reshape(-1), normals_d.reshape(-1))
    M_inv = block_inv(M.astype(inv_dtype))
    return M, M_inv


def make_state(nodes, normals, weights, operator, M_inv, dtype=jnp.float64,
               precond_dtype=None) -> PeripheryState:
    """``precond_dtype`` stores M_inv (the preconditioner — accuracy does not
    matter) in a lower precision, halving its HBM footprint in mixed mode."""
    N = len(nodes)
    return PeripheryState(
        nodes=jnp.asarray(nodes, dtype=dtype),
        normals=jnp.asarray(normals, dtype=dtype),
        weights=jnp.asarray(weights, dtype=dtype),
        M_inv=jnp.asarray(M_inv, dtype=precond_dtype or dtype),
        stresslet_plus_complementary=jnp.asarray(operator, dtype=dtype),
        density=jnp.zeros(3 * N, dtype=dtype),
    )


def grow_capacity(shell: PeripheryState, new_n: int) -> PeripheryState:
    """Shell state padded to ``new_n`` quadrature rows (masked inert).

    The shell leg of skelly-bucket's capacity discipline: padded rows
    replicate node 0's position (silent sources — their normals are zero,
    so the double-layer density f_dl vanishes there; exact-coincidence
    pairs are dropped by the kernels anyway), weigh zero, and both dense
    operators grow block-diagonally with the identity — so the padded
    system's inverse IS the padded inverse and padded density entries
    solve to exact zero. ``new_n == n_nodes`` still attaches the mask so
    an exact-fit scene shares its bucket's pytree structure.
    """
    n = shell.n_nodes
    if new_n < n:
        raise ValueError(
            f"periphery.grow_capacity: new_n {new_n} below current shell "
            f"size {n} (capacity never shrinks)")
    mask = np.zeros(new_n, dtype=bool)
    live = (np.asarray(shell.node_mask) if shell.node_mask is not None
            else np.ones(n, dtype=bool))
    mask[:n] = live
    pad = new_n - n
    if pad == 0:
        return shell._replace(node_mask=jnp.asarray(mask))

    def pad_rows(a):
        a = np.asarray(a)
        fill = np.repeat(a[:1], pad, axis=0)
        return np.concatenate([a, fill], axis=0)

    def pad_op(m):
        m = np.asarray(m)
        out = np.eye(3 * new_n, dtype=m.dtype)
        out[:3 * n, :3 * n] = m
        return out

    dtype = shell.nodes.dtype
    normals = np.concatenate(
        [np.asarray(shell.normals), np.zeros((pad, 3))], axis=0)
    return PeripheryState(
        nodes=jnp.asarray(pad_rows(shell.nodes), dtype=dtype),
        normals=jnp.asarray(normals, dtype=dtype),
        weights=jnp.asarray(np.concatenate(
            [np.asarray(shell.weights), np.zeros(pad)]), dtype=dtype),
        M_inv=jnp.asarray(pad_op(shell.M_inv), dtype=shell.M_inv.dtype),
        stresslet_plus_complementary=jnp.asarray(
            pad_op(shell.stresslet_plus_complementary),
            dtype=shell.stresslet_plus_complementary.dtype),
        density=jnp.asarray(np.concatenate(
            [np.asarray(shell.density), np.zeros(3 * pad)]), dtype=dtype),
        node_mask=jnp.asarray(mask))


# ------------------------------------------------------------------ operators

def matvec(shell: PeripheryState, x, v_on_shell):
    """A_shell x = (S + N) x + v (`periphery.cpp:38-47`); v is [N, 3].

    Padded quadrature rows (``node_mask``) drop their v contribution so
    they stay on the identity — the flow evaluators produce garbage values
    at the padded placeholder targets."""
    if shell.node_mask is not None:
        v_on_shell = jnp.where(shell.node_mask[:, None],
                               v_on_shell.reshape(-1, 3), 0.0)
    return shell.stresslet_plus_complementary @ x + v_on_shell.reshape(-1)


def apply_preconditioner(shell: PeripheryState, x):
    """P^-1 x = M_inv x (`periphery.cpp:21-29`); applied in M_inv's (possibly
    lower) precision and cast back."""
    return (shell.M_inv @ x.astype(shell.M_inv.dtype)).astype(x.dtype)


def update_RHS(v_on_shell, node_mask=None):
    """RHS = -v_on_shell (`periphery.cpp:86`); padded quadrature rows
    (``node_mask``) get exact-zero RHS so their density solves to zero."""
    if node_mask is not None:
        v_on_shell = jnp.where(node_mask[:, None],
                               v_on_shell.reshape(-1, 3), 0.0)
    return -v_on_shell.reshape(-1)


def flow(shell: PeripheryState, r_trg, density, eta, *, evaluator: str = "direct",
         mesh=None, impl: str = "exact", ewald_plan=None, ewald_anchors=None,
         pair=None, pair_anchors=None):
    """Shell -> target velocities via the double-layer stresslet
    (`periphery.cpp:55-79`): f_dl = 2 eta n (x) rho.

    Evaluator selection rides a `ops.evaluator.PairEvaluator` spec
    (``pair`` + traced ``pair_anchors``) or the legacy loose kwargs.
    ``evaluator="ring"`` (with a mesh) rotates shell-node source blocks around
    the ICI ring — the same pair-evaluator seam as `fibers.container.flow`
    (reference: one evaluator serves all components, `kernels.hpp:78-122`).
    Zero-strength far-point pads make the node count mesh-divisible; callers
    pad the *target* rows (see `System._ring_pad_targets`).

    ``evaluator="ewald"`` (with a plan covering shell nodes + targets) sums
    the double layer in O(N log N) via the free-space Ewald stresslet,
    ``evaluator="tree"`` via the barycentric-treecode stresslet, and
    ``evaluator="spectral"`` via the periodic particle-mesh stresslet
    (`ops.spectral.stresslet_spectral`) — the
    reference's one-evaluator-serves-all design (`periphery.cpp:337-352`
    routes the shell's stresslet through the FMM). The shell's
    SELF-interaction is not computed here in any mode: `System._apply_matvec`
    evaluates this flow at fiber/body rows only, the self block living in
    the dense stored operator.
    """
    from ..ops.evaluator import resolve

    evaluator, impl, ewald_plan, ewald_anchors, pair_anchors = resolve(
        pair, pair_anchors, r_trg.dtype, evaluator, impl, ewald_plan,
        ewald_anchors)
    rho = density.reshape(-1, 3)
    f_dl = 2.0 * eta * shell.normals[:, :, None] * rho[:, None, :]
    if (pair is not None and evaluator == "tree" and pair.plan is not None):
        from ..ops import treecode as tcode

        if pair.plan.depth == 0:
            return kernels.stresslet_direct(shell.nodes, r_trg, f_dl, eta,
                                            impl=impl)
        return tcode._stresslet_tree_impl(pair.plan, pair_anchors,
                                          shell.nodes, r_trg, f_dl, eta)
    if evaluator == "ewald" and ewald_plan is not None:
        from ..ops import ewald as ew

        if ewald_anchors is None:
            ewald_anchors = ew.plan_anchors(ewald_plan, r_trg.dtype)
            ewald_plan = ew.strip_anchors(ewald_plan)
        vel = ew._stresslet_ewald_impl(ewald_plan, ewald_anchors,
                                       shell.nodes, r_trg, f_dl)
        # the screened kernels scale as 1/eta and the plan baked plan.eta in
        return vel * (ewald_plan.eta / eta)
    if (pair is not None and evaluator == "spectral"
            and pair.plan is not None):
        from ..ops import spectral as spec

        vel = spec._stresslet_spectral_impl(pair.plan, pair_anchors,
                                            shell.nodes, r_trg, f_dl)
        return vel * (pair.plan.eta / eta)
    if evaluator == "ring" and mesh is not None:
        src = shell.nodes
        pad = (-src.shape[0]) % mesh.size
        if pad:
            src = jnp.concatenate(
                [src, jnp.full((pad, 3), 1e7, dtype=src.dtype)], axis=0)
            f_dl = jnp.concatenate(
                [f_dl, jnp.zeros((pad, 3, 3), dtype=f_dl.dtype)], axis=0)
        if impl in ("df", "pallas_df"):
            # see fibers.container.flow_multi: "df" = XLA blocks,
            # "pallas_df" = fused Pallas DF tile per chip; cast back to the
            # target dtype like the direct seam
            from ..parallel.ring import ring_stresslet_df

            return ring_stresslet_df(src, r_trg, f_dl, eta, mesh=mesh,
                                     impl=impl).astype(r_trg.dtype)
        from ..parallel.ring import ring_stresslet

        return ring_stresslet(src, r_trg, f_dl, eta, mesh=mesh, impl=impl)
    return kernels.stresslet_direct(shell.nodes, r_trg, f_dl, eta, impl=impl)


def flow_local(shell: PeripheryState, r_loc, r_rep, density, eta, *,
               axis_name, n_dev: int, impl: str = "exact"):
    """`flow` for callers ALREADY INSIDE a `shard_map` over the fiber axis
    (`parallel.spmd`): ``shell`` is this shard's row block (nodes/normals
    node-aligned with ``density``'s [3*N/D] rows).

    Like `fibers.container.flow_multi_local`, two target classes:
    ``r_loc`` (shard-resident rows — fiber nodes) accumulates over the
    rotating shell source blocks with `lax.ppermute`; ``r_rep``
    (replicated rows — body nodes) is one local source-block partial for
    the caller to `psum` — the replication discipline (docs/parallel.md,
    enforced by the `replication` audit check: ringing replicated rows is
    the ring-order-accumulation finding). Returns ``(v_loc,
    v_rep_partial)``. The shell
    SELF-interaction is not computed in any mode — it lives in the dense
    stored operator (`System._apply_matvec`)."""
    from ..parallel.ring import ring_flow_local

    rho = density.reshape(-1, 3)
    f_dl = 2.0 * eta * shell.normals[:, :, None] * rho[:, None, :]
    src = shell.nodes

    v_loc = ring_flow_local("stresslet", impl, r_loc, src, f_dl, eta,
                            axis_name=axis_name, n_dev=n_dev, ring=True)
    v_rep = (ring_flow_local("stresslet", impl, r_rep, src, f_dl, eta,
                             axis_name=axis_name, n_dev=n_dev, ring=False)
             if r_rep is not None else None)
    return v_loc, v_rep


# ------------------------------------------------- shape-specific interactions

def signed_clearance(shape: PeripheryShape, points):
    """[n] signed node-periphery clearance: positive inside (clear of the
    wall), NEGATIVE once a point crosses it — so penetration is visible
    as a magnitude, unlike `check_collision`'s bool (the flight
    recorder's ``min_clearance`` diagnostic, obs.flight).

    sphere: ``radius - |p|``; ellipsoid: the radial distance to the
    cortex point of `check_collision`'s comparison, ``|r_cortex| - |p|``
    (exact on the axes, a radial-ray approximation elsewhere — a
    diagnostic, not a force); generic: +inf (no wall physics, stub
    parity with the zero steric force)."""
    if shape.kind == "sphere":
        return shape.radius - jnp.linalg.norm(points, axis=-1)
    if shape.kind == "ellipsoid":
        a, b, c = shape.abc
        abc = jnp.asarray(shape.abc, dtype=points.dtype)
        r_scaled = points / abc
        r_scaled_mag = jnp.linalg.norm(r_scaled, axis=-1)
        phi = jnp.arctan2(r_scaled[:, 1], r_scaled[:, 0] + 1e-12)
        theta = jnp.arccos(jnp.clip(r_scaled[:, 2] / (1e-12 + r_scaled_mag),
                                    -1, 1))
        sin_t = jnp.sin(theta)
        r_cortex = jnp.stack([a * sin_t * jnp.cos(phi),
                              b * sin_t * jnp.sin(phi),
                              c * jnp.cos(theta)], axis=-1)
        return (jnp.linalg.norm(r_cortex, axis=-1)
                - jnp.linalg.norm(points, axis=-1))
    return jnp.full(points.shape[:-1], jnp.inf, dtype=points.dtype)


def check_collision(shape: PeripheryShape, points, threshold):
    """True if any point crosses the shell (vectorized over [n, 3] points).

    sphere: any |p| >= radius - threshold (`periphery.cpp:126-133`)
    ellipsoid: radial comparison against the threshold-shrunk cortex point
    (`periphery.cpp:204-224`); generic: never collides (stub parity,
    `periphery.cpp:312-319`).
    """
    if shape.kind == "sphere":
        r2 = jnp.sum(points**2, axis=-1)
        return jnp.any(r2 >= (shape.radius - threshold) ** 2)
    if shape.kind == "ellipsoid":
        a, b, c = shape.abc
        abc = jnp.asarray(shape.abc, dtype=points.dtype)
        r_scaled = points / abc
        r_scaled_mag = jnp.linalg.norm(r_scaled, axis=-1)
        phi = jnp.arctan2(r_scaled[:, 1], r_scaled[:, 0] + 1e-12)
        theta = jnp.arccos(jnp.clip(r_scaled[:, 2] / (1e-12 + r_scaled_mag), -1, 1))
        sin_t = jnp.sin(theta)
        r_cortex = jnp.stack([(a - threshold) * sin_t * jnp.cos(phi),
                              (b - threshold) * sin_t * jnp.sin(phi),
                              (c - threshold) * jnp.cos(theta)], axis=-1)
        return jnp.any(jnp.sum(points**2, axis=-1) >= jnp.sum(r_cortex**2, axis=-1))
    return jnp.asarray(False)


def fiber_steric_force(shape: PeripheryShape, points, f_0, l_0, skip_first):
    """Exponential repulsion wall force on fiber nodes [n, 3] -> [n, 3].

    sphere: f = f_0 * dr/|dr| * exp(-(R - r)/l_0) for r < R
    (`periphery.cpp:140-162`); ellipsoid analogue (`periphery.cpp:232-263`);
    generic: zero (stub parity). ``skip_first`` masks the clamped minus-end node.
    """
    n = points.shape[0]
    mask = jnp.arange(n, dtype=jnp.int32) >= jnp.where(skip_first, 1, 0)
    if shape.kind == "sphere":
        r_mag = jnp.linalg.norm(points, axis=-1)
        safe_r = jnp.where(r_mag > 0, r_mag, 1.0)
        u_hat = points / safe_r[:, None]
        dr = points - u_hat * shape.radius
        d = jnp.linalg.norm(dr, axis=-1)
        safe_d = jnp.where(d > 0, d, 1.0)
        f = f_0 * dr / safe_d[:, None] * jnp.exp(-(shape.radius - r_mag) / l_0)[:, None]
        inside = (r_mag < shape.radius) & mask
        return jnp.where(inside[:, None], f, 0.0)
    if shape.kind == "ellipsoid":
        a, b, c = shape.abc
        abc = jnp.asarray(shape.abc, dtype=points.dtype)
        r_scaled = points / abc
        r_scaled_mag = jnp.linalg.norm(r_scaled, axis=-1)
        r_mag = jnp.linalg.norm(points, axis=-1)
        phi = jnp.arctan2(r_scaled[:, 1], r_scaled[:, 0] + 1e-12)
        theta = jnp.arccos(jnp.clip(r_scaled[:, 2] / (1e-12 + r_scaled_mag), -1, 1))
        sin_t = jnp.sin(theta)
        r_cortex = jnp.stack([a * sin_t * jnp.cos(phi),
                              b * sin_t * jnp.sin(phi),
                              c * jnp.cos(theta)], axis=-1)
        r_cortex_mag = jnp.linalg.norm(r_cortex, axis=-1)
        dr = points - r_cortex
        d = jnp.linalg.norm(dr, axis=-1)
        safe_d = jnp.where(d > 0, d, 1.0)
        f = f_0 * dr / safe_d[:, None] * jnp.exp(-(r_cortex_mag - r_mag) / l_0)[:, None]
        inside = (r_mag < r_cortex_mag) & mask
        return jnp.where(inside[:, None], f, 0.0)
    return jnp.zeros_like(points)
