from .quadrature import surface_quadrature_weights  # noqa: F401
from .shapes import ShapeSpec, sphere_shape, ellipsoid_shape, surface_of_revolution_shape  # noqa: F401
