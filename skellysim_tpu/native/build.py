"""Compile-on-first-use loader for the native helpers."""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading

_DIR = os.path.dirname(os.path.abspath(__file__))
_LOCK = threading.Lock()
_CACHE: dict[str, ctypes.CDLL | None] = {}


def load_library(name: str) -> ctypes.CDLL | None:
    """Load `<name>.cpp` as a shared library, compiling if stale.

    Returns None when no working C++ toolchain is available (callers fall back
    to pure Python).
    """
    with _LOCK:
        if name in _CACHE:
            return _CACHE[name]
        src = os.path.join(_DIR, f"{name}.cpp")
        so = os.path.join(_DIR, f"_{name}.so")
        lib = None
        try:
            if (not os.path.exists(so)
                    or os.path.getmtime(so) < os.path.getmtime(src)):
                # build to a process-unique temp path and rename atomically so
                # concurrent processes never dlopen a half-written ELF
                tmp = f"{so}.{os.getpid()}.tmp"
                subprocess.run(
                    ["g++", "-O3", "-shared", "-fPIC", "-std=c++17", src, "-o", tmp],
                    check=True, capture_output=True)
                os.replace(tmp, so)
            lib = ctypes.CDLL(so)
        except (OSError, subprocess.CalledProcessError):
            lib = None
        _CACHE[name] = lib
        return lib
