"""Native (C++) runtime helpers, loaded lazily via ctypes.

Each helper ships as a single .cpp compiled on first use with the system g++
into a shared object cached next to the source. Every native path has a pure
Python fallback so the framework works without a toolchain.
"""

from .build import load_library
