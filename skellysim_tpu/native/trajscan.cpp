// Native trajectory frame scanner.
//
// TPU-native analogue of the reference's C++ index builder
// (/root/reference/src/core/trajectory_reader.cpp:78-124): streams through a
// msgpack trajectory file without decoding payloads, recording the byte offset
// and `time` value of every top-level frame map. Used by the Python
// TrajectoryReader through ctypes; building the index natively matters for
// multi-GB trajectories where a Python msgpack skip-walk is the bottleneck.
//
// Build: g++ -O3 -shared -fPIC trajscan.cpp -o _trajscan.so

#include <cstdint>
#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <vector>

namespace {

struct Cursor {
    const uint8_t *p;
    const uint8_t *end;
    bool ok = true;

    bool need(size_t n) {
        if ((size_t)(end - p) < n) {
            ok = false;
            return false;
        }
        return true;
    }
    uint8_t u8() { return *p++; }
    uint64_t be(int n) {
        uint64_t v = 0;
        for (int i = 0; i < n; ++i)
            v = (v << 8) | *p++;
        return v;
    }
};

// Skip one msgpack object. Returns false on truncated/invalid input.
bool skip_obj(Cursor &c);

bool skip_n(Cursor &c, uint64_t n) {
    for (uint64_t i = 0; i < n; ++i)
        if (!skip_obj(c))
            return false;
    return true;
}

bool skip_obj(Cursor &c) {
    if (!c.need(1))
        return false;
    uint8_t b = c.u8();
    if (b <= 0x7f || b >= 0xe0 || b == 0xc0 || b == 0xc2 || b == 0xc3)
        return true;                                  // fixint / nil / bool
    if (b >= 0x80 && b <= 0x8f)
        return skip_n(c, 2ull * (b & 0x0f));          // fixmap
    if (b >= 0x90 && b <= 0x9f)
        return skip_n(c, b & 0x0f);                   // fixarray
    if (b >= 0xa0 && b <= 0xbf) {                     // fixstr
        uint64_t n = b & 0x1f;
        if (!c.need(n)) return false;
        c.p += n;
        return true;
    }
    switch (b) {
    case 0xc4: case 0xd9: {                           // bin8 / str8
        if (!c.need(1)) return false;
        uint64_t n = c.be(1);
        if (!c.need(n)) return false;
        c.p += n;
        return true;
    }
    case 0xc5: case 0xda: {                           // bin16 / str16
        if (!c.need(2)) return false;
        uint64_t n = c.be(2);
        if (!c.need(n)) return false;
        c.p += n;
        return true;
    }
    case 0xc6: case 0xdb: {                           // bin32 / str32
        if (!c.need(4)) return false;
        uint64_t n = c.be(4);
        if (!c.need(n)) return false;
        c.p += n;
        return true;
    }
    case 0xc7: case 0xc8: case 0xc9: {                // ext8/16/32
        int ls = b == 0xc7 ? 1 : b == 0xc8 ? 2 : 4;
        if (!c.need(ls)) return false;
        uint64_t n = c.be(ls);
        if (!c.need(n + 1)) return false;
        c.p += n + 1;
        return true;
    }
    case 0xca: if (!c.need(4)) return false; c.p += 4; return true;  // f32
    case 0xcb: if (!c.need(8)) return false; c.p += 8; return true;  // f64
    case 0xcc: case 0xd0: if (!c.need(1)) return false; c.p += 1; return true;
    case 0xcd: case 0xd1: if (!c.need(2)) return false; c.p += 2; return true;
    case 0xce: case 0xd2: if (!c.need(4)) return false; c.p += 4; return true;
    case 0xcf: case 0xd3: if (!c.need(8)) return false; c.p += 8; return true;
    case 0xd4: case 0xd5: case 0xd6: case 0xd7: case 0xd8: {         // fixext
        uint64_t n = 1ull << (b - 0xd4);
        if (!c.need(n + 1)) return false;
        c.p += n + 1;
        return true;
    }
    case 0xdc: {                                       // array16
        if (!c.need(2)) return false;
        return skip_n(c, c.be(2));
    }
    case 0xdd: {                                       // array32
        if (!c.need(4)) return false;
        return skip_n(c, c.be(4));
    }
    case 0xde: {                                       // map16
        if (!c.need(2)) return false;
        return skip_n(c, 2 * c.be(2));
    }
    case 0xdf: {                                       // map32
        if (!c.need(4)) return false;
        return skip_n(c, 2 * c.be(4));
    }
    default:
        return false;                                  // 0xc1 never used
    }
}

// Parse a number-valued object into *out (only forms the writer emits for time).
bool read_number(Cursor &c, double *out) {
    if (!c.need(1))
        return false;
    uint8_t b = c.u8();
    if (b <= 0x7f) { *out = b; return true; }
    if (b >= 0xe0) { *out = (int8_t)b; return true; }
    switch (b) {
    case 0xca: {
        if (!c.need(4)) return false;
        uint32_t v = (uint32_t)c.be(4);
        float f;
        memcpy(&f, &v, 4);
        *out = f;
        return true;
    }
    case 0xcb: {
        if (!c.need(8)) return false;
        uint64_t v = c.be(8);
        double d;
        memcpy(&d, &v, 8);
        *out = d;
        return true;
    }
    case 0xcc: if (!c.need(1)) return false; *out = (double)c.be(1); return true;
    case 0xcd: if (!c.need(2)) return false; *out = (double)c.be(2); return true;
    case 0xce: if (!c.need(4)) return false; *out = (double)c.be(4); return true;
    case 0xcf: if (!c.need(8)) return false; *out = (double)c.be(8); return true;
    case 0xd0: if (!c.need(1)) return false; *out = (int8_t)c.be(1); return true;
    case 0xd1: if (!c.need(2)) return false; *out = (int16_t)c.be(2); return true;
    case 0xd2: if (!c.need(4)) return false; *out = (int32_t)c.be(4); return true;
    case 0xd3: if (!c.need(8)) return false; *out = (int64_t)c.be(8); return true;
    default:
        return false;
    }
}

// Read a map header; returns pair count or -1 if the object is not a map.
int64_t map_header(Cursor &c) {
    if (!c.need(1))
        return -1;
    uint8_t b = c.u8();
    if (b >= 0x80 && b <= 0x8f)
        return b & 0x0f;
    if (b == 0xde) {
        if (!c.need(2)) return -1;
        return (int64_t)c.be(2);
    }
    if (b == 0xdf) {
        if (!c.need(4)) return -1;
        return (int64_t)c.be(4);
    }
    return -1;
}

// Match a fixstr/str8 key against "time" without allocating.
bool key_is_time(Cursor &c, bool *matched) {
    if (!c.need(1))
        return false;
    uint8_t b = c.u8();
    uint64_t n;
    if (b >= 0xa0 && b <= 0xbf)
        n = b & 0x1f;
    else if (b == 0xd9) {
        if (!c.need(1)) return false;
        n = c.be(1);
    } else {
        c.p--;  // not a string key: skip generically
        *matched = false;
        return skip_obj(c);
    }
    if (!c.need(n))
        return false;
    *matched = (n == 4 && memcmp(c.p, "time", 4) == 0);
    c.p += n;
    return true;
}

} // namespace

extern "C" {

// Scan `buf[0:len)` for top-level maps carrying a "time" key. Fills
// freshly-malloc'd arrays of frame byte offsets and times; returns the frame
// count, or -1 on malformed input. A trailing partial frame is ignored,
// matching the reference index builder's OutOfData handling.
int64_t trajscan_buffer(const uint8_t *buf, uint64_t len, uint64_t **offsets_out,
                        double **times_out) {
    Cursor c{buf, buf + len};
    std::vector<uint64_t> offsets;
    std::vector<double> times;

    while (c.p < c.end) {
        const uint8_t *start = c.p;
        Cursor probe = c;
        int64_t pairs = map_header(probe);
        bool has_time = false;
        double t = 0.0;
        if (pairs >= 0) {
            bool good = true;
            for (int64_t i = 0; i < pairs && good; ++i) {
                bool is_time = false;
                good = key_is_time(probe, &is_time);
                if (!good)
                    break;
                if (is_time) {
                    good = read_number(probe, &t);
                    has_time = good;
                } else {
                    good = skip_obj(probe);
                }
            }
            if (!good)
                break;  // truncated trailing frame
            c.p = probe.p;
        } else {
            if (!skip_obj(c))
                break;
        }
        if (has_time) {
            offsets.push_back((uint64_t)(start - buf));
            times.push_back(t);
        }
    }

    uint64_t n = offsets.size();
    *offsets_out = (uint64_t *)malloc(sizeof(uint64_t) * (n ? n : 1));
    *times_out = (double *)malloc(sizeof(double) * (n ? n : 1));
    if (n) {
        memcpy(*offsets_out, offsets.data(), sizeof(uint64_t) * n);
        memcpy(*times_out, times.data(), sizeof(double) * n);
    }
    return (int64_t)n;
}

void trajscan_free(void *p) { free(p); }

} // extern "C"
