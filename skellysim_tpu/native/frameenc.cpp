// Native trajectory fiber-frame encoder.
//
// TPU-native analogue of the reference's C++ frame serialization
// (/root/reference/src/core/system.cpp:100-177 packs per-rank msgpack fiber
// maps in C++): emits the msgpack bytes of the active-fiber map array,
// byte-identical to the Python `io.trajectory._fiber_array_bytes` (which is
// itself wire-identical to `msgpack.packb` of the object frame). At the
// 10k-fiber BASELINE scale this turns the remaining ~0.1 s Python encode into
// a few milliseconds of memcpy-dominated work.
//
// Wire contract per fiber (trajectory v1, `include/io_maps.hpp:30-38` /
// `fiber_finite_difference.hpp:160-161` field set): a 12-entry map
//   n_nodes_ (uint), radius_/length_/length_prev_/bending_rigidity_/
//   penalty_param_/force_scale_/beta_tstep_ (float64),
//   binding_site_ ([int, int]), tension_ (__eigen__ n x 1),
//   x_ (__eigen__ 3 x n, row-major [n,3] ravel), minus_clamped_ (bool).
//
// Build: g++ -O3 -shared -fPIC frameenc.cpp -o _frameenc.so

#include <cstdint>
#include <cstdlib>
#include <cstring>
#include <vector>

namespace {

struct Buf {
    std::vector<uint8_t> b;

    void u8(uint8_t v) { b.push_back(v); }
    void raw(const void *p, size_t n) {
        const uint8_t *q = (const uint8_t *)p;
        b.insert(b.end(), q, q + n);
    }
    void be16(uint16_t v) {
        u8(v >> 8);
        u8(v & 0xff);
    }
    void be32(uint32_t v) {
        u8(v >> 24);
        u8((v >> 16) & 0xff);
        u8((v >> 8) & 0xff);
        u8(v & 0xff);
    }

    // fixstr only (every key/tag here is < 32 chars)
    void str(const char *s) {
        size_t n = strlen(s);
        u8(0xa0 | (uint8_t)n);
        raw(s, n);
    }

    // matches msgpack-python's minimal int encoding
    void sint(int64_t v) {
        if (v >= 0) {
            if (v < 128) u8((uint8_t)v);
            else if (v < 256) { u8(0xcc); u8((uint8_t)v); }
            else if (v < 65536) { u8(0xcd); be16((uint16_t)v); }
            else { u8(0xce); be32((uint32_t)v); }
        } else {
            if (v >= -32) u8((uint8_t)(int8_t)v);
            else if (v >= -128) { u8(0xd0); u8((uint8_t)(int8_t)v); }
            else if (v >= -32768) { u8(0xd1); be16((uint16_t)(int16_t)v); }
            else { u8(0xd2); be32((uint32_t)(int32_t)v); }
        }
    }

    void f64(double v) {
        u8(0xcb);
        uint64_t bits;
        memcpy(&bits, &v, 8);
        for (int i = 7; i >= 0; --i)
            u8((bits >> (8 * i)) & 0xff);
    }

    void arr_hdr(uint64_t n) {
        if (n < 16) u8(0x90 | (uint8_t)n);
        else if (n < 65536) { u8(0xdc); be16((uint16_t)n); }
        else { u8(0xdd); be32((uint32_t)n); }
    }

    void map_hdr(uint64_t n) {
        if (n < 16) u8(0x80 | (uint8_t)n);
        else if (n < 65536) { u8(0xde); be16((uint16_t)n); }
        else { u8(0xdf); be32((uint32_t)n); }
    }

    void eigen(const double *data, int64_t rows, int64_t cols, int64_t count) {
        arr_hdr(3 + count);
        str("__eigen__");
        sint(rows);
        sint(cols);
        for (int64_t i = 0; i < count; ++i)
            f64(data[i]);
    }
};

} // namespace

extern "C" {

// Encode the active-fiber map array. Scalar fields are [nf] doubles; x is
// [nf, n, 3] and tension [nf, n], both row-major contiguous; binding is
// [nf, 2] int32; active/minus_clamped are [nf] uint8. The returned buffer is
// malloc'd; free with frameenc_free.
int64_t frameenc_fibers(const double *x, const double *tension,
                        const double *radius, const double *length,
                        const double *length_prev, const double *bending,
                        const double *penalty, const double *force_scale,
                        const double *beta, const int32_t *binding,
                        const uint8_t *active, const uint8_t *minus_clamped,
                        int64_t nf, int64_t n, uint8_t **out,
                        uint64_t *out_len) {
    if (nf < 0 || n <= 0 || !out || !out_len)
        return -1;
    int64_t n_active = 0;
    for (int64_t i = 0; i < nf; ++i)
        n_active += active[i] ? 1 : 0;

    Buf buf;
    // ~9 bytes per double + map overhead; reserve once
    buf.b.reserve(64 + (size_t)n_active * (200 + 9 * (size_t)(4 * n)));
    buf.arr_hdr(n_active);
    for (int64_t i = 0; i < nf; ++i) {
        if (!active[i])
            continue;
        buf.map_hdr(12);
        buf.str("n_nodes_");
        buf.sint(n);
        buf.str("radius_");
        buf.f64(radius[i]);
        buf.str("length_");
        buf.f64(length[i]);
        buf.str("length_prev_");
        buf.f64(length_prev[i]);
        buf.str("bending_rigidity_");
        buf.f64(bending[i]);
        buf.str("penalty_param_");
        buf.f64(penalty[i]);
        buf.str("force_scale_");
        buf.f64(force_scale[i]);
        buf.str("beta_tstep_");
        buf.f64(beta[i]);
        buf.str("binding_site_");
        buf.arr_hdr(2);
        buf.sint(binding[2 * i]);
        buf.sint(binding[2 * i + 1]);
        buf.str("tension_");
        buf.eigen(tension + i * n, n, 1, n);
        buf.str("x_");
        buf.eigen(x + i * 3 * n, 3, n, 3 * n);
        buf.str("minus_clamped_");
        buf.u8(minus_clamped[i] ? 0xc3 : 0xc2);
    }

    uint8_t *mem = (uint8_t *)malloc(buf.b.size());
    if (!mem)
        return -1;
    memcpy(mem, buf.b.data(), buf.b.size());
    *out = mem;
    *out_len = buf.b.size();
    return n_active;
}

void frameenc_free(uint8_t *p) { free(p); }

} // extern "C"
