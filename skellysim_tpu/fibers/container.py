"""Batched fiber state + vmapped operator assembly.

TPU-native replacement for `FiberContainerFiniteDifference`
(`/root/reference/src/core/fiber_container_finite_difference.cpp`): instead of a
`std::list<FiberFiniteDifference>` with per-fiber loops and MPI round-robin
distribution, all fibers of one resolution live in dense batched arrays
([n_fib, n_nodes, ...]) and every per-fiber operation is a `jax.vmap` of the
single-fiber functions in `fd_fiber`. The fiber batch axis is the data-parallel
axis to shard over a device mesh (the analogue of the reference's rank
decomposition, `fiber_container_finite_difference.cpp:98-121`).

An `active` mask supports dynamic instability (nucleation/catastrophe changes
the live fiber count without reshaping the arrays): inactive slots contribute
zero flow/force/error and solve an identity system. How dead slots are
neutralized (select-not-multiply, sentinels, origin-pinned positions) is
docs/audit.md "Masking discipline" — proven per program by the `mask`
audit check, not restated here.
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp
import numpy as np

from ..ops import kernels
from . import fd_fiber
from .fd_fiber import FiberScalars
from .matrices import FibMats, get_mats, padded_rt_mats, typed


class FiberGroup(NamedTuple):
    """State of a batch of same-resolution fibers (a pytree; [nf] leading axis)."""

    x: jnp.ndarray             # [nf, n, 3] node positions
    tension: jnp.ndarray       # [nf, n]
    length: jnp.ndarray        # [nf] target length
    length_prev: jnp.ndarray   # [nf] last accepted length
    bending_rigidity: jnp.ndarray
    radius: jnp.ndarray
    penalty: jnp.ndarray
    beta_tstep: jnp.ndarray
    force_scale: jnp.ndarray
    v_growth: jnp.ndarray
    minus_clamped: jnp.ndarray  # bool [nf]
    plus_pinned: jnp.ndarray    # bool [nf]
    binding_body: jnp.ndarray   # int32 [nf], -1 = unbound
    binding_site: jnp.ndarray   # int32 [nf]
    active: jnp.ndarray         # bool [nf]
    #: int32 [nf] original config-order rank. With multiple resolution
    #: buckets the solver layout is bucket-major; trajectory writers sort
    #: fibers back to this rank so the wire stays reference-ordered
    #: (`trajectory_reader.cpp` reads fibers in config order).
    config_rank: jnp.ndarray = None
    #: runtime node-capacity mats (`matrices.FibMatsRT`) or None. When set,
    #: the trailing node rows beyond the live count are masked inert
    #: capacity (skelly-bucket's node axis): the live resolution's
    #: differentiation matrices ride the pytree as DATA, so scenes with
    #: different live node counts share one compiled program at the same
    #: node capacity. None (the default) keeps the static per-resolution
    #: constants — bit-identical to the pre-bucket programs.
    rt_mats: object = None

    @property
    def n_fibers(self) -> int:
        return self.x.shape[0]

    @property
    def n_nodes(self) -> int:
        return self.x.shape[1]

    @property
    def mats(self):
        if self.rt_mats is not None:
            return typed(self.rt_mats, self.x.dtype)
        # cast to the state dtype so f32 groups never promote to f64 under x64
        return typed(get_mats(self.n_nodes), self.x.dtype)

    def scalars(self) -> FiberScalars:
        return FiberScalars(self.length, self.length_prev, self.bending_rigidity,
                            self.radius, self.penalty, self.beta_tstep, self.v_growth)


class FiberCaches(NamedTuple):
    """Per-step derived quantities (`update_cache_variables` + BC application)."""

    xs: jnp.ndarray         # [nf, n, 3]
    xss: jnp.ndarray
    xsss: jnp.ndarray
    xssss: jnp.ndarray
    #: [nf, 3n, 3n] dense self-mobility (interleaved-xyz 2-D layout: a
    #: [.., n, 3]-shaped leaf would be tile-padded 3 -> 128 by XLA, a 42x
    #: HBM blowup at large fiber counts)
    stokeslet: jnp.ndarray
    force_op: jnp.ndarray   # [nf, 3n, 4n]
    A_bc: jnp.ndarray       # [nf, 4n, 4n] (BC-applied)
    RHS: jnp.ndarray        # [nf, 4n] (BC-applied)
    lu: jnp.ndarray         # batched LU factors of A_bc
    piv: jnp.ndarray


def make_group(x, lengths, bending_rigidity, radius, *, eta=None,
               penalty=fd_fiber.DEFAULT_PENALTY, beta_tstep=fd_fiber.DEFAULT_BETA_TSTEP,
               force_scale=0.0, v_growth=0.0, minus_clamped=False,
               binding_body=None, binding_site=None, config_rank=None,
               dtype=jnp.float64) -> FiberGroup:
    """Build a FiberGroup from [nf, n, 3] positions and broadcastable per-fiber params."""
    x = jnp.asarray(x, dtype=dtype)
    nf, n = x.shape[0], x.shape[1]
    get_mats(n)  # validate resolution

    def vec(v, d=dtype):
        return jnp.broadcast_to(jnp.asarray(v, dtype=d), (nf,))

    return FiberGroup(
        x=x,
        tension=jnp.zeros((nf, n), dtype=dtype),
        length=vec(lengths), length_prev=vec(lengths),
        bending_rigidity=vec(bending_rigidity), radius=vec(radius),
        penalty=vec(penalty), beta_tstep=vec(beta_tstep),
        force_scale=vec(force_scale), v_growth=vec(v_growth),
        minus_clamped=vec(minus_clamped, jnp.bool_),
        plus_pinned=jnp.zeros(nf, dtype=jnp.bool_),
        binding_body=vec(-1 if binding_body is None else binding_body, jnp.int32),
        binding_site=vec(-1 if binding_site is None else binding_site, jnp.int32),
        active=jnp.ones(nf, dtype=jnp.bool_),
        config_rank=(jnp.arange(nf, dtype=jnp.int32) if config_rank is None
                     else jnp.asarray(config_rank, dtype=jnp.int32)),
    )


def as_buckets(fibers) -> tuple:
    """Normalize a fibers field (None | FiberGroup | iterable of groups) to
    a tuple of resolution buckets. `FiberGroup` is itself a NamedTuple, so
    the single-group test must precede any generic tuple handling."""
    if fibers is None:
        return ()
    if isinstance(fibers, FiberGroup):
        return (fibers,)
    return tuple(fibers)


def node_positions(group: FiberGroup) -> jnp.ndarray:
    """[nf * n, 3] flattened node positions (`get_local_node_positions`)."""
    return group.x.reshape(-1, 3)


def live_node_count(group: FiberGroup) -> int:
    """Host-side live node count per fiber (== n_nodes without node padding)."""
    if group.rt_mats is None:
        return group.n_nodes
    return int(np.asarray(group.rt_mats.node_mask).sum())


def node_mask_np(group: FiberGroup) -> np.ndarray:
    """Host-side [n] bool node mask (all-True without node padding)."""
    if group.rt_mats is None:
        return np.ones(group.n_nodes, dtype=bool)
    return np.asarray(group.rt_mats.node_mask)


def strip_node_padding(group: FiberGroup) -> FiberGroup:
    """Group with masked padding node rows removed (live prefix only) and
    runtime mats dropped — the WIRE view: trajectory frames carry live
    nodes only, exactly like they carry active fibers only, so a padded
    run's output is byte-identical to an unpadded run's."""
    if group.rt_mats is None:
        return group
    nl = live_node_count(group)
    return group._replace(x=group.x[:, :nl], tension=group.tension[:, :nl],
                          rt_mats=None)


def node_active_flat(group: FiberGroup) -> jnp.ndarray:
    """Traced [nf * n] bool: node row is live AND its fiber is active —
    the per-node generalization of the `active` mask (masked-node
    discipline; consumed by `_spread_inactive` and the fast planners)."""
    act = jnp.repeat(group.active, group.n_nodes)
    if group.rt_mats is not None:
        act = act & jnp.tile(group.rt_mats.node_mask, group.n_fibers)
    return act


def update_cache(group: FiberGroup, dt, eta) -> FiberCaches:
    """Derivatives, self-mobility, pre-BC operator, force operator (vmapped).

    Mirror of `update_cache_variables` (`fiber_container_finite_difference.cpp:147-157`)
    minus the BC/RHS stage, which needs the explicit flow field (see
    `update_rhs_and_bc`).
    """
    mats = group.mats
    sc = group.scalars()

    xs, xss, xsss, xssss = jax.vmap(
        lambda x, lp: fd_fiber.derivatives(x, lp, mats))(group.x, group.length_prev)

    n3 = 3 * group.n_nodes
    stokeslet = jax.vmap(
        lambda x: kernels.oseen_tensor(x, x, eta).reshape(n3, n3))(group.x)
    force_op = jax.vmap(
        lambda a, b, s: fd_fiber.force_operator(a, b, eta, s, mats))(xs, xss, sc)

    zeros44 = jnp.zeros((group.n_fibers, 4 * group.n_nodes, 4 * group.n_nodes), dtype=group.x.dtype)
    zeros4 = jnp.zeros((group.n_fibers, 4 * group.n_nodes), dtype=group.x.dtype)
    return FiberCaches(xs=xs, xss=xss, xsss=xsss, xssss=xssss, stokeslet=stokeslet,
                       force_op=force_op, A_bc=zeros44, RHS=zeros4,
                       lu=zeros44, piv=jnp.zeros((group.n_fibers, 4 * group.n_nodes), dtype=jnp.int32))


def update_rhs_and_bc(group: FiberGroup, caches: FiberCaches, dt, eta,
                      v_on_fibers, f_total, f_ext,
                      precond_dtype=None) -> FiberCaches:
    """Assemble BC-applied A/RHS and the batched LU preconditioner.

    Mirrors the prep sequence of `System::prep_state_for_solver`
    (`system.cpp:448-453`): RHS uses the total force (motor + external), the BC
    rows use only the external force. ``precond_dtype`` stores the LU factors
    in a lower precision (f32 for TPU, whose LuDecomposition is f32-only)
    while A/RHS stay in the state dtype.
    """
    mats = group.mats
    sc = group.scalars()

    def one(x, xs, xss, xsss, s, mc, pp, v, ft, fe):
        A = fd_fiber.build_A(xs, xss, xsss, dt, eta, s, mats)
        RHS = fd_fiber.build_RHS(x, xs, xss, dt, eta, s, mats, flow=v, f_external=ft)
        A_bc, RHS_bc = fd_fiber.apply_bc_rectangular(
            A, RHS, x, xs, xss, dt, eta, s, mats, mc, pp, v_on_fiber=v, f_on_fiber=fe)
        # inactive slots solve the identity so the LU stays well-posed
        eye = jnp.eye(A_bc.shape[0], dtype=A_bc.dtype)
        return A_bc, RHS_bc, eye

    A_bc, RHS_bc, eye = jax.vmap(one)(
        group.x, caches.xs, caches.xss, caches.xsss, sc,
        group.minus_clamped, group.plus_pinned, v_on_fibers, f_total, f_ext)
    act = group.active[:, None, None]
    A_bc = jnp.where(act, A_bc, eye)
    RHS_bc = jnp.where(group.active[:, None], RHS_bc, 0.0)

    A_lu = A_bc if precond_dtype is None else A_bc.astype(precond_dtype)
    lu, piv = jax.vmap(jax.scipy.linalg.lu_factor)(A_lu)
    return caches._replace(A_bc=A_bc, RHS=RHS_bc, lu=lu, piv=piv)


def weighted_forces(group: FiberGroup, forces) -> jnp.ndarray:
    """Quadrature-weighted node forces for the all-to-all flow: 0.5 * L * w0 * f.

    (`fiber_container_finite_difference.cpp:185-192`); inactive fibers weigh zero.
    """
    w0 = jnp.asarray(group.mats.weights0, dtype=group.x.dtype)
    w = 0.5 * group.length[:, None] * w0[None, :]
    # select AFTER the product: zeroing only the weight would leave
    # 0 * inf = NaN if an inactive slot's force bits were nonfinite
    # (docs/audit.md "Masking discipline")
    return jnp.where(group.active[:, None, None], w[:, :, None] * forces,
                     0.0)


def flow(group: FiberGroup, caches: FiberCaches, r_trg, forces, eta,
         subtract_self: bool = True, evaluator: str = "direct",
         mesh=None, impl: str = "exact", ewald_plan=None,
         ewald_anchors=None, pair=None, pair_anchors=None) -> jnp.ndarray:
    """Velocity at targets from all fiber nodes (`flow`, `:172-214`).

    ``forces`` is [nf, n, 3]; when ``subtract_self`` the first nf*n targets are
    assumed to be the fiber nodes themselves and each fiber's dense
    self-interaction is subtracted (it is handled by the SBT mobility instead).
    Evaluator selection rides a `ops.evaluator.PairEvaluator` spec
    (``pair`` + traced ``pair_anchors``) — the reference's pair_evaluator
    seam (`fiber_container_base.cpp:20-33`); a spec carrying a
    `ops.treecode.TreePlan` sums through the barycentric treecode. The
    legacy loose kwargs remain for direct callers of the older paths only:
    ``evaluator="ring"`` (with a mesh) rotates source blocks around the ICI
    ring instead of the GSPMD all-gather, ``evaluator="ewald"`` (with an
    `ops.ewald.EwaldPlan`) sums on the spectral grid; the treecode has no
    loose spelling — it is reachable only via the spec.
    """
    return flow_multi((group,), (caches,), r_trg, (forces,), eta,
                      subtract_self=subtract_self, evaluator=evaluator,
                      mesh=mesh, impl=impl, ewald_plan=ewald_plan,
                      ewald_anchors=ewald_anchors, pair=pair,
                      pair_anchors=pair_anchors)


def _spread_inactive(buckets, pos, fills):
    """Replace inactive slots' (replicated) node rows with the planner's
    spread fill sequence: inactive slots replicate slot 0 (`grow_capacity`),
    which would pile their nodes into one cell/leaf and blow up the fast
    plans' static bucket capacity; their weighted forces are zero, so only
    occupancy changes. Indexed by compacted rank among the inactive slots
    so the runtime fill set is exactly the first-n_fill sequence prefix the
    planner counted occupancy for — raw slot indices would select an
    arbitrary subsequence whose phases can locally align and overflow the
    planned capacity (silent point eviction). Padded node rows of ACTIVE
    fibers (skelly-bucket's node axis) are fill slots too — same zero
    weighted force, same occupancy-only role."""
    act = jnp.concatenate([node_active_flat(g) for g in buckets])
    rank = jnp.clip(jnp.cumsum(~act) - 1, 0, None)
    return jnp.where(act[:, None], pos, fills[rank])


def flow_multi(buckets, caches_list, r_trg, forces_list, eta,
               subtract_self: bool = True, evaluator: str = "direct",
               mesh=None, impl: str = "exact", ewald_plan=None,
               ewald_anchors=None, pair=None,
               pair_anchors=None) -> jnp.ndarray:
    """`flow` over a tuple of resolution buckets in ONE evaluator pass.

    The TPU answer to the reference's mixed-resolution `std::list` container
    (`fiber_container_finite_difference.cpp:519-562`): each resolution is a
    dense vmapped bucket, and the all-to-all flow concatenates every
    bucket's sources so the pair evaluator (dense tile, ICI ring, Ewald
    grid, or treecode) runs once over the union instead of once per
    bucket. When ``subtract_self`` the leading targets must be the
    concatenated fiber nodes in bucket order; each bucket's dense
    self-interaction is subtracted at its own slice.

    ``pair`` (a `ops.evaluator.PairEvaluator`) supersedes the loose
    ``evaluator``/``impl``/``ewald_plan`` kwargs, which remain for direct
    callers; when ``pair_anchors`` is None the plan's own stored anchors
    are materialized (so pass anchors explicitly for stripped plans).
    """
    from ..ops.evaluator import resolve

    evaluator, impl, ewald_plan, ewald_anchors, pair_anchors = resolve(
        pair, pair_anchors, r_trg.dtype, evaluator, impl, ewald_plan,
        ewald_anchors)
    tree_plan = pair.plan if (pair is not None
                              and pair.evaluator == "tree") else None
    spectral_plan = pair.plan if (pair is not None
                                  and pair.evaluator == "spectral") else None
    pos = jnp.concatenate([node_positions(g) for g in buckets], axis=0)
    wf = jnp.concatenate([weighted_forces(g, f).reshape(-1, 3)
                          for g, f in zip(buckets, forces_list)], axis=0)
    # dead slots' weighted forces are exact zeros, so their positions are
    # occupancy-only: pin them to the origin so no garbage coordinate ever
    # enters a pair kernel (a nonfinite stale position would turn the
    # zero-force product into NaN — docs/audit.md "Masking discipline").
    # The fast planners re-fill them with spread anchors (`_spread_inactive`)
    act = jnp.concatenate([node_active_flat(g) for g in buckets])
    pos = jnp.where(act[:, None], pos, 0.0)
    n_fib_nodes = pos.shape[0]
    if subtract_self:
        # keep the leading self targets consistent with the pinned sources
        r_trg = jnp.concatenate([pos, r_trg[n_fib_nodes:]], axis=0)
    if evaluator == "ring" and mesh is not None:
        if impl in ("df", "pallas_df"):
            # the DF ring entry point serves both spellings: "df" runs the
            # XLA blocks, "pallas_df" the fused Pallas DF tile per chip.
            # Cast back to the target dtype like the direct seam — the f64
            # ring output would otherwise promote an f32 solve's pipeline
            from ..parallel.ring import ring_stokeslet_df

            vel = ring_stokeslet_df(pos, r_trg, wf, eta, mesh=mesh,
                                    impl=impl).astype(r_trg.dtype)
        else:
            from ..parallel.ring import ring_stokeslet

            vel = ring_stokeslet(pos, r_trg, wf, eta, mesh=mesh, impl=impl)
    elif evaluator == "ewald" and ewald_plan is not None:
        from ..ops import ewald as ew

        if ewald_anchors is None:
            ewald_anchors = ew.plan_anchors(ewald_plan, r_trg.dtype)
            ewald_plan = ew.strip_anchors(ewald_plan)
        # the plan reserved fill room for inactive slots
        # (`plan_ewald(n_fill=...)`; see `_spread_inactive`)
        fills = ew.fill_positions(ewald_plan, ewald_anchors[1],
                                  n_fib_nodes, pos.dtype)
        pos = _spread_inactive(buckets, pos, fills)
        n_self = n_fib_nodes if subtract_self else 0
        if n_self:
            # the leading targets are the fiber nodes: keep them consistent
            # with the (spread) source positions so self pairs stay exact
            r_trg = jnp.concatenate([pos, r_trg[n_self:]], axis=0)
        vel = ew._stokeslet_ewald_impl(ewald_plan, ewald_anchors, pos, r_trg,
                                       wf, n_self)
        # the kernel scales as 1/eta and the plan baked plan.eta in; honor
        # this call's eta like the direct/ring branches do
        vel = vel * (ewald_plan.eta / eta)
    elif evaluator == "spectral" and spectral_plan is not None:
        from ..ops import spectral as spec

        # same fill discipline as the ewald branch: the plan reserved
        # occupancy room for inactive slots (`plan_spectral(n_fill=...)`)
        fills = spec.fill_positions(spectral_plan, pair_anchors[1],
                                    n_fib_nodes, pos.dtype)
        pos = _spread_inactive(buckets, pos, fills)
        n_self = n_fib_nodes if subtract_self else 0
        if n_self:
            r_trg = jnp.concatenate([pos, r_trg[n_self:]], axis=0)
        vel = spec._stokeslet_spectral_impl(spectral_plan, pair_anchors, pos,
                                            r_trg, wf, n_self)
        # the kernel scales as 1/eta and the plan baked plan.eta in
        vel = vel * (spectral_plan.eta / eta)
    elif evaluator == "tree" and tree_plan is not None:
        from ..ops import treecode as tcode

        fills = tcode.fill_positions(tree_plan, pair_anchors[0],
                                     n_fib_nodes, pos.dtype)
        pos = _spread_inactive(buckets, pos, fills)
        if subtract_self:
            # keep the leading (fiber-node) targets consistent with the
            # spread source positions so self pairs stay exactly coincident
            # (the treecode's near tile drops them like the dense kernel)
            r_trg = jnp.concatenate([pos, r_trg[n_fib_nodes:]], axis=0)
        if tree_plan.depth == 0:
            vel = kernels.stokeslet_direct(pos, r_trg, wf, eta, impl=impl)
        else:
            vel = tcode._stokeslet_tree_impl(tree_plan, pair_anchors, pos,
                                             r_trg, wf, eta)
    else:
        vel = kernels.stokeslet_direct(pos, r_trg, wf, eta, impl=impl)
    if subtract_self:
        off = 0
        for g, caches in zip(buckets, caches_list):
            nfn = g.n_fibers * g.n_nodes
            self_vel = jnp.einsum("fij,fj->fi", caches.stokeslet,
                                  wf[off:off + nfn].reshape(g.n_fibers, -1))
            vel = vel.at[off:off + nfn].add(-self_vel.reshape(-1, 3))
            off += nfn
    return vel


def flow_multi_local(buckets, caches_list, forces_list, r_loc, r_rep, eta, *,
                     axis_name, n_dev: int, subtract_self: bool = True,
                     impl: str = "exact", pair=None, pair_anchors=None):
    """`flow_multi` for callers ALREADY INSIDE a `shard_map` over the fiber
    axis (the SPMD implicit step, `parallel.spmd`).

    ``buckets``/``caches_list``/``forces_list`` are this shard's resident
    fiber blocks. Two target classes with different evaluation strategies:

    * ``r_loc`` — targets resident on this shard (its own fiber nodes, its
      shell row block). Source blocks rotate the ring (`lax.ppermute`), so
      every shard's resident targets see all sources: n_dev-1 nearest-
      neighbor hops, O(N/D) peak memory, identical to `parallel.ring`.
    * ``r_rep`` — targets REPLICATED across shards (body nodes, a
      replicated shell). Evaluated as one local source block partial whose
      `psum` is the caller's job — the replication discipline
      (docs/parallel.md "Replication discipline", statically enforced by
      the `replication` audit check): a ring accumulation onto replicated
      rows is the deadlock anti-pattern the analyzer flags as
      ring-order-accumulation.

    Returns ``(v_loc, v_rep_partial)`` (``None`` for an absent class); when
    ``subtract_self`` the leading rows of ``r_loc`` must be this shard's
    concatenated fiber nodes in bucket order. DF impls ("df"/"pallas_df")
    accumulate in float64 and cast back to the target dtype at the seam,
    like `flow_multi`'s ring branch.

    A ``pair`` spec with ``evaluator="tree"`` composes the treecode with
    the SPMD decomposition: every shard buckets the all-gathered source
    set into the SHARED global `TreePlan` (the plan covers the whole
    cloud, a subset just lowers occupancy) and evaluates its own resident
    targets — one all-gather of [N, 3] sources replaces the n_dev-1 ring
    hops of the same total bytes, and per-shard compute drops from
    O(N^2/D) dense tiles to the treecode's near+cluster work. Replicated
    targets keep the partial-sum contract (each shard sums its LOCAL
    sources through the tree; the caller's psum keeps replicated rows
    bitwise identical across shards, same as the ring path).
    """
    from ..parallel.ring import ring_flow_local

    pos = jnp.concatenate([node_positions(g) for g in buckets], axis=0)
    wf = jnp.concatenate([weighted_forces(g, f).reshape(-1, 3)
                          for g, f in zip(buckets, forces_list)], axis=0)

    if (pair is not None and pair.evaluator == "tree"
            and pair.plan is not None and pair.plan.depth > 0):
        from jax import lax

        from ..ops import treecode as tcode

        pos_all = lax.all_gather(pos, axis_name, axis=0, tiled=True)
        wf_all = lax.all_gather(wf, axis_name, axis=0, tiled=True)
        v_loc = tcode._stokeslet_tree_impl(pair.plan, pair_anchors, pos_all,
                                           r_loc, wf_all, eta)
        v_rep = (tcode._stokeslet_tree_impl(pair.plan, pair_anchors, pos,
                                            r_rep, wf, eta)
                 if r_rep is not None else None)
    else:
        v_loc = ring_flow_local("stokeslet", impl, r_loc, pos, wf, eta,
                                axis_name=axis_name, n_dev=n_dev, ring=True)
        v_rep = (ring_flow_local("stokeslet", impl, r_rep, pos, wf, eta,
                                 axis_name=axis_name, n_dev=n_dev,
                                 ring=False)
                 if r_rep is not None else None)

    if subtract_self:
        off = 0
        for g, caches in zip(buckets, caches_list):
            nfn = g.n_fibers * g.n_nodes
            self_vel = jnp.einsum("fij,fj->fi", caches.stokeslet,
                                  wf[off:off + nfn].reshape(g.n_fibers, -1))
            v_loc = v_loc.at[off:off + nfn].add(
                -self_vel.reshape(-1, 3).astype(v_loc.dtype))
            off += nfn
    return v_loc, v_rep


def apply_fiber_force(group: FiberGroup, caches: FiberCaches, x_all) -> jnp.ndarray:
    """Solution -> force density on nodes, [nf, n, 3] (`apply_fiber_force`, `:272-287`)."""
    f = jnp.einsum("fij,fj->fi", caches.force_op, x_all)  # [nf, 3n]
    n = group.n_nodes
    return jnp.stack([f[:, :n], f[:, n:2 * n], f[:, 2 * n:]], axis=-1)


def matvec(group: FiberGroup, caches: FiberCaches, x_all, v_fib, v_boundary) -> jnp.ndarray:
    """Block-diagonal fiber matvec [nf, 4n] (`matvec`, `:216-234`)."""
    mats = group.mats
    sc = group.scalars()
    res = jax.vmap(
        lambda A, xv, v, vb, xs, s, pp: fd_fiber.matvec(A, xv, v, vb, xs, s, mats, pp)
    )(caches.A_bc, x_all, v_fib, v_boundary, caches.xs, sc, group.plus_pinned)
    return jnp.where(group.active[:, None], res, x_all)


def apply_preconditioner(group: FiberGroup, caches: FiberCaches, x_all) -> jnp.ndarray:
    """Batched LU solves, [nf, 4n] (`apply_preconditioner`, `:331-339`).

    Solves in the LU factors' (possibly lower) precision and casts back — a
    preconditioner only needs to approximate A^-1.
    """
    out = jax.vmap(lambda lu, piv, b: jax.scipy.linalg.lu_solve((lu, piv), b))(
        caches.lu, caches.piv, x_all.astype(caches.lu.dtype))
    return out.astype(x_all.dtype)


def step(group: FiberGroup, fiber_sol) -> FiberGroup:
    """Advance positions/tension from the solution [nf, 4n] (`step`, `:292-302`)."""
    n = group.n_nodes
    x_new = jnp.stack([fiber_sol[:, :n], fiber_sol[:, n:2 * n], fiber_sol[:, 2 * n:3 * n]], axis=-1)
    t_new = fiber_sol[:, 3 * n:]
    x_new = jnp.where(group.active[:, None, None], x_new, group.x)
    t_new = jnp.where(group.active[:, None], t_new, group.tension)
    if group.rt_mats is not None:
        # padded node entries solve the identity to exact zero; keep their
        # far-point placeholder positions instead (distinct coordinates are
        # what keeps the dense kernels and self-mobility finite)
        nm = group.rt_mats.node_mask
        x_new = jnp.where(nm[None, :, None], x_new, group.x)
        t_new = jnp.where(nm[None, :], t_new, group.tension)
    return group._replace(x=x_new, tension=t_new, length_prev=group.length)


def generate_constant_force(group: FiberGroup, caches: FiberCaches) -> jnp.ndarray:
    """Implicit motor force f = force_scale * xs [nf, n, 3] (`generate_constant_force`)."""
    return group.force_scale[:, None, None] * caches.xs


def fiber_errors(group: FiberGroup) -> jnp.ndarray:
    """[nf] per-fiber inextensibility violation, inactive slots masked to 0
    — the flight recorder's per-fiber strain diagnostic (obs.flight);
    `fiber_error` is its max."""
    mats = group.mats
    errs = jax.vmap(lambda x, L: fd_fiber.fiber_error(x, L, mats))(group.x, group.length)
    return jnp.where(group.active, errs, 0.0)


def fiber_error(group: FiberGroup) -> jnp.ndarray:
    """Max inextensibility violation over active fibers (`fiber_error_local`)."""
    # -inf sentinel so inactive slots can never win the max; the outer
    # maximum(0, ·) keeps the all-inactive value finite and is otherwise
    # a no-op (errors are nonnegative) — docs/audit.md "Masking discipline"
    errs = jax.vmap(lambda x, L: fd_fiber.fiber_error(x, L, group.mats))(
        group.x, group.length)
    return jnp.maximum(0.0, jnp.max(jnp.where(group.active, errs, -jnp.inf)))


def solution_size(group: FiberGroup) -> int:
    return group.n_fibers * 4 * group.n_nodes


def sort_fibers_morton(group: FiberGroup) -> FiberGroup:
    """Reorder fibers by the Morton (Z-order) code of their centroids.

    Makes consecutive fibers spatially local, so the source *chunks* of the
    chunked pairwise kernels (`ops.kernels._pair_sum`) and the rotating ring
    blocks are compact in space — which is what keeps the MXU matmul-form
    tiles accurate in f32 (their per-block recentering bound scales with the
    block's spatial extent; see `stokeslet_block_mxu`). Safe to apply at any
    time: all per-fiber state rides along, and nothing indexes fibers by
    position (body bindings point at bodies, not fibers). Host-side; call at
    setup or after nucleation bursts, not per step.
    """
    nf = group.n_fibers
    if nf <= 1:
        return group
    # f64 centroids regardless of group dtype: a float32 span floored with a
    # denormal underflows to 0 and NaN-poisons the Morton codes; node-padded
    # groups centroid over LIVE nodes only (far-point pad rows would snap
    # every centroid to one octant)
    nm = node_mask_np(group)
    cent = np.asarray(
        jnp.mean(group.x[:, nm, :], axis=1), dtype=np.float64)  # [nf, 3]
    lo = cent.min(axis=0)
    span = np.maximum(cent.max(axis=0) - lo, np.finfo(np.float64).tiny)
    q = np.clip((cent - lo) / span * 1023.0, 0, 1023).astype(np.uint64)

    def spread(v):
        # interleave 10 bits with two zero bits (standard Morton dilation)
        v = (v | (v << 16)) & np.uint64(0x030000FF)
        v = (v | (v << 8)) & np.uint64(0x0300F00F)
        v = (v | (v << 4)) & np.uint64(0x030C30C3)
        v = (v | (v << 2)) & np.uint64(0x09249249)
        return v

    code = spread(q[:, 0]) | (spread(q[:, 1]) << np.uint64(1)) \
        | (spread(q[:, 2]) << np.uint64(2))
    order = np.argsort(code, kind="stable")

    def permute(name, leaf):
        if name == "rt_mats" or leaf is None:
            return leaf  # group-level runtime mats carry no fiber axis
        leaf = np.asarray(leaf)
        if leaf.ndim >= 1 and leaf.shape[0] == nf:
            return leaf[order]
        return leaf

    return type(group)(*[permute(n, l)
                         for n, l in zip(group._fields, group)])


def grow_capacity(group: FiberGroup, new_cap: int,
                  node_multiple: int = 1) -> FiberGroup:
    """Pad every [nf]-leading leaf to ``new_cap`` slots (padding inactive).

    Used by dynamic instability (geometric capacity growth) and by the
    builder to round the fiber batch up to a mesh-divisible count for the
    ring evaluator. ``node_multiple`` (the mesh size) rounds ``new_cap``
    further up until the total node count divides it — every grower must
    preserve the ring divisibility invariant or a long run dies mid-flight
    in `System._fiber_flow`. Padded slots replicate slot 0 instead of
    zero-filling: a zero-length/zero-x fiber makes the cache derivatives
    inf/NaN, and 0-weight * NaN leaks NaN through the stokeslet sum even for
    inactive slots. Padded slots are inert: inactive and unbound.
    """
    if node_multiple > 1:
        while (new_cap * group.n_nodes) % node_multiple != 0:
            new_cap += 1
    nf = group.n_fibers
    pad = new_cap - nf
    if pad <= 0:
        return group

    def pad_leaf(name, leaf):
        if name == "rt_mats" or leaf is None:
            return leaf  # group-level runtime mats carry no fiber axis
        leaf = np.asarray(leaf)
        if leaf.ndim >= 1 and leaf.shape[0] == nf:
            if nf == 0:
                fill = np.zeros((pad,) + leaf.shape[1:], dtype=leaf.dtype)
            else:
                fill = np.repeat(leaf[:1], pad, axis=0)
            return np.concatenate([leaf, fill], axis=0)
        return leaf

    padded = type(group)(*[pad_leaf(n, l)
                           for n, l in zip(group._fields, group)])
    active = np.asarray(padded.active)
    active[nf:] = False
    binding_body = np.asarray(padded.binding_body)
    binding_body[nf:] = -1
    return padded._replace(active=active, binding_body=binding_body)


def grow_node_capacity(group: FiberGroup, new_n: int) -> FiberGroup:
    """Pad the NODE axis to ``new_n`` rows per fiber (padding masked inert).

    `grow_capacity` extended to the second shape axis (skelly-bucket): the
    live resolution's matrices become runtime data (`matrices.FibMatsRT`)
    riding the group, padded node rows replicate the fiber's FIRST node
    (the same placeholder discipline as `grow_capacity`'s replicated slot
    0: zero quadrature weight makes them silent sources, exact-coincidence
    pairs are dropped by every kernel impl, and staying inside the live
    geometry keeps the f32 MXU tiles' recentering extent honest), and
    every operator reduces to the live fiber's math on the live block.
    ``new_n == n_nodes`` still ATTACHES runtime mats — an exact-fit scene
    must share its bucket's pytree structure, or it would compile its own
    program and defeat the bucket.
    """
    n = group.n_nodes
    n_live = live_node_count(group)
    if new_n < n:
        raise ValueError(
            f"grow_node_capacity: new_n {new_n} below current node capacity "
            f"{n} (node capacity never shrinks)")
    dtype = group.x.dtype
    rt = padded_rt_mats(n_live, new_n, dtype)
    pad = new_n - n
    if pad == 0:
        return group._replace(rt_mats=rt)
    nf = group.n_fibers

    x_np = np.asarray(group.x)
    fill = np.repeat(x_np[:, :1, :], pad, axis=1)      # replicate node 0
    x = np.concatenate([x_np, fill], axis=1)
    tension = np.concatenate(
        [np.asarray(group.tension),
         np.zeros((nf, pad), dtype=np.asarray(group.tension).dtype)], axis=1)
    return group._replace(x=jnp.asarray(x, dtype=dtype),
                          tension=jnp.asarray(tension, dtype=dtype),
                          rt_mats=rt)
