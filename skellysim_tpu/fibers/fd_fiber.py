"""Slender-body-theory finite-difference fiber: operator/RHS/BC/force assembly.

TPU-native re-derivation of `FiberFiniteDifference`
(`/root/reference/src/core/fiber_finite_difference.cpp`): each fiber has 4
unknowns per node (x, y, z, tension), an implicit linear operator A [4n, 4n]
with SBT coefficients c0/c1 and a tension penalty, a rectangular
boundary-condition reduction (barycentric downsampling + 14 BC rows), and a
force operator mapping the solution to force density.

Everything here operates on ONE fiber with row-major arrays (x: [n, 3],
solution: [4n] ordered [x-block, y-block, z-block, T-block]) and is written to
be `jax.vmap`-ed over a batch of same-resolution fibers. Branch-y BC logic is
expressed as `jnp.where` selects over boolean flags so it stays vmappable.

Boundary conditions (mirroring `update_boundary_conditions`,
`fiber_finite_difference.cpp:74-91`):
  * minus end: clamped (Velocity/AngularVelocity) when attached to a body or
    `minus_clamped`, else free (Force/Torque)
  * plus end: hinged (Velocity/Torque) when near a binding-active periphery,
    else free (Force/Torque)

SBT constants (`fiber_finite_difference.hpp:140-144`):
  epsilon = radius / length, c0 = -log(e * eps^2) / (8 pi eta),
  c1 = 2 / (8 pi eta).
"""

from __future__ import annotations

from typing import NamedTuple

import jax.numpy as jnp

from . import matrices

DEFAULT_PENALTY = 500.0   # penalty_param_, fiber_finite_difference.hpp:31
DEFAULT_BETA_TSTEP = 1.0  # beta_tstep_, fiber_finite_difference.hpp:36


class FiberScalars(NamedTuple):
    """Per-fiber scalar parameters (each a 0-d array under vmap)."""

    length: jnp.ndarray
    length_prev: jnp.ndarray
    bending_rigidity: jnp.ndarray
    radius: jnp.ndarray
    penalty: jnp.ndarray
    beta_tstep: jnp.ndarray
    v_growth: jnp.ndarray


def sbt_constants(radius, length, eta):
    """c0, c1 of slender body theory (`fiber_finite_difference.hpp:140-144`)."""
    epsilon = radius / length
    c0 = -jnp.log(jnp.e * epsilon**2) / (8.0 * jnp.pi * eta)
    c1 = 2.0 / (8.0 * jnp.pi * eta)
    return c0, c1


def derivatives(x, length_prev, mats):
    """xs..xssss [n, 3] at the *previous accepted* length (`update_derivatives`)."""
    mats = matrices.typed(mats, x.dtype)
    s = 2.0 / length_prev
    xs = s * (mats.D1 @ x)
    xss = s**2 * (mats.D2 @ x)
    xsss = s**3 * (mats.D3 @ x)
    xssss = s**4 * (mats.D4 @ x)
    return xs, xss, xsss, xssss


def build_A(xs, xss, xsss, dt, eta, sc: FiberScalars, mats):
    """Full (pre-BC) implicit linear operator A [4n, 4n] (`update_linear_operator`).

    Blocks act on the [x, y, z, T] node-block solution layout; derivative
    matrices are scaled to the *target* length (`fiber_finite_difference.cpp:102-105`).
    """
    n = xs.shape[0]
    mats = matrices.typed(mats, xs.dtype)
    E = sc.bending_rigidity
    c0, c1 = sbt_constants(sc.radius, sc.length, eta)
    s = 2.0 / sc.length
    D1, D2, D3, D4 = s * mats.D1, s**2 * mats.D2, s**3 * mats.D3, s**4 * mats.D4
    diag = jnp.eye(n, dtype=bool)

    def XX(i):
        # select, not `scalar * eye`: 0 * inf = NaN would leak the scalar
        # into off-diagonal slots (docs/audit.md "Masking discipline")
        return jnp.where(diag, sc.beta_tstep / dt, 0.0) \
            + E * c0 * ((1.0 + xs[:, i] ** 2)[:, None] * D4) \
            + E * c1 * ((1.0 - xs[:, i] ** 2)[:, None] * D4)

    def XY(i, j):
        return E * (c0 - c1) * ((xs[:, i] * xs[:, j])[:, None] * D4)

    def XT(i):
        return -2.0 * c0 * (xs[:, i][:, None] * D1) - (c0 + c1) * jnp.diag(xss[:, i])

    def TX(i):
        return -(c1 + 7.0 * c0) * E * (xss[:, i][:, None] * D4) \
            - 6.0 * c0 * E * (xsss[:, i][:, None] * D3) \
            - sc.penalty * (xs[:, i][:, None] * D1)

    A_TT = -2.0 * c0 * D2 + (c0 + c1) * jnp.diag(jnp.sum(xss**2, axis=1))

    row_x = jnp.concatenate([XX(0), XY(0, 1), XY(0, 2), XT(0)], axis=1)
    row_y = jnp.concatenate([XY(0, 1), XX(1), XY(1, 2), XT(1)], axis=1)
    row_z = jnp.concatenate([XY(0, 2), XY(1, 2), XX(2), XT(2)], axis=1)
    row_t = jnp.concatenate([TX(0), TX(1), TX(2), A_TT], axis=1)
    return jnp.concatenate([row_x, row_y, row_z, row_t], axis=0)


def build_RHS(x, xs, xss, dt, eta, sc: FiberScalars, mats, flow=None, f_external=None):
    """Full (pre-BC) RHS [4n] (`update_RHS`, `fiber_finite_difference.cpp:198-274`)."""
    n = x.shape[0]
    mats = matrices.typed(mats, x.dtype)
    c0, c1 = sbt_constants(sc.radius, sc.length, eta)
    D1s = (2.0 / sc.length) * mats.D1
    alpha = jnp.asarray(mats.alpha, dtype=x.dtype)
    s_dot = (1.0 + alpha) * (0.5 * sc.v_growth)

    rhs_xyz = x / dt + s_dot[:, None] * xs  # [n, 3]
    rhs_T = -sc.penalty * jnp.ones(n, dtype=x.dtype)

    if flow is not None:
        rhs_xyz = rhs_xyz + flow
        rhs_T = rhs_T + jnp.sum(xs * (D1s @ flow), axis=1)

    if f_external is not None:
        f = f_external
        xsf = jnp.sum(xs * f, axis=1)  # [n]
        rhs_xyz = rhs_xyz + c0 * (f + xs * xsf[:, None]) + c1 * (f - xs * xsf[:, None])
        rhs_T = rhs_T + 2.0 * c0 * jnp.sum(xs * (D1s @ f), axis=1) \
            + (c0 - c1) * jnp.sum(xss * f, axis=1)

    return jnp.concatenate([rhs_xyz[:, 0], rhs_xyz[:, 1], rhs_xyz[:, 2], rhs_T])


def _last_node(mats):
    """Last-LIVE-node selector: ``a[-1]`` with static mats, an ``e_last``
    contraction with runtime node-padded mats (`matrices.FibMatsRT`) where
    the last live node's index is data, not a static."""
    e_last = getattr(mats, "e_last", None)
    if e_last is None:
        return lambda a: a[-1]
    return lambda a: jnp.tensordot(e_last.astype(a.dtype), a, axes=1)


def _bc_rows(x, xs, xss, dt, eta, sc: FiberScalars, mats,
             minus_clamped, plus_pinned, v_on_fiber, f_on_fiber):
    """The 14 boundary-condition rows B [14, 4n] and their RHS [14].

    Mirror of `apply_bc_rectangular` (`fiber_finite_difference.cpp:347-513`).
    Both branch variants are built densely and selected by the boolean flags so
    the result is vmappable; per-row costs are O(n) so this is cheap. With
    runtime node-padded mats the plus-end rows read the last LIVE node via
    the `e_last` one-hot instead of the static ``[-1]`` (the padded suffix
    rows are inert capacity, not the fiber's plus end)."""
    n = x.shape[0]
    dtype = x.dtype
    mats = matrices.typed(mats, dtype)
    last = _last_node(mats)
    E = sc.bending_rigidity
    c0, _c1 = sbt_constants(sc.radius, sc.length, eta)
    s = 2.0 / sc.length
    d1_0, d2_0, d3_0 = s * mats.D1[0], s**2 * mats.D2[0], s**3 * mats.D3[0]
    d1_e, d2_e, d3_e = s * last(mats.D1), s**2 * last(mats.D2), \
        s**3 * last(mats.D3)

    zero = jnp.zeros(n, dtype=dtype)
    e0 = jnp.zeros(n, dtype=dtype).at[0].set(1.0)
    ee = (jnp.zeros(n, dtype=dtype).at[-1].set(1.0)
          if getattr(mats, "e_last", None) is None
          else mats.e_last.astype(dtype))
    x_e, xs_e, xss_e = last(x), last(xs), last(xss)

    def row(bx=None, by=None, bz=None, bt=None):
        parts = [zero if b is None else b for b in (bx, by, bz, bt)]
        return jnp.concatenate(parts)

    v0 = v_on_fiber[0] if v_on_fiber is not None else jnp.zeros(3, dtype=dtype)
    ve = (last(v_on_fiber) if v_on_fiber is not None
          else jnp.zeros(3, dtype=dtype))
    f0 = f_on_fiber[0] if f_on_fiber is not None else jnp.zeros(3, dtype=dtype)
    fe = (last(f_on_fiber) if f_on_fiber is not None
          else jnp.zeros(3, dtype=dtype))

    bod = sc.beta_tstep / dt

    # ---- minus end, first condition (rows 0-3): Velocity (clamped) vs Force (free)
    clamped_rows = jnp.stack([
        row(bx=bod * e0),
        row(by=bod * e0),
        row(bz=bod * e0),
        row(bx=6.0 * E * c0 * xss[0, 0] * d3_0,
            by=6.0 * E * c0 * xss[0, 1] * d3_0,
            bz=6.0 * E * c0 * xss[0, 2] * d3_0,
            bt=2.0 * c0 * d1_0),
    ])
    clamped_rhs = jnp.concatenate([
        x[0] / dt,
        (-jnp.dot(xs[0], v0) - 2.0 * c0 * jnp.dot(xs[0], f0))[None],
    ])
    free_rows = jnp.stack([
        row(bx=E * d3_0, bt=-xs[0, 0] * e0),
        row(by=E * d3_0, bt=-xs[0, 1] * e0),
        row(bz=E * d3_0, bt=-xs[0, 2] * e0),
        row(bx=-E * xss[0, 0] * d2_0,
            by=-E * xss[0, 1] * d2_0,
            bz=-E * xss[0, 2] * d2_0,
            bt=-e0),
    ])
    free_rhs = jnp.concatenate([f0, jnp.dot(f0, xs[0])[None]])
    rows_m1 = jnp.where(minus_clamped, clamped_rows, free_rows)
    rhs_m1 = jnp.where(minus_clamped, clamped_rhs, free_rhs)

    # ---- minus end, second condition (rows 4-6): AngularVelocity vs Torque
    angvel_rows = jnp.stack([row(bx=bod * d1_0), row(by=bod * d1_0), row(bz=bod * d1_0)])
    angvel_rhs = xs[0] / dt
    torque0_rows = jnp.stack([row(bx=d2_0), row(by=d2_0), row(bz=d2_0)])
    torque0_rhs = jnp.zeros(3, dtype=dtype)
    rows_m2 = jnp.where(minus_clamped, angvel_rows, torque0_rows)
    rhs_m2 = jnp.where(minus_clamped, angvel_rhs, torque0_rhs)

    # ---- plus end, first condition (rows 7-10): Velocity (hinged) vs Force (free)
    # NOTE the reference's pinned rows 7-9 place the beta/dt entries at flat
    # columns (n-1, 2n-1, 3n-1) = x/y/z blocks' last node (`:447-449`).
    pinned_rows = jnp.stack([
        row(bx=bod * ee),
        row(by=bod * ee),
        row(bz=bod * ee),
        row(bx=6.0 * E * c0 * xss_e[0] * d3_e,
            by=6.0 * E * c0 * xss_e[1] * d3_e,
            bz=6.0 * E * c0 * xss_e[2] * d3_e,
            bt=2.0 * c0 * d1_e),
    ])
    pinned_rhs = jnp.concatenate([
        x_e / dt,
        (-jnp.dot(xs_e, ve) - 2.0 * c0 * jnp.dot(xs_e, fe))[None],
    ])
    freep_rows = jnp.stack([
        row(bx=-E * d3_e, bt=xs_e[0] * ee),
        row(by=-E * d3_e, bt=xs_e[1] * ee),
        row(bz=-E * d3_e, bt=xs_e[2] * ee),
        row(bx=E * xss_e[0] * d2_e,
            by=E * xss_e[1] * d2_e,
            bz=E * xss_e[2] * d2_e,
            bt=ee),
    ])
    freep_rhs = jnp.concatenate([fe, jnp.dot(fe, xs_e)[None]])
    rows_p1 = jnp.where(plus_pinned, pinned_rows, freep_rows)
    rhs_p1 = jnp.where(plus_pinned, pinned_rhs, freep_rhs)

    # ---- plus end, second condition (rows 11-13): always Torque
    rows_p2 = jnp.stack([row(bx=d2_e), row(by=d2_e), row(bz=d2_e)])
    rhs_p2 = jnp.zeros(3, dtype=dtype)

    B = jnp.concatenate([rows_m1, rows_m2, rows_p1, rows_p2], axis=0)
    B_rhs = jnp.concatenate([rhs_m1, rhs_m2, rhs_p1, rhs_p2])
    return B, B_rhs


def apply_bc_rectangular(A, RHS, x, xs, xss, dt, eta, sc: FiberScalars, mats,
                         minus_clamped, plus_pinned, v_on_fiber=None, f_on_fiber=None):
    """Downsample A/RHS and overwrite the last 14 rows with BC rows.

    Mirror of `apply_bc_rectangular` (`fiber_finite_difference.cpp:347-513`).
    With runtime node-padded mats, each padded solution entry's row is then
    overwritten with its P_down one-hot (an exact unit row) and its RHS
    zeroed: padded entries solve the identity, exactly like inactive fiber
    SLOTS do in `container.update_rhs_and_bc` — the masked-node half of the
    skelly-bucket discipline."""
    P = jnp.asarray(mats.P_down, dtype=A.dtype)
    B, B_rhs = _bc_rows(x, xs, xss, dt, eta, sc, mats,
                        minus_clamped, plus_pinned, v_on_fiber, f_on_fiber)
    A_bc = jnp.concatenate([P @ A, B], axis=0)
    RHS_bc = jnp.concatenate([P @ RHS, B_rhs])
    rm = getattr(mats, "row_mask", None)
    if rm is not None:
        # the padded rows of P are one-hot at their own solution entry, so
        # selecting P itself there makes those rows exact unit rows; the 14
        # BC rows are always live (rm is True there)
        unit = jnp.concatenate(
            [P, jnp.zeros((14, P.shape[1]), dtype=A.dtype)], axis=0)
        A_bc = jnp.where(rm[:, None], A_bc, unit)
        RHS_bc = jnp.where(rm, RHS_bc, 0.0)
    return A_bc, RHS_bc


def force_operator(xs, xss, eta, sc: FiberScalars, mats):
    """Force-density operator [3n, 4n]: solution -> force on nodes.

    f_i = -E x_i'''' + xss_i * T + xs_i * (T)'  (`update_force_operator`,
    `fiber_finite_difference.cpp:317-335`).
    """
    n = xs.shape[0]
    mats = matrices.typed(mats, xs.dtype)
    s = 2.0 / sc.length
    D1s, D4s = s * mats.D1, s**4 * mats.D4
    E = sc.bending_rigidity
    Z = jnp.zeros((n, n), dtype=xs.dtype)

    def comp(i):
        ft = jnp.diag(xss[:, i]) + xs[:, i][:, None] * D1s
        blocks = [Z, Z, Z, ft]
        blocks[i] = -E * D4s
        return jnp.concatenate(blocks, axis=1)

    return jnp.concatenate([comp(0), comp(1), comp(2)], axis=0)


def matvec(A_bc, xvec, v, v_boundary, xs, sc: FiberScalars, mats, plus_pinned):
    """Per-fiber matvec: A_bc @ x - P_down(vT) + BC velocity couplings.

    Mirror of `FiberFiniteDifference::matvec` (`fiber_finite_difference.cpp:276-312`).
    ``v`` is [n, 3] velocity on the fiber nodes from all hydrodynamic flows;
    ``v_boundary`` is the 7-row body-link condition (zeros when unattached).
    """
    n = xs.shape[0]
    mats = matrices.typed(mats, xvec.dtype)
    bc_start = 4 * n - 14
    nm = getattr(mats, "node_mask", None)
    if nm is not None:
        # padded node rows carry whatever the flow evaluator computed at
        # their far-point placeholders; they must contribute exactly zero
        # so padded solution entries stay on the identity
        v = jnp.where(nm[:, None], v, 0.0)
    last = _last_node(mats)
    D1p = (2.0 / sc.length_prev) * mats.D1
    vT_tension = D1p @ jnp.sum(xs * v, axis=1)
    vT = jnp.concatenate([v[:, 0], v[:, 1], v[:, 2], vT_tension])
    P = jnp.asarray(mats.P_down, dtype=xvec.dtype)
    vT_in = jnp.concatenate([P @ vT, jnp.zeros(14, dtype=xvec.dtype)])

    res = A_bc @ xvec - vT_in
    res = res.at[bc_start + 3].add(jnp.dot(v[0], xs[0]))
    res = res.at[bc_start + 10].add(
        jnp.where(plus_pinned, jnp.dot(last(v), last(xs)), 0.0))
    if v_boundary is not None:
        res = res.at[bc_start:bc_start + 7].add(v_boundary)
    return res


def fiber_error(x, length, mats):
    """max_i | ||xs_i|| - 1 | — inextensibility violation (`fiber_error_local`).

    Padded node rows (runtime mats) are excluded: their xs vanish
    identically, which would read as a permanent error of 1."""
    mats = matrices.typed(mats, x.dtype)
    xs = (2.0 / length) * (mats.D1 @ x)
    err = jnp.abs(jnp.linalg.norm(xs, axis=1) - 1.0)
    nm = getattr(mats, "node_mask", None)
    if nm is not None:
        err = jnp.where(nm, err, 0.0)
    return jnp.max(err)
