from .matrices import FibMats, get_mats, VALID_NODE_COUNTS  # noqa: F401
