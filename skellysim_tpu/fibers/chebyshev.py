"""Chebyshev spectral machinery for the experimental spectral fiber.

TPU-native counterpart of the reference's header-only Chebyshev toolkit
(`/root/reference/include/skelly_chebyshev.hpp:27-384`, itself a port of David
Stein's FiberTets.jl): quadrature points (reversed Chebyshev order),
Vandermonde transforms between coefficient (c) and node (n) space, spectral
derivative/integration matrices, dealiased products, and Clenshaw evaluation.

Matrix builders run in float64 numpy at setup (they parameterize compiled
programs); the vector operations are jnp and differentiable, so
`jax.jacfwd` through them replaces the reference's autodiff dual types.
"""

from __future__ import annotations

from functools import lru_cache

import jax.numpy as jnp
import numpy as np

# representation tags (`skelly_chebyshev.hpp:36` REPR enum)
C = "c"
N = "n"


def chebyshev_ratio(lb: float, ub: float) -> float:
    return (ub - lb) / 2.0


def chebyshev_points(order: int, lb: float = -1.0, ub: float = 1.0) -> np.ndarray:
    """Chebyshev points in reversed-traditional order, optionally scaled to
    [lb, ub] (`ChebyshevTPoints`, `skelly_chebyshev.hpp:68-84`)."""
    thetas = np.pi / 2.0 * (2.0 * np.linspace(order, 1.0, order) - 1.0) / order
    x = np.cos(thetas)
    if (lb, ub) == (-1.0, 1.0):
        return x
    return (x + 1.0) * chebyshev_ratio(lb, ub) + lb


def vander(x: np.ndarray, n: int) -> np.ndarray:
    """Chebyshev Vandermonde with columns T_0..T_n at points x
    (`vander_julia_chebyshev`, `skelly_chebyshev.hpp:90-102`)."""
    x = np.asarray(x, dtype=np.float64)
    A = np.empty((x.size, n + 1))
    A[:, 0] = 1.0
    if n > 0:
        A[:, 1] = x
        for i in range(2, n + 1):
            A[:, i] = 2.0 * x * A[:, i - 1] - A[:, i - 2]
    return A


def _frozen(a: np.ndarray) -> np.ndarray:
    """Cached builders hand out read-only arrays: a caller mutating the
    result would otherwise poison the cache process-wide. Callers that need
    to edit (e.g. zeroing the integration matrix's T_0 row) must copy."""
    a.setflags(write=False)
    return a


@lru_cache(maxsize=None)
def vandermonde(order: int) -> np.ndarray:
    return _frozen(vander(chebyshev_points(order), order - 1))


@lru_cache(maxsize=None)
def inverse_vandermonde(order: int) -> np.ndarray:
    return _frozen(np.linalg.inv(vandermonde(order)))


def toggle_representation_matrix(op: np.ndarray, op_in: str, op_out: str,
                                 req_in: str, req_out: str) -> np.ndarray:
    """Re-express an operator between c/n input/output spaces
    (`ToggleRepresentation`, `skelly_chebyshev.hpp:136-155`)."""
    nop = np.asarray(op, dtype=np.float64)
    if op_in == C and req_in == N:
        nop = nop @ inverse_vandermonde(nop.shape[1])
    elif op_in == N and req_in == C:
        nop = nop @ vandermonde(nop.shape[1])
    if op_out == C and req_out == N:
        nop = vandermonde(nop.shape[0]) @ nop
    elif op_out == N and req_out == C:
        nop = inverse_vandermonde(nop.shape[0]) @ nop
    return nop


def derivative_coeffs(p: np.ndarray) -> np.ndarray:
    """d/dx of a Chebyshev series in coefficient space
    (`derivative_julia_chebyshev`, `skelly_chebyshev.hpp:162-187`; equals
    numpy's chebder)."""
    q = np.array(p[1:], dtype=np.float64)
    n = q.size
    der = np.zeros(n)
    for j in range(n, 2, -1):
        der[j - 1] = 2.0 * j * q[j - 1]
        q[j - 3] += j * q[j - 1] / (j - 2)
    if n > 1:
        der[1] = 4.0 * q[1]
    if n > 0:
        der[0] = q[0]
    return der


def nth_derivative_of_Tn(n: int, D: int) -> np.ndarray:
    """Coefficients of the D-th derivative of T_n
    (`NthDerivativeOfChebyshevTn`, `skelly_chebyshev.hpp:196-216`)."""
    q = np.zeros(n + 1)
    q[-1] = 1.0
    der = derivative_coeffs(q)
    for _ in range(2, D + 1):
        der = derivative_coeffs(der)
    return der


@lru_cache(maxsize=None)
def derivative_matrix(n: int, D: int, in_type: str = C, out_type: str = C,
                      scale_factor: float = 1.0) -> np.ndarray:
    """Spectral derivative matrix [n-D, n] (`DerivativeMatrix`,
    `skelly_chebyshev.hpp:219-230`)."""
    DM = np.zeros((n - D, n))
    for i in range(D, n):
        col = nth_derivative_of_Tn(i, D)
        DM[:len(col), i] = col[:n - D]
    DM = DM * scale_factor ** D
    return _frozen(toggle_representation_matrix(DM, C, C, in_type, out_type))


@lru_cache(maxsize=None)
def integration_matrix(order: int, in_type: str = C, out_type: str = C,
                       scale_factor: float = 1.0) -> np.ndarray:
    """Spectral integration matrix: inverse of [derivative; eval-at(-1)]
    (`IntegrationMatrix`, `skelly_chebyshev.hpp:233-242`)."""
    DMat = derivative_matrix(order, 1, C, C, scale_factor)
    VM = vander(np.array([-1.0]), order - 1)
    A = np.vstack([DMat, VM])
    return _frozen(toggle_representation_matrix(np.linalg.inv(A), C, C,
                                                in_type, out_type))


# ------------------------------------------------- runtime (jnp) vector ops

def c2f(xc, order: int | None = None):
    """Coefficient -> node space (`C2F`)."""
    n = order or xc.shape[-1]
    return jnp.asarray(vandermonde(n), dtype=xc.dtype) @ xc


def f2c(xf, order: int | None = None):
    """Node -> coefficient space (`F2C`)."""
    n = order or xf.shape[-1]
    return jnp.asarray(inverse_vandermonde(n), dtype=xf.dtype) @ xf


def toggle(x, in_type: str, out_type: str):
    if in_type == out_type:
        return x
    return f2c(x) if in_type == N else c2f(x)


def resize(x, n: int, in_type: str, out_type: str):
    """Pad/truncate a series in coefficient space (`Resize`,
    `skelly_chebyshev.hpp:307-323`)."""
    wx = toggle(x, in_type, C)
    m = wx.shape[-1]
    if n > m:
        wx = jnp.concatenate([wx, jnp.zeros((n - m,), dtype=wx.dtype)])
    else:
        wx = wx[:n]
    return toggle(wx, C, out_type)


def multiply(x, y, xt: str, yt: str, xyt: str, n_out: int | None = None,
             nm: int | None = None):
    """Dealiased pointwise product of two series (`Multiply`,
    `skelly_chebyshev.hpp:326-340`): upsample to nm nodes, multiply, resize."""
    nin = max(x.shape[-1], y.shape[-1])
    n_out = n_out if n_out is not None else nin
    nm = nm if nm is not None else 2 * nin
    xr = resize(x, nm, xt, N)
    yr = resize(y, nm, yt, N)
    return resize(xr * yr, n_out, N, xyt)


def evalpoly(x, ch):
    """Clenshaw evaluation of a Chebyshev series at scalar x (`evalpoly`,
    `skelly_chebyshev.hpp:343-356`)."""
    c0 = ch[-2]
    c1 = ch[-1]
    for i in range(ch.shape[-1] - 3, -1, -1):
        c0, c1 = ch[i] - c1, c0 + c1 * 2.0 * x
    return c0 + c1 * x


def left_eval(ch):
    return evalpoly(-1.0, ch)


def right_eval(ch):
    return evalpoly(1.0, ch)
