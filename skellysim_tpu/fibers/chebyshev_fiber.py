"""Experimental Chebyshev penalty fiber (integrated spectral representation).

TPU-native counterpart of the reference's header-only next-gen fiber
(`/root/reference/include/fiber_chebyshev_penalty_autodiff.hpp:34-271`,
`include/skelly_fiber.hpp:30-288`, `include/fiber_state.hpp`): a planar (x, y)
filament whose unknowns are the Chebyshev coefficients of the 4th arclength
derivative plus integration constants (2nd derivative for tension), evolved
with backward Euler under a penalty (approximately inextensible) tension
equation and solved with Newton iterations.

Where the reference pushes `autodiff::dual` types through the objective to
assemble the Jacobian, here the objective is a pure jnp function and
`jax.jacfwd` produces the same Jacobian — the idiomatic JAX equivalent.
Like the reference, this discretization is not reachable from `System`
(fiber_type only accepts "FiniteDifference", `system.cpp:657-666`); it is an
exercised-by-tests experimental component.
"""

from __future__ import annotations

from functools import partial
from typing import NamedTuple

import jax
import jax.numpy as jnp
import numpy as np

from . import chebyshev as cheb


class FiberState(NamedTuple):
    """All derivative caches of one divided state (`fiber_state.hpp:29-60`)."""

    XX: jnp.ndarray
    XC: jnp.ndarray
    XsC: jnp.ndarray
    XssC: jnp.ndarray
    XsssC: jnp.ndarray
    XssssC: jnp.ndarray
    YC: jnp.ndarray
    YsC: jnp.ndarray
    YssC: jnp.ndarray
    YsssC: jnp.ndarray
    YssssC: jnp.ndarray
    TC: jnp.ndarray
    TsC: jnp.ndarray
    TssC: jnp.ndarray


class BoundaryCondition(NamedTuple):
    """(X1, X2, Y1, Y2, T) rows appended to the spectral equations
    (`skelly_fiber.hpp` FiberBoundaryCondition)."""

    X1: jnp.ndarray
    X2: jnp.ndarray
    Y1: jnp.ndarray
    Y2: jnp.ndarray
    T: jnp.ndarray


class FiberSolverChebyshevPenalty:
    """Discretization: N nodes for x/y, NT for tension, Neq/NeqT equations
    (`fiber_chebyshev_penalty_autodiff.hpp:41-77`)."""

    def __init__(self, n_nodes: int, n_nodes_tension: int, n_equations: int,
                 n_equations_tension: int):
        self.n_nodes = n_nodes
        self.n_nodes_tension = n_nodes_tension
        self.n_equations = n_equations
        self.n_equations_tension = n_equations_tension

        self.s = cheb.chebyshev_points(n_nodes, 0.0, 1.0)
        self.sT = cheb.chebyshev_points(n_nodes_tension, 0.0, 1.0)

        IM = np.array(cheb.integration_matrix(n_equations))
        IMT = np.array(cheb.integration_matrix(n_equations_tension))
        IM[0, :] = 0.0   # the T_0 row is fixed by the integration constant
        IMT[0, :] = 0.0
        self.IM = jnp.asarray(IM)
        self.IMT = jnp.asarray(IMT)

    # ------------------------------------------------------------- splitting

    def split_main(self, x):
        N, NT = self.n_nodes, self.n_nodes_tension
        return x[:N], x[N:2 * N], x[2 * N:2 * N + NT]

    # ------------------------------------------------- integration cascades

    def _integrate(self, IM, top, rat, consts, factors):
        """Repeatedly integrate ``top``; consts[-1], consts[-2], ... feed the
        T_0 coefficient of each antiderivative with the given factors
        (`IntegrateUp4`/`IntegrateUpTension2`,
        `fiber_chebyshev_penalty_autodiff.hpp:119-169`)."""
        out = []
        cur = top
        c = consts
        for factor in factors:
            cur = (IM @ cur) * rat
            cur = cur.at[0].add(factor * c[-1])
            c = c[:-1]
            out.append(cur)
        return out

    def divide_and_construct(self, XX, L: float) -> FiberState:
        """State vector -> all derivative caches (`DivideAndConstruct`,
        `fiber_chebyshev_penalty_autodiff.hpp:96-117`)."""
        Neq, NeqT = self.n_equations, self.n_equations_tension
        XW, YW, TW = self.split_main(XX)
        XssssC, Dx = XW[:Neq], XW[Neq:]
        YssssC, Dy = YW[:Neq], YW[Neq:]
        TssC, Dt = TW[:NeqT], TW[NeqT:]

        rat = L / 2.0
        XsssC, XssC, XsC, XC = self._integrate(self.IM, XssssC, rat, Dx,
                                               (6.0, 2.0, 1.0, 1.0))
        YsssC, YssC, YsC, YC = self._integrate(self.IM, YssssC, rat, Dy,
                                               (6.0, 2.0, 1.0, 1.0))
        TsC, TC = self._integrate(self.IMT, TssC, rat, Dt, (1.0, 1.0))

        return FiberState(XX=XX, XC=XC, XsC=XsC, XssC=XssC, XsssC=XsssC,
                          XssssC=XssssC, YC=YC, YsC=YsC, YssC=YssC,
                          YsssC=YsssC, YssssC=YssssC, TC=TC, TsC=TsC,
                          TssC=TssC)

    @property
    def solution_size(self) -> int:
        return 2 * self.n_nodes + self.n_nodes_tension


# ---------------------------------------------------------- physics assembly

def fiber_forces(div: FiberState, odiv: FiberState, E: float, n_eq: int):
    """Euler-Bernoulli + SBT force densities (`FiberForces`,
    `skelly_fiber.hpp:36-71`)."""
    m = cheb.multiply
    FxC = (-E * div.XssssC + m(div.TC, odiv.XssC, "c", "c", "c", n_eq)
           + m(div.TsC, odiv.XsC, "c", "c", "c", n_eq))
    FyC = (-E * div.YssssC + m(div.TC, odiv.YssC, "c", "c", "c", n_eq)
           + m(div.TsC, odiv.YsC, "c", "c", "c", n_eq))
    ones = jnp.ones((n_eq,), dtype=FxC.dtype)
    AxxF = ones + m(odiv.XsC, odiv.XsC, "c", "c", "n", n_eq)
    AxyF = m(odiv.XsC, odiv.YsC, "c", "c", "n", n_eq)
    AyyF = ones + m(odiv.YsC, odiv.YsC, "c", "c", "n", n_eq)
    AFxC = (m(AxxF, FxC, "n", "c", "c", n_eq) + m(AxyF, FyC, "n", "c", "c", n_eq))
    AFyC = (m(AxyF, FxC, "n", "c", "c", n_eq) + m(AyyF, FyC, "n", "c", "c", n_eq))
    return FxC, FyC, AFxC, AFyC


def fiber_evolution(AFxC, AFyC, div: FiberState, odiv: FiberState, UC, VC,
                    dt: float):
    """Backward-Euler evolution residuals (`FiberEvolution`,
    `skelly_fiber.hpp:75-81`)."""
    eqXC = div.XC - dt * AFxC - dt * UC - odiv.XC
    eqYC = div.YC - dt * AFyC - dt * VC - odiv.YC
    return eqXC, eqYC


def fiber_penalty_tension(div: FiberState, odiv: FiberState, UsC, VsC,
                          dt: float, n_eq_T: int):
    """Penalty tension residual (`FiberPenaltyTension`,
    `skelly_fiber.hpp:84-130`; the reference's vestigial nUsC/nVsC arguments
    are unused there and dropped here)."""
    m = cheb.multiply
    WXC = (7.0 * m(odiv.XssC, div.XssssC, "c", "c", "c", n_eq_T)
           + 6.0 * m(odiv.XsssC, div.XsssC, "c", "c", "c", n_eq_T))
    WYC = (7.0 * m(odiv.YssC, div.YssssC, "c", "c", "c", n_eq_T)
           + 6.0 * m(odiv.YsssC, div.YsssC, "c", "c", "c", n_eq_T))
    W1C = (m(odiv.XssC, odiv.XssC, "c", "c", "c", n_eq_T)
           + m(odiv.YssC, odiv.YssC, "c", "c", "c", n_eq_T))
    W2C = (m(UsC, odiv.XsC, "c", "c", "c", n_eq_T)
           + m(VsC, odiv.YsC, "c", "c", "c", n_eq_T))
    W3F = (m(odiv.XsC, div.XsC, "c", "c", "n", n_eq_T)
           + m(odiv.YsC, div.YsC, "c", "c", "n", n_eq_T)
           - jnp.ones((n_eq_T,), dtype=div.XsC.dtype))
    W3C = cheb.f2c(W3F)
    WTC = cheb.multiply(div.TC, W1C, "c", "c", "c", n_eq_T)
    return 2.0 * div.TssC - WTC + WXC + WYC + W2C + W3C / dt


def clamped_bc(div: FiberState, odiv: FiberState, side: str, clamp_position,
               clamp_director) -> BoundaryCondition:
    """Clamped end (`ClampedBC`, `skelly_fiber.hpp:133-156`)."""
    ev = cheb.left_eval if side == "left" else cheb.right_eval
    W1 = ev(div.XsssC) * ev(odiv.XssC) + ev(div.YsssC) * ev(odiv.YssC)
    return BoundaryCondition(
        X1=ev(div.XC) - clamp_position[0], X2=ev(div.XsC) - clamp_director[0],
        Y1=ev(div.YC) - clamp_position[1], Y2=ev(div.YsC) - clamp_director[1],
        T=ev(div.TsC) + 3.0 * W1)


def free_bc(div: FiberState, side: str) -> BoundaryCondition:
    """Force/torque-free end (`FreeBC`, `skelly_fiber.hpp:159-171`)."""
    ev = cheb.left_eval if side == "left" else cheb.right_eval
    return BoundaryCondition(X1=ev(div.XssC), X2=ev(div.XsssC),
                             Y1=ev(div.YssC), Y2=ev(div.YsssC),
                             T=ev(div.TC))


def _combine(eq, *bcs):
    return jnp.concatenate([eq, jnp.stack(bcs)])


def sheer_deflection_objective(XX, solver: FiberSolverChebyshevPenalty, oldXX,
                               L: float, zeta: float, dt: float):
    """Residual of one backward-Euler step in background shear u = zeta*y
    (`SheerDeflectionObjective`, `fiber_chebyshev_penalty_autodiff.hpp:192-236`)."""
    div = solver.divide_and_construct(XX, L)
    odiv = solver.divide_and_construct(oldXX, L)

    _, _, AFxC, AFyC = fiber_forces(div, odiv, 1.0, solver.n_equations)

    UC = zeta * div.YC
    VC = jnp.zeros_like(div.YC)
    UsC = zeta * div.YsC
    VsC = jnp.zeros_like(div.YsC)

    teqXC, teqYC = fiber_evolution(AFxC, AFyC, div, odiv, UC, VC, dt)
    teqTC = fiber_penalty_tension(div, odiv, UsC, VsC, dt,
                                  solver.n_equations_tension)

    cpos = jnp.zeros((2,), dtype=XX.dtype)
    cdir = jnp.asarray([0.0, 1.0], dtype=XX.dtype)
    BCL = clamped_bc(div, odiv, "left", cpos, cdir)
    BCR = free_bc(div, "right")

    eqXC = _combine(teqXC, BCL.X1, BCL.X2, BCR.X1, BCR.X2)
    eqYC = _combine(teqYC, BCL.Y1, BCL.Y2, BCR.Y1, BCR.Y2)
    eqTC = _combine(teqTC, BCL.T, BCR.T)
    return jnp.concatenate([eqXC, eqYC, eqTC])


# ------------------------------------------------------------ solve / evolve

def setup_solver_initialstate(N: int, L: float):
    """Solver + straight vertical fiber initial state
    (`SetupSolverInitialstate`, `fiber_chebyshev_penalty_autodiff.hpp:241-263`)."""
    NT, Neq, NTeq = N - 2, N - 4, N - 4
    solver = FiberSolverChebyshevPenalty(N, NT, Neq, NTeq)
    init_X = np.zeros(N)
    init_Y = np.zeros(N)
    init_T = np.zeros(NT)
    init_Y[-4] = L / 2.0
    init_Y[-3] = 1.0
    XX = jnp.asarray(np.concatenate([init_X, init_Y, init_T]))
    return solver, XX


def newton_step(solver: FiberSolverChebyshevPenalty, XX, oldXX, L, zeta, dt):
    """One Newton iteration XX - J^-1 F via jacfwd (the reference's
    `autodiff::jacobian` + dense inverse, `jnewton_fiberpenalty_test.cpp:34-52`)."""

    def objective(x):
        return sheer_deflection_objective(x, solver, oldXX, L, zeta, dt)

    F = objective(XX)
    J = jax.jacfwd(objective)(XX)
    return XX - jnp.linalg.solve(J, F)


@partial(jax.jit, static_argnames=("solver", "n_steps", "newton_iterations"))
def _evolve_impl(solver, XX, L, zeta, dt, n_steps, newton_iterations):
    def step(carry, _):
        x = carry
        old = x
        for _ in range(newton_iterations):
            x = newton_step(solver, x, old, L, zeta, dt)
        return x, _extensibility_error_state(solver.divide_and_construct(x, L))

    return jax.lax.scan(step, XX, None, length=n_steps)


def evolve(solver: FiberSolverChebyshevPenalty, XX, *, L: float, zeta: float,
           dt: float, n_steps: int, newton_iterations: int = 1):
    """Backward-Euler time loop with single (or multi) Newton updates per step
    (`UpdateSingleNewtonBackwardEuler`, `jnewton_fiberpenalty_test.cpp:55-66`).
    One jit'd lax.scan program, cached per (solver, n_steps) so parameter
    sweeps compile once."""
    return _evolve_impl(solver, XX, L, zeta, dt, n_steps, newton_iterations)


def _extensibility_error_state(div: FiberState):
    m = cheb.multiply
    W = (m(div.XsC, div.XsC, "c", "c", "n") + m(div.YsC, div.YsC, "c", "c", "n")
         - 1.0)
    return jnp.max(jnp.abs(W))


def extricate(solver: FiberSolverChebyshevPenalty, XX, L: float):
    """(XC, YC, TC, extensibility error) (`Extricate`,
    `fiber_chebyshev_penalty_autodiff.hpp:266-274`)."""
    div = solver.divide_and_construct(XX, L)
    return div.XC, div.YC, div.TC, _extensibility_error_state(div)


def extensibility_error(solver: FiberSolverChebyshevPenalty, XX, L: float):
    """max |Xs.Xs + Ys.Ys - 1| (`ExtensibilityError`,
    `skelly_fiber.hpp:216-236`)."""
    return _extensibility_error_state(solver.divide_and_construct(XX, L))


def node_positions(solver: FiberSolverChebyshevPenalty, XX, L: float):
    """(x(s), y(s)) at the solver's Chebyshev nodes."""
    div = solver.divide_and_construct(XX, L)
    return cheb.c2f(div.XC), cheb.c2f(div.YC)
