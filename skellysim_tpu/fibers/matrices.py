"""Per-resolution static fiber matrices.

Mirror of `compute_matrices_finitediff` (`/root/reference/src/core/fiber_finite_difference.cpp:519-562`):
for each supported node count, the 4th-order finite-difference differentiation
matrices D1..D4 on the [-1, 1] reference interval, the barycentric downsampling
matrices P_X (n -> n-4) and P_T (n -> n-2), the combined boundary-condition
downsampling operator P_downsample_bc ([4n-14, 4n]), and trapezoid quadrature
weights. Built once in NumPy float64 and closed over by jit'd code as constants.

Unlike the reference we keep D_k in "derivative = D @ values" orientation
(the reference pre-transposes for its columns-as-points Eigen layout).
"""

from __future__ import annotations

from dataclasses import dataclass
from functools import lru_cache

import jax.numpy as jnp
import numpy as np

from ..ops.finite_diff import barycentric_matrix, finite_diff

VALID_NODE_COUNTS = (8, 16, 24, 32, 48, 64, 96, 128)

#: order of the finite differencing scheme (reference hard-codes 4,
#: `src/core/fiber_finite_difference.cpp:560-562`)
FD_ORDER = 4


@dataclass(frozen=True)
class FibMats:
    """Static matrices for one fiber resolution (all NumPy float64)."""

    n_nodes: int
    alpha: np.ndarray          # [n] equispaced nodes on [-1, 1]
    alpha_roots: np.ndarray    # [n-4] cell-centered grid for position rows
    alpha_tension: np.ndarray  # [n-2] cell-centered grid for tension rows
    D1: np.ndarray             # [n, n] first-derivative matrix (unscaled)
    D2: np.ndarray
    D3: np.ndarray
    D4: np.ndarray
    P_X: np.ndarray            # [n-4, n]
    P_T: np.ndarray            # [n-2, n]
    P_down: np.ndarray         # [4n-14, 4n] block-diag(P_X, P_X, P_X, P_T)
    weights0: np.ndarray       # [n] trapezoid weights on [-1, 1]


from typing import NamedTuple


class FibMatsRT(NamedTuple):
    """Runtime (traced) fiber matrices for a node-capacity bucket.

    The shape-polymorphism twin of `FibMats` (skelly-bucket): the live
    resolution's matrices live as the top-left block of capacity-sized
    ARRAYS that ride the `FiberGroup` pytree as data, so two scenes with
    different live node counts but the same node capacity share one
    compiled program — the live count is a value, not a static. Padded
    (suffix) node rows/columns are exact zeros in every derivative
    matrix, so derivatives of padded rows vanish identically and the
    masked operators reduce to the live fiber's math bit-for-bit on the
    live block.

    A NamedTuple (hence a pytree): ensemble stacking, donation, and
    sharding treat the matrices like any other state leaf. All leaves are
    group-level (no [nf] axis) — the container's vmapped per-fiber
    closures capture them broadcast, like the static mats they replace.
    """

    alpha: jnp.ndarray      # [n_cap] live alpha prefix (pad values unused)
    D1: jnp.ndarray         # [n_cap, n_cap] live block top-left, zeros pad
    D2: jnp.ndarray
    D3: jnp.ndarray
    D4: jnp.ndarray
    #: [4n_cap-14, 4n_cap]: the live P_down blocks scattered into capacity
    #: coordinates; each padded solution entry gets its own one-hot row, so
    #: `where(row_mask, P @ A, P)` leaves padded rows as exact unit rows
    P_down: jnp.ndarray
    weights0: jnp.ndarray   # [n_cap] live trapezoid weights, zeros pad
    #: [n_cap] one-hot at the LAST LIVE node — replaces every static
    #: ``x[-1]`` / ``D[-1]`` boundary-condition read with a data-dependent
    #: contraction
    e_last: jnp.ndarray
    node_mask: jnp.ndarray  # [n_cap] bool, True on live nodes
    #: [4n_cap] bool over the BC'd row space [P_down rows | 14 BC rows]:
    #: False exactly on the padded entries' one-hot rows
    row_mask: jnp.ndarray
    #: [4n_cap] bool over the solution layout [x | y | z | T]
    sol_mask: jnp.ndarray

    @property
    def n_nodes(self) -> int:
        return self.D1.shape[0]


def padded_rt_mats(n_live: int, n_cap: int, dtype=np.float64) -> FibMatsRT:
    """Host-side FibMatsRT for ``n_live`` live nodes in an ``n_cap`` bucket.

    ``n_live == n_cap`` is valid (runtime mats with no padded rows — the
    shape a bucket's program is traced for serves every smaller live
    count). Both counts must be in `VALID_NODE_COUNTS`."""
    if n_live > n_cap:
        raise ValueError(f"n_live {n_live} exceeds node capacity {n_cap}")
    live = get_mats(n_live)
    if n_cap not in VALID_NODE_COUNTS:
        raise ValueError(
            f"node capacity must be one of {VALID_NODE_COUNTS}, got {n_cap}")
    nl, nc = n_live, n_cap
    pad = nc - nl

    def pad_mat(m):
        out = np.zeros((nc, nc))
        out[:nl, :nl] = m
        return out

    alpha = np.zeros(nc)
    alpha[:nl] = live.alpha
    weights0 = np.zeros(nc)
    weights0[:nl] = live.weights0
    e_last = np.zeros(nc)
    e_last[nl - 1] = 1.0
    node_mask = np.zeros(nc, dtype=bool)
    node_mask[:nl] = True

    # P_down in capacity coordinates: per solution block (x, y, z, T) the
    # live downsample rows come first, then one one-hot row per padded
    # entry (rows land where `apply_bc_rectangular`'s padded-row overwrite
    # expects exact unit rows)
    P = np.zeros((4 * nc - 14, 4 * nc))
    row_mask = np.ones(4 * nc, dtype=bool)
    r = 0
    for b, (blk, nrow) in enumerate(
            [(live.P_X, nl - 4)] * 3 + [(live.P_T, nl - 2)]):
        P[r:r + nrow, b * nc:b * nc + nl] = blk
        r += nrow
        for j in range(pad):
            P[r, b * nc + nl + j] = 1.0
            row_mask[r] = False
            r += 1
    assert r == 4 * nc - 14

    sol_mask = np.tile(node_mask, 4)
    c = np.dtype(dtype)
    return FibMatsRT(
        alpha=jnp.asarray(alpha, dtype=c), D1=jnp.asarray(pad_mat(live.D1), dtype=c),
        D2=jnp.asarray(pad_mat(live.D2), dtype=c),
        D3=jnp.asarray(pad_mat(live.D3), dtype=c),
        D4=jnp.asarray(pad_mat(live.D4), dtype=c),
        P_down=jnp.asarray(P, dtype=c),
        weights0=jnp.asarray(weights0, dtype=c),
        e_last=jnp.asarray(e_last, dtype=c),
        node_mask=jnp.asarray(node_mask),
        row_mask=jnp.asarray(row_mask),
        sol_mask=jnp.asarray(sol_mask))


def _cast_mats(m: FibMats, dtype_name: str) -> FibMats:  # skelly-lint: ignore-function[host-sync] — casts host NumPy FibMats constants (never traced values) with a static dtype name; runs at trace time by design (module docstring)
    def c(a):
        return np.asarray(a, dtype=dtype_name)

    return FibMats(m.n_nodes, c(m.alpha), c(m.alpha_roots), c(m.alpha_tension),
                   c(m.D1), c(m.D2), c(m.D3), c(m.D4),
                   c(m.P_X), c(m.P_T), c(m.P_down), c(m.weights0))


@lru_cache(maxsize=None)
def _typed_mats(n_nodes: int, dtype_name: str) -> FibMats:
    return _cast_mats(get_mats(n_nodes), dtype_name)


def typed(mats: FibMats, dtype) -> FibMats:
    """FibMats with every array cast to ``dtype``.

    The matrices are built in float64 for accuracy, but closing f64 NumPy
    constants over f32 jit code promotes every downstream op to f64 under
    `jax_enable_x64` — which breaks the TPU path (XLA `LuDecomposition` is
    f32-only on TPU). Cast once here; use-site dtype follows the state.

    The canonical `get_mats` instance casts through a per-resolution cache;
    a caller-customized FibMats is cast directly (never swapped for the
    pristine cached matrices).
    """
    if isinstance(mats, FibMatsRT):
        # runtime mats are traced data: constructed at the state dtype by
        # `padded_rt_mats`, so the cast is a no-op on the hot path; a
        # mismatched caller gets explicit converts rather than silent
        # promotion
        if mats.D1.dtype == jnp.dtype(dtype):
            return mats
        return FibMatsRT(*[
            leaf.astype(dtype) if jnp.issubdtype(leaf.dtype, jnp.floating)
            else leaf for leaf in mats])
    name = np.dtype(dtype).name
    if mats.D1.dtype == np.dtype(dtype):
        return mats
    if mats is get_mats(mats.n_nodes):
        return _typed_mats(mats.n_nodes, name)
    return _cast_mats(mats, name)


@lru_cache(maxsize=None)
def get_mats(n_nodes: int) -> FibMats:
    if n_nodes not in VALID_NODE_COUNTS:
        raise ValueError(f"n_nodes must be one of {VALID_NODE_COUNTS}, got {n_nodes}")
    n = n_nodes
    alpha = np.linspace(-1.0, 1.0, n)
    n_roots = n - 4
    alpha_roots = 2 * (0.5 + np.arange(n_roots)) / n_roots - 1
    n_tension = n - 2
    alpha_tension = 2 * (0.5 + np.arange(n_tension)) / n_tension - 1

    D1 = finite_diff(alpha, 1, FD_ORDER + 1)
    D2 = finite_diff(alpha, 2, FD_ORDER + 2)
    D3 = finite_diff(alpha, 3, FD_ORDER + 3)
    D4 = finite_diff(alpha, 4, FD_ORDER + 4)

    P_X = barycentric_matrix(alpha, alpha_roots)
    P_T = barycentric_matrix(alpha, alpha_tension)

    P_down = np.zeros((4 * n - 14, 4 * n))
    P_down[0 * (n - 4):1 * (n - 4), 0 * n:1 * n] = P_X
    P_down[1 * (n - 4):2 * (n - 4), 1 * n:2 * n] = P_X
    P_down[2 * (n - 4):3 * (n - 4), 2 * n:3 * n] = P_X
    P_down[3 * (n - 4):3 * (n - 4) + n_tension, 3 * n:4 * n] = P_T

    weights0 = np.full(n, 2.0)
    weights0[0] = 1.0
    weights0[-1] = 1.0
    weights0 /= n - 1

    return FibMats(n, alpha, alpha_roots, alpha_tension, D1, D2, D3, D4,
                   P_X, P_T, P_down, weights0)
