"""Per-resolution static fiber matrices.

Mirror of `compute_matrices_finitediff` (`/root/reference/src/core/fiber_finite_difference.cpp:519-562`):
for each supported node count, the 4th-order finite-difference differentiation
matrices D1..D4 on the [-1, 1] reference interval, the barycentric downsampling
matrices P_X (n -> n-4) and P_T (n -> n-2), the combined boundary-condition
downsampling operator P_downsample_bc ([4n-14, 4n]), and trapezoid quadrature
weights. Built once in NumPy float64 and closed over by jit'd code as constants.

Unlike the reference we keep D_k in "derivative = D @ values" orientation
(the reference pre-transposes for its columns-as-points Eigen layout).
"""

from __future__ import annotations

from dataclasses import dataclass
from functools import lru_cache

import numpy as np

from ..ops.finite_diff import barycentric_matrix, finite_diff

VALID_NODE_COUNTS = (8, 16, 24, 32, 48, 64, 96, 128)

#: order of the finite differencing scheme (reference hard-codes 4,
#: `src/core/fiber_finite_difference.cpp:560-562`)
FD_ORDER = 4


@dataclass(frozen=True)
class FibMats:
    """Static matrices for one fiber resolution (all NumPy float64)."""

    n_nodes: int
    alpha: np.ndarray          # [n] equispaced nodes on [-1, 1]
    alpha_roots: np.ndarray    # [n-4] cell-centered grid for position rows
    alpha_tension: np.ndarray  # [n-2] cell-centered grid for tension rows
    D1: np.ndarray             # [n, n] first-derivative matrix (unscaled)
    D2: np.ndarray
    D3: np.ndarray
    D4: np.ndarray
    P_X: np.ndarray            # [n-4, n]
    P_T: np.ndarray            # [n-2, n]
    P_down: np.ndarray         # [4n-14, 4n] block-diag(P_X, P_X, P_X, P_T)
    weights0: np.ndarray       # [n] trapezoid weights on [-1, 1]


def _cast_mats(m: FibMats, dtype_name: str) -> FibMats:  # skelly-lint: ignore-function[host-sync] — casts host NumPy FibMats constants (never traced values) with a static dtype name; runs at trace time by design (module docstring)
    def c(a):
        return np.asarray(a, dtype=dtype_name)

    return FibMats(m.n_nodes, c(m.alpha), c(m.alpha_roots), c(m.alpha_tension),
                   c(m.D1), c(m.D2), c(m.D3), c(m.D4),
                   c(m.P_X), c(m.P_T), c(m.P_down), c(m.weights0))


@lru_cache(maxsize=None)
def _typed_mats(n_nodes: int, dtype_name: str) -> FibMats:
    return _cast_mats(get_mats(n_nodes), dtype_name)


def typed(mats: FibMats, dtype) -> FibMats:
    """FibMats with every array cast to ``dtype``.

    The matrices are built in float64 for accuracy, but closing f64 NumPy
    constants over f32 jit code promotes every downstream op to f64 under
    `jax_enable_x64` — which breaks the TPU path (XLA `LuDecomposition` is
    f32-only on TPU). Cast once here; use-site dtype follows the state.

    The canonical `get_mats` instance casts through a per-resolution cache;
    a caller-customized FibMats is cast directly (never swapped for the
    pristine cached matrices).
    """
    name = np.dtype(dtype).name
    if mats.D1.dtype == np.dtype(dtype):
        return mats
    if mats is get_mats(mats.n_nodes):
        return _typed_mats(mats.n_nodes, name)
    return _cast_mats(mats, name)


@lru_cache(maxsize=None)
def get_mats(n_nodes: int) -> FibMats:
    if n_nodes not in VALID_NODE_COUNTS:
        raise ValueError(f"n_nodes must be one of {VALID_NODE_COUNTS}, got {n_nodes}")
    n = n_nodes
    alpha = np.linspace(-1.0, 1.0, n)
    n_roots = n - 4
    alpha_roots = 2 * (0.5 + np.arange(n_roots)) / n_roots - 1
    n_tension = n - 2
    alpha_tension = 2 * (0.5 + np.arange(n_tension)) / n_tension - 1

    D1 = finite_diff(alpha, 1, FD_ORDER + 1)
    D2 = finite_diff(alpha, 2, FD_ORDER + 2)
    D3 = finite_diff(alpha, 3, FD_ORDER + 3)
    D4 = finite_diff(alpha, 4, FD_ORDER + 4)

    P_X = barycentric_matrix(alpha, alpha_roots)
    P_T = barycentric_matrix(alpha, alpha_tension)

    P_down = np.zeros((4 * n - 14, 4 * n))
    P_down[0 * (n - 4):1 * (n - 4), 0 * n:1 * n] = P_X
    P_down[1 * (n - 4):2 * (n - 4), 1 * n:2 * n] = P_X
    P_down[2 * (n - 4):3 * (n - 4), 2 * n:3 * n] = P_X
    P_down[3 * (n - 4):3 * (n - 4) + n_tension, 3 * n:4 * n] = P_T

    weights0 = np.full(n, 2.0)
    weights0[0] = 1.0
    weights0[-1] = 1.0
    weights0 /= n - 1

    return FibMats(n, alpha, alpha_roots, alpha_tension, D1, D2, D3, D4,
                   P_X, P_T, P_down, weights0)
