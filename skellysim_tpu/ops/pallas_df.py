"""Pallas double-float (compensated f32) pairwise kernels.

Fuses the `ops.df_kernels` arithmetic — Dekker/Knuth error-free
transformations giving ~1e-14-class relative accuracy from pure f32 VPU ops
— into VMEM interaction tiles like `ops.pallas_kernels`. The XLA DF path
measures ~0.34 Gpairs/s on a v5e chip (the per-pair chain is ~15x the exact
kernel's flops and XLA spends it through HBM-staged fusions); keeping the
whole chain on-tile removes the HBM round trips, the same transformation
that took the exact kernel 14.6 -> 53 Gpairs/s.

Numerics: per-pair arithmetic is double-float (every value an unevaluated
(hi, lo) f32 pair); in-tile reduction is a compensated halving tree down to
one 128-lane vreg, then a lane-roll log-reduction — no f32-rounded sum
anywhere between the pair terms and the final hi+lo -> f64 reconstruction
on the host side of the kernel. Cross-tile accumulation along the source
grid axis is a DF add into a (hi, lo) output pair.

FMA-contraction hardening: the inexact-product-feeding-add sites are
`_mbar`-wrapped exactly like `ops.df_kernels` (see the long analysis
there). On real TPUs the Mosaic pipeline evaluates each kernel value once
into a vreg (no XLA-style cross-fusion cloning), so the hazard class that
motivated the hardening cannot arise; in `interpret=True` mode the kernel
body runs through XLA:CPU where LLVM's FMA contraction is live, and the
`select` hardening keeps the compensation intact there. The on-TPU
agreement gate (`tests/test_pallas_df.py::test_tpu_agreement`) is the
authority for real-hardware accuracy, mirroring the exact-kernel gate.

Reference parity: same evaluator contract as `kernels.{stokeslet,
stresslet}_direct` (self pairs drop, factor 1/(8 pi eta); stresslet factor
-3 on the double-layer sum) — the backend-agreement threshold for every
evaluator is ||err|| <= 5e-9 (`/root/reference/tests/core/kernel_test.cpp:93`);
these tiles sit ~5 orders under it.
"""

from __future__ import annotations

import math
from functools import partial

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from .pallas_kernels import _out_struct, _pad_to

__all__ = ["stokeslet_pallas_df", "stresslet_pallas_df",
           "stokeslet_pallas_df_block", "stresslet_pallas_df_block"]

# DF tiles hold ~3x the live [tile_t, tile_s] temporaries of the exact
# kernels; smaller defaults keep the working set inside VMEM
DF_TILE_T = 128
DF_TILE_S = 512

#: Dekker split constant for f32 (2^ceil(24/2) + 1)
_SPLIT_F32 = 4097.0


def _mbar(x):
    """Value barrier on a rounded intermediate.

    `df_kernels` uses `lax.optimization_barrier` for these sites, but a
    barrier has no guaranteed Mosaic lowering inside a Pallas kernel; this
    select is value-preserving (operands are non-NaN), cannot be folded
    without NaN reasoning, and lowers on every path (Mosaic, interpret/XLA).
    Without it the compiler algebraically collapses the error-extraction
    expressions — measured 2.7e-8 instead of 1e-14 on this very kernel
    (round 5), the same failure class `df_kernels` documents.
    """
    return jnp.where(x == x, x, jnp.zeros_like(x))


def _two_sum(a, b):
    """Error-free a + b = s + e (Knuth; no magnitude ordering required)."""
    s = _mbar(a + b)
    bb = _mbar(s - a)
    e = (a - _mbar(s - bb)) + (b - bb)
    return s, e


def _quick_two_sum(a, b):
    """Error-free a + b = s + e assuming |a| >= |b|."""
    s = _mbar(a + b)
    e = b - (s - a)
    return s, e


def _two_prod(a, b):
    """Error-free a * b = p + e via Dekker splitting (no FMA dependency)."""
    p = _mbar(a * b)
    a_big = _mbar(_SPLIT_F32 * a)
    a_hi = _mbar(a_big - _mbar(a_big - a))
    a_lo = a - a_hi
    b_big = _mbar(_SPLIT_F32 * b)
    b_hi = _mbar(b_big - _mbar(b_big - b))
    b_lo = b - b_hi
    e = ((a_hi * b_hi - p) + a_hi * b_lo + a_lo * b_hi) + a_lo * b_lo
    return p, e


def _df_add(xh, xl, yh, yl):
    s, e = _two_sum(xh, yh)
    e = e + (xl + yl)
    return _quick_two_sum(s, e)


def _df_mul(xh, xl, yh, yl):
    p, e = _two_prod(xh, yh)
    e = e + (_mbar(xh * yl) + _mbar(xl * yh))
    return _quick_two_sum(p, e)


def _df_rsqrt(xh, xl):
    """1/sqrt(x) as DF: f32 hardware seed + one DF Newton step (doubles the
    accurate bits to full DF precision). Assumes x > 0 (callers mask)."""
    y0 = lax.rsqrt(xh)
    z = jnp.zeros_like(y0)
    th, tl = _df_mul(xh, xl, y0, z)
    th, tl = _df_mul(th, tl, y0, z)
    rh, rl = _df_add(jnp.full_like(th, 3.0), z, -th, -tl)
    yh, yl = _df_mul(rh, rl, y0, z)
    return 0.5 * yh, 0.5 * yl


def _df_reduce_lanes(h, l):
    """Compensated sum along the lane axis of [t, s] -> [t] DF pairs.

    Halving slices keep full 128-lane vregs down to one vreg width; the
    final 128 lanes reduce by lane rolls (full-shape ops Mosaic handles
    natively — no sub-128 slicing). The rolled-in lanes make every lane k
    hold sum(lanes k..k+2^m-1 mod 128); lane 0 is the true total, selected
    by the caller's final [:, 0].
    """
    while h.shape[1] > 128:
        m = h.shape[1] // 2
        h, l = _df_add(h[:, :m], l[:, :m], h[:, m:], l[:, m:])
    w = 64
    while w >= 1:
        # rotation direction is irrelevant for a log-reduce (pltpu.roll
        # requires non-negative shifts): after all steps every lane holds
        # the full 128-lane total
        hr = pltpu.roll(h, w, 1)
        lr = pltpu.roll(l, w, 1)
        h, l = _df_add(h, l, hr, lr)
        w //= 2
    return h[:, 0], l[:, 0]


def _df_diff(t_hi, t_lo, s_hi, s_lo):
    """DF displacement component t - s with full two_sum (nearly coincident
    f64 points can have lo-word differences exceeding |hi difference|)."""
    dh, de = _two_sum(t_hi[:, None], -s_hi[None, :])
    return _two_sum(dh, de + (t_lo[:, None] - s_lo[None, :]))


def _stokeslet_df_kernel(trg_ref, src_ref, f_ref, out_ref):
    """One DF interaction tile; trg/src/f refs carry hi rows then lo rows."""
    j = pl.program_id(1)

    @pl.when(j == 0)
    def _():
        out_ref[:] = jnp.zeros_like(out_ref)

    d = [_df_diff(trg_ref[k, :], trg_ref[3 + k, :],
                  src_ref[k, :], src_ref[3 + k, :]) for k in range(3)]

    r2h, r2l = _df_mul(*d[0], *d[0])
    r2h, r2l = _df_add(r2h, r2l, *_df_mul(*d[1], *d[1]))
    r2h, r2l = _df_add(r2h, r2l, *_df_mul(*d[2], *d[2]))

    mask = r2h > 0.0
    rih, ril = _df_rsqrt(jnp.where(mask, r2h, 1.0), jnp.where(mask, r2l, 0.0))
    rih = jnp.where(mask, rih, 0.0)
    ril = jnp.where(mask, ril, 0.0)
    r3h, r3l = _df_mul(rih, ril, rih, ril)
    r3h, r3l = _df_mul(r3h, r3l, rih, ril)

    fs = [(f_ref[k, :][None, :], f_ref[3 + k, :][None, :]) for k in range(3)]
    dfh, dfl = _df_mul(*d[0], *fs[0])
    dfh, dfl = _df_add(dfh, dfl, *_df_mul(*d[1], *fs[1]))
    dfh, dfl = _df_add(dfh, dfl, *_df_mul(*d[2], *fs[2]))
    ch, cl = _df_mul(dfh, dfl, r3h, r3l)

    for k in range(3):
        uh, ul = _df_mul(rih, ril, *fs[k])
        uh, ul = _df_add(uh, ul, *_df_mul(ch, cl, *d[k]))
        sh, sl = _df_reduce_lanes(uh, ul)
        ah, al = _df_add(out_ref[k, :], out_ref[3 + k, :], sh, sl)
        out_ref[k, :] = ah
        out_ref[3 + k, :] = al


def _stresslet_df_kernel(trg_ref, src_ref, s_ref, out_ref):
    """DF stresslet tile: u_k = sum -3 (d.S.d) d_k / r^5, self pairs drop.
    s_ref carries the 9 hi rows then the 9 lo rows of S (row-major)."""
    j = pl.program_id(1)

    @pl.when(j == 0)
    def _():
        out_ref[:] = jnp.zeros_like(out_ref)

    d = [_df_diff(trg_ref[k, :], trg_ref[3 + k, :],
                  src_ref[k, :], src_ref[3 + k, :]) for k in range(3)]

    r2h, r2l = _df_mul(*d[0], *d[0])
    r2h, r2l = _df_add(r2h, r2l, *_df_mul(*d[1], *d[1]))
    r2h, r2l = _df_add(r2h, r2l, *_df_mul(*d[2], *d[2]))

    mask = r2h > 0.0
    rih, ril = _df_rsqrt(jnp.where(mask, r2h, 1.0), jnp.where(mask, r2l, 0.0))
    rih = jnp.where(mask, rih, 0.0)
    ril = jnp.where(mask, ril, 0.0)
    r2ih, r2il = _df_mul(rih, ril, rih, ril)
    r4ih, r4il = _df_mul(r2ih, r2il, r2ih, r2il)
    r5h, r5l = _df_mul(r4ih, r4il, rih, ril)

    dSdh = dSdl = None
    for i in range(3):
        zh, zl = _df_mul(s_ref[3 * i, :][None, :], s_ref[9 + 3 * i, :][None, :],
                         *d[0])
        zh, zl = _df_add(zh, zl, *_df_mul(s_ref[3 * i + 1, :][None, :],
                                          s_ref[9 + 3 * i + 1, :][None, :],
                                          *d[1]))
        zh, zl = _df_add(zh, zl, *_df_mul(s_ref[3 * i + 2, :][None, :],
                                          s_ref[9 + 3 * i + 2, :][None, :],
                                          *d[2]))
        th, tl = _df_mul(*d[i], zh, zl)
        dSdh, dSdl = (th, tl) if dSdh is None else _df_add(dSdh, dSdl, th, tl)

    ch, cl = _df_mul(dSdh, dSdl, r5h, r5l)

    for k in range(3):
        uh, ul = _df_mul(ch, cl, *d[k])
        sh, sl = _df_reduce_lanes(uh, ul)
        ah, al = _df_add(out_ref[k, :], out_ref[3 + k, :], sh, sl)
        out_ref[k, :] = ah
        out_ref[3 + k, :] = al


def _df_split_T(a):
    """[n, c...] f64/f32 array -> [2c, n] rows (hi, then lo) via the shared
    `df_kernels._df_split` (one split implementation for both DF tiers)."""
    from .df_kernels import _df_split

    return _hl_to_rows(_df_split(a))


def _pallas_df_call(kernel, trg_hl, src_hl, payload_hl, n_trg, tile_t, tile_s,
                    interpret, flops_per_pair):
    """Shared pallas_call driver for the DF kernels; returns [n_trg, 3] f64."""
    # the lane reduction's halving tree + 128-lane roll reduce is only
    # correct for tile_s = 128 * 2^k (e.g. 384 leaves 96 lanes where the
    # roll offsets double-count; 64 makes roll-by-64 the identity)
    if tile_s < 128 or (tile_s // 128) & (tile_s // 128 - 1) or tile_s % 128:
        raise ValueError(f"tile_s must be 128 * 2^k, got {tile_s}")
    if tile_t < 1:
        raise ValueError(f"tile_t must be positive, got {tile_t}")
    rows_p = payload_hl.shape[0]
    nt = pl.cdiv(n_trg, tile_t) * tile_t
    ns = pl.cdiv(src_hl.shape[1], tile_s) * tile_s

    # zero padding everywhere — NOT the exact tiles' 1e18 sentinel: the
    # Dekker split multiplies by 4097, and (sentinel^2)*4097 overflows f32
    # to inf inside _df_rsqrt (NaN via inf - inf). Zero-pad sources are safe
    # here for the same reason as the XLA DF driver: every additive term
    # carries a payload factor (zero-padded), and an exactly-coincident
    # pad/target pair is dropped by the r2 > 0 mask.
    trg_p = _pad_to(trg_hl, nt, axis=1)
    src_p = _pad_to(src_hl, ns, axis=1)
    pay_p = _pad_to(payload_hl, ns, axis=1)

    grid = (nt // tile_t, ns // tile_s)
    z = np.int32(0)  # i64/i32 index-map mix breaks Mosaic (pallas_kernels)
    out = pl.pallas_call(
        kernel,
        out_shape=_out_struct((6, nt), jnp.float32, trg_p, src_p, pay_p),
        grid=grid,
        in_specs=[
            pl.BlockSpec((6, tile_t), lambda i, j: (z, i),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((6, tile_s), lambda i, j: (z, j),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((rows_p, tile_s), lambda i, j: (z, j),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec((6, tile_t), lambda i, j: (z, i),
                               memory_space=pltpu.VMEM),
        cost_estimate=pl.CostEstimate(
            flops=flops_per_pair * nt * ns,
            bytes_accessed=4 * (6 * nt + (6 + rows_p) * ns + 6 * nt),
            transcendentals=nt * ns),
        interpret=interpret,
    )(trg_p, src_p, pay_p)

    # hi + lo is exactly representable in f64: one conversion per target
    u = (out[:3].astype(jnp.float64) + out[3:].astype(jnp.float64))
    return u.T[:n_trg]


def _require_x64(what):
    if not jax.config.jax_enable_x64:
        raise RuntimeError(
            f"{what} needs jax_enable_x64 for its float64 output "
            "(the pair arithmetic itself is f32)")


def _hl_to_rows(hl):
    """((hi, lo)) pair of [n, c...] arrays -> [2c, n] rows (hi, then lo)."""
    hi, lo = hl
    return jnp.concatenate([hi.reshape(hi.shape[0], -1).T,
                            lo.reshape(lo.shape[0], -1).T], axis=0)


def stokeslet_pallas_df_block(trg_hl, src_hl, f_hl, *, interpret: bool = False):
    """Unscaled DF Stokeslet partial sum for the ring evaluator.

    Same contract as `df_kernels._stokeslet_block_df`: operands are (hi, lo)
    f32 pairs of [n, 3] arrays (the `parallel.ring._ring_df` split), result
    is the UNSCALED [t, 3] float64 partial — the ring driver applies
    1/(8 pi eta) once at the end.
    """
    n_trg = trg_hl[0].shape[0]
    return _pallas_df_call(_stokeslet_df_kernel, _hl_to_rows(trg_hl),
                           _hl_to_rows(src_hl), _hl_to_rows(f_hl), n_trg,
                           DF_TILE_T, DF_TILE_S, interpret,
                           flops_per_pair=320)


def stresslet_pallas_df_block(trg_hl, src_hl, s_hl, *, interpret: bool = False):
    """Unscaled DF stresslet partial (includes the kernel's -3, like
    `df_kernels._stresslet_block_df`); ``s_hl`` is the (hi, lo) pair of the
    [n, 3, 3] double-layer source."""
    n_trg = trg_hl[0].shape[0]
    u = _pallas_df_call(_stresslet_df_kernel, _hl_to_rows(trg_hl),
                        _hl_to_rows(src_hl), _hl_to_rows(s_hl), n_trg,
                        DF_TILE_T, DF_TILE_S, interpret, flops_per_pair=420)
    return -3.0 * u


@partial(jax.jit, static_argnames=("tile_t", "tile_s", "interpret"))
def stokeslet_pallas_df(r_src, r_trg, f_src, eta, *, tile_t: int = DF_TILE_T,
                        tile_s: int = DF_TILE_S, interpret: bool = False):
    """Fused double-float Stokeslet sum (same contract as
    `kernels.stokeslet_direct`; f32/f64 inputs, float64 output)."""
    _require_x64("stokeslet_pallas_df")
    n_trg = r_trg.shape[0]
    if n_trg == 0 or r_src.shape[0] == 0:
        return jnp.zeros((n_trg, 3), dtype=jnp.float64)
    u = _pallas_df_call(_stokeslet_df_kernel, _df_split_T(r_trg),
                        _df_split_T(r_src), _df_split_T(f_src), n_trg,
                        tile_t, tile_s, interpret, flops_per_pair=320)
    return u / (8.0 * math.pi) / jnp.asarray(eta, dtype=jnp.float64)


@partial(jax.jit, static_argnames=("tile_t", "tile_s", "interpret"))
def stresslet_pallas_df(r_dl, r_trg, f_dl, eta, *, tile_t: int = DF_TILE_T,
                        tile_s: int = DF_TILE_S, interpret: bool = False):
    """Fused double-float stresslet sum (same contract as
    `kernels.stresslet_direct`: ``f_dl`` is [n_src, 3, 3]; float64 output).

    The -3 scale applies on the f64 reconstruction (scaling the (hi, lo)
    words by a non-power-of-two would round each word separately and
    destroy the compensation — `df_kernels` measured 2.7e-8 doing that).
    """
    _require_x64("stresslet_pallas_df")
    n_trg = r_trg.shape[0]
    if n_trg == 0 or r_dl.shape[0] == 0:
        return jnp.zeros((n_trg, 3), dtype=jnp.float64)
    u = _pallas_df_call(_stresslet_df_kernel, _df_split_T(r_trg),
                        _df_split_T(r_dl), _df_split_T(f_dl), n_trg,
                        tile_t, tile_s, interpret, flops_per_pair=420)
    return -3.0 * u / (8.0 * math.pi) / jnp.asarray(eta, dtype=jnp.float64)
