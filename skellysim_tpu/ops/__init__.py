from . import kernels, finite_diff  # noqa: F401
