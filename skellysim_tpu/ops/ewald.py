"""Free-space spectral Ewald Stokeslet evaluator: O(N log N) on a grid.

The TPU-native answer to the reference's hierarchical evaluator slot
(`/root/reference/include/kernels.hpp:56-134` wraps STKFMM/PVFMM, a distributed
kernel-independent FMM). A tree code is hostile to XLA (data-dependent
recursion, dynamic shapes); an Ewald split maps perfectly: the far field is a
gridded convolution (FFTs + one diagonal multiply — MXU/VPU-native), the near
field is dense pairwise tiles over a static cell decomposition (the same
blocked arithmetic as `ops.kernels`, restricted to 27 neighbor cells).

Mathematical structure (classic Hasimoto splitting, re-derived here and pinned
by tests against the dense kernel):

* The Stokeslet is an operator applied to the biharmonic kernel:
  ``G = (1/8 pi eta) (I lap - grad grad) B`` with ``B(r) = r``.
* Screened split ``B_far(r) = r erf(xi r) + exp(-xi^2 r^2)/(xi sqrt(pi))``
  gives ``B_far' = erf(xi r)`` and the radial-calculus identity
  ``G_rad[phi](r) = (1/8 pi eta)[(phi'' + phi'/r) I + (phi'/r - phi'') rhat rhat]``
  yields closed forms:
    G_far  = (1/8 pi eta)[ erf(xi r)(I + rhat rhat)/r
                           + (2 xi/sqrt(pi)) e^{-xi^2 r^2}(I - rhat rhat) ]
    G_near = (1/8 pi eta)[ erfc(xi r)(I + rhat rhat)/r
                           - (2 xi/sqrt(pi)) e^{-xi^2 r^2}(I - rhat rhat) ]
  G_near decays like erfc(xi r) — truncate at r_c with error ~erfc(xi r_c).
* Free space (no periodicity) via the truncated-kernel trick
  (Vico-Greengard-class): convolve with
  ``K^R = (I lap - grad grad)[(B 1_{r<R}) * g]`` where ghat is the Hasimoto
  mollifier ``(1 + k^2/(4 xi^2)) e^{-k^2/(4 xi^2)}``. K^R equals G_far
  exactly for pair distances < R - O(1/xi) and has compact support
  ~R + O(1/xi), so on an FFT box of size >= D + R + margin (D = cloud
  diameter) the periodization is EXACT — no images, no k=0 ambiguity. The
  scalar transform is closed-form (`bhat_far_trunc`); the mollifier damps
  the truncation's non-decaying r = R surface terms so the k-window error
  matches the classic Ewald estimate. The tensor multiplier never
  materializes:
    uhat_i(k) = -(1/8 pi eta) Bhat(k) [ k^2 fhat_i - k_i (k . fhat) ]
  (sign pinned by `tests/test_ewald.py` against the analytic G_far).
* Spreading/interpolation: separable truncated-Gaussian window of support P
  grid points per dim, deconvolved in k by dividing by what(k)^2 (both the
  type-1 spread and the type-2 interpolation contribute one factor).

Cost model: near field O(N * 27 * occupancy), far field O(M^3 log M) + O(N P^3)
gridding. Accuracy knobs: xi r_c (near truncation), k_max/(2 xi) (Fourier
truncation), P (window). `plan_ewald` picks them from a target tolerance.
"""

from __future__ import annotations

import math
from dataclasses import dataclass
from functools import partial

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax

__all__ = ["EwaldPlan", "plan_ewald", "stokeslet_ewald",
           "stresslet_ewald", "strip_anchors",
           "plan_anchors", "fill_positions", "stokeslet_near_block",
           "stokeslet_disp_block", "stresslet_disp_block_ewald",
           "g_far_pair", "bhat_far_trunc"]

_SQRT_PI = math.sqrt(math.pi)


# --------------------------------------------------------------- closed forms

def g_far_pair(rvec, xi, eta):
    """Far-field (screened) Stokeslet tensor for displacement(s) [..., 3].

    Smooth everywhere (r -> 0 limit: (4 xi/sqrt(pi)) I/(8 pi eta) * ...);
    used by tests and for small direct checks, not in the fast path.
    """
    r2 = jnp.sum(rvec * rvec, axis=-1)
    r = jnp.sqrt(r2)
    safe_r = jnp.where(r > 0, r, 1.0)
    rhat = rvec / safe_r[..., None]
    eye = jnp.eye(3, dtype=rvec.dtype)
    erf_term = jax.scipy.special.erf(xi * r) / safe_r
    # r -> 0: erf(xi r)/r -> 2 xi / sqrt(pi)
    erf_term = jnp.where(r > 0, erf_term, 2.0 * xi / _SQRT_PI)
    gauss = (2.0 * xi / _SQRT_PI) * jnp.exp(-(xi * r) ** 2)
    rr = rhat[..., :, None] * rhat[..., None, :]
    # at r == 0 the rhat rhat terms cancel between the two parts: erf_term
    # multiplies (I + rr) and gauss multiplies (I - rr); with rhat = 0 the
    # limit is handled by safe_r already
    G = (erf_term[..., None, None] * (eye + rr)
         + gauss[..., None, None] * (eye - rr))
    return G / (8.0 * math.pi * eta)


def stokeslet_near_block(trg, src, f_src, xi):
    """Unscaled near-field partial sum of one (target, source) block pair.

    ``u_i = sum_j [ erfc(xi r)(f/r + (d.f) d/r^3)
                    - (2 xi/sqrt(pi)) e^{-(xi r)^2} (f - (d.f) d/r^2) ]``
    (multiply by 1/(8 pi eta) outside). Coincident pairs drop, matching
    `kernels.stokeslet_block`.
    """
    return stokeslet_disp_block(trg[:, None, :] - src[None, :, :], f_src, xi)


def stokeslet_disp_block(d, f_src, xi):
    """`stokeslet_near_block` on a precomputed displacement tile ``d``
    [t, s, 3] — the seam `ops.spectral`'s periodic near field uses to
    minimum-image the displacements before the screened channel math."""
    r2 = jnp.sum(d * d, axis=-1)
    mask = r2 > 0.0
    r2s = jnp.where(mask, r2, 1.0)
    rinv = jnp.where(mask, lax.rsqrt(r2s), 0.0)
    r = r2 * rinv                      # = r, 0 at masked pairs
    erfc = jax.scipy.special.erfc(xi * r) * jnp.where(mask, 1.0, 0.0)
    gauss = (2.0 * xi / _SQRT_PI) * jnp.exp(-(xi * r) ** 2) \
        * jnp.where(mask, 1.0, 0.0)
    df = jnp.einsum("tsk,sk->ts", d, f_src)
    rinv3 = rinv * rinv * rinv
    a = erfc * rinv                    # multiplies f
    b = erfc * rinv3                   # multiplies (d.f) d
    c = gauss                          # multiplies -(f - (d.f) d / r^2)
    u = jnp.einsum("ts,sk->tk", a - c, f_src) \
        + jnp.einsum("ts,tsk->tk", df * (b + c * rinv * rinv), d)
    return u


def stresslet_near_block_ewald(trg, src, S, xi):
    """Unscaled stresslet near-field partial sum of one block pair.

    From the screened-biharmonic split (multiply by 1/(8 pi eta) outside):
    with phi = B_far, a = (phi'' - phi'/r)/r, c3 = phi''' - 3a,
    e = (phi''' + 2 phi''/r - 2 phi'/r^2)/2, the FAR kernel is
      u_far_i = -[ c3 (rh.S.rh) rh_i + (a - e)(((S + S^T) rh)_i
                   + tr(S) rh_i) ]
    and the near kernel is the exact stresslet minus it:
      u_near_i = -[ (3/r^2 - c3)(rh.S.rh) rh_i - (a - e)(...) ].
    phi' = erf(xi r), phi'' = g e^{-(xi r)^2} (g = 2 xi/sqrt(pi)),
    phi''' = -2 xi^2 r g e^{-(xi r)^2}. All coefficients decay like
    e^{-(xi r)^2} net of the exact kernel, and every one vanishes at r = 0
    (B_far is smooth and even), so there is no self term. Coincident pairs
    masked like `kernels.stresslet_block`.
    """
    return stresslet_disp_block_ewald(trg[:, None, :] - src[None, :, :],
                                      S, xi)


def stresslet_disp_block_ewald(d, S, xi):
    """`stresslet_near_block_ewald` on a precomputed displacement tile
    ``d`` [t, s, 3] (the periodic evaluator min-images ``d`` first)."""
    g = 2.0 * xi / _SQRT_PI
    r2 = jnp.sum(d * d, axis=-1)
    mask = r2 > 0.0
    r2s = jnp.where(mask, r2, 1.0)
    rinv = jnp.where(mask, lax.rsqrt(r2s), 0.0)
    r = r2 * rinv
    rinv2 = rinv * rinv
    expf = jnp.exp(-(xi * r) ** 2) * jnp.where(mask, 1.0, 0.0)
    erf_r = jax.scipy.special.erf(xi * r)
    p1 = erf_r * rinv                  # phi'/r (0 at masked pairs via rinv)
    p2 = g * expf                      # phi''
    p3 = -2.0 * xi * xi * r * g * expf  # phi'''
    a = (p2 - p1) * rinv
    c3 = p3 - 3.0 * a
    ame = -0.5 * p3                    # a - e simplifies to -phi'''/2

    # near = exact - far = exact + c3-channel + (a-e)-channel:
    #   [ -3(rhSrh)/r^2 + c3 (rhSrh) ] rh_i + (a-e)(((S+S^T) rh)_i + tr rh_i)
    dSd = jnp.einsum("tsi,sij,tsj->ts", d, S, d)      # d.S.d
    rhSrh = dSd * rinv2                                # rh.S.rh
    coeff_exact = -3.0 * rhSrh * rinv2                 # -3(rhSrh)/r^2
    chan1 = (coeff_exact + c3 * rhSrh) * rinv          # * rh_i = * d_i rinv
    Ssym_d = jnp.einsum("sij,tsj->tsi", S, d) + jnp.einsum(
        "sji,tsj->tsi", S, d)                          # (S + S^T) d
    trS = jnp.einsum("sii->s", S)
    u = chan1[..., None] * d \
        + ame[..., None] * (Ssym_d + trS[None, :, None] * d) * rinv[..., None]
    return jnp.sum(u, axis=1)


def bhat_far_trunc(k, xi, R):
    """Screened transform of the truncated biharmonic kernel.

    ``Bhat(k) = T(k) * (1 + k^2/(4 xi^2)) e^{-k^2/(4 xi^2)}`` where T is the
    sharp transform of ``r * 1_{r<R}``:
      T(k) = 4 pi [ 2(cos(kR)-1)/k^4 + 2 R sin(kR)/k^3 - R^2 cos(kR)/k^2 ]
      (series 4 pi R^4 [ 1/4 - (kR)^2/36 + (kR)^4/960 - ... ] for small kR).

    The screening factor is the Hasimoto mollifier ghat: the real-space
    kernel this represents is ``(I lap - grad grad)[(B 1_{r<R}) * g]`` which
    equals G_far exactly for pair distances < R - O(1/xi) and has compact
    support ~R + O(1/xi) — the free-space (aperiodic) trick. Crucially the
    truncation's non-decaying boundary oscillations (the r = R surface
    deltas) are damped by ghat's e^{-k^2/4xi^2}, so the k-grid window error
    matches the classic Ewald estimate. T as R -> infinity oscillates about
    -8 pi/k^4 (the distributional transform of r), recovering the textbook
    Hasimoto multiplier.
    """
    k = jnp.asarray(k)
    dtype = k.dtype
    kR = k * R
    small = kR < 0.5
    ks = jnp.where(small, 1.0, k)      # safe denominators

    cos_kR = jnp.cos(kR)
    sin_kR = jnp.sin(kR)
    T_exact = 4.0 * math.pi * (2.0 * (cos_kR - 1.0) / ks**4
                               + 2.0 * R * sin_kR / ks**3
                               - R**2 * cos_kR / ks**2)
    kR2 = kR * kR
    T_series = 4.0 * math.pi * R**4 * (0.25 - kR2 / 36.0 + kR2**2 / 960.0
                                       - kR2**3 / 50400.0)
    T = jnp.where(small, T_series, T_exact)

    x = k * k / (4.0 * xi * xi)
    ghat = (1.0 + x) * jnp.exp(-x)
    return (T * ghat).astype(dtype)


# ---------------------------------------------------------------------- plan

@dataclass(frozen=True)
class EwaldPlan:
    """Static geometry/resolution of one Ewald evaluation (hashable; selects
    compiled programs). Built host-side by `plan_ewald` from the point cloud's
    bounding box — the analogue of the reference FMM's tree setup
    (`kernels.hpp:78-122` rebuilds when points move).

    The two anchors (``box_lo``, ``cell_lo``) are carried here for
    convenience but enter the computation as *traced* operands: callers that
    jit on the plan must strip them (`strip_anchors`) so a quantized-anchor
    hop under drift reuses the compiled program.
    """

    xi: float                 # splitting parameter
    rc: float                 # near-field cutoff
    R: float                  # kernel truncation radius (> cloud diameter)
    box_lo: tuple             # FFT box lower corner (traced at run time)
    box_L: float              # FFT box edge (>= D + R + mollifier margin)
    M: int                    # grid points per dim
    P: int                    # window support (grid points per dim)
    tau: float                # Gaussian window variance parameter
    cell_lo: tuple            # near-field cell-lattice anchor (traced)
    cells3: tuple             # per-axis cell counts (cloud bbox + slack ONLY
                              # — not the FFT box, whose kernel margin holds
                              # no points)
    cell_size: float
    max_occ: int              # static per-cell capacity
    eta: float
    #: near-field backend: "cells" (27-neighbor buckets; robust, handles
    #: fill padding) or "blocks" (block-sparse: full tiles of consecutive
    #: nodes x top-K nearest blocks — no occupancy padding waste, the right
    #: mode for line-clustered fiber clouds where per-cell max occupancy is
    #: ~100x the mean)
    near_mode: str = "cells"
    block: int = 128          # nodes per block in "blocks" mode
    K: int = 32               # source blocks kept per target block

    @property
    def h(self) -> float:
        return self.box_L / self.M


def strip_anchors(plan: EwaldPlan) -> EwaldPlan:
    """Zero the traced anchor fields — the hashable jit key for this plan."""
    import dataclasses

    return dataclasses.replace(plan, box_lo=(0.0, 0.0, 0.0),
                               cell_lo=(0.0, 0.0, 0.0))


def plan_anchors(plan: EwaldPlan, dtype=None):
    """[2, 3] traced-operand anchors (box_lo, cell_lo)."""
    return jnp.asarray([plan.box_lo, plan.cell_lo],
                       dtype=dtype or jnp.float64)


#: additive plastic-constant lattice (the R2 low-discrepancy sequence) used
#: to spread padding/inactive source nodes uniformly over the cell region so
#: they cannot pile into one cell and blow up max_occ
_R2_ALPHAS = (0.8191725133961645, 0.6710436067037893, 0.5497004779019703)


def fill_positions(plan: EwaldPlan, cell_lo, n, dtype):
    """[n, 3] well-spread positions inside the near-field cell region.

    Deterministic (the same sequence the planner's occupancy count uses).
    Intended for inactive/padding nodes whose strengths are zero: they must
    live *somewhere* with static shapes, and any clustered placement —
    including the zero/replicated padding other paths use — concentrates
    bucket occupancy and with it the dense near-field tile size.
    """
    t = (jnp.arange(n, dtype=dtype) + 0.5)[:, None]
    alphas = jnp.asarray(_R2_ALPHAS, dtype=dtype)[None, :]
    frac = (t * alphas) % 1.0
    extent = (jnp.asarray(plan.cells3, dtype=dtype) - 0.01) * plan.cell_size
    return jnp.asarray(cell_lo, dtype=dtype) + frac * extent


def _fill_positions_np(plan_like, n):
    """NumPy mirror of `fill_positions` for host-side occupancy counting."""
    t = (np.arange(n, dtype=np.float64) + 0.5)[:, None]
    frac = (t * np.asarray(_R2_ALPHAS)[None, :]) % 1.0
    cell_lo, cells3, cell_size = plan_like
    extent = (np.asarray(cells3, dtype=np.float64) - 0.01) * cell_size
    return np.asarray(cell_lo) + frac * extent


def _ladder(x, base, ratio=1.25):
    """Quantize x upward onto a geometric ladder (plan-stability helper)."""
    return base * ratio ** math.ceil(math.log(max(x, base) / base)
                                     / math.log(ratio))


def plan_ewald(points, eta, tol=1e-6, max_grid=448, target_occ=32.0,
               n_fill=0, n_src=None):
    """Choose (xi, rc, R, grid M, window P, cell lattice) for a target
    relative tolerance.

    Host-side (NumPy): runs once per step/geometry like the reference's FMM
    tree rebuild (`kernels.hpp:78-122`). Calibrated rules (each pinned by
    `tests/test_ewald.py`):
      * near cutoff from cell geometry: ~`target_occ` points per cell at
        cell_size = rc -> rc = (target_occ * V / N)^(1/3)
      * xi from erfc(xi rc) ~ tol -> xi = sqrt(ln(1/tol)) / rc
      * kernel truncation R = D + (sqrt(ln(1/tol)) + 3)/xi: the r = R
        surface terms of the truncated biharmonic leak through the Hasimoto
        mollifier as ~e^{-xi^2 (R-D)^2} * poly — measured 2e-4 at R = D,
        4e-9 at the rule's margin (tol 1e-9)
      * k_max = 2 xi sqrt(ln(1/tol) + 4); the grid is capped at `max_grid`
        by relaxing xi through a short fixed-point iteration (R and the box
        depend on xi, so a single-shot relaxation leaves the Fourier
        truncation short of tol)
      * Gaussian window of support P points/dim, tau = (P h)^2/(16 ln(1/tol))
        — measured error ~e^{-1.2 P} (P=12 floors at 7e-7, P=16 reaches
        5e-9), so P = ln(1/tol)/1.2 + 2.

    Every derived quantity is a deterministic function of ladder-quantized
    inputs (diameter, extent, count, occupancy) so the plan — the jit
    compilation key — is stable while the geometry drifts; the two anchors
    additionally hop only on their own lattices and enter traced.

    ``n_fill`` reserves occupancy for that many zero-strength padding nodes
    placed by `fill_positions` (inactive fiber slots under dynamic
    instability).
    """
    pts = np.asarray(points, dtype=np.float64)
    lo = pts.min(axis=0)
    hi = pts.max(axis=0)
    D = max(float(np.linalg.norm(hi - lo)), 1e-3)
    D = _ladder(D, 1e-3)
    N = len(pts) + int(n_fill)
    vol = float(np.prod(np.maximum(hi - lo, 1e-3)))
    vol = min(_ladder(vol, 1e-9), D**3)

    logtol = math.log(1.0 / tol)
    N_q = max(1, 2 ** math.ceil(math.log2(max(N, 1))))
    rc = (target_occ * vol / N_q) ** (1.0 / 3.0)
    rc = min(rc, D)
    xi = math.sqrt(max(logtol, 1.0)) / rc
    P = max(6, min(26, int(math.ceil(logtol / 1.2)) + 2))

    # fixed point for (xi, R, L_box, M) under the grid cap: R and L depend
    # on xi, and the capped grid's k_max depends on L
    k_rule = 2.0 * math.sqrt(logtol + 4.0)
    for _ in range(4):
        R = D + (math.sqrt(logtol) + 3.0) / xi
        L_box = D + R + 4.0 / xi
        M_req = int(math.ceil(k_rule * xi * L_box / math.pi))
        if M_req <= max_grid:
            break
        xi = (math.pi * max_grid / L_box) / k_rule
    M = min(M_req, max_grid)
    M = max(M, 2 * P)
    M += M % 2
    rc = math.sqrt(max(logtol, 1.0)) / xi
    h = L_box / M
    tau = (P * h) ** 2 / (16.0 * logtol)

    # near-field cell lattice over the CLOUD bbox only (per axis), one slack
    # cell each side; anchors quantized to the cell lattice so an anchor hop
    # shifts the partition by whole cells (occupancy-invariant)
    cell_size = max(rc, 1e-6)
    ext_q = np.array([_ladder(float(e), 1e-3)
                      for e in np.maximum(hi - lo, 1e-3)])
    cells3 = tuple(int(math.ceil(e / cell_size)) + 2 for e in ext_q)
    cell_lo = tuple(float(cell_size * (math.floor(a / cell_size) - 1))
                    for a in lo)

    center = (lo + hi) / 2.0
    anchor = cell_size * np.floor(center / cell_size)
    box_lo = tuple(float(a) for a in (anchor - L_box / 2.0))

    ci = np.clip(((pts - np.asarray(cell_lo)) / cell_size).astype(int), 0,
                 np.asarray(cells3) - 1)
    if n_fill:
        fp = _fill_positions_np((cell_lo, cells3, cell_size), int(n_fill))
        cif = np.clip(((fp - np.asarray(cell_lo)) / cell_size).astype(int),
                      0, np.asarray(cells3) - 1)
        ci = np.vstack([ci, cif])
    flat = (ci[:, 0] * cells3[1] + ci[:, 1]) * cells3[2] + ci[:, 2]
    occ = int(np.bincount(flat, minlength=int(np.prod(cells3))).max()) \
        if len(flat) else 1
    # geometric capacity ladder (x1.5 rungs, 8-aligned) with 15% headroom:
    # a clamped point silently loses near-field pairs, and crossing a rung
    # (a recompile) should need a ~30% occupancy swing, not 1-point jitter
    need = occ * 1.15
    rung = 8.0
    while rung < need:
        rung *= 1.5
    occ = int(-8 * (-rung // 8))

    # near-field backend selection: line-clustered clouds (fiber nodes at
    # ~1/n spacing) concentrate max occupancy ~100x the mean, and the cells
    # mode pays C^3 * max_occ * 27 max_occ regardless of true occupancy.
    # The block-sparse mode has no padding waste but cannot host the spread
    # fill points (their blocks would need unbounded K), so it requires
    # n_fill == 0.
    near_mode = "cells"
    block = 128
    K = 0
    n_src_eff = len(pts) if n_src is None else int(n_src)
    if (n_fill == 0 and n_src_eff >= 4 * block
            and occ > 6.0 * target_occ):
        near_mode = "blocks"

        def bboxes(a):
            nb = -(-len(a) // block)
            padded = np.concatenate(
                [a, np.repeat(a[-1:], nb * block - len(a), axis=0)])
            blk = padded.reshape(nb, block, 3)
            return blk.min(axis=1), blk.max(axis=1)

        # K measured with the RUNTIME partitions: source blocks over the
        # leading n_src points (the fiber nodes `stokeslet_ewald` will see),
        # target blocks over the full cloud — valid ONLY for target arrays
        # that lead with the sources (the solve layout). Disjoint probe
        # sets re-blockify from their own offset and can out-count K
        # (straddling blocks); `_stokeslet_ewald_impl` routes those calls
        # to the cells path, so do NOT weaken its n_self gate.
        s_lo, s_hi = bboxes(pts[:n_src_eff])
        t_lo, t_hi = bboxes(pts)
        gap = np.maximum(0.0, np.maximum(s_lo[None] - t_hi[:, None],
                                         t_lo[:, None] - s_hi[None]))
        within = (gap**2).sum(-1) <= rc * rc
        k_need = int(within.sum(axis=1).max()) * 1.3
        rung = 8.0
        while rung < k_need:
            rung *= 1.5
        K = int(min(-8 * (-rung // 8), len(s_lo)))

    return EwaldPlan(xi=float(xi), rc=float(rc), R=float(R),
                     box_lo=box_lo, box_L=float(L_box), M=int(M), P=int(P),
                     tau=float(tau), cell_lo=cell_lo, cells3=cells3,
                     cell_size=float(cell_size), max_occ=occ,
                     eta=float(eta), near_mode=near_mode, block=block,
                     K=int(K))


# ---------------------------------------------------------------- near field

def _bucket_points(plan: EwaldPlan, cell_lo, pts, payload):
    """Sort points into [prod(cells3), max_occ] buckets (padded, masked)."""
    Cx, Cy, Cz = plan.cells3
    C3 = Cx * Cy * Cz
    ci = ((pts - cell_lo) / plan.cell_size).astype(jnp.int32)
    ci = jnp.clip(ci, 0, jnp.asarray(plan.cells3, dtype=jnp.int32) - 1)
    flat = (ci[:, 0] * Cy + ci[:, 1]) * Cz + ci[:, 2]
    order = jnp.argsort(flat)
    flat_s = flat[order]
    pts_s = pts[order]
    pay_s = payload[order]
    counts = jnp.zeros(C3, dtype=jnp.int32).at[flat_s].add(1)
    starts = jnp.concatenate([jnp.zeros(1, jnp.int32),
                              jnp.cumsum(counts)[:-1].astype(jnp.int32)])
    rank = jnp.arange(flat_s.shape[0], dtype=jnp.int32) - starts[flat_s]
    rank = jnp.minimum(rank, plan.max_occ - 1)  # clamp overflow (plan sized it)
    slot = flat_s * plan.max_occ + rank
    B = C3 * plan.max_occ
    # far sentinel for empty slots: pairwise distances stay > rc, masked by
    # zero payload anyway
    bpts = jnp.full((B, 3), 1e8, dtype=pts.dtype).at[slot].set(pts_s)
    bpay = jnp.zeros((B,) + payload.shape[1:], dtype=payload.dtype
                     ).at[slot].set(pay_s)
    return (bpts.reshape(C3, plan.max_occ, 3),
            bpay.reshape((C3, plan.max_occ) + payload.shape[1:]),
            order, flat)


_NBR_OFFSETS = np.array([(i, j, k) for i in (-1, 0, 1)
                         for j in (-1, 0, 1) for k in (-1, 0, 1)],
                        dtype=np.int32)  # [27, 3]

#: elements per near-field chunk tile — bounds the materialized
#: [chunk, max_occ, 27 * max_occ] intermediates to ~hundreds of MB
_NEAR_TILE_BUDGET = 3_000_000


def _near_field(plan: EwaldPlan, cell_lo, r_src, f_src, r_trg,
                near_fn=None):
    """Cell-list near field: dense G_near tiles over the 27 neighbor cells.

    Static shapes throughout ([cells, max_occ] buckets padded with far
    sentinels / zero strengths); boundary-clipped neighbor ids are
    de-duplicated by a 27x27 mask so edge cells don't double-count. Cells
    are processed in chunks via lax.map so peak memory is bounded by
    `_NEAR_TILE_BUDGET` elements regardless of the cell count.

    ``near_fn(trg, src, payload, xi) -> [t, 3]`` is the screened pair tile
    (Stokeslet by default; the stresslet evaluator passes its own), with
    ``f_src`` of any trailing rank.
    """
    if near_fn is None:
        near_fn = stokeslet_near_block
    Cx, Cy, Cz = plan.cells3
    C3 = Cx * Cy * Cz
    mo = plan.max_occ
    src_b, f_b, _, _ = _bucket_points(plan, cell_lo, r_src, f_src)
    trg_b, idx_b, _, flat_t = _bucket_points(
        plan, cell_lo, r_trg, jnp.arange(r_trg.shape[0], dtype=jnp.int32))

    cid = jnp.arange(C3, dtype=jnp.int32)
    cx, rem = cid // (Cy * Cz), cid % (Cy * Cz)
    cy, cz = rem // Cz, rem % Cz
    offs = jnp.asarray(_NBR_OFFSETS)
    nx = jnp.clip(cx[:, None] + offs[None, :, 0], 0, Cx - 1)
    ny = jnp.clip(cy[:, None] + offs[None, :, 1], 0, Cy - 1)
    nz = jnp.clip(cz[:, None] + offs[None, :, 2], 0, Cz - 1)
    nid = (nx * Cy + ny) * Cz + nz                   # [C3, 27]
    eq = nid[:, :, None] == nid[:, None, :]
    tri = jnp.tril(jnp.ones((27, 27), dtype=bool), k=-1)
    uniq = ~jnp.any(eq & tri[None], axis=2)          # first occurrence only

    def per_cell(t_pts, n_ids, n_uniq):
        s_pts = src_b[n_ids].reshape(-1, 3)          # [27 * mo, 3]
        pay = f_b[n_ids]
        mask = n_uniq.reshape((27,) + (1,) * (pay.ndim - 1))
        s_f = jnp.where(mask, pay, 0.0).reshape((-1,) + f_b.shape[2:])
        return near_fn(t_pts, s_pts, s_f, plan.xi)

    chunk = max(1, min(C3, _NEAR_TILE_BUDGET // max(27 * mo * mo, 1)))
    n_chunks = -(-C3 // chunk)
    pad = n_chunks * chunk - C3

    def padded(a, fill):
        widths = ((0, pad),) + ((0, 0),) * (a.ndim - 1)
        return jnp.pad(a, widths, constant_values=fill).reshape(
            (n_chunks, chunk) + a.shape[1:])

    u_b = lax.map(
        lambda args: jax.vmap(per_cell)(*args),
        (padded(trg_b, 1e8), padded(nid, 0), padded(uniq, False)))
    u_b = u_b.reshape(n_chunks * chunk, mo, 3)[:C3]

    # scatter back to original target order; padded slots carry target
    # index 0, so mask by per-cell occupancy
    counts_t = jnp.zeros(C3, dtype=jnp.int32).at[flat_t].add(1)
    slot_rank = jnp.arange(C3 * mo, dtype=jnp.int32) % mo
    valid = slot_rank < jnp.repeat(counts_t, mo)
    out = jnp.zeros((r_trg.shape[0], 3), dtype=r_trg.dtype)
    out = out.at[idx_b.reshape(-1)].add(
        jnp.where(valid[:, None], u_b.reshape(-1, 3), 0.0))
    return out / (8.0 * math.pi * plan.eta)


def _near_field_blocks(plan: EwaldPlan, r_src, f_src, r_trg):
    """Block-sparse near field: full tiles of `plan.block` consecutive nodes,
    each target block paired with its `plan.K` nearest source blocks by
    bounding-box gap.

    No occupancy padding: every tile is dense work on real points, which is
    what makes this the right mode for line-clustered fiber clouds (spatial
    locality of consecutive nodes is assumed — fiber order or a Morton sort
    gives it; the plan measured K on the actual cloud). Source blocks whose
    bbox gap exceeds r_c contribute < erfc(xi r_c) ~ tol and may be dropped,
    which is exactly what the top-K selection does.
    """
    B = plan.block
    # the plan sized K for its own cloud; a smaller runtime source set
    # (fewer blocks) must clamp or top_k is over-asked and crashes
    K = min(plan.K, -(-r_src.shape[0] // B))

    def blockify(a, n):
        pad = -(-n // B) * B - n
        return jnp.concatenate([a, jnp.repeat(a[-1:], pad, axis=0)]
                               ) if pad else a

    n_s = r_src.shape[0]
    n_t = r_trg.shape[0]
    sp = blockify(r_src, n_s).reshape(-1, B, 3)
    # duplicated pad rows must carry zero strength (the pad target rows are
    # sliced off, but pad SOURCE rows would double-count the last point)
    sf = jnp.concatenate(
        [f_src, jnp.zeros((sp.shape[0] * B - n_s, 3), f_src.dtype)]
    ).reshape(-1, B, 3)
    tp = blockify(r_trg, n_t).reshape(-1, B, 3)

    s_lo, s_hi = sp.min(axis=1), sp.max(axis=1)
    t_lo, t_hi = tp.min(axis=1), tp.max(axis=1)
    gap = jnp.maximum(0.0, jnp.maximum(s_lo[None] - t_hi[:, None],
                                       t_lo[:, None] - s_hi[None]))
    d2 = jnp.sum(gap * gap, axis=-1)                  # [TB, SB]
    _, sidx = lax.top_k(-d2, K)                       # [TB, K] nearest blocks

    def per_tblock(t_pts, idx):
        s_pts = sp[idx].reshape(K * B, 3)
        s_f = sf[idx].reshape(K * B, 3)
        return stokeslet_near_block(t_pts, s_pts, s_f, plan.xi)

    chunk = max(1, min(tp.shape[0], _NEAR_TILE_BUDGET // max(B * K * B, 1)))
    n_chunks = -(-tp.shape[0] // chunk)
    pad_c = n_chunks * chunk - tp.shape[0]

    def padded(a):
        widths = ((0, pad_c),) + ((0, 0),) * (a.ndim - 1)
        return jnp.pad(a, widths).reshape((n_chunks, chunk) + a.shape[1:])

    u = lax.map(lambda args: jax.vmap(per_tblock)(*args),
                (padded(tp), padded(sidx)))
    u = u.reshape(-1, 3)[:n_t]
    return u / (8.0 * math.pi * plan.eta)


# ----------------------------------------------------------------- far field

def _window_1d(plan: EwaldPlan, x, dtype):
    """Separable Gaussian window: offsets + weights for P grid points.

    Returns (i0 [N] leftmost grid index, w [N, P] weights) along one axis.
    """
    h = plan.h
    P = plan.P
    u = x / h
    i0 = jnp.floor(u - (P - 1) / 2.0).astype(jnp.int32)
    grid_pos = (i0[:, None]
                + jnp.arange(P, dtype=jnp.int32)[None, :]).astype(dtype) * h
    d = x[:, None] - grid_pos
    return i0, jnp.exp(-d * d / (4.0 * plan.tau))


def _window_indices(plan: EwaldPlan, pts_local, dtype):
    """Shared gridding geometry: flat (periodically wrapped) indices
    [N, P, P, P] and separable weights product [N, P, P, P]."""
    M = plan.M
    P = plan.P
    ix, wx = _window_1d(plan, pts_local[:, 0], dtype)
    iy, wy = _window_1d(plan, pts_local[:, 1], dtype)
    iz, wz = _window_1d(plan, pts_local[:, 2], dtype)
    # periodic wrap is EXACT for the FFT convolution; the plan's box margin
    # keeps wrapped kernel images outside every pair distance
    p_idx = jnp.arange(P, dtype=jnp.int32)
    gx = (ix[:, None] + p_idx[None, :]) % M
    gy = (iy[:, None] + p_idx[None, :]) % M
    gz = (iz[:, None] + p_idx[None, :]) % M
    flat = ((gx[:, :, None, None] * M + gy[:, None, :, None]) * M
            + gz[:, None, None, :])
    w3 = (wx[:, :, None, None] * wy[:, None, :, None]
          * wz[:, None, None, :])
    return flat, w3


#: elements per gridding chunk — the [chunk, P^3] index/weight/value
#: intermediates would otherwise reach several GB at BASELINE point counts
_GRID_CHUNK_BUDGET = 16_000_000


def _point_chunks(plan: EwaldPlan, n):
    P3 = plan.P ** 3
    chunk = max(1, min(n, _GRID_CHUNK_BUDGET // P3))
    return chunk, -(-n // chunk)


def _spread(plan: EwaldPlan, pts_local, values, dtype):
    """Type-1 gridding: scatter values [N, C] onto the [M, M, M, C] grid,
    in point chunks so the [chunk, P, P, P] intermediates stay bounded."""
    M = plan.M
    n = pts_local.shape[0]
    C = values.shape[-1]
    chunk, n_chunks = _point_chunks(plan, n)
    pad = n_chunks * chunk - n
    # padded points spread zero values: harmless wherever they land
    pts_p = jnp.pad(pts_local, ((0, pad), (0, 0))).reshape(n_chunks, chunk, 3)
    val_p = jnp.pad(values, ((0, pad), (0, 0))).reshape(n_chunks, chunk, C)

    def body(grid, args):
        pts_c, val_c = args
        flat, w3 = _window_indices(plan, pts_c, dtype)
        contrib = w3[..., None] * val_c[:, None, None, None, :]
        return grid.at[flat.reshape(-1)].add(contrib.reshape(-1, C)), None

    grid, _ = lax.scan(body, jnp.zeros((M * M * M, C), dtype=dtype),
                       (pts_p, val_p))
    return grid.reshape(M, M, M, C)


def _interp(plan: EwaldPlan, pts_local, grid, dtype):
    """Type-2 interpolation: gather grid [M, M, M, C] at points [N, 3],
    chunked like `_spread`."""
    n = pts_local.shape[0]
    C = grid.shape[-1]
    chunk, n_chunks = _point_chunks(plan, n)
    pad = n_chunks * chunk - n
    pts_p = jnp.pad(pts_local, ((0, pad), (0, 0))).reshape(n_chunks, chunk, 3)
    flat_grid = grid.reshape(-1, C)

    def body(pts_c):
        flat, w3 = _window_indices(plan, pts_c, dtype)
        vals = flat_grid[flat.reshape(-1)].reshape(flat.shape + (C,))
        return jnp.einsum("npqr,npqrk->nk", w3, vals)

    out = lax.map(body, pts_p)
    return out.reshape(n_chunks * chunk, C)[:n]


def _kgrid(plan: EwaldPlan, dtype):
    """Shared spectral geometry: (kx, ky, kz, k2, scalar fold) where the
    scalar folds the truncated-screened transform, the h^3 quadrature
    factor, the window deconvolution, and 1/(8 pi eta) — identical for the
    Stokeslet and stresslet far fields."""
    M = plan.M
    h = plan.h
    k_full = (2.0 * math.pi * jnp.fft.fftfreq(M, d=h)).astype(dtype)
    k_half = (2.0 * math.pi * jnp.fft.rfftfreq(M, d=h)).astype(dtype)
    kx = k_full[:, None, None]
    ky = k_full[None, :, None]
    kz = k_half[None, None, :]
    k2 = kx * kx + ky * ky + kz * kz
    Bhat = bhat_far_trunc(jnp.sqrt(k2), plan.xi, plan.R)
    what = ((4.0 * math.pi * plan.tau) ** 1.5) * jnp.exp(-plan.tau * k2)
    scalar = Bhat * (h ** 3) / (what * what) / (8.0 * math.pi * plan.eta)
    return kx, ky, kz, k2, scalar


def _far_field(plan: EwaldPlan, lo, r_src, f_src, r_trg):
    """Gridded far field.

    Normalization (Gaussian NUFFT, derived and pinned by tests): with
    what(k) = (4 pi tau)^{3/2} e^{-tau k^2},
      fhat(k) ~ h^3 FFT(spread)(k)/what(k)          (type 1)
      u(x)    = (1/L^3) sum_k Khat(k) fhat(k) e^{ikx}
              ~ sum_m w(x - y_m) IFFT[Khat fhat / (h^3 what)](y_m)  (type 2)
    so the grid-side multiplier is Khat(k) h^3 / what(k)^2 with a plain
    inverse FFT (its 1/M^3 supplies the 1/L^3 via h^3 M^3 = L^3). The grid
    field is real, so the transforms are rfftn/irfftn over a half-spectrum
    — half the FFT flops and spectral memory of complex fftn.
    """
    dtype = r_src.dtype
    M = plan.M

    H = _spread(plan, r_src - lo, f_src, dtype)           # [M, M, M, 3]
    Hk = jnp.fft.rfftn(H, axes=(0, 1, 2))                 # [M, M, M//2+1, 3]

    kx, ky, kz, k2, scalar = _kgrid(plan, dtype)
    # Khat = -(k^2 I - k k^T) Bhat / (8 pi eta)
    coeff = -scalar

    kdotF = kx * Hk[..., 0] + ky * Hk[..., 1] + kz * Hk[..., 2]
    Uk = jnp.stack([
        coeff * (k2 * Hk[..., 0] - kx * kdotF),
        coeff * (k2 * Hk[..., 1] - ky * kdotF),
        coeff * (k2 * Hk[..., 2] - kz * kdotF),
    ], axis=-1)
    U = jnp.fft.irfftn(Uk, s=(M, M, M), axes=(0, 1, 2))
    return _interp(plan, r_trg - lo, U.astype(dtype), dtype)


def _far_field_stresslet(plan: EwaldPlan, lo, r_dl, f_dl, r_trg):
    """Gridded stresslet (double-layer) far field.

    Spreads the 9-component source, applies the k-space multiplier
      uhat_i = (i Bhat/(8 pi eta)) [ k_i (k.Shat.k)
               - (k^2/2)(((Shat + Shat^T) k)_i + tr(Shat) k_i) ]
    (sign pinned by `tests/test_ewald.py` against the closed-form screened
    stresslet), with the same window deconvolution as the Stokeslet path.
    """
    dtype = r_dl.dtype
    M = plan.M

    H = _spread(plan, r_dl - lo, f_dl.reshape(-1, 9), dtype)
    Hk = jnp.fft.rfftn(H, axes=(0, 1, 2))                 # [M, M, Mh, 9]

    kx, ky, kz, k2, scalar = _kgrid(plan, dtype)
    coeff = 1j * scalar

    kv = (kx, ky, kz)
    # k.Shat.k and ((Shat + Shat^T) k)_i from the 9 channels (row-major jk)
    kSk = sum(kv[j] * kv[k] * Hk[..., 3 * j + k]
              for j in range(3) for k in range(3))
    Uk = jnp.stack([
        coeff * (kv[i] * kSk
                 - 0.5 * k2 * (sum(kv[k] * (Hk[..., 3 * i + k]
                                            + Hk[..., 3 * k + i])
                                   for k in range(3))
                               + (Hk[..., 0] + Hk[..., 4] + Hk[..., 8])
                               * kv[i]))
        for i in range(3)], axis=-1)
    U = jnp.fft.irfftn(Uk, s=(M, M, M), axes=(0, 1, 2))
    return _interp(plan, r_trg - lo, U.astype(dtype), dtype)


@partial(jax.jit, static_argnames=("plan",))
def _stresslet_ewald_impl(plan: EwaldPlan, anchors, r_dl, r_trg, f_dl):
    lo_box = anchors[0].astype(r_dl.dtype)
    lo_cell = anchors[1].astype(r_dl.dtype)
    # always the cells near field: the blocks-mode K was measured for the
    # fiber-node source partition, not shell/body double-layer sources
    u_near = _near_field(plan, lo_cell, r_dl, f_dl, r_trg,
                         near_fn=stresslet_near_block_ewald)
    u_far = _far_field_stresslet(plan, lo_box, r_dl, f_dl, r_trg)
    # no self term: every coefficient of the screened double-layer kernel
    # vanishes at r = 0 (B_far is smooth and even)
    return u_near + u_far


def stresslet_ewald(plan: EwaldPlan, r_dl, r_trg, f_dl):
    """Singular stresslet (double-layer) sum via spectral Ewald.

    Same semantics as `kernels.stresslet_direct` (``f_dl`` [n_src, 3, 3],
    coincident pairs drop, factor 1/(8 pi eta)); the anchors enter traced
    like `stokeslet_ewald`.
    """
    return _stresslet_ewald_impl(strip_anchors(plan),
                                 plan_anchors(plan, r_dl.dtype),
                                 r_dl, r_trg, f_dl)


@partial(jax.jit, static_argnames=("plan", "n_self"))
def _stokeslet_ewald_impl(plan: EwaldPlan, anchors, r_src, r_trg, f_src,
                          n_self: int):
    """Jitted core; ``plan`` must be anchor-stripped (`strip_anchors`) and
    ``anchors`` is the [2, 3] (box_lo, cell_lo) traced operand."""
    lo_box = anchors[0].astype(r_src.dtype)
    lo_cell = anchors[1].astype(r_src.dtype)
    # blocks mode is only partition-safe when the runtime target array leads
    # with the sources (the solve layout the plan measured K against);
    # disjoint probe sets (n_self == 0) re-blockify from their own offset,
    # where a straddling block can out-count plan.K and top_k silently
    # drops within-rc pairs — those calls take the cells path, whose
    # capacity was measured on the full planning cloud (probes included)
    if plan.near_mode == "blocks" and n_self == r_src.shape[0]:
        u_near = _near_field_blocks(plan, r_src, f_src, r_trg)
    else:
        u_near = _near_field(plan, lo_cell, r_src, f_src, r_trg)
    u_far = _far_field(plan, lo_box, r_src, f_src, r_trg)
    if n_self:
        self_coeff = 4.0 * plan.xi / (_SQRT_PI * 8.0 * math.pi * plan.eta)
        u_far = u_far.at[:n_self].add(-self_coeff * f_src[:n_self])
    return u_near + u_far


def stokeslet_ewald(plan: EwaldPlan, r_src, r_trg, f_src,
                    n_self: int | None = None):
    """Singular Stokeslet sum via spectral Ewald: near (cell list) + far (FFT).

    Same semantics as `kernels.stokeslet_direct`: coincident self pairs drop
    — the near tile masks them, and the gridded far field's smooth self term
    ``G_far(0) f_i = 4 xi/(sqrt(pi) 8 pi eta) f_i`` is subtracted
    analytically for the first ``n_self`` targets, which must be exactly
    ``r_src[:n_self]`` in order (the mobility-matvec layout: targets =
    [sources | other component nodes]). ``n_self=None`` auto-detects the
    common all-coincident case by *object identity* (``r_trg is r_src``) —
    shape equality is not evidence of coincidence — and otherwise subtracts
    nothing; pass ``n_self`` explicitly for mixed target sets.

    The box/cell anchors enter as traced operands (stripped from the plan's
    compilation key): a drifting cloud whose quantized anchors hop one
    lattice step reuses the compiled program.
    """
    if n_self is None:
        n_self = r_src.shape[0] if r_trg is r_src else 0
    return _stokeslet_ewald_impl(strip_anchors(plan),
                                 plan_anchors(plan, r_src.dtype),
                                 r_src, r_trg, f_src, int(n_self))
