"""Double-float (compensated f32) pairwise kernels.

TPU f64 is software-emulated (~113x slower than f32 on the measured v5
chip, `docs/performance.md`), but the reference's backend-agreement gate is
5e-9 (`/root/reference/tests/core/kernel_test.cpp:93`) — unreachable in plain
f32. These kernels evaluate the Stokeslet in double-float arithmetic: every
value is an unevaluated (hi, lo) pair of f32 with ~2*24 bits of significand
(Dekker/Knuth error-free transformations), giving ~1e-14-class per-pair
accuracy from pure f32 VPU ops at a small-constant-factor cost instead of the
emulated-f64 cliff. Pair contributions are exact-converted to f64 (hi + lo is
exactly representable) only for the final accumulation.

Intended use: the high-precision residual matvec of the mixed-precision
solver (`solver.gmres_ir`) at scales where the native-f64 kernels are too
slow, and the on-device kernel-agreement gate. Dtype-generic (the same
transformations double f64 on CPU), but f32 inputs are the point.

References: Dekker (1971) / Knuth TAOCP two_sum & two_prod; the standard
double-double recipes (e.g. Hida-Li-Bailey's QD library's add/mul shapes) —
re-derived here for branch-free jnp arrays.
"""

from __future__ import annotations

import math
from functools import partial

import jax
import jax.numpy as jnp
from jax import lax

__all__ = ["stokeslet_direct_df", "stresslet_direct_df"]


# Every rounded intermediate that error-extraction expressions subtract back
# is wrapped in `_bar` (a plain optimization barrier). That alone is NOT
# sufficient on every pipeline: the current XLA CPU stack REMOVES
# optimization barriers during compilation (measured, jax 0.9: 5 barriers
# in the StableHLO, zero in the optimized HLO), after which LLVM's FMA
# contraction can evaluate a CLONED expression inconsistently across
# consumer fusions — fl(e + x*y) fused as fma(x, y, e) in one clone, two
# roundings in the other — so the `s` a two-sum returns and the `s` its
# error extraction consumed are DIFFERENT values, and the compensation no
# longer captures the rounding of anything (measured: 5.9e-8 instead of
# 1e-14 relative on the squared displacement, at fusion-shape-dependent
# block sizes).
#
# The only inexact-product-feeding-add sites in the whole DF chain are the
# two full-width cross products in `_df_mul` (Dekker's partial products in
# `_two_prod` are exact by construction, and every other chain is add/sub
# only, which FMA contraction cannot touch). Those two sites get `_mbar`:
# `select(x == x, x, 0)` is value-preserving (inputs are non-NaN), cannot
# be folded without NaN reasoning, and emits a real select between the mul
# and any consumer add at the LLVM level. Hardening only these two keeps
# the rest of the graph fusion-friendly — the select everywhere variant
# blew CPU compile time up >50x at production block shapes.
#
# Default tiles are (256, 1024): XLA:CPU compile time scales with the tile
# AREA for this op-dense graph (~13 s at 256x1024 vs many minutes at
# 1024x4096 with the hardening in place); the runtime cost of the extra
# scan iterations is noise next to the per-pair arithmetic.
_bar = lax.optimization_barrier


def _mbar(x):
    """Contraction breaker for an inexact product about to be summed."""
    return jnp.where(x == x, x, jnp.zeros_like(x))


def _two_sum(a, b):
    """Error-free a + b = s + e (Knuth; no magnitude ordering required).

    Add/sub only: FMA contraction cannot rewrite it, so it is exact as long
    as its OPERANDS are deterministic values — which `_mbar` on the cross
    products in `_df_mul` guarantees for every caller in this module."""
    s = _bar(a + b)
    bb = _bar(s - a)
    e = (a - _bar(s - bb)) + (b - bb)
    return s, e


def _quick_two_sum(a, b):
    """Error-free a + b = s + e assuming |a| >= |b| (see `_two_sum` on
    operand determinism)."""
    s = _bar(a + b)
    e = b - (s - a)
    return s, e


def _split_factor(dtype):
    # 2^ceil(p/2) + 1: 4097 for f32 (p=24), 134217729 for f64 (p=53)
    bits = jnp.finfo(dtype).nmant + 1
    return float(2 ** math.ceil(bits / 2) + 1)  # skelly-lint: ignore[host-sync] — Python-int mantissa arithmetic on a static dtype, never a traced value


def _two_prod(a, b):
    """Error-free a * b = p + e via Dekker splitting (no FMA dependency).

    The split muls ``c * a`` are `_mbar`-hardened: Dekker's half extraction
    depends on the ROUNDING of c*a, and FMA contraction of c*a into the
    following subtract (fma(c, a, -a)) skips exactly that rounding, leaving
    a_hi a non-half and the "exact" partial products inexact (measured:
    3.8e-8 at fusion-shape-dependent block sizes). ``p = a * b`` needs only
    `_bar`: both its consumers subtract it, and the partial products that
    meet it in ``a_hi * b_hi - p`` are exact, so contraction there is
    value-preserving."""
    c = _split_factor(a.dtype)
    # p is also hardened: its own mul can contract into the consuming
    # subtraction (a_hi*b_hi - p -> fma(-a, b, ...)), skipping p's rounding
    # in one clone but not the returned value
    p = _bar(_mbar(a * b))
    a_big = _bar(_mbar(c * a))
    a_hi = _bar(a_big - _bar(a_big - a))
    a_lo = a - a_hi
    b_big = _bar(_mbar(c * b))
    b_hi = _bar(b_big - _bar(b_big - b))
    b_lo = b - b_hi
    e = ((a_hi * b_hi - p) + a_hi * b_lo + a_lo * b_hi) + a_lo * b_lo
    return p, e


def _df_add(xh, xl, yh, yl):
    s, e = _two_sum(xh, yh)
    e = e + (xl + yl)
    return _quick_two_sum(s, e)


def _df_mul(xh, xl, yh, yl):
    p, e = _two_prod(xh, yh)
    # the cross products are full-width (inexact) muls feeding an add:
    # break FMA contraction so clones cannot evaluate them inconsistently
    # (`_two_prod`'s partial products are Dekker-split EXACT and need none)
    e = e + (_mbar(xh * yl) + _mbar(xl * yh))
    return _quick_two_sum(p, e)


def _df_neg(xh, xl):
    return -xh, -xl


def _df_rsqrt(xh, xl):
    """1/sqrt(x) in double-float: f32 seed + one DF Newton step.

    y_{n+1} = y_n * (3 - x y_n^2) / 2 doubles the accurate bits, so one step
    from the ~2^-24 hardware estimate reaches the full DF precision. Assumes
    x > 0 (callers mask zero/coincident pairs before the sqrt).
    """
    y0 = lax.rsqrt(xh)
    # t = x * y0 * y0  (DF)
    th, tl = _df_mul(xh, xl, y0, jnp.zeros_like(y0))
    th, tl = _df_mul(th, tl, y0, jnp.zeros_like(y0))
    # r = 3 - t (DF)
    rh, rl = _df_add(jnp.full_like(th, 3.0), jnp.zeros_like(th), *_df_neg(th, tl))
    # y = y0 * r / 2
    yh, yl = _df_mul(rh, rl, y0, jnp.zeros_like(y0))
    return 0.5 * yh, 0.5 * yl


def _df_sum(h, l, axis):
    """Sum (h, l) double-float values along ``axis`` with renormalizing DF
    adds in a log-depth halving tree — all f32. One f64 conversion per
    *result* element happens in the caller, so the per-pair emulated-f64
    cost of a naive `jnp.sum(hi.astype(f64) + lo.astype(f64))` (ruinous on
    TPU, where f64 is software-emulated) never appears."""
    n = h.shape[axis]
    p = 1 << max(n - 1, 0).bit_length()
    if p != n:
        pads = [(0, 0)] * h.ndim
        pads[axis] = (0, p - n)
        h = jnp.pad(h, pads)
        l = jnp.pad(l, pads)
    while h.shape[axis] > 1:
        m = h.shape[axis] // 2
        h, l = _df_add(lax.slice_in_dim(h, 0, m, axis=axis),
                       lax.slice_in_dim(l, 0, m, axis=axis),
                       lax.slice_in_dim(h, m, 2 * m, axis=axis),
                       lax.slice_in_dim(l, m, 2 * m, axis=axis))
    return jnp.squeeze(h, axis), jnp.squeeze(l, axis)


def _df_split(x):
    """f64 -> (hi, lo) f32 pair with hi + lo ~ x to ~2^-48; f32 passes
    through with lo = 0."""
    if x.dtype == jnp.float32:
        return x, jnp.zeros_like(x)
    hi = x.astype(jnp.float32)
    lo = (x - hi.astype(jnp.float64)).astype(jnp.float32)
    return hi, lo


def _stokeslet_block_df(trg_hl, src_hl, f_hl):
    """One (target-block, source-chunk) Stokeslet partial sum, accumulated in
    f64 from per-pair double-float contributions. Operands are (hi, lo) f32
    pairs ([t, 3] / [s, 3]); returns [t, 3] float64."""
    trg_h, trg_l = trg_hl
    src_h, src_l = src_hl
    f_h, f_l = f_hl

    def comp(k):
        dh, de = _two_sum(trg_h[:, None, k], -src_h[None, :, k])
        # full two_sum, not quick: for nearly coincident f64 points the
        # lo-word difference can exceed |dh|, violating quick_two_sum's
        # magnitude precondition
        return _two_sum(dh, de + (trg_l[:, None, k] - src_l[None, :, k]))

    dxh, dxl = comp(0)
    dyh, dyl = comp(1)
    dzh, dzl = comp(2)

    r2h, r2l = _df_mul(dxh, dxl, dxh, dxl)
    r2h, r2l = _df_add(r2h, r2l, *_df_mul(dyh, dyl, dyh, dyl))
    r2h, r2l = _df_add(r2h, r2l, *_df_mul(dzh, dzl, dzh, dzl))

    mask = r2h > 0.0
    safe = jnp.where(mask, r2h, 1.0)
    rih, ril = _df_rsqrt(safe, jnp.where(mask, r2l, 0.0))
    rih = jnp.where(mask, rih, 0.0)
    ril = jnp.where(mask, ril, 0.0)
    r3h, r3l = _df_mul(rih, ril, rih, ril)
    r3h, r3l = _df_mul(r3h, r3l, rih, ril)

    fs = [(f_h[None, :, k], f_l[None, :, k]) for k in range(3)]
    dfh, dfl = _df_mul(dxh, dxl, *fs[0])
    dfh, dfl = _df_add(dfh, dfl, *_df_mul(dyh, dyl, *fs[1]))
    dfh, dfl = _df_add(dfh, dfl, *_df_mul(dzh, dzl, *fs[2]))

    ch, cl = _df_mul(dfh, dfl, r3h, r3l)

    out = []
    for (fkh, fkl), dh, dl in ((fs[0], dxh, dxl), (fs[1], dyh, dyl),
                               (fs[2], dzh, dzl)):
        uh, ul = _df_mul(rih, ril, fkh, fkl)
        uh, ul = _df_add(uh, ul, *_df_mul(ch, cl, dh, dl))
        sh, sl = _df_sum(uh, ul, axis=1)
        # hi + lo is exactly representable in f64: one conversion per target
        out.append(sh.astype(jnp.float64) + sl.astype(jnp.float64))
    return jnp.stack(out, axis=-1)


def _stresslet_block_df(trg_hl, src_hl, S_hl):
    """One (target-block, source-chunk) stresslet partial sum in double-float.

    ``u_k = sum_s -3 (d . S_s . d) d_k / r^5`` with d = t - s and self pairs
    dropped — the DF mirror of `kernels.stresslet_block`. ``S_hl`` is the
    (hi, lo) pair of the [s, 3, 3] double-layer source. Returns [t, 3] f64.
    """
    S_h, S_l = S_hl
    d = []   # displacement components as DF pairs
    trg_h, trg_l = trg_hl
    src_h, src_l = src_hl
    for k in range(3):
        dh, de = _two_sum(trg_h[:, None, k], -src_h[None, :, k])
        d.append(_two_sum(dh, de + (trg_l[:, None, k] - src_l[None, :, k])))

    r2h, r2l = _df_mul(*d[0], *d[0])
    r2h, r2l = _df_add(r2h, r2l, *_df_mul(*d[1], *d[1]))
    r2h, r2l = _df_add(r2h, r2l, *_df_mul(*d[2], *d[2]))

    mask = r2h > 0.0
    safe = jnp.where(mask, r2h, 1.0)
    rih, ril = _df_rsqrt(safe, jnp.where(mask, r2l, 0.0))
    rih = jnp.where(mask, rih, 0.0)
    ril = jnp.where(mask, ril, 0.0)
    # r^-5 = (r^-1)^4 * r^-1
    r2ih, r2il = _df_mul(rih, ril, rih, ril)
    r4ih, r4il = _df_mul(r2ih, r2il, r2ih, r2il)
    r5h, r5l = _df_mul(r4ih, r4il, rih, ril)

    # z_i = sum_j S_ij d_j  (DF), then dSd = sum_i d_i z_i
    dSdh = dSdl = None
    for i in range(3):
        zh, zl = _df_mul(S_h[None, :, i, 0], S_l[None, :, i, 0], *d[0])
        zh, zl = _df_add(zh, zl, *_df_mul(S_h[None, :, i, 1],
                                          S_l[None, :, i, 1], *d[1]))
        zh, zl = _df_add(zh, zl, *_df_mul(S_h[None, :, i, 2],
                                          S_l[None, :, i, 2], *d[2]))
        th, tl = _df_mul(*d[i], zh, zl)
        dSdh, dSdl = (th, tl) if dSdh is None else _df_add(dSdh, dSdl, th, tl)

    ch, cl = _df_mul(dSdh, dSdl, r5h, r5l)

    out = []
    for k in range(3):
        uh, ul = _df_mul(ch, cl, *d[k])
        sh, sl = _df_sum(uh, ul, axis=1)
        # the -3 scale applies on the exact f64 reconstruction: scaling the
        # (hi, lo) words separately by a non-power-of-two rounds each word
        # and destroys the compensation (measured: 2.7e-8 instead of 1e-13)
        out.append(-3.0 * (sh.astype(jnp.float64) + sl.astype(jnp.float64)))
    return jnp.stack(out, axis=-1)


def _direct_df(block_fn, r_src, r_trg, payload, eta, block_size, source_block):
    """Shared target-blocked, source-chunked driver for the DF kernels.

    ``block_fn(trg_hl, src_hl, payload_hl) -> [t, 3] f64`` is one
    (target-block, source-chunk) partial sum; ``payload`` is the per-source
    strength array (any trailing rank). Zero-padded tail sources must
    contribute zero (payload pads are zero and both block functions mask
    coincident pairs). Applies the common 1/(8 pi eta) scale.
    """
    from .kernels import _block_iter

    if not jax.config.jax_enable_x64:
        # without x64, every float64 request silently canonicalizes to f32
        # and the result would be ordinary f32 accuracy wearing a DF label
        raise RuntimeError(
            "DF kernels need jax_enable_x64 for their float64 "
            "accumulator/output (the pair arithmetic itself is f32)")

    n_trg = r_trg.shape[0]
    n_src = r_src.shape[0]
    if n_trg == 0:
        return jnp.zeros((0, 3), dtype=jnp.float64)

    def blocks(a, block, nb, pad):
        hi, lo = _df_split(a)
        widths = ((0, pad),) + ((0, 0),) * (a.ndim - 1)
        shape = (nb, block) + a.shape[1:]
        return (jnp.pad(hi, widths).reshape(shape),
                jnp.pad(lo, widths).reshape(shape))

    nb_t = _block_iter(n_trg, block_size)
    trg_blocks = blocks(r_trg, block_size, nb_t, nb_t * block_size - n_trg)

    nb_s = _block_iter(n_src, source_block)
    pad_s = nb_s * source_block - n_src
    src_chunks = blocks(r_src, source_block, nb_s, pad_s)
    payload_chunks = blocks(payload, source_block, nb_s, pad_s)

    def per_target_block(trg_hl):
        def body(acc, chunk):
            sh, sl, ph, pl = chunk
            return acc + block_fn(trg_hl, (sh, sl), (ph, pl)), None

        acc, _ = lax.scan(
            body, jnp.zeros((trg_hl[0].shape[0], 3), dtype=jnp.float64),
            (src_chunks[0], src_chunks[1],
             payload_chunks[0], payload_chunks[1]))
        return acc

    u = lax.map(per_target_block, trg_blocks)
    u = u.reshape(nb_t * block_size, 3)[:n_trg]
    return u / (8.0 * math.pi) / jnp.asarray(eta, dtype=jnp.float64)


@partial(jax.jit, static_argnames=("block_size", "source_block"))
def stresslet_direct_df(r_dl, r_trg, f_dl, eta, *, block_size: int = 256,
                        source_block: int = 1024):
    """Singular stresslet (double-layer) sum in double-float arithmetic.

    Same semantics as `kernels.stresslet_direct` (``f_dl`` is [n_src, 3, 3],
    self pairs drop, factor 1/(8 pi eta)), evaluated to ~1e-14-class relative
    accuracy from f32 VPU ops; the shell -> target flow is the dominant term
    of the mixed solver's f64 refinement matvec at walkthrough scale, where
    emulated f64 costs ~100x f32. Returns float64.
    """
    return _direct_df(_stresslet_block_df, r_dl, r_trg, f_dl, eta,
                      block_size, source_block)


@partial(jax.jit, static_argnames=("block_size", "source_block"))
def stokeslet_direct_df(r_src, r_trg, f_src, eta, *, block_size: int = 256,
                        source_block: int = 1024):
    """Singular Stokeslet sum with double-float per-pair arithmetic.

    Same semantics as `kernels.stokeslet_direct` (self pairs drop, factor
    1/(8 pi eta)), evaluated to ~1e-14-class relative accuracy — far under
    the reference's 5e-9 backend-agreement gate — without native f64 pair
    arithmetic. f32 inputs pass straight in; f64 inputs split into (hi, lo)
    f32 pairs (~2^-48 representation error), so this serves as the
    high-precision residual matvec for `solver.gmres_ir` at scales where
    emulated f64 is too slow. Returns float64.

    Accuracy envelope: per-pair relative error ~max(1e-14,
    2^-48 * |x| / |d|) — the split bounds how precisely a displacement
    between close points is represented. Physical node spacings (>= 1e-2 at
    O(10) coordinates) sit comfortably under the gate; pathological
    separations below ~1e-6 * |x| degrade gracefully toward f32-class for
    that pair only.
    """
    return _direct_df(_stokeslet_block_df, r_src, r_trg, f_src, eta,
                      block_size, source_block)
